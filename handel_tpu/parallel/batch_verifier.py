"""Shared batch-verifier service: many logical nodes, one device plane.

SURVEY.md §2.4 ("Intra-instance concurrency" row): the reference packs many
Handel instances into one process (simul/node/main.go:61-78) but each verifies
serially on its own goroutine. Here all co-located nodes funnel their
(bitset, signature) candidates into one queue; a collector task drains it,
pads to the device batch size, and issues a single multi-pairing launch —
the device equivalent of a shared syscall batcher. This is the prerequisite
for single-host thousands-of-nodes simulation (VERDICT r1 item 9).

Multi-tenant extension (ROADMAP item 3, handel_tpu/service/): requests are
tagged with the aggregation SESSION they belong to. A deficit-round-robin
`TenantQueue` (service/fairness.py) replaces the single FIFO, so N
concurrent Handel sessions share the device plane without a hot session
starving the rest, and one coalesced launch fills its 64/128 lanes from
whichever sessions have pending work. Devices exposing `dispatch_multi`
(per-lane messages — models/bn254_jax.py, or the host adapter in
service/driver.py) take the whole mixed-session batch as ONE launch;
legacy single-message devices fall back to one launch per distinct
message. Dedup verdicts are keyed per session: the same aggregate content
seen by two different sessions is two different facts (different
committees/rounds), never cross-deduped.

Fleet-of-chips extension (ROADMAP item 2, parallel/plane.py): the service
accepts either one device engine or a `DevicePlane` of K. Each plane lane
owns its dispatch slot, in-flight window, and circuit breaker; the
collector reserves the least-loaded free lane BEFORE draining the tenant
queue, then per-lane dispatcher/fetcher tasks run the two pipeline stages
concurrently across chips — fetch latency on one chip never idles the
others, and a single open breaker degrades the plane to K-1 lanes instead
of failing the run. A bare engine is wrapped in a plane of one, so the
single-chip path is the same code with K=1.
"""

from __future__ import annotations

import asyncio
from functools import partial
from typing import Callable, Sequence

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.store import VerifiedAggCache
from handel_tpu.core.trace import SERVICE_TID, trace_now
from handel_tpu.parallel.mesh_plane import MODE_LATENCY, ModePolicy
from handel_tpu.parallel.plane import BREAKER_CODE, DeviceLane, DevicePlane
from handel_tpu.service.fairness import TenantQueue
from handel_tpu.utils.breaker import CircuitBreaker

__all__ = ["BatchVerifierService", "CircuitBreaker", "DevicePlane"]


# the host fallback contract: (msg, [(global bitset, signature)]) -> verdicts,
# synchronous (it runs in an executor thread). The natural implementation is
# the scheme's own host-side serial batch_verify over the registry pubkeys
# (core/crypto.py Constructor.batch_verify -> ops/bn254_ref math).
FallbackVerifier = Callable[[bytes, Sequence[tuple[BitSet, object]]], list]

# queued-request tuple layout (one flat tuple, future LAST — every consumer
# below indexes it positionally): (session, msg, pubkeys, bitset, sig, fut)
_SESSION, _MSG, _PUBKEYS, _BITSET, _SIG, _FUT = range(6)


class BatchVerifierService:
    """Fuses verify requests from any number of nodes into shared launches.

    Wire into every node's Config.verifier via `.verifier` (or a
    session-tagged wrapper from `session_verifier`). Requests are answered
    with per-candidate verdicts; the collector waits up to `max_delay_ms`
    to fill a batch (latency/occupancy tradeoff knob).

    Per-session dedup: co-located nodes of ONE session all receive (and
    would all verify) the same winning aggregate per level. Requests are
    keyed by exact content — (session, msg, bitset words, signature bytes)
    — against a shared `VerifiedAggCache`, so a candidate ANY co-located
    node of that session already verified resolves instantly, and
    concurrent duplicates coalesce onto the one in-flight copy's lane
    instead of each taking their own. The session id in the key is the
    tenant-isolation boundary: identical bytes in two sessions stay two
    verifications.

    `device` may be a single engine (wrapped in a plane of one; the
    `breaker` argument becomes that lane's breaker) or a `DevicePlane`
    whose lanes already own their breakers. `self.device`/`self.breaker`
    always alias lane 0 — the single-chip monitoring/back-compat surface.
    """

    def __init__(
        self,
        device,
        max_delay_ms: float = 2.0,
        max_inflight: int = 2,
        dedup_cache: VerifiedAggCache | None = None,
        fallback: FallbackVerifier | None = None,
        breaker: CircuitBreaker | None = None,
        retry_limit: int = 2,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 1.0,
        logger: Logger = DEFAULT_LOGGER,
        recorder=None,
        quantum: int = 8,
        max_pending_per_session: int = 4096,
        queue_capacity: int = 0,
        mode_policy: ModePolicy | None = None,
    ):
        if isinstance(device, DevicePlane):
            self.plane = device
        else:
            self.plane = DevicePlane(
                [device], breakers=[breaker or CircuitBreaker()]
            )
        self.device = self.plane.lanes[0].engine
        self.breaker = self.plane.lanes[0].breaker
        # flight recorder (core/trace.py): dispatch-pack (host prep) and
        # device-verify (launch wall) spans + breaker/failover instants,
        # recorded on the service's own trace lane (SERVICE_TID)
        self.rec = recorder
        if recorder is not None:
            recorder.name_thread(SERVICE_TID, "batch-verifier")
            # each chip is a named trace thread carrying its launch
            # lifecycle (queued/staged/on-device/fetched spans below)
            for lane in self.plane.lanes:
                recorder.name_thread(
                    lane.trace_tid, f"device-lane-{lane.index}"
                )
        for lane in self.plane.lanes:
            self._hook_breaker(lane)
        self.max_delay = max_delay_ms / 1000.0
        self.max_inflight = max(1, max_inflight)
        # -- resilience plane: per-lane breakers + host failover ------------
        # transient device errors retry with capped exponential backoff;
        # persistent ones open THAT lane's breaker so the scheduler routes
        # around the chip. Only when every lane's breaker is open do batches
        # go to `fallback` (host reference verifier) — a dead accelerator
        # degrades throughput instead of stalling every node.
        self.fallback = fallback
        self.retry_limit = max(0, retry_limit)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.log = logger
        self.device_retries = 0
        self.failover_batches = 0
        self.failover_candidates = 0
        # tenant-tagged pending queue: per-session FIFOs drained
        # deficit-round-robin so one hot session cannot starve the rest.
        # The per-tenant bound is the service-side admission control — a
        # refused push fails that request's future immediately and the
        # session's own pipeline absorbs it under its retry budget.
        # `queue_capacity` > 0 arms SLO load shedding (service/fairness.py
        # SloTier): global depth past a tier's shed_at fraction refuses
        # that tier's new work at the door, bronze before gold
        self.queue = TenantQueue(
            quantum=quantum, max_pending=max_pending_per_session,
            capacity=queue_capacity,
        )
        self._kick = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._lane_tasks: list[asyncio.Task] = []
        self._free: asyncio.Event | None = None
        # lifecycle plane (handel_tpu/lifecycle/): the validator-set epoch
        # joins every dedup key, so a verdict computed against epoch E's
        # registry is never replayed after a rotation; `_gate` pauses the
        # collector's intake during quiesce_and (set = running), and
        # `_collector_busy` marks the collector mid-batch so the quiesce
        # knows when it has parked at the gate.
        self.epoch = 0
        self._gate: asyncio.Event | None = None
        self._collector_busy = False
        self.quiesce_ct = 0
        self.last_quiesce_stall_ms = 0.0
        # the batch held by the collector between queue.take() and lane
        # hand-off — outside the queue and every lane structure — so stop()
        # can fail its waiters too (ADVICE r5 #1). Batches held by lane
        # stages are tracked on the lanes (dispatching/fetching); the
        # `_collecting`/`_fetching` properties below present the union.
        self._collector_held: list | None = None
        # verified-aggregate dedup (shared across every node on this
        # service, keyed per session)
        self.cache = dedup_cache or VerifiedAggCache(capacity=8192)
        self._inflight: dict[tuple, asyncio.Future] = {}
        # counters for the monitor plane
        self.launches = 0
        self.candidates = 0
        # launch fill accounting (satellite fix): occupied lanes / lane
        # capacity recorded PER DISPATCHED LAUNCH, so coalescing wins are
        # measurable against the pre-service baseline. `launches`/
        # `candidates` above count at fetch (verdict) time and exclude
        # failover batches; these count at dispatch time.
        self.fill_sum = 0.0
        self.fill_launches = 0
        self.last_fill = 0.0
        self.coalesced_launches = 0  # launches mixing >1 distinct message
        # dual-mode scheduling (parallel/mesh_plane.py): consulted the
        # moment the plane carries a mesh lane. Counters split the launch
        # groups by the mode that actually dispatched them; a latency-
        # eligible group that found the mesh busy (or broken) falls back
        # to the throughput path and counts a mesh fallback.
        self.mode_policy = mode_policy or ModePolicy()
        self.latency_launches = 0
        self.throughput_launches = 0
        self.mesh_fallbacks = 0
        # per-tenant counters (service plane labels)
        self.tenant_candidates: dict[str, int] = {}
        self.tenant_dedup_hits: dict[str, int] = {}

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._free = asyncio.Event()
        self._gate = asyncio.Event()
        self._gate.set()
        self._lane_tasks = []
        for lane in self.plane.lanes:
            # hand-off cell (collector -> lane dispatcher; capacity 1: a
            # lane is reserved before the collector drains the queue, so it
            # never carries more than one undelivered group) and the
            # bounded dispatch->fetch window: dispatch of launch N+1
            # proceeds while N's verdicts are still in flight, so the
            # per-dispatch round trip (~66 ms through this environment's
            # tunnel, results/verify_profile.json) amortizes across
            # concurrent launches instead of serializing with the chip
            # compute. maxsize bounds device-side queue depth PER LANE.
            self._wire_lane(loop, lane)
        self._task = loop.create_task(self._collector())

    def _wire_lane(self, loop, lane: DeviceLane) -> None:
        """Bind one lane's asyncio plumbing and spawn its task pair (used
        by start() for the initial plane and attach_lane() for growth)."""
        lane.q = asyncio.Queue(maxsize=1)
        lane.fetch_q = asyncio.Queue(maxsize=self.max_inflight)
        lane.tasks = (
            loop.create_task(self._lane_dispatcher(lane)),
            loop.create_task(self._lane_fetcher(lane)),
        )
        self._lane_tasks.extend(lane.tasks)

    def stop(self) -> None:
        """Cancel every pipeline stage and FAIL any unanswered waiters —
        dropping them would leave callers awaiting forever. That includes
        the batch each stage holds OUTSIDE the queues while it works
        (collector hand-off, dispatch or fetch in flight on any lane):
        cancelling the stage strands those futures unless they are failed
        here. Resetting _task lets a later verify() restart the service."""
        if self._task:
            self._task.cancel()
            self._task = None
        for t in self._lane_tasks:
            t.cancel()
        self._lane_tasks = []
        err = RuntimeError("batch verifier stopped")

        def fail(items) -> None:
            for it in items or ():
                if not it[_FUT].done():
                    it[_FUT].set_exception(err)

        for lane in self.plane.lanes:
            if lane.fetch_q is not None:
                while True:
                    try:
                        items = lane.fetch_q.get_nowait()[1]
                    except asyncio.QueueEmpty:
                        break
                    fail(items)
                lane.fetch_q = None
            if lane.q is not None:
                while True:
                    try:
                        items = lane.q.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    fail(items)
                lane.q = None
            fail(lane.dispatching)
            fail(lane.fetching)
            lane.dispatching = lane.fetching = None
            lane.tasks = ()
        fail(self._collector_held)
        self._collector_held = None
        fail(self.queue.drain())
        # coalesced duplicates chained onto a failed primary are resolved by
        # their done-callbacks when the loop next runs; nothing to do here
        self._inflight.clear()

    # -- back-compat observation surface (telemetry + stop()-era tests) ----

    @property
    def _collecting(self) -> list | None:
        """The batch (if any) currently between the tenant queue and a
        lane's fetch window — collector hand-off or dispatch in flight."""
        if self._collector_held is not None:
            return self._collector_held
        for lane in self.plane.lanes:
            if lane.dispatching is not None:
                return lane.dispatching
        return None

    @property
    def _fetching(self) -> list | None:
        for lane in self.plane.lanes:
            if lane.fetching is not None:
                return lane.fetching
        return None

    @property
    def _fetch_q(self) -> asyncio.Queue | None:
        """Lane 0's in-flight window (single-chip back-compat; telemetry
        prefers `inflight_launches()` which sums the fleet)."""
        return self.plane.lanes[0].fetch_q

    def inflight_launches(self) -> int:
        """Dispatched launches whose verdicts haven't landed, fleet-wide."""
        return self.plane.inflight_launches()

    async def verify(
        self, msg, pubkeys, requests, session: str = "",
        dedup_scope: str | None = None,
    ) -> list[bool]:
        """AsyncVerifier-compatible entry (core/processing.py). `session`
        tags the requests with their aggregation instance: fairness,
        admission bounds and teardown are all keyed by it. Dedup verdicts
        are keyed by `dedup_scope` when given, else by `session`: the swarm
        runtime (handel_tpu/swarm/) runs one session per COMMITTEE MEMBER,
        and every member of one committee sees the same winning aggregates —
        a shared scope lets the whole committee cross-dedup identical
        content while fairness still isolates per-member queues. Distinct
        committees must pass distinct scopes (the tenant-isolation rule
        from the class docstring, one level up)."""
        if self._task is None:
            self.start()
        loop = asyncio.get_running_loop()
        scope = session if dedup_scope is None else dedup_scope
        futs = []
        for bs, sig in requests:
            # content digest, not raw words: one 65k-committee bitset is
            # 4 KB of words and this cache holds thousands of entries. The
            # epoch rides the key so a registry rotation invalidates every
            # pre-rotation verdict without a cache sweep (scope stays the
            # key head: drop_scope/forget_session match on it).
            key = (
                scope, self.epoch, msg,
                VerifiedAggCache.content_digest(bs, sig),
            )
            cached = self.cache.get(key)
            if cached is not None:
                # some co-located node of this session already verified
                # this exact aggregate
                self.tenant_dedup_hits[session] = (
                    self.tenant_dedup_hits.get(session, 0) + 1
                )
                fut = loop.create_future()
                fut.set_result(cached)
                futs.append(fut)
                continue
            primary = self._inflight.get(key)
            if primary is not None and not primary.done():
                # identical candidate already in flight: ride its lane. A
                # dedup hit for lane accounting — undo the get()'s miss count
                self.cache.misses -= 1
                self.cache.hits += 1
                self.tenant_dedup_hits[session] = (
                    self.tenant_dedup_hits.get(session, 0) + 1
                )
                fut = loop.create_future()
                primary.add_done_callback(partial(self._chain, fut))
                futs.append(fut)
                continue
            fut = loop.create_future()
            if not self.queue.push(
                session, (session, msg, pubkeys, bs, sig, fut)
            ):
                # per-tenant admission bound: the hot session absorbs its
                # own refusal through the pipeline's requeue/retry budget
                fut.set_exception(
                    RuntimeError(
                        f"batch verifier: session {session!r} queue full"
                    )
                )
                futs.append(fut)
                continue
            self.tenant_candidates[session] = (
                self.tenant_candidates.get(session, 0) + 1
            )
            self._inflight[key] = fut
            fut.add_done_callback(partial(self._uninflight, key))
            futs.append(fut)
        self._kick.set()
        return list(await asyncio.gather(*futs))

    def session_verifier(self, session: str, dedup_scope: str | None = None):
        """A Config.verifier-shaped wrapper tagging every request with
        `session` (the per-node pipeline's verifier contract has no session
        argument — the tag rides the closure). `dedup_scope` overrides the
        verdict-cache scope (see `verify`); the swarm passes its committee
        id so co-resident members share verdicts."""

        async def verify(msg, pubkeys, requests):
            return await self.verify(
                msg, pubkeys, requests, session=session,
                dedup_scope=dedup_scope,
            )

        return verify

    def forget_session(self, session: str) -> int:
        """Drop every trace of one tenant (SessionManager evict): queued
        requests fail immediately, dedup verdicts and counters vanish.
        Returns the number of queued requests dropped."""
        dropped = self.queue.drop_tenant(session)
        err = RuntimeError(f"batch verifier: session {session!r} evicted")
        for it in dropped:
            if not it[_FUT].done():
                it[_FUT].set_exception(err)
        for key in [k for k in self._inflight if k[0] == session]:
            self._inflight.pop(key, None)
        self.cache.drop_scope(session)
        self.tenant_candidates.pop(session, None)
        self.tenant_dedup_hits.pop(session, None)
        return len(dropped)

    # -- lifecycle plane (handel_tpu/lifecycle/) ---------------------------

    def _plane_idle(self) -> bool:
        """No launch anywhere between collector hand-off and verdict."""
        if self._collector_busy or self._collector_held is not None:
            return False
        return not any(
            l.dispatching is not None or l.fetching is not None
            or (l.fetch_q is not None and l.fetch_q.qsize())
            for l in self.plane.lanes
        )

    async def quiesce_and(self, fn: Callable[[], None]) -> float:
        """Pause intake, wait until every in-flight launch has resolved,
        run `fn` (e.g. flip every engine's staged registry bank), resume.
        Queued work is NOT dropped — it waits in the tenant queue and
        dispatches against the post-`fn` plane; nothing in flight is
        interrupted, so zero futures drop. Returns the stall in seconds
        (gate-closed wall — the launch gap an epoch swap costs)."""
        if self._task is None:
            fn()
            return 0.0
        t0 = trace_now()
        self._gate.clear()
        try:
            while not self._plane_idle():
                await asyncio.sleep(0.001)
            fn()
        finally:
            self._gate.set()
            self._kick.set()
        stall = trace_now() - t0
        self.quiesce_ct += 1
        self.last_quiesce_stall_ms = stall * 1e3
        if self.rec is not None:
            self.rec.span(
                "plane_quiesce", t0, t0 + stall, tid=SERVICE_TID,
                cat="lifecycle", args={"stall_ms": round(stall * 1e3, 3)},
            )
        return stall

    def _hook_breaker(self, lane: DeviceLane) -> None:
        """Make this lane's breaker transitions observable: each state
        edge emits a trace instant on the lane's own trace thread so
        incident attribution (obs/incidents.py) can cite the exact
        open/half-open/close sequence between scrapes. The monotonic
        count itself rides the breaker (`transitions`, summed into
        values() breakerTransitionsCt)."""
        def on_transition(prev: str, new: str,
                          _lane: DeviceLane = lane) -> None:
            if self.rec is not None:
                self.rec.instant(
                    "breaker_transition", tid=_lane.trace_tid,
                    cat="resilience",
                    args={"lane": _lane.index, "from": prev, "to": new},
                )

        lane.breaker.on_transition = on_transition

    def attach_lane(self, engine, breaker: CircuitBreaker | None = None,
                    mesh: bool = False) -> DeviceLane:
        """Grow the verify plane by one lane, live (LaneAutoscaler scale-up
        or breaker-open replacement). When the service is running, the
        lane's dispatcher/fetcher pair spawns immediately and the scheduler
        can route to it from the next pick. `mesh=True` attaches a
        latency-plane mesh lane (parallel/mesh_plane.py enable_latency_
        plane): only latency-mode groups are routed to it."""
        lane = self.plane.add_lane(engine, breaker, mesh=mesh)
        self._hook_breaker(lane)
        if self.rec is not None:
            kind = "device-mesh" if mesh else "device-lane"
            self.rec.name_thread(lane.trace_tid, f"{kind}-{lane.index}")
            self.rec.instant(
                "lane_attached", tid=SERVICE_TID, cat="lifecycle",
                args={
                    "lane": lane.index, "lanes": len(self.plane),
                    "mesh": mesh,
                },
            )
        if self._task is not None:
            self._wire_lane(asyncio.get_running_loop(), lane)
            self._free.set()  # a new free lane exists: wake the collector
        return lane

    async def drain_lane(
        self, lane: DeviceLane, timeout_s: float = 30.0,
    ) -> bool:
        """Gracefully retire one lane: stop routing to it, let its
        in-flight launches resolve, then cancel its task pair and drop it
        from the plane. Returns False when the drain timed out (the lane's
        remaining work was failed over and the lane removed anyway — a
        wedged chip must not be immortal)."""
        lane.draining = True
        deadline = trace_now() + timeout_s
        while (
            lane.dispatching is not None or lane.fetching is not None
            or (lane.fetch_q is not None and lane.fetch_q.qsize())
        ):
            if trace_now() >= deadline:
                break
            await asyncio.sleep(0.001)
        clean = (
            lane.dispatching is None and lane.fetching is None
            and (lane.fetch_q is None or not lane.fetch_q.qsize())
        )
        for t in lane.tasks:
            t.cancel()
            try:
                self._lane_tasks.remove(t)
            except ValueError:
                pass
        # anything the timeout stranded goes to failover/failure so no
        # caller awaits forever (the stop() contract, per lane)
        leftovers: list = []
        if lane.fetch_q is not None:
            while True:
                try:
                    leftovers.extend(lane.fetch_q.get_nowait()[1])
                except asyncio.QueueEmpty:
                    break
        if lane.dispatching is not None:
            leftovers.extend(lane.dispatching)
        if lane.fetching is not None:
            leftovers.extend(lane.fetching)
        lane.dispatching = lane.fetching = None
        lane.q = lane.fetch_q = None
        lane.tasks = ()
        self.plane.remove_lane(lane)
        if leftovers:
            await self._failover(leftovers)
        if self.rec is not None:
            self.rec.instant(
                "lane_drained", tid=SERVICE_TID, cat="lifecycle",
                args={
                    "lane": lane.index, "clean": clean,
                    "lanes": len(self.plane),
                },
            )
        if self._free is not None:
            self._free.set()  # re-evaluate scheduling after the shrink
        return clean

    @staticmethod
    def _chain(fut: asyncio.Future, primary: asyncio.Future) -> None:
        """Copy a resolved primary's outcome onto a coalesced duplicate."""
        if fut.done():
            return
        if primary.cancelled():
            fut.cancel()
        elif primary.exception() is not None:
            fut.set_exception(primary.exception())
        else:
            fut.set_result(primary.result())

    def _uninflight(self, key: tuple, fut: asyncio.Future) -> None:
        """Primary resolved: drop the in-flight marker and remember the
        verdict so later copies of this aggregate never reach the device."""
        if self._inflight.get(key) is fut:
            del self._inflight[key]
        if not fut.cancelled() and fut.exception() is None:
            self.cache.put(key, bool(fut.result()))

    @property
    def verifier(self):
        return self.verify

    def queue_depth(self) -> int:
        """Total queued candidates across every tenant (telemetry plane)."""
        return len(self.queue)

    def _plan_launches(self, batch: list) -> list[list]:
        """Split one fairly-drained batch into launch groups. A device with
        `dispatch_multi` (per-lane messages) takes the WHOLE mixed-session
        batch as one coalesced launch; a single-message device gets one
        launch per distinct message (the pre-service behavior)."""
        if hasattr(self.device, "dispatch_multi"):
            return [batch]
        by_msg: dict[bytes, list] = {}
        for it in batch:
            by_msg.setdefault(it[_MSG], []).append(it)
        return list(by_msg.values())

    def _launch_call(self, lane: DeviceLane, items: list):
        """The device call for one launch group (runs in an executor)."""
        if hasattr(lane.engine, "dispatch_multi"):
            return partial(
                lane.engine.dispatch_multi,
                [(it[_MSG], it[_PUBKEYS], it[_BITSET], it[_SIG])
                 for it in items],
            )
        return partial(
            lane.engine.dispatch,
            items[0][_MSG],
            [(it[_BITSET], it[_SIG]) for it in items],
        )

    def _group_tier(self, items):
        """The best SLO tier riding one launch group — highest DRR weight,
        ties broken by the tighter p99 target. A mixed gold/bronze group
        routes by its gold passenger: the urgent work defines the group's
        latency entitlement."""
        tiers = {self.queue.tier_of(it[_SESSION]) for it in items}
        return max(tiers, key=lambda t: (t.weight, -t.p99_target_s))

    def _route_mesh(self, items) -> DeviceLane | None:
        """Dual-mode scheduling (parallel/mesh_plane.py): pick this launch
        group's mode from its size, the backlog left in the tenant queue,
        and its best SLO tier; return a free mesh lane for latency-mode
        groups. None = throughput path — either the policy said so, the
        plane has no mesh lane, or the mesh is busy/broken (counted as a
        mesh fallback; breaker-open mesh lanes degrade latency mode to
        throughput, never to failover)."""
        mesh = self.plane.mesh_lanes()
        if not mesh:
            return None
        mesh_batch = min(l.engine.batch_size for l in mesh)
        mode = self.mode_policy.pick_mode(
            len(items), len(self.queue), self._group_tier(items), mesh_batch
        )
        if mode != MODE_LATENCY:
            self.throughput_launches += 1
            return None
        lane = self.plane.pick_mesh()
        if lane is None:
            self.mesh_fallbacks += 1
            self.throughput_launches += 1
            return None
        self.latency_launches += 1
        return lane

    async def _acquire_lane(self) -> DeviceLane | None:
        """Reserve the least-loaded free THROUGHPUT lane, waiting for one
        to free up when every admissible lane is occupied. None means every
        throughput lane's breaker is open — the caller routes the group to
        failover (the single-chip breaker-open behavior, fleet-wide; a
        healthy mesh lane does not keep bulk groups alive, they don't fit
        its launch shape)."""
        while True:
            lane = self.plane.pick()
            if lane is not None or not self.plane.throughput_pool():
                return lane
            self._free.clear()
            await self._free.wait()

    async def _collector(self) -> None:
        while True:
            self._collector_busy = False
            # quiesce gate (lifecycle/epoch.py): cleared while a registry
            # flip needs the plane idle; intake parks here, the tenant
            # queue keeps absorbing (and admission-bounding) arrivals
            await self._gate.wait()
            if not len(self.queue):
                self._kick.clear()
                await self._kick.wait()
                continue  # re-check the gate before touching the queue
            self._collector_busy = True
            # brief accumulation window so co-located nodes (and sessions)
            # share the launch
            if len(self.queue) < self.device.batch_size:
                await asyncio.sleep(self.max_delay)
            # reserve a dispatch slot BEFORE draining the tenant queue:
            # while every lane is occupied, pending work stays in the
            # tenant queue where fairness, admission bounds and
            # forget_session() can still reach it
            lane = await self._acquire_lane()
            batch = self.queue.take(self.device.batch_size)
            if not batch:
                continue
            # from here until every group is handed to a lane the batch
            # lives in neither the queue nor any lane structure: track it
            # on self so stop() can fail these futures if this task is
            # cancelled mid-hand-off
            self._collector_held = batch
            for i, items in enumerate(self._plan_launches(batch)):
                # dual-mode routing: a latency-mode group takes the mesh
                # lane (no wait — _route_mesh only returns a FREE one);
                # everything else rides the reserved throughput lane
                target = self._route_mesh(items)
                if target is None:
                    if i:
                        lane = await self._acquire_lane()
                    target = lane
                if target is None:
                    # every breaker open: host failover (or fail the
                    # futures when no fallback exists)
                    await self._failover(items)
                    continue
                # mark BEFORE the put: `dispatching` is both the lane's
                # occupied flag and stop()'s handle on the group (the queue
                # item is the same list object, so a drain double-fail is a
                # no-op). No await between pick and put -> put_nowait is
                # safe on the capacity-1 cell.
                target.dispatching = items
                if self.rec is not None and self.rec.enabled:
                    # launch_queued span start (the dispatcher reads it when
                    # it takes the group off the capacity-1 cell)
                    target.queued_ts = trace_now()
                target.q.put_nowait(items)
            self._collector_held = None

    def _lane_span_args(self, lane: DeviceLane, items: list) -> dict:
        """Launch-lifecycle span args: lane, group size, and the sessions
        whose candidates ride this launch (computed only while tracing —
        the set build never runs on the untraced hot path)."""
        args = {
            "lane": lane.index, "n": len(items),
            "mode": "mesh" if lane.mesh else "lane",
        }
        sessions = sorted({it[_SESSION] for it in items if it[_SESSION]})
        if sessions:
            args["sessions"] = ",".join(sessions)
        return args

    async def _lane_dispatcher(self, lane: DeviceLane) -> None:
        """Per-lane first pipeline stage: dispatch groups handed to this
        lane (host prep + async enqueue), then push the handle into the
        lane's in-flight window. Blocking on a full window keeps the lane
        marked occupied — that is the per-chip backpressure."""
        while True:
            items = await lane.q.get()
            handle = None
            tracing = self.rec is not None and self.rec.enabled
            t_deq = trace_now() if tracing else 0.0
            if tracing and lane.queued_ts:
                # time the group sat in the hand-off cell waiting for this
                # lane — the first stage of its lifecycle timeline
                self.rec.span(
                    "launch_queued",
                    lane.queued_ts,
                    t_deq,
                    tid=lane.trace_tid,
                    cat="device",
                    args=self._lane_span_args(lane, items),
                )
            if lane.breaker.allow():
                t0 = trace_now()
                handle = await self._dispatch_with_retries(
                    lane, self._launch_call(lane, items)
                )
                if tracing:
                    t_disp = trace_now()
                    # the host half of a launch: request packing + the
                    # async enqueue (PR 1's host_pack_ms lives in here)
                    self.rec.span(
                        "dispatch_pack",
                        t0,
                        t_disp,
                        tid=SERVICE_TID,
                        cat="verifier",
                        args={
                            "n": len(items),
                            "ok": handle is not None,
                            "device": lane.index,
                        },
                    )
                    # same interval on the lane's own timeline: host staging
                    self.rec.span(
                        "launch_staged",
                        t0,
                        t_disp,
                        tid=lane.trace_tid,
                        cat="device",
                        args=self._lane_span_args(lane, items),
                    )
            if handle is None:
                # this lane's breaker opened (or retries exhausted): the
                # group fails over; FUTURE groups go to other lanes
                await self._failover(items)
            else:
                # launch fill: occupied lanes over THIS lane's capacity
                # (a mesh lane's small-batch engine fills differently from
                # the throughput lanes), recorded per dispatched launch on
                # both the service aggregate and the device-labeled row
                fill = len(items) / lane.engine.batch_size
                self.last_fill = fill
                self.fill_sum += fill
                self.fill_launches += 1
                lane.last_fill = fill
                lane.fill_sum += fill
                lane.launches += 1
                lane.candidates += len(items)
                if len({it[_MSG] for it in items}) > 1:
                    self.coalesced_launches += 1
                # dispatch-completion stamp rides to the fetcher: the
                # launch_on_device span starts where staging ended
                await lane.fetch_q.put((handle, items, trace_now()))
            lane.dispatching = None
            self._free.set()

    async def _dispatch_with_retries(self, lane: DeviceLane, call):
        """Try the lane's device up to 1 + retry_limit times; None = gave
        up (each failure feeds THAT lane's breaker)."""
        loop = asyncio.get_running_loop()
        for attempt in range(1 + self.retry_limit):
            try:
                return await loop.run_in_executor(None, call)
            except asyncio.CancelledError:
                raise  # stop() fails the futures via lane.dispatching
            except Exception as e:
                lane.breaker.record_failure()
                if self.rec is not None:
                    self.rec.instant(
                        "device_error",
                        tid=SERVICE_TID,
                        cat="verifier",
                        args={
                            "stage": "dispatch",
                            "device": lane.index,
                            "breaker": lane.breaker.state,
                        },
                    )
                self.log.warn(
                    "verifier_device_error",
                    f"dispatch attempt {attempt + 1} "
                    f"(device {lane.index}): {e}",
                )
                if not lane.breaker.allow() or attempt >= self.retry_limit:
                    return None
                self.device_retries += 1
                lane.retries += 1
                await asyncio.sleep(
                    min(self.backoff_base_s * 2**attempt, self.backoff_cap_s)
                )
        return None

    async def _failover(self, items) -> None:
        """Resolve a launch group through the host reference verifier; with
        no fallback configured, fail the futures (BatchProcessing requeues
        the candidates under its retry budget — the pre-breaker behavior).
        A coalesced group can span messages: the (msg, reqs) fallback
        contract is honored by resolving one message group at a time."""
        if self.fallback is None:
            err = RuntimeError("batch verifier: device unavailable")
            for it in items:
                if not it[_FUT].done():
                    it[_FUT].set_exception(err)
            return
        if self.rec is not None:
            self.rec.instant(
                "verifier_failover",
                tid=SERVICE_TID,
                cat="verifier",
                args={
                    "n": len(items),
                    "devices_available": len(self.plane.allowed()),
                },
            )
        by_msg: dict[bytes, list] = {}
        for it in items:
            by_msg.setdefault(it[_MSG], []).append(it)
        loop = asyncio.get_running_loop()
        for msg, group in by_msg.items():
            reqs = [(it[_BITSET], it[_SIG]) for it in group]
            try:
                verdicts = await loop.run_in_executor(
                    None, partial(self.fallback, msg, reqs)
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                for it in group:
                    if not it[_FUT].done():
                        it[_FUT].set_exception(
                            RuntimeError(f"batch verifier: {e}")
                        )
                continue
            self.failover_batches += 1
            self.failover_candidates += len(group)
            for it, ok in zip(group, verdicts):
                if not it[_FUT].done():
                    it[_FUT].set_result(bool(ok))

    async def _lane_fetcher(self, lane: DeviceLane) -> None:
        """Per-lane second pipeline stage: pull verdicts for this lane's
        dispatched launches, in dispatch order, and resolve the waiters."""
        loop = asyncio.get_running_loop()
        while True:
            handle, items, t_disp = await lane.fetch_q.get()
            # outside the window until resolved: visible to stop() (see
            # _collector's mirror note)
            lane.fetching = items
            t0 = trace_now()
            try:
                verdicts = await loop.run_in_executor(
                    None, partial(lane.engine.fetch, handle)
                )
            except asyncio.CancelledError:
                raise  # stop() fails the futures via lane.fetching
            except Exception as e:
                # a fetch-side device death (verdict transfer failed) takes
                # the same breaker + host-failover path as dispatch errors
                lane.breaker.record_failure()
                self.log.warn(
                    "verifier_device_error",
                    f"fetch (device {lane.index}): {e}",
                )
                await self._failover(items)
                lane.fetching = None
                continue
            if self.rec is not None and self.rec.enabled:
                t_end = trace_now()
                # device wall per launch (verdict-arrival latency), the
                # counterpart of dispatch_pack's host half
                self.rec.span(
                    "device_verify",
                    t0,
                    t_end,
                    tid=SERVICE_TID,
                    cat="verifier",
                    args={"n": len(items), "device": lane.index},
                )
                largs = self._lane_span_args(lane, items)
                # lane-timeline remainder of the lifecycle: in flight on
                # the chip since dispatch, and the verdict transfer window.
                # Mesh launches carry their own span name so the critical-
                # path analyzer (sim/trace_cli.py) attributes whole-mesh
                # walls distinctly from per-chip lane walls.
                self.rec.span(
                    "launch_on_mesh" if lane.mesh else "launch_on_device",
                    t_disp,
                    t_end,
                    tid=lane.trace_tid,
                    cat="device",
                    args=largs,
                )
                self.rec.span(
                    "launch_fetched",
                    t0,
                    t_end,
                    tid=lane.trace_tid,
                    cat="device",
                    args=largs,
                )
            lane.breaker.record_success()
            lane.fetched += 1
            self.launches += 1
            self.candidates += len(items)
            for it, ok in zip(items, verdicts):
                if not it[_FUT].done():
                    it[_FUT].set_result(ok)
            lane.fetching = None

    def session_values(self) -> dict[str, dict[str, float]]:
        """Per-tenant reporter surface for the `session`-labeled metrics
        plane (core/metrics.py register_labeled_values): every session that
        currently has queued work or has ever enqueued through this
        service."""
        depths = self.queue.depths()
        out: dict[str, dict[str, float]] = {}
        for sid in set(depths) | set(self.tenant_candidates):
            out[sid] = {
                "queueDepth": float(depths.get(sid, 0)),
                "candidates": float(self.tenant_candidates.get(sid, 0)),
                "dedupHits": float(self.tenant_dedup_hits.get(sid, 0)),
            }
        return out

    def session_gauge_keys(self) -> set[str]:
        return {"queueDepth"}

    def values(self) -> dict[str, float]:
        # host pack/dispatch accounting SUMMED over the fleet's engines
        # (it used to read the counters off device 0 only — wrong the
        # moment a second chip dispatched anything)
        hc = self.plane.host_cost()
        pack_ms, pack_n = hc["pack_ms"], hc["pack_launches"]
        disp_ms, disp_n = hc["dispatch_ms"], hc["dispatch_launches"]
        return {
            "verifierLaunches": float(self.launches),
            "verifierCandidates": float(self.candidates),
            "verifierOccupancy": (
                self.candidates / (self.launches * self.device.batch_size)
                if self.launches
                else 0.0
            ),
            # launch fill plane (dispatch-side): per-launch occupied lanes /
            # lane capacity — mean over every dispatched launch plus the
            # most recent launch's fill. The coalescing win metric: a
            # multi-session service should fill lanes the single-session
            # baseline leaves empty.
            "launchFillRatio": (
                self.fill_sum / self.fill_launches if self.fill_launches
                else 0.0
            ),
            "lastLaunchFill": self.last_fill,
            "coalescedLaunches": float(self.coalesced_launches),
            # multi-tenant plane: live tenants with queued work, total
            # queued candidates, per-tenant admission refusals
            "sessionsQueued": float(self.queue.tenants()),
            "verifierQueueDepth": float(len(self.queue)),
            "admissionRefused": float(self.queue.refused),
            # SLO admission plane: tier-shed pushes + the shed fraction
            "admissionShed": float(self.queue.shed),
            "shedRate": self.queue.shed_rate(),
            # host cost of building device inputs (vectorized packer,
            # models/bn254_jax.py); 0 for device stubs without the counter.
            # The cumulative sums are counters; the *PerLaunch averages are
            # declared gauges so `sim watch` / Prometheus render a stable
            # per-launch number instead of a monotonically growing one.
            "hostPackMs": pack_ms,
            "hostPackLaunches": pack_n,
            "hostPackMsPerLaunch": pack_ms / pack_n if pack_n else 0.0,
            # the other host half of a launch: staging handoff + async
            # kernel enqueue (host_dispatch_ms split, models/bn254_jax.py)
            "hostDispatchMs": disp_ms,
            "hostDispatchLaunches": disp_n,
            "hostDispatchMsPerLaunch": disp_ms / disp_n if disp_n else 0.0,
            # resilience plane: worst lane state + fleet-summed counters
            "breakerState": max(
                BREAKER_CODE[l.breaker.state] for l in self.plane.lanes
            ),
            "breakerOpenCt": float(
                sum(l.breaker.open_count for l in self.plane.lanes)
            ),
            # every observed open/half-open/close edge across the fleet
            # (utils/breaker.py transitions) — the storm-detection signal
            # the alert plane differences (obs/detect.py counter_rate)
            "breakerTransitionsCt": float(
                sum(l.breaker.transitions for l in self.plane.lanes)
            ),
            "deviceRetryCt": float(self.device_retries),
            "failoverBatches": float(self.failover_batches),
            "failoverCandidates": float(self.failover_candidates),
            # dual-mode scheduling plane (parallel/mesh_plane.py): launch
            # groups by dispatched mode + latency-eligible groups that
            # found the mesh busy/broken and fell back to a lane
            "modeLatencyLaunches": float(self.latency_launches),
            "modeThroughputLaunches": float(self.throughput_launches),
            "meshFallbacks": float(self.mesh_fallbacks),
            # lifecycle plane: validator-set epoch + quiesce accounting
            "epoch": float(self.epoch),
            "quiesceCt": float(self.quiesce_ct),
            "lastQuiesceStallMs": self.last_quiesce_stall_ms,
            # fleet plane: lane count, admissible lanes, scheduler audit
            **self.plane.values(),
            # process-wide dedup plane (monitor keys: verifier_dedup*)
            **self.cache.values(),
        }

    def gauge_keys(self) -> set[str]:
        """Explicit gauge declarations (core/metrics.py is_gauge_key)."""
        return {
            "verifierOccupancy",
            "breakerState",
            "launchFillRatio",
            "lastLaunchFill",
            "sessionsQueued",
            "verifierQueueDepth",
            "hostPackMsPerLaunch",
            "hostDispatchMsPerLaunch",
            "devicesTotal",
            "devicesAvailable",
            "meshLanes",
            "meshLanesAvailable",
            "checkMode",
            "bisectionDepthMax",
            "epoch",
            "lastQuiesceStallMs",
            "shedRate",
        } | self.cache.gauge_keys()

"""Shared batch-verifier service: many logical nodes, one device launch.

SURVEY.md §2.4 ("Intra-instance concurrency" row): the reference packs many
Handel instances into one process (simul/node/main.go:61-78) but each verifies
serially on its own goroutine. Here all co-located nodes funnel their
(bitset, signature) candidates into one queue; a collector task drains it,
pads to the device batch size, and issues a single multi-pairing launch —
the device equivalent of a shared syscall batcher. This is the prerequisite
for single-host thousands-of-nodes simulation (VERDICT r1 item 9).
"""

from __future__ import annotations

import asyncio
from functools import partial
from typing import Callable, Sequence

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.store import VerifiedAggCache
from handel_tpu.core.trace import SERVICE_TID, trace_now
from handel_tpu.models.bn254_jax import BN254Device
from handel_tpu.utils.breaker import CircuitBreaker

__all__ = ["BatchVerifierService", "CircuitBreaker"]


# the host fallback contract: (msg, [(global bitset, signature)]) -> verdicts,
# synchronous (it runs in an executor thread). The natural implementation is
# the scheme's own host-side serial batch_verify over the registry pubkeys
# (core/crypto.py Constructor.batch_verify -> ops/bn254_ref math).
FallbackVerifier = Callable[[bytes, Sequence[tuple[BitSet, object]]], list]


class BatchVerifierService:
    """Fuses verify requests from any number of nodes into shared launches.

    Wire into every node's Config.verifier via `.verifier`. Requests are
    answered with per-candidate verdicts; the collector waits up to
    `max_delay_ms` to fill a batch (latency/occupancy tradeoff knob).

    Process-wide dedup: co-located nodes all receive (and would all verify)
    the same winning aggregate per level. Requests are keyed by exact
    content — (msg, bitset words, signature bytes) — against a shared
    `VerifiedAggCache`, so a candidate ANY co-located node already verified
    resolves instantly, and concurrent duplicates coalesce onto the one
    in-flight copy's lane instead of each taking their own.
    """

    def __init__(
        self,
        device: BN254Device,
        max_delay_ms: float = 2.0,
        max_inflight: int = 2,
        dedup_cache: VerifiedAggCache | None = None,
        fallback: FallbackVerifier | None = None,
        breaker: CircuitBreaker | None = None,
        retry_limit: int = 2,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 1.0,
        logger: Logger = DEFAULT_LOGGER,
        recorder=None,
    ):
        self.device = device
        # flight recorder (core/trace.py): dispatch-pack (host prep) and
        # device-verify (launch wall) spans + breaker/failover instants,
        # recorded on the service's own trace lane (SERVICE_TID)
        self.rec = recorder
        if recorder is not None:
            recorder.name_thread(SERVICE_TID, "batch-verifier")
        self.max_delay = max_delay_ms / 1000.0
        self.max_inflight = max(1, max_inflight)
        # -- resilience plane: breaker + host failover ---------------------
        # transient device errors retry with capped exponential backoff;
        # persistent ones open the breaker and route batches to `fallback`
        # (host reference verifier) so a dead accelerator degrades
        # throughput instead of stalling every node
        self.fallback = fallback
        self.breaker = breaker or CircuitBreaker()
        self.retry_limit = max(0, retry_limit)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.log = logger
        self.device_retries = 0
        self.failover_batches = 0
        self.failover_candidates = 0
        self._pending: list[tuple[bytes, BitSet, object, asyncio.Future]] = []
        self._kick = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._fetch_task: asyncio.Task | None = None
        self._fetch_q: asyncio.Queue | None = None
        # batches held by a pipeline stage OUTSIDE _pending/_fetch_q — the
        # collector's dispatch-in-progress and the fetcher's fetch-in-progress
        # — so stop() can fail their waiters too (a cancelled stage would
        # otherwise strand them awaiting forever; ADVICE r5 #1)
        self._collecting: list | None = None
        self._fetching: list | None = None
        # verified-aggregate dedup (shared across every node on this service)
        self.cache = dedup_cache or VerifiedAggCache(capacity=8192)
        self._inflight: dict[tuple, asyncio.Future] = {}
        # counters for the monitor plane
        self.launches = 0
        self.candidates = 0

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        # bounded handoff queue between the dispatch and fetch stages:
        # dispatch of launch N+1 proceeds while N's verdicts are still in
        # flight, so the per-dispatch round trip (~66 ms through this
        # environment's tunnel, results/verify_profile.json) amortizes
        # across concurrent launches instead of serializing with the chip
        # compute. maxsize bounds device-side queue depth.
        self._fetch_q = asyncio.Queue(maxsize=self.max_inflight)
        self._task = loop.create_task(self._collector())
        self._fetch_task = loop.create_task(self._fetcher())

    def stop(self) -> None:
        """Cancel both pipeline stages and FAIL any unanswered waiters —
        dropping them would leave callers awaiting forever. That includes
        the batch each stage holds OUTSIDE _pending/_fetch_q while it works
        (dispatch or fetch in flight): cancelling the stage strands those
        futures unless they are failed here. Resetting _task lets a later
        verify() restart the service."""
        if self._task:
            self._task.cancel()
            self._task = None
        if self._fetch_task:
            self._fetch_task.cancel()
            self._fetch_task = None
        err = RuntimeError("batch verifier stopped")
        if self._fetch_q is not None:
            while True:
                try:
                    _, _, items = self._fetch_q.get_nowait()
                except asyncio.QueueEmpty:
                    break
                for _, _, fut in items:
                    if not fut.done():
                        fut.set_exception(err)
            self._fetch_q = None
        for stage in (self._collecting, self._fetching):
            for _, _, fut in stage or ():
                if not fut.done():
                    fut.set_exception(err)
        self._collecting = self._fetching = None
        for _, _, _, fut in self._pending:
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()
        # coalesced duplicates chained onto a failed primary are resolved by
        # their done-callbacks when the loop next runs; nothing to do here
        self._inflight.clear()

    async def verify(self, msg, pubkeys, requests) -> list[bool]:
        """AsyncVerifier-compatible entry (core/processing.py)."""
        if self._task is None:
            self.start()
        loop = asyncio.get_running_loop()
        futs = []
        for bs, sig in requests:
            key = (msg, bs.words().tobytes(), sig.marshal())
            cached = self.cache.get(key)
            if cached is not None:
                # some co-located node already verified this exact aggregate
                fut = loop.create_future()
                fut.set_result(cached)
                futs.append(fut)
                continue
            primary = self._inflight.get(key)
            if primary is not None and not primary.done():
                # identical candidate already in flight: ride its lane. A
                # dedup hit for lane accounting — undo the get()'s miss count
                self.cache.misses -= 1
                self.cache.hits += 1
                fut = loop.create_future()
                primary.add_done_callback(partial(self._chain, fut))
                futs.append(fut)
                continue
            fut = loop.create_future()
            self._inflight[key] = fut
            fut.add_done_callback(partial(self._uninflight, key))
            self._pending.append((msg, bs, sig, fut))
            futs.append(fut)
        self._kick.set()
        return list(await asyncio.gather(*futs))

    @staticmethod
    def _chain(fut: asyncio.Future, primary: asyncio.Future) -> None:
        """Copy a resolved primary's outcome onto a coalesced duplicate."""
        if fut.done():
            return
        if primary.cancelled():
            fut.cancel()
        elif primary.exception() is not None:
            fut.set_exception(primary.exception())
        else:
            fut.set_result(primary.result())

    def _uninflight(self, key: tuple, fut: asyncio.Future) -> None:
        """Primary resolved: drop the in-flight marker and remember the
        verdict so later copies of this aggregate never reach the device."""
        if self._inflight.get(key) is fut:
            del self._inflight[key]
        if not fut.cancelled() and fut.exception() is None:
            self.cache.put(key, bool(fut.result()))

    @property
    def verifier(self):
        return self.verify

    async def _collector(self) -> None:
        while True:
            if not self._pending:
                self._kick.clear()
                await self._kick.wait()
            # brief accumulation window so co-located nodes share the launch
            if len(self._pending) < self.device.batch_size:
                await asyncio.sleep(self.max_delay)
            batch = self._pending[: self.device.batch_size]
            self._pending = self._pending[self.device.batch_size :]
            if not batch:
                continue
            # from here until every group is handed to _fetch_q the batch
            # lives in neither _pending nor the queue: track it on self so
            # stop() can fail these futures if this task is cancelled
            self._collecting = [(bs, sig, fut) for _, bs, sig, fut in batch]
            # group by message (one launch per distinct msg in the batch;
            # a simulation run shares a single msg, so this is one launch)
            by_msg: dict[bytes, list[tuple[BitSet, object, asyncio.Future]]] = {}
            for msg, bs, sig, fut in batch:
                by_msg.setdefault(msg, []).append((bs, sig, fut))
            for msg, items in by_msg.items():
                reqs = [(bs, sig) for bs, sig, _ in items]
                handle = None
                if self.breaker.allow():
                    # dispatch only (host prep + async enqueue) — the fetch
                    # stage blocks on the verdicts so this loop can already
                    # build and dispatch the next launch. Transient errors
                    # retry with capped exponential backoff; each failure
                    # feeds the breaker.
                    t0 = trace_now()
                    handle = await self._dispatch_with_retries(msg, reqs)
                    if self.rec is not None and self.rec.enabled:
                        # the host half of a launch: request packing + the
                        # async enqueue (PR 1's host_pack_ms lives in here)
                        self.rec.span(
                            "dispatch_pack",
                            t0,
                            trace_now(),
                            tid=SERVICE_TID,
                            cat="verifier",
                            args={"n": len(reqs), "ok": handle is not None},
                        )
                if handle is None:
                    # breaker open, or retries exhausted: host failover
                    # (or fail the futures when no fallback exists)
                    await self._failover(msg, items)
                    continue
                await self._fetch_q.put((handle, msg, items))
            self._collecting = None

    async def _dispatch_with_retries(self, msg, reqs):
        """Try the device up to 1 + retry_limit times; None = gave up."""
        loop = asyncio.get_running_loop()
        for attempt in range(1 + self.retry_limit):
            try:
                return await loop.run_in_executor(
                    None, partial(self.device.dispatch, msg, reqs)
                )
            except asyncio.CancelledError:
                raise  # stop() fails the futures via _collecting
            except Exception as e:
                self.breaker.record_failure()
                if self.rec is not None:
                    self.rec.instant(
                        "device_error",
                        tid=SERVICE_TID,
                        cat="verifier",
                        args={"stage": "dispatch", "breaker": self.breaker.state},
                    )
                self.log.warn(
                    "verifier_device_error",
                    f"dispatch attempt {attempt + 1}: {e}",
                )
                if not self.breaker.allow() or attempt >= self.retry_limit:
                    return None
                self.device_retries += 1
                await asyncio.sleep(
                    min(self.backoff_base_s * 2**attempt, self.backoff_cap_s)
                )
        return None

    async def _failover(self, msg, items) -> None:
        """Resolve a batch through the host reference verifier; with no
        fallback configured, fail the futures (BatchProcessing requeues the
        candidates under its retry budget — the pre-breaker behavior)."""
        if self.fallback is None:
            err = RuntimeError("batch verifier: device unavailable")
            for _, _, fut in items:
                if not fut.done():
                    fut.set_exception(err)
            return
        if self.rec is not None:
            self.rec.instant(
                "verifier_failover",
                tid=SERVICE_TID,
                cat="verifier",
                args={"n": len(items), "breaker": self.breaker.state},
            )
        reqs = [(bs, sig) for bs, sig, _ in items]
        loop = asyncio.get_running_loop()
        try:
            verdicts = await loop.run_in_executor(
                None, partial(self.fallback, msg, reqs)
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            for _, _, fut in items:
                if not fut.done():
                    fut.set_exception(RuntimeError(f"batch verifier: {e}"))
            return
        self.failover_batches += 1
        self.failover_candidates += len(items)
        for (_, _, fut), ok in zip(items, verdicts):
            if not fut.done():
                fut.set_result(bool(ok))

    async def _fetcher(self) -> None:
        """Second pipeline stage: pull verdicts for dispatched launches, in
        dispatch order, and resolve the waiters."""
        loop = asyncio.get_running_loop()
        while True:
            handle, msg, items = await self._fetch_q.get()
            # outside _fetch_q until resolved: visible to stop() (see
            # _collector's mirror note)
            self._fetching = items
            t0 = trace_now()
            try:
                verdicts = await loop.run_in_executor(
                    None, partial(self.device.fetch, handle)
                )
            except asyncio.CancelledError:
                raise  # stop() fails the futures via _fetching
            except Exception as e:
                # a fetch-side device death (verdict transfer failed) takes
                # the same breaker + host-failover path as dispatch errors
                self.breaker.record_failure()
                self.log.warn("verifier_device_error", f"fetch: {e}")
                await self._failover(msg, items)
                self._fetching = None
                continue
            if self.rec is not None and self.rec.enabled:
                # device wall per launch (verdict-arrival latency), the
                # counterpart of dispatch_pack's host half
                self.rec.span(
                    "device_verify",
                    t0,
                    trace_now(),
                    tid=SERVICE_TID,
                    cat="verifier",
                    args={"n": len(items)},
                )
            self.breaker.record_success()
            self.launches += 1
            self.candidates += len(items)
            for (_, _, fut), ok in zip(items, verdicts):
                if not fut.done():
                    fut.set_result(ok)
            self._fetching = None

    def values(self) -> dict[str, float]:
        pack_ms = float(getattr(self.device, "host_pack_ms", 0.0))
        pack_n = float(getattr(self.device, "host_pack_launches", 0))
        disp_ms = float(getattr(self.device, "host_dispatch_ms", 0.0))
        disp_n = float(getattr(self.device, "host_dispatch_launches", 0))
        return {
            "verifierLaunches": float(self.launches),
            "verifierCandidates": float(self.candidates),
            "verifierOccupancy": (
                self.candidates / (self.launches * self.device.batch_size)
                if self.launches
                else 0.0
            ),
            # host cost of building device inputs (vectorized packer,
            # models/bn254_jax.py); 0 for device stubs without the counter.
            # The cumulative sums are counters; the *PerLaunch averages are
            # declared gauges so `sim watch` / Prometheus render a stable
            # per-launch number instead of a monotonically growing one.
            "hostPackMs": pack_ms,
            "hostPackLaunches": pack_n,
            "hostPackMsPerLaunch": pack_ms / pack_n if pack_n else 0.0,
            # the other host half of a launch: staging handoff + async
            # kernel enqueue (host_dispatch_ms split, models/bn254_jax.py)
            "hostDispatchMs": disp_ms,
            "hostDispatchLaunches": disp_n,
            "hostDispatchMsPerLaunch": disp_ms / disp_n if disp_n else 0.0,
            # resilience plane: breaker + host-failover counters
            "breakerState": {"closed": 0.0, "half-open": 0.5, "open": 1.0}[
                self.breaker.state
            ],
            "breakerOpenCt": float(self.breaker.open_count),
            "deviceRetryCt": float(self.device_retries),
            "failoverBatches": float(self.failover_batches),
            "failoverCandidates": float(self.failover_candidates),
            # process-wide dedup plane (monitor keys: verifier_dedup*)
            **self.cache.values(),
        }

    def gauge_keys(self) -> set[str]:
        """Explicit gauge declarations (core/metrics.py is_gauge_key)."""
        return {
            "verifierOccupancy",
            "breakerState",
            "hostPackMsPerLaunch",
            "hostDispatchMsPerLaunch",
        } | self.cache.gauge_keys()

"""Device telemetry: XLA/runtime gauges + on-demand profiler capture.

The verification plane's health questions ("is the chip compiling mid-run?",
"is device memory growing?", "how deep is the dispatch queue?") have no
monitor-plane answer — the CSV lands after the run. This collector samples
them live into the metrics registry (core/metrics.py, plane "device"):

    handel_device_xla_compile_ct        jax.monitoring compile events
    handel_device_live_arrays           jax.live_arrays() count
    handel_device_live_array_bytes      total nbytes of live arrays
    handel_device_mem_bytes_in_use      runtime memory_stats (TPU; 0 on CPU)
    handel_device_dispatch_queue_depth  BatchVerifierService pending lane
    handel_device_inflight_launches     dispatched, verdicts not yet fetched
    handel_device_breaker_state         0 closed / 0.5 half-open / 1 open
    handel_device_mesh_lanes            latency-plane mesh lanes (+_available)
    handel_device_mesh_launches         launches that rode the whole mesh

jax is imported lazily and every sample degrades to 0.0 on a missing API —
a fake-scheme node (which must never import jax) can still register this
collector as long as no scrape arrives, and a CPU-only run scrapes zeros
for the TPU-only stats instead of erroring.

`profile(seconds)` is the `POST /debug/profile?seconds=N` handler: captures
a `jax.profiler` trace into the run's trace dir (reusing the `--trace-dir`
plumbing from the span flight recorder) and returns the capture directory.
"""

from __future__ import annotations

import os
import threading
import time

#: process-wide compile counters, fed by the jax.monitoring listeners
#: (registered at most once per process; listeners cannot be unregistered
#: individually, so the counters live at module scope, not per collector)
_compile_events = 0
_compile_secs = 0.0
_listener_registered = False
_listener_lock = threading.Lock()

#: one entry per backend (XLA) compilation — the mid-run-compile detector;
#: jax 0.4.x records it as a duration event
_COMPILE_EVENT = "backend_compile"


def _on_event(event: str, **kwargs) -> None:
    global _compile_events
    if _COMPILE_EVENT in event:
        _compile_events += 1


def _on_duration(event: str, duration_secs: float, **kwargs) -> None:
    global _compile_events, _compile_secs
    if _COMPILE_EVENT in event:
        _compile_events += 1
        _compile_secs += float(duration_secs)


def _ensure_listener() -> bool:
    """Register the compile listeners once (both forms: plain events and
    duration events — jax 0.4.x reports backend compiles as the latter);
    False if the monitoring API is unavailable in this jax build."""
    global _listener_registered
    with _listener_lock:
        if _listener_registered:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(_on_duration)
            _listener_registered = True
            return True
        except Exception:
            return False


class DeviceTelemetry:
    """Reporter-shaped (`values()` / `gauge_keys()`) device-state sampler.

    service: the process's BatchVerifierService, or None (chip-less node).
    trace_dir: where `profile()` drops its capture ("" = a tmp-adjacent
    default under the current directory).
    """

    def __init__(self, service=None, trace_dir: str = ""):
        self.service = service
        self.trace_dir = trace_dir
        self.profile_captures = 0
        self._profiling = threading.Lock()
        _ensure_listener()

    # -- sampling ------------------------------------------------------------

    def _jax(self):
        """The already-imported jax module, or None. NEVER imports: a scrape
        must not be the thing that initializes a backend (or hangs on a
        downed TPU tunnel)."""
        import sys

        return sys.modules.get("jax")

    def values(self) -> dict[str, float]:
        out = {
            "xlaCompileCt": float(_compile_events),
            "xlaCompileTimeMs": _compile_secs * 1000.0,
            "liveArrays": 0.0,
            "liveArrayBytes": 0.0,
            "memBytesInUse": 0.0,
            "dispatchQueueDepth": 0.0,
            "inflightLaunches": 0.0,
            "breakerState": 0.0,
            "deviceLanes": 0.0,
            "deviceLanesAvailable": 0.0,
            "meshLanes": 0.0,
            "meshLanesAvailable": 0.0,
            "meshLaunches": 0.0,
            "profileCaptures": float(self.profile_captures),
        }
        jax = self._jax()
        if jax is not None:
            try:
                live = jax.live_arrays()
                out["liveArrays"] = float(len(live))
                out["liveArrayBytes"] = float(
                    sum(getattr(a, "nbytes", 0) for a in live)
                )
            except Exception:
                pass
            try:
                stats = jax.local_devices()[0].memory_stats()
                if stats:
                    out["memBytesInUse"] = float(
                        stats.get("bytes_in_use", 0.0)
                    )
            except Exception:
                pass  # CPU backends have no memory_stats
        svc = self.service
        if svc is not None:
            out["dispatchQueueDepth"] = float(svc.queue_depth())
            # fleet-aware in-flight count (parallel/plane.py): sums every
            # lane's window, not just device 0's. Stub services without the
            # method fall back to the single _fetch_q.
            infl = getattr(svc, "inflight_launches", None)
            if callable(infl):
                out["inflightLaunches"] = float(infl())
            else:
                q = getattr(svc, "_fetch_q", None)
                out["inflightLaunches"] = (
                    float(q.qsize()) if q is not None else 0.0
                )
            out["breakerState"] = {
                "closed": 0.0, "half-open": 0.5, "open": 1.0
            }[svc.breaker.state]
            plane = getattr(svc, "plane", None)
            if plane is not None:
                out["deviceLanes"] = float(len(plane.lanes))
                out["deviceLanesAvailable"] = float(len(plane.allowed()))
                # latency plane (parallel/mesh_plane.py): mesh lane census
                # and whole-mesh launch count; getattr keeps pre-mesh stub
                # planes scrapeable
                mesh_lanes = getattr(plane, "mesh_lanes", None)
                if callable(mesh_lanes):
                    mesh = mesh_lanes()
                    out["meshLanes"] = float(len(mesh))
                    out["meshLanesAvailable"] = float(sum(
                        1 for l in mesh
                        if not l.draining and l.breaker.allow()
                    ))
                    out["meshLaunches"] = float(
                        sum(l.launches for l in mesh)
                    )
            else:
                out["deviceLanes"] = out["deviceLanesAvailable"] = 1.0
        return out

    def gauge_keys(self) -> set[str]:
        # everything here is point-in-time except the event/launch counters
        return {
            "liveArrays", "liveArrayBytes", "memBytesInUse",
            "dispatchQueueDepth", "inflightLaunches", "breakerState",
            "deviceLanes", "deviceLanesAvailable",
            "meshLanes", "meshLanesAvailable",
        }

    # -- profiler capture (POST /debug/profile) ------------------------------

    def profile(self, seconds: float) -> str:
        """Capture a jax.profiler trace for `seconds`; returns the capture
        dir. Raises on an unavailable profiler (the HTTP layer turns that
        into a 500/501, never a crash) and refuses concurrent captures."""
        jax = self._jax()
        if jax is None:
            raise RuntimeError("jax not initialized in this process")
        if not self._profiling.acquire(blocking=False):
            raise RuntimeError("a profile capture is already running")
        try:
            out = os.path.join(
                self.trace_dir or os.getcwd(),
                f"profile_{int(time.time())}",
            )
            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            self.profile_captures += 1
            return out
        finally:
            self._profiling.release()

    def profiler(self):
        """The MetricsServer `profiler=` hook: seconds -> capture dir."""
        return self.profile

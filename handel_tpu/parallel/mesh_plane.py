"""Mesh latency plane: one verify launch model-parallel over the whole mesh.

ROADMAP item 2: PR 9 sharded *across* launches — `bn254_plane` pins one
engine per chip and the DevicePlane schedules launch groups least-loaded —
but one Miller loop + final exponentiation still ran on a single chip, so a
small/urgent batch (the ACE "sub-second cryptographic finality" regime,
PAPERS.md arxiv 2603.10242) could never use more than 1/K of the mesh. This
module adds the second shape: a MESH LANE whose engine spans ALL K devices
for a single launch (`BN254Device(mesh_devices=K)` — registry axis of the
masked G2 sum and candidate axis of the Miller loop/final exp partitioned
with shard_map, parallel/sharding.py), plus the policy that decides, per
launch group, which shape it rides:

  * **latency** mode — the group is small enough to fit one mesh launch,
    the backlog is shallow, and its best SLO tier is entitled to the mesh
    (gold by default): route to the mesh lane, cutting the single-launch
    wall ~K/2x (`small_batch_verify_p50_ms` bench contract).
  * **throughput** mode — bulk batches and backlogged queues: today's
    per-lane path, where the mesh is worth more as K independent lanes.

The scheduler integration lives in `DevicePlane.pick_mesh` (parallel/
plane.py) and `BatchVerifierService._route_mesh` (batch_verifier.py); this
module owns the policy, the engine builders, and the CI/bench host engine.
Like plane.py, nothing here imports jax at module level — the jax-backed
builder (`bn254_mesh_engine`) imports lazily.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from handel_tpu.service.fairness import SloTier

__all__ = [
    "MODE_LATENCY",
    "MODE_THROUGHPUT",
    "ModePolicy",
    "HostMeshDevice",
    "bn254_mesh_engine",
    "host_mesh_engine",
    "enable_latency_plane",
]

MODE_LATENCY = "latency"
MODE_THROUGHPUT = "throughput"


@dataclass(frozen=True)
class ModePolicy:
    """When does a launch group ride the whole-mesh latency lane?

    `small_batch_max` caps latency-mode group size (the mesh engine's own
    batch_size also caps it — a group must fit ONE mesh launch).
    `max_queue_depth` is the backlog bound: a queue deeper than this keeps
    groups on the per-lane throughput path, where K independent lanes beat
    one fast lane. `latency_tiers` names the SLO tiers (service/fairness.py
    TIERS) entitled to the mesh — the routing table HACKING.md documents:
    gold-tier small batches go latency, bronze bulk stays per-lane.
    """

    small_batch_max: int = 64
    max_queue_depth: int = 128
    latency_tiers: tuple = ("gold",)

    def pick_mode(
        self,
        n_items: int,
        queue_depth: int,
        tier,
        mesh_batch: int,
    ) -> str:
        if n_items > min(self.small_batch_max, mesh_batch):
            return MODE_THROUGHPUT
        if queue_depth > self.max_queue_depth:
            return MODE_THROUGHPUT
        name = tier.name if isinstance(tier, SloTier) else str(tier)
        if name not in self.latency_tiers:
            return MODE_THROUGHPUT
        return MODE_LATENCY


class HostMeshDevice:
    """Host-math engine modeling ONE whole-mesh launch (the CI/bench shape).

    The real latency engine is `BN254Device(mesh_devices=K)`; its pairing
    walls can't be measured on a CI box where K forced host devices share
    one core, so — exactly like service/driver.py HostDevice under
    fleet_bench — this engine keeps the real verdict math (the scheme
    constructor's batch_verify) and SIMULATES the wall. Unlike HostDevice's
    fixed `launch_ms`, the wall here models INTRA-launch parallelism: each
    candidate costs `per_candidate_ms`, the candidates shard over
    `devices` concurrent workers (real threads — the measured wall is the
    max over workers, contention included), and `collective_ms` is the
    serial all_gather + combine-tree share that Amdahl-caps the win. So a
    batch-n launch walls ~ per_candidate_ms * ceil(n/K) + collective_ms,
    and `devices=1` is the single-lane baseline with identical code — the
    pair the `small_batch_verify_p50_ms` bench contract compares.
    """

    def __init__(
        self,
        constructor,
        batch_size: int = 64,
        devices: int = 8,
        per_candidate_ms: float = 1.0,
        collective_ms: float = 0.5,
    ):
        self.constructor = constructor
        self.batch_size = batch_size
        self.mesh_devices = max(1, devices)
        self.per_candidate_ms = per_candidate_ms
        self.collective_ms = collective_ms
        self.dispatched = 0
        self.mesh_launches = 0
        self.mesh_candidates = 0
        self._pool = (
            ThreadPoolExecutor(max_workers=self.mesh_devices)
            if self.mesh_devices > 1
            else None
        )
        # epoch-rotation protocol parity (lifecycle/epoch.py, same stubs as
        # HostDevice): no resident bank to flip, but the stage -> quiesce ->
        # activate choreography must reach mesh lanes too
        self.epoch = 0
        self._staged = None
        self.registry_stagings = 0
        self.registry_staged_ms = 0.0

    def stage_registry(self, registry_pubkeys, build_prefix: bool = True) -> int:
        self._staged = registry_pubkeys
        self.registry_stagings += 1
        return len(registry_pubkeys)

    def activate_staged(self) -> int:
        if self._staged is None:
            raise RuntimeError("no staged registry: call stage_registry first")
        self._staged = None
        self.epoch += 1
        return self.epoch

    def _verify_shard(self, items, idxs):
        verdicts = {}
        for i in idxs:
            msg, pubkeys, bitset, sig = items[i]
            ok = self.constructor.batch_verify(msg, pubkeys, [(bitset, sig)])
            verdicts[i] = bool(ok[0])
        if self.per_candidate_ms > 0:
            time.sleep(self.per_candidate_ms * len(idxs) / 1000.0)
        return verdicts

    def dispatch_multi(self, items):
        k = self.mesh_devices
        shards = [list(range(i, len(items), k)) for i in range(k)]
        shards = [s for s in shards if s]
        if self._pool is None or len(shards) <= 1:
            merged = self._verify_shard(items, list(range(len(items))))
        else:
            futs = [
                self._pool.submit(self._verify_shard, items, s)
                for s in shards
            ]
            merged = {}
            for f in futs:
                merged.update(f.result())
        if self.collective_ms > 0:
            time.sleep(self.collective_ms / 1000.0)
        self.dispatched += 1
        self.mesh_launches += 1
        self.mesh_candidates += len(items)
        return [merged[i] for i in range(len(items))]

    def fetch(self, handle):
        return handle


def host_mesh_engine(
    constructor,
    devices: int = 8,
    batch_size: int = 64,
    per_candidate_ms: float = 1.0,
    collective_ms: float = 0.5,
) -> HostMeshDevice:
    """The CI/bench mesh engine (see HostMeshDevice)."""
    return HostMeshDevice(
        constructor,
        batch_size=batch_size,
        devices=devices,
        per_candidate_ms=per_candidate_ms,
        collective_ms=collective_ms,
    )


def bn254_mesh_engine(
    registry_pubkeys,
    devices: int,
    batch_size: int = 8,
    curves=None,
    warmup: bool = False,
):
    """The real whole-mesh latency engine: ONE BN254Device spanning all K
    devices (`mesh_devices=K` — the staged sharded pipeline of models/
    bn254_jax.py), vs `bn254_plane`'s one-engine-per-chip throughput shape.
    Warmup is off by default for the same reason as bn254_plane: the
    pairing tail compiles in minutes — smokes drive the aggregation stage
    standalone."""
    import jax

    from handel_tpu.models.bn254_jax import BN254Device
    from handel_tpu.ops.curve import BN254Curves

    if devices > len(jax.devices()):
        raise ValueError(
            f"mesh of {devices} devices requested but only "
            f"{len(jax.devices())} visible "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    eng = BN254Device(
        registry_pubkeys,
        batch_size=batch_size,
        curves=curves or BN254Curves(),
        mesh_devices=devices,
    )
    if warmup:
        eng.warmup()
    return eng


def enable_latency_plane(service, engine, policy: ModePolicy | None = None,
                         breaker=None):
    """Attach `engine` as the service's mesh lane and arm dual-mode
    scheduling (BatchVerifierService._route_mesh consults the policy the
    moment a mesh lane exists). On a running service the lane's
    dispatcher/fetcher pair spawns immediately; before start() it simply
    joins the plane and wires with the rest. Returns the new lane."""
    if policy is not None:
        service.mode_policy = policy
    return service.attach_lane(engine, breaker, mesh=True)

"""Network batch-verification plane: chip-less hosts verify via the TPU host.

The reference scales verification by giving every AWS instance its own
cores (simul/platform/aws.go fleet); this framework's analog resource is
ONE accelerator shared by the whole fleet (BASELINE.json north_star:
candidate batches marshaled to a co-located JAX worker). In a
RemotePlatform run only the host holding the chip can launch kernels, so
every other host's nodes ship their (bitset, signature) candidates to it
over a length-prefixed TCP protocol and get verdicts back; the device
host fuses local and remote candidates into the same shared launches
through its BatchVerifierService (parallel/batch_verifier.py).

No external RPC dependency (the image has no grpc/capnp): frames are
struct-packed, length-prefixed, multiplexed by request id over one
persistent connection per client process — the same single-event-loop
discipline as the rest of the runtime.

Wire format (all big-endian):
  frame    := u32 body_len || body
  request  := u64 req_id || u32 msg_len || msg
              || u16 count || count * item
  item     := u32 bs_len || bitset.marshal() || u32 sig_len || sig.marshal()
  response := u64 req_id || u8 status || payload
              (status 0: payload = count verdict bytes 0/1;
               status 1: payload = utf-8 error text)

Faults: a dropped connection fails all in-flight futures; the caller
(core/processing.py BatchProcessing) requeues those candidates with its
per-candidate retry budget, and the client reconnects on the next verify
call — so a verifier-host restart degrades to retries, not node crashes.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Sequence

from handel_tpu.core.bitset import BitSet
from handel_tpu.network.stream import TaskSet, frame

_MAX_FRAME = 64 << 20  # hard cap against a malformed/hostile length prefix


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    hdr = await reader.readexactly(4)
    (length,) = struct.unpack(">I", hdr)
    if length > _MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds cap")
    return await reader.readexactly(length)


def _write_frame(writer: asyncio.StreamWriter, body: bytes) -> None:
    writer.write(frame(body))


def _pack_request(req_id: int, msg: bytes, requests) -> bytes:
    parts = [struct.pack(">QI", req_id, len(msg)), msg,
             struct.pack(">H", len(requests))]
    for bs, sig in requests:
        b, s = bs.marshal(), sig.marshal()
        parts.append(struct.pack(">I", len(b)))
        parts.append(b)
        parts.append(struct.pack(">I", len(s)))
        parts.append(s)
    return b"".join(parts)


def _unpack_request(body: bytes, constructor):
    req_id, msg_len = struct.unpack_from(">QI", body, 0)
    off = 12
    msg = body[off : off + msg_len]
    off += msg_len
    (count,) = struct.unpack_from(">H", body, off)
    off += 2
    requests = []
    for _ in range(count):
        (bs_len,) = struct.unpack_from(">I", body, off)
        off += 4
        bs, consumed = BitSet.unmarshal(body[off : off + bs_len])
        if consumed != bs_len:
            raise ValueError("bitset length mismatch in rpc item")
        off += bs_len
        (sig_len,) = struct.unpack_from(">I", body, off)
        off += 4
        sig = constructor.unmarshal_signature(body[off : off + sig_len])
        off += sig_len
        requests.append((bs, sig))
    return req_id, msg, requests


class VerifierServer:
    """Serves a local BatchVerifierService over TCP.

    Runs in the device host's node process (sim/node.py --serve-verifier):
    remote candidates join the local nodes' shared launch queue, so one
    chip serves the whole fleet at full batch occupancy.
    """

    def __init__(self, service, constructor, host: str = "0.0.0.0",
                 port: int = 0):
        self.service = service  # BatchVerifierService (or any .verify)
        self.constructor = constructor
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        # monitor plane
        self.requests_served = 0
        self.candidates_served = 0
        self.errors = 0

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    def stop(self) -> None:
        if self._server:
            self._server.close()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # one writer lock per connection: responses from concurrently
        # processed requests must not interleave mid-frame
        lock = asyncio.Lock()
        tasks = TaskSet()
        try:
            while True:
                try:
                    body = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                tasks.spawn(self._serve_one(body, writer, lock))
        finally:
            tasks.cancel_all()
            writer.close()

    async def _serve_one(self, body: bytes, writer, lock) -> None:
        # recover req_id independently of full request parsing: an error
        # response under id 0 would resolve NO client future and leave the
        # sender's verify() awaiting forever
        req_id = (
            struct.unpack_from(">Q", body, 0)[0] if len(body) >= 8 else 0
        )
        try:
            req_id, msg, requests = _unpack_request(body, self.constructor)
            verdicts = await self.service.verify(msg, None, requests)
            payload = struct.pack(">QB", req_id, 0) + bytes(
                1 if v else 0 for v in verdicts
            )
            self.requests_served += 1
            self.candidates_served += len(requests)
        except Exception as e:  # malformed frame or device failure
            self.errors += 1
            payload = struct.pack(">QB", req_id, 1) + str(e).encode()[:512]
        async with lock:
            try:
                _write_frame(writer, payload)
                await writer.drain()
            except ConnectionError:
                pass  # client gone; its futures fail on their side

    def values(self) -> dict[str, float]:
        return {
            "rpcServedRequests": float(self.requests_served),
            "rpcServedCandidates": float(self.candidates_served),
            "rpcServeErrors": float(self.errors),
        }


class RPCVerifier:
    """AsyncVerifier client: ships candidate batches to a VerifierServer.

    Drop-in for Config.verifier (core/processing.py AsyncVerifier shape —
    the `pubkeys` argument is ignored; the server's device holds the
    registry). One persistent connection per process, multiplexed by
    request id; lazy (re)connect with a handful of quick retries so node
    startup races against the server's bind are absorbed.
    """

    def __init__(self, address: str, connect_retries: int = 20,
                 retry_delay: float = 0.5):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.connect_retries = connect_retries
        self.retry_delay = retry_delay
        self._writer: asyncio.StreamWriter | None = None
        self._reader_task: asyncio.Task | None = None
        self._inflight: dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._conn_lock = asyncio.Lock()
        # monitor plane
        self.requests_sent = 0
        self.candidates_sent = 0
        self.errors = 0

    async def _connect(self) -> None:
        last: Exception | None = None
        for _ in range(self.connect_retries):
            try:
                reader, writer = await asyncio.open_connection(
                    self.host, self.port
                )
                self._writer = writer
                self._reader_task = asyncio.get_running_loop().create_task(
                    self._read_loop(reader)
                )
                return
            except OSError as e:
                last = e
                await asyncio.sleep(self.retry_delay)
        raise ConnectionError(
            f"verifier server {self.host}:{self.port} unreachable: {last}"
        )

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                body = await _read_frame(reader)
                req_id, status = struct.unpack_from(">QB", body, 0)
                fut = self._inflight.pop(req_id, None)
                if fut is None or fut.done():
                    continue
                if status == 0:
                    fut.set_result([b == 1 for b in body[9:]])
                else:
                    fut.set_exception(
                        RuntimeError(
                            f"verifier server: {body[9:].decode(errors='replace')}"
                        )
                    )
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            ValueError,
            struct.error,  # body under 9 bytes: garbage on the port
        ) as e:
            # only the CURRENT connection's reader may tear down shared
            # state: a stale reader surviving a reconnect would otherwise
            # fail the new connection's futures and null the fresh writer
            if self._reader_task is asyncio.current_task():
                self._teardown(e)

    def _teardown(self, exc: Exception) -> None:
        """Drop the connection and fail everything that rode it. In-flight
        futures all belong to the dying connection (reconnect happens
        before new registrations), so failing them routes those candidates
        into BatchProcessing's retry path."""
        task = self._reader_task
        self._reader_task = None
        if task is not None and task is not asyncio.current_task():
            task.cancel()
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        self._fail_inflight(exc)

    def _fail_inflight(self, exc: Exception) -> None:
        self.errors += 1
        for fut in self._inflight.values():
            if not fut.done():
                fut.set_exception(ConnectionError(f"verifier link lost: {exc}"))
        self._inflight.clear()

    def stop(self) -> None:
        task = self._reader_task
        self._reader_task = None
        if task is not None:
            task.cancel()
        if self._writer:
            self._writer.close()
            self._writer = None

    async def verify(self, msg: bytes, pubkeys, requests) -> list[bool]:
        async with self._conn_lock:
            if self._writer is None:
                await self._connect()
            writer = self._writer
        self._next_id += 1
        req_id = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._inflight[req_id] = fut
        try:
            _write_frame(writer, _pack_request(req_id, msg, requests))
            await writer.drain()
        except (ConnectionError, OSError) as e:
            # the link is dead for every in-flight request, not just this
            # one — tear down so siblings fail fast into their retry path
            # instead of awaiting responses that will never arrive. Our own
            # future is popped first (we raise; nobody will await it)
            self._inflight.pop(req_id, None)
            self._teardown(e)
            raise ConnectionError(f"verifier send failed: {e}") from e
        self.requests_sent += 1
        self.candidates_sent += len(requests)
        return await fut

    @property
    def verifier(self):
        return self.verify

    def values(self) -> dict[str, float]:
        return {
            "rpcSentRequests": float(self.requests_sent),
            "rpcSentCandidates": float(self.candidates_sent),
            "rpcLinkErrors": float(self.errors),
        }

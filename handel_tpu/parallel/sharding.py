"""Mesh sharding for the verification batch plane.

The scaling axis of this framework is the pairing/aggregation batch
(SURVEY.md §5.7): candidates shard over the mesh's data axis, the registry
shards over the same devices for the masked G2 segment-sum, and partial sums
combine with an `all_gather` + log-depth point-add tree (EC point addition is
not an elementwise monoid, so `psum` does not apply; the gather rides ICI).

Two entry points:
  * `sharded_masked_sum_g2` — shard_map over the registry axis: each device
    masked-tree-sums its registry shard for every candidate, then all_gather
    + combine. Explicit-collective form.
  * `sharded_pairing_check` — shard_map over the candidate axis: each device
    runs the Miller loop + shared final exp for its local candidates (both
    pairs of a candidate live on its home device), zero collectives.
    shard_map (not jit-with-shardings) deliberately: XLA compiles the small
    per-device program directly; running GSPMD's propagation/partitioning
    passes over a pairing-sized graph measured >1 h on a 1-core CPU host,
    vs minutes for the shard_map body.

Latency-plane partition rules (ROADMAP item 2, parallel/mesh_plane.py):
`launch_partition_rules` + `match_partition_rules` map launch-operand NAMES
to PartitionSpecs by first-matching regex — the rule-matching idiom of the
t5x/EasyLM partitioning helpers (SNIPPETS.md [1]) — and `make_shard_fns`
turns the matched specs into per-operand placement functions
(SNIPPETS.md [2]'s shard_fns, built on `jax.device_put` + NamedSharding
rather than pjit for the GSPMD-avoidance reason above). BN254Device's mesh
path uses them to pre-place per-launch operands in their shard_map layout,
so the whole-mesh launch pays no per-launch all-to-all re-shard.
"""

from __future__ import annotations

import re
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map
except ImportError:  # pre-0.6 jax: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _exp_shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=check_vma,
        )

from jax.sharding import Mesh, PartitionSpec as P

from handel_tpu.ops.curve import BN254Curves
from handel_tpu.ops.pairing import BN254Pairing


def make_mesh(n_devices: int | None = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(
            f"mesh of {n} devices requested but only {len(devs)} visible "
            f"(platform {devs[0].platform}); for CPU tests set "
            f"xla_force_host_platform_device_count"
        )
    return Mesh(np.array(devs[:n]), (axis,))


def launch_partition_rules(axis: str = "dp"):
    """(regex, PartitionSpec) rules for one whole-mesh verify launch's
    operands, matched by name. The mesh-resident banks (registry
    coordinates, prefix table) shard their point axis; the per-launch
    candidate mask shards its registry-major rows with them; everything
    per-candidate (signatures, H(m), validity, range bounds) stays
    replicated — `sharded_pairing_check` re-shards candidates itself.

    Resident residue planes (ops/rns.py `to_resident`) are (k_all, B)
    like positional limb arrays — batch-last — so any operand spelled
    `res_*` / `resident_*` shards its trailing batch axis the same way
    the registry banks do.

    The RLC launch class (models/bn254_jax.py `_rlc_combined_launch`)
    adds three per-candidate operands — the random-coefficient bit plane
    `r_bits` (nbits, C), the message-group one-hot `group_oh` (G, C) and
    the group-occupancy mask `g_occ` (G,) — all candidate-axis-last and
    REPLICATED, named explicitly (not left to the catch-all) because the
    `mask`-style row rule must never capture them: sharding the scalar
    plane would split one candidate's bit column across chips and the
    MSM's bucket masks with it."""
    return (
        (r"^(reg|prefix)", P(None, axis)),
        (r"^res(ident)?_", P(None, axis)),
        (r"^mask$", P(axis, None)),
        (r"^(r_bits|group_oh|g_occ)$", P()),
        (r"", P()),
    )


def match_partition_rules(rules, names) -> dict:
    """{name: PartitionSpec} by FIRST matching rule (SNIPPETS.md [1]'s
    tree-path matcher, flattened to plain operand names — launches pass
    flat arrays, not a pytree of parameters). Rules are searched, not
    fullmatched, so one table covers `reg_x`/`reg_x0` spellings; a
    catch-all `(r"", P())` terminal makes the table total."""
    out = {}
    for name in names:
        for pat, spec in rules:
            if re.search(pat, name):
                out[name] = spec
                break
        else:
            raise ValueError(f"no partition rule matches operand {name!r}")
    return out


def make_shard_fns(mesh: Mesh, specs: dict) -> dict:
    """{name: placement fn} from matched specs: each fn `device_put`s its
    operand with the spec's NamedSharding so downstream shard_map regions
    see already-placed shards (SNIPPETS.md [2]'s make_shard_and_gather_fns
    role; device_put instead of pjit keeps GSPMD away from pairing-sized
    graphs — module docstring)."""
    from jax.sharding import NamedSharding

    return {
        name: partial(jax.device_put, device=NamedSharding(mesh, spec))
        for name, spec in specs.items()
    }


def sharded_masked_sum_g2(
    curves: BN254Curves, mesh: Mesh, n_registry: int, batch: int, axis: str = "dp"
):
    """Build a jitted masked G2 aggregation sharded over the registry axis.

    Returns fn(reg_x0, reg_x1, reg_y0, reg_y1, mask) -> projective G2 batch.
    reg_* are (L, N) limb arrays, mask is (N, batch) bool. Each device owns
    N/n_dev registry points, computes its partial masked tree-sum for all
    `batch` candidates, and the partials are all_gathered and combined with
    ceil(log2 n_dev) further point-add stages — the collective path the
    reference's serial Combine loop (processing.go:355-361) never needed.
    """
    g2 = curves.g2
    ndev = mesh.shape[axis]
    # non-divisible registries (4000 nodes on 8 chips) are padded up to the
    # next multiple with edge-replicated points masked out of every sum —
    # callers never see the padding
    pad_n = (-n_registry) % ndev
    local_n = (n_registry + pad_n) // ndev

    def local_block(reg_x0, reg_x1, reg_y0, reg_y1, mask):
        # shapes here are per-device: (L, local_n), (local_n, batch)
        tile = lambda a: jnp.repeat(a, batch, axis=1)
        Ppt = g2.from_affine(
            (tile(reg_x0), tile(reg_x1)), (tile(reg_y0), tile(reg_y1))
        )
        partial = g2.masked_sum(Ppt, mask.reshape(-1), local_n)
        # gather every device's partial point: leaves become (ndev, L, batch)
        gathered = jax.tree_util.tree_map(
            lambda a: jax.lax.all_gather(a, axis), partial
        )
        # combine with a log-depth point-add tree over the leading axis
        def level(pts, k):
            while k > 1:
                half = k // 2
                lo = jax.tree_util.tree_map(lambda a: a[:half], pts)
                hi = jax.tree_util.tree_map(lambda a: a[half : 2 * half], pts)
                s = g2_add_leading(lo, hi)
                if k % 2:
                    s = jax.tree_util.tree_map(
                        lambda a, b: jnp.concatenate([a, b[2 * half : k]], 0),
                        s,
                        pts,
                    )
                    k = half + 1
                else:
                    k = half
                pts = s
            return jax.tree_util.tree_map(lambda a: a[0], pts)

        def g2_add_leading(lo, hi):
            # vmap the complete add over the leading (device) axis
            return jax.vmap(g2.add)(lo, hi)

        return level(gathered, ndev)

    fn = shard_map(
        local_block,
        mesh=mesh,
        in_specs=(
            P(None, axis),
            P(None, axis),
            P(None, axis),
            P(None, axis),
            P(axis, None),
        ),
        out_specs=P(),  # combined point replicated on every device
        check_vma=False,
    )

    def padded(reg_x0, reg_x1, reg_y0, reg_y1, mask):
        # registries arriving PRE-PADDED to the device multiple — the
        # mesh-resident commit from `commit_registry_sharded` — skip the
        # pad and keep their committed shards (no per-launch re-shard);
        # unpadded arrays take the historical pad-inside-jit path. The
        # branch is on static shapes, so each caller traces exactly one
        # of the two forms.
        if pad_n and reg_x0.shape[1] == n_registry:
            pad_pt = lambda a: jnp.pad(a, ((0, 0), (0, pad_n)), mode="edge")
            reg_x0, reg_x1 = pad_pt(reg_x0), pad_pt(reg_x1)
            reg_y0, reg_y1 = pad_pt(reg_y0), pad_pt(reg_y1)
        if pad_n and mask.shape[0] == n_registry:
            # masks arriving pre-padded AND pre-placed in the registry-axis
            # sharding (launch_partition_rules / make_shard_fns) keep their
            # shards; unpadded masks pad inside the jit as before
            mask = jnp.pad(mask, ((0, pad_n), (0, 0)))  # padded rows: False
        return fn(reg_x0, reg_x1, reg_y0, reg_y1, mask)

    return jax.jit(padded)


def commit_registry_sharded(
    mesh: Mesh, reg_x, reg_y, n_registry: int, axis: str = "dp"
):
    """Commit a registry's (L, N) G2 coordinate arrays to the mesh ONCE,
    one shard per device — the multi-chip counterpart of the single-chip
    resident-registry commit in models/bn254_jax.py.

    Pads to the device multiple on the host (edge-replicated points, same
    rule as `sharded_masked_sum_g2`'s internal pad — the padded columns are
    masked out of every sum) and `device_put`s with the registry-axis
    NamedSharding, so `sharded_masked_sum_g2` sees already-placed shards
    instead of re-sharding the full replicated arrays every launch.
    Returns ((x0, x1), (y0, y1)) committed arrays.
    """
    from jax.sharding import NamedSharding

    ndev = mesh.shape[axis]
    pad_n = (-n_registry) % ndev
    sh = NamedSharding(mesh, P(None, axis))

    def put(a):
        a = np.asarray(a)
        if pad_n:
            a = np.pad(a, ((0, 0), (0, pad_n)), mode="edge")
        return jax.device_put(a, sh)

    return (
        (put(reg_x[0]), put(reg_x[1])),
        (put(reg_y[0]), put(reg_y[1])),
    )


def sharded_pairing_check(
    pairing: BN254Pairing, mesh: Mesh, groups: int, pairs: int = 2, axis: str = "dp"
):
    """Product-of-pairings verdicts with candidates sharded over the mesh.

    Returns fn(ps, qs, mask) -> (groups,) bool where
      ps = tuple of `pairs` G1 points, each (x, y) with (L, groups) leaves,
      qs = tuple of `pairs` G2 points, each ((x0,x1), (y0,y1)) Fp2 pairs,
      mask = (groups,) per-candidate validity.
    Pair i of candidate j is ps[i]/qs[i] lane j, so a candidate's whole
    product lives on one device; the per-device program is the plain batched
    pairing_check at groups/n_dev lanes per pair. Inputs may arrive with any
    committed sharding — shard_map's in_specs repartition them.
    """
    ndev = mesh.shape[axis]
    # non-divisible candidate counts are padded with masked-out lanes
    pad_g = (-groups) % ndev
    local = (groups + pad_g) // ndev

    def body(ps, qs, mask):
        # build the local chunk-major lane layout: lane i*local + j holds
        # pair i of local candidate j
        px = jnp.concatenate([p[0] for p in ps], axis=1)
        py = jnp.concatenate([p[1] for p in ps], axis=1)
        qx = (
            jnp.concatenate([q[0][0] for q in qs], axis=1),
            jnp.concatenate([q[0][1] for q in qs], axis=1),
        )
        qy = (
            jnp.concatenate([q[1][0] for q in qs], axis=1),
            jnp.concatenate([q[1][1] for q in qs], axis=1),
        )
        lane_mask = jnp.concatenate([mask] * len(ps))
        ok = pairing.pairing_check((px, py), (qx, qy), lane_mask, local)
        # a fully-masked candidate products to 1 (vacuously True) — fold
        # validity in so mask False means verdict False, as documented
        return ok & mask

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )

    def padded(ps, qs, mask):
        if pad_g:
            pad_pt = lambda a: jnp.pad(a, ((0, 0), (0, pad_g)), mode="edge")
            ps = jax.tree_util.tree_map(pad_pt, ps)
            qs = jax.tree_util.tree_map(pad_pt, qs)
            mask = jnp.pad(mask, (0, pad_g))  # padded lanes: invalid
        out = fn(ps, qs, mask)
        return out[:groups] if pad_g else out

    return jax.jit(padded)

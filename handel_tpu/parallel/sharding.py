"""Mesh sharding for the verification batch plane.

The scaling axis of this framework is the pairing/aggregation batch
(SURVEY.md §5.7): candidates shard over the mesh's data axis, the registry
shards over the same devices for the masked G2 segment-sum, and partial sums
combine with an `all_gather` + log-depth point-add tree (EC point addition is
not an elementwise monoid, so `psum` does not apply; the gather rides ICI).

Two entry points:
  * `sharded_masked_sum_g2` — shard_map over the registry axis: each device
    masked-tree-sums its registry shard for every candidate, then all_gather
    + combine. Explicit-collective form.
  * `sharded_pairing_check` — jit + sharding annotations (GSPMD): candidates
    are data-parallel lanes; XLA partitions the Miller loop/final exp with no
    cross-lane communication at all.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from handel_tpu.ops.curve import BN254Curves
from handel_tpu.ops.pairing import BN254Pairing


def make_mesh(n_devices: int | None = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def sharded_masked_sum_g2(
    curves: BN254Curves, mesh: Mesh, n_registry: int, batch: int, axis: str = "dp"
):
    """Build a jitted masked G2 aggregation sharded over the registry axis.

    Returns fn(reg_x0, reg_x1, reg_y0, reg_y1, mask) -> projective G2 batch.
    reg_* are (L, N) limb arrays, mask is (N, batch) bool. Each device owns
    N/n_dev registry points, computes its partial masked tree-sum for all
    `batch` candidates, and the partials are all_gathered and combined with
    ceil(log2 n_dev) further point-add stages — the collective path the
    reference's serial Combine loop (processing.go:355-361) never needed.
    """
    g2 = curves.g2
    ndev = mesh.shape[axis]
    if n_registry % ndev:
        raise ValueError("registry size must divide evenly over the mesh")
    local_n = n_registry // ndev

    def local_block(reg_x0, reg_x1, reg_y0, reg_y1, mask):
        # shapes here are per-device: (L, local_n), (local_n, batch)
        tile = lambda a: jnp.repeat(a, batch, axis=1)
        Ppt = g2.from_affine(
            (tile(reg_x0), tile(reg_x1)), (tile(reg_y0), tile(reg_y1))
        )
        partial = g2.masked_sum(Ppt, mask.reshape(-1), local_n)
        # gather every device's partial point: leaves become (ndev, L, batch)
        gathered = jax.tree_util.tree_map(
            lambda a: jax.lax.all_gather(a, axis), partial
        )
        # combine with a log-depth point-add tree over the leading axis
        def level(pts, k):
            while k > 1:
                half = k // 2
                lo = jax.tree_util.tree_map(lambda a: a[:half], pts)
                hi = jax.tree_util.tree_map(lambda a: a[half : 2 * half], pts)
                s = g2_add_leading(lo, hi)
                if k % 2:
                    s = jax.tree_util.tree_map(
                        lambda a, b: jnp.concatenate([a, b[2 * half : k]], 0),
                        s,
                        pts,
                    )
                    k = half + 1
                else:
                    k = half
                pts = s
            return jax.tree_util.tree_map(lambda a: a[0], pts)

        def g2_add_leading(lo, hi):
            # vmap the complete add over the leading (device) axis
            return jax.vmap(g2.add)(lo, hi)

        return level(gathered, ndev)

    fn = shard_map(
        local_block,
        mesh=mesh,
        in_specs=(
            P(None, axis),
            P(None, axis),
            P(None, axis),
            P(None, axis),
            P(axis, None),
        ),
        out_specs=P(),  # combined point replicated on every device
        check_rep=False,
    )
    return jax.jit(fn)


def sharded_pairing_check(
    pairing: BN254Pairing, mesh: Mesh, groups: int, pairs: int = 2, axis: str = "dp"
):
    """Jit the batched product-of-pairings check with candidate lanes sharded
    over the mesh (pure data parallelism: no collectives needed; GSPMD keeps
    every lane's Miller loop + shared-final-exp on its home device).

    Returns fn(p, q, mask) like BN254Pairing.pairing_check with
    groups*pairs lanes, chunk-major.
    """
    lane_sharding = NamedSharding(mesh, P(None, axis))
    mask_sharding = NamedSharding(mesh, P(axis))

    jitted = jax.jit(
        lambda p, q, mask: pairing.pairing_check(p, q, mask, groups),
        out_shardings=NamedSharding(mesh, P(axis)),
    )

    def check(p, q, mask):
        # reshard eagerly: inputs may arrive committed with a different layout
        # (e.g. the replicated output of sharded_masked_sum_g2), and jit
        # in_shardings refuses committed-but-mismatched args; device_put is
        # the documented reshard path and jit then infers lane parallelism
        # from the committed input shardings.
        reshard = lambda a: jax.device_put(a, lane_sharding)
        p = jax.tree_util.tree_map(reshard, p)
        q = jax.tree_util.tree_map(reshard, q)
        mask = jax.device_put(mask, mask_sharding)
        return jitted(p, q, mask)

    return check

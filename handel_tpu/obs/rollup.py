"""Hierarchical telemetry roll-ups — O(hosts) fleet observability.

ROADMAP item 3's observability prerequisite: the swarm proves ~200 KB per
identity, so a 2^20-identity fleet spans 8-16 hosts — but a master that
keeps one reporter row, one labeled metric family, and one raw span ring
per *identity* melts long before the memory does. This module collapses
the per-identity surfaces at the host and ships bounded digests:

``HostRollup`` folds a process's N reporter surfaces (swarm vnodes,
sessions, device lanes, federation regions) into one digest whose size
depends on the *key union*, never on N:

- counters are summed,
- gauges carry ``(sum, max, n)`` — NOT a pre-computed mean — so a
  second-level merge recombines exactly (mean of means is not the mean),
- ``LogHistogram``s merge through the existing sparse wire form,
- a *local* ``DetectorBank`` picks the top-K anomalous series so the
  master sees K rows, not every series,
- the trace ring is digested to per-stage totals plus the slowest causal
  chain (``sim.trace_cli.critical_path`` when the ring holds one) — raw
  span rings never leave the host.

The digest travels as a changed-keys-only delta (absolute values, never
increments, so redelivery is idempotent) chunked under the monitor
``Sink``'s 1400 B UDP budget.

``FleetRollup`` on the master ingests host digests. The merge is
order-invariant and two-level == flat (property-tested in
tests/test_rollup.py): counters add, gauge triples add/max, histograms
merge sparse, trace stages add with the slowest host's chain kept. It
exposes ``handel_fleet_*`` families with ``host`` labels, a ``/fleet``
JSON payload, and feeds the *same* ``AlertPlane`` the single-host
harnesses tick — merged counters become the (good, bad) burn sources,
hosts-up the page-on-loss series — preserving the
exactly-one-incident-per-outage contract with attribution that names the
offending host(s).
"""

from __future__ import annotations

import json
import time
from typing import Callable, Iterable, Mapping

from handel_tpu.core.metrics import is_gauge_key
from handel_tpu.core.trace import LogHistogram

from .detect import DetectorBank, EwmaDetector
from .slo import BurnRule

# Mirrors handel_tpu.sim.monitor.MAX_DATAGRAM (asserted equal in tests);
# obs/ stays importable without the sim package.
MAX_DATAGRAM = 1400

_SECTIONS = ("counters", "gauges", "hists")


def _json_len(obj) -> int:
    return len(json.dumps(obj).encode())


def trace_digest(events: list[dict], *, chain_tail: int = 8) -> dict:
    """Digest a traceEvents list to per-stage totals + the slowest chain.

    Bounded by the stage-name union, not the ring length. The causal
    chain comes from ``critical_path`` when the ring holds a threshold
    instant; otherwise the tail falls back to the slowest raw spans.
    """
    spans = [e for e in events if e.get("ph") == "X"]
    if not spans:
        return {}
    stages: dict[str, list] = {}
    t0 = None
    t1 = None
    for e in spans:
        st = stages.setdefault(e.get("name", "?"), [0.0, 0])
        dur = float(e.get("dur", 0.0))
        st[0] += dur / 1e3  # us -> ms
        st[1] += 1
        ts = float(e.get("ts", 0.0))
        t0 = ts if t0 is None else min(t0, ts)
        t1 = ts + dur if t1 is None else max(t1, ts + dur)
    out = {
        "wall_ms": (t1 - t0) / 1e3,
        "spans": len(spans),
        "stages_ms": {k: v[0] for k, v in sorted(stages.items())},
        "stage_ct": {k: v[1] for k, v in sorted(stages.items())},
    }
    try:
        from handel_tpu.sim.trace_cli import critical_path

        cp = critical_path(events)
    except Exception:
        cp = None
    if cp:
        out["chain_tail"] = (cp.get("chain") or [])[-chain_tail:]
        out["chain_wall_ms"] = cp.get("wall_ms")
    else:
        slow = sorted(spans, key=lambda e: -float(e.get("dur", 0.0)))
        out["chain_tail"] = [
            {"stage": e.get("name", "?"),
             "ms": round(float(e.get("dur", 0.0)) / 1e3, 3)}
            for e in slow[:chain_tail]
        ]
    return out


def merge_trace_digests(parts: Iterable[tuple[str, dict]]) -> dict:
    """Order-invariant merge: stage totals add, the slowest host's chain
    wins (max wall is order-free)."""
    stages: dict[str, float] = {}
    stage_ct: dict[str, int] = {}
    spans = 0
    wall = 0.0
    chain: list = []
    slowest_host = ""
    for host, t in sorted(parts):
        if not t:
            continue
        spans += int(t.get("spans", 0))
        for k, v in t.get("stages_ms", {}).items():
            stages[k] = stages.get(k, 0.0) + v
        for k, v in t.get("stage_ct", {}).items():
            stage_ct[k] = stage_ct.get(k, 0) + int(v)
        w = float(t.get("wall_ms", 0.0))
        if w > wall:
            wall = w
            chain = t.get("chain_tail", [])
            slowest_host = host
    if not spans:
        return {}
    return {
        "wall_ms": wall,
        "spans": spans,
        "stages_ms": dict(sorted(stages.items())),
        "stage_ct": dict(sorted(stage_ct.items())),
        "chain_tail": chain,
        "slowest_host": slowest_host,
    }


class HostRollup:
    """Fold one process's reporter surfaces into a bounded digest.

    Sources are attached once; every ``digest()`` samples them fresh so
    the digest is a pure function of current state (delta encoding and
    redelivery idempotence fall out of that). ``fold`` sources cover the
    N-vnode case: a callable yielding ``(values, gauge_keys)`` per vnode
    keeps this object O(key-union) while walking O(N) surfaces.
    """

    def __init__(self, host: str, *, top_k: int = 8,
                 clock: Callable[[], float] = time.monotonic):
        self.host = host
        self.top_k = top_k
        self.bank = DetectorBank(clock=clock)
        self.trace_source: Callable[[], list] | None = None
        self.seq = 0
        self.emits = 0
        self.bytes_sent = 0
        self.surfaces = 0
        self._reporters: list[tuple[str, object, frozenset | None]] = []
        self._folds: list[tuple[str, Callable[[], Iterable]]] = []
        self._last: dict = {}
        self.sample_errors = 0

    # -- source attachment ----------------------------------------------------

    def attach_reporter(self, plane: str, reporter,
                        gauges: Iterable[str] | None = None) -> None:
        """A live values()/gauge_keys()/histograms() surface, sampled at
        every digest."""
        g = frozenset(gauges) if gauges is not None else None
        self._reporters.append((plane, reporter, g))

    def attach_fold(self, plane: str,
                    fn: Callable[[], Iterable]) -> None:
        """``fn()`` yields ``(values, gauge_keys)`` pairs — one per vnode
        or session — folded into the shared key union."""
        self._folds.append((plane, fn))

    def watch(self, name: str, source: Callable[[], float | None],
              detector=None, **kw) -> None:
        """Attach a series to the local DetectorBank (top-K selection)."""
        self.bank.attach(name, source, detector or EwmaDetector(), **kw)

    def set_trace(self, trace_source: Callable[[], list]) -> None:
        self.trace_source = trace_source

    def tick(self, now: float | None = None):
        """Advance the local detectors (call on the harness cadence)."""
        return self.bank.tick(now)

    # -- digest ----------------------------------------------------------------

    @staticmethod
    def _fold_values(counters, gauges, plane, values, declared) -> None:
        for k, v in values.items():
            key = f"{plane}.{k}"
            if is_gauge_key(k, declared):
                g = gauges.get(key)
                if g is None:
                    gauges[key] = [float(v), float(v), 1]
                else:
                    g[0] += float(v)
                    g[1] = max(g[1], float(v))
                    g[2] += 1
            else:
                counters[key] = counters.get(key, 0.0) + float(v)

    def digest(self) -> dict:
        counters: dict[str, float] = {}
        gauges: dict[str, list] = {}
        hists: dict[str, LogHistogram] = {}
        surfaces = 0
        for plane, rep, declared in self._reporters:
            try:
                g = declared
                if g is None and hasattr(rep, "gauge_keys"):
                    g = frozenset(rep.gauge_keys())
                if hasattr(rep, "values"):
                    self._fold_values(counters, gauges, plane, rep.values(),
                                      g)
                    surfaces += 1
                if hasattr(rep, "histograms"):
                    for k, h in rep.histograms().items():
                        if not h.count:
                            continue
                        hists.setdefault(f"{plane}.{k}",
                                         LogHistogram()).merge(h)
            except Exception:
                # a dying surface (killed region, torn-down cluster) must
                # not take the whole host digest with it
                self.sample_errors += 1
        for plane, fn in self._folds:
            try:
                for item in fn():
                    values, gkeys = item
                    self._fold_values(counters, gauges, plane, values, gkeys)
                    surfaces += 1
            except Exception:
                self.sample_errors += 1
        self.surfaces = surfaces
        out = {
            "host": self.host,
            "seq": self.seq,
            "surfaces": surfaces,
            "counters": counters,
            "gauges": {k: {"s": g[0], "m": g[1], "n": g[2]}
                       for k, g in gauges.items()},
            "hists": {k: h.to_sparse() for k, h in hists.items()},
            "anoms": self.bank.top_anomalous(self.top_k),
        }
        if self.trace_source is not None:
            try:
                events = self.trace_source()
                out["trace"] = trace_digest(events) if events else {}
            except Exception:
                out["trace"] = {}
        return out

    def series_count(self) -> int:
        d = self.digest()
        return sum(len(d[s]) for s in _SECTIONS)

    # -- delta + wire ----------------------------------------------------------

    def delta(self) -> dict:
        """Changed-keys-only delta vs the last emission. Values are
        ABSOLUTE (never increments): re-applying any delta or chunk is a
        no-op, which is what makes UDP redelivery safe."""
        d = self.digest()
        full = not self._last
        self.seq += 1
        d["seq"] = self.seq
        out: dict = {"host": self.host, "seq": self.seq}
        if full:
            out["full"] = True
        for sec in _SECTIONS:
            prev = self._last.get(sec, {})
            cur = d[sec]
            changed = {k: v for k, v in cur.items()
                       if full or prev.get(k) != v}
            if changed:
                out[sec] = changed
            removed = sorted(set(prev) - set(cur))
            if removed:
                out.setdefault("removed", {})[sec] = removed
        for sec in ("anoms", "trace", "surfaces"):
            cur = d.get(sec)
            if cur is not None and (full or self._last.get(sec) != cur):
                out[sec] = cur
        self._last = d
        return out

    def emit(self, send: Callable[[dict], None] | None = None) -> int:
        """Delta -> chunks under the UDP budget -> ``send`` each.
        Returns bytes that went on the wire (counted even without a
        sender, so harnesses can measure the budget they'd spend)."""
        n = 0
        for payload in chunk_delta(self.delta()):
            n += _json_len(payload)
            if send is not None:
                send(payload)
        self.emits += 1
        self.bytes_sent += n
        return n

    # -- reporter surface (so a host rollup registers like anything else) ------

    def values(self) -> dict[str, float]:
        return {
            "rollupEmitsCt": float(self.emits),
            "rollupBytesCt": float(self.bytes_sent),
            "rollupSampleErrorsCt": float(self.sample_errors),
            "rollupSeq": float(self.seq),
            "rollupSurfaces": float(self.surfaces),
        }

    def gauge_keys(self) -> set[str]:
        return {"rollupSeq", "rollupSurfaces"}


def chunk_delta(delta: dict, budget: int = MAX_DATAGRAM) -> list[dict]:
    """Split a delta into ``{"rollup": {...}}`` payloads whose JSON stays
    under ``budget``. Every chunk repeats host/seq (and the full-replace
    flag) so chunks apply independently and in any order within a seq;
    histogram bucket maps split across chunks with lo/hi/sum repeated.
    A single oversized item still ships alone — truncation is never
    silent, the budget is a packing target. An empty delta yields one
    heartbeat chunk so liveness tracking keeps working."""
    head = {"host": delta["host"], "seq": delta["seq"]}
    if delta.get("full"):
        head["full"] = True
    base = _json_len({"rollup": head})
    chunks: list[dict] = []
    cur: dict = {}
    size = base

    def flush() -> None:
        nonlocal cur, size
        if cur:
            chunks.append({"rollup": {**head, **cur}})
        cur = {}
        size = base

    def put(section: str, key: str, value) -> None:
        nonlocal size
        item = _json_len({key: value}) + len(section) + 6
        if cur and size + item > budget:
            flush()
        cur.setdefault(section, {})[key] = value
        size += item

    for sec in ("surfaces", "anoms", "trace", "removed"):
        if sec in delta:
            item = _json_len({sec: delta[sec]}) + 4
            if cur and size + item > budget:
                flush()
            cur[sec] = delta[sec]
            size += item
    for sec in ("counters", "gauges"):
        for k in sorted(delta.get(sec, {})):
            put(sec, k, delta[sec][k])
    for k in sorted(delta.get("hists", {})):
        sparse = delta["hists"][k]
        meta = {"lo": sparse.get("lo", 0.0), "hi": sparse.get("hi", 0.0),
                "sum": sparse.get("sum", 0.0)}
        meta_cost = _json_len({k: {**meta, "b": {}}}) + 12
        buckets: dict = {}
        bsize = 0
        items = sorted(sparse.get("b", {}).items(), key=lambda kv: int(kv[0]))
        for bk, bv in items:
            cost = _json_len({bk: bv}) + 1
            if buckets and size + meta_cost + bsize + cost > budget:
                put("hists", k, {**meta, "b": buckets})
                flush()
                buckets = {}
                bsize = 0
            buckets[bk] = bv
            bsize += cost
        put("hists", k, {**meta, "b": buckets})
    flush()
    if not chunks:
        chunks.append({"rollup": dict(head)})
    return chunks


class _HostState:
    __slots__ = ("seq", "counters", "gauges", "hists", "anoms", "trace",
                 "surfaces", "last_seen", "lost")

    def __init__(self):
        self.seq = -1
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, dict] = {}
        self.hists: dict[str, dict] = {}
        self.anoms: list = []
        self.trace: dict = {}
        self.surfaces = 0
        self.last_seen = 0.0
        self.lost = False

    def reset(self):
        self.counters.clear()
        self.gauges.clear()
        self.hists.clear()
        self.anoms = []
        self.trace = {}


class FleetRollup:
    """Master-side merge of host digests + the alert-plane feed.

    ``ingest`` applies delta chunks (absolute values; stale seqs dropped,
    redelivery idempotent). ``merged()`` recombines across hosts in
    sorted-host order so the result is independent of arrival order and
    equal to a flat single-level fold of the same surfaces.
    """

    def __init__(self, *, top_k: int = 8, stale_after_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.top_k = top_k
        self.stale_after_s = stale_after_s
        self.clock = clock
        self._hosts: dict[str, _HostState] = {}
        self.ingests = 0
        self.ingest_bytes = 0
        self.stale_drops = 0
        self.merges = 0
        self.last_merge_ms = 0.0

    # -- ingest ----------------------------------------------------------------

    def ingest(self, payload: Mapping, now: float | None = None) -> bool:
        """Apply one delta chunk. Returns False when dropped as stale."""
        r = payload.get("rollup", payload)
        host = r.get("host")
        seq = int(r.get("seq", 0))
        if not host:
            return False
        st = self._hosts.setdefault(host, _HostState())
        if seq < st.seq:
            self.stale_drops += 1
            return False
        if seq > st.seq:
            if r.get("full"):
                st.reset()
            st.seq = seq
        st.last_seen = self.clock() if now is None else now
        st.lost = False
        self.ingests += 1
        self.ingest_bytes += _json_len(dict(r))
        st.counters.update(r.get("counters", {}))
        st.gauges.update(r.get("gauges", {}))
        for k, sparse in r.get("hists", {}).items():
            h = st.hists.setdefault(k, {"b": {}, "lo": 0.0, "hi": 0.0,
                                        "sum": 0.0})
            # bucket counts are monotone within a host, so replace-by-key
            # over the chunked absolute map reassembles the exact state
            # and re-applying any chunk is a no-op
            h["b"].update(sparse.get("b", {}))
            h["lo"] = sparse.get("lo", h["lo"])
            h["hi"] = sparse.get("hi", h["hi"])
            h["sum"] = sparse.get("sum", h["sum"])
        for sec, keys in r.get("removed", {}).items():
            store = getattr(st, sec, None)
            if isinstance(store, dict):
                for k in keys:
                    store.pop(k, None)
        if "anoms" in r:
            st.anoms = r["anoms"]
        if "trace" in r:
            st.trace = r["trace"]
        if "surfaces" in r:
            st.surfaces = int(r["surfaces"])
        return True

    def ingest_digest(self, digest: Mapping,
                      now: float | None = None) -> bool:
        """File-based path: apply a full digest as a full-replace delta."""
        return self.ingest({**dict(digest), "full": True}, now=now)

    # -- liveness --------------------------------------------------------------

    def mark_lost(self, host: str) -> None:
        self._hosts.setdefault(host, _HostState()).lost = True

    def lost_hosts(self, now: float | None = None) -> list[str]:
        t = self.clock() if now is None else now
        out = []
        for host, st in self._hosts.items():
            stale = (self.stale_after_s > 0
                     and t - st.last_seen > self.stale_after_s)
            if st.lost or stale:
                out.append(host)
        return sorted(out)

    def hosts_up(self, now: float | None = None) -> int:
        return len(self._hosts) - len(self.lost_hosts(now))

    # -- merge -----------------------------------------------------------------

    def merged(self) -> dict:
        t0 = time.perf_counter()
        counters: dict[str, float] = {}
        gauges: dict[str, list] = {}
        hists: dict[str, LogHistogram] = {}
        anoms: list = []
        traces: list = []
        surfaces = 0
        for host in sorted(self._hosts):
            st = self._hosts[host]
            surfaces += st.surfaces
            for k, v in st.counters.items():
                counters[k] = counters.get(k, 0.0) + v
            for k, g in st.gauges.items():
                cur = gauges.get(k)
                if cur is None:
                    gauges[k] = [g["s"], g["m"], g["n"]]
                else:
                    cur[0] += g["s"]
                    cur[1] = max(cur[1], g["m"])
                    cur[2] += g["n"]
            for k, sparse in st.hists.items():
                hists.setdefault(k, LogHistogram()).merge_sparse(sparse)
            anoms.extend({**a, "host": host} for a in st.anoms)
            if st.trace:
                traces.append((host, st.trace))
        anoms.sort(key=lambda a: -abs(a.get("z", 0.0)))
        out = {
            "hosts": len(self._hosts),
            "surfaces": surfaces,
            "counters": dict(sorted(counters.items())),
            "gauges": {k: {"s": g[0], "m": g[1], "n": g[2]}
                       for k, g in sorted(gauges.items())},
            "hists": hists,
            "anoms": anoms[:self.top_k],
            "trace": merge_trace_digests(traces),
        }
        out["series"] = sum(len(out[s]) for s in _SECTIONS)
        self.merges += 1
        self.last_merge_ms = (time.perf_counter() - t0) * 1e3
        return out

    def merged_counters(self) -> dict[str, float]:
        """Cheap counter-only merge for burn/series sources."""
        counters: dict[str, float] = {}
        for host in sorted(self._hosts):
            for k, v in self._hosts[host].counters.items():
                counters[k] = counters.get(k, 0.0) + v
        return counters

    def series_count(self) -> int:
        return self.merged()["series"]

    # -- alert-plane feed ------------------------------------------------------

    def burn_source(self, good_key: str,
                    bad_key: str) -> Callable[[], tuple[float, float]]:
        """Cumulative (good, bad) counts for a BurnRule, merged fleet-wide."""
        def src() -> tuple[float, float]:
            c = self.merged_counters()
            return c.get(good_key, 0.0), c.get(bad_key, 0.0)
        return src

    def series_source(self, key: str) -> Callable[[], float | None]:
        """A merged counter (sum) or gauge (mean) as a detector series."""
        def src() -> float | None:
            c = self.merged_counters()
            if key in c:
                return c[key]
            for host in sorted(self._hosts):
                g = self._hosts[host].gauges.get(key)
                if g is not None:
                    s = n = 0.0
                    for h2 in sorted(self._hosts):
                        g2 = self._hosts[h2].gauges.get(key)
                        if g2 is not None:
                            s += g2["s"]
                            n += g2["n"]
                    return s / n if n else None
            return None
        return src

    def attach_alerts(self, plane, *,
                      burn_rules: Iterable[tuple[BurnRule, str, str]] = (),
                      series: Iterable[tuple[str, str]] = (),
                      z_threshold: float = 6.0, ewma_alpha: float = 0.3,
                      min_consecutive: int = 1) -> None:
        """Feed the SAME AlertPlane the single-host harnesses tick.

        Burn rules read merged fleet counters; a hosts-up series pages on
        host loss and holds the incident open while any host stays lost,
        so one outage maps to exactly one incident — and the attribution
        snapshot names the offending host(s) via the lost_hosts context.
        """
        for rule, good_key, bad_key in burn_rules:
            plane.evaluator.add_rule(rule, self.burn_source(good_key,
                                                            bad_key))
        plane.detectors.attach(
            "fleet-hosts-up", lambda: float(self.hosts_up()),
            EwmaDetector(alpha=ewma_alpha, z_threshold=z_threshold,
                         warmup=2),
            min_consecutive=min_consecutive, opens_incident=True,
            direction="down", hold_while=lambda: bool(self.lost_hosts()),
        )
        for name, key in series:
            plane.detectors.attach(
                name, self.series_source(key),
                EwmaDetector(alpha=ewma_alpha, z_threshold=z_threshold),
                min_consecutive=min_consecutive,
            )
        plane.add_context("lost_hosts", self.lost_hosts)
        plane.add_context("fleet", lambda: {
            "hosts": len(self._hosts), "hosts_up": self.hosts_up(),
            "series": self.series_count(),
        })

    # -- metrics + /fleet ------------------------------------------------------

    def values(self) -> dict[str, float]:
        up = self.hosts_up()
        return {
            "hostsTotal": float(len(self._hosts)),
            "hostsUp": float(up),
            "hostsDown": float(len(self._hosts) - up),
            "seriesTotal": float(self.series_count()),
            "ingestsCt": float(self.ingests),
            "ingestBytesCt": float(self.ingest_bytes),
            "staleDropsCt": float(self.stale_drops),
            "mergesCt": float(self.merges),
            "lastMergeMs": self.last_merge_ms,
        }

    def gauge_keys(self) -> set[str]:
        return {"hostsTotal", "hostsUp", "hostsDown", "seriesTotal",
                "lastMergeMs"}

    def labeled_values(self) -> dict[str, dict[str, float]]:
        lost = set(self.lost_hosts())
        out: dict[str, dict[str, float]] = {}
        for host in sorted(self._hosts):
            st = self._hosts[host]
            row: dict[str, float] = {
                "hostUp": 0.0 if host in lost else 1.0,
                "digestSeq": float(st.seq),
                "seriesCt": float(len(st.counters) + len(st.gauges)
                                  + len(st.hists)),
                "topZ": max((abs(a.get("z", 0.0)) for a in st.anoms),
                            default=0.0),
            }
            row.update(st.counters)
            for k, g in st.gauges.items():
                row[k] = g["s"] / g["n"] if g["n"] else 0.0
            out[host] = row
        return out

    def labeled_gauge_keys(self) -> set[str]:
        out = {"hostUp", "digestSeq", "seriesCt", "topZ"}
        for st in self._hosts.values():
            out.update(st.gauges)
        return out

    def histograms(self) -> dict[str, LogHistogram]:
        return self.merged()["hists"]

    def fleet_payload(self) -> dict:
        """The /fleet JSON endpoint body."""
        m = self.merged()
        return {
            "hosts": {h: {"up": h not in set(self.lost_hosts()),
                          "seq": st.seq,
                          "surfaces": st.surfaces,
                          "series": len(st.counters) + len(st.gauges)
                          + len(st.hists),
                          "top_anomalous": st.anoms}
                      for h, st in sorted(self._hosts.items())},
            "hosts_up": self.hosts_up(),
            "lost_hosts": self.lost_hosts(),
            "series_total": m["series"],
            "surfaces": m["surfaces"],
            "top_anomalous": m["anoms"],
            "trace": m["trace"],
            "ingests": self.ingests,
            "ingest_bytes": self.ingest_bytes,
            "last_merge_ms": round(self.last_merge_ms, 3),
        }

    def register_metrics(self, registry) -> None:
        """handel_fleet_* families (host-labeled rows + merged
        histograms) and the /fleet endpoint on an existing registry."""
        registry.register_values("fleet", self)
        registry.register_labeled_values("fleet", self, label="host")
        registry.register_histograms("fleet", self)
        registry.set_fleet_source(self.fleet_payload)

"""Streaming anomaly detection over reporter keys and histogram quantiles.

Two detector families, both O(1) memory per series and deterministic
under a fixed seed (the determinism test replays a stream and asserts
bit-identical z traces):

- `EwmaDetector` — exponentially weighted mean/variance; z-score of each
  new sample against the pre-update estimates. Cheap, fast to adapt,
  right for smooth gauges (fill ratio, dedup rate, goodput).
- `MadDetector` — frugal streaming median + MAD sketches (one estimate
  and one adaptive step each, rng only for the coin flips the frugal
  update needs — hence the seed). Robust to heavy tails and spikes,
  right for latency quantiles and queue depths.

A `DetectorBank` owns named series: each binds a zero-argument source
callable to a detector with a firing policy (direction, consecutive
count, whether a firing may open an incident). Sources are sampled at
tick time only — an idle bank costs nothing. Helper factories wrap the
three source shapes the repo has: a reporter `values()` key, a
LogHistogram quantile, and a counter differenced into a rate.
"""

from __future__ import annotations

import math
import random
import time
from dataclasses import dataclass
from typing import Callable


class EwmaDetector:
    """EWMA mean/variance z-score. `update(x)` returns the SIGNED z of x
    against the estimates from before x was absorbed; during the first
    `warmup` samples it returns 0.0 (estimates are still forming)."""

    def __init__(self, alpha: float = 0.3, z_threshold: float = 6.0,
                 warmup: int = 5, eps: float = 1e-9):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.z_threshold = z_threshold
        self.warmup = warmup
        self.eps = eps
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def update(self, x: float) -> float:
        x = float(x)
        if self.n == 0:
            self.mean = x
            self.n = 1
            return 0.0
        z = (x - self.mean) / math.sqrt(self.var + self.eps)
        d = x - self.mean
        self.mean += self.alpha * d
        # EWMA variance of the residual (West 1979 incremental form)
        self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1
        return 0.0 if self.n <= self.warmup else z


class MadDetector:
    """Frugal streaming median + MAD with a robust z-score.

    Two frugal-quantile sketches: `med` tracks the median of x, `mad`
    the median of |x - med|. Each keeps one float estimate and one
    adaptive step (doubles while moving the same way, halves on
    direction change — frugal-2U). The frugal update flips a seeded
    coin per sample, which is the ONLY nondeterminism: a fixed seed
    replays exactly. z = 0.6745 * (x - med) / mad (the normal-consistent
    MAD scaling)."""

    def __init__(self, z_threshold: float = 6.0, warmup: int = 8,
                 seed: int = 0, eps: float = 1e-9):
        self.z_threshold = z_threshold
        self.warmup = warmup
        self.eps = eps
        self.rng = random.Random(seed * 1_000_003 + 101)
        self.med = 0.0
        self.mad = 0.0
        self._med_step = 1e-6
        self._mad_step = 1e-6
        self._med_dir = 0
        self._mad_dir = 0
        self.n = 0

    def _frugal(self, est: float, step: float, last_dir: int,
                x: float) -> tuple[float, float, int]:
        if x == est or self.rng.random() >= 0.5:
            return est, step, last_dir
        d = 1 if x > est else -1
        step = min(step * 2.0, abs(x - est)) if d == last_dir \
            else max(step * 0.5, self.eps)
        est += d * step
        # never step past the sample — frugal overshoot control
        if (d > 0 and est > x) or (d < 0 and est < x):
            est = x
        return est, step, d

    def update(self, x: float) -> float:
        x = float(x)
        if self.n == 0:
            self.med = x
            self._med_step = max(abs(x) * 0.1, 1e-6)
            self._mad_step = self._med_step
            self.n = 1
            return 0.0
        dev = abs(x - self.med)
        z = 0.6745 * (x - self.med) / (self.mad + self.eps)
        self.med, self._med_step, self._med_dir = self._frugal(
            self.med, self._med_step, self._med_dir, x
        )
        self.mad, self._mad_step, self._mad_dir = self._frugal(
            self.mad, self._mad_step, self._mad_dir, dev
        )
        self.n += 1
        return 0.0 if self.n <= self.warmup else z


@dataclass
class Detection:
    """One firing series at one tick."""

    name: str
    z: float
    value: float
    at: float
    opens_incident: bool


class _Series:
    __slots__ = ("name", "source", "detector", "min_consecutive",
                 "opens_incident", "direction", "hold_while", "consecutive",
                 "active", "last_value", "last_z", "firings")

    def __init__(self, name, source, detector, min_consecutive,
                 opens_incident, direction, hold_while):
        self.name = name
        self.source = source
        self.detector = detector
        self.min_consecutive = min_consecutive
        self.opens_incident = opens_incident
        self.direction = direction
        self.hold_while = hold_while
        self.consecutive = 0
        self.active = False
        self.last_value = 0.0
        self.last_z = 0.0
        self.firings = 0

    def anomalous(self, z: float) -> bool:
        t = self.detector.z_threshold
        if self.direction == "up":
            return z >= t
        if self.direction == "down":
            return z <= -t
        return abs(z) >= t


class DetectorBank:
    """Named detector series sampled together each tick."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._series: dict[str, _Series] = {}
        self.ticks = 0

    def attach(self, name: str, source: Callable[[], float | None],
               detector, min_consecutive: int = 3,
               opens_incident: bool = False,
               direction: str = "both",
               hold_while: Callable[[], bool] | None = None) -> None:
        """Bind `source` to `detector` under `name`. `direction` gates
        which side of the baseline fires ("up"/"down"/"both");
        `min_consecutive` anomalous ticks are required before the series
        fires (blip suppression); only `opens_incident=True` series feed
        the incident log — the rest are attribution context.

        `hold_while` decouples detection from resolution: a z-score
        detector spots a STEP (one or two anomalous ticks before the
        estimates adapt), but the condition it detected may persist for
        minutes. Once fired, the series keeps firing while `hold_while()`
        is true (e.g. "a region is still unhealthy"), so the incident it
        opened closes on actual recovery, not on the detector's
        adaptation."""
        if name in self._series:
            raise ValueError(f"duplicate detector series {name!r}")
        if direction not in ("up", "down", "both"):
            raise ValueError(f"bad direction {direction!r}")
        self._series[name] = _Series(
            name, source, detector, min_consecutive, opens_incident,
            direction, hold_while,
        )

    def tick(self, now: float | None = None) -> list[Detection]:
        """Sample every source; return the series currently FIRING
        (anomalous for >= min_consecutive ticks, or held firing by their
        `hold_while` condition)."""
        now = self.clock() if now is None else now
        self.ticks += 1
        out: list[Detection] = []
        for s in self._series.values():
            try:
                v = s.source()
            except Exception:
                continue  # a dying source must not kill the bank
            if v is None:
                continue
            z = s.detector.update(v)
            s.last_value = float(v)
            s.last_z = z
            if s.anomalous(z):
                s.consecutive += 1
            else:
                s.consecutive = 0
            if s.consecutive >= s.min_consecutive:
                s.firings += 1
                s.active = True
            elif s.active:
                try:
                    held = s.hold_while is not None and bool(s.hold_while())
                except Exception:
                    held = False
                if not held:
                    s.active = False
            if s.active:
                out.append(Detection(s.name, z, float(v), now,
                                     s.opens_incident))
        return out

    def top_anomalous(self, n: int = 5) -> list[dict]:
        """The n series with the largest current |z| — the anomalous-
        series half of an incident's attribution snapshot."""
        rows = sorted(
            self._series.values(), key=lambda s: abs(s.last_z),
            reverse=True,
        )
        return [
            {"series": s.name, "z": round(s.last_z, 3),
             "value": s.last_value}
            for s in rows[:n] if s.last_z
        ]

    # -- reporter surface ---------------------------------------------------

    def values(self) -> dict[str, float]:
        return {
            "seriesTotal": float(len(self._series)),
            "seriesAnomalous": float(sum(
                1 for s in self._series.values() if s.active
            )),
            "detectTicksCt": float(self.ticks),
            "firingsCt": float(sum(
                s.firings for s in self._series.values()
            )),
        }

    def gauge_keys(self) -> set[str]:
        return {"seriesTotal", "seriesAnomalous"}

    def labeled_values(self) -> dict[str, dict[str, float]]:
        return {
            s.name: {
                "lastValue": s.last_value,
                "lastZ": s.last_z,
                "anomalousTicks": float(s.consecutive),
                "seriesFiringsCt": float(s.firings),
            }
            for s in self._series.values()
        }

    def labeled_gauge_keys(self) -> set[str]:
        return {"lastValue", "lastZ", "anomalousTicks"}


# -- source factories ---------------------------------------------------------


def reporter_key_source(reporter, key: str) -> Callable[[], float | None]:
    """Sample one key of a `values()` reporter (core/report.py)."""

    def src() -> float | None:
        return dict(reporter.values()).get(key)

    return src


def histogram_quantile_source(hist_fn, q: float) -> Callable[[], float | None]:
    """Sample a quantile of a LogHistogram-returning callable — e.g.
    `lambda: reporter.histograms().get("verifyLatencyS")`."""

    def src() -> float | None:
        h = hist_fn()
        return h.quantile(q) if h is not None and h.count else None

    return src


def counter_rate(source: Callable[[], float | None],
                 clock: Callable[[], float] = time.monotonic
                 ) -> Callable[[], float | None]:
    """Difference a cumulative counter source into a per-second rate
    (first sample primes the baseline and returns None)."""
    prev: list = [None, None]  # [value, t]

    def src() -> float | None:
        v = source()
        if v is None:
            return None
        now = clock()
        pv, pt = prev
        prev[0], prev[1] = v, now
        if pv is None or now <= pt:
            return None
        return (v - pv) / (now - pt)

    return src

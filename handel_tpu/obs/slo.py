"""Multi-window error-budget burn-rate SLO evaluation.

The math is the standard SRE formulation, kept closed-form so tests can
oracle it exactly. An SLO of `1 - budget` (e.g. 99% of gold sessions
inside their p99 target -> budget 0.01) burns at

    burn = windowed_error_rate / budget

so burn 1x consumes exactly the budget over the SLO period, and a
sustained 14.4x burn exhausts a 30-day budget in ~2 days — the classic
page threshold. Each rule is evaluated over TWO windows (fast ~1m /
slow ~15m, both scaled by `window_scale` so short drills exercise the
same math): the fast window makes detection quick, the slow window makes
the alert *stay* firing long enough to matter and suppresses blips.
A rule pages only when BOTH windows burn >= `page_x`, warns when both
burn >= `warn_x`.

Rules read cumulative (good, bad) event counts from a zero-argument
source callable; the evaluator snapshots them per tick into a bounded
deque (O(slow_window / tick) memory) and differences the window edges —
no per-event state, so a source can be as cheap as two counters.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

#: alert state codes for the metrics plane (gauge `alertState`)
STATE_CODE = {"ok": 0.0, "warn": 1.0, "page": 2.0}


@dataclass(frozen=True)
class BurnRule:
    """One SLO burn-rate rule: a named error budget with page/warn
    multipliers. `budget` is the allowed error fraction (1 - SLO target);
    the thresholds are burn multiples, not error rates."""

    name: str
    budget: float
    page_x: float = 14.4
    warn_x: float = 6.0
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(
                f"rule {self.name!r}: budget must be in (0, 1], "
                f"got {self.budget}"
            )
        if self.warn_x >= self.page_x:
            raise ValueError(
                f"rule {self.name!r}: warn_x {self.warn_x} must be below "
                f"page_x {self.page_x}"
            )


class BurnRateEvaluator:
    """Ticks every rule's (good, bad) source and classifies ok/warn/page.

    Reporter surface (core/report.py contract): `values()` carries the
    aggregate plane, `labeled_values()` one row per rule under the `rule`
    label — both with explicit gauge declarations so the metrics plane
    never falls back to the suffix heuristic.
    """

    def __init__(self, fast_window_s: float = 60.0,
                 slow_window_s: float = 900.0,
                 window_scale: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if fast_window_s >= slow_window_s:
            raise ValueError(
                f"fast window {fast_window_s}s must be shorter than the "
                f"slow window {slow_window_s}s"
            )
        self.fast_window_s = fast_window_s * window_scale
        self.slow_window_s = slow_window_s * window_scale
        self.clock = clock
        self._rules: dict[str, BurnRule] = {}
        self._sources: dict[str, Callable[[], tuple[float, float]]] = {}
        #: per rule: deque of (t, good, bad) cumulative snapshots
        self._snaps: dict[str, deque] = {}
        self._state: dict[str, str] = {}
        self._burns: dict[str, tuple[float, float]] = {}
        self.ticks = 0
        self.page_transitions = 0
        self.warn_transitions = 0

    # -- registration -------------------------------------------------------

    def add_rule(self, rule: BurnRule,
                 source: Callable[[], tuple[float, float]]) -> None:
        """`source()` returns CUMULATIVE (good, bad) event counts."""
        if rule.name in self._rules:
            raise ValueError(f"duplicate burn rule {rule.name!r}")
        self._rules[rule.name] = rule
        self._sources[rule.name] = source
        self._snaps[rule.name] = deque()
        self._state[rule.name] = "ok"
        self._burns[rule.name] = (0.0, 0.0)

    @property
    def rules(self) -> dict[str, BurnRule]:
        return dict(self._rules)

    # -- the math -----------------------------------------------------------

    @staticmethod
    def _window_burn(snaps, now: float, window_s: float,
                     budget: float) -> float:
        """Burn multiple over [now - window_s, now] from the snapshot
        deque. The window edge is the newest snapshot at or before the
        edge time (falling back to the oldest — early in a run both
        windows see the whole history, which is the correct multiwindow
        degenerate case: with little history fast == slow)."""
        if len(snaps) < 2:
            return 0.0
        edge_t = now - window_s
        edge = snaps[0]
        for s in snaps:
            if s[0] <= edge_t:
                edge = s
            else:
                break
        head = snaps[-1]
        dgood = head[1] - edge[1]
        dbad = head[2] - edge[2]
        total = dgood + dbad
        if total <= 0:
            return 0.0
        return (dbad / total) / budget

    def tick(self, now: float | None = None) -> dict[str, str]:
        """Snapshot every source, recompute burns, return rule states."""
        now = self.clock() if now is None else now
        self.ticks += 1
        for name, rule in self._rules.items():
            try:
                good, bad = self._sources[name]()
            except Exception:
                continue  # a dying source must not kill the evaluator
            snaps = self._snaps[name]
            snaps.append((now, float(good), float(bad)))
            # prune past the slow window (keep one snapshot beyond the
            # edge so the window difference stays full-width)
            while len(snaps) > 2 and snaps[1][0] <= now - self.slow_window_s:
                snaps.popleft()
            fast = self._window_burn(snaps, now, self.fast_window_s,
                                     rule.budget)
            slow = self._window_burn(snaps, now, self.slow_window_s,
                                     rule.budget)
            self._burns[name] = (fast, slow)
            # absorb float rounding so an exactly-threshold stream (the
            # closed-form 6x / 14.4x oracles) classifies at the threshold
            eps = 1e-9
            if fast >= rule.page_x - eps and slow >= rule.page_x - eps:
                state = "page"
            elif fast >= rule.warn_x - eps and slow >= rule.warn_x - eps:
                state = "warn"
            else:
                state = "ok"
            prev = self._state[name]
            if state == "page" and prev != "page":
                self.page_transitions += 1
            if state == "warn" and prev == "ok":
                self.warn_transitions += 1
            self._state[name] = state
        return dict(self._state)

    def states(self) -> dict[str, str]:
        return dict(self._state)

    def burns(self, name: str) -> tuple[float, float]:
        """(fast, slow) burn multiples of one rule as of the last tick."""
        return self._burns[name]

    def firing(self) -> list[tuple[str, str]]:
        """[(rule name, severity)] for every rule not currently ok."""
        return [(n, s) for n, s in self._state.items() if s != "ok"]

    # -- reporter surface ---------------------------------------------------

    def values(self) -> dict[str, float]:
        states = self._state.values()
        return {
            "rulesTotal": float(len(self._rules)),
            "rulesWarn": float(sum(1 for s in states if s == "warn")),
            "rulesPage": float(sum(1 for s in states if s == "page")),
            "evalTicksCt": float(self.ticks),
            "pageTransitionsCt": float(self.page_transitions),
            "warnTransitionsCt": float(self.warn_transitions),
        }

    def gauge_keys(self) -> set[str]:
        return {"rulesTotal", "rulesWarn", "rulesPage"}

    def labeled_values(self) -> dict[str, dict[str, float]]:
        out: dict[str, dict[str, float]] = {}
        for name, rule in self._rules.items():
            fast, slow = self._burns[name]
            out[name] = {
                "burnFast": fast,
                "burnSlow": slow,
                "budget": rule.budget,
                "alertState": STATE_CODE[self._state[name]],
            }
        return out

    def labeled_gauge_keys(self) -> set[str]:
        return {"burnFast", "burnSlow", "budget", "alertState"}

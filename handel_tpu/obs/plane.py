"""AlertPlane: one object composing evaluator + detectors + incidents.

The piece the control loop sees. `LifecycleController` (lifecycle/
controller.py) ticks it on its cadence; each tick snapshots every burn
rule, samples every detector series, and feeds the combined firing set
into the incident log. Harnesses (sim/load.py, sim/soak.py,
service/driver.py) build one from the `[alerts]` TOML section
(sim/config.py AlertParams), attach their rules/series, and register the
metrics surfaces:

    handel_alerts_*     evaluator + detector-bank planes, with per-rule
                        (`rule` label) and per-series (`series` label)
                        rows
    handel_incidents_*  incident log aggregates + per-incident rows
    GET /alerts         JSON snapshot (rules, series, incidents)

Attribution snapshots are assembled here: the slowest critical-path
chain from the FlightRecorder (via the `sim trace` walker), the top
anomalous detector series, plus any harness-registered context
providers (unhealthy regions, open breaker lanes).
"""

from __future__ import annotations

import time
from typing import Callable

from handel_tpu.obs.detect import DetectorBank
from handel_tpu.obs.incidents import IncidentLog
from handel_tpu.obs.slo import BurnRateEvaluator


class AlertPlane:
    """Evaluator + detector bank + incident log behind one tick()."""

    def __init__(self, fast_window_s: float = 60.0,
                 slow_window_s: float = 900.0, window_scale: float = 1.0,
                 min_hold_s: float = 2.0, cooldown_s: float = 5.0,
                 recorder=None,
                 trace_source: Callable[[], list] | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.evaluator = BurnRateEvaluator(
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            window_scale=window_scale, clock=clock,
        )
        self.detectors = DetectorBank(clock=clock)
        self.incidents = IncidentLog(
            snapshot_fn=self.snapshot, recorder=recorder,
            min_hold_s=min_hold_s, cooldown_s=cooldown_s, clock=clock,
        )
        #: FlightRecorder events source for the critical-path half of the
        #: attribution snapshot (e.g. `lambda: rec.export()["traceEvents"]`)
        self.trace_source = trace_source
        self._context: dict[str, Callable[[], object]] = {}

    @classmethod
    def from_params(cls, p, recorder=None, trace_source=None,
                    clock: Callable[[], float] = time.monotonic
                    ) -> "AlertPlane":
        """Build from an `[alerts]` params object (sim/config.py
        AlertParams — duck-typed so obs/ never imports sim/)."""
        return cls(
            fast_window_s=p.fast_window_s, slow_window_s=p.slow_window_s,
            window_scale=p.window_scale, min_hold_s=p.min_hold_s,
            cooldown_s=p.cooldown_s, recorder=recorder,
            trace_source=trace_source, clock=clock,
        )

    # -- attribution --------------------------------------------------------

    def add_context(self, name: str, fn: Callable[[], object]) -> None:
        """Harness-specific attribution context sampled at incident-open
        time (e.g. "unhealthy_regions" -> list of region names)."""
        self._context[name] = fn

    def snapshot(self) -> dict:
        """The causal-attribution snapshot captured when an incident
        opens: critical path, top anomalous series, harness context."""
        out: dict = {"top_anomalous": self.detectors.top_anomalous(5)}
        if self.trace_source is not None:
            try:
                from handel_tpu.sim.trace_cli import critical_path

                events = self.trace_source()
                cp = critical_path(events) if events else None
            except Exception:
                cp = None
            if cp:
                out["critical_path"] = {
                    "wall_ms": cp.get("wall_ms"),
                    "coverage": cp.get("coverage"),
                    "region_hops": cp.get("region_hops"),
                    "stages_ms": cp.get("stages_ms"),
                    # the slowest chain's tail is the causal headline;
                    # the full walk lives in the trace export itself
                    "chain_tail": (cp.get("chain") or [])[-8:],
                }
        for name, fn in self._context.items():
            try:
                out[name] = fn()
            except Exception as e:
                out[name] = f"context failed: {e}"
        return out

    # -- the control-loop tick ----------------------------------------------

    def tick(self, now: float | None = None) -> list[tuple[str, str]]:
        """One evaluation round; returns the firing set it observed."""
        now = self.clock() if now is None else now
        self.evaluator.tick(now)
        detections = self.detectors.tick(now)
        firings = self.evaluator.firing() + [
            (d.name, "page") for d in detections if d.opens_incident
        ]
        self.incidents.observe(firings, now)
        return firings

    # -- surfaces -----------------------------------------------------------

    def register_metrics(self, registry) -> None:
        """Wire the handel_alerts_* / handel_incidents_* families and the
        /alerts endpoint onto a MetricsRegistry."""
        registry.register_values("alerts", self.evaluator)
        registry.register_labeled_values(
            "alerts", self.evaluator, label="rule",
            gauges=self.evaluator.labeled_gauge_keys(),
        )
        registry.register_values("alerts", self.detectors)
        registry.register_labeled_values(
            "alerts", self.detectors, label="series",
            gauges=self.detectors.labeled_gauge_keys(),
        )
        registry.register_values("incidents", self.incidents)
        registry.register_labeled_values(
            "incidents", self.incidents, label="incident",
            gauges=self.incidents.labeled_gauge_keys(),
        )
        registry.set_alerts_source(self.alerts_payload)

    def alerts_payload(self) -> dict:
        """The GET /alerts JSON body."""
        rules = {}
        for name, row in self.evaluator.labeled_values().items():
            fast, slow = self.evaluator.burns(name)
            rules[name] = {
                "state": self.evaluator.states()[name],
                "burn_fast": round(fast, 3),
                "burn_slow": round(slow, 3),
                "budget": row["budget"],
            }
        return {
            "open": self.incidents.current is not None,
            "rules": rules,
            "series": self.detectors.labeled_values(),
            "incidents": [i.to_dict() for i in self.incidents.incidents],
        }

    def values(self) -> dict[str, float]:
        """Combined plane for the controller's reporter union."""
        out = dict(self.evaluator.values())
        out.update(self.detectors.values())
        out.update(self.incidents.values())
        return out

    def gauge_keys(self) -> set[str]:
        return (
            self.evaluator.gauge_keys()
            | self.detectors.gauge_keys()
            | self.incidents.gauge_keys()
        )

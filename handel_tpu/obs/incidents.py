"""Incident lifecycle: firing rules open, escalate and close incidents.

One `IncidentLog.observe(firings)` call per tick takes the currently
firing rules (burn rules from obs/slo.py, incident-opening detector
series from obs/detect.py) and drives the state machine:

- **open** — firings while nothing is open start an incident. At open
  time the log captures a causal-attribution snapshot via `snapshot_fn`
  (obs/plane.py: the slowest critical-path chain from the FlightRecorder
  through the `sim trace` walker, the top anomalous series, unhealthy
  regions, open breakers) — attribution reflects the moment the alert
  fired, not the later post-mortem.
- **correlate** — new rules firing while an incident is open attach to
  it as timeline entries instead of opening a second incident (one
  outage = one incident, even when a region kill also burns three tier
  budgets); severity escalates warn -> page at most once.
- **close** — an incident closes only after its rules have been
  continuously quiet for `min_hold_s` (min-hold half of flap
  suppression). A refire within `cooldown_s` of a close REOPENS the same
  incident and counts a flap instead of minting a new id (cooldown
  half).

Every transition emits a trace instant (`incident_open` /
`incident_escalate` / `incident_close`, cat="incident") so the incident
timeline lands in the same Perfetto export as the signals that caused
it, and `to_report()` serializes the full timeline as the
`incident_report.json` artifact.
"""

from __future__ import annotations

import time
from typing import Callable

#: trace tid for incident instants — the service-level control lane
#: (matches service/federation.py SERVICE_TID)
SERVICE_TID = -1

SEVERITY_CODE = {"warn": 1.0, "page": 2.0}
STATE_CODE = {"open": 1.0, "closed": 0.0}


class Incident:
    """One incident: id, severity, firing rules, attribution, timeline."""

    __slots__ = ("id", "kind", "severity", "state", "opened_at",
                 "escalated_at", "closed_at", "attribution", "rules",
                 "timeline", "flaps")

    def __init__(self, iid: int, kind: str, severity: str, opened_at: float,
                 attribution: dict):
        self.id = iid
        self.kind = kind  # the rule that opened it
        self.severity = severity
        self.state = "open"
        self.opened_at = opened_at
        self.escalated_at: float | None = None
        self.closed_at: float | None = None
        self.attribution = attribution
        self.rules: set[str] = {kind}
        self.timeline: list[dict] = []
        self.flaps = 0

    def event(self, at: float, what: str, **kw) -> None:
        self.timeline.append({"at": round(at, 4), "event": what, **kw})

    def age_s(self, now: float) -> float:
        return (self.closed_at or now) - self.opened_at

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "kind": self.kind,
            "severity": self.severity,
            "state": self.state,
            "opened_at": round(self.opened_at, 4),
            "escalated_at": (
                round(self.escalated_at, 4)
                if self.escalated_at is not None else None
            ),
            "closed_at": (
                round(self.closed_at, 4)
                if self.closed_at is not None else None
            ),
            "rules": sorted(self.rules),
            "flaps": self.flaps,
            "attribution": self.attribution,
            "timeline": self.timeline,
        }


class IncidentLog:
    """The incident state machine plus its reporter/report surfaces."""

    def __init__(self, snapshot_fn: Callable[[], dict] | None = None,
                 recorder=None, min_hold_s: float = 2.0,
                 cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.snapshot_fn = snapshot_fn
        self.recorder = recorder
        self.min_hold_s = min_hold_s
        self.cooldown_s = cooldown_s
        self.clock = clock
        self.incidents: list[Incident] = []
        self.current: Incident | None = None
        self._clear_since: float | None = None
        self._next_id = 1
        self.opened = 0
        self.escalated = 0
        self.closed = 0
        self.flapped = 0
        #: open/escalate/close listeners: fn(event, incident) — the
        #: control wiring (autoscaler repair, front-door mark-down)
        self._listeners: list[Callable[[str, Incident], None]] = []

    def add_listener(self, fn: Callable[[str, Incident], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, event: str, inc: Incident) -> None:
        for fn in self._listeners:
            try:
                fn(event, inc)
            except Exception:
                pass  # a broken consumer must not break the log

    def _instant(self, name: str, inc: Incident, now: float) -> None:
        if self.recorder is not None:
            self.recorder.instant(
                name, tid=SERVICE_TID, cat="incident",
                args={"incident": inc.id, "kind": inc.kind,
                      "severity": inc.severity},
            )

    def _snapshot(self) -> dict:
        if self.snapshot_fn is None:
            return {}
        try:
            return self.snapshot_fn()
        except Exception as e:
            return {"error": f"snapshot failed: {e}"}

    # -- the state machine --------------------------------------------------

    def observe(self, firings: list[tuple[str, str]],
                now: float | None = None) -> None:
        """One tick of [(rule name, severity)] currently firing."""
        now = self.clock() if now is None else now
        inc = self.current
        if firings:
            self._clear_since = None
            worst = ("page" if any(s == "page" for _, s in firings)
                     else "warn")
            if inc is None:
                last = self.incidents[-1] if self.incidents else None
                if (
                    last is not None
                    and last.closed_at is not None
                    and now - last.closed_at < self.cooldown_s
                ):
                    # flap: refire inside the cooldown reopens, no new id
                    inc = last
                    inc.state = "open"
                    inc.closed_at = None
                    inc.flaps += 1
                    self.flapped += 1
                    inc.event(now, "reopen", rules=[n for n, _ in firings])
                    self._instant("incident_reopen", inc, now)
                else:
                    inc = Incident(
                        self._next_id, firings[0][0], worst, now,
                        self._snapshot(),
                    )
                    self._next_id += 1
                    self.incidents.append(inc)
                    self.opened += 1
                    inc.event(now, "open", rules=[n for n, _ in firings])
                    self._instant("incident_open", inc, now)
                    self._notify("open", inc)
                self.current = inc
            for name, _sev in firings:
                if name not in inc.rules:
                    inc.rules.add(name)
                    inc.event(now, "correlate", rule=name)
            if worst == "page" and inc.severity != "page":
                inc.severity = "page"
                inc.escalated_at = now
                self.escalated += 1
                inc.event(now, "escalate")
                self._instant("incident_escalate", inc, now)
                self._notify("escalate", inc)
        elif inc is not None:
            if self._clear_since is None:
                self._clear_since = now
            if now - self._clear_since >= self.min_hold_s:
                inc.state = "closed"
                inc.closed_at = now
                self.closed += 1
                inc.event(now, "close")
                self._instant("incident_close", inc, now)
                self._notify("close", inc)
                self.current = None
                self._clear_since = None

    # -- reporter surface ---------------------------------------------------

    def values(self) -> dict[str, float]:
        return {
            "incidentsOpen": 1.0 if self.current is not None else 0.0,
            "openedCt": float(self.opened),
            "escalatedCt": float(self.escalated),
            "closedCt": float(self.closed),
            "flapCt": float(self.flapped),
        }

    def gauge_keys(self) -> set[str]:
        return {"incidentsOpen"}

    def labeled_values(self) -> dict[str, dict[str, float]]:
        now = self.clock()
        return {
            str(inc.id): {
                "severityCode": SEVERITY_CODE[inc.severity],
                "stateCode": STATE_CODE[inc.state],
                "ageS": inc.age_s(now),
                "ruleCt": float(len(inc.rules)),
                "flapsCt": float(inc.flaps),
            }
            for inc in self.incidents
        }

    def labeled_gauge_keys(self) -> set[str]:
        return {"severityCode", "stateCode", "ageS"}

    # -- the artifact -------------------------------------------------------

    def to_report(self, t0: float = 0.0) -> dict:
        """The incident_report.json timeline body. `t0` rebases the
        monotonic timestamps to run-relative seconds."""

        def rel(inc: dict) -> dict:
            out = dict(inc)
            for k in ("opened_at", "escalated_at", "closed_at"):
                if out.get(k) is not None:
                    out[k] = round(out[k] - t0, 4)
            out["timeline"] = [
                {**e, "at": round(e["at"] - t0, 4)} for e in inc["timeline"]
            ]
            return out

        return {
            "incidents": [rel(i.to_dict()) for i in self.incidents],
            "opened": self.opened,
            "escalated": self.escalated,
            "closed": self.closed,
            "flaps": self.flapped,
        }

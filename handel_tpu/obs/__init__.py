"""Detection-and-incident plane over the existing observability surfaces.

The repo emits every signal a production verify service needs (reporter
`values()` planes, LogHistogram quantiles, causal traces, region-labeled
federation gauges) but until this package nothing *interpreted* them — a
human watching `sim watch` was the alerting system. `obs/` closes the
loop:

- `slo.py`       multi-window error-budget burn-rate evaluation over the
                 tiered SLO targets (service/fairness.py) and the
                 federation goodput/shed planes
- `detect.py`    streaming EWMA + MAD z-score anomaly detectors,
                 attachable to any reporter key or histogram quantile,
                 seeded-deterministic and O(1) memory per series
- `incidents.py` firing rules open/escalate/close Incident objects with
                 a causal-attribution snapshot captured at open time
- `plane.py`     AlertPlane composes the three, ticks from the
                 LifecycleController, exports `handel_alerts_*` /
                 `handel_incidents_*` metrics and the `/alerts` endpoint
- `rollup.py`    hierarchical HostRollup/FleetRollup digests so the
                 fleet-scale plane costs O(hosts), not O(identities):
                 per-host bounded digests ride the monitor Sink as
                 chunked deltas, the master merge feeds the same
                 AlertPlane and exports `handel_fleet_*` + `/fleet`
"""

from handel_tpu.obs.detect import (  # noqa: F401
    Detection,
    DetectorBank,
    EwmaDetector,
    MadDetector,
    counter_rate,
    histogram_quantile_source,
    reporter_key_source,
)
from handel_tpu.obs.incidents import Incident, IncidentLog  # noqa: F401
from handel_tpu.obs.plane import AlertPlane  # noqa: F401
from handel_tpu.obs.rollup import (  # noqa: F401
    FleetRollup,
    HostRollup,
    chunk_delta,
    merge_trace_digests,
    trace_digest,
)
from handel_tpu.obs.slo import BurnRateEvaluator, BurnRule  # noqa: F401

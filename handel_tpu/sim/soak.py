"""`python -m handel_tpu.sim soak` — the lifecycle plane's CI proof.

A ~90 s continuously-loaded service run that exercises every production
lifecycle mechanism (handel_tpu/lifecycle/) mid-flight and writes a
bench-record-shaped `soak_report.json`:

- **sustained load** — a spawner keeps `concurrency` tiered sessions live
  for `duration_s`; every completion immediately back-fills, so the shared
  verify plane never idles.
- **mid-run epoch swap** — at `swap_at_frac` the EpochManager stages an
  identically-sized registry on every lane engine, quiesces, and flips.
  The registry CONTENT is unchanged (correctness trivially holds under the
  fake scheme); what the soak measures is the mechanics: the gate-closed
  stall, and that no launch gap around the swap exceeds twice the
  steady-state inter-launch p50 (+ a small timer-jitter floor).
- **forced lane loss** — at `lane_loss_at_frac` lane 0's breaker is
  tripped open; the LifecycleController's next autoscaler tick must
  replace it (attach first, drain second) with per-tenant p99 still
  inside every tier's SLO target.
- **zero dropped work** — every spawned session must reach a terminal
  verdict; `sessions_expired == 0` and nothing left live at exit.

Launch times are measured by tapping each lane engine's `dispatch_multi`
(exact, immune to flight-recorder ring eviction); the autotuner is fed
the causal tracer's real `stages_ms` attribution recomputed from the live
recorder every `autotune_every_s`.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from handel_tpu.core.logging import DEFAULT_LOGGER
from handel_tpu.core.trace import FlightRecorder
from handel_tpu.lifecycle import (
    CriticalPathAutotuner,
    EpochManager,
    LaneAutoscaler,
    LifecycleController,
)
from handel_tpu.models.fake import FakeScheme
from handel_tpu.obs import AlertPlane, EwmaDetector
from handel_tpu.service.driver import HostDevice, MultiSessionCluster
from handel_tpu.sim.report_checks import SOAK_CHECKS, attach

# scheduling-jitter floor for the swap-gap bound: a CI hypervisor can
# stretch any 2 ms sleep past 2x p50 with no swap involved at all
JITTER_FLOOR_MS = 10.0


def _tap_engine(engine, times: list, clock=time.monotonic):
    """Record a wall timestamp per dispatch — the exact launch times the
    gap analysis runs over."""
    orig = engine.dispatch_multi

    def wrapped(items, _orig=orig):
        times.append(clock())
        return _orig(items)

    engine.dispatch_multi = wrapped
    return engine


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def _gap_analysis(times: list[float], swap_t: float | None) -> dict:
    """Inter-launch gaps (ms): steady-state p50/p99/max plus the single
    gap straddling the epoch swap. The swap gap is EXCLUDED from the
    steady-state stats — it is the thing being compared against them."""
    ts = sorted(times)
    gaps = [
        (b - a) * 1e3 for a, b in zip(ts, ts[1:])
    ]
    swap_gap_ms = 0.0
    if swap_t is not None:
        for i, (a, b) in enumerate(zip(ts, ts[1:])):
            if a <= swap_t <= b:
                swap_gap_ms = gaps.pop(i)
                break
    gaps.sort()
    return {
        "launches": len(ts),
        "gap_p50_ms": round(_quantile(gaps, 0.50), 3),
        "gap_p99_ms": round(_quantile(gaps, 0.99), 3),
        "gap_max_ms": round(gaps[-1], 3) if gaps else 0.0,
        "swap_gap_ms": round(swap_gap_ms, 3),
    }


class SoakRun:
    """One soak: build the cluster + lifecycle plane, drive the timeline,
    emit the report. Split from the CLI so tests can run short soaks
    in-process with deterministic knobs."""

    def __init__(self, p, alert_p=None, logger=DEFAULT_LOGGER):
        self.p = p
        self.ap = alert_p
        self.log = logger
        self.launch_times: list[float] = []
        self.scheme = FakeScheme()
        self.rec = FlightRecorder(capacity=p.trace_capacity)
        self.cluster = MultiSessionCluster(
            sessions=0,  # the spawner drives arrivals, not cluster.run()
            nodes=p.nodes,
            scheme=self.scheme,
            devices=p.devices,
            batch_size=p.batch_size,
            max_sessions=max(2 * p.concurrency, 4),
            session_ttl_s=p.session_ttl_s,
            queue_capacity=p.queue_capacity,
            recorder=self.rec,
        )
        for lane in self.cluster.service.plane.lanes:
            _tap_engine(lane.engine, self.launch_times)
        self.epochs = EpochManager(
            self.cluster.service, self.cluster.manager, logger=logger
        )
        self.autoscaler = LaneAutoscaler(
            self.cluster.service,
            engine_factory=self._new_engine,
            # floor at the starting plane size: the lane-loss drill needs a
            # surviving lane while the replacement spins up
            min_lanes=p.devices,
            max_lanes=p.max_lanes,
            drain_timeout_s=5.0,  # a wedged drain must not stall the loop
            logger=logger,
        )
        self.autotuner = CriticalPathAutotuner(
            self.cluster.service, logger=logger
        )
        # detection-and-incident plane: the breaker-storm drill's witness,
        # ticked BY the controller so its autoscaler nudge lands in the
        # same control interval
        self.alerts: AlertPlane | None = (
            self._build_alert_plane()
            if alert_p is not None and alert_p.enabled else None
        )
        self.host_rollup = self._build_host_rollup()
        self.controller = LifecycleController(
            self.cluster.service,
            autoscaler=self.autoscaler,
            autotuner=self.autotuner,
            epoch_manager=self.epochs,
            alert_plane=self.alerts,
            host_rollup=self.host_rollup,
            report_source=self._stage_report,
            interval_s=p.control_interval_s,
            logger=logger,
        )
        self._tiers = [
            t.strip() for t in p.tiers.split(",") if t.strip()
        ]
        self._spawned = 0
        self._last_report: dict | None = None
        self._last_report_t = 0.0
        self.swap_t: float | None = None
        self.swap_stall_s = 0.0
        self.lane_lost_index: int | None = None
        self.lane_loss_t: float | None = None
        self.t0 = 0.0

    # -- the alert plane ----------------------------------------------------

    def _open_breaker_lanes(self) -> list[int]:
        return [
            l.index for l in self.cluster.service.plane.lanes
            if l.breaker.state == "open"
        ]

    def _build_alert_plane(self) -> AlertPlane:
        ap = self.ap
        plane = AlertPlane.from_params(
            ap, recorder=self.rec,
            trace_source=lambda: self.rec.export()["traceEvents"],
        )
        # the drill signal: breaker transitions are ~0/tick in steady
        # state, so a storm's burst of closed->open flips is a step the
        # EWMA catches immediately; hold_while keeps the incident open
        # until no lane is sitting on an open breaker
        plane.detectors.attach(
            "breaker-storm",
            lambda: self.cluster.service.values()["breakerTransitionsCt"],
            EwmaDetector(alpha=ap.ewma_alpha, z_threshold=ap.z_threshold),
            min_consecutive=ap.min_consecutive,
            opens_incident=True,
            direction="up",
            hold_while=lambda: bool(self._open_breaker_lanes()),
        )
        plane.detectors.attach(
            "queue-depth",
            lambda: float(self.cluster.service.queue_depth()),
            EwmaDetector(alpha=ap.ewma_alpha, z_threshold=ap.z_threshold),
            min_consecutive=max(2, ap.min_consecutive),
            direction="up",
        )
        plane.add_context("open_breaker_lanes", self._open_breaker_lanes)
        plane.add_context(
            "autoscaler",
            lambda: {
                "lanes": len(self.cluster.service.plane),
                "replaced": self.autoscaler.lanes_replaced,
            },
        )

        # breaker-storm incident -> repair-first scaling: the autoscaler's
        # next tick waives its grow/shrink cooldown
        def on_incident(event: str, inc) -> None:
            if event == "open" and "breaker" in inc.kind:
                self.autoscaler.notify_incident(inc.kind)

        plane.incidents.add_listener(on_incident)
        return plane

    def _build_host_rollup(self):
        """This process's hierarchical digest (obs/rollup.py): the
        per-session and per-lane surfaces fold to the key union, so the
        soak report (and any master this host reports to) carries one
        bounded block however many sessions the spawner churns through.
        Ticked by the LifecycleController on the control cadence."""
        from handel_tpu.obs.rollup import HostRollup

        top_k = self.ap.rollup_top_k if self.ap is not None else 8
        hr = HostRollup("soak0", top_k=top_k)
        m = self.cluster.manager
        svc = self.cluster.service
        hr.attach_reporter("service", svc)
        hr.attach_fold("sessions", lambda: (
            (vals, m.labeled_gauge_keys())
            for vals in m.labeled_values().values()
        ))
        hr.attach_fold("device", lambda: (
            (vals, svc.plane.labeled_gauge_keys())
            for vals in svc.plane.labeled_values().values()
        ))
        hr.set_trace(lambda: self.rec.export()["traceEvents"])
        hr.watch("rollup-queue-depth", lambda: float(svc.queue_depth()))
        hr.watch("rollup-sessions-live", lambda: float(m.live_count()))
        return hr

    def _rollup_block(self) -> dict:
        """Nested rollup block: digest bounds + the wire budget a chunked
        delta emission costs at report time."""
        d = self.host_rollup.digest()
        nbytes = self.host_rollup.emit()
        return {
            "host": d["host"],
            "surfaces": d["surfaces"],
            "series": sum(len(d[s]) for s in ("counters", "gauges",
                                              "hists")),
            "delta_bytes": nbytes,
            "top_anomalous": d["anoms"],
        }

    def _alert_block(self) -> dict | None:
        """Nested alerts block: the drill's detection latency (first
        incident open after the forced storm) plus the incident report."""
        if self.alerts is None:
            return None
        log = self.alerts.incidents
        latency_ms = None
        for inc in log.incidents:
            if (
                self.lane_loss_t is not None
                and inc.opened_at >= self.lane_loss_t
            ):
                latency_ms = round(
                    (inc.opened_at - self.lane_loss_t) * 1e3, 3
                )
                break
        return {
            "detection_latency_ms": latency_ms,
            "incident_nudges": self.autoscaler.incident_nudges,
            "report": log.to_report(self.t0),
        }

    def _new_engine(self):
        return _tap_engine(
            HostDevice(self.scheme.constructor, batch_size=self.p.batch_size),
            self.launch_times,
        )

    def _stage_report(self) -> dict | None:
        """The autotuner's stage attribution: the causal tracer's real
        critical-path walk over the live ring, recomputed at most every
        `autotune_every_s` (the walk is O(ring), not free)."""
        now = time.monotonic()
        if now - self._last_report_t < self.p.autotune_every_s:
            return self._last_report
        self._last_report_t = now
        from handel_tpu.sim.trace_cli import critical_path

        events = self.rec.export()["traceEvents"]
        self._last_report = critical_path(events)
        return self._last_report

    async def _spawner(self, t_end: float) -> None:
        """Hold `concurrency` sessions live until t_end, back-filling every
        completion; tiers deal round-robin so every SLO class is always
        represented in the mix."""
        m = self.cluster.manager
        while time.monotonic() < t_end:
            for sid, s in list(m.sessions.items()):
                if s.finished:
                    m.evict(sid)  # terminal verdict already banked
            while m.live_count() < self.p.concurrency:
                tier = (
                    self._tiers[self._spawned % len(self._tiers)]
                    if self._tiers
                    else None
                )
                s = m.spawn(
                    self.p.nodes,
                    seed=self._spawned,
                    tier=tier,
                    config_tweak=self._tweak,
                )
                m.start(s.sid)
                self._spawned += 1
            await asyncio.sleep(0.01)

    def _tweak(self, node_cfg, i):
        node_cfg.update_period = self.p.period_ms / 1000.0

    async def _rotate_epoch(self) -> None:
        """The mid-run swap: same-size registry (content irrelevant to the
        fake scheme), full stage -> quiesce -> flip choreography."""
        pubkeys = [
            self.scheme.keygen(i)[1] for i in range(self.p.registry)
        ]
        await self.epochs.begin_rotation(pubkeys)
        self.swap_t = time.monotonic()
        self.swap_stall_s = await self.epochs.commit_rotation()

    async def _force_lane_loss(self) -> None:
        """Trip lane 0's breaker open and wait for the controller's
        autoscaler tick to replace it."""
        lane = self.cluster.service.plane.lanes[0]
        self.lane_lost_index = lane.index
        self.lane_loss_t = time.monotonic()
        while lane.breaker.state != "open":
            lane.breaker.record_failure()
        # drive ticks directly (serialized against the background loop by
        # the controller lock) so a long drain in a prior interval can't
        # push the replacement past the drill window
        deadline = time.monotonic() + 15.0
        while (
            self.autoscaler.lanes_replaced < 1
            and time.monotonic() < deadline
        ):
            await self.controller.tick()
            await asyncio.sleep(0.1)

    async def run(self) -> dict:
        p = self.p
        self.t0 = t0 = time.monotonic()
        t_end = t0 + p.duration_s
        self.cluster.service.start()
        self.controller.start()
        spawner = asyncio.ensure_future(self._spawner(t_end))
        try:
            await asyncio.sleep(p.swap_at_frac * p.duration_s)
            await self._rotate_epoch()
            await asyncio.sleep(
                max(0.0, (p.lane_loss_at_frac - p.swap_at_frac) * p.duration_s)
            )
            await self._force_lane_loss()
            await spawner
            # drain: let the tail of live sessions reach their verdicts
            await self.cluster.manager.wait_all(p.session_ttl_s + 30.0)
            if self.alerts is not None:
                # a recovered drill should report a CLOSED incident: give
                # the controller its min-hold of quiet ticks (bounded)
                deadline = (
                    time.monotonic() + self.ap.min_hold_s
                    + 20.0 * p.control_interval_s
                )
                while (
                    self.alerts.incidents.current is not None
                    and time.monotonic() < deadline
                ):
                    await asyncio.sleep(p.control_interval_s)
        finally:
            spawner.cancel()
            await self.controller.stop()
        wall = time.monotonic() - t0
        return self._report(wall)

    def _report(self, wall_s: float) -> dict:
        p = self.p
        m = self.cluster.manager
        summary = self.cluster.summary(wall_s)
        gaps = _gap_analysis(self.launch_times, self.swap_t)
        tiers = m.tier_quantiles()
        unresolved = m.live_count()
        stall_ms = self.swap_stall_s * 1e3
        # the swap must hide inside the launch cadence the service already
        # exhibits: 2x the steady p50, or the steady p99 when session-wave
        # load makes the gap tail heavier than any swap, or the timer floor
        bound_ms = max(
            2 * gaps["gap_p50_ms"], gaps["gap_p99_ms"], JITTER_FLOOR_MS
        )
        soak_p99 = summary["session_p99_s"]
        report = {
            # bench-record shape (scripts/bench_check.py): headline +
            # SIDE_METRICS keys flat on the record, detail nested
            "metric": "soak_p99_s",
            "value": soak_p99,
            "backend": "cpu",
            "captured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "epoch_swap_stall_ms": round(stall_ms, 3),
            "soak_p99_s": soak_p99,
            "shed_rate": summary["shed_rate"],
            "aggregates_per_s": summary["aggregates_per_s"],
            "launch_fill_ratio": summary["launch_fill_ratio"],
            "soak": {
                "duration_s": p.duration_s,
                "wall_s": round(wall_s, 3),
                "sessions_spawned": self._spawned,
                "completed": summary["completed"],
                "expired": summary["expired"],
                "unresolved": unresolved,
                "swap_gap_bound_ms": round(bound_ms, 3),
                "epoch_rotations": self.epochs.rotations,
                "lane_lost": self.lane_lost_index,
                "lanes_replaced": self.autoscaler.lanes_replaced,
                "devices_floor": p.devices,
                "gaps": gaps,
                "tiers": tiers,
                # the causal attribution the autotuner last acted on
                "stages_ms": (self._last_report or {}).get("stages_ms", {}),
                "autotune_dominant": self.autotuner.last_dominant,
                "summary": summary,
                "lifecycle": self.controller.values(),
                "alerts": self._alert_block(),
                "rollup": self._rollup_block(),
            },
        }
        # the shared invariant specs (sim/report_checks.py) stamp `checks`
        # + `ok` — the same predicates soak_smoke re-asserts, so the
        # artifact and the gate can't drift
        return attach(report, SOAK_CHECKS)


async def run_soak(p, workdir: str, logger=DEFAULT_LOGGER,
                   alert_p=None) -> dict:
    """Run one soak and persist `<workdir>/soak_report.json`."""
    os.makedirs(workdir, exist_ok=True)
    run = SoakRun(p, alert_p=alert_p, logger=logger)
    try:
        report = await run.run()
    finally:
        run.cluster.stop()
    path = os.path.join(workdir, "soak_report.json")
    with open(path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    logger.info(
        "soak",
        f"{'OK' if report['ok'] else 'FAILED'} "
        f"completed={report['soak']['completed']} "
        f"swap_stall={report['epoch_swap_stall_ms']:.2f}ms "
        f"p99={report['soak_p99_s']:.3f}s shed={report['shed_rate']:.4f} "
        f"-> {path}",
    )
    return report

"""`sim watch`: live terminal dashboard over a run's /metrics endpoints.

Usage:
    python -m handel_tpu.sim watch <config.toml> [--workdir DIR]
        [--interval 1.0] [--snapshot PATH]
    python -m handel_tpu.sim watch --attach <workdir>  (scrape a running run)

The first form launches the simulation (forcing `metrics = true` and a
short post-END linger so the final counter state is scrapeable), discovers
every node process's endpoint from `<workdir>/metrics_ports.json`
(sim/platform.py writes it before spawning), and refreshes an ANSI
dashboard about once a second: the per-level completion wave across the
fleet, verify/queue-wait p50/p99 from the merged histograms, dedup hit
rate, breaker states, and penalty/ban counts. `--attach` skips launching
and scrapes an existing run dir instead (e.g. one started by another
terminal, or a remote run with forwarded ports).

`--snapshot` writes the last successful raw /metrics scrape of every
endpoint to a file — the captured evidence form (results/README.md).

Everything here is stdlib: urllib scrapes, ANSI escape rendering (no
curses dependency — a dumb pipe gets plain refreshing blocks instead).
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

from handel_tpu.core.metrics import merged_histogram, parse_exposition

SCRAPE_TIMEOUT_S = 0.75


# -- discovery ----------------------------------------------------------------


def discover_endpoints(workdir: str) -> list[str]:
    """Metrics addresses of a run dir: the platform's metrics_ports.json
    plus any `metrics_*.addr` files dropped by manually started nodes."""
    out: list[str] = []
    path = os.path.join(workdir, "metrics_ports.json")
    try:
        with open(path) as f:
            plan = json.load(f)
        out.extend(plan.get("addresses", {}).values())
    except (OSError, ValueError):
        pass
    for p in sorted(glob.glob(os.path.join(workdir, "metrics_*.addr"))):
        try:
            with open(p) as f:
                addr = f.read().strip()
            if addr and addr not in out:
                out.append(addr)
        except OSError:
            continue
    return out


def scrape(addr: str) -> tuple[dict, str] | None:
    """(parsed families, raw text) of one endpoint, or None when down."""
    try:
        with urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=SCRAPE_TIMEOUT_S
        ) as r:
            text = r.read().decode()
        return parse_exposition(text), text
    except (urllib.error.URLError, OSError, ValueError):
        return None


# -- aggregation --------------------------------------------------------------


def _samples(fams: dict, name: str) -> list[tuple[dict, float]]:
    fam = fams.get(name)
    return list(fam["samples"]) if fam else []


def _merge_all(scrapes: list[dict]) -> dict:
    """Concatenate parsed families across endpoints (samples keep their
    per-node labels, so nothing collapses)."""
    merged: dict = {}
    for fams in scrapes:
        for name, fam in fams.items():
            dst = merged.setdefault(name, {"type": fam["type"], "samples": []})
            dst["samples"].extend(fam["samples"])
    return merged


def aggregate(scrapes: list[dict]) -> dict:
    """The dashboard model from any number of parsed endpoint scrapes."""
    fams = _merge_all(scrapes)

    per_node_levels: dict[str, float] = {}
    for labels, v in _samples(fams, "handel_sigs_levels_completed_ct"):
        per_node_levels[labels.get("node", "?")] = v
    best = [v for _, v in _samples(fams, "handel_sigs_best_cardinality")]

    def hist_q(name, q):
        h = merged_histogram(fams, name)
        return h.quantile(q) if h else None

    def total(name):
        s = _samples(fams, name)
        return sum(v for _, v in s) if s else None

    def mean(name):
        s = _samples(fams, name)
        return sum(v for _, v in s) / len(s) if s else None

    # per-device breaker rows (the fleet plane) are rendered separately;
    # keep this count at one entry per verifier service
    breaker = [v for labels, v in _samples(
        fams, "handel_device_verifier_breaker_state"
    ) if "device" not in labels] + [
        v for _, v in _samples(fams, "handel_device_breaker_state")
    ]

    # multi-tenant service plane (handel_tpu/service/): per-session rows
    # keyed by the `session` label dimension, plus the manager aggregates
    sessions: dict[str, dict] = {}
    for field, name in (
        ("state", "handel_service_state"),
        ("pending", "handel_service_pending"),
        ("nodes_done", "handel_service_nodes_done"),
        ("nodes", "handel_service_nodes"),
        ("best", "handel_service_best_cardinality"),
        ("threshold", "handel_service_threshold"),
        ("queue", "handel_service_queue_depth"),
    ):
        for labels, v in _samples(fams, name):
            sid = labels.get("session")
            if sid:
                sessions.setdefault(sid, {})[field] = v

    # fleet-of-chips verifier plane (parallel/plane.py): per-device rows
    # keyed by the `device` label dimension beside the session axis
    devices: dict[str, dict] = {}
    for field, name in (
        ("launches", "handel_device_verifier_launches"),
        ("candidates", "handel_device_verifier_candidates"),
        ("fill", "handel_device_verifier_fill_ratio"),
        ("last_fill", "handel_device_verifier_last_fill"),
        ("inflight", "handel_device_verifier_inflight"),
        ("load", "handel_device_verifier_load"),
        ("retries", "handel_device_verifier_retries"),
        ("breaker", "handel_device_verifier_breaker_state"),
        # dual-mode scheduling (parallel/mesh_plane.py): 1 = whole-mesh
        # latency lane, 0 = per-chip throughput lane
        ("mode", "handel_device_verifier_mode"),
        # batch-check mode (models/rlc.py): 1 = rlc, 0 = per-candidate
        ("check", "handel_device_verifier_check_mode"),
        ("bisections", "handel_device_verifier_bisection_ct"),
    ):
        for labels, v in _samples(fams, name):
            did = labels.get("device")
            if did is not None:
                devices.setdefault(did, {})[field] = v

    # geo-federation plane (service/federation.py): one row per region
    # from the `region` label dimension, beside the front-door aggregates
    regions: dict[str, dict] = {}
    for field, name in (
        ("healthy", "handel_federation_region_healthy"),
        ("arrivals", "handel_federation_arrivals"),
        ("admitted", "handel_federation_admitted"),
        ("spill_in", "handel_federation_spill_in"),
        ("live", "handel_federation_sessions_live"),
        ("completed", "handel_federation_completed"),
        ("shed_rate", "handel_federation_shed_rate"),
        ("epoch", "handel_federation_epoch"),
        ("kills", "handel_federation_kills"),
    ):
        for labels, v in _samples(fams, name):
            rid = labels.get("region")
            if rid is not None:
                regions.setdefault(rid, {})[field] = v

    # hierarchical roll-up plane (obs/rollup.py FleetRollup
    # .register_metrics): one row per host digest from the `host` label
    # dimension, beside the master's O(hosts) merge aggregates
    fleet_hosts: dict[str, dict] = {}
    for field, name in (
        ("up", "handel_fleet_host_up"),
        ("seq", "handel_fleet_digest_seq"),
        ("series", "handel_fleet_series_ct"),
        ("top_z", "handel_fleet_top_z"),
    ):
        for labels, v in _samples(fams, name):
            hid = labels.get("host")
            if hid is not None:
                fleet_hosts.setdefault(hid, {})[field] = v

    # alert/incident plane (handel_tpu/obs/ via AlertPlane
    # .register_metrics): one row per burn rule from the `rule` label
    # dimension, beside the detector-bank and incident-log aggregates
    alert_rules: dict[str, dict] = {}
    for field, name in (
        ("state", "handel_alerts_alert_state"),
        ("burn_fast", "handel_alerts_burn_fast"),
        ("burn_slow", "handel_alerts_burn_slow"),
    ):
        for labels, v in _samples(fams, name):
            rid = labels.get("rule")
            if rid is not None:
                alert_rules.setdefault(rid, {})[field] = v

    def first(name):
        s = _samples(fams, name)
        return s[0][1] if s else None

    def first_global(name):
        # skip region-labeled samples of families that exist on both the
        # federation plane and the per-region plane (e.g. epoch)
        s = [v for labels, v in _samples(fams, name)
             if "region" not in labels]
        return s[0] if s else None

    return {
        "sessions": sessions,
        "devices": devices,
        "service_live": total("handel_service_sessions_live"),
        "service_completed": total("handel_service_sessions_completed"),
        "service_expired": total("handel_service_sessions_expired"),
        "service_evicted": total("handel_service_sessions_evicted"),
        "service_p50": first("handel_service_session_completion_p50_s"),
        "service_p99": first("handel_service_session_completion_p99_s"),
        "launch_fill": mean("handel_device_verifier_launch_fill_ratio"),
        "nodes": len(per_node_levels),
        "levels": per_node_levels,
        "best_min": min(best) if best else None,
        "best_max": max(best) if best else None,
        "verify_p50": hist_q("handel_sigs_verify_latency_s", 0.5),
        "verify_p99": hist_q("handel_sigs_verify_latency_s", 0.99),
        "queue_p50": hist_q("handel_sigs_queue_wait_s", 0.5),
        "queue_p99": hist_q("handel_sigs_queue_wait_s", 0.99),
        "wave_p50": hist_q("handel_sigs_level_complete_s", 0.5),
        "wave_p99": hist_q("handel_sigs_level_complete_s", 0.99),
        "dedup_rate": mean("handel_device_verifier_dedup_hit_rate")
        if fams.get("handel_device_verifier_dedup_hit_rate")
        else mean("handel_sigs_dedup_hit_rate"),
        "breaker_open": sum(1 for v in breaker if v >= 1.0),
        "breaker_half": sum(1 for v in breaker if v == 0.5),
        "breaker_total": len(breaker),
        "penalty_reports": total("handel_penalty_peer_penalty_reports"),
        "peers_banned": total("handel_penalty_peers_banned"),
        "invalid_packets": total("handel_sigs_invalid_packet_ct"),
        "net_sent": total("handel_net_sent_packets"),
        "net_rcvd": total("handel_net_rcvd_packets"),
        "net_dropped": total("handel_net_dropped_packets"),
        "verifier_launches": total("handel_device_verifier_verifier_launches"),
        "occupancy": mean("handel_device_verifier_verifier_occupancy"),
        # lifecycle plane (handel_tpu/lifecycle/ via the verifier values()):
        # registry epoch, plane-quiesce count + last gate-closed stall, SLO
        # admission shedding, and autoscaler lane churn
        "epoch": first("handel_device_verifier_epoch"),
        "quiesce_ct": total("handel_device_verifier_quiesce_ct"),
        "quiesce_stall_ms": first(
            "handel_device_verifier_last_quiesce_stall_ms"
        ),
        "admission_shed": total("handel_device_verifier_admission_shed"),
        "shed_rate": mean("handel_device_verifier_shed_rate"),
        "lanes_added": total("handel_device_verifier_lanes_added"),
        "lanes_removed": total("handel_device_verifier_lanes_removed"),
        # latency plane / dual-mode scheduling (parallel/mesh_plane.py)
        "mesh_lanes": total("handel_device_verifier_mesh_lanes"),
        "mesh_launches": total("handel_device_verifier_mesh_launches"),
        "mode_latency": total(
            "handel_device_verifier_mode_latency_launches"
        ),
        "mode_throughput": total(
            "handel_device_verifier_mode_throughput_launches"
        ),
        "mesh_fallbacks": total("handel_device_verifier_mesh_fallbacks"),
        # flight-recorder plane (core/trace.py values()): ring fill, drops
        # and the spans/s emit rate — the satellite-1 observability row
        "trace_events": total("handel_trace_trace_events"),
        "trace_dropped": total("handel_trace_trace_dropped"),
        "trace_rate": mean("handel_trace_trace_span_rate"),
        # geo-federation plane (service/federation.py) + the open-loop
        # load harness's own gauges (sim/load.py values())
        "regions": regions,
        "fed_regions_total": first_global("handel_federation_regions_total"),
        "fed_regions_healthy": first_global(
            "handel_federation_regions_healthy"
        ),
        "fed_retries": total("handel_federation_front_door_retries"),
        "fed_spillovers": total("handel_federation_spillover_ct"),
        "fed_sheds": total("handel_federation_front_door_sheds"),
        "fed_failures": total("handel_federation_front_door_failures"),
        "fed_epoch": first_global("handel_federation_epoch"),
        "load_arrivals": first("handel_load_arrivals"),
        "load_p50": first("handel_load_open_loop_p50_s"),
        "load_p99": first("handel_load_open_loop_p99_s"),
        "load_goodput": first("handel_load_goodput"),
        # hierarchical roll-up plane (obs/rollup.py): per-host digest rows
        # plus the master FleetRollup's merge aggregates — the watch
        # surface stays O(hosts) no matter how many identities run
        "fleet_hosts": fleet_hosts,
        "fleet_hosts_total": first("handel_fleet_hosts_total"),
        "fleet_hosts_up": first("handel_fleet_hosts_up"),
        "fleet_hosts_down": first("handel_fleet_hosts_down"),
        "fleet_series_total": first("handel_fleet_series_total"),
        "fleet_ingests": total("handel_fleet_ingests_ct"),
        "fleet_ingest_bytes": total("handel_fleet_ingest_bytes_ct"),
        "fleet_merge_ms": first("handel_fleet_last_merge_ms"),
        # alert/incident plane (handel_tpu/obs/): burn-rule rows plus the
        # incident-lifecycle counters — the `sim watch` alerting surface
        "alert_rules": alert_rules,
        "alerts_warn": total("handel_alerts_rules_warn"),
        "alerts_page": total("handel_alerts_rules_page"),
        "series_total": total("handel_alerts_series_total"),
        "series_anomalous": total("handel_alerts_series_anomalous"),
        "incidents_open": total("handel_incidents_incidents_open"),
        "incidents_opened": total("handel_incidents_opened_ct"),
        "incidents_closed": total("handel_incidents_closed_ct"),
        "incidents_flaps": total("handel_incidents_flap_ct"),
        "families": len(fams),
    }


# -- rendering ----------------------------------------------------------------


def _ms(v) -> str:
    return "  --  " if v is None else f"{v * 1e3:6.1f}ms"


def _num(v) -> str:
    return "--" if v is None else f"{v:.0f}"


def _bar(filled: int, total: int, width: int = 24) -> str:
    if total <= 0:
        return "." * width
    n = round(width * filled / total)
    return "#" * n + "." * (width - n)


#: handel_service_state code -> display name (service/session.py STATE_CODE)
_STATE_NAMES = {0: "spawned", 1: "running", 2: "done", 3: "expired",
                4: "evicted"}

TOP_K_SESSIONS = 8


def render_sessions(model: dict) -> list[str]:
    """Per-session row block: top-K sessions by pending work, each with
    its state and completion wave (nodes at threshold / committee size)."""
    sessions = model.get("sessions") or {}
    if not sessions and model.get("service_live") is None:
        return []
    lines = [
        f"sessions  live {_num(model.get('service_live'))}  "
        f"done {_num(model.get('service_completed'))}  "
        f"expired {_num(model.get('service_expired'))}  "
        f"evicted {_num(model.get('service_evicted'))}   "
        f"completion p50 {_ms(model.get('service_p50'))}  "
        f"p99 {_ms(model.get('service_p99'))}"
    ]
    top = sorted(
        sessions.items(),
        key=lambda kv: kv[1].get("pending", 0.0),
        reverse=True,
    )[:TOP_K_SESSIONS]
    for sid, row in top:
        state = _STATE_NAMES.get(int(row.get("state", 0)), "?")
        nodes = int(row.get("nodes", 0))
        done = int(row.get("nodes_done", 0))
        lines.append(
            f"  {sid:>8} {state:<8} pending {int(row.get('pending', 0)):>6}"
            f"  wave {_bar(done, nodes, 16)} {done}/{nodes}"
            f"  best {int(row.get('best', 0))}/{int(row.get('threshold', 0))}"
        )
    if len(sessions) > len(top):
        lines.append(f"  ... {len(sessions) - len(top)} more sessions")
    return lines


_BREAKER_NAMES = {0.0: "closed", 0.5: "half", 1.0: "open"}


def render_devices(model: dict) -> list[str]:
    """Per-device row block (fleet-of-chips verifier plane): scheduling
    mode, occupancy, fill and breaker state per plane lane, from the
    `device` label. Mesh lanes (latency plane, parallel/mesh_plane.py)
    render `mesh` in the mode column; their mean fill plus the service's
    mode-split counters make up the summary line."""
    devices = model.get("devices") or {}
    if not devices:
        return []
    mesh_rows = [r for r in devices.values() if r.get("mode", 0.0) >= 1.0]
    head = f"devices  ({len(devices)} verifier lanes"
    if mesh_rows:
        head += f", {len(mesh_rows)} mesh"
    lines = [head + ")"]
    for did in sorted(devices, key=lambda d: (len(d), d)):
        row = devices[did]
        fill = row.get("fill")
        breaker = _BREAKER_NAMES.get(row.get("breaker", 0.0), "?")
        mode = "mesh" if row.get("mode", 0.0) >= 1.0 else "lane"
        # batch-check mode column (models/rlc.py): rlc lanes also show
        # their bisection recheck count beside the verdict launches
        check = "rlc" if row.get("check", 0.0) >= 1.0 else "percand"
        bis = ""
        if check == "rlc":
            bis = f"  bisect {int(row.get('bisections', 0)):>4}"
        lines.append(
            f"  dev {did:>3} mode {mode}"
            f"  check {check:<7}"
            f"  launches {int(row.get('launches', 0)):>6}"
            f"  inflight {int(row.get('inflight', 0)):>2}"
            f"  load {int(row.get('load', 0)):>2}"
            f"  fill {('--' if fill is None else f'{fill:.2f}')}"
            f"  retries {int(row.get('retries', 0)):>3}"
            f"  breaker {breaker}{bis}"
        )
    if mesh_rows:
        fills = [r["fill"] for r in mesh_rows if r.get("fill") is not None]
        mesh_fill = sum(fills) / len(fills) if fills else None
        lat = model.get("mode_latency")
        thr = model.get("mode_throughput")
        fb = model.get("mesh_fallbacks")
        lines.append(
            f"  mesh     launches {int(model.get('mesh_launches') or 0):>6}"
            f"  fill {('--' if mesh_fill is None else f'{mesh_fill:.2f}')}"
            f"  modes latency {int(lat or 0)}"
            f" / throughput {int(thr or 0)}"
            f"  fallbacks {int(fb or 0)}"
        )
    return lines


def render_federation(model: dict) -> list[str]:
    """Geo-federation row block (service/federation.py): front-door
    aggregates, one row per region from the `region` label, and the
    open-loop arrival gauges — the `sim watch` surface of a
    `sim load` run (sim/load.py) with --metrics-port."""
    regions = model.get("regions") or {}
    if not regions and model.get("fed_regions_total") is None:
        return []
    lines = [
        f"federation  regions "
        f"{_num(model.get('fed_regions_healthy'))}/"
        f"{_num(model.get('fed_regions_total'))} healthy  "
        f"spillovers {_num(model.get('fed_spillovers'))}  "
        f"retries {_num(model.get('fed_retries'))}  "
        f"sheds {_num(model.get('fed_sheds'))}  "
        f"failures {_num(model.get('fed_failures'))}  "
        f"epoch {_num(model.get('fed_epoch'))}"
    ]
    for rid in sorted(regions):
        row = regions[rid]
        up = "up" if row.get("healthy", 0.0) >= 1.0 else "DOWN"
        sr = row.get("shed_rate")
        lines.append(
            f"  {rid:>10} {up:<4}"
            f"  live {int(row.get('live', 0)):>4}"
            f"  done {int(row.get('completed', 0)):>6}"
            f"  spill-in {int(row.get('spill_in', 0)):>4}"
            f"  shed {('--' if sr is None else f'{sr:.1%}')}"
            f"  kills {int(row.get('kills', 0))}"
        )
    if model.get("load_arrivals") is not None:
        gp = model.get("load_goodput")
        lines.append(
            f"  open-loop  arrivals {_num(model.get('load_arrivals'))}"
            f"  p50 {_ms(model.get('load_p50'))}"
            f"  p99 {_ms(model.get('load_p99'))}"
            f"  goodput {('--' if gp is None else f'{gp:.1%}')}"
        )
    return lines


def render_fleet(model: dict) -> list[str]:
    """Hierarchical roll-up block (obs/rollup.py): the master
    FleetRollup's O(hosts) view — hosts up/down, merged series count,
    wire ingest volume, one row per host digest, and the top anomalous
    host by its detectors' strongest z-score. The burn state beside it
    comes from the same AlertPlane the roll-ups feed (render_alerts)."""
    hosts = model.get("fleet_hosts") or {}
    if not hosts and model.get("fleet_hosts_total") is None:
        return []
    top = None
    for hid, row in hosts.items():
        z = row.get("top_z")
        if z is not None and (top is None or abs(z) > abs(top[1])):
            top = (hid, z)
    mm = model.get("fleet_merge_ms")
    head = (
        f"fleet    hosts {_num(model.get('fleet_hosts_up'))}/"
        f"{_num(model.get('fleet_hosts_total'))} up"
        f" ({_num(model.get('fleet_hosts_down'))} down)  "
        f"series {_num(model.get('fleet_series_total'))}  "
        f"ingests {_num(model.get('fleet_ingests'))} "
        f"({_num(model.get('fleet_ingest_bytes'))} B)  "
        f"merge {('--' if mm is None else f'{mm:.2f}ms')}"
    )
    if model.get("alerts_page") is not None:
        burn = "PAGE" if model["alerts_page"] else (
            "warn" if model.get("alerts_warn") else "ok"
        )
        head += f"  burn {burn}"
    lines = [head]
    if top is not None:
        lines.append(f"  top anomalous host {top[0]}  z {top[1]:+.2f}")
    for hid in sorted(hosts):
        row = hosts[hid]
        up = "up" if row.get("up", 0.0) >= 1.0 else "DOWN"
        lines.append(
            f"  {hid:>10} {up:<4}"
            f"  seq {int(row.get('seq', 0)):>5}"
            f"  series {int(row.get('series', 0)):>4}"
            f"  top-z {row.get('top_z', 0.0):+.2f}"
        )
    return lines


#: handel_alerts_alert_state code -> display name (obs/slo.py STATE_CODE)
_ALERT_STATE_NAMES = {0.0: "ok", 1.0: "WARN", 2.0: "PAGE"}


def render_alerts(model: dict) -> list[str]:
    """Alerts/incidents row block (handel_tpu/obs/): rule states with
    their fast/slow burn multiples, anomalous detector series, and the
    incident-lifecycle counters — non-ok rules render first."""
    rules = model.get("alert_rules") or {}
    if not rules and model.get("incidents_opened") is None:
        return []
    open_ct = model.get("incidents_open")
    lines = [
        f"alerts   warn {_num(model.get('alerts_warn'))}  "
        f"page {_num(model.get('alerts_page'))}  "
        f"anomalous {_num(model.get('series_anomalous'))}/"
        f"{_num(model.get('series_total'))} series   "
        f"incidents {'OPEN' if open_ct else 'none open'}  "
        f"opened {_num(model.get('incidents_opened'))}  "
        f"closed {_num(model.get('incidents_closed'))}  "
        f"flaps {_num(model.get('incidents_flaps'))}"
    ]
    for rid in sorted(
        rules, key=lambda r: (-rules[r].get("state", 0.0), r)
    ):
        row = rules[rid]
        state = _ALERT_STATE_NAMES.get(row.get("state", 0.0), "?")
        lines.append(
            f"  {rid:>16} {state:<4}"
            f"  burn fast {row.get('burn_fast', 0.0):7.2f}x"
            f"  slow {row.get('burn_slow', 0.0):7.2f}x"
        )
    return lines


def render(model: dict, endpoints: list[str], up: int, tick: int) -> str:
    """One dashboard frame as plain text (the caller adds ANSI)."""
    lines = [
        f"handel-tpu live telemetry — {up}/{len(endpoints)} endpoints up, "
        f"{model['families']} families, scrape #{tick} "
        f"@ {time.strftime('%H:%M:%S')}",
        "",
    ]
    levels = model["levels"]
    if levels:
        max_l = int(max(levels.values()) or 0)
        lines.append(f"aggregation wave ({model['nodes']} nodes reporting)")
        for l in range(1, max_l + 1):
            done = sum(1 for v in levels.values() if v >= l)
            lines.append(
                f"  level {l:>2} complete {_bar(done, len(levels))} "
                f"{done}/{len(levels)}"
            )
        if model["best_min"] is not None:
            lines.append(
                f"  best cardinality  min {_num(model['best_min'])}  "
                f"max {_num(model['best_max'])}"
            )
        if model["wave_p50"] is not None:
            lines.append(
                f"  level-complete    p50 {_ms(model['wave_p50'])}  "
                f"p99 {_ms(model['wave_p99'])}"
            )
    elif not model.get("sessions"):
        lines.append("aggregation wave: no sigs plane scraped yet")
    srows = render_sessions(model)
    if srows:
        lines.append("")
        lines.extend(srows)
    drows = render_devices(model)
    if drows:
        lines.append("")
        lines.extend(drows)
    frows = render_federation(model)
    if frows:
        lines.append("")
        lines.extend(frows)
    flrows = render_fleet(model)
    if flrows:
        lines.append("")
        lines.extend(flrows)
    arows = render_alerts(model)
    if arows:
        lines.append("")
        lines.extend(arows)
    lines.append("")
    lines.append(
        f"verify   p50 {_ms(model['verify_p50'])}  "
        f"p99 {_ms(model['verify_p99'])}   "
        f"queue wait p50 {_ms(model['queue_p50'])}  "
        f"p99 {_ms(model['queue_p99'])}"
    )
    dd = model["dedup_rate"]
    occ = model["occupancy"]
    fill = model.get("launch_fill")
    lines.append(
        f"verifier launches {_num(model['verifier_launches'])}  "
        f"occupancy {('--' if occ is None else f'{occ:.2f}')}  "
        f"fill {('--' if fill is None else f'{fill:.2f}')}  "
        f"dedup hit rate {('--' if dd is None else f'{dd:.1%}')}"
    )
    if model["breaker_total"]:
        state = (
            f"{model['breaker_open']} open / {model['breaker_half']} "
            f"half-open / {model['breaker_total']} total"
        )
    else:
        state = "no verifier plane"
    lines.append(f"breakers {state}")
    if model.get("epoch") is not None:
        sr = model.get("shed_rate")
        stall = model.get("quiesce_stall_ms")
        lines.append(
            f"lifecycle epoch {_num(model['epoch'])}  "
            f"quiesces {_num(model.get('quiesce_ct'))}"
            f" (last stall "
            f"{('--' if stall is None else f'{stall:.1f}ms')})  "
            f"shed {_num(model.get('admission_shed'))} "
            f"({('--' if sr is None else f'{sr:.1%}')})  "
            f"lanes +{_num(model.get('lanes_added'))}"
            f"/-{_num(model.get('lanes_removed'))}"
        )
    lines.append(
        f"penalties reports {_num(model['penalty_reports'])}  "
        f"peers banned {_num(model['peers_banned'])}  "
        f"invalid packets {_num(model['invalid_packets'])}"
    )
    lines.append(
        f"network  sent {_num(model['net_sent'])}  "
        f"rcvd {_num(model['net_rcvd'])}  "
        f"dropped {_num(model['net_dropped'])}"
    )
    if model.get("trace_events") is not None:
        rate = model.get("trace_rate")
        lines.append(
            f"tracing  spans {_num(model['trace_events'])}  "
            f"dropped {_num(model['trace_dropped'])}  "
            f"rate {('--' if rate is None else f'{rate:,.0f}/s')}"
        )
    return "\n".join(lines)


# -- the loop -----------------------------------------------------------------


def watch_loop(
    workdir: str,
    interval: float,
    done: threading.Event | None = None,
    snapshot: str = "",
    max_seconds: float = 0.0,
    out=sys.stdout,
) -> int:
    """Scrape-and-render until `done` is set (and endpoints drain) or
    `max_seconds` elapses. Returns the number of successful scrape rounds."""
    tick = 0
    rounds = 0
    last_raw: dict[str, str] = {}
    ansi = out.isatty() if hasattr(out, "isatty") else False
    t0 = time.monotonic()
    try:
        while True:
            endpoints = discover_endpoints(workdir)
            results = [(a, scrape(a)) for a in endpoints]
            parsed = [r[0] for _, r in results if r is not None]
            for a, r in results:
                if r is not None:
                    last_raw[a] = r[1]
            tick += 1
            if parsed:
                rounds += 1
                frame = render(aggregate(parsed), endpoints, len(parsed), tick)
                if ansi:
                    out.write("\x1b[2J\x1b[H" + frame + "\n")
                else:
                    out.write(frame + "\n" + "-" * 72 + "\n")
                out.flush()
            finished = done is not None and done.is_set()
            if finished and not parsed:
                break  # run over and every endpoint drained
            if max_seconds and time.monotonic() - t0 > max_seconds:
                break
            if done is None and tick > 3 and not parsed:
                break  # attach mode: nothing answering any more
            time.sleep(interval if not finished else min(interval, 0.2))
    except KeyboardInterrupt:
        pass
    if snapshot and last_raw:
        with open(snapshot, "w") as f:
            for addr in sorted(last_raw):
                f.write(f"# scrape http://{addr}/metrics\n")
                f.write(last_raw[addr])
                f.write("\n")
        print(f"snapshot: {snapshot} ({len(last_raw)} endpoints)",
              file=sys.stderr)
    return rounds


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m handel_tpu.sim watch",
        description="live dashboard over a simulation's /metrics endpoints",
    )
    ap.add_argument("config", nargs="?", help="simulation TOML to launch")
    ap.add_argument("--attach", default="",
                    help="scrape an existing run dir instead of launching")
    ap.add_argument("--workdir", default="sim_out")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--snapshot", default="",
                    help="write the final raw /metrics scrape here")
    ap.add_argument("--max-seconds", type=float, default=0.0,
                    help="stop watching after this long (0 = until run end)")
    args = ap.parse_args(argv)

    if args.attach:
        watch_loop(args.attach, args.interval, done=None,
                   snapshot=args.snapshot, max_seconds=args.max_seconds)
        return 0

    if not args.config:
        ap.error("need a config to launch, or --attach <workdir>")

    from handel_tpu.sim.config import load_config
    from handel_tpu.sim.platform import run_simulation

    cfg = load_config(args.config)
    cfg.metrics = True  # the whole point of watching
    # keep endpoints up past END long enough for a final full scrape
    cfg.metrics_linger_s = max(cfg.metrics_linger_s, 2.0 * args.interval)

    done = threading.Event()
    results: list = []

    def run() -> None:
        try:
            results.extend(
                asyncio.run(run_simulation(cfg, args.workdir))
            )
        finally:
            done.set()

    t = threading.Thread(target=run, name="sim-run", daemon=True)
    t.start()
    watch_loop(args.workdir, args.interval, done=done,
               snapshot=args.snapshot, max_seconds=args.max_seconds)
    t.join(timeout=cfg.max_timeout_s * (len(cfg.runs) + 1))
    ok = bool(results) and all(r.ok for r in results)
    for i, r in enumerate(results):
        print(f"run {i}: {'success' if r.ok else 'FAILED'} -> {r.csv_path}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

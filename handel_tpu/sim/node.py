"""Per-process simulation node entry point.

Reference: simul/node/main.go:33-144 — connect the monitor sink, load config
+ registry CSV, build K Handel instances (one per -id), signal the START
barrier, run until threshold, record `sigen`/`net`/`sigs` measures, verify
the final signature against the registry, signal END.

Run as: python -m handel_tpu.sim.node --config C --registry R --master M
        --monitor MON --run I --ids 1,2,3

All logical nodes in this process share one asyncio loop, one UDP socket per
node, and (with --shared-verifier) one device batch-verifier launch queue.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

from handel_tpu.core.crypto import Constructor, verify_multisignature
from handel_tpu.core.handel import Handel
from handel_tpu.models.registry import is_device_scheme, new_scheme
from handel_tpu.network.chaos import ChaosNetwork
from handel_tpu.network.encoding import CounterEncoding
from handel_tpu.network.udp import UDPNetwork
from handel_tpu.network.tcp import TCPNetwork
from handel_tpu.network.quic import QUICNetwork
from handel_tpu.sim import keys as simkeys
from handel_tpu.sim.adversary import (
    adversary_roles,
    build_adversary,
    check_threshold_reachable,
)
from handel_tpu.core.trace import FlightRecorder
from handel_tpu.sim.allocator import new_allocator
from handel_tpu.sim.config import load_config
from handel_tpu.sim.monitor import CounterIO, HistogramIO, Sink, TimeMeasure
from handel_tpu.sim.sync import STATE_END, STATE_START, SyncSlave

MSG = b"handel-tpu simulation message"


async def run_node_process(args) -> int:
    cfg = load_config(args.config)
    run = cfg.runs[args.run]

    # live telemetry plane (core/metrics.py): the HTTP endpoint comes up
    # BEFORE the scheme builds, so /healthz answers during a long warmup
    # while /readyz stays 503 until the readiness probes pass — scheme
    # warmed, breaker not open, monitor sink connected. `metrics = false`
    # (or no --metrics-port from the platform) keeps the plane fully off:
    # zero threads, zero sockets.
    mreg = mserver = None
    ready_state = {"scheme_warmed": False, "service": None}
    if cfg.metrics and getattr(args, "metrics_port", -1) >= 0:
        from handel_tpu.core.metrics import MetricsRegistry, MetricsServer

        mreg = MetricsRegistry()
        mreg.add_readiness(
            "scheme_warmed", lambda: ready_state["scheme_warmed"]
        )
        mreg.add_readiness(
            "breaker_closed",
            lambda: (
                ready_state["service"] is None
                or ready_state["service"].breaker.state != "open"
            ),
        )
        mreg.add_readiness(
            "monitor_sink", lambda: bool(sink) or not args.monitor
        )
        sink = None  # readiness closes over it before the real bind below
        mserver = MetricsServer(mreg, port=args.metrics_port).start()
        # the BOUND port is authoritative (--metrics-port 0 = ephemeral):
        # drop it next to the config so scrapers can discover manual runs
        addr_path = os.path.join(
            os.path.dirname(os.path.abspath(args.config)),
            f"metrics_{args.ids.split(',')[0]}.addr",
        )
        try:
            with open(addr_path, "w") as f:
                f.write(mserver.address + "\n")
        except OSError:
            pass
        print(f"metrics: serving on http://{mserver.address}", flush=True)

    if is_device_scheme(cfg.scheme):
        # select the JAX backend BEFORE the scheme module imports jax;
        # fake/host schemes never touch jax at all. mesh_devices > 1 on a
        # chip-less host needs that many virtual CPU devices
        from handel_tpu.utils.jaxenv import apply_platform_env

        apply_platform_env(
            force_host_device_count=(
                cfg.mesh_devices if cfg.mesh_devices > 1 else None
            )
        )
    scheme = new_scheme(
        cfg.scheme,
        **(
            {
                "batch_size": cfg.batch_size,
                "mesh_devices": cfg.mesh_devices,
                "fp_backend": cfg.fp_backend,
                # residency only means something on the rns backend; None
                # lets the pairing layer auto-detect (and avoids the
                # explicit-True-on-cios error)
                "rns_resident": (
                    cfg.rns_resident if cfg.fp_backend == "rns" else None
                ),
            }
            if is_device_scheme(cfg.scheme)
            else {}
        ),
    )
    ids = [int(x) for x in args.ids.split(",") if x != ""]
    threshold = run.resolved_threshold()
    # scheme construction runs the device warmup (models/bn254_jax.py
    # warms its kernels at build); fake/host schemes are warm by definition
    ready_state["scheme_warmed"] = True

    # span flight recorder (core/trace.py): one ring per process, every
    # logical node recording under its id as the Chrome-trace tid; dumped
    # as trace_<first-id>.json into --trace-dir after the END barrier
    recorder = None
    if getattr(args, "trace_dir", ""):
        recorder = FlightRecorder(capacity=cfg.trace_capacity, pid=os.getpid())

    sink = Sink(args.monitor) if args.monitor else None
    # process-wide batch-plane telemetry (SURVEY.md §5.1): G2 subgroup-check
    # cost (which starts accruing at registry load, right below), shared
    # launch fill ratio and device wall time added once the service exists.
    # Snapshot BEFORE the registry unmarshals so startup cost is attributed.
    plane = device_meas = None
    if sink:
        from handel_tpu.core.report import SUBGROUP_CHECKS, ReportAggregator

        plane = ReportAggregator(subgroup=SUBGROUP_CHECKS)
        device_meas = CounterIO(sink, "device", plane)

    records = simkeys.read_registry_csv(args.registry)
    registry = simkeys.registry_from_records(records, scheme)

    # WAN scenario plane (sim/config.py ScenarioParams): geo placement,
    # stake weights, weighted threshold — all derived identically in every
    # process from the shared TOML
    scen = cfg.scenario
    geo_base = scen.geo_config() if scen.geo_enabled() else None
    weights = scen.make_weights(run.nodes) if scen.weights_enabled() else None
    weight_threshold = (
        scen.weight_threshold(threshold, run.nodes, weights)
        if weights is not None
        else 0.0
    )

    # byzantine roles (sim/adversary.py): recompute the allocator's offline
    # set locally so every process derives the SAME id -> role mapping
    roles: dict[int, str] = {}
    if run.adversaries.total():
        alloc = new_allocator(cfg.allocator).allocate(
            run.nodes, 1, run.processes, run.failing
        )
        offline = {nid for nid, slot in alloc.items() if not slot.active}
        roles = adversary_roles(run.adversaries.counts(), run.nodes, offline)
        check_threshold_reachable(
            threshold,
            run.nodes,
            run.failing,
            roles,
            weights=weights,
            weight_threshold=weight_threshold,
        )

    # one transport per logical node, bound to its registry address
    nets, handels = [], []
    shared_service = None
    rpc_client = None
    rpc_server = None
    if args.verifier and not cfg.baseline:
        # chip-less process: ship candidate batches to the fleet's device
        # host instead of preparing a local device (no kernels compiled
        # here at all — parallel/rpc_verifier.py)
        from handel_tpu.parallel.rpc_verifier import RPCVerifier

        rpc_client = RPCVerifier(args.verifier)
        if plane is not None:
            plane.add("rpc", rpc_client)
    elif (
        cfg.shared_verifier
        and hasattr(scheme.constructor, "Device")
        and not cfg.baseline
    ):
        from handel_tpu.core.report import KernelTimer
        from handel_tpu.parallel.batch_verifier import BatchVerifierService

        # prepare() builds the device for this scheme's curve family AND
        # caches it on the constructor, so per-node constructor.batch_verify
        # calls reuse the same registry upload + executables
        device = scheme.constructor.prepare(registry.public_keys())
        # kernel-time trace hook (SURVEY.md §5.1): every shared launch's
        # wall time lands on the monitor plane. The timer sits on fetch
        # (verdict-arrival latency) + dispatch (host prep/enqueue) because
        # the pipelined service calls those directly; device.batch_verify
        # routes through the same instance attributes, so direct calls are
        # timed too
        launch_timer = KernelTimer(device.fetch, name="launch")
        device.fetch = launch_timer
        dispatch_timer = KernelTimer(device.dispatch, name="dispatch")
        device.dispatch = dispatch_timer
        # host failover target for the verifier circuit breaker: the
        # scheme's inherited host-side serial batch_verify (aggregate the
        # registry pubkey objects + one reference pairing check per
        # candidate) — a dead device degrades throughput, not the run
        pubkeys = registry.public_keys()

        def host_fallback(msg, reqs, _c=scheme.constructor, _pk=pubkeys):
            return Constructor.batch_verify(_c, msg, _pk, reqs)

        shared_service = BatchVerifierService(
            device, fallback=host_fallback, recorder=recorder
        )
        ready_state["service"] = shared_service
        if plane is not None:
            plane.add("verifier", shared_service)
            plane.add("launch", launch_timer)
            plane.add("dispatch", dispatch_timer)
        if args.serve_verifier:
            # this is the fleet's device host: serve the batch plane to
            # every chip-less process BEFORE the START barrier, so remote
            # clients never race the bind
            from handel_tpu.parallel.rpc_verifier import VerifierServer

            rpc_server = VerifierServer(
                shared_service,
                scheme.constructor,
                port=args.serve_verifier,
            )
            await rpc_server.start()
            if plane is not None:
                plane.add("rpcserve", rpc_server)

    for nid in ids:
        rec = records[nid]
        enc = CounterEncoding()
        if cfg.network == "tcp":
            net = TCPNetwork(rec.address, encoding=enc)
        elif cfg.network == "quic":
            net = QUICNetwork(rec.address, encoding=enc)
        else:
            net = UDPNetwork(rec.address, encoding=enc)
        if geo_base is not None:
            # geo-latency planet model (network/geo.py): region-pair WAN
            # delay, chaos faults composed on top when any rate is set
            from handel_tpu.network.geo import GeoNetwork

            net = GeoNetwork(
                net,
                geo_base.for_node(nid),
                chaos=cfg.chaos.for_node(nid) if cfg.chaos.any() else None,
            )
        elif cfg.chaos.any():
            # fault-injection plane (network/chaos.py): same transport
            # underneath, seeded per-link faults on top
            net = ChaosNetwork(net, cfg.chaos.for_node(nid))
        await net.start()
        nets.append(net)
        sk = simkeys.secret_of(rec, scheme)
        if cfg.baseline:  # comparison protocols (simul/p2p shared binary)
            from handel_tpu.baselines.gossip import GossipAggregator
            from handel_tpu.baselines.gossipsub import GossipSubAggregator

            agg_cls, kw = (
                (GossipSubAggregator, {})
                if cfg.baseline == "gossipsub"
                else (
                    GossipAggregator,
                    # same recv/verify/merge spans as Handel, so baseline
                    # traces compare like-for-like in the trace CLI
                    {
                        "connector": "full",
                        "recorder": recorder,
                        "trace_tid": nid,
                    },
                )
            )
            h = agg_cls(
                net,
                registry,
                registry.identity(nid),
                scheme.constructor,
                MSG,
                sk.sign(MSG),
                threshold,
                **kw,
            )
        else:
            hconf = run.handel.to_config(threshold, seed=nid)
            hconf.batch_size = cfg.batch_size
            hconf.recorder = recorder
            if geo_base is not None:
                hconf.region = geo_base.region_of(nid)
            if weights is not None:
                hconf.weights = weights
                hconf.weight_threshold = weight_threshold
            if shared_service is not None:
                hconf.verifier = shared_service.verify
            elif rpc_client is not None:
                hconf.verifier = rpc_client.verify
            if nid in roles:
                h = build_adversary(
                    roles[nid],
                    net,
                    registry,
                    registry.identity(nid),
                    scheme.constructor,
                    MSG,
                    sk,
                    hconf,
                    flood_pps=run.adversaries.flood_pps,
                    leave_after_s=run.adversaries.churn_after_ms / 1000.0,
                )
            else:
                h = Handel(
                    net,
                    registry,
                    registry.identity(nid),
                    scheme.constructor,
                    MSG,
                    sk.sign(MSG),
                    hconf,
                )
        handels.append((nid, h, net))

    # churn: a departing node notifies its co-located survivors directly
    # (Handel.mark_departed -> re-level + threshold re-evaluation). Cross-
    # process survivors see the departure as silence, exactly like a
    # `failing` node — the callback is a process-local accelerant, not a
    # consensus channel.
    from handel_tpu.sim.adversary import ROLE_CHURNER

    churners = [h for _, h, _ in handels if getattr(h, "role", None) == ROLE_CHURNER]
    if churners:
        survivors = [h for _, h, _ in handels]

        def _on_depart(departed_id: int, _peers=survivors) -> None:
            for p in _peers:
                md = getattr(p, "mark_departed", None)
                if md is not None:
                    md(departed_id)

        for ch in churners:
            ch.on_depart = _on_depart

    # registry-backed scrape surfaces: every logical node's protocol (sigs),
    # transport (net) and peer-penalty planes under a node label, the
    # process-wide verifier under device_verifier, device/XLA state under
    # device, host crypto counters under host (naming: handel_<plane>_<key>)
    if mreg is not None:
        for nid, h, net in handels:
            lbl = {"node": str(nid)}
            if hasattr(h, "values"):
                mreg.register_values("sigs", h, labels=lbl)
            if hasattr(h, "histograms"):
                mreg.register_histograms("sigs", h, labels=lbl)
            if hasattr(net, "values"):
                mreg.register_values("net", net, labels=lbl)
            if hasattr(net, "histograms"):
                mreg.register_histograms("net", net, labels=lbl)
            scorer = getattr(h, "scorer", None)
            if scorer is not None:
                mreg.register_values("penalty", scorer, labels=lbl)
        if shared_service is not None:
            mreg.register_values("device_verifier", shared_service)
        if plane is not None:
            mreg.register_values("host", plane)
        if recorder is not None:
            mreg.register_values("trace", recorder)
        if is_device_scheme(cfg.scheme) and not cfg.baseline:
            from handel_tpu.parallel.telemetry import DeviceTelemetry

            telemetry = DeviceTelemetry(
                service=shared_service,
                trace_dir=getattr(args, "trace_dir", "")
                or os.path.dirname(os.path.abspath(args.config)),
            )
            mreg.register_values("device", telemetry)
            mserver.set_profiler(telemetry.profile)

    # barrier: ready to start (one slave per logical node id)
    slaves = []
    for nid, _, _ in handels:
        s = SyncSlave(args.master, nid)
        await s.start()
        slaves.append(s)
    await asyncio.gather(
        *(s.signal_and_wait(STATE_START, cfg.max_timeout_s) for s in slaves)
    )
    if recorder is not None and slaves:
        # best (min-RTT) offset-vs-master estimate from the START handshake
        # (sim/sync.py): carried in the trace export so merge_traces aligns
        # this process's timeline with the rest of the fleet
        best_slave = min(slaves, key=lambda s: s.clock_rtt)
        if best_slave.clock_rtt != float("inf"):
            recorder.clock_offset = best_slave.clock_offset

    measures = []
    for nid, h, net in handels:
        if sink:
            # Handel.values() now carries the whole per-node plane —
            # processing + store + penalty counters; gossip reports itself.
            # Histogram reporters additionally ship the latency
            # distributions behind the _p50/_p90/_p99 CSV columns.
            ms = [TimeMeasure(sink, "sigen"), CounterIO(sink, "net", net),
                  CounterIO(sink, "sigs", h)]
            if hasattr(h, "histograms"):
                ms.append(HistogramIO(sink, "sigs", h))
            if hasattr(net, "histograms"):
                # chaos/geo delay distribution -> net_delayMs_p50/_p90/_p99
                ms.append(HistogramIO(sink, "net", net))
            measures.append(tuple(ms))
        else:
            measures.append(None)
        h.start()

    async def one_done(h):
        if hasattr(h, "final_signatures"):  # Handel
            return await h.final_signatures.get()
        return await h.final  # gossip baseline

    # adversarial nodes never emit an honest final signature — only the
    # honest cohort gates run completion
    honest = [
        (nid, h, net)
        for nid, h, net in handels
        if getattr(h, "role", None) is None
    ]
    try:
        finals = await asyncio.wait_for(
            asyncio.gather(*(one_done(h) for _, h, _ in honest)),
            timeout=cfg.max_timeout_s,
        )
    except asyncio.TimeoutError:
        # stall diagnostics: per-node progress is the only evidence a
        # multi-process deadlock leaves behind
        for nid, h, net in handels:
            best = getattr(h, "store", None) and h.store.full_signature()
            card = best.cardinality() if best else 0
            vals = net.values() if hasattr(net, "values") else {}
            print(
                f"node {nid}: STALLED at {card}/{threshold} "
                f"(sent={vals.get('sentPackets')} rcvd={vals.get('rcvdPackets')} "
                f"dropped={vals.get('droppedPackets')})",
                file=sys.stderr,
            )
        raise

    ok = True
    finals_by_nid = dict(zip((nid for nid, _, _ in honest), finals))
    for (nid, h, net), m in zip(handels, measures):
        if m:
            for meas in m:
                meas.record()
        ms = finals_by_nid.get(nid)
        if ms is not None and not verify_multisignature(
            MSG, ms, registry, scheme.constructor
        ):
            print(f"node {nid}: FINAL SIGNATURE INVALID", file=sys.stderr)
            ok = False
        h.stop()
        net.stop()

    await asyncio.gather(
        *(s.signal_and_wait(STATE_END, cfg.max_timeout_s) for s in slaves)
    )
    # batch-plane record (once per process) AFTER the fleet-wide END
    # barrier: a verifier-serving process keeps answering other hosts'
    # RPC batches until every node everywhere is done, so recording at
    # local-node completion would freeze its served counters early. The
    # master's monitor stays up until it has collected process exits, so
    # this post-barrier record still lands.
    if device_meas is not None:
        device_meas.record()
    if recorder is not None:
        recorder.dump(
            os.path.join(args.trace_dir, f"trace_{ids[0] if ids else 0}.json")
        )
    if mserver is not None:
        # keep the endpoint up briefly so scrapers catch the final counter
        # state of a short run (`sim watch` sets this; default 0)
        if cfg.metrics_linger_s > 0:
            await asyncio.sleep(cfg.metrics_linger_s)
        mserver.stop()
    for s in slaves:
        s.stop()
    if rpc_client is not None:
        rpc_client.stop()
    if rpc_server is not None:
        rpc_server.stop()
    if sink:
        sink.close()
    if ok:
        print(f"node process finished OK ids={ids}")
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--registry", required=True)
    ap.add_argument("--master", required=True)
    ap.add_argument("--monitor", default="")
    ap.add_argument("--run", type=int, default=0)
    ap.add_argument("--ids", required=True)
    # run-scoping marker only: never read, but present in argv so the
    # orchestrator's cleanup pkill can match THIS run's node processes
    # without killing other simulations on a shared host (sim/remote.py)
    ap.add_argument("--tag", default="")
    # batch-plane RPC (parallel/rpc_verifier.py): serve the local shared
    # verifier on this port / verify through the fleet's device host
    ap.add_argument("--serve-verifier", type=int, default=0)
    ap.add_argument("--verifier", default="")
    # span tracing: record a flight recorder (core/trace.py) and dump its
    # Chrome trace_event JSON into this directory at run end
    ap.add_argument("--trace-dir", default="")
    # live telemetry (core/metrics.py): serve /metrics+/healthz+/readyz on
    # this port (0 = ephemeral, bound port written next to the config);
    # absent (-1) or `metrics = false` in the TOML = plane fully off
    ap.add_argument("--metrics-port", type=int, default=-1)
    args = ap.parse_args()
    return asyncio.run(run_node_process(args))


if __name__ == "__main__":
    sys.exit(main())

"""Node -> process/instance allocation with offline injection.

Reference: simul/lib/allocator.go:25-197 — `RoundRobin` (deterministic,
evenly spaced offline nodes) and `RoundRandomOffline` (random offline set),
plus allocation validation. The allocation maps every logical node id to a
(process, instance) slot and marks Failing of them inactive; inactive nodes
are simply never launched (platform passes only active ids).
"""

from __future__ import annotations

import random
from dataclasses import dataclass


@dataclass
class NodeSlot:
    id: int
    instance: int  # machine index
    process: int  # process index within the machine (global numbering)
    active: bool


class RoundRobin:
    """Deterministic allocation: ids round-robin over processes; offline ids
    evenly spaced through the id range (allocator.go:52-86)."""

    def allocate(
        self, total: int, instances: int, procs_per_instance: int, failing: int
    ) -> dict[int, NodeSlot]:
        nproc = instances * procs_per_instance
        offline = set()
        if failing:
            step = total / failing
            offline = {int(i * step) for i in range(failing)}
        out = {}
        for nid in range(total):
            proc = nid % nproc
            out[nid] = NodeSlot(
                id=nid,
                instance=proc // procs_per_instance,
                process=proc,
                active=nid not in offline,
            )
        return verify_allocation(out, total, failing)


class RoundRandomOffline:
    """Round-robin placement with a seeded-random offline set
    (allocator.go:146-162)."""

    def __init__(self, seed: int = 777):
        self.seed = seed

    def allocate(
        self, total: int, instances: int, procs_per_instance: int, failing: int
    ) -> dict[int, NodeSlot]:
        nproc = instances * procs_per_instance
        rng = random.Random(self.seed)
        offline = set(rng.sample(range(total), failing)) if failing else set()
        out = {}
        for nid in range(total):
            proc = nid % nproc
            out[nid] = NodeSlot(
                id=nid,
                instance=proc // procs_per_instance,
                process=proc,
                active=nid not in offline,
            )
        return verify_allocation(out, total, failing)


def verify_allocation(
    alloc: dict[int, NodeSlot], total: int, failing: int
) -> dict[int, NodeSlot]:
    """Invariant checks (allocator.go verifyAllocation)."""
    if len(alloc) != total:
        raise ValueError(f"allocation covers {len(alloc)}/{total} nodes")
    inactive = sum(1 for s in alloc.values() if not s.active)
    if inactive != failing:
        raise ValueError(f"{inactive} offline nodes, expected {failing}")
    return alloc


def new_allocator(name: str):
    """simul/lib/config.go:228-238 allocator factory."""
    name = (name or "round-robin").lower()
    if name in ("round-robin", "roundrobin", "linear"):
        return RoundRobin()
    if name in ("round-random", "random"):
        return RoundRandomOffline()
    raise ValueError(f"unknown allocator {name!r}")

"""Simulation orchestrator CLI.

Reference: simul/main.go:24-68 — load the TOML config, run each RunConfig
in order on the chosen platform, abort a run after MaxTimeout.

Usage: python -m handel_tpu.sim --config sim.toml --workdir out/
       python -m handel_tpu.sim trace <trace-dir>   (analyze a traced run)
       python -m handel_tpu.sim watch sim.toml      (live /metrics dashboard)
       python -m handel_tpu.sim serve sim.toml      (multi-session service)
       python -m handel_tpu.sim swarm sim.toml      (virtual-node swarm)
       python -m handel_tpu.sim soak                (lifecycle soak proof)
       python -m handel_tpu.sim load                (open-loop federation load)
       python -m handel_tpu.sim scenario --config s.toml   (WAN scenario)
       python -m handel_tpu.sim confgen --scenario geo     (emit TOMLs)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from handel_tpu.sim.config import load_config
from handel_tpu.sim.platform import run_simulation


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "trace":
        # trace-analysis subcommand (sim/trace_cli.py): reconstruct the
        # aggregation wave + span attribution from flight-recorder dumps
        from handel_tpu.sim.trace_cli import main as trace_main

        return trace_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "watch":
        # live-telemetry subcommand (sim/watch_cli.py): launch a run with
        # metrics forced on and render the fleet's /metrics at ~1 Hz
        from handel_tpu.sim.watch_cli import main as watch_main

        return watch_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        # multi-tenant service subcommand (handel_tpu/service/driver.py):
        # run the [service] TOML section's K concurrent sessions over M
        # worker processes, one shared BatchVerifierService per process
        sap = argparse.ArgumentParser(prog="python -m handel_tpu.sim serve")
        sap.add_argument("config")
        sap.add_argument("--workdir", default="serve_out")
        sargs = sap.parse_args(sys.argv[2:])
        from handel_tpu.service.driver import run_service

        cfg = load_config(sargs.config)
        summary = asyncio.run(run_service(cfg, sargs.workdir, sargs.config))
        print(json.dumps(summary))
        return 0 if summary["ok"] else 1
    if len(sys.argv) > 1 and sys.argv[1] == "soak":
        # lifecycle soak subcommand (sim/soak.py): a continuously-loaded
        # service run with a mid-run epoch swap and a forced lane loss —
        # the production lifecycle plane's CI proof (handel_tpu/lifecycle/)
        kap = argparse.ArgumentParser(prog="python -m handel_tpu.sim soak")
        kap.add_argument("--config", default="", help="TOML with a [soak] section")
        kap.add_argument("--workdir", default="soak_out")
        kap.add_argument("--duration", type=float, default=0.0,
                         help="override [soak] duration_s")
        kargs = kap.parse_args(sys.argv[2:])
        from handel_tpu.sim.config import AlertParams, SoakParams
        from handel_tpu.sim.soak import run_soak

        if kargs.config:
            kcfg = load_config(kargs.config)
            p, al = kcfg.soak, kcfg.alerts
        else:
            p, al = SoakParams(), AlertParams()
        if kargs.duration > 0:
            p.duration_s = kargs.duration
        report = asyncio.run(run_soak(p, kargs.workdir, alert_p=al))
        print(json.dumps(report))
        return 0 if report["ok"] else 1
    if len(sys.argv) > 1 and sys.argv[1] == "load":
        # open-loop load subcommand (sim/load.py): seeded Poisson/diurnal/
        # burst arrivals against a geo-federated verify plane with an
        # optional mid-run region kill+recovery drill; writes the
        # federation_report.json robustness artifact into --workdir
        lap = argparse.ArgumentParser(prog="python -m handel_tpu.sim load")
        lap.add_argument("--config", default="",
                         help="TOML with [load] (+ optional [federation])")
        lap.add_argument("--workdir", default="load_out")
        lap.add_argument("--duration", type=float, default=0.0,
                         help="override [load] duration_s")
        lap.add_argument("--rate", type=float, default=0.0,
                         help="override [load] rate_sps")
        lap.add_argument("--metrics-port", type=int, default=None,
                         help="serve /metrics while the run is live")
        largs = lap.parse_args(sys.argv[2:])
        from handel_tpu.sim.config import (
            AlertParams,
            FederationParams,
            LoadParams,
        )
        from handel_tpu.sim.load import run_load

        if largs.config:
            lcfg = load_config(largs.config)
            lo, fe, al = lcfg.load, lcfg.federation, lcfg.alerts
        else:
            lo, fe, al = (
                LoadParams(rate_sps=4.0), FederationParams(), AlertParams()
            )
        if largs.duration > 0:
            lo.duration_s = largs.duration
        if largs.rate > 0:
            lo.rate_sps = largs.rate
        if not lo.enabled():
            lap.error("[load] rate_sps must be > 0 (or pass --rate)")
        report = asyncio.run(
            run_load(lo, fe, largs.workdir,
                     metrics_port=largs.metrics_port, alert_p=al)
        )
        print(json.dumps(report))
        return 0 if report["ok"] else 1
    if len(sys.argv) > 1 and sys.argv[1] == "swarm":
        # virtual-node swarm subcommand (handel_tpu/swarm/driver.py): run
        # the [swarm] TOML section's N identities as cooperative vnodes
        # multiplexed over a few event-loop processes
        wap = argparse.ArgumentParser(prog="python -m handel_tpu.sim swarm")
        wap.add_argument("config")
        wap.add_argument("--workdir", default="swarm_out")
        wargs = wap.parse_args(sys.argv[2:])
        from handel_tpu.swarm.driver import run_swarm

        cfg = load_config(wargs.config)
        summary = asyncio.run(run_swarm(cfg, wargs.workdir, wargs.config))
        print(json.dumps(summary))
        return 0 if summary["ok"] else 1
    if len(sys.argv) > 1 and sys.argv[1] == "scenario":
        # WAN scenario subcommand (handel_tpu/scenario/engine.py): run the
        # [scenario] TOML section's composed geo/churn/weights run in one
        # process and write the bench-shaped report + trace into --workdir
        zap = argparse.ArgumentParser(
            prog="python -m handel_tpu.sim scenario"
        )
        zap.add_argument("--config", required=True,
                         help="TOML with a [scenario] section")
        zap.add_argument("--workdir", default="scenario_out")
        zargs = zap.parse_args(sys.argv[2:])
        import os

        from handel_tpu.scenario import run_scenario

        cfg = load_config(zargs.config)
        os.makedirs(zargs.workdir, exist_ok=True)
        report = asyncio.run(run_scenario(cfg, zargs.workdir))
        print(json.dumps(report))
        return 0 if report["ok"] else 1
    if len(sys.argv) > 1 and sys.argv[1] == "confgen":
        # experiment-matrix generator (sim/confgen.py): emit ready-to-run
        # TOMLs; --scenario narrows to named entries (geo, churn,
        # weighted, geo_weighted, node_count, ...), default = all
        gap = argparse.ArgumentParser(
            prog="python -m handel_tpu.sim confgen"
        )
        gap.add_argument(
            "--scenario", action="append", default=None,
            help="scenario name (repeatable); omit for the full matrix",
        )
        gap.add_argument("--outdir", default="configs")
        gargs = gap.parse_args(sys.argv[2:])
        from handel_tpu.sim.confgen import generate

        for p in generate(gargs.outdir, gargs.scenario):
            print(p)
        return 0
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--workdir", default="sim_out")
    # platform dispatch (simul/main.go -platform flag)
    ap.add_argument("--platform", default="localhost")
    args = ap.parse_args()
    cfg = load_config(args.config)
    results = asyncio.run(run_simulation(cfg, args.workdir, platform=args.platform))
    ok = all(r.ok for r in results)
    for i, r in enumerate(results):
        status = "success" if r.ok else "FAILED"
        print(f"run {i}: {status} -> {r.csv_path}")
        if not r.ok:
            for out, err in r.outputs:
                if err:
                    sys.stderr.write(err.decode(errors="replace"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

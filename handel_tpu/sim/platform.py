"""Simulation platforms: configure -> deploy -> start -> collect.

Reference: simul/platform/platform.go:15-89 (lifecycle), localhost.go:16-266
(keygen + registry CSV + allocation + process spawning + barriers + stats
CSV). The AWS platform's role (aws.go) maps to a pod/GKE runner and is out of
scope for single-host rounds; the localhost platform is the primary vehicle
(SURVEY.md §2.5).
"""

from __future__ import annotations

import asyncio
import os
import socket
import sys

from handel_tpu.models.registry import is_device_scheme, new_scheme
from handel_tpu.sim import keys as simkeys
from handel_tpu.sim.allocator import new_allocator
from handel_tpu.sim.config import SimConfig, dump_config
from handel_tpu.sim.monitor import Monitor
from handel_tpu.sim.sync import STATE_END, STATE_START, SyncMaster


# the kernel's ephemeral source-port range: ports returned by bind(0) live
# here, so a released probe port can be re-grabbed as the SOURCE port of any
# connected socket (sync slaves, monitor sinks) before its intended process
# binds it — at 256+ node sockets per run that race is near-certain. Probing
# sequentially OUTSIDE the range closes it.
def _probe_window() -> tuple[int, int] | None:
    """(lo, hi) port window disjoint from the ephemeral range, or None when
    the configured range leaves no usable window (degrade to bind(0))."""
    eph_lo, eph_hi = 32768, 60999
    try:
        with open("/proc/sys/net/ipv4/ip_local_port_range") as f:
            eph_lo, eph_hi = (int(x) for x in f.read().split()[:2])
    except (OSError, ValueError):
        pass
    if eph_lo - 10000 >= 4096:  # enough room below the range
        return (max(10000, eph_lo - 22768), eph_lo)
    if 65536 - (eph_hi + 1) >= 2048:  # room above it
        return (eph_hi + 1, 65536)
    return None


_WINDOW = _probe_window()
# offset the start per process so concurrent runs on one host don't probe
# the same sequence (each still verifies by binding)
_probe_cursor = [
    _WINDOW[0] + (os.getpid() * 37) % ((_WINDOW[1] - _WINDOW[0]) // 2)
    if _WINDOW
    else 0
]


def free_ports(n: int) -> list[int]:
    """simul/lib/net.go:13-52, hardened for single-host scale: sequential
    ports outside the ephemeral range, each probed as BOTH udp and tcp so the
    result is usable by either transport family. All probe sockets are held
    until the full set is allocated. Falls back to kernel-chosen ports when
    the ephemeral range covers everything (pathological sysctl)."""
    socks, ports = [], []
    port = _probe_cursor[0]
    probes = 0
    max_probes = (_WINDOW[1] - _WINDOW[0]) if _WINDOW else 0
    while len(ports) < n:
        if _WINDOW is not None and probes >= max_probes + n:
            # one full pass over the window without filling the request:
            # every port is occupied (or n exceeds the window) — fail with a
            # diagnosable error instead of spinning forever
            for s in socks:
                s.close()
            raise OSError(
                f"free_ports: no {n} free ports in window {_WINDOW} "
                f"after {probes} probes ({len(ports)} found)"
            )
        u = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        t = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            if _WINDOW is None:  # no disjoint window: old bind(0) behavior
                u.bind(("127.0.0.1", 0))
                t.bind(("127.0.0.1", u.getsockname()[1]))
            else:
                if port >= _WINDOW[1]:
                    port = _WINDOW[0]  # wrap
                u.bind(("127.0.0.1", port))
                t.bind(("127.0.0.1", port))
        except OSError:  # something holds it: try the next port
            u.close()
            t.close()
            port += 1
            probes += 1
            continue
        socks += [u, t]
        ports.append(u.getsockname()[1])
        port += 1
    _probe_cursor[0] = port  # successive allocations advance, not reuse
    for s in socks:
        s.close()
    return ports


def port_plan(cfg, nodes: int) -> tuple[list[int], int, int, int]:
    """The fleet's port layout, shared by both platforms: node i at
    base_port + i, master at -2, monitor at -1, verifier RPC at -3.
    With base_port unset, ports are probed (free_ports holds-and-releases
    to guarantee availability; the verifier slot returns 0 — the caller
    probes one on demand). Returns (node_ports, master, monitor, verifier).
    """
    base = cfg.base_port
    if not base:
        ports = free_ports(nodes + 2)
        return ports[:nodes], ports[nodes], ports[nodes + 1], 0
    if base < 4 or base + nodes > 65536:
        raise ValueError(
            f"base_port {base} with {nodes} nodes leaves no room for the "
            f"master/monitor/verifier slots (need 4 <= base_port and "
            f"base_port + nodes <= 65536)"
        )
    return [base + i for i in range(nodes)], base - 2, base - 1, base - 3


def metrics_port_plan(cfg, nodes: int, nprocs: int) -> list[int]:
    """Per-process metrics ports (ISSUE 5 port hygiene): one /metrics
    endpoint per node process, so multi-process runs on one host never
    collide. With base_port set the plan is fixed ABOVE the node block
    (base_port + nodes + 1 + i — the master/monitor/verifier slots live
    below base, the node block ends at base + nodes); otherwise ports are
    probed like the node ports. Empty when `metrics = false` — the plane
    then costs zero sockets and zero threads."""
    if not cfg.metrics or nprocs <= 0:
        return []
    if cfg.base_port:
        lo = cfg.base_port + nodes + 1
        if lo + nprocs > 65536:
            raise ValueError(
                f"base_port {cfg.base_port} with {nodes} nodes leaves no "
                f"room for {nprocs} metrics ports above the node block"
            )
        return [lo + i for i in range(nprocs)]
    return free_ports(nprocs)


def write_metrics_ports(
    workdir: str, run_index: int, by_proc_ports: dict[int, int]
) -> str:
    """Persist the run's metrics endpoints (`sim watch` discovery file):
    {"run": i, "addresses": {"<process>": "127.0.0.1:<port>"}}."""
    import json

    path = os.path.join(workdir, "metrics_ports.json")
    with open(path, "w") as f:
        json.dump(
            {
                "run": run_index,
                "addresses": {
                    str(p): f"127.0.0.1:{port}"
                    for p, port in sorted(by_proc_ports.items())
                },
            },
            f,
            indent=1,
        )
    return path


def preflight_ports(ports: list[int]) -> None:
    """Fail fast if any fixed-plan port is already taken on this host:
    a silent bind failure inside one node process otherwise surfaces only
    as a full max_timeout_s barrier stall. Binds and immediately closes
    (sequential, so no fd accumulation at 16k ports)."""
    for p in ports:
        for fam in (socket.SOCK_DGRAM, socket.SOCK_STREAM):
            s = socket.socket(socket.AF_INET, fam)
            try:
                s.bind(("127.0.0.1", p))
            except OSError as e:
                raise OSError(
                    f"fixed port {p} is already in use ({e}); pick a "
                    f"different base_port"
                ) from e
            finally:
                s.close()


class LocalhostPlatform:
    """Spawn every node process on this machine (localhost.go:16-266)."""

    def __init__(self, cfg: SimConfig, workdir: str):
        self.cfg = cfg
        self.dir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.config_path = os.path.join(workdir, "sim.toml")
        with open(self.config_path, "w") as f:
            f.write(dump_config(cfg))

    async def start_run(self, run_index: int) -> "RunResult":
        cfg = self.cfg
        run = cfg.runs[run_index]
        if is_device_scheme(cfg.scheme):
            # select the JAX backend before the scheme module imports jax
            # (a downed TPU tunnel would otherwise hang keygen forever)
            from handel_tpu.utils.jaxenv import apply_platform_env

            apply_platform_env()
        scheme = new_scheme(cfg.scheme)

        # ports: node addresses + master + monitor. With base_port set the
        # fixed plan applies (probing holds 2 fds per port simultaneously,
        # which blows the fd limit at committee sizes like 16384) — with a
        # fail-fast probe of the range, since a taken port would otherwise
        # surface only as a barrier stall after max_timeout_s
        node_ports, master_p, monitor_p, _ = port_plan(cfg, run.nodes)
        if cfg.base_port:
            preflight_ports(node_ports + [master_p, monitor_p])
        addresses = [f"127.0.0.1:{p}" for p in node_ports]
        master_addr = f"127.0.0.1:{master_p}"
        monitor_port = cfg.monitor_port or monitor_p

        # keygen -> registry CSV (localhost.go:79-92)
        records = simkeys.generate_nodes(scheme, addresses)
        registry_path = os.path.join(self.dir, f"registry_{run_index}.csv")
        simkeys.write_registry_csv(registry_path, records)

        # allocation (localhost.go:82-120): offline nodes never launch
        alloc = new_allocator(cfg.allocator).allocate(
            run.nodes, 1, run.processes, run.failing
        )
        by_proc: dict[int, list[int]] = {}
        for nid, slot in alloc.items():
            if slot.active:
                by_proc.setdefault(slot.process, []).append(nid)
        active = sum(len(v) for v in by_proc.values())

        # master services. Declared keys pin the CSV schema: a degraded run
        # (every honest node timed out / adversarial-only reporters) emits
        # NaN columns with a warning instead of silently narrowing the CSV
        # the plots are keyed on (sim/monitor.py Stats.declare).
        monitor = Monitor(
            monitor_port,
            expected_keys=("sigen_wall", "sigs_sigCheckedCt", "net_sentPackets"),
        )
        await monitor.start()
        sync = SyncMaster(int(master_addr.rsplit(":", 1)[1]), active)
        await sync.start()

        # span tracing: each node process dumps its flight recorder into the
        # run's trace dir; `python -m handel_tpu.sim trace <dir>` analyzes it
        trace_dir = ""
        if cfg.trace:
            trace_dir = os.path.join(self.dir, f"trace_{run_index}")
            os.makedirs(trace_dir, exist_ok=True)

        # live telemetry: one /metrics endpoint per node process, plan
        # written to the run dir BEFORE spawning so `sim watch` can attach
        # from the first scrape (ISSUE 5)
        metrics_ports = metrics_port_plan(cfg, run.nodes, len(by_proc))
        metrics_by_proc: dict[int, int] = {}
        if metrics_ports:
            if cfg.base_port:
                preflight_ports(metrics_ports)
            metrics_by_proc = dict(
                zip((p for p, _ in sorted(by_proc.items())), metrics_ports)
            )
            write_metrics_ports(self.dir, run_index, metrics_by_proc)

        procs = []
        try:
            for pidx, ids in sorted(by_proc.items()):
                cmd = [
                    sys.executable,
                    "-m",
                    "handel_tpu.sim.node",
                    "--config",
                    self.config_path,
                    "--registry",
                    registry_path,
                    "--master",
                    master_addr,
                    "--monitor",
                    f"127.0.0.1:{monitor_port}",
                    "--run",
                    str(run_index),
                    "--ids",
                    ",".join(map(str, ids)),
                ]
                if trace_dir:
                    cmd += ["--trace-dir", trace_dir]
                if pidx in metrics_by_proc:
                    cmd += ["--metrics-port", str(metrics_by_proc[pidx])]
                procs.append(
                    await asyncio.create_subprocess_exec(
                        *cmd,
                        stdout=asyncio.subprocess.PIPE,
                        stderr=asyncio.subprocess.PIPE,
                    )
                )

            timed_out = False
            try:
                await sync.wait_all(STATE_START, cfg.max_timeout_s)
                await sync.wait_all(STATE_END, cfg.max_timeout_s)
            except asyncio.TimeoutError:
                # a node died or stalled before signaling: kill the tree but
                # REAP the children and keep their output — the only
                # diagnostics a multi-process stall leaves behind
                timed_out = True
                for p in procs:
                    if p.returncode is None:
                        p.kill()
            outs = await asyncio.gather(*(p.communicate() for p in procs))
            rcs = [p.returncode for p in procs]
        finally:
            for p in procs:
                if p.returncode is None:
                    p.kill()
            sync.stop()
            monitor.stop()

        # stats CSV (localhost.go:201-206)
        monitor.stats.extra = run.stats_extra(run_index)
        csv_path = os.path.join(self.dir, f"results_{run_index}.csv")
        monitor.stats.write_csv(csv_path)
        ok = (
            not timed_out
            and all(rc == 0 for rc in rcs)
            and all(b"finished OK" in out for out, _ in outs)
        )
        return RunResult(
            ok=ok,
            csv_path=csv_path,
            outputs=outs,
            returncodes=rcs,
            trace_dir=trace_dir,
        )


class RunResult:
    def __init__(self, ok, csv_path, outputs, returncodes, trace_dir=""):
        self.ok = ok
        self.csv_path = csv_path
        self.outputs = outputs
        self.returncodes = returncodes
        self.trace_dir = trace_dir


def new_platform(name: str, cfg: SimConfig, workdir: str):
    """Platform dispatch (simul/platform/platform.go:59 NewPlatform:
    "localhost" | "aws"). "remote" is the aws analog (sim/remote.py):
    ship the package to a host list (ssh or localhost-as-remote), start node
    processes there, run the barriers from this process. Cloud provisioning
    (the Terraform layer) stays out of scope — a GKE/TPU-pod runner is
    `platform=remote` plus an externally provisioned host list."""
    if name == "localhost":
        return LocalhostPlatform(cfg, workdir)
    if name == "remote":
        from handel_tpu.sim.remote import RemotePlatform

        return RemotePlatform(cfg, workdir)
    raise ValueError(
        f"unknown platform {name!r} (available: localhost, remote)"
    )


async def run_simulation(
    cfg: SimConfig, workdir: str, platform: str = "localhost"
) -> list[RunResult]:
    """Orchestrator: run every RunConfig sequentially (simul/main.go:24-68)."""
    plat = new_platform(platform, cfg, workdir)
    results = []
    for i in range(len(cfg.runs)):
        res = None
        for attempt in range(cfg.retrials):
            try:
                res = await plat.start_run(i)
            except asyncio.TimeoutError:
                # barrier never released (a node died before signaling):
                # that's exactly what retrials exist for (config.go Retrials)
                res = RunResult(
                    ok=False, csv_path="", outputs=[], returncodes=[]
                )
            if res.ok:
                break
        results.append(res)
    return results

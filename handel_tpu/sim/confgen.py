"""Experiment-matrix TOML generator.

Reference: simul/confgenerator/confgenerator.go:18-469 — programmatic
generation of the paper's scenario files (node-count sweeps 100->4000,
failing-node grids, threshold increments, update-period/timeout sweeps,
baseline nsquare/libp2p matrices), each emitted as a simulation TOML.

Each scenario function returns a SimConfig; `generate(outdir)` writes the
whole matrix. The TPU additions ride the same knobs: scheme selects the
device path ("bn254-jax"), `batch_size` the launch width, `shared_verifier`
the fused many-node device service.
"""

from __future__ import annotations

import os

from handel_tpu.sim.config import (
    AdversaryParams,
    HandelParams,
    RunConfig,
    ScenarioParams,
    SimConfig,
    SoakParams,
    SwarmParams,
    dump_config,
)

# the reference's standard sweep (confgenerator.go nodesCount scenarios)
NODE_SWEEP = [100, 300, 500, 1000, 2000, 4000]

# ceiling on generated worker processes: the uncapped n//500 rule was an
# AWS-fleet assumption — at swarm scale it emits configs asking one host
# for 131 Python processes (65536 nodes), which fork-bombs a laptop and
# adds nothing once processes exceed cores. Above this, use the swarm
# runtime (scenario_swarm) which multiplexes identities as vnodes instead.
MAX_PROCESSES = 16


def default_processes(n: int) -> int:
    """Process count for an n-node run: the reference's one-per-500 rule,
    capped at MAX_PROCESSES."""
    return min(max(1, n // 500), MAX_PROCESSES)


def _runs(nodes_list, threshold_of, failing_of=lambda n: 0, processes_of=None, **hp):
    if processes_of is None:
        processes_of = default_processes
    return [
        RunConfig(
            nodes=n,
            threshold=threshold_of(n),
            failing=failing_of(n),
            processes=processes_of(n),
            handel=HandelParams(**hp),
        )
        for n in nodes_list
    ]


def scenario_node_count(scheme: str = "bn254-jax") -> SimConfig:
    """Completion time vs committee size at 99% threshold (the headline
    curve, confgenerator.go nodeCount scenario)."""
    return SimConfig(
        network="udp",
        scheme=scheme,
        runs=_runs(NODE_SWEEP, lambda n: n * 99 // 100),
    )


def scenario_threshold_inc(nodes: int = 2000) -> SimConfig:
    """Threshold sweep 51/75/90/99% at fixed N (thresholdInc scenario)."""
    return SimConfig(
        network="udp",
        scheme="bn254-jax",
        runs=[
            RunConfig(nodes=nodes, threshold=nodes * pct // 100,
                      processes=default_processes(nodes))
            for pct in (51, 75, 90, 99)
        ],
    )


def scenario_failing(nodes: int = 4000) -> SimConfig:
    """Failing-node grid at fixed N: up to 49% dead, threshold 51%
    (confgenerator.go failing scenario / handel_4000_failing.csv)."""
    return SimConfig(
        network="udp",
        scheme="bn254-jax",
        runs=[
            RunConfig(
                nodes=nodes,
                threshold=nodes * 51 // 100,
                failing=f,
                processes=default_processes(nodes),
            )
            for f in (0, nodes // 10, nodes // 4, nodes * 49 // 100)
        ],
    )


def scenario_period(nodes: int = 2000) -> SimConfig:
    """Update-period sweep (periods scenario)."""
    return SimConfig(
        network="udp",
        scheme="bn254-jax",
        runs=[
            r
            for ms in (10.0, 20.0, 50.0, 100.0)
            for r in _runs([nodes], lambda n: n * 99 // 100, period_ms=ms)
        ],
    )


def scenario_timeout(nodes: int = 2000) -> SimConfig:
    """Level-timeout sweep (timeout scenario)."""
    return SimConfig(
        network="udp",
        scheme="bn254-jax",
        runs=[
            r
            for ms in (50.0, 100.0, 200.0, 500.0)
            for r in _runs([nodes], lambda n: n * 99 // 100, timeout_ms=ms)
        ],
    )


def scenario_update_count(nodes: int = 2000) -> SimConfig:
    """Per-tick update fanout sweep: how many peers each node refreshes per
    period (confgenerator.go:135-162 updateCountScenario, updates 1/10/20
    at N=2000)."""
    return SimConfig(
        network="udp",
        scheme="bn254-jax",
        runs=[
            r
            for uc in (1, 10, 20)
            for r in _runs([nodes], lambda n: n * 99 // 100, update_count=uc)
        ],
    )


def scenario_nsquare() -> SimConfig:
    """Full-diffusion gossip baseline matrix (nsquare scenario)."""
    return SimConfig(
        network="udp",
        scheme="bn254",
        baseline="nsquare",
        runs=_runs(NODE_SWEEP[:4], lambda n: n * 51 // 100),
    )


def scenario_gossipsub() -> SimConfig:
    """Gossipsub baseline matrix (libp2p scenario): per-topic meshes,
    GRAFT/PRUNE, IHAVE/IWANT — baselines/gossipsub.py."""
    return SimConfig(
        network="udp",
        scheme="bn254",
        baseline="gossipsub",
        runs=_runs(NODE_SWEEP[:4], lambda n: n * 51 // 100),
    )


def scenario_practical(nodes: int = 4000) -> SimConfig:
    """The README headline run: N=4000, 99% threshold, real crypto on the
    device path with the shared verifier fusing co-located nodes' batches."""
    return SimConfig(
        network="udp",
        scheme="bn254-jax",
        shared_verifier=True,
        batch_size=128,
        runs=_runs([nodes], lambda n: n * 99 // 100),
    )


def scenario_evaluator(nodes: int = 2000) -> SimConfig:
    """Verification-strategy A/B at fixed N: store-scored vs verify-everything
    vs arrival-order FIFO (confgenerator.go evaluator scenario)."""
    return SimConfig(
        network="udp",
        scheme="bn254-jax",
        runs=[
            RunConfig(
                nodes=nodes,
                threshold=nodes * 99 // 100,
                processes=default_processes(nodes),
                handel=HandelParams(evaluator=ev),
            )
            for ev in ("store", "eval1", "fifo")
        ],
    )


def scenario_swarm(identities: int = 65536, processes: int = 1) -> SimConfig:
    """Virtual-node swarm run (handel_tpu/swarm/; `sim swarm`): identities
    beyond what per-node processes can carry, multiplexed as vnodes on a
    shared event loop. Gossip is set sparse — the in-memory router is
    lossless and the id-staggered fast-path cascade covers every level
    deterministically, so each gossip round only costs CPU (roughly
    identities x active-levels deliveries per period on one core)."""
    return SimConfig(
        trace=True,
        trace_capacity=1 << 22,
        swarm=SwarmParams(
            identities=identities,
            processes=processes,
            period_ms=120000.0,
            timeout_ms=50.0,
            fast_path=3,
            timeout_s=5400.0,
        ),
    )


def _scenario_base(nodes: int, scenario: ScenarioParams,
                   churner: int = 0, churn_after_ms: float = 300.0) -> SimConfig:
    """Shared shape for the WAN scenario engine configs (`sim scenario`):
    fake scheme (the WAN model, not pairings, is under test), tracing on
    for region attribution, and a short [soak] section so the same TOML is
    directly runnable as a `sim soak` workload too."""
    return SimConfig(
        scheme="fake",
        trace=True,
        trace_capacity=1 << 18,
        max_timeout_s=60.0,
        scenario=scenario,
        soak=SoakParams(duration_s=20.0, nodes=min(nodes, 32)),
        runs=[
            RunConfig(
                nodes=nodes,
                processes=1,
                adversaries=AdversaryParams(
                    churner=churner, churn_after_ms=churn_after_ms
                ),
                handel=HandelParams(period_ms=10.0, timeout_ms=50.0),
            )
        ],
    )


def scenario_geo(nodes: int = 32) -> SimConfig:
    """Geo-latency planet run: 3 regions, seeded per-link WAN delays,
    region-tagged spans (`sim scenario --config geo.toml`)."""
    return _scenario_base(
        nodes,
        ScenarioParams(
            name="geo", planet="planet-3region-fast", jitter_ms=1.0,
            geo_seed=7,
        ),
    )


def scenario_churn(nodes: int = 32) -> SimConfig:
    """Dynamic-membership run: ~10% of the committee departs mid-round on
    a deterministic staggered schedule; survivors re-level and the
    threshold stays reachable."""
    return _scenario_base(
        nodes,
        ScenarioParams(name="churn", joins=2, geo_seed=7),
        churner=max(1, nodes // 10),
    )


def scenario_weighted(nodes: int = 32) -> SimConfig:
    """Stake-weighted run: heavy-tailed pareto weights, completion gated
    on 60% of total stake instead of a contribution count."""
    return _scenario_base(
        nodes,
        ScenarioParams(
            name="weighted", weight_profile="pareto",
            weight_threshold_frac=0.6, weight_seed=7,
        ),
    )


def scenario_geo_weighted(nodes: int = 128) -> SimConfig:
    """The capture shape: 5-region planet + >=10% churn + non-uniform
    stake, all axes at once (results/geo_weighted_report.json)."""
    return _scenario_base(
        nodes,
        ScenarioParams(
            name="geo_weighted", planet="planet-5region", jitter_ms=3.0,
            geo_seed=7, joins=4, weight_profile="pareto",
            weight_threshold_frac=0.55, weight_seed=7,
        ),
        churner=max(1, nodes // 10),
        churn_after_ms=400.0,
    )


SCENARIOS = {
    "node_count": scenario_node_count,
    "threshold_inc": scenario_threshold_inc,
    "evaluator": scenario_evaluator,
    "failing": scenario_failing,
    "period": scenario_period,
    "timeout": scenario_timeout,
    "update_count": scenario_update_count,
    "nsquare": scenario_nsquare,
    "gossipsub": scenario_gossipsub,
    "practical": scenario_practical,
    "swarm": scenario_swarm,
    "geo": scenario_geo,
    "churn": scenario_churn,
    "weighted": scenario_weighted,
    "geo_weighted": scenario_geo_weighted,
}


def generate(outdir: str, names=None) -> list[str]:
    """Write every (or the named) scenario TOMLs; returns the paths."""
    os.makedirs(outdir, exist_ok=True)
    paths = []
    for name in names or SCENARIOS:
        cfg = SCENARIOS[name]()
        path = os.path.join(outdir, f"{name}.toml")
        with open(path, "w") as f:
            f.write(dump_config(cfg))
        paths.append(path)
    return paths


if __name__ == "__main__":
    import sys

    outdir = sys.argv[1] if len(sys.argv) > 1 else "configs"
    for p in generate(outdir):
        print(p)

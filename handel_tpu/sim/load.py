"""`python -m handel_tpu.sim load` — open-loop production traffic.

Everything before this measured closed-loop: batches arrived when the
harness felt like it (cluster.run spawns, the soak back-fills on
completion). A production verify plane faces OPEN-LOOP arrivals — a
seeded Poisson/diurnal/burst clock fires sessions at the federation
(service/federation.py) whether or not it keeps up — so the first-class
metrics change shape: arrival→verdict p50/p99 (routing + backoff +
queueing + aggregation, not just service time), goodput against a
per-session deadline, and the spill/shed/retry attribution of every
arrival that didn't complete where it was born.

Arrival models (all exact under a fixed seed, via Lewis-Shedler thinning
against the model's peak rate):

- **poisson** — homogeneous at `rate_sps`.
- **diurnal** — rate * (1 + amplitude * sin(2πt/period)): a compressed
  day, peak and trough traffic in one run.
- **burst**  — rate * burst_x inside each `burst_len_s` window every
  `burst_every_s`: flash-crowd spikes over a steady floor.

The chaos drill rides mid-run when `[federation] kill_region` is set:
the named region's cluster stops cold at `kill_at_frac` (its live
sessions re-enter the front door and spill), recovery at
`recover_at_frac` rebuilds it and rejoins via a federation-wide epoch
rotation, and the report's `kill` block carries the full timeline —
killed_at → unhealthy_detected (probe/passive) → recover_started →
readmitted → first post-recovery completion (`region_recovery_s`).

The report (`<workdir>/federation_report.json`) extends the soak_report
schema: bench-record shaped, SIDE_METRICS flat on the record
(`open_loop_p99_s`, `region_recovery_s`, `spillover_rate`), `checks`
stamped by the shared specs in sim/report_checks.py.
"""

from __future__ import annotations

import asyncio
import json
import math
import os
import random
import time

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.test_harness import FakeScheme
from handel_tpu.core.trace import FlightRecorder
from handel_tpu.obs import AlertPlane, BurnRule, EwmaDetector, MadDetector
from handel_tpu.service.fairness import DEFAULT_TIER, TIERS
from handel_tpu.service.federation import Federation
from handel_tpu.service.session import STATE_DONE
from handel_tpu.sim.report_checks import FEDERATION_CHECKS, attach


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


# -- arrival models -----------------------------------------------------------


def rate_at(p, t: float) -> float:
    """Instantaneous arrival rate (sessions/s) of model `p` at offset t."""
    if p.model == "diurnal":
        return p.rate_sps * (
            1.0
            + p.diurnal_amplitude
            * math.sin(2.0 * math.pi * t / p.diurnal_period_s)
        )
    if p.model == "burst":
        in_burst = (t % p.burst_every_s) < p.burst_len_s
        return p.rate_sps * (p.burst_x if in_burst else 1.0)
    return p.rate_sps  # poisson


def peak_rate(p) -> float:
    if p.model == "diurnal":
        return p.rate_sps * (1.0 + p.diurnal_amplitude)
    if p.model == "burst":
        return p.rate_sps * max(1.0, p.burst_x)
    return p.rate_sps


def arrival_offsets(p) -> list[float]:
    """Seeded arrival clock: offsets (s) into the load window.

    Lewis-Shedler thinning — candidate arrivals at the peak rate, each
    accepted with probability rate(t)/peak — keeps the nonhomogeneous
    models exact, and one `random.Random(seed)` stream keeps the whole
    trace reproducible run over run."""
    rng = random.Random(p.seed * 1_000_003 + 17)
    peak = peak_rate(p)
    out: list[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= p.duration_s:
            return out
        if rng.random() * peak <= rate_at(p, t):
            out.append(t)


# -- per-arrival record -------------------------------------------------------


class SessionRecord:
    """One open-loop arrival, from its clock tick to its attributed end.

    outcome: None while in flight, then exactly one of "completed",
    "shed" (every region at its shed bound through the retry budget),
    "failed" (every region dead through the budget), or "expired"
    (admitted but hit the region's session TTL). The report's
    zero-dropped check is precisely `sum(outcomes) == arrivals`."""

    __slots__ = ("idx", "origin", "tier", "t_arrival", "t_done", "outcome",
                 "region", "attempts", "spilled", "rerouted")

    def __init__(self, idx: int, origin: str, tier: str | None,
                 t_arrival: float):
        self.idx = idx
        self.origin = origin
        self.tier = tier
        self.t_arrival = t_arrival
        self.t_done: float | None = None
        self.outcome: str | None = None
        self.region: str | None = None
        self.attempts = 0
        self.spilled = False
        self.rerouted = 0  # times a region kill handed it back

    def latency_s(self) -> float | None:
        if self.t_done is None:
            return None
        return self.t_done - self.t_arrival


class LoadRun:
    """One open-loop run: build the federation, replay the arrival trace,
    drive the chaos timeline, emit the report. Split from the CLI so
    tests and the bench can run short traces in-process."""

    def __init__(self, load_p, fed_p, alert_p=None,
                 logger: Logger = DEFAULT_LOGGER):
        self.lp = load_p
        self.fp = fed_p
        self.ap = alert_p
        self.log = logger
        self.rec = FlightRecorder(capacity=fed_p.trace_capacity)
        self.scheme = FakeScheme()
        self.fed = Federation(
            fed_p, scheme=self.scheme, recorder=self.rec, logger=logger
        )
        if fed_p.kill_region and fed_p.kill_region not in self.fed.by_name:
            raise ValueError(
                f"federation.kill_region {fed_p.kill_region!r} not in "
                f"planet {fed_p.planet!r} "
                f"(regions: {', '.join(self.fed.region_names())})"
            )
        self.records: list[SessionRecord] = []
        self._live: dict[tuple[str, str], SessionRecord] = {}
        self._tiers = [
            t.strip() for t in load_p.tiers.split(",") if t.strip()
        ]
        # origin sampling gets its own stream so adding a region never
        # perturbs the arrival clock for a given seed
        self._origin_rng = random.Random(load_p.seed * 1_000_003 + 29)
        self._tasks: set[asyncio.Task] = set()
        self.interrupted_ct = 0
        # chaos timeline (monotonic timestamps)
        self.kill_t: float | None = None
        self.recover_start_t: float | None = None
        self.recovery_first_completion_t: float | None = None
        self.rotation_stall_s = 0.0
        self.t0 = 0.0
        # detection-and-incident plane (handel_tpu/obs/): burn rules over
        # the tier/goodput/shed planes + the region-health detector the
        # chaos drill validates
        self.alerts: AlertPlane | None = (
            self._build_alert_plane() if alert_p is not None
            and alert_p.enabled else None
        )
        # hierarchical roll-ups (obs/rollup.py): one HostRollup per
        # region "host", one FleetRollup on this master — the fleet's
        # hosts-up series joins the SAME alert plane, so a region kill
        # correlates into the one region-health incident with the lost
        # host named in the attribution
        self.host_rollups, self.fleet = self._build_rollups()
        self._last_rollup_emit = 0.0
        if self.alerts is not None:
            self.fleet.attach_alerts(
                self.alerts,
                z_threshold=alert_p.z_threshold,
                ewma_alpha=alert_p.ewma_alpha,
                min_consecutive=alert_p.min_consecutive,
            )

    def _build_rollups(self):
        from handel_tpu.obs.rollup import FleetRollup, HostRollup

        top_k = self.ap.rollup_top_k if self.ap is not None else 8
        stale = self.ap.rollup_stale_s if self.ap is not None else 5.0
        hosts: dict[str, HostRollup] = {}
        for name, region in self.fed.by_name.items():
            hr = HostRollup(name, top_k=top_k)

            def region_fold(region=region):
                return [(region.stats(), self.fed.labeled_gauge_keys())]

            hr.attach_fold("region", region_fold)

            def session_fold(region=region):
                m = region.cluster.manager
                return ((vals, m.labeled_gauge_keys())
                        for vals in m.labeled_values().values())

            hr.attach_fold("sessions", session_fold)

            def device_fold(region=region):
                plane = region.cluster.service.plane
                return ((vals, plane.labeled_gauge_keys())
                        for vals in plane.labeled_values().values())

            hr.attach_fold("device", device_fold)
            hr.watch(
                f"{name}-queue-depth",
                lambda region=region: float(
                    region.cluster.service.queue_depth()
                ),
            )
            hosts[name] = hr
        return hosts, FleetRollup(top_k=top_k, stale_after_s=stale)

    def _rollup_emit(self, now: float) -> None:
        """Per-region digest deltas -> chunked wire form -> the fleet.
        A killed region stops emitting (its process would be gone), so
        the fleet marks it lost and the hosts-up series pages."""
        self._last_rollup_emit = now
        for name, hr in self.host_rollups.items():
            if self.fed.by_name[name].killed:
                self.fleet.mark_lost(name)
                continue
            hr.emit(self.fleet.ingest)

    # -- the alert plane ----------------------------------------------------

    def _tier_counts(self, tier: str) -> tuple[float, float]:
        """Cumulative (good, bad) for one tier's burn rule: a resolved
        arrival is good iff it completed inside the tier's p99 target —
        sheds/failures/expiries burn the tier's budget too (an arrival the
        service turned away is an SLO miss the user saw)."""
        target = TIERS.get(tier, DEFAULT_TIER).p99_target_s
        good = bad = 0
        for r in self.records:
            if r.outcome is None or (r.tier or "standard") != tier:
                continue
            if r.outcome == "completed" and r.latency_s() <= target:
                good += 1
            else:
                bad += 1
        return float(good), float(bad)

    def _goodput_counts(self) -> tuple[float, float]:
        good = bad = 0
        for r in self.records:
            if r.outcome is None:
                continue
            if (
                r.outcome == "completed"
                and r.latency_s() <= self.lp.deadline_s
            ):
                good += 1
            else:
                bad += 1
        return float(good), float(bad)

    def _shed_counts(self) -> tuple[float, float]:
        shed = sum(1 for r in self.records if r.outcome == "shed")
        other = sum(
            1 for r in self.records
            if r.outcome is not None and r.outcome != "shed"
        )
        return float(other), float(shed)

    def _unhealthy_regions(self) -> list[str]:
        return [
            name for name, vals in self.fed.labeled_values().items()
            if vals.get("regionHealthy", 1.0) < 1.0
        ]

    def _build_alert_plane(self) -> AlertPlane:
        ap = self.ap
        plane = AlertPlane.from_params(
            ap, recorder=self.rec,
            trace_source=lambda: self.rec.export()["traceEvents"],
        )
        ev = plane.evaluator
        for tier in dict.fromkeys(self._tiers or ["standard"]):
            ev.add_rule(
                BurnRule(f"tier-{tier}-p99", budget=0.01,
                         page_x=ap.page_x, warn_x=ap.warn_x,
                         description=f"99% of {tier} arrivals inside "
                                     "the tier p99 target"),
                lambda t=tier: self._tier_counts(t),
            )
        ev.add_rule(
            BurnRule("goodput", budget=1.0 - ap.goodput_slo,
                     page_x=ap.page_x, warn_x=ap.warn_x,
                     description="deadline-met fraction of all arrivals"),
            self._goodput_counts,
        )
        ev.add_rule(
            BurnRule("shed", budget=self.fp.shed_ceiling,
                     page_x=ap.page_x, warn_x=ap.warn_x,
                     description="attributed sheds under the federation "
                                 "shed ceiling"),
            self._shed_counts,
        )
        # the drill signal: a region dropping out of the healthy count is
        # a step the EWMA catches in one tick; hold_while keeps the
        # detection (and its incident) open until the region is back
        plane.detectors.attach(
            "region-health",
            lambda: self.fed.values()["regionsHealthy"],
            EwmaDetector(alpha=ap.ewma_alpha, z_threshold=ap.z_threshold),
            min_consecutive=ap.min_consecutive,
            opens_incident=True,
            direction="down",
            hold_while=lambda: bool(self._unhealthy_regions()),
        )
        # context series: anomalous values land in attribution snapshots
        # but never open incidents on their own
        plane.detectors.attach(
            "open-loop-p99",
            lambda: self.values()["openLoopP99S"] or None,
            MadDetector(z_threshold=ap.z_threshold, seed=ap.seed),
            min_consecutive=max(2, ap.min_consecutive),
            direction="up",
        )
        plane.detectors.attach(
            "frontdoor-markdowns",
            lambda: self.fed.values()["markdownCt"],
            EwmaDetector(alpha=ap.ewma_alpha, z_threshold=ap.z_threshold),
            min_consecutive=ap.min_consecutive,
            direction="up",
        )
        plane.add_context("unhealthy_regions", self._unhealthy_regions)
        plane.add_context(
            "front_door",
            lambda: {
                "markdowns": self.fed.front_door.markdowns,
                "retries": self.fed.front_door.retries,
                "spillovers": self.fed.front_door.spillovers,
            },
        )
        # region incident -> front-door mark-down: the incident plane is
        # a health signal beside the probes (FrontDoor.mark dedups, so a
        # probe-detected death just makes this a no-op)
        def on_incident(event: str, inc) -> None:
            if event != "open":
                return
            for name in inc.attribution.get("unhealthy_regions", []):
                self.fed.front_door.mark(name, False)

        plane.incidents.add_listener(on_incident)
        return plane

    async def _alert_loop(self) -> None:
        while True:
            await asyncio.sleep(self.ap.tick_interval_s)
            now = time.monotonic()
            for hr in self.host_rollups.values():
                hr.tick(now)
            if now - self._last_rollup_emit >= self.ap.rollup_interval_s:
                self._rollup_emit(now)
            self.alerts.tick()

    # -- arrival path -------------------------------------------------------

    def _spawn(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _on_done(self, rec: SessionRecord):
        def cb(sess) -> None:
            now = time.monotonic()
            rec.t_done = now
            rec.outcome = (
                "completed" if sess.state == STATE_DONE else "expired"
            )
            self._live.pop((rec.region, sess.sid), None)
            if (
                rec.outcome == "completed"
                and rec.region == self.fp.kill_region
                and self.recover_start_t is not None
                and self.recovery_first_completion_t is None
            ):
                # the recovery check's evidence: the rejoined region is
                # not just marked healthy, it is COMPLETING work again
                self.recovery_first_completion_t = now
        return cb

    async def _arrive(self, rec: SessionRecord) -> None:
        outcome, sess, plane, attempts = await self.fed.submit(
            rec.origin, nodes=self.lp.nodes, tier=rec.tier,
            seed=rec.idx, on_done=self._on_done(rec),
        )
        rec.attempts += attempts
        if outcome == "admitted":
            rec.region = plane.name
            if plane.name != self.fed.front_door._order[rec.origin][0]:
                rec.spilled = True
            self._live[(plane.name, sess.sid)] = rec
        else:  # "shed" | "failed" — attributed, never silent
            rec.outcome = outcome
            rec.t_done = time.monotonic()

    # -- chaos timeline -----------------------------------------------------

    def _kill_and_reroute(self) -> None:
        region = self.fp.kill_region
        self.kill_t = time.monotonic()
        live_sids = self.fed.kill_region(region)
        # sessions the kill interrupted mid-flight re-enter the front
        # door: their arrival clock does NOT reset, so their open-loop
        # latency carries the disruption they lived through
        for sid in live_sids:
            rec = self._live.pop((region, sid), None)
            if rec is None:
                continue
            rec.region = None
            rec.rerouted += 1
            self.interrupted_ct += 1
            self._spawn(self._arrive(rec))
        self.log.info(
            "load",
            f"killed {region}: {len(live_sids)} live sessions re-routed",
        )

    async def _chaos(self, duration_s: float) -> None:
        fp = self.fp
        await asyncio.sleep(fp.kill_at_frac * duration_s)
        self._kill_and_reroute()
        await asyncio.sleep(
            (fp.recover_at_frac - fp.kill_at_frac) * duration_s
        )
        self.recover_start_t = time.monotonic()
        self.rotation_stall_s = await self.fed.recover_region(
            fp.kill_region
        )
        self.log.info(
            "load",
            f"recovered {fp.kill_region} "
            f"(epoch {self.fed.epoch}, worst stall "
            f"{self.rotation_stall_s * 1e3:.1f}ms)",
        )

    # -- the run ------------------------------------------------------------

    async def run(self) -> dict:
        lp, fp = self.lp, self.fp
        offsets = arrival_offsets(lp)
        regions = self.fed.region_names()
        self.t0 = t0 = time.monotonic()
        self.fed.start()
        chaos = (
            asyncio.ensure_future(self._chaos(lp.duration_s))
            if fp.kill_region
            else None
        )
        alert_task = (
            asyncio.ensure_future(self._alert_loop())
            if self.alerts is not None
            else None
        )
        try:
            for i, off in enumerate(offsets):
                ahead = off - (time.monotonic() - t0)
                if ahead > 0:
                    await asyncio.sleep(ahead)
                tier = (
                    self._tiers[i % len(self._tiers)]
                    if self._tiers
                    else None
                )
                rec = SessionRecord(
                    i, self._origin_rng.choice(regions), tier,
                    time.monotonic(),
                )
                self.records.append(rec)
                self._spawn(self._arrive(rec))
            if chaos is not None:
                await chaos
            await self._drain()
            await self._await_incident_close()
        finally:
            if chaos is not None:
                chaos.cancel()
            if alert_task is not None:
                alert_task.cancel()
            await self.fed.stop()
        wall = time.monotonic() - t0
        return self._report(wall)

    async def _await_incident_close(self) -> None:
        """After drain, give an open incident its min-hold of quiet so a
        recovered drill run reports closed incidents, not a snapshot taken
        mid-hold (bounded — a genuinely stuck condition still reports)."""
        if self.alerts is None or self.alerts.incidents.current is None:
            return
        deadline = (
            time.monotonic() + self.ap.min_hold_s
            + 20.0 * self.ap.tick_interval_s
        )
        while (
            self.alerts.incidents.current is not None
            and time.monotonic() < deadline
        ):
            await asyncio.sleep(self.ap.tick_interval_s)

    async def _drain(self) -> None:
        """Let in-flight routing finish and every admitted session reach
        its verdict (TTL bounds the tail)."""
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
        deadline = time.monotonic() + self.fp.session_ttl_s + 30.0
        while self._live and time.monotonic() < deadline:
            await asyncio.sleep(0.05)

    # -- live telemetry (register_values plane "load") ----------------------

    def values(self) -> dict[str, float]:
        done = sorted(
            r.latency_s() for r in self.records if r.outcome == "completed"
        )
        arrivals = len(self.records)
        met = sum(
            1 for r in self.records
            if r.outcome == "completed"
            and r.latency_s() <= self.lp.deadline_s
        )
        return {
            "arrivals": float(arrivals),
            "arrivalSps": float(self.lp.rate_sps),
            "completed": float(len(done)),
            "shed": float(
                sum(1 for r in self.records if r.outcome == "shed")
            ),
            "failed": float(
                sum(1 for r in self.records if r.outcome == "failed")
            ),
            "openLoopP50S": _quantile(done, 0.50),
            "openLoopP99S": _quantile(done, 0.99),
            "goodput": met / arrivals if arrivals else 0.0,
        }

    def gauge_keys(self) -> set[str]:
        return {"arrivalSps", "openLoopP50S", "openLoopP99S", "goodput"}

    # -- the report ---------------------------------------------------------

    def _tier_quantiles(self) -> dict[str, dict[str, float]]:
        """Per-SLO-tier OPEN-LOOP latency (arrival→verdict — strictly
        harsher than the manager's start→verdict buckets) against the
        tier's p99 target."""
        buckets: dict[str, list[float]] = {}
        for r in self.records:
            if r.outcome == "completed":
                buckets.setdefault(r.tier or "standard", []).append(
                    r.latency_s()
                )
        out: dict[str, dict[str, float]] = {}
        for tier, vals in buckets.items():
            done = sorted(vals)
            target = TIERS.get(tier, DEFAULT_TIER).p99_target_s
            p99 = _quantile(done, 0.99)
            out[tier] = {
                "completed": float(len(done)),
                "p50_s": _quantile(done, 0.50),
                "p99_s": p99,
                "target_s": target,
                "met": 1.0 if p99 <= target else 0.0,
            }
        return out

    def _kill_block(self) -> dict | None:
        if not self.fp.kill_region:
            return None
        fd = self.fed.front_door
        region = self.fp.kill_region

        def rel(t: float | None) -> float | None:
            return round(t - self.t0, 3) if t is not None else None

        recovery_s = None
        if (
            self.recover_start_t is not None
            and self.recovery_first_completion_t is not None
        ):
            recovery_s = round(
                self.recovery_first_completion_t - self.recover_start_t, 3
            )
        post = sum(
            1 for r in self.records
            if r.outcome == "completed" and r.region == region
            and self.recover_start_t is not None
            and r.t_done >= self.recover_start_t
        )
        return {
            "region": region,
            "killed_at_s": rel(self.kill_t),
            "unhealthy_detected_s": rel(fd.unhealthy_at.get(region)),
            "recover_started_s": rel(self.recover_start_t),
            "readmitted_s": rel(fd.rehealthy_at.get(region)),
            "recovery_s": recovery_s,
            "post_recovery_completed": post,
            "interrupted_rerouted": self.interrupted_ct,
            "rotation_stall_ms": round(self.rotation_stall_s * 1e3, 3),
        }

    def _alert_block(self) -> tuple[dict | None, float, float]:
        """(nested alerts block, detection_latency_ms,
        false_positive_rate). Detection latency is first-incident-open
        minus region-kill time; an open with no kill in flight (or before
        it) is a false positive — clean control runs must report 0.0 by
        opening nothing at all."""
        if self.alerts is None:
            return None, 0.0, 0.0
        log = self.alerts.incidents
        expected = 0
        latency_ms = 0.0
        for inc in log.incidents:
            if self.kill_t is not None and inc.opened_at >= self.kill_t:
                expected += 1
                if expected == 1:
                    latency_ms = round(
                        (inc.opened_at - self.kill_t) * 1e3, 3
                    )
        total = len(log.incidents)
        fp_rate = (total - expected) / total if total else 0.0
        ev = self.alerts.evaluator
        block = {
            "rules": {
                name: {
                    "state": state,
                    "burn_fast": round(ev.burns(name)[0], 4),
                    "burn_slow": round(ev.burns(name)[1], 4),
                }
                for name, state in ev.states().items()
            },
            "report": log.to_report(self.t0),
        }
        return block, latency_ms, round(fp_rate, 4)

    def _fleet_block(self, wall_s: float) -> dict:
        """The hierarchical roll-up summary: each region is one host, the
        master's FleetRollup merged their digests over the run. Series
        count is O(key-union across hosts) — the flatness of
        `series_total` across load sweeps is the O(hosts) contract."""
        series = self.fleet.series_count()  # merges -> fresh lastMergeMs
        fv = self.fleet.values()
        bytes_total = fv["ingestBytesCt"]
        hosts = max(1, len(self.host_rollups))
        return {
            "hosts": sorted(self.host_rollups),
            "hosts_up": self.fleet.hosts_up(),
            "lost_hosts": self.fleet.lost_hosts(),
            "series_total": series,
            "ingests": fv["ingestsCt"],
            "ingest_bytes": bytes_total,
            "rollup_bytes_per_host_s": round(
                bytes_total / hosts / max(wall_s, 1e-9), 1
            ),
            "fleet_eval_ms": fv["lastMergeMs"],
        }

    def _report(self, wall_s: float) -> dict:
        lp, fp = self.lp, self.fp
        fd = self.fed.front_door
        arrivals = len(self.records)
        by_outcome = {"completed": 0, "shed": 0, "failed": 0, "expired": 0}
        unresolved = 0
        for r in self.records:
            if r.outcome is None:
                unresolved += 1
            else:
                by_outcome[r.outcome] += 1
        accounted = sum(by_outcome.values()) + unresolved
        done = sorted(
            r.latency_s() for r in self.records if r.outcome == "completed"
        )
        met = sum(
            1 for r in self.records
            if r.outcome == "completed"
            and r.latency_s() <= lp.deadline_s
        )
        tiers = self._tier_quantiles()
        # the headline is the GOLD tier's open-loop p99 — the strictest
        # promise — falling back to the all-tier p99 on untiered runs
        p99 = (
            tiers["gold"]["p99_s"] if "gold" in tiers
            else _quantile(done, 0.99)
        )
        kill = self._kill_block()
        alerts, detect_ms, fp_rate = self._alert_block()
        report = {
            # bench-record shape (scripts/bench_check.py): headline +
            # SIDE_METRICS keys flat on the record, detail nested
            "metric": "open_loop_p99_s",
            "value": p99,
            "backend": "cpu",
            "captured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "open_loop_p99_s": p99,
            "open_loop_p50_s": _quantile(done, 0.50),
            # session-level shed rate: attributed shed arrivals over all
            # arrivals (the candidate-level rate is per-region in stats)
            "shed_rate": round(
                by_outcome["shed"] / arrivals, 4
            ) if arrivals else 0.0,
            "region_recovery_s": (kill or {}).get("recovery_s") or 0.0,
            "spillover_rate": round(
                fd.spillovers / arrivals, 4
            ) if arrivals else 0.0,
            "goodput": round(met / arrivals, 4) if arrivals else 0.0,
            "detection_latency_ms": detect_ms,
            "false_positive_rate": fp_rate,
            "alerts": alerts,
            "federation": {
                "planet": fp.planet,
                "model": lp.model,
                "rate_sps": lp.rate_sps,
                "duration_s": lp.duration_s,
                "wall_s": round(wall_s, 3),
                "deadline_s": lp.deadline_s,
                "arrivals": arrivals,
                "completed": by_outcome["completed"],
                "shed": by_outcome["shed"],
                "failed": by_outcome["failed"],
                "expired": by_outcome["expired"],
                "unresolved": unresolved,
                "unaccounted": arrivals - accounted,
                "deadline_met": met,
                "spillovers": fd.spillovers,
                "front_door_retries": fd.retries,
                "probe_rounds": fd.probe_rounds,
                "shed_ceiling": fp.shed_ceiling,
                "tiers": tiers,
                "kill": kill,
                "epoch": self.fed.epoch,
                "regions": {
                    name: vals
                    for name, vals in self.fed.labeled_values().items()
                },
            },
            "fleet": self._fleet_block(wall_s),
        }
        # shared invariant specs (sim/report_checks.py): the same
        # predicates load_smoke re-asserts stamp `checks` + `ok`
        return attach(report, FEDERATION_CHECKS)


async def run_load(load_p, fed_p, workdir: str,
                   logger: Logger = DEFAULT_LOGGER,
                   metrics_port: int | None = None,
                   alert_p=None) -> dict:
    """Run one open-loop trace and persist
    `<workdir>/federation_report.json` (+ the region-tagged trace dump
    beside it for `sim trace --critical-path`, and
    `incident_report.json` when the alert plane is on)."""
    os.makedirs(workdir, exist_ok=True)
    run = LoadRun(load_p, fed_p, alert_p=alert_p, logger=logger)
    server = None
    if metrics_port is not None:
        from handel_tpu.core.metrics import MetricsRegistry, MetricsServer

        reg = MetricsRegistry(
            series_cap=alert_p.series_cap if alert_p is not None else 0,
        )
        reg.register_values("federation", run.fed)
        reg.register_labeled_values(
            "federation", run.fed, label="region",
            gauges=run.fed.labeled_gauge_keys(),
        )
        reg.register_values("load", run)
        # handel_fleet_* families + the /fleet JSON endpoint, fed by the
        # per-region HostRollup digests the alert loop emits
        run.fleet.register_metrics(reg)
        if run.alerts is not None:
            run.alerts.register_metrics(reg)
        reg.add_readiness("federation_up", lambda: True)
        server = MetricsServer(reg, port=metrics_port).start()
    try:
        report = await run.run()
    finally:
        if server is not None:
            server.stop()
    path = os.path.join(workdir, "federation_report.json")
    with open(path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    if report.get("alerts") is not None:
        incident_path = os.path.join(workdir, "incident_report.json")
        with open(incident_path, "w") as f:
            json.dump(
                {
                    "detection_latency_ms": report["detection_latency_ms"],
                    "false_positive_rate": report["false_positive_rate"],
                    "kill": report["federation"]["kill"],
                    **report["alerts"],
                },
                f, indent=1,
            )
            f.write("\n")
    # trace_* naming so `sim trace <workdir> --critical-path` resolves it
    run.rec.dump(os.path.join(workdir, "trace_federation.json"))
    fed = report["federation"]
    logger.info(
        "load",
        f"{'OK' if report['ok'] else 'FAILED'} "
        f"{fed['completed']}/{fed['arrivals']} arrivals completed "
        f"p99={report['open_loop_p99_s']:.3f}s "
        f"goodput={report['goodput']:.4f} "
        f"spill={report['spillover_rate']:.4f} "
        f"shed={report['shed_rate']:.4f} -> {path}",
    )
    return report

"""UDP distributed barrier for simulation runs.

Reference: simul/lib/sync.go:27-378 — slaves spam READY(state) datagrams with
their ids every 500 ms; the master counts distinct ids per state and releases
the barrier once it has seen 99.5% of the expected count (probabilistic
early release, sync.go:92-98,170, masking straggler datagram loss), then
acks every subsequent READY so late slaves unblock too. States: START, END.
"""

from __future__ import annotations

import asyncio
import json

STATE_START = 1
STATE_END = 2

RESEND_PERIOD = 0.5  # slave READY period (sync.go)
RELEASE_FRACTION = 0.995  # probabilistic early release (sync.go:92-98)


class _MasterProto(asyncio.DatagramProtocol):
    def __init__(self, master: "SyncMaster"):
        self.master = master

    def connection_made(self, transport):
        self.master._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = json.loads(data.decode())
        except ValueError:
            return
        self.master._on_ready(int(msg["state"]), int(msg["id"]), addr)


class SyncMaster:
    """Barrier master expecting `expected` distinct ids per state
    (sync.go:163-260)."""

    def __init__(self, listen_port: int, expected: int):
        self.port = listen_port
        self.expected = expected
        self._transport = None
        self._seen: dict[int, set[int]] = {}
        self._released: dict[int, asyncio.Event] = {}
        self._addrs: dict[int, set] = {}

    def _event(self, state: int) -> asyncio.Event:
        if state not in self._released:
            self._released[state] = asyncio.Event()
        return self._released[state]

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.create_datagram_endpoint(
            lambda: _MasterProto(self), local_addr=("0.0.0.0", self.port)
        )

    def stop(self) -> None:
        if self._transport:
            self._transport.close()

    def _on_ready(self, state: int, node_id: int, addr) -> None:
        self._seen.setdefault(state, set()).add(node_id)
        self._addrs.setdefault(state, set()).add(addr)
        need = max(1, int(self.expected * RELEASE_FRACTION))
        if len(self._seen[state]) >= need:
            self._event(state).set()
        if self._event(state).is_set():
            # ack so the sender stops resending (and stragglers unblock)
            self._transport.sendto(
                json.dumps({"state": state, "ack": True}).encode(), addr
            )

    async def wait_all(self, state: int, timeout: float | None = None) -> None:
        await asyncio.wait_for(self._event(state).wait(), timeout)
        # ack everyone who already reported
        for addr in self._addrs.get(state, ()):
            self._transport.sendto(
                json.dumps({"state": state, "ack": True}).encode(), addr
            )


class _SlaveProto(asyncio.DatagramProtocol):
    def __init__(self, slave: "SyncSlave"):
        self.slave = slave

    def connection_made(self, transport):
        self.slave._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = json.loads(data.decode())
        except ValueError:
            return
        if msg.get("ack"):
            ev = self.slave._acked.get(int(msg["state"]))
            if ev:
                ev.set()


class SyncSlave:
    """Barrier participant (sync.go:263-344): signal readiness for a state
    and wait for the master's release ack."""

    def __init__(self, master_addr: str, node_id: int):
        host, _, port = master_addr.rpartition(":")
        self.master = (host or "127.0.0.1", int(port))
        self.node_id = node_id
        self._transport = None
        self._acked: dict[int, asyncio.Event] = {}

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.create_datagram_endpoint(
            lambda: _SlaveProto(self), remote_addr=self.master
        )

    def stop(self) -> None:
        if self._transport:
            self._transport.close()

    async def signal_and_wait(self, state: int, timeout: float | None = None) -> None:
        ev = self._acked.setdefault(state, asyncio.Event())
        payload = json.dumps({"state": state, "id": self.node_id}).encode()

        async def spam():
            while not ev.is_set():
                self._transport.sendto(payload)
                await asyncio.sleep(RESEND_PERIOD)

        task = asyncio.get_running_loop().create_task(spam())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        finally:
            task.cancel()

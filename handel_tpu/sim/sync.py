"""UDP distributed barrier for simulation runs.

Reference: simul/lib/sync.go:27-378 — slaves spam READY(state) datagrams with
their ids every 500 ms; the master counts distinct ids per state and releases
the barrier once it has seen 99.5% of the expected count (probabilistic
early release, sync.go:92-98,170, masking straggler datagram loss), then
acks every subsequent READY so late slaves unblock too. States: START, END.

Clock-offset piggyback (ISSUE 10): each READY carries the slave's send stamp
`ts`; a direct ack echoes it plus the master's receive-side stamp `mts`. The
slave then has a one-shot NTP-style sample — offset = mts - (ts + rtt/2),
bounded by ±rtt/2 — and keeps the estimate from the smallest-RTT exchange.
`sim/node.py` copies the START-barrier estimate onto the flight recorder so
`merge_traces` (core/trace.py) aligns multi-host timelines at export time.
Bulk release acks (wait_all) carry no `ts` and never update the estimate.
"""

from __future__ import annotations

import asyncio
import json
import math
import time

STATE_START = 1
STATE_END = 2

RESEND_PERIOD = 0.5  # slave READY period (sync.go)
RELEASE_FRACTION = 0.995  # probabilistic early release (sync.go:92-98)


class _MasterProto(asyncio.DatagramProtocol):
    def __init__(self, master: "SyncMaster"):
        self.master = master

    def connection_made(self, transport):
        self.master._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = json.loads(data.decode())
        except ValueError:
            return
        ts = msg.get("ts")
        self.master._on_ready(
            int(msg["state"]),
            int(msg["id"]),
            addr,
            ts=float(ts) if ts is not None else None,
        )


class SyncMaster:
    """Barrier master expecting `expected` distinct ids per state
    (sync.go:163-260)."""

    def __init__(self, listen_port: int, expected: int):
        self.port = listen_port
        self.expected = expected
        self._transport = None
        self._seen: dict[int, set[int]] = {}
        self._released: dict[int, asyncio.Event] = {}
        self._addrs: dict[int, set] = {}

    def _event(self, state: int) -> asyncio.Event:
        if state not in self._released:
            self._released[state] = asyncio.Event()
        return self._released[state]

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.create_datagram_endpoint(
            lambda: _MasterProto(self), local_addr=("0.0.0.0", self.port)
        )

    def stop(self) -> None:
        if self._transport:
            self._transport.close()

    def _on_ready(
        self, state: int, node_id: int, addr, ts: float | None = None
    ) -> None:
        self._seen.setdefault(state, set()).add(node_id)
        self._addrs.setdefault(state, set()).add(addr)
        # ceil, not floor: for small fleets int() would release the barrier
        # one participant early (expected=2 -> int(1.99) = 1), letting a
        # block start gossiping before its sibling can even receive
        need = max(1, math.ceil(self.expected * RELEASE_FRACTION))
        if len(self._seen[state]) >= need:
            self._event(state).set()
        if self._event(state).is_set():
            # ack so the sender stops resending (and stragglers unblock);
            # echoing the slave's stamp + our own makes the exchange a
            # clock-offset sample on the slave side (module docstring)
            ack: dict = {"state": state, "ack": True}
            if ts is not None:
                ack["ts"] = ts
                ack["mts"] = time.time()
            self._transport.sendto(json.dumps(ack).encode(), addr)

    async def wait_all(self, state: int, timeout: float | None = None) -> None:
        await asyncio.wait_for(self._event(state).wait(), timeout)
        # ack everyone who already reported
        for addr in self._addrs.get(state, ()):
            self._transport.sendto(
                json.dumps({"state": state, "ack": True}).encode(), addr
            )


class _SlaveProto(asyncio.DatagramProtocol):
    def __init__(self, slave: "SyncSlave"):
        self.slave = slave

    def connection_made(self, transport):
        self.slave._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = json.loads(data.decode())
        except ValueError:
            return
        if msg.get("ack"):
            ts = msg.get("ts")
            if ts is not None and "mts" in msg:
                self.slave._offset_sample(float(ts), float(msg["mts"]))
            ev = self.slave._acked.get(int(msg["state"]))
            if ev:
                ev.set()


class SyncSlave:
    """Barrier participant (sync.go:263-344): signal readiness for a state
    and wait for the master's release ack."""

    def __init__(self, master_addr: str, node_id: int):
        host, _, port = master_addr.rpartition(":")
        self.master = (host or "127.0.0.1", int(port))
        self.node_id = node_id
        self._transport = None
        self._acked: dict[int, asyncio.Event] = {}
        # NTP-style clock estimate vs the master (module docstring): seconds
        # to ADD to our clock to land on the master's, plus the RTT of the
        # exchange that produced it (the estimate's ±rtt/2 error bound)
        self.clock_offset = 0.0
        self.clock_rtt = float("inf")

    def _offset_sample(self, ts: float, mts: float) -> None:
        rtt = time.time() - ts
        if rtt < 0.0 or rtt >= self.clock_rtt:
            return  # clock stepped backwards, or a noisier sample than kept
        self.clock_rtt = rtt
        self.clock_offset = mts - (ts + rtt / 2.0)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.create_datagram_endpoint(
            lambda: _SlaveProto(self), remote_addr=self.master
        )

    def stop(self) -> None:
        if self._transport:
            self._transport.close()

    async def signal_and_wait(self, state: int, timeout: float | None = None) -> None:
        ev = self._acked.setdefault(state, asyncio.Event())

        async def spam():
            while not ev.is_set():
                # fresh `ts` per resend: every direct ack is a new offset
                # sample, and the min-RTT one wins (_offset_sample)
                self._transport.sendto(
                    json.dumps(
                        {"state": state, "id": self.node_id, "ts": time.time()}
                    ).encode()
                )
                await asyncio.sleep(RESEND_PERIOD)

        task = asyncio.get_running_loop().create_task(spam())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        finally:
            task.cancel()

"""Simulation & benchmarking harness.

Reference: simul/ (~7.6 kLoC, SURVEY.md §2.5) — orchestrator, TOML config
matrix, platforms (localhost/AWS), node & master binaries, UDP sync barrier,
allocator, keygen/registry CSV, metrics monitor, confgenerator, plots.

This package rebuilds that capability Python-first: the localhost platform
spawns real OS processes running `python -m handel_tpu.sim.node`, synchronized
by a UDP barrier, reporting to a UDP JSON monitor whose stats land in CSV the
plots understand. The TPU twist: one process can host thousands of logical
nodes sharing a single device batch-verifier (parallel/batch_verifier.py).
"""

"""Standalone master: sync barriers + monitor sink for multi-host runs.

Reference: simul/master/main.go:36-118 — on a distributed deployment one
host runs the SyncMaster and the metrics Monitor while node processes on
other hosts connect over DCN; at END it writes the stats CSV. The localhost
platform embeds this role in-process (sim/platform.py); this entry point is
the multi-host form.

Usage: python -m handel_tpu.sim.master --port 5555 --monitor-port 5556 \
           --expected 64 --csv results.csv [--timeout 600]
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from handel_tpu.sim.monitor import Monitor
from handel_tpu.sim.sync import STATE_END, STATE_START, SyncMaster


async def run_master(
    port: int, monitor_port: int, expected: int, csv: str, timeout: float
) -> int:
    monitor = Monitor(monitor_port)
    await monitor.start()
    sync = SyncMaster(port, expected)
    await sync.start()
    print(f"master: waiting for {expected} nodes on :{port}", flush=True)
    try:
        await sync.wait_all(STATE_START, timeout)
        print("master: START released", flush=True)
        await sync.wait_all(STATE_END, timeout)
        print("master: END released", flush=True)
        # linger: the barrier releases at the probabilistic fraction
        # (sync.go:92-98), so stragglers may still be resending READY —
        # keep acking briefly or they'd time out waiting for a dead master
        await asyncio.sleep(2.0)
    except asyncio.TimeoutError:
        print("master: barrier timeout", file=sys.stderr, flush=True)
        return 1
    finally:
        sync.stop()
        monitor.stop()
    if csv:
        monitor.stats.write_csv(csv)
        print(f"master: stats -> {csv}", flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--monitor-port", type=int, required=True)
    ap.add_argument("--expected", type=int, required=True)
    ap.add_argument("--csv", default="")
    ap.add_argument("--timeout", type=float, default=600.0)
    args = ap.parse_args()
    return asyncio.run(
        run_master(
            args.port, args.monitor_port, args.expected, args.csv, args.timeout
        )
    )


if __name__ == "__main__":
    sys.exit(main())

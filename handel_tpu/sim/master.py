"""Standalone master: sync barriers + monitor sink for multi-host runs.

Reference: simul/master/main.go:36-118 — on a distributed deployment one
host runs the SyncMaster and the metrics Monitor while node processes on
other hosts connect over DCN; at END it writes the stats CSV. The localhost
platform embeds this role in-process (sim/platform.py); this entry point is
the multi-host form.

Usage: python -m handel_tpu.sim.master --port 5555 --monitor-port 5556 \
           --expected 64 --csv results.csv [--timeout 600]
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from handel_tpu.sim.monitor import DataFilter, Monitor
from handel_tpu.sim.sync import STATE_END, STATE_START, SyncMaster


async def run_master(
    port: int,
    monitor_port: int,
    expected: int,
    csv: str,
    timeout: float,
    data_filter: DataFilter | None = None,
    extra: dict[str, float] | None = None,
    expected_keys: list[str] | None = None,
) -> int:
    monitor = Monitor(
        monitor_port, data_filter=data_filter, expected_keys=expected_keys or ()
    )
    # run/nodes/threshold/failing columns the plots key on (platform.py does
    # this in-process; the standalone master takes them from the CLI)
    monitor.stats.extra.update(extra or {})
    await monitor.start()
    sync = SyncMaster(port, expected)
    await sync.start()
    print(f"master: waiting for {expected} nodes on :{port}", flush=True)
    try:
        await sync.wait_all(STATE_START, timeout)
        print("master: START released", flush=True)
        await sync.wait_all(STATE_END, timeout)
        print("master: END released", flush=True)
        # linger: the barrier releases at the probabilistic fraction
        # (sync.go:92-98), so stragglers may still be resending READY —
        # keep acking briefly or they'd time out waiting for a dead master
        await asyncio.sleep(2.0)
    except asyncio.TimeoutError:
        print("master: barrier timeout", file=sys.stderr, flush=True)
        return 1
    finally:
        sync.stop()
        monitor.stop()
    if csv:
        monitor.stats.write_csv(csv)
        print(f"master: stats -> {csv}", flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--monitor-port", type=int, required=True)
    ap.add_argument("--expected", type=int, required=True)
    ap.add_argument("--csv", default="")
    ap.add_argument("--timeout", type=float, default=600.0)

    def _kv(spec: str) -> tuple[str, float]:
        key, eq, val = spec.partition("=")
        try:
            if not (key and eq):
                raise ValueError
            return key, float(val)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"expected KEY=NUMBER, got {spec!r}"
            ) from None

    ap.add_argument(
        "--filter",
        action="append",
        default=[],
        type=_kv,
        metavar="KEY=PCT",
        help="percentile outlier filter per stats key (stats.go DataFilter), "
        "e.g. --filter sigen_wall=99",
    )
    ap.add_argument(
        "--extra",
        action="append",
        default=[],
        type=_kv,
        metavar="KEY=VAL",
        help="constant CSV columns (run/nodes/threshold/failing) the plots "
        "key on, e.g. --extra nodes=4000 --extra threshold=3960",
    )
    args = ap.parse_args()
    pcts = dict(args.filter)
    return asyncio.run(
        run_master(
            args.port,
            args.monitor_port,
            args.expected,
            args.csv,
            args.timeout,
            data_filter=DataFilter(pcts) if pcts else None,
            extra=dict(args.extra),
        )
    )


if __name__ == "__main__":
    sys.exit(main())

"""Adversarial node roles for simulation and test runs.

The paper assumes up to f byzantine nodes, but the seed reproduction only
modeled failures as SILENT nodes (`RunConfig.failing`: never launched).
These roles actively misbehave, each aimed at one hardening layer:

  invalid_signer   a full Handel node whose own contribution is garbage —
                   wrong-message signature bytes under a valid bitset. Every
                   aggregate it forwards fails the receiver's pairing check,
                   exercising failure attribution + peer penalties
                   (core/penalty.py) and negative-verdict dedup caching.
  stale_replayer   participates, but its periodic updates replay the FIRST
                   (lowest-weight) aggregate it ever saw per level instead
                   of its best combined signature — valid but useless
                   traffic that the dedup cache must absorb.
  flooder          packet storms at one level: bursts of parseable packets
                   with random signature bytes, each content-distinct, so
                   only the bounded pending queue (BatchProcessing
                   max_pending) and the ban threshold stop the growth.
  churner          dynamic membership (scenario engine): participates
                   HONESTLY until `leave_after_s`, then departs — stops
                   gossiping and fires `on_depart(node_id)` so the harness
                   can broadcast Handel.mark_departed to survivors, who
                   re-level around the hole and re-evaluate threshold
                   reachability. Not byzantine, but seated by the same
                   deterministic role machinery.

Role assignment (`adversary_roles`) is deterministic from the run config so
every node process computes the same mapping independently: adversaries take
the highest non-offline ids, invalid signers first.
"""

from __future__ import annotations

import asyncio
import random

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.handel import Handel
from handel_tpu.core.net import Packet

ROLE_INVALID_SIGNER = "invalid_signer"
ROLE_STALE_REPLAYER = "stale_replayer"
ROLE_FLOODER = "flooder"
ROLE_CHURNER = "churner"
ROLES = (ROLE_INVALID_SIGNER, ROLE_STALE_REPLAYER, ROLE_FLOODER, ROLE_CHURNER)


def forged_signature(sk, msg: bytes):
    """A wrong-message signature: parseable, combinable, and guaranteed to
    fail verification for `msg`. For schemes whose signatures ignore the
    message entirely (the fake scheme), fall back to the scheme's explicit
    invalid-signature construction."""
    sig = sk.sign(b"forged:" + msg)
    if sig.marshal() == sk.sign(msg).marshal():
        # message-independent scheme: fake-style bool constructor
        return type(sig)(False)
    return sig


def adversary_roles(
    counts: dict[str, int], total: int, offline: set[int] | frozenset[int] = frozenset()
) -> dict[int, str]:
    """Deterministic node-id -> role mapping: highest non-offline ids,
    in ROLES order. Raises when the committee cannot seat them all."""
    roles: dict[int, str] = {}
    candidates = (i for i in range(total - 1, -1, -1) if i not in offline)
    for role in ROLES:
        for _ in range(int(counts.get(role, 0) or 0)):
            nid = next(candidates, None)
            if nid is None:
                raise ValueError(
                    f"cannot seat {counts} adversaries in a {total}-node "
                    f"committee with {len(offline)} offline"
                )
            roles[nid] = role
    return roles


def check_threshold_reachable(
    threshold: int,
    total: int,
    failing: int,
    roles: dict[int, str],
    *,
    weights=None,
    weight_threshold: float = 0.0,
    departed: frozenset[int] | set[int] = frozenset(),
) -> None:
    """Fail fast when the run can never complete: invalid signers contribute
    nothing countable (their signatures are rejected), churners and already-
    departed identities may leave before contributing, so the guaranteed
    honest supply is total - failing - invalid - churners - departed.

    With per-identity `weights` (indexed by node id) the check is on stake:
    the reachable weight is the surviving cohort's total minus the WORST
    CASE placement of the `failing` silent nodes — the heaviest survivors.
    `weight_threshold` 0.0 derives the same stake fraction the count
    threshold is of the node count."""
    gone = {
        i
        for i, r in roles.items()
        if r in (ROLE_INVALID_SIGNER, ROLE_CHURNER)
    }
    gone |= set(departed)
    if weights is None:
        reachable = total - failing - len(gone)
        if threshold > reachable:
            raise ValueError(
                f"threshold {threshold} unreachable: only {reachable} honest "
                f"contributions exist ({total} nodes - {failing} failing - "
                f"{len(gone)} invalid/departing)"
            )
        return
    w = [float(weights[i]) for i in range(total)]
    remaining = sorted((w[i] for i in range(total) if i not in gone),
                       reverse=True)
    lost_to_failing = sum(remaining[:failing]) if failing > 0 else 0.0
    reachable_w = sum(remaining) - lost_to_failing
    want = weight_threshold or (threshold * sum(w) / total)
    if want > reachable_w + 1e-9:
        raise ValueError(
            f"weighted threshold {want:.3f} unreachable: at most "
            f"{reachable_w:.3f} stake can contribute ({total} nodes, "
            f"{failing} failing worst-case, {len(gone)} invalid/departing)"
        )


class InvalidSigner(Handel):
    """A protocol-conformant node built on a forged own signature — the
    construction site (build_adversary / the test harness) swaps its own_sig
    for `forged_signature(...)`, and the normal gossip machinery does the
    rest: every aggregate that includes its contribution is invalid."""

    role = ROLE_INVALID_SIGNER


class StaleReplayer(Handel):
    """Freezes its outbound updates at the FIRST aggregate it could send per
    level — usually just its own signature — and replays that forever
    instead of its improving best. The replayed content is correctly scoped
    for its peers and verifies under any scheme (it is genuinely its own
    stale aggregate), so the traffic is valid-but-useless: the honest
    defense is the dedup cache, not a pairing rejection. (Replaying RECEIVED
    packets would instead be cross-subtree garbage — a level-l bitset only
    means anything to the subtree it was addressed to — i.e. a noisier
    invalid_signer, which is the other role's job.)"""

    role = ROLE_STALE_REPLAYER

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._stale: dict[int, bytes] = {}
        self.replayed_ct = 0

    def _send_update(self, lvl, count: int) -> None:
        stale = self._stale.get(lvl.id)
        if stale is None:
            ms = self.store.combined(lvl.id - 1)
            if ms is None:
                return
            stale = self._stale[lvl.id] = ms.marshal()
        peers = lvl.select_next_peers(count)
        if not peers:
            return
        self.msg_sent_ct += len(peers)
        self.replayed_ct += len(peers)
        self.net.send(
            peers, Packet(origin=self.id.id, level=lvl.id, multisig=stale)
        )

    def values(self) -> dict[str, float]:
        return {**super().values(), "advReplayedCt": float(self.replayed_ct)}


class Flooder(Handel):
    """Packet storm at one level: bursts of parseable, content-distinct
    packets (valid one-bit bitset + random signature bytes)."""

    role = ROLE_FLOODER

    def __init__(
        self,
        *args,
        flood_pps: float = 200.0,
        flood_level: int | None = None,
        flood_burst: int = 16,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.flood_pps = max(1.0, flood_pps)
        self.flood_burst = max(1, flood_burst)
        self.flood_level = flood_level
        self._flood_rng = random.Random(0xF100D ^ self.id.id)
        self._flood_task: asyncio.Task | None = None
        self.flooded_ct = 0

    def start(self) -> None:
        super().start()
        self._flood_task = asyncio.get_running_loop().create_task(
            self._flood_loop()
        )

    def stop(self) -> None:
        if self._flood_task is not None:
            self._flood_task.cancel()
            self._flood_task = None
        super().stop()

    def _flood_packet(self, level: int) -> Packet:
        size = len(self.levels[level].nodes)
        bs = BitSet(size)
        bs.set(self._flood_rng.randrange(size), True)
        wire = bs.marshal() + self._flood_rng.randbytes(
            self.cons.signature_size()
        )
        return Packet(origin=self.id.id, level=level, multisig=wire)

    async def _flood_loop(self) -> None:
        level = self.flood_level or max(self.levels)
        lvl = self.levels[level]
        interval = self.flood_burst / self.flood_pps
        pos = 0
        while True:
            for _ in range(self.flood_burst):
                peer = lvl.nodes[pos % len(lvl.nodes)]
                pos += 1
                self.net.send([peer], self._flood_packet(level))
                self.flooded_ct += 1
                self.msg_sent_ct += 1
            await asyncio.sleep(interval)

    def values(self) -> dict[str, float]:
        return {**super().values(), "advFloodedCt": float(self.flooded_ct)}


class Churner(Handel):
    """Honest until `leave_after_s`, then gone: cancels its own gossip and
    fires `on_depart(node_id)` (set post-construction by the harness) so
    survivors can `mark_departed` and re-level. The contribution it made
    BEFORE leaving stays valid in any aggregate that already merged it —
    departure removes future supply, not recorded history."""

    role = ROLE_CHURNER

    def __init__(self, *args, leave_after_s: float = 0.5, **kwargs):
        super().__init__(*args, **kwargs)
        self.leave_after_s = leave_after_s
        self.on_depart = None  # callable(node_id), wired by the harness
        self.left = False
        self._leave_handle: asyncio.TimerHandle | None = None

    def start(self) -> None:
        super().start()
        self._leave_handle = asyncio.get_running_loop().call_later(
            self.leave_after_s, self._depart
        )

    def _depart(self) -> None:
        if self.left:
            return
        self.left = True
        self._leave_handle = None
        self.stop()
        if self.on_depart is not None:
            self.on_depart(self.id.id)

    def stop(self) -> None:
        if self._leave_handle is not None:
            self._leave_handle.cancel()
            self._leave_handle = None
        super().stop()

    def values(self) -> dict[str, float]:
        return {**super().values(), "advLeftCt": float(self.left)}


ADVERSARY_CLASSES = {
    ROLE_INVALID_SIGNER: InvalidSigner,
    ROLE_STALE_REPLAYER: StaleReplayer,
    ROLE_FLOODER: Flooder,
    ROLE_CHURNER: Churner,
}


def build_adversary(
    role: str,
    network,
    registry,
    identity,
    constructor,
    msg: bytes,
    sk,
    config=None,
    *,
    flood_pps: float = 200.0,
    leave_after_s: float = 0.5,
):
    """Construct the adversarial node for `role` (Handel ctor signature,
    with the secret key in place of a pre-made own signature — the invalid
    signer forges its own)."""
    cls = ADVERSARY_CLASSES.get(role)
    if cls is None:
        raise ValueError(f"unknown adversary role {role!r} (known: {ROLES})")
    own_sig = (
        forged_signature(sk, msg)
        if role == ROLE_INVALID_SIGNER
        else sk.sign(msg)
    )
    kwargs = {}
    if role == ROLE_FLOODER:
        kwargs = {"flood_pps": flood_pps}
    elif role == ROLE_CHURNER:
        kwargs = {"leave_after_s": leave_after_s}
    return cls(
        network, registry, identity, constructor, msg, own_sig, config, **kwargs
    )

"""Key generation and the CSV node registry.

Reference: simul/lib/generator.go:1-53 (per-node keypairs), parser.go:14-156
(CSV registry `(id, addr, privHex, pubHex)` + NodeList implementing Registry),
nodes.go:10-64.
"""

from __future__ import annotations

import csv
from dataclasses import dataclass
from typing import Sequence

from handel_tpu.core.identity import ArrayRegistry, Identity


@dataclass
class NodeRecord:
    id: int
    address: str
    secret_hex: str
    public_hex: str


def generate_nodes(scheme, addresses: Sequence[str]) -> list[NodeRecord]:
    """Deterministic per-id keypairs for every address (generator.go:1-53)."""
    out = []
    for i, addr in enumerate(addresses):
        sk, pk = scheme.keygen(i)
        out.append(
            NodeRecord(
                id=i,
                address=addr,
                secret_hex=sk.marshal().hex(),
                public_hex=pk.marshal().hex(),
            )
        )
    return out


def write_registry_csv(path: str, records: Sequence[NodeRecord]) -> None:
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        for r in records:
            w.writerow([r.id, r.address, r.secret_hex, r.public_hex])


def read_registry_csv(path: str) -> list[NodeRecord]:
    out = []
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if not row:
                continue
            out.append(NodeRecord(int(row[0]), row[1], row[2], row[3]))
    out.sort(key=lambda r: r.id)
    return out


def registry_from_records(records: Sequence[NodeRecord], scheme) -> ArrayRegistry:
    """Build the runtime Registry (parser.go NodeList.Registry equivalent)."""
    idents = []
    for r in records:
        pk = scheme.unmarshal_public(bytes.fromhex(r.public_hex))
        idents.append(Identity(r.id, r.address, pk))
    return ArrayRegistry(idents)


def secret_of(record: NodeRecord, scheme):
    return scheme.unmarshal_secret(bytes.fromhex(record.secret_hex))

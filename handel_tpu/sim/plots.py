"""Result plotting over the monitor's CSV schema.

Reference: simul/plots/*.py (~12 matplotlib scripts — comparison_time.py,
reallike.py, sigchecked.py, lib.py …) reading the stats CSVs
(simul/plots/csv/*.csv) whose columns the monitor writes
(`sigen_wall_avg`, `net_sentBytes_avg`, `sigs_sigCheckedCt_avg`, run/nodes/
threshold extras).

One module replaces the script pile: each plot function takes CSV paths as
produced by `Stats.write_csv` (sim/monitor.py) and writes a PNG. CLI:
`python -m handel_tpu.sim.plots <kind> out.png run1.csv [run2.csv ...]`.
"""

from __future__ import annotations

import csv
import functools


def read_rows(path: str) -> list[dict[str, float]]:
    """CSV -> list of {column: float} rows (plots/lib.py read_csv)."""
    with open(path, newline="") as f:
        reader = csv.DictReader(f)
        return [
            {k: float(v) for k, v in row.items() if v not in (None, "")}
            for row in reader
        ]


def _series(rows, xcol, ycol):
    import math

    # NaN cells come from declared-but-unsampled stats keys (sim/monitor.py
    # schema stability): skip the point, keep the rest of the series
    pts = sorted(
        (r[xcol], r[ycol])
        for r in rows
        if xcol in r
        and ycol in r
        and not (math.isnan(r[xcol]) or math.isnan(r[ycol]))
    )
    return [p[0] for p in pts], [p[1] for p in pts]


def _plot_xy(series, xlabel, ylabel, out, logx=False, logy=False):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for label, xs, ys in series:
        ax.plot(xs, ys, marker="o", label=label)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    if logx:
        ax.set_xscale("log")
    if logy:
        ax.set_yscale("log")
    ax.grid(True, alpha=0.3)
    ax.legend()
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    return out


def plot_time_vs_nodes(csvs: dict[str, str], out: str):
    """Completion time vs committee size, one line per protocol/config
    (plots/comparison_time.py). csvs: label -> path."""
    series = []
    for label, path in csvs.items():
        xs, ys = _series(read_rows(path), "nodes", "sigen_wall_avg")
        series.append((label, xs, ys))
    return _plot_xy(series, "nodes", "aggregation time (s)", out, logx=True)


def plot_network_vs_nodes(csvs: dict[str, str], out: str):
    """Per-node bytes sent vs committee size (plots/comparison_net.py)."""
    series = []
    for label, path in csvs.items():
        xs, ys = _series(read_rows(path), "nodes", "net_sentBytes_avg")
        series.append((label, xs, [y / 1024.0 for y in ys]))
    return _plot_xy(series, "nodes", "KB sent / node", out, logx=True, logy=True)


def plot_sigs_checked(csvs: dict[str, str], out: str):
    """Signatures checked per node vs committee size (plots/sigchecked.py)."""
    series = []
    for label, path in csvs.items():
        xs, ys = _series(read_rows(path), "nodes", "sigs_sigCheckedCt_avg")
        series.append((label, xs, ys))
    return _plot_xy(series, "nodes", "signatures checked / node", out, logx=True)


def plot_failing(csvs: dict[str, str], out: str):
    """Completion time vs number of failing nodes (plots/reallike.py)."""
    series = []
    for label, path in csvs.items():
        xs, ys = _series(read_rows(path), "failing", "sigen_wall_avg")
        series.append((label, xs, ys))
    return _plot_xy(series, "failing nodes", "aggregation time (s)", out)


def plot_sweep(csvs: dict[str, str], out: str, *, xcol: str = "period_ms"):
    """Protocol-knob sweep: completion time (left axis) and signatures
    checked per node (right axis) vs the swept parameter (`period_ms` |
    `timeout_ms` | `update_count`, columns the platforms embed per run) —
    the periodInc/timeoutInc/updateCount figures of
    simul/confgenerator/confgenerator.go. Twin axes because the two
    metrics live on different scales (~1 s vs ~60 sigs)."""
    import sys

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax_t = plt.subplots(figsize=(7, 4.5))
    ax_s = ax_t.twinx()
    plotted = False
    for label, path in csvs.items():
        rows = read_rows(path)
        xs, ys = _series(rows, xcol, "sigen_wall_avg")
        if not xs:
            # pre-knob-column captures would silently vanish from a
            # comparison figure otherwise
            print(
                f"plot_sweep: '{label}' has no '{xcol}' column, skipped",
                file=sys.stderr,
            )
            continue
        ax_t.plot(xs, ys, marker="o", label=f"{label}: time (s)")
        xs, ys = _series(rows, xcol, "sigs_sigCheckedCt_avg")
        ax_s.plot(
            xs, ys, marker="s", linestyle="--", label=f"{label}: sigs checked"
        )
        plotted = True
    if not plotted:
        raise ValueError(f"no '{xcol}' sweep columns in the given CSVs")
    ax_t.set_xlabel(xcol)
    ax_t.set_ylabel("aggregation time (s)")
    ax_s.set_ylabel("signatures checked / node")
    ax_t.grid(True, alpha=0.3)
    h1, l1 = ax_t.get_legend_handles_labels()
    h2, l2 = ax_s.get_legend_handles_labels()
    ax_t.legend(h1 + h2, l1 + l2, fontsize=8)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    return out


def plot_batch_plane(csvs: dict[str, str], out: str):
    """Batch-plane telemetry vs committee size: shared-launch occupancy,
    device wall time per launch, and host G2 subgroup-check time — the
    columns sim/node.py's `device` CounterIO records. Attributes where a
    large-N run's time goes (host unmarshal vs device launches)."""
    series = []
    for label, path in csvs.items():
        rows = read_rows(path)
        for col, tag in (
            ("device_verifier_verifierOccupancy_avg", "occupancy"),
            ("device_launch_launchTimeMs_avg", "launch ms"),
            ("device_subgroup_g2SubgroupCheckTimeMs_avg", "subgroup ms"),
        ):
            xs, ys = _series(rows, "nodes", col)
            if xs:
                series.append((f"{label}: {tag}", xs, ys))
    if not series:
        raise ValueError("no batch-plane columns in the given CSVs")
    return _plot_xy(series, "nodes", "batch plane (ratio / ms)", out, logx=True)


def plot_trace_timeline(wave: dict[int, tuple[float, float, float]], out: str):
    """The aggregation wave from a traced run (sim/trace_cli.py
    level_timeline): per level, the first -> last completion window across
    nodes with the median marked — the per-run, per-level form of the
    paper's logarithmic completion-time claim."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if not wave:
        raise ValueError("no level_complete events in the trace")
    fig, ax = plt.subplots(figsize=(7, 4.5))
    levels = sorted(wave)
    for lvl in levels:
        first, med, last = wave[lvl]
        ax.plot([first, last], [lvl, lvl], lw=4, alpha=0.4, color="C0")
        ax.plot([med], [lvl], marker="o", color="C0")
    ax.set_xlabel("time since first event (s)")
    ax.set_ylabel("level completed")
    ax.set_yticks(levels)
    ax.grid(True, alpha=0.3)
    ax.set_title("aggregation wave: first-median-last completion per level")
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    return out


KINDS = {
    "time": plot_time_vs_nodes,
    "network": plot_network_vs_nodes,
    "sigchecked": plot_sigs_checked,
    "failing": plot_failing,
    "batchplane": plot_batch_plane,
    "period": functools.partial(plot_sweep, xcol="period_ms"),
    "timeout": functools.partial(plot_sweep, xcol="timeout_ms"),
    "updatecount": functools.partial(plot_sweep, xcol="update_count"),
}


def main(argv) -> int:
    if len(argv) < 3 or argv[0] not in KINDS:
        print(
            "usage: python -m handel_tpu.sim.plots "
            f"{{{'|'.join(KINDS)}}} out.png run1.csv [run2.csv ...]"
        )
        return 2
    kind, out, *paths = argv
    csvs: dict[str, str] = {}
    for p in paths:
        label = p.rsplit("/", 1)[-1].removesuffix(".csv")
        if label in csvs:  # basename collision: fall back to the full path
            label = p
        csvs[label] = p
    KINDS[kind](csvs, out)
    print(out)
    return 0


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv[1:]))

"""One source of truth for report acceptance checks.

The soak and federation harnesses both emit bench-record-shaped reports
carrying a `checks` block, and their CI smokes re-assert the same
invariants with human-readable failure detail. Before this module the
predicate logic lived twice — once in the report builder, once in the
smoke's asserts — and could silently drift. Now each invariant is one
`Check`: a name, a predicate over the REPORT dict (so it can be
re-evaluated from the persisted JSON alone), and a failure-message
renderer the smokes raise with.

`attach(report, checks)` is what report builders call (sets `checks` +
`ok`); `assert_checks(report, checks)` is what smokes call — both read
the same predicates, so an artifact that says `ok` is exactly an
artifact the smoke would accept.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence


@dataclass(frozen=True)
class Check:
    name: str
    predicate: Callable[[dict], bool]
    describe: Callable[[dict], str]


def evaluate(report: dict, checks: Sequence[Check]) -> dict[str, bool]:
    return {c.name: bool(c.predicate(report)) for c in checks}


def attach(report: dict, checks: Sequence[Check]) -> dict:
    """Stamp `checks` + `ok` onto a report (the builder-side entry)."""
    report["checks"] = evaluate(report, checks)
    report["ok"] = all(report["checks"].values())
    return report


def assert_checks(report: dict, checks: Sequence[Check]) -> None:
    """Re-assert every check with its failure detail (the smoke-side
    entry) — evaluated fresh from the report, not trusted from `ok`."""
    for c in checks:
        assert c.predicate(report), f"check {c.name}: {c.describe(report)}"


# -- the lifecycle soak's invariants (sim/soak.py report schema) -------------

SOAK_CHECKS: tuple[Check, ...] = (
    Check(
        "zero_dropped",
        # every spawned session reached a terminal verdict, none of them
        # by expiry: zero dropped futures across swap + lane loss
        lambda r: r["soak"]["expired"] == 0 and r["soak"]["unresolved"] == 0,
        lambda r: (
            f"dropped work: expired={r['soak']['expired']} "
            f"unresolved={r['soak']['unresolved']}"
        ),
    ),
    Check(
        "epoch_advanced",
        lambda r: (
            r["soak"]["epoch_rotations"] == 1
            and r["soak"]["summary"]["epoch"] >= 1
        ),
        lambda r: "epoch rotation did not complete",
    ),
    Check(
        # the swap hid between launches: neither the measured stall nor
        # the launch gap straddling it exceeded the cadence bound
        "swap_bounded",
        lambda r: (
            r["epoch_swap_stall_ms"] <= r["soak"]["swap_gap_bound_ms"]
            and r["soak"]["gaps"]["swap_gap_ms"]
            <= r["soak"]["swap_gap_bound_ms"]
        ),
        lambda r: (
            f"epoch swap not hidden between launches: "
            f"stall {r['epoch_swap_stall_ms']}ms / swap gap "
            f"{r['soak']['gaps']['swap_gap_ms']}ms vs bound "
            f"{r['soak']['swap_gap_bound_ms']}ms"
        ),
    ),
    Check(
        "lane_replaced",
        lambda r: (
            r["soak"]["lanes_replaced"] >= 1
            and r["soak"]["summary"]["devices"] >= r["soak"]["devices_floor"]
        ),
        lambda r: "forced lane loss was not repaired by the autoscaler",
    ),
    Check(
        "p99_within_slo",
        lambda r: bool(r["soak"]["tiers"])
        and all(t["met"] for t in r["soak"]["tiers"].values()),
        lambda r: f"tier p99 breached its SLO target: {r['soak']['tiers']}",
    ),
)


# -- the federation load run's invariants (sim/load.py report schema) --------
#
# The kill-drill checks pass vacuously when no kill was scheduled (the
# report's `kill` block is None), so one static list serves both plain
# open-loop runs and the chaos variant.


def _kill(r: dict) -> dict | None:
    return r["federation"].get("kill")


FEDERATION_CHECKS: tuple[Check, ...] = (
    Check(
        "zero_dropped",
        # open-loop accounting closes: every arrival is a completion, an
        # attributed shed, a traced retry-budget failure, or an expiry —
        # nothing silently vanished, nothing still unresolved at exit
        lambda r: (
            r["federation"]["unaccounted"] == 0
            and r["federation"]["unresolved"] == 0
        ),
        lambda r: (
            f"dropped sessions: unaccounted="
            f"{r['federation']['unaccounted']} "
            f"unresolved={r['federation']['unresolved']} of "
            f"{r['federation']['arrivals']} arrivals"
        ),
    ),
    Check(
        "p99_within_slo",
        lambda r: bool(r["federation"]["tiers"])
        and all(t["met"] for t in r["federation"]["tiers"].values()),
        lambda r: (
            f"open-loop tier p99 breached its SLO target: "
            f"{r['federation']['tiers']}"
        ),
    ),
    Check(
        "shed_bounded",
        lambda r: r["shed_rate"] <= r["federation"]["shed_ceiling"],
        lambda r: (
            f"shed rate {r['shed_rate']} above the configured ceiling "
            f"{r['federation']['shed_ceiling']}"
        ),
    ),
    Check(
        "region_killed",
        lambda r: _kill(r) is None
        or (
            _kill(r)["killed_at_s"] is not None
            and _kill(r)["unhealthy_detected_s"] is not None
        ),
        lambda r: (
            f"region kill drill incomplete: {_kill(r)} — the region was "
            f"not stopped or the front door never marked it unhealthy"
        ),
    ),
    Check(
        "spillover_observed",
        lambda r: _kill(r) is None or r["federation"]["spillovers"] > 0,
        lambda r: (
            "a region died but no arrival spilled over to another region"
        ),
    ),
    Check(
        "recovery_traced",
        lambda r: _kill(r) is None
        or (
            _kill(r)["recovery_s"] is not None
            and _kill(r)["post_recovery_completed"] > 0
        ),
        lambda r: (
            f"region recovery not observed: {_kill(r)} — no completion "
            f"landed in the recovered region after its rejoin"
        ),
    ),
)

"""Metrics plane: UDP JSON sink, measures, stats aggregation, CSV output.

Reference: simul/monitor/ — nodes `ConnectSink` and push JSON measures
(monitor.go:41-156, measure.go:33-229); the master aggregates per-key
min/max/avg/sum/dev columns (stats.go:23-480) into the CSV schema the plots
consume (simul/plots/csv/*.csv headers, e.g. `sigen_wall_avg`).

Measure kinds mirrored here: `TimeMeasure` (wall + user/system CPU via
resource.getrusage, measure.go:54-143 + rtime.go:17-26), `CounterIO`
(delta of a Values() map), and single values. The TPU addition: kernel-time
counters flow through the same pipe (SURVEY.md §5.1).

Observability additions (ISSUE 4): `HistogramIO` ships fixed-log-bucket
histograms (core/trace.py LogHistogram) through the same UDP pipe as sparse
{bucket: count} maps; the master merges them by summing counts and emits
`_p50/_p90/_p99/_n` CSV columns next to the classic stats. Large payloads
are chunked below the UDP-safe datagram size instead of risking an
oversized-send OSError silently swallowing the whole measure.
"""

from __future__ import annotations

import asyncio
import json
import math
import resource
import time
import warnings
from typing import Iterator, Mapping, Sequence

from handel_tpu.core.trace import LogHistogram

# conservative single-datagram budget: 1500 MTU minus IP/UDP headers with
# margin — loopback allows much more, but the multi-host master does not
MAX_DATAGRAM = 1400


# -- node side: the sink client ---------------------------------------------


class Sink:
    """Fire-and-forget UDP JSON metric emitter (monitor.go ConnectSink)."""

    def __init__(self, addr: str):
        host, _, port = addr.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        import socket

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def record(self, name: str, values: Mapping[str, float]) -> None:
        vals = {k: float(v) for k, v in values.items()}
        for payload in _chunk_values(name, vals):
            self._send(payload)

    def record_histograms(
        self, name: str, hists: Mapping[str, LogHistogram]
    ) -> None:
        """Ship each histogram as sparse bucket maps, chunked per datagram;
        the master merges chunks by summing bucket counts (LogHistogram
        .merge_sparse), so a split histogram reassembles exactly."""
        for key, h in hists.items():
            if h.count == 0:
                continue  # nothing measured: the master emits NaN columns
            for payload in _chunk_hist(name, key, h):
                self._send(payload)

    def record_rollup(self, rollup) -> int:
        """Emit a HostRollup's changed-keys delta over this sink (each
        chunk already fits the datagram budget). Returns wire bytes."""
        return rollup.emit(self._send)

    def _send(self, payload: dict) -> None:
        try:
            self._sock.sendto(json.dumps(payload).encode(), self.addr)
        except OSError:
            pass

    def close(self) -> None:
        self._sock.close()


def _chunk_values(name: str, vals: dict[str, float]) -> Iterator[dict]:
    """Split a values map into payloads whose JSON stays <= MAX_DATAGRAM.

    One oversized sendto raises OSError and (fire-and-forget) loses EVERY
    key of the measure; chunking loses none. Sizes are computed on the
    JSON-encoded items themselves, so the estimate is exact up to the two
    enclosing braces."""
    base = len(json.dumps({"name": name, "values": {}}).encode())
    out: dict[str, float] = {}
    size = base
    for k, v in vals.items():
        item = len(json.dumps({k: v}).encode())  # includes braces ≈ separator slack
        if out and size + item > MAX_DATAGRAM:
            yield {"name": name, "values": out}
            out, size = {}, base
        out[k] = v
        size += item
    if out or not vals:
        yield {"name": name, "values": out}


def _chunk_hist(name: str, key: str, h: LogHistogram) -> Iterator[dict]:
    """Split one histogram's sparse buckets across datagrams. Every chunk
    repeats lo/hi (idempotent min/max merge); `sum` rides the first chunk
    only, so the master-side total adds up exactly once."""
    sparse = h.to_sparse()
    items = list(sparse["b"].items())
    base = len(
        json.dumps(
            {"name": name, "hists": {key: {"b": {}, "lo": sparse["lo"],
                                           "hi": sparse["hi"], "sum": sparse["sum"]}}}
        ).encode()
    )
    first = True
    out: dict[str, int] = {}
    size = base
    for bk, bc in items:
        item = len(json.dumps({bk: bc}).encode())
        if out and size + item > MAX_DATAGRAM:
            yield _hist_payload(name, key, out, sparse, include_sum=first)
            first = False
            out, size = {}, base
        out[bk] = bc
        size += item
    if out:
        yield _hist_payload(name, key, out, sparse, include_sum=first)


def _hist_payload(name, key, buckets, sparse, include_sum):
    body = {"b": buckets, "lo": sparse["lo"], "hi": sparse["hi"]}
    if include_sum:
        body["sum"] = sparse["sum"]
    return {"name": name, "hists": {key: body}}


class TimeMeasure:
    """Wall + user/system CPU interval measure (measure.go:54-143)."""

    def __init__(self, sink: Sink, name: str):
        self.sink = sink
        self.name = name
        self._wall = time.perf_counter()
        ru = resource.getrusage(resource.RUSAGE_SELF)
        self._user, self._sys = ru.ru_utime, ru.ru_stime

    def record(self) -> None:
        ru = resource.getrusage(resource.RUSAGE_SELF)
        self.sink.record(
            self.name,
            {
                "wall": time.perf_counter() - self._wall,
                "user": ru.ru_utime - self._user,
                "system": ru.ru_stime - self._sys,
            },
        )


class CounterIO:
    """Delta-of-Values() measure (measure.go CounterMeasure): snapshot a
    reporter's counters at construction, record the difference.

    Gauge keys are point-in-time ratios or levels (hit rates, launch
    occupancy, cache sizes, breaker state): `now - base` is meaningless for
    a ratio whenever the construction-time snapshot is nonzero, so those
    are recorded as-is. Reporters declare their gauge keys EXPLICITLY via a
    `gauge_keys()` method (core/store.py VerifiedAggCache, core/handel.py,
    parallel/batch_verifier.py, ...) or the caller passes `gauges=`; the
    name-suffix heuristic is kept only as a fallback, so a new
    registry-backed gauge without a magic suffix can't be silently averaged
    as a counter (core/metrics.py is_gauge_key is the one classifier)."""

    GAUGE_SUFFIXES = ("Rate", "Occupancy", "Size", "State")

    def __init__(self, sink: Sink, name: str, reporter, gauges=None):
        self.sink = sink
        self.name = name
        self.reporter = reporter
        if gauges is not None:
            self._gauges = set(gauges)
        else:
            gk = getattr(reporter, "gauge_keys", None)
            self._gauges = set(gk()) if callable(gk) else set()
        self._base = dict(reporter.values())

    def _is_gauge(self, key: str) -> bool:
        return key in self._gauges or key.endswith(self.GAUGE_SUFFIXES)

    def record(self) -> None:
        now = self.reporter.values()
        self.sink.record(
            self.name,
            {
                k: (v if self._is_gauge(k) else v - self._base.get(k, 0.0))
                for k, v in now.items()
            },
        )


class HistogramIO:
    """Ships a reporter's `histograms()` map (key -> LogHistogram) through
    the sink at record time. Histograms are cumulative over the run, so no
    construction-time base is needed — record once at run end, like the
    reference records its measures at the END barrier."""

    def __init__(self, sink: Sink, name: str, reporter):
        self.sink = sink
        self.name = name
        self.reporter = reporter

    def record(self) -> None:
        self.sink.record_histograms(self.name, self.reporter.histograms())


# -- master side: the sink server + stats ------------------------------------


class _SinkProto(asyncio.DatagramProtocol):
    def __init__(self, mon: "Monitor"):
        self.mon = mon

    def connection_made(self, transport):
        self.mon._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = json.loads(data.decode())
        except ValueError:
            return
        if isinstance(msg, dict) and "rollup" in msg:
            fleet = self.mon.fleet
            if fleet is not None:
                try:
                    fleet.ingest(msg)
                except (ValueError, TypeError, AttributeError):
                    pass  # malformed digest chunk: drop, never kill
            return
        try:
            name = str(msg["name"])
            values = msg.get("values", {})
            hists = msg.get("hists", {})
        except (ValueError, KeyError, AttributeError, TypeError):
            return
        try:
            for k, v in values.items():
                self.mon.stats.update(f"{name}_{k}", float(v))
            for k, payload in hists.items():
                self.mon.stats.update_hist(f"{name}_{k}", payload)
        except (ValueError, TypeError, AttributeError):
            return  # malformed measure: drop, never kill the endpoint


class Monitor:
    """UDP sink aggregating every node's measures (monitor.go:41-156)."""

    def __init__(
        self,
        port: int,
        data_filter: "DataFilter | None" = None,
        expected_keys: Sequence[str] = (),
        fleet=None,
    ):
        self.port = port
        self.stats = Stats(data_filter=data_filter, expected=expected_keys)
        #: optional obs.rollup.FleetRollup — `{"rollup": ...}` datagrams
        #: are host-digest chunks routed here instead of Stats columns
        self.fleet = fleet
        self._transport = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.create_datagram_endpoint(
            lambda: _SinkProto(self), local_addr=("0.0.0.0", self.port)
        )

    def stop(self) -> None:
        if self._transport:
            self._transport.close()


class DataFilter:
    """Percentile outlier filter applied per key before aggregation
    (stats.go DataFilter): for each configured key, keep only samples at or
    below that key's given percentile. Keys not configured pass through."""

    def __init__(self, percentiles: Mapping[str, float] | None = None):
        self.percentiles = dict(percentiles or {})

    def apply(self, key: str, values: list[float]) -> list[float]:
        pct = self.percentiles.get(key)
        if pct is None or not values:
            return values
        ordered = sorted(values)
        # nearest-rank: the ceil(n*pct/100)-th smallest value is the cut
        rank = max(1, math.ceil(len(ordered) * pct / 100.0))
        cut = ordered[min(len(ordered), rank) - 1]
        return [v for v in values if v <= cut]


HIST_STATS = ("p50", "p90", "p99", "n")


class Stats:
    """Per-key streaming min/max/avg/sum/dev (stats.go:23-480), plus merged
    log-bucket histograms (`_p50/_p90/_p99/_n` columns) and a stable schema:
    a declared key with zero samples still emits its columns — as NaN, with
    a warning — so CSVs from degraded runs line up with healthy ones."""

    def __init__(
        self,
        extra: Mapping[str, float] | None = None,
        data_filter: DataFilter | None = None,
        expected: Sequence[str] = (),
    ):
        self._keys: dict[str, list[float]] = {}
        self._hists: dict[str, LogHistogram] = {}
        self._expected: set[str] = set(expected)
        self._gauges: set[str] = set()
        self.extra = dict(extra or {})
        self.filter = data_filter or DataFilter()

    def update(self, key: str, value: float) -> None:
        self._keys.setdefault(key, []).append(value)

    def update_hist(self, key: str, payload: Mapping) -> None:
        """Merge one sparse-histogram datagram (LogHistogram.merge_sparse)."""
        self._hists.setdefault(key, LogHistogram()).merge_sparse(payload)

    def declare(self, *keys: str, gauge: bool = False) -> None:
        """Pin keys into the schema: zero samples -> NaN columns + warning
        instead of silently narrowing the CSV (plots keyed on the column
        would otherwise drop the whole run). `gauge=True` additionally
        declares them point-in-time, so downstream consumers (the metrics
        registry bridging a Stats object, tests asserting classification)
        never fall back to the name-suffix heuristic."""
        self._expected.update(keys)
        if gauge:
            self._gauges.update(keys)

    def is_gauge(self, key: str) -> bool:
        """Explicit declaration first, suffix heuristic as fallback
        (the single classification rule, core/metrics.py is_gauge_key)."""
        return key in self._gauges or key.endswith(CounterIO.GAUGE_SUFFIXES)

    def gauge_keys(self) -> set[str]:
        return set(self._gauges)

    def _stat_keys(self) -> list[str]:
        return sorted(set(self._keys) | self._expected)

    def columns(self) -> list[str]:
        cols = sorted(self.extra)
        for key in self._stat_keys():
            cols += [f"{key}_{s}" for s in ("min", "max", "avg", "sum", "dev")]
        for key in sorted(self._hists):
            cols += [f"{key}_{s}" for s in HIST_STATS]
        return cols

    def row(self) -> list[float]:
        out = [self.extra[k] for k in sorted(self.extra)]
        for key in self._stat_keys():
            vs = self.filter.apply(key, self._keys.get(key, []))
            if not vs:
                warnings.warn(
                    f"stats key {key!r} has no samples this run; "
                    f"emitting NaN columns to keep the CSV schema stable",
                    RuntimeWarning,
                    stacklevel=2,
                )
                out += [float("nan")] * 5
                continue
            n = len(vs)
            avg = sum(vs) / n
            dev = math.sqrt(sum((v - avg) ** 2 for v in vs) / n)
            out += [min(vs), max(vs), avg, sum(vs), dev]
        for key in sorted(self._hists):
            h = self._hists[key]
            out += [h.quantile(0.5), h.quantile(0.9), h.quantile(0.99),
                    float(h.count)]
        return out

    def write_csv(self, path: str, append: bool = False) -> None:
        import csv as _csv

        mode = "a" if append else "w"
        with open(path, mode, newline="") as f:
            w = _csv.writer(f)
            if not append:
                w.writerow(self.columns())
            w.writerow([f"{v:.6g}" for v in self.row()])


class Rollup:
    """Hierarchical per-process metric rollup for the vnode swarm
    (handel_tpu/swarm): 65,536 identities cannot each push a CounterIO
    measure — the UDP sink and the CSV would drown — so each process folds
    its vnodes' `values()` maps into ONE record: counters summed, gauges
    averaged + maxed (the Stats.is_gauge classification), and a bounded
    top-k of the SLOWEST vnodes by an externally supplied figure (time to
    threshold), which is the per-vnode detail worth keeping at scale."""

    def __init__(self, top_k: int = 16):
        self.top_k = top_k
        self._n = 0
        self._counters: dict[str, float] = {}
        self._gauge_sum: dict[str, float] = {}
        self._gauge_max: dict[str, float] = {}
        self._gauge_n: dict[str, int] = {}
        self._heap: list[tuple[float, int]] = []  # min-heap of (slow, id)
        self._unfinished = 0

    def add(
        self,
        vnode_id: int,
        values: Mapping[str, float],
        gauge_keys: set[str] = frozenset(),
        slow_value: float | None = None,
    ) -> None:
        import heapq

        self._n += 1
        for k, v in values.items():
            if k in gauge_keys or k.endswith(CounterIO.GAUGE_SUFFIXES):
                self._gauge_sum[k] = self._gauge_sum.get(k, 0.0) + v
                self._gauge_n[k] = self._gauge_n.get(k, 0) + 1
                if v > self._gauge_max.get(k, -math.inf):
                    self._gauge_max[k] = v
            else:
                self._counters[k] = self._counters.get(k, 0.0) + v
        if slow_value is None:
            self._unfinished += 1
        elif len(self._heap) < self.top_k:
            heapq.heappush(self._heap, (slow_value, vnode_id))
        elif slow_value > self._heap[0][0]:
            heapq.heapreplace(self._heap, (slow_value, vnode_id))

    def record(self) -> dict:
        return {
            "vnodes": self._n,
            "unfinished": self._unfinished,
            "counters": dict(sorted(self._counters.items())),
            "gauges": {
                k: {
                    "mean": self._gauge_sum[k] / self._gauge_n[k],
                    "max": self._gauge_max[k],
                }
                for k in sorted(self._gauge_sum)
            },
            "slowest": [
                {"id": vid, "slow_s": s}
                for s, vid in sorted(self._heap, reverse=True)
            ],
        }

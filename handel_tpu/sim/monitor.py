"""Metrics plane: UDP JSON sink, measures, stats aggregation, CSV output.

Reference: simul/monitor/ — nodes `ConnectSink` and push JSON measures
(monitor.go:41-156, measure.go:33-229); the master aggregates per-key
min/max/avg/sum/dev columns (stats.go:23-480) into the CSV schema the plots
consume (simul/plots/csv/*.csv headers, e.g. `sigen_wall_avg`).

Measure kinds mirrored here: `TimeMeasure` (wall + user/system CPU via
resource.getrusage, measure.go:54-143 + rtime.go:17-26), `CounterIO`
(delta of a Values() map), and single values. The TPU addition: kernel-time
counters flow through the same pipe (SURVEY.md §5.1).
"""

from __future__ import annotations

import asyncio
import json
import math
import resource
import time
from typing import Mapping


# -- node side: the sink client ---------------------------------------------


class Sink:
    """Fire-and-forget UDP JSON metric emitter (monitor.go ConnectSink)."""

    def __init__(self, addr: str):
        host, _, port = addr.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        import socket

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def record(self, name: str, values: Mapping[str, float]) -> None:
        payload = {"name": name, "values": {k: float(v) for k, v in values.items()}}
        try:
            self._sock.sendto(json.dumps(payload).encode(), self.addr)
        except OSError:
            pass

    def close(self) -> None:
        self._sock.close()


class TimeMeasure:
    """Wall + user/system CPU interval measure (measure.go:54-143)."""

    def __init__(self, sink: Sink, name: str):
        self.sink = sink
        self.name = name
        self._wall = time.perf_counter()
        ru = resource.getrusage(resource.RUSAGE_SELF)
        self._user, self._sys = ru.ru_utime, ru.ru_stime

    def record(self) -> None:
        ru = resource.getrusage(resource.RUSAGE_SELF)
        self.sink.record(
            self.name,
            {
                "wall": time.perf_counter() - self._wall,
                "user": ru.ru_utime - self._user,
                "system": ru.ru_stime - self._sys,
            },
        )


class CounterIO:
    """Delta-of-Values() measure (measure.go CounterMeasure): snapshot a
    reporter's counters at construction, record the difference.

    Keys ending in a GAUGE_SUFFIX are point-in-time ratios or levels (hit
    rates, launch occupancy, cache sizes, breaker state — e.g. the dedup
    plane's `dedupHitRate`/`dedupSize`, core/store.py VerifiedAggCache.values,
    and the verifier breaker's `breakerState`, parallel/batch_verifier.py):
    `now - base` is meaningless for a ratio whenever the construction-time
    snapshot is nonzero, so those are recorded as-is."""

    GAUGE_SUFFIXES = ("Rate", "Occupancy", "Size", "State")

    def __init__(self, sink: Sink, name: str, reporter):
        self.sink = sink
        self.name = name
        self.reporter = reporter
        self._base = dict(reporter.values())

    def record(self) -> None:
        now = self.reporter.values()
        self.sink.record(
            self.name,
            {
                k: (
                    v
                    if k.endswith(self.GAUGE_SUFFIXES)
                    else v - self._base.get(k, 0.0)
                )
                for k, v in now.items()
            },
        )


# -- master side: the sink server + stats ------------------------------------


class _SinkProto(asyncio.DatagramProtocol):
    def __init__(self, mon: "Monitor"):
        self.mon = mon

    def connection_made(self, transport):
        self.mon._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = json.loads(data.decode())
            name = str(msg["name"])
            values = msg["values"]
        except (ValueError, KeyError):
            return
        for k, v in values.items():
            self.mon.stats.update(f"{name}_{k}", float(v))


class Monitor:
    """UDP sink aggregating every node's measures (monitor.go:41-156)."""

    def __init__(self, port: int, data_filter: "DataFilter | None" = None):
        self.port = port
        self.stats = Stats(data_filter=data_filter)
        self._transport = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.create_datagram_endpoint(
            lambda: _SinkProto(self), local_addr=("0.0.0.0", self.port)
        )

    def stop(self) -> None:
        if self._transport:
            self._transport.close()


class DataFilter:
    """Percentile outlier filter applied per key before aggregation
    (stats.go DataFilter): for each configured key, keep only samples at or
    below that key's given percentile. Keys not configured pass through."""

    def __init__(self, percentiles: Mapping[str, float] | None = None):
        self.percentiles = dict(percentiles or {})

    def apply(self, key: str, values: list[float]) -> list[float]:
        pct = self.percentiles.get(key)
        if pct is None or not values:
            return values
        ordered = sorted(values)
        # nearest-rank: the ceil(n*pct/100)-th smallest value is the cut
        rank = max(1, math.ceil(len(ordered) * pct / 100.0))
        cut = ordered[min(len(ordered), rank) - 1]
        return [v for v in values if v <= cut]


class Stats:
    """Per-key streaming min/max/avg/sum/dev (stats.go:23-480)."""

    def __init__(
        self,
        extra: Mapping[str, float] | None = None,
        data_filter: DataFilter | None = None,
    ):
        self._keys: dict[str, list[float]] = {}
        self.extra = dict(extra or {})
        self.filter = data_filter or DataFilter()

    def update(self, key: str, value: float) -> None:
        self._keys.setdefault(key, []).append(value)

    def columns(self) -> list[str]:
        cols = sorted(self.extra)
        for key in sorted(self._keys):
            cols += [f"{key}_{s}" for s in ("min", "max", "avg", "sum", "dev")]
        return cols

    def row(self) -> list[float]:
        out = [self.extra[k] for k in sorted(self.extra)]
        for key in sorted(self._keys):
            vs = self.filter.apply(key, self._keys[key])
            n = len(vs)
            avg = sum(vs) / n
            dev = math.sqrt(sum((v - avg) ** 2 for v in vs) / n)
            out += [min(vs), max(vs), avg, sum(vs), dev]
        return out

    def write_csv(self, path: str, append: bool = False) -> None:
        import csv as _csv

        mode = "a" if append else "w"
        with open(path, mode, newline="") as f:
            w = _csv.writer(f)
            if not append:
                w.writerow(self.columns())
            w.writerow([f"{v:.6g}" for v in self.row()])

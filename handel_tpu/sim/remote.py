"""Multi-host simulation platform: ship, configure, start, collect.

Reference: simul/platform/aws.go:18-489 + simul/platform/aws/* — the
reference cross-compiles the node binary, ships binaries + configs (S3),
then SSH-configures and starts master and slaves across a fleet; nodes find
the master over DCN and the UDP sync barrier (simul/lib/sync.go:27-378)
coordinates the run. Terraform provisioning and the EC2 SDK are n/a here
(SURVEY.md §2.5); what this module keeps is the platform's JOB: given a
list of reachable hosts, deploy the package and run a distributed
simulation without any shared filesystem.

Host connectors:
  * ``local``  — this machine, via subprocesses. Deployment still goes
    through the tar ship path into a per-host staging dir, so CI exercises
    the exact multi-host flow with N "hosts" on localhost
    (localhost-as-remote; the reference tests its command builders the same
    way, simul/platform/aws/*_test.go).
  * ``ssh:<target>`` — a remote machine via ssh/OpenSSH. Shipping is
    `tar | ssh tar -x`; node processes stay attached to their ssh client so
    stdout/stderr stream back (the reference's exec-channel model,
    simul/platform/aws/sshController.go).

The orchestrator host runs the SyncMaster + Monitor in-process (the
reference's master binary role, simul/master/main.go) and writes the stats
CSV; remote nodes connect back over `master_ip`.

TOML:

    platform = "remote"          # or --platform remote on the CLI
    master_ip = "10.0.0.1"       # address nodes dial back to
    base_port = 21000            # node ports; 0 = probe (all-local only)
    [[hosts]]
    connect = "local"            # or "ssh:user@worker1"
    ip = "127.0.0.1"             # address other nodes dial this host's nodes
    python = "python3"
"""

from __future__ import annotations

import asyncio
import os
import re
import shlex
import sys
import tarfile
import tempfile

from handel_tpu.models.registry import is_device_scheme, new_scheme
from handel_tpu.sim import keys as simkeys
from handel_tpu.sim.allocator import new_allocator
from handel_tpu.sim.config import HostSpec, SimConfig, dump_config
from handel_tpu.sim.monitor import Monitor
from handel_tpu.sim.sync import STATE_END, STATE_START, SyncMaster


class HostConnector:
    """Transport to one host: ship files, run attached commands, kill."""

    def __init__(self, spec: HostSpec, staging: str):
        self.spec = spec
        self.staging = staging  # per-host working directory on the host

    async def ship(self, tar_path: str) -> None:
        raise NotImplementedError

    async def run(self, cmd: str) -> asyncio.subprocess.Process:
        raise NotImplementedError

    async def kill_pattern(self, pattern: str) -> None:
        raise NotImplementedError


class LocalConnector(HostConnector):
    """localhost-as-remote: same ship/run/kill contract via subprocesses."""

    async def ship(self, tar_path: str) -> None:
        await _check(
            await asyncio.create_subprocess_shell(
                f"mkdir -p {shlex.quote(self.staging)} && "
                f"tar -xzf {shlex.quote(tar_path)} -C {shlex.quote(self.staging)}"
            ),
            "local ship",
        )

    async def run(self, cmd: str) -> asyncio.subprocess.Process:
        # own session/process group: killing the wrapper shell alone would
        # orphan the python node process it spawned
        return await asyncio.create_subprocess_shell(
            f"cd {shlex.quote(self.staging)} && {cmd}",
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            start_new_session=True,
        )

    async def kill_pattern(self, pattern: str) -> None:
        p = await asyncio.create_subprocess_shell(
            f"pkill -f {shlex.quote(pattern)} 2>/dev/null; true"
        )
        await p.wait()


class SSHConnector(HostConnector):
    """OpenSSH transport (aws.go's sshController analog). BatchMode so a
    missing key fails fast instead of prompting."""

    SSH = "ssh -o BatchMode=yes -o StrictHostKeyChecking=accept-new"

    def __init__(self, spec: HostSpec, staging: str):
        super().__init__(spec, staging)
        self.target = spec.connect.split(":", 1)[1]

    def _remote(self, remote_cmd: str) -> str:
        """Local shell line running `remote_cmd` on the target: the remote
        command (already internally quoted) is quoted ONCE as a whole —
        hand-nesting quotes inside a single-quoted string breaks on any
        path that itself needs quoting."""
        q = shlex.quote
        return f"{self.SSH} {q(self.target)} {q(remote_cmd)}"

    async def ship(self, tar_path: str) -> None:
        q = shlex.quote
        remote = f"mkdir -p {q(self.staging)} && tar -xzf - -C {q(self.staging)}"
        await _check(
            await asyncio.create_subprocess_shell(
                f"cat {q(tar_path)} | {self._remote(remote)}"
            ),
            f"ssh ship to {self.target}",
        )

    async def run(self, cmd: str) -> asyncio.subprocess.Process:
        q = shlex.quote
        return await asyncio.create_subprocess_shell(
            self._remote(f"cd {q(self.staging)} && {cmd}"),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )

    async def kill_pattern(self, pattern: str) -> None:
        q = shlex.quote
        p = await asyncio.create_subprocess_shell(
            self._remote(f"pkill -f {q(pattern)} 2>/dev/null; true")
        )
        await p.wait()


def _kill_all(procs) -> None:
    """Kill each launcher's whole process group (LocalConnector starts new
    sessions, so pgid == pid covers the shell AND the node python under it;
    ssh launchers have no local children — the remote side is handled by
    kill_pattern)."""
    import signal

    for p in procs:
        if p.returncode is None:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                p.kill()


async def _check(proc: asyncio.subprocess.Process, what: str) -> None:
    rc = await proc.wait()
    if rc != 0:
        raise RuntimeError(f"{what} failed (rc={rc})")


def _connector(spec: HostSpec, staging: str) -> HostConnector:
    if spec.connect == "local":
        return LocalConnector(spec, staging)
    if spec.connect.startswith("ssh:"):
        return SSHConnector(spec, staging)
    raise ValueError(f"unknown host connector {spec.connect!r}")


def _pack_tree(workdir: str) -> str:
    """Tar the package source for shipping (the aws.go `pack` analog —
    Python ships source where Go shipped a cross-compiled binary)."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    tar_path = os.path.join(workdir, "handel_tpu_pkg.tar.gz")
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(
            os.path.join(repo, "handel_tpu"),
            arcname="handel_tpu",
            filter=lambda ti: None if "__pycache__" in ti.name else ti,
        )
        pj = os.path.join(repo, "pyproject.toml")
        if os.path.exists(pj):
            tf.add(pj, arcname="pyproject.toml")
    return tar_path


class RemotePlatform:
    """Drive one simulation across the configured host list.

    Mirrors the aws platform lifecycle (platform.go:15-89 doc:
    configure -> build -> cleanup -> deploy -> start -> wait): `configure`
    packs + ships the package once; each `start_run` ships that run's
    registry/config, starts node processes on every host, runs the barrier,
    and writes the stats CSV locally.
    """

    def __init__(self, cfg: SimConfig, workdir: str):
        if not cfg.hosts:
            raise ValueError(
                "platform=remote needs at least one [[hosts]] entry"
            )
        self.cfg = cfg
        self.dir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.config_path = os.path.join(workdir, "sim.toml")
        with open(self.config_path, "w") as f:
            f.write(dump_config(cfg))
        # default staging dirs carry the orchestrator pid: two concurrent
        # runs with same-basename workdirs must not clobber each other's
        # shipped package/registry
        run_tag = (
            f"{os.path.basename(os.path.normpath(workdir)) or 'run'}"
            f"_{os.getpid()}"
        )
        self.connectors = [
            _connector(
                h,
                h.workdir
                or os.path.join(
                    tempfile.gettempdir(), f"handel_tpu_remote_{run_tag}_{i}"
                ),
            )
            for i, h in enumerate(cfg.hosts)
        ]
        self._configured = False

    async def configure(self) -> None:
        """Pack once, ship to every host concurrently (aws.go:80-232)."""
        tar_path = _pack_tree(self.dir)
        await asyncio.gather(*(c.ship(tar_path) for c in self.connectors))
        self._configured = True

    async def _kill_everywhere(self, procs) -> None:
        """Kill the local client processes AND this run's remote nodes.
        Remote processes outlive their dead ssh client; the --tag (per-run
        staging dir, regex-escaped for pkill -f) scopes the kill to THIS
        run's nodes, not every simulation on a shared host."""
        _kill_all(procs)
        await asyncio.gather(
            *(
                c.kill_pattern(
                    f"handel_tpu[.]sim[.]node.*--tag {re.escape(c.staging)}"
                )
                for c in self.connectors
                if isinstance(c, SSHConnector)
            )
        )

    async def start_run(self, run_index: int):
        from handel_tpu.sim.platform import RunResult, free_ports, port_plan

        if not self._configured:
            await self.configure()
        cfg = self.cfg
        run = cfg.runs[run_index]
        hosts = cfg.hosts
        if is_device_scheme(cfg.scheme):
            from handel_tpu.utils.jaxenv import apply_platform_env

            apply_platform_env()
        scheme = new_scheme(cfg.scheme)

        # allocation: logical nodes round-robin over hosts ("instances"),
        # then over each host's processes (allocator.go:52-86)
        alloc = new_allocator(cfg.allocator).allocate(
            run.nodes, len(hosts), run.processes, run.failing
        )

        # addresses: every node advertised at its host's routable ip. With
        # base_port=0 (single-machine CI) ports are probed locally; a real
        # fleet sets base_port and the shared fixed plan applies
        # (platform.py port_plan: node i at base_port + i)
        if not cfg.base_port and any(h.connect != "local" for h in hosts):
            raise ValueError("base_port required with non-local hosts")
        ports, master_port, monitor_port, verifier_slot = port_plan(
            cfg, run.nodes
        )
        addresses = [
            f"{hosts[alloc[nid].instance].ip}:{ports[nid]}"
            for nid in range(run.nodes)
        ]

        # keygen -> registry CSV, shipped to every host (aws.go: S3 transfer)
        records = simkeys.generate_nodes(scheme, addresses)
        registry_name = f"registry_{run_index}.csv"
        registry_path = os.path.join(self.dir, registry_name)
        simkeys.write_registry_csv(registry_path, records)
        ship_tar = os.path.join(self.dir, f"run_{run_index}.tar.gz")
        with tarfile.open(ship_tar, "w:gz") as tf:
            tf.add(registry_path, arcname=registry_name)
            tf.add(self.config_path, arcname="sim.toml")
        await asyncio.gather(*(c.ship(ship_tar) for c in self.connectors))

        # batch-plane RPC (parallel/rpc_verifier.py): with a device-flagged
        # host and the shared verifier on a device scheme, exactly one
        # process on that host serves every other process's verification.
        # A fixed fleet uses the plan's base_port - 3 slot; otherwise the
        # port is probed on the orchestrator
        verifier_host_idx = next(
            (i for i, h in enumerate(hosts) if h.device), None
        )
        serve_verifier = (
            cfg.shared_verifier
            and is_device_scheme(cfg.scheme)
            and verifier_host_idx is not None
            and not cfg.baseline  # baseline runs never touch the verifier
        )
        verifier_port = (
            (verifier_slot or free_ports(1)[0]) if serve_verifier else 0
        )
        if serve_verifier and not any(
            alloc[nid].active and alloc[nid].instance == verifier_host_idx
            for nid in alloc
        ):
            raise ValueError(
                "device host has no active node process to serve the "
                "verifier from (all its nodes are failing)"
            )
        by_host_proc: dict[int, dict[int, list[int]]] = {}
        for nid, slot in alloc.items():
            if slot.active:
                by_host_proc.setdefault(slot.instance, {}).setdefault(
                    slot.process, []
                ).append(nid)
        active = sum(
            len(ids) for procs in by_host_proc.values() for ids in procs.values()
        )
        # both bind 0.0.0.0 (sim/sync.py, sim/monitor.py) so off-host nodes
        # can reach them at master_ip. Declared keys keep the CSV schema
        # stable when a degraded run records no samples (NaN + warning).
        monitor = Monitor(
            monitor_port,
            expected_keys=("sigen_wall", "sigs_sigCheckedCt", "net_sentPackets"),
        )
        await monitor.start()
        sync = SyncMaster(master_port, active)
        await sync.start()

        procs: list[asyncio.subprocess.Process] = []
        timed_out = False
        try:
            served = False
            for hidx, by_proc in sorted(by_host_proc.items()):
                conn = self.connectors[hidx]
                py = hosts[hidx].python or sys.executable
                for pidx, ids in sorted(by_proc.items()):
                    flags = (
                        f"--config sim.toml --registry {registry_name} "
                        f"--master {cfg.master_ip}:{master_port} "
                        f"--monitor {cfg.master_ip}:{monitor_port} "
                        f"--run {run_index} --ids {','.join(map(str, ids))} "
                        f"--tag {shlex.quote(conn.staging)}"
                    )
                    if cfg.trace:
                        # dumps land in the host's staging dir (node cwd);
                        # ssh hosts keep them host-side for manual fetch
                        flags += " --trace-dir ."
                    if serve_verifier:
                        if hidx == verifier_host_idx and not served:
                            flags += f" --serve-verifier {verifier_port}"
                            served = True
                        else:
                            flags += (
                                " --verifier "
                                f"{hosts[verifier_host_idx].ip}:{verifier_port}"
                            )
                    env = "PYTHONPATH=. "
                    if os.environ.get("HANDEL_TPU_PLATFORM"):
                        env += (
                            "HANDEL_TPU_PLATFORM="
                            f"{os.environ['HANDEL_TPU_PLATFORM']} "
                        )
                    procs.append(
                        await conn.run(
                            f"{env}{py} -m handel_tpu.sim.node {flags}"
                        )
                    )
            try:
                await sync.wait_all(STATE_START, cfg.max_timeout_s)
                await sync.wait_all(STATE_END, cfg.max_timeout_s)
            except asyncio.TimeoutError:
                timed_out = True
                await self._kill_everywhere(procs)
            try:
                # grace period: a node can pass the END barrier yet fail to
                # exit (stuck device teardown) — don't hang the run forever
                outs = await asyncio.wait_for(
                    asyncio.gather(*(p.communicate() for p in procs)),
                    timeout=60.0,
                )
            except asyncio.TimeoutError:
                timed_out = True
                await self._kill_everywhere(procs)
                outs = [(b"", b"")] * len(procs)
            rcs = [p.returncode for p in procs]
        finally:
            _kill_all(procs)
            sync.stop()
            monitor.stop()

        monitor.stats.extra = run.stats_extra(run_index)
        csv_path = os.path.join(self.dir, f"results_{run_index}.csv")
        monitor.stats.write_csv(csv_path)
        ok = (
            not timed_out
            and all(rc == 0 for rc in rcs)
            and all(b"finished OK" in out for out, _ in outs)
        )
        return RunResult(ok=ok, csv_path=csv_path, outputs=outs, returncodes=rcs)

"""Simulation TOML configuration.

Reference: simul/lib/config.go:41-344 — the global section (Network, Curve,
Encoding, Allocator, MonitorPort, Simulation, MaxTimeout, Retrials) plus a
`[[runs]]` matrix ({Nodes, Threshold, Failing, Processes, Handel{Period,
UpdateCount, NodeCount, Timeout, UnsafeSleepTimeOnSigVerify}}), the factory
methods, and `GetHandelConfig` bridging into the library Config
(simul/lib/config.go:290-319).

TPU additions: `scheme` ("fake"/"bn254"/"bn254-jax"), `batch_size` (device
launch width), `shared_verifier` (fuse co-located nodes' batches).
"""

from __future__ import annotations

import random

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: the tomli backport is the
    import tomli as tomllib  # same module under its pre-stdlib name

from dataclasses import dataclass, field

from handel_tpu.core.config import Config
from handel_tpu.network.chaos import ChaosConfig


@dataclass
class HandelParams:
    period_ms: float = 10.0
    update_count: int = 1
    fast_path: int = 10
    timeout_ms: float = 50.0
    unsafe_sleep_verify_ms: int = 0
    # verification strategy sweep axis (HandelConfig.Evaluator + the
    # confgenerator's `evaluator` scenario): "store" (score by the store),
    # "eval1" (verify everything), "fifo" (arrival order, no scoring)
    evaluator: str = "store"

    def to_config(self, threshold: int, seed: int) -> Config:
        c = Config()
        c.update_period = self.period_ms / 1000.0
        c.update_count = self.update_count
        c.fast_path = self.fast_path
        c.level_timeout = self.timeout_ms / 1000.0
        c.unsafe_sleep_on_verify_ms = self.unsafe_sleep_verify_ms
        c.contributions = threshold
        c.rand = random.Random(seed)
        if self.evaluator == "eval1":
            from handel_tpu.core.processing import Evaluator1

            c.new_evaluator = lambda store, h: Evaluator1()
        elif self.evaluator == "fifo":
            from handel_tpu.core.processing import FifoProcessing

            c.new_processing = FifoProcessing
        elif self.evaluator != "store":
            raise ValueError(f"unknown evaluator {self.evaluator!r}")
        return c


@dataclass
class AdversaryParams:
    """Byzantine roles per run (sim/adversary.py): how many nodes play each
    role, assigned deterministically to the highest non-offline ids."""

    invalid_signer: int = 0
    stale_replayer: int = 0
    flooder: int = 0
    flood_pps: float = 200.0
    # dynamic membership (scenario engine): nodes that participate honestly
    # then DEPART mid-run, triggering survivor re-leveling + threshold
    # re-evaluation (Handel.mark_departed)
    churner: int = 0
    churn_after_ms: float = 500.0

    def total(self) -> int:
        return (
            self.invalid_signer
            + self.stale_replayer
            + self.flooder
            + self.churner
        )

    def counts(self) -> dict[str, int]:
        return {
            "invalid_signer": self.invalid_signer,
            "stale_replayer": self.stale_replayer,
            "flooder": self.flooder,
            "churner": self.churner,
        }


@dataclass
class RunConfig:
    nodes: int = 8
    threshold: int = 0  # 0 -> default percentage
    failing: int = 0
    processes: int = 1
    handel: HandelParams = field(default_factory=HandelParams)
    adversaries: AdversaryParams = field(default_factory=AdversaryParams)

    def resolved_threshold(self) -> int:
        if self.threshold > 0:
            return self.threshold
        from handel_tpu.core.config import (
            DEFAULT_CONTRIBUTIONS_PERC,
            percentage_to_contributions,
        )

        return percentage_to_contributions(DEFAULT_CONTRIBUTIONS_PERC, self.nodes)

    def stats_extra(self, run_index: int) -> dict[str, float]:
        """Per-run identity + swept protocol knobs for the stats CSV, so
        parameter-sweep captures are self-describing (the reference embeds
        the lib.Config fields the same way). Shared by both platforms."""
        return {
            "run": float(run_index),
            "nodes": float(self.nodes),
            "threshold": float(self.resolved_threshold()),
            "failing": float(self.failing),
            "adversaries": float(self.adversaries.total()),
            "period_ms": float(self.handel.period_ms),
            "timeout_ms": float(self.handel.timeout_ms),
            "update_count": float(self.handel.update_count),
        }


@dataclass
class ServiceParams:
    """`[service]` section: the multi-tenant aggregation service
    (handel_tpu/service/). sessions = 0 keeps service mode off; `sim
    serve` requires it > 0. Each of `sessions` concurrent aggregation
    instances runs `nodes` logical Handel nodes over its own committee,
    all multiplexed onto one shared BatchVerifierService per process."""

    sessions: int = 0
    nodes: int = 16
    threshold: int = 0  # 0 -> default percentage of `nodes`
    processes: int = 1  # worker node-processes the sessions shard over
    devices: int = 1  # verifier plane lanes (DevicePlane) per process
    mesh_devices: int = 0  # whole-mesh latency lane width (parallel/
    # mesh_plane.py); 0 -> no mesh lane, dual-mode scheduling off
    mesh_batch_size: int = 8  # the mesh lane's (small) launch width
    max_sessions: int = 0  # live-session admission cap; 0 -> `sessions`
    session_ttl_s: float = 60.0  # running session expiry deadline
    quantum: int = 8  # DRR lane credits per tenant ring visit
    max_pending_per_session: int = 4096  # per-tenant verifier queue bound
    queue_capacity: int = 0  # global SLO shed bound (fairness.py); 0 -> off,
    # leaving the flat per-session bound above as the only admission control
    tiers: str = ""  # comma-separated SLO tier cycle assigned to sessions
    # round-robin, e.g. "gold,bronze" (fairness.py TIERS); "" -> untiered
    batch_size: int = 0  # shared-launch lanes; 0 -> global batch_size
    spawn_stagger_ms: float = 0.0  # delay between session spawns
    period_ms: float = 10.0  # gossip period of the session nodes
    fp_backend: str = ""  # Field modmul kernel for the service's verify
    # plane ("cios"/"rns", ops/fp.py backend seam); "" -> global fp_backend
    batch_check: str = "per_candidate"  # verifier check mode: "per_candidate"
    # (one pairing check per lane) or "rlc" (random-linear-combination
    # combined check with bisection fallback, models/rlc.py)

    def enabled(self) -> bool:
        return self.sessions > 0


@dataclass
class SoakParams:
    """`[soak]` section: the lifecycle soak harness (sim/soak.py,
    `python -m handel_tpu.sim soak`). Defaults are the ~90 s CI shape:
    sustained tiered load on a 2-lane host plane with a mid-run epoch swap
    at 40% and a forced lane-0 loss at 60% of the run."""

    duration_s: float = 90.0  # load window (drain tail rides on top)
    nodes: int = 16  # Handel nodes per session
    concurrency: int = 8  # sessions held live by the spawner
    devices: int = 2  # starting verify-plane lanes
    max_lanes: int = 4  # LaneAutoscaler ceiling
    batch_size: int = 64  # shared-launch width
    queue_capacity: int = 4096  # global SLO shed bound (fairness.py)
    session_ttl_s: float = 60.0  # per-session expiry (an expiry = a drop)
    tiers: str = "gold,silver,bronze,standard"  # round-robin SLO cycle
    period_ms: float = 5.0  # session node gossip period
    registry: int = 256  # rotated validator-set size (epoch swap payload)
    swap_at_frac: float = 0.4  # epoch rotation point, fraction of duration
    lane_loss_at_frac: float = 0.6  # forced lane-0 breaker-open point
    control_interval_s: float = 0.25  # LifecycleController tick
    autotune_every_s: float = 5.0  # critical-path recompute throttle
    trace_capacity: int = 1 << 17  # flight-recorder ring (events)


@dataclass
class LoadParams:
    """`[load]` section: the open-loop arrival generator (sim/load.py,
    `python -m handel_tpu.sim load`). rate_sps = 0 keeps load mode off.

    Unlike `[service]`/`[soak]` (closed-loop: the harness back-fills on
    completion), sessions arrive on a seeded Poisson/diurnal/burst clock
    whether or not the federation keeps up — open-loop p50/p99 and
    goodput against `deadline_s` are the first-class metrics."""

    rate_sps: float = 0.0  # mean session arrivals per second; 0 -> off
    duration_s: float = 60.0  # arrival window (drain tail rides on top)
    model: str = "poisson"  # arrival process: poisson | diurnal | burst
    seed: int = 0  # arrival clock + origin sampling seed
    nodes: int = 8  # Handel committee size per arriving session
    deadline_s: float = 5.0  # per-session arrival->verdict deadline
    # (goodput = completions inside it / arrivals)
    tiers: str = "gold,silver,bronze,standard"  # round-robin SLO cycle
    # -- diurnal model: rate * (1 + amplitude*sin(2*pi*t/period)) --------
    diurnal_amplitude: float = 0.5  # peak swing as a fraction of the mean
    diurnal_period_s: float = 30.0  # one day, compressed
    # -- burst model: rate * burst_x inside each burst window ------------
    burst_every_s: float = 10.0  # burst cadence
    burst_x: float = 4.0  # rate multiplier inside a burst
    burst_len_s: float = 2.0  # burst width

    def enabled(self) -> bool:
        return self.rate_sps > 0


@dataclass
class FederationParams:
    """`[federation]` section: the geo-federated service plane the load
    generator drives (service/federation.py). One MultiSessionCluster per
    region of the `planet` preset; a front door routes each arrival to
    the nearest healthy region by RTT, spilling over on shed/death."""

    planet: str = "planet-3region"  # scenario/planets.py preset
    geo_seed: int = 0
    devices: int = 1  # verify-plane lanes per region cluster
    batch_size: int = 32  # shared-launch width per region
    queue_capacity: int = 512  # per-region SLO shed bound (fairness.py)
    max_sessions: int = 64  # per-region live-session admission cap
    session_ttl_s: float = 30.0  # per-session expiry inside a region
    period_ms: float = 5.0  # session node gossip period
    probe_interval_s: float = 0.25  # front-door health probe cadence
    # capped exponential backoff when EVERY region refuses an arrival:
    # min(retry_cap_ms, retry_base_ms * 2^attempt), retry_budget attempts
    retry_base_ms: float = 50.0
    retry_cap_ms: float = 500.0
    retry_budget: int = 4
    registry: int = 64  # validator-set size staged on region rejoin
    shed_ceiling: float = 0.15  # acceptance bound on the global shed rate
    # -- chaos: scheduled mid-run region kill + recovery -----------------
    kill_region: str = ""  # region name; "" -> no kill drill
    kill_at_frac: float = 0.35  # of the load window
    recover_at_frac: float = 0.65
    trace_capacity: int = 1 << 17  # flight-recorder ring (events)


@dataclass
class AlertParams:
    """`[alerts]` section: the detection-and-incident plane (handel_tpu/
    obs/). Rides every harness that carries a control loop — `sim load`
    ticks it beside the federation, `sim soak` through the
    LifecycleController, `sim serve` beside its metrics registry. All
    knobs default to the production shape; `window_scale` compresses the
    burn windows so a 45 s drill exercises the same multi-window math a
    30-day SLO would."""

    enabled: bool = True
    # burn-rate evaluation (obs/slo.py): fast/slow window pair, scaled
    fast_window_s: float = 60.0
    slow_window_s: float = 900.0
    window_scale: float = 1.0
    page_x: float = 14.4  # page when BOTH windows burn >= this multiple
    warn_x: float = 6.0
    goodput_slo: float = 0.95  # deadline-met fraction the goodput rule holds
    # anomaly detection (obs/detect.py)
    z_threshold: float = 6.0
    ewma_alpha: float = 0.3
    min_consecutive: int = 1  # anomalous ticks before a series fires
    seed: int = 0  # MAD frugal-sketch coin-flip stream
    # incident lifecycle (obs/incidents.py): flap suppression pair
    min_hold_s: float = 2.0  # quiet time required before close
    cooldown_s: float = 5.0  # refire inside this reopens, not re-mints
    tick_interval_s: float = 0.25  # evaluation cadence
    # hierarchical roll-ups (obs/rollup.py): per-host digests -> fleet
    series_cap: int = 0  # labeled-family cardinality cap (0 = uncapped)
    rollup_top_k: int = 8  # anomalous series carried per host digest
    rollup_interval_s: float = 1.0  # host digest emit cadence
    rollup_stale_s: float = 5.0  # host counts as down after this silence


@dataclass
class SwarmParams:
    """`[swarm]` section: the virtual-node runtime (handel_tpu/swarm/).

    One committee of `identities` members, every member a co-resident
    virtual node, sharded over `processes` worker processes in contiguous
    ID blocks. identities = 0 keeps swarm mode off; `sim swarm` requires
    it > 0. The fake scheme is implied — swarm scale is a host-runtime
    experiment, not a pairing benchmark (the verify plane still runs
    through the shared BatchVerifierService so the launch path is real).
    """

    identities: int = 0
    processes: int = 1
    threshold: int = 0  # 0 -> default percentage of `identities`
    period_ms: float = 2000.0  # vnode gossip period. The in-memory router is
    # lossless and candidate order is id-staggered, so the fast-path cascade
    # alone covers every level deterministically; gossip is a repair net, and
    # every period costs ~identities × active-levels deliveries of CPU.
    timeout_ms: float = 50.0  # level-start timeout per vnode
    fast_path: int = 3  # completed-level burst fanout. With id-staggered
    # candidate order each peer receives exactly this many copies per level,
    # so it is the redundancy factor of the wave (10, the WAN default, just
    # multiplies single-core CPU by 3x for no extra coverage)
    tick_ms: float = 10.0  # TimerWheel resolution
    batch_size: int = 64  # shared verifier launch width
    max_pending: int = 256  # per-vnode unverified-candidate bound
    chunk_bits: int = 12  # registry pager chunk = 2^chunk_bits identities
    page_budget: int = 64  # resident chunks per process
    timeout_s: float = 0.0  # run deadline; 0 -> global max_timeout_s

    def enabled(self) -> bool:
        return self.identities > 0


@dataclass
class ScenarioParams:
    """`[scenario]` section: the WAN scenario engine (handel_tpu/scenario/).

    One declarative knob set composing three orthogonal axes on top of any
    run: a geo-latency planet model (GeoNetwork region RTT matrices), stake
    weights (weighted thresholds in core/handel.py), and join-side dynamic
    membership (epoch-staged registry admission). Departure-side churn
    rides the existing adversary machinery (`[runs.adversaries] churner`).
    All axes default off; a `[scenario]` with only `weight_profile =
    "count"` activates the weighted code path with all-1.0 weights — by
    construction bit-for-bit identical to the count threshold."""

    name: str = ""  # label stamped into reports/captures
    # -- geo planet model: a named preset (scenario/planets.py) OR an
    # inline regions + rtt_ms matrix; preset wins when both are set ------
    planet: str = ""
    regions: list[str] = field(default_factory=list)
    rtt_ms: list[list[float]] = field(default_factory=list)
    jitter_ms: float = 0.0  # per-hop Gaussian jitter (std dev, ms)
    geo_seed: int = 0
    # -- dynamic membership: join-side admissions through the epoch path
    # (lifecycle/epoch.py stage_registry/activate_staged) ----------------
    joins: int = 0
    join_at_frac: float = 0.5  # of the run window (scenario engine)
    # -- stake weights: per-identity weight profile (scenario/weights.py);
    # "" = count threshold (weighted path off) ---------------------------
    weight_profile: str = ""  # "" | count | linear | pareto | split
    weight_seed: int = 0
    # weighted threshold as a fraction of total stake; 0 -> derive the
    # same fraction the count threshold is of the node count
    weight_threshold_frac: float = 0.0

    def geo_enabled(self) -> bool:
        return bool(self.planet or self.regions)

    def weights_enabled(self) -> bool:
        return bool(self.weight_profile)

    def enabled(self) -> bool:
        return self.geo_enabled() or self.weights_enabled() or self.joins > 0

    def geo_config(self):
        """Resolve preset/inline matrix into a validated GeoConfig
        (region placement derives per node via .for_node)."""
        from handel_tpu.network.geo import GeoConfig
        from handel_tpu.scenario.planets import planet_preset

        if self.planet:
            regions, rtt = planet_preset(self.planet)
        else:
            regions, rtt = list(self.regions), [list(r) for r in self.rtt_ms]
        return GeoConfig(
            regions=regions,
            rtt_ms=rtt,
            jitter_ms=self.jitter_ms,
            seed=self.geo_seed,
        ).validate()

    def make_weights(self, n: int):
        from handel_tpu.scenario.weights import make_weights

        return make_weights(self.weight_profile, n, seed=self.weight_seed)

    def weight_threshold(self, count_threshold: int, n: int, weights) -> float:
        total = float(sum(weights))
        if self.weight_threshold_frac > 0.0:
            return self.weight_threshold_frac * total
        # same fraction of stake as the count threshold is of the node
        # count — all-1.0 weights make this exactly `count_threshold`
        return count_threshold * total / n


@dataclass
class HostSpec:
    """One host of the remote platform's fleet (sim/remote.py; the analog
    of an aws.go instance entry)."""

    connect: str = "local"  # "local" | "ssh:<user@host>"
    ip: str = "127.0.0.1"  # address other nodes dial this host's nodes at
    python: str = ""  # remote python executable ("" = this interpreter)
    workdir: str = ""  # staging dir on the host ("" = per-host tmp dir)
    # this host holds the accelerator: with shared_verifier + a device
    # scheme, one process here serves the batch plane over TCP and every
    # chip-less process in the fleet verifies through it
    # (parallel/rpc_verifier.py)
    device: bool = False


@dataclass
class SimConfig:
    network: str = "udp"  # udp | tcp | inproc
    scheme: str = "bn254"
    allocator: str = "round-robin"
    monitor_port: int = 0  # 0 -> pick free
    max_timeout_s: float = 60.0
    retrials: int = 1
    batch_size: int = 16
    shared_verifier: bool = False
    # device-mesh width for the verification plane (>1 = sharded kernels;
    # on chip-less hosts virtual CPU devices are forced to this count)
    mesh_devices: int = 1
    # Field modmul kernel for device schemes: "cios" (VPU Pallas kernel) or
    # "rns" (residue-number-system MXU pipeline, ops/rns.py); plumbed
    # node -> new_scheme -> models/*_jax.py -> ops/curve.py -> ops/fp.py
    fp_backend: str = "cios"
    # With fp_backend = "rns": keep pairing values resident as residue
    # planes across the Miller loop / final exponentiation, reconstructing
    # through the CRT only at line boundaries (ops/pairing.py). Ignored by
    # "cios". `true` is the optimized default; `false` forces the legacy
    # per-mul round-trip form for debugging.
    rns_resident: bool = True
    debug: bool = False
    # live telemetry plane (core/metrics.py): every node process serves
    # /metrics + /healthz + /readyz on its own port (allocated by the
    # platform, written to <workdir>/metrics_ports.json); `metrics = false`
    # keeps the plane fully off — zero threads, zero sockets
    metrics: bool = False
    # seconds a node keeps its metrics endpoint up after the END barrier so
    # scrapers (`sim watch`, Prometheus) catch the final counter state of a
    # short run; 0 = exit immediately
    metrics_linger_s: float = 0.0
    # span tracing (core/trace.py): node processes record a per-contribution
    # flight recorder and dump Chrome trace_event JSON into the run's
    # trace dir; analyze with `python -m handel_tpu.sim trace <dir>`
    trace: bool = False
    # flight-recorder ring capacity (events per process)
    trace_capacity: int = 1 << 16
    # "" = Handel; "nsquare" / "gossipsub" select the comparison baselines
    # (simul/p2p; here handel_tpu/baselines/gossip.py)
    baseline: str = ""
    # -- fault injection (network/chaos.py): applied to every node's
    # transport when any rate is nonzero; seeds derive per node ------------
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    # -- multi-tenant service (handel_tpu/service/; `sim serve`) -----------
    service: ServiceParams = field(default_factory=ServiceParams)
    # -- lifecycle soak harness (sim/soak.py; `sim soak`) ------------------
    soak: SoakParams = field(default_factory=SoakParams)
    # -- open-loop load generator (sim/load.py; `sim load`) ----------------
    load: LoadParams = field(default_factory=LoadParams)
    # -- geo federation the load drives (service/federation.py) ------------
    federation: FederationParams = field(default_factory=FederationParams)
    # -- SLO alerting + incident plane (handel_tpu/obs/) -------------------
    alerts: AlertParams = field(default_factory=AlertParams)
    # -- virtual-node swarm (handel_tpu/swarm/; `sim swarm`) ---------------
    swarm: SwarmParams = field(default_factory=SwarmParams)
    # -- WAN scenario engine (handel_tpu/scenario/; `sim scenario`) --------
    scenario: ScenarioParams = field(default_factory=ScenarioParams)
    # -- remote platform (sim/remote.py; aws.go analog) --------------------
    hosts: list[HostSpec] = field(default_factory=list)
    master_ip: str = "127.0.0.1"  # address remote nodes dial the master at
    base_port: int = 0  # node port base; 0 = probe locally (all-local only)
    runs: list[RunConfig] = field(default_factory=list)


def load_config(path: str) -> SimConfig:
    with open(path, "rb") as f:
        raw = tomllib.load(f)
    cfg = SimConfig(
        network=raw.get("network", "udp"),
        scheme=raw.get("scheme", raw.get("curve", "bn254")),
        allocator=raw.get("allocator", "round-robin"),
        monitor_port=int(raw.get("monitor_port", 0)),
        max_timeout_s=float(raw.get("max_timeout_s", 60.0)),
        retrials=int(raw.get("retrials", 1)),
        batch_size=int(raw.get("batch_size", 16)),
        shared_verifier=bool(raw.get("shared_verifier", False)),
        mesh_devices=int(raw.get("mesh_devices", 1)),
        fp_backend=str(raw.get("fp_backend", "cios")),
        rns_resident=bool(raw.get("rns_resident", True)),
        debug=bool(raw.get("debug", False)),
        metrics=bool(raw.get("metrics", False)),
        metrics_linger_s=float(raw.get("metrics_linger_s", 0.0)),
        trace=bool(raw.get("trace", False)),
        trace_capacity=int(raw.get("trace_capacity", 1 << 16)),
        baseline=str(raw.get("baseline", "")),
        master_ip=str(raw.get("master_ip", "127.0.0.1")),
        base_port=int(raw.get("base_port", 0)),
    )
    ch = raw.get("chaos", {})
    cfg.chaos = ChaosConfig(
        drop_rate=float(ch.get("drop_rate", 0.0)),
        corrupt_rate=float(ch.get("corrupt_rate", 0.0)),
        duplicate_rate=float(ch.get("duplicate_rate", 0.0)),
        reorder_rate=float(ch.get("reorder_rate", 0.0)),
        delay_rate=float(ch.get("delay_rate", 0.0)),
        delay_ms=float(ch.get("delay_ms", 0.0)),
        delay_jitter_ms=float(ch.get("delay_jitter_ms", 0.0)),
        seed=int(ch.get("seed", 0)),
    ).validate()
    sv = raw.get("service", {})
    cfg.service = ServiceParams(
        sessions=int(sv.get("sessions", 0)),
        nodes=int(sv.get("nodes", 16)),
        threshold=int(sv.get("threshold", 0)),
        processes=int(sv.get("processes", 1)),
        devices=int(sv.get("devices", 1)),
        mesh_devices=int(sv.get("mesh_devices", 0)),
        mesh_batch_size=int(sv.get("mesh_batch_size", 8)),
        max_sessions=int(sv.get("max_sessions", 0)),
        session_ttl_s=float(sv.get("session_ttl_s", 60.0)),
        quantum=int(sv.get("quantum", 8)),
        max_pending_per_session=int(sv.get("max_pending_per_session", 4096)),
        queue_capacity=int(sv.get("queue_capacity", 0)),
        tiers=str(sv.get("tiers", "")),
        batch_size=int(sv.get("batch_size", 0)),
        spawn_stagger_ms=float(sv.get("spawn_stagger_ms", 0.0)),
        period_ms=float(sv.get("period_ms", 10.0)),
        fp_backend=str(sv.get("fp_backend", "")),
        batch_check=str(sv.get("batch_check", "per_candidate")),
    )
    if cfg.fp_backend not in ("cios", "rns") or cfg.service.fp_backend not in (
        "", "cios", "rns",
    ):
        raise ValueError(
            f"fp_backend must be one of 'cios', 'rns', got "
            f"{cfg.fp_backend!r} / service {cfg.service.fp_backend!r} "
            "(the 'rns' backend additionally honours the boolean "
            "`rns_resident` knob for residue-resident pairing)"
        )
    if cfg.service.batch_check not in ("per_candidate", "rlc"):
        raise ValueError(
            "service.batch_check must be one of 'per_candidate', 'rlc', got "
            f"{cfg.service.batch_check!r}"
        )
    so = raw.get("soak", {})
    cfg.soak = SoakParams(
        duration_s=float(so.get("duration_s", 90.0)),
        nodes=int(so.get("nodes", 16)),
        concurrency=int(so.get("concurrency", 8)),
        devices=int(so.get("devices", 2)),
        max_lanes=int(so.get("max_lanes", 4)),
        batch_size=int(so.get("batch_size", 64)),
        queue_capacity=int(so.get("queue_capacity", 4096)),
        session_ttl_s=float(so.get("session_ttl_s", 60.0)),
        tiers=str(so.get("tiers", "gold,silver,bronze,standard")),
        period_ms=float(so.get("period_ms", 5.0)),
        registry=int(so.get("registry", 256)),
        swap_at_frac=float(so.get("swap_at_frac", 0.4)),
        lane_loss_at_frac=float(so.get("lane_loss_at_frac", 0.6)),
        control_interval_s=float(so.get("control_interval_s", 0.25)),
        autotune_every_s=float(so.get("autotune_every_s", 5.0)),
        trace_capacity=int(so.get("trace_capacity", 1 << 17)),
    )
    lo = raw.get("load", {})
    cfg.load = LoadParams(
        rate_sps=float(lo.get("rate_sps", 0.0)),
        duration_s=float(lo.get("duration_s", 60.0)),
        model=str(lo.get("model", "poisson")),
        seed=int(lo.get("seed", 0)),
        nodes=int(lo.get("nodes", 8)),
        deadline_s=float(lo.get("deadline_s", 5.0)),
        tiers=str(lo.get("tiers", "gold,silver,bronze,standard")),
        diurnal_amplitude=float(lo.get("diurnal_amplitude", 0.5)),
        diurnal_period_s=float(lo.get("diurnal_period_s", 30.0)),
        burst_every_s=float(lo.get("burst_every_s", 10.0)),
        burst_x=float(lo.get("burst_x", 4.0)),
        burst_len_s=float(lo.get("burst_len_s", 2.0)),
    )
    if cfg.load.model not in ("poisson", "diurnal", "burst"):
        raise ValueError(
            "load.model must be one of 'poisson', 'diurnal', 'burst', got "
            f"{cfg.load.model!r}"
        )
    if not 0.0 <= cfg.load.diurnal_amplitude < 1.0:
        raise ValueError(
            "load.diurnal_amplitude must be in [0, 1) — the rate must stay "
            f"positive, got {cfg.load.diurnal_amplitude}"
        )
    fe = raw.get("federation", {})
    cfg.federation = FederationParams(
        planet=str(fe.get("planet", "planet-3region")),
        geo_seed=int(fe.get("geo_seed", 0)),
        devices=int(fe.get("devices", 1)),
        batch_size=int(fe.get("batch_size", 32)),
        queue_capacity=int(fe.get("queue_capacity", 512)),
        max_sessions=int(fe.get("max_sessions", 64)),
        session_ttl_s=float(fe.get("session_ttl_s", 30.0)),
        period_ms=float(fe.get("period_ms", 5.0)),
        probe_interval_s=float(fe.get("probe_interval_s", 0.25)),
        retry_base_ms=float(fe.get("retry_base_ms", 50.0)),
        retry_cap_ms=float(fe.get("retry_cap_ms", 500.0)),
        retry_budget=int(fe.get("retry_budget", 4)),
        registry=int(fe.get("registry", 64)),
        shed_ceiling=float(fe.get("shed_ceiling", 0.15)),
        kill_region=str(fe.get("kill_region", "")),
        kill_at_frac=float(fe.get("kill_at_frac", 0.35)),
        recover_at_frac=float(fe.get("recover_at_frac", 0.65)),
        trace_capacity=int(fe.get("trace_capacity", 1 << 17)),
    )
    if cfg.federation.retry_base_ms <= 0 or (
        cfg.federation.retry_cap_ms < cfg.federation.retry_base_ms
    ):
        raise ValueError(
            "federation retry backoff needs retry_base_ms > 0 and "
            f"retry_cap_ms >= retry_base_ms, got base "
            f"{cfg.federation.retry_base_ms} / cap "
            f"{cfg.federation.retry_cap_ms}"
        )
    if cfg.federation.kill_region and not (
        0.0 < cfg.federation.kill_at_frac
        < cfg.federation.recover_at_frac <= 1.0
    ):
        raise ValueError(
            "federation kill drill needs 0 < kill_at_frac < recover_at_frac "
            f"<= 1, got kill {cfg.federation.kill_at_frac} / recover "
            f"{cfg.federation.recover_at_frac}"
        )
    al = raw.get("alerts", {})
    cfg.alerts = AlertParams(
        enabled=bool(al.get("enabled", True)),
        fast_window_s=float(al.get("fast_window_s", 60.0)),
        slow_window_s=float(al.get("slow_window_s", 900.0)),
        window_scale=float(al.get("window_scale", 1.0)),
        page_x=float(al.get("page_x", 14.4)),
        warn_x=float(al.get("warn_x", 6.0)),
        goodput_slo=float(al.get("goodput_slo", 0.95)),
        z_threshold=float(al.get("z_threshold", 6.0)),
        ewma_alpha=float(al.get("ewma_alpha", 0.3)),
        min_consecutive=int(al.get("min_consecutive", 1)),
        seed=int(al.get("seed", 0)),
        min_hold_s=float(al.get("min_hold_s", 2.0)),
        cooldown_s=float(al.get("cooldown_s", 5.0)),
        tick_interval_s=float(al.get("tick_interval_s", 0.25)),
        series_cap=int(al.get("series_cap", 0)),
        rollup_top_k=int(al.get("rollup_top_k", 8)),
        rollup_interval_s=float(al.get("rollup_interval_s", 1.0)),
        rollup_stale_s=float(al.get("rollup_stale_s", 5.0)),
    )
    if cfg.alerts.fast_window_s >= cfg.alerts.slow_window_s:
        raise ValueError(
            "alerts needs fast_window_s < slow_window_s, got fast "
            f"{cfg.alerts.fast_window_s} / slow {cfg.alerts.slow_window_s}"
        )
    if cfg.alerts.warn_x >= cfg.alerts.page_x:
        raise ValueError(
            "alerts needs warn_x < page_x, got warn "
            f"{cfg.alerts.warn_x} / page {cfg.alerts.page_x}"
        )
    if not 0.0 < cfg.alerts.goodput_slo < 1.0:
        raise ValueError(
            "alerts.goodput_slo must be in (0, 1), got "
            f"{cfg.alerts.goodput_slo}"
        )
    if cfg.alerts.window_scale <= 0.0 or cfg.alerts.tick_interval_s <= 0.0:
        raise ValueError(
            "alerts needs window_scale > 0 and tick_interval_s > 0, got "
            f"scale {cfg.alerts.window_scale} / tick "
            f"{cfg.alerts.tick_interval_s}"
        )
    if cfg.alerts.min_hold_s < 0.0 or cfg.alerts.cooldown_s < 0.0:
        raise ValueError(
            "alerts needs min_hold_s >= 0 and cooldown_s >= 0, got "
            f"hold {cfg.alerts.min_hold_s} / cooldown "
            f"{cfg.alerts.cooldown_s}"
        )
    if cfg.alerts.series_cap < 0:
        raise ValueError(
            f"alerts.series_cap must be >= 0, got {cfg.alerts.series_cap}"
        )
    if cfg.alerts.rollup_top_k < 1:
        raise ValueError(
            f"alerts.rollup_top_k must be >= 1, got {cfg.alerts.rollup_top_k}"
        )
    if cfg.alerts.rollup_interval_s <= 0.0 or cfg.alerts.rollup_stale_s <= 0.0:
        raise ValueError(
            "alerts needs rollup_interval_s > 0 and rollup_stale_s > 0, got "
            f"interval {cfg.alerts.rollup_interval_s} / stale "
            f"{cfg.alerts.rollup_stale_s}"
        )
    sc = raw.get("scenario", {})
    cfg.scenario = ScenarioParams(
        name=str(sc.get("name", "")),
        planet=str(sc.get("planet", "")),
        regions=[str(x) for x in sc.get("regions", [])],
        rtt_ms=[[float(v) for v in row] for row in sc.get("rtt_ms", [])],
        jitter_ms=float(sc.get("jitter_ms", 0.0)),
        geo_seed=int(sc.get("geo_seed", 0)),
        joins=int(sc.get("joins", 0)),
        join_at_frac=float(sc.get("join_at_frac", 0.5)),
        weight_profile=str(sc.get("weight_profile", "")),
        weight_seed=int(sc.get("weight_seed", 0)),
        weight_threshold_frac=float(sc.get("weight_threshold_frac", 0.0)),
    )
    sw = raw.get("swarm", {})
    cfg.swarm = SwarmParams(
        identities=int(sw.get("identities", 0)),
        processes=int(sw.get("processes", 1)),
        threshold=int(sw.get("threshold", 0)),
        period_ms=float(sw.get("period_ms", 2000.0)),
        timeout_ms=float(sw.get("timeout_ms", 50.0)),
        fast_path=int(sw.get("fast_path", 3)),
        tick_ms=float(sw.get("tick_ms", 10.0)),
        batch_size=int(sw.get("batch_size", 64)),
        max_pending=int(sw.get("max_pending", 256)),
        chunk_bits=int(sw.get("chunk_bits", 12)),
        page_budget=int(sw.get("page_budget", 64)),
        timeout_s=float(sw.get("timeout_s", 0.0)),
    )
    for h in raw.get("hosts", []):
        cfg.hosts.append(
            HostSpec(
                connect=str(h.get("connect", "local")),
                ip=str(h.get("ip", "127.0.0.1")),
                python=str(h.get("python", "")),
                workdir=str(h.get("workdir", "")),
                device=bool(h.get("device", False)),
            )
        )
    for r in raw.get("runs", []):
        h = r.get("handel", {})
        a = r.get("adversaries", {})
        cfg.runs.append(
            RunConfig(
                nodes=int(r.get("nodes", 8)),
                threshold=int(r.get("threshold", 0)),
                failing=int(r.get("failing", 0)),
                processes=int(r.get("processes", 1)),
                adversaries=AdversaryParams(
                    invalid_signer=int(a.get("invalid_signer", 0)),
                    stale_replayer=int(a.get("stale_replayer", 0)),
                    flooder=int(a.get("flooder", 0)),
                    flood_pps=float(a.get("flood_pps", 200.0)),
                    churner=int(a.get("churner", 0)),
                    churn_after_ms=float(a.get("churn_after_ms", 500.0)),
                ),
                handel=HandelParams(
                    period_ms=float(h.get("period_ms", 10.0)),
                    update_count=int(h.get("update_count", 1)),
                    fast_path=int(h.get("fast_path", 10)),
                    timeout_ms=float(h.get("timeout_ms", 50.0)),
                    unsafe_sleep_verify_ms=int(h.get("unsafe_sleep_verify_ms", 0)),
                    evaluator=str(h.get("evaluator", "store")),
                ),
            )
        )
    if not cfg.runs:
        cfg.runs.append(RunConfig())
    return cfg


def dump_config(cfg: SimConfig) -> str:
    """SimConfig -> TOML text (tomllib is read-only; layout kept trivial)."""
    lines = [
        f'network = "{cfg.network}"',
        f'scheme = "{cfg.scheme}"',
        f'allocator = "{cfg.allocator}"',
        f"monitor_port = {cfg.monitor_port}",
        f"max_timeout_s = {cfg.max_timeout_s}",
        f"retrials = {cfg.retrials}",
        f"batch_size = {cfg.batch_size}",
        f"shared_verifier = {str(cfg.shared_verifier).lower()}",
        f"mesh_devices = {cfg.mesh_devices}",
        f'fp_backend = "{cfg.fp_backend}"',
        f"rns_resident = {str(cfg.rns_resident).lower()}",
        f"debug = {str(cfg.debug).lower()}",
        f"metrics = {str(cfg.metrics).lower()}",
        f"metrics_linger_s = {cfg.metrics_linger_s}",
        f"trace = {str(cfg.trace).lower()}",
        f"trace_capacity = {cfg.trace_capacity}",
        f'baseline = "{cfg.baseline}"',
        f'master_ip = "{cfg.master_ip}"',
        f"base_port = {cfg.base_port}",
    ]
    if cfg.chaos.any():
        lines += [
            "",
            "[chaos]",
            f"drop_rate = {cfg.chaos.drop_rate}",
            f"corrupt_rate = {cfg.chaos.corrupt_rate}",
            f"duplicate_rate = {cfg.chaos.duplicate_rate}",
            f"reorder_rate = {cfg.chaos.reorder_rate}",
            f"delay_rate = {cfg.chaos.delay_rate}",
            f"delay_ms = {cfg.chaos.delay_ms}",
            f"delay_jitter_ms = {cfg.chaos.delay_jitter_ms}",
            f"seed = {cfg.chaos.seed}",
        ]
    if cfg.service.enabled():
        lines += [
            "",
            "[service]",
            f"sessions = {cfg.service.sessions}",
            f"nodes = {cfg.service.nodes}",
            f"threshold = {cfg.service.threshold}",
            f"processes = {cfg.service.processes}",
            f"devices = {cfg.service.devices}",
            f"mesh_devices = {cfg.service.mesh_devices}",
            f"mesh_batch_size = {cfg.service.mesh_batch_size}",
            f"max_sessions = {cfg.service.max_sessions}",
            f"session_ttl_s = {cfg.service.session_ttl_s}",
            f"quantum = {cfg.service.quantum}",
            f"max_pending_per_session = {cfg.service.max_pending_per_session}",
            f"queue_capacity = {cfg.service.queue_capacity}",
            f"tiers = {cfg.service.tiers!r}",
            f"batch_size = {cfg.service.batch_size}",
            f"spawn_stagger_ms = {cfg.service.spawn_stagger_ms}",
            f"period_ms = {cfg.service.period_ms}",
            f'fp_backend = "{cfg.service.fp_backend}"',
            f'batch_check = "{cfg.service.batch_check}"',
        ]
    if cfg.soak != SoakParams():  # non-default soak shapes round-trip
        lines += [
            "",
            "[soak]",
            f"duration_s = {cfg.soak.duration_s}",
            f"nodes = {cfg.soak.nodes}",
            f"concurrency = {cfg.soak.concurrency}",
            f"devices = {cfg.soak.devices}",
            f"max_lanes = {cfg.soak.max_lanes}",
            f"batch_size = {cfg.soak.batch_size}",
            f"queue_capacity = {cfg.soak.queue_capacity}",
            f"session_ttl_s = {cfg.soak.session_ttl_s}",
            f"tiers = {cfg.soak.tiers!r}",
            f"period_ms = {cfg.soak.period_ms}",
            f"registry = {cfg.soak.registry}",
            f"swap_at_frac = {cfg.soak.swap_at_frac}",
            f"lane_loss_at_frac = {cfg.soak.lane_loss_at_frac}",
            f"control_interval_s = {cfg.soak.control_interval_s}",
            f"autotune_every_s = {cfg.soak.autotune_every_s}",
            f"trace_capacity = {cfg.soak.trace_capacity}",
        ]
    if cfg.load.enabled():
        lo = cfg.load
        lines += [
            "",
            "[load]",
            f"rate_sps = {lo.rate_sps}",
            f"duration_s = {lo.duration_s}",
            f'model = "{lo.model}"',
            f"seed = {lo.seed}",
            f"nodes = {lo.nodes}",
            f"deadline_s = {lo.deadline_s}",
            f"tiers = {lo.tiers!r}",
            f"diurnal_amplitude = {lo.diurnal_amplitude}",
            f"diurnal_period_s = {lo.diurnal_period_s}",
            f"burst_every_s = {lo.burst_every_s}",
            f"burst_x = {lo.burst_x}",
            f"burst_len_s = {lo.burst_len_s}",
        ]
    if cfg.load.enabled() or cfg.federation != FederationParams():
        fe = cfg.federation
        lines += [
            "",
            "[federation]",
            f'planet = "{fe.planet}"',
            f"geo_seed = {fe.geo_seed}",
            f"devices = {fe.devices}",
            f"batch_size = {fe.batch_size}",
            f"queue_capacity = {fe.queue_capacity}",
            f"max_sessions = {fe.max_sessions}",
            f"session_ttl_s = {fe.session_ttl_s}",
            f"period_ms = {fe.period_ms}",
            f"probe_interval_s = {fe.probe_interval_s}",
            f"retry_base_ms = {fe.retry_base_ms}",
            f"retry_cap_ms = {fe.retry_cap_ms}",
            f"retry_budget = {fe.retry_budget}",
            f"registry = {fe.registry}",
            f"shed_ceiling = {fe.shed_ceiling}",
            f'kill_region = "{fe.kill_region}"',
            f"kill_at_frac = {fe.kill_at_frac}",
            f"recover_at_frac = {fe.recover_at_frac}",
            f"trace_capacity = {fe.trace_capacity}",
        ]
    if cfg.alerts != AlertParams():  # non-default alert shapes round-trip
        al = cfg.alerts
        lines += [
            "",
            "[alerts]",
            f"enabled = {str(al.enabled).lower()}",
            f"fast_window_s = {al.fast_window_s}",
            f"slow_window_s = {al.slow_window_s}",
            f"window_scale = {al.window_scale}",
            f"page_x = {al.page_x}",
            f"warn_x = {al.warn_x}",
            f"goodput_slo = {al.goodput_slo}",
            f"z_threshold = {al.z_threshold}",
            f"ewma_alpha = {al.ewma_alpha}",
            f"min_consecutive = {al.min_consecutive}",
            f"seed = {al.seed}",
            f"min_hold_s = {al.min_hold_s}",
            f"cooldown_s = {al.cooldown_s}",
            f"tick_interval_s = {al.tick_interval_s}",
            f"series_cap = {al.series_cap}",
            f"rollup_top_k = {al.rollup_top_k}",
            f"rollup_interval_s = {al.rollup_interval_s}",
            f"rollup_stale_s = {al.rollup_stale_s}",
        ]
    if cfg.scenario.enabled():
        sc = cfg.scenario
        lines += [
            "",
            "[scenario]",
            f'name = "{sc.name}"',
            f'planet = "{sc.planet}"',
        ]
        if sc.regions:
            regions = ", ".join(f'"{r}"' for r in sc.regions)
            lines.append(f"regions = [{regions}]")
        if sc.rtt_ms:
            rows = ", ".join(
                "[" + ", ".join(str(v) for v in row) + "]"
                for row in sc.rtt_ms
            )
            lines.append(f"rtt_ms = [{rows}]")
        lines += [
            f"jitter_ms = {sc.jitter_ms}",
            f"geo_seed = {sc.geo_seed}",
            f"joins = {sc.joins}",
            f"join_at_frac = {sc.join_at_frac}",
            f'weight_profile = "{sc.weight_profile}"',
            f"weight_seed = {sc.weight_seed}",
            f"weight_threshold_frac = {sc.weight_threshold_frac}",
        ]
    if cfg.swarm.enabled():
        lines += [
            "",
            "[swarm]",
            f"identities = {cfg.swarm.identities}",
            f"processes = {cfg.swarm.processes}",
            f"threshold = {cfg.swarm.threshold}",
            f"period_ms = {cfg.swarm.period_ms}",
            f"timeout_ms = {cfg.swarm.timeout_ms}",
            f"fast_path = {cfg.swarm.fast_path}",
            f"tick_ms = {cfg.swarm.tick_ms}",
            f"batch_size = {cfg.swarm.batch_size}",
            f"max_pending = {cfg.swarm.max_pending}",
            f"chunk_bits = {cfg.swarm.chunk_bits}",
            f"page_budget = {cfg.swarm.page_budget}",
            f"timeout_s = {cfg.swarm.timeout_s}",
        ]
    for h in cfg.hosts:
        lines += [
            "",
            "[[hosts]]",
            f'connect = "{h.connect}"',
            f'ip = "{h.ip}"',
            f'python = "{h.python}"',
            f'workdir = "{h.workdir}"',
            f"device = {str(h.device).lower()}",
        ]
    for r in cfg.runs:
        lines += [
            "",
            "[[runs]]",
            f"nodes = {r.nodes}",
            f"threshold = {r.threshold}",
            f"failing = {r.failing}",
            f"processes = {r.processes}",
        ]
        if r.adversaries.total():
            lines += [
                "[runs.adversaries]",
                f"invalid_signer = {r.adversaries.invalid_signer}",
                f"stale_replayer = {r.adversaries.stale_replayer}",
                f"flooder = {r.adversaries.flooder}",
                f"flood_pps = {r.adversaries.flood_pps}",
                f"churner = {r.adversaries.churner}",
                f"churn_after_ms = {r.adversaries.churn_after_ms}",
            ]
        lines += [
            "[runs.handel]",
            f"period_ms = {r.handel.period_ms}",
            f"update_count = {r.handel.update_count}",
            f"fast_path = {r.handel.fast_path}",
            f"timeout_ms = {r.handel.timeout_ms}",
            f"unsafe_sleep_verify_ms = {r.handel.unsafe_sleep_verify_ms}",
            f'evaluator = "{r.handel.evaluator}"',
        ]
    return "\n".join(lines) + "\n"

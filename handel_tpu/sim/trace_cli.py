"""Trace-analysis CLI: reconstruct the aggregation wave from trace dumps.

`python -m handel_tpu.sim trace <run-trace-dir | trace.json ...>` loads the
per-process Chrome `trace_event` dumps a traced run leaves behind
(sim/node.py --trace-dir, or FlightRecorder.dump from an in-process
cluster) and answers the questions the CSV cannot:

- the aggregation wave: per level, when the first / median / last node
  completed it (the paper's completion-time curve, observed per run);
- slowest-span attribution: which pipeline stage (recv, queue, verify,
  merge, dispatch_pack, device_verify, net_transit) the wall time went to;
- per-contribution chains: recv -> queue -> verify -> merge span coverage,
  surfacing where a contribution stalled;
- the CRITICAL PATH to threshold (`--critical-path`): walk the
  threshold-reaching merge backwards through verify/queue/recv/net_transit
  and across processes via the packet span ids (ISSUE 10 flow links) to a
  contributor's first send, with per-stage (net/queue/verify/merge/device)
  attribution — the causal answer to "why did this run take X ms".

Options: `--merged out.json` writes the combined timeline (open in
chrome://tracing or Perfetto); `--plot out.png` draws the wave via
sim/plots.py; `--top N` bounds the attribution table; `--report out.json`
writes the machine-readable `trace_report.json` (bench-record shaped, so
scripts/bench_check.py tracks time-to-threshold / coverage / flow linkage /
lane occupancy as side metrics).
"""

from __future__ import annotations

import argparse
import glob
import heapq
import json
import math
import os
import sys
from array import array
from collections import OrderedDict

from handel_tpu.core.trace import merge_traces

#: pipeline spans that make up a contribution's recv -> merge chain
CHAIN_SPANS = ("recv", "queue", "verify", "merge")

#: chain span name -> critical-path attribution stage
STAGE_OF = {
    "net_transit": "net",
    "recv": "recv",
    "queue": "queue",
    "verify": "verify",
    "merge": "merge",
    "send": "send",
}


def resolve_trace_files(paths: list[str]) -> list[str]:
    """Expand directories into their trace dumps (node trace_*.json and
    swarm swarm_trace_*.json both count)."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "trace_*.json"))))
            files.extend(
                sorted(glob.glob(os.path.join(p, "swarm_trace_*.json")))
            )
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no trace_*.json under {paths}")
    return files


def load_exports(paths: list[str]) -> list[dict]:
    """Load the raw per-process exports (clockOffset intact). Holds every
    file at once — fine for small runs and the merge/plot paths; the
    analysis pipeline itself streams (`stream_report`), because a 65k-node
    swarm's dumps do not fit an analyst laptop's memory all at once."""
    exports = []
    for f in resolve_trace_files(paths):
        with open(f) as fh:
            exports.append(json.load(fh))
    return exports


def load_traces(paths: list[str]) -> list[dict]:
    """Load trace events from files and/or directories of trace_*.json."""
    return merge_traces(load_exports(paths))["traceEvents"]


def _t0(events: list[dict]) -> float:
    tss = [e["ts"] for e in events if e.get("ph") in ("X", "i")]
    return min(tss) if tss else 0.0


def level_timeline(events: list[dict]) -> dict[int, tuple[float, float, float]]:
    """Per protocol level: (first, median, last) completion time in seconds
    relative to the earliest event — the aggregation wave."""
    t0 = _t0(events)
    by_level: dict[int, list[float]] = {}
    for e in events:
        if e.get("ph") == "i" and e.get("name") == "level_complete":
            lvl = int(e.get("args", {}).get("level", -1))
            by_level.setdefault(lvl, []).append((e["ts"] - t0) / 1e6)
    out = {}
    for lvl, tss in sorted(by_level.items()):
        tss.sort()
        out[lvl] = (tss[0], tss[len(tss) // 2], tss[-1])
    return out


def span_table(events: list[dict]) -> list[dict]:
    """Aggregate complete ("X") spans by name: count/total/mean/max (ms),
    sorted by total descending — the slowest-span attribution table."""
    agg: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") == "X":
            agg.setdefault(e["name"], []).append(e.get("dur", 0.0) / 1e3)
    rows = []
    for name, durs in agg.items():
        rows.append(
            {
                "name": name,
                "count": len(durs),
                "total_ms": sum(durs),
                "mean_ms": sum(durs) / len(durs),
                "max_ms": max(durs),
            }
        )
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def contribution_chains(events: list[dict]) -> dict[tuple, dict]:
    """Group pipeline spans into per-contribution chains keyed by
    (pid, tid, origin, level, rts, ind) — `rts` is the arrival stamp that
    separates re-deliveries of the same aggregate, `ind` splits a packet's
    multisig from its piggybacked individual sig (they share one recv).
    Coverage is the UNION of the chain's span intervals over the
    recv-start -> merge-end wall — the fraction of a contribution's life
    the trace can attribute to a pipeline stage."""
    recvs: dict[tuple, dict] = {}
    chains: dict[tuple, list[dict]] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("name") not in CHAIN_SPANS:
            continue
        a = e.get("args", {})
        if "origin" not in a or "level" not in a or "rts" not in a:
            continue
        pkt_key = (e.get("pid", 0), e.get("tid", 0), a["origin"], a["level"],
                   a["rts"])
        if e["name"] == "recv":
            recvs[pkt_key] = e
        else:
            chains.setdefault(pkt_key + (bool(a.get("ind")),), []).append(e)
    out = {}
    for key, evs in chains.items():
        recv = recvs.get(key[:-1])
        if recv is None:
            continue
        evs = evs + [recv]
        names = {e["name"] for e in evs}
        if "merge" not in names:
            continue  # incomplete chain (e.g. never verified)
        start = recv["ts"]
        end = max(e["ts"] + e.get("dur", 0.0) for e in evs if e["name"] == "merge")
        wall = end - start
        ivs = sorted(
            (max(e["ts"], start), min(e["ts"] + e.get("dur", 0.0), end))
            for e in evs
        )
        covered, cur_lo, cur_hi = 0.0, None, None
        for lo, hi in ivs:
            if hi <= lo:
                continue
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    covered += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        if cur_hi is not None:
            covered += cur_hi - cur_lo
        out[key] = {
            "wall_ms": wall / 1e3,
            "coverage": covered / wall if wall > 0 else 1.0,
            "stages": {
                n: sum(e.get("dur", 0.0) for e in evs if e["name"] == n) / 1e3
                for n in sorted(names)
            },
        }
    return out


def _interval_union(ivs: list[tuple[float, float]]) -> float:
    """Total length of the union of [lo, hi) intervals (µs in, µs out)."""
    covered, cur_lo, cur_hi = 0.0, None, None
    for lo, hi in sorted(ivs):
        if hi <= lo:
            continue
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                covered += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        covered += cur_hi - cur_lo
    return covered


class _TraceIndex:
    """The span indexes the critical-path walk needs — built over one
    process's export (streamed path) or the whole merged run
    (`critical_path`)."""

    def __init__(self, events: list[dict] = ()):
        self.merges: dict[tuple, list[dict]] = {}
        self.pipeline: dict[tuple, dict[str, list[dict]]] = {}
        self.transits: dict[tuple, list[dict]] = {}
        self.sends: dict[int, dict] = {}
        self.device_ivs: dict[int, list[tuple[float, float]]] = {}
        if events:
            self.add_events(events)

    def add_events(self, events: list[dict]) -> None:
        for e in events:
            if e.get("ph") != "X":
                continue
            name, a = e.get("name"), e.get("args", {})
            pt = (e.get("pid", 0), e.get("tid", 0))
            if name == "merge":
                self.merges.setdefault(pt, []).append(e)
            if name in ("merge", "verify", "queue", "recv") and "rts" in a:
                key = pt + (a.get("origin"), a.get("level"), a["rts"])
                self.pipeline.setdefault(key, {}).setdefault(
                    name, []
                ).append(e)
            elif name == "net_transit":
                self.transits.setdefault(
                    pt + (a.get("origin"), a.get("level")), []
                ).append(e)
            elif name == "send" and a.get("span"):
                self.sends[a["span"]] = e
            elif name == "device_verify":
                self.device_ivs.setdefault(e.get("pid", 0), []).append(
                    (e["ts"], e["ts"] + e.get("dur", 0.0))
                )
        for evs in self.merges.values():
            evs.sort(key=lambda e: e["ts"] + e.get("dur", 0.0))

    def enclosing_merge(self, pt: tuple, ts: float) -> dict | None:
        """The merge containing ts on (pid, tid), else the latest one
        ending at/before ts (a periodic resend of an earlier merge)."""
        best = None
        for m in self.merges.get(pt, ()):
            lo, hi = m["ts"], m["ts"] + m.get("dur", 0.0)
            if lo <= ts <= hi:
                return m
            if hi <= ts:
                best = m  # sorted by end: the last such wins
        return best

    @staticmethod
    def pick(evs: list[dict] | None, span: int) -> dict | None:
        """Prefer the event whose span arg matches; else the longest."""
        if not evs:
            return None
        same = [e for e in evs if e.get("args", {}).get("span") == span]
        pool = same or evs
        return max(pool, key=lambda e: e.get("dur", 0.0))


def _walk_chain(anchor: dict, index_of, send_of) -> list[dict]:
    """The backwards walk shared by `critical_path` and `stream_report`:
    `index_of(pid)` resolves a process's _TraceIndex (the streamed path
    loads it lazily), `send_of(span)` resolves a packet span id to the
    sender's send event wherever that process's dump lives."""
    chain: list[dict] = []
    visited: set[tuple] = set()
    idx = index_of(anchor.get("pid", 0))
    cur = None
    if idx is not None:
        cur = idx.enclosing_merge(
            (anchor.get("pid", 0), anchor.get("tid", 0)), anchor["ts"]
        )
    while cur is not None:
        pt = (cur.get("pid", 0), cur.get("tid", 0))
        mkey = pt + (cur["ts"],)  # value identity: stable across reloads
        if mkey in visited:
            break
        visited.add(mkey)
        a = cur.get("args", {})
        key = pt + (a.get("origin"), a.get("level"), a.get("rts"))
        span = a.get("span", 0)
        hop = [cur]
        stages = idx.pipeline.get(key, {})
        for name in ("verify", "queue", "recv"):
            m = _TraceIndex.pick(stages.get(name), span)
            if m is not None:
                hop.append(m)
        nt = _TraceIndex.pick(
            idx.transits.get(pt + (a.get("origin"), a.get("level"))), span
        )
        if nt is not None:
            hop.append(nt)
        chain.extend(hop)
        send = send_of(span) if span else None
        if send is None:
            break
        chain.append(send)
        idx = index_of(send.get("pid", 0))
        cur = None
        if idx is not None:
            cur = idx.enclosing_merge(
                (send.get("pid", 0), send.get("tid", 0)), send["ts"]
            )
    return chain


def _chain_to_report(chain: list[dict], anchor: dict, device_ivs_of) -> dict:
    """Fold a walked chain into the critical-path report dict;
    `device_ivs_of(pid)` yields that process's device_verify intervals for
    the verify -> device re-attribution."""
    chain = list(reversed(chain))  # origin-first: send ... -> final merge
    start = min(e["ts"] for e in chain) if chain else anchor["ts"]
    wall = anchor["ts"] - start
    ivs = [
        (e["ts"], min(e["ts"] + e.get("dur", 0.0), anchor["ts"]))
        for e in chain
    ]
    stages_us: dict[str, float] = {}
    for e in chain:
        stage = STAGE_OF.get(e["name"], e["name"])
        lo, hi = e["ts"], min(e["ts"] + e.get("dur", 0.0), anchor["ts"])
        dur = max(0.0, hi - lo)
        if e["name"] == "verify":
            # chip wall inside the verify window attributes to `device`
            on_dev = _interval_union([
                (max(lo, dlo), min(hi, dhi))
                for dlo, dhi in device_ivs_of(e.get("pid", 0))
                if dhi > lo and dlo < hi
            ])
            stages_us["device"] = stages_us.get("device", 0.0) + on_dev
            dur -= on_dev
        stages_us[stage] = stages_us.get(stage, 0.0) + dur
    # region-pair attribution (scenario engine, network/geo.py): every
    # cross-node hop pairs the sender's span region tag with the first
    # downstream span recorded by a DIFFERENT node — "eu-west->ap-east"
    # strings naming where the critical path's WAN time went
    region_hops: list[str] = []
    for i, e in enumerate(chain):
        if e["name"] != "send":
            continue
        src = e.get("args", {}).get("region")
        here = (e.get("pid", 0), e.get("tid", 0))
        dst = None
        for nxt in chain[i + 1:]:
            r = nxt.get("args", {}).get("region")
            if r and (nxt.get("pid", 0), nxt.get("tid", 0)) != here:
                dst = r
                break
        if src and dst:
            region_hops.append(f"{src}->{dst}")
    return {
        "anchor": {
            "pid": anchor.get("pid", 0),
            "tid": anchor.get("tid", 0),
            "args": anchor.get("args", {}),
        },
        "threshold_ts": anchor["ts"],
        "start_ts": start,
        "wall_ms": wall / 1e3,
        "coverage": _interval_union(ivs) / wall if wall > 0 else 1.0,
        "hops": sum(1 for e in chain if e["name"] == "send"),
        "stages_ms": {k: v / 1e3 for k, v in sorted(stages_us.items())},
        "region_hops": region_hops,
        "chain": [
            {
                "name": e["name"],
                "pid": e.get("pid", 0),
                "tid": e.get("tid", 0),
                "t_ms": (e["ts"] - start) / 1e3,
                "dur_ms": e.get("dur", 0.0) / 1e3,
                "origin": e.get("args", {}).get("origin"),
                "level": e.get("args", {}).get("level"),
                "span": e.get("args", {}).get("span"),
                "region": e.get("args", {}).get("region"),
            }
            for e in chain
        ],
    }


def critical_path(events: list[dict]) -> dict | None:
    """Walk the threshold-reaching aggregate backwards to a contributor's
    first send — the slowest CAUSAL chain, not a heuristic stitching.

    Anchor: the fleet's earliest `threshold_reached` instant. From the
    merge span enclosing it, the local pipeline is matched by
    (pid, tid, origin, level, rts); the cross-process hop resolves the
    merge's packet span id to the SENDER's `send` span, then recurses into
    the merge that produced that send (fast-path sends happen inside the
    producing merge's interval, core/handel.py _check_completed_level).
    The walk ends at a send with no producing merge — the contribution's
    origin. Returns None when the trace holds no threshold instant.

    Verify time overlapping the shared service's `device_verify` launches
    (same process) is re-attributed to the `device` stage, so host-queue
    wait and chip wall are separated in the stage breakdown.
    """
    thresholds = [
        e for e in events
        if e.get("ph") == "i" and e.get("name") == "threshold_reached"
    ]
    if not thresholds:
        return None
    anchor = min(thresholds, key=lambda e: e["ts"])
    idx = _TraceIndex(events)
    chain = _walk_chain(anchor, lambda pid: idx, idx.sends.get)
    return _chain_to_report(
        chain, anchor, lambda pid: idx.device_ivs.get(pid, ())
    )


def flow_linkage(events: list[dict]) -> tuple[float, int, int]:
    """(linked fraction, linked, total) over recv spans that carry a trace
    context: a recv is LINKED when its packet span id resolves to a send
    span somewhere in the merged trace. Unlinked recvs are degraded
    contexts (span 0) or senders whose dump is missing."""
    send_ids = {
        e["args"]["span"]
        for e in events
        if e.get("ph") == "X" and e.get("name") == "send"
        and e.get("args", {}).get("span")
    }
    total = linked = 0
    for e in events:
        if e.get("ph") != "X" or e.get("name") != "recv":
            continue
        a = e.get("args", {})
        if "span" not in a:
            continue  # pre-ISSUE-10 trace
        total += 1
        if a["span"] and a["span"] in send_ids:
            linked += 1
    return (linked / total if total else 0.0), linked, total


def lane_occupancy(events: list[dict]) -> dict:
    """Per device lane: on-device busy fraction over the lane's active
    window (union of its launch_on_device / launch_on_mesh spans,
    first-to-last extent), plus the fleet mean — the timeline form of the
    plane's fill gauges. Mesh launches keep their own span name (distinct
    attribution in the span table) but busy a lane like any other."""
    by_lane: dict[tuple, list[tuple[float, float]]] = {}
    for e in events:
        if e.get("ph") == "X" and e.get("name") in (
            "launch_on_device", "launch_on_mesh",
        ):
            by_lane.setdefault(
                (e.get("pid", 0), e.get("tid", 0)), []
            ).append((e["ts"], e["ts"] + e.get("dur", 0.0)))
    lanes = {}
    for (pid, tid), ivs in sorted(by_lane.items()):
        window = max(hi for _, hi in ivs) - min(lo for lo, _ in ivs)
        lanes[f"{pid}/{tid}"] = (
            _interval_union(ivs) / window if window > 0 else 1.0
        )
    mean = sum(lanes.values()) / len(lanes) if lanes else 0.0
    return {"mean": mean, "lanes": lanes}


def _load_shifted(path: str) -> tuple[dict, list[dict]]:
    """One export, its clock offset already applied to event timestamps
    (the per-file half of core/trace.py merge_traces)."""
    with open(path) as fh:
        ex = json.load(fh)
    evs = ex.get("traceEvents", [])
    off = float(ex.get("clockOffset", 0.0) or 0.0) * 1e6
    if off:
        for e in evs:
            if "ts" in e:
                e["ts"] += off
    return ex, evs


class _ExportStream:
    """Lazy per-process _TraceIndex cache for the streamed critical-path
    walk: the walk touches O(hops) processes, so at most `cap` dumps are
    ever resident at once."""

    def __init__(self, file_of_pid: dict[int, str], cap: int = 4):
        self._files = file_of_pid
        self._cache: OrderedDict[str, _TraceIndex] = OrderedDict()
        self._cap = cap

    def index_of(self, pid: int) -> _TraceIndex | None:
        f = self._files.get(pid)
        if f is None:
            return None
        idx = self._cache.get(f)
        if idx is None:
            idx = _TraceIndex(_load_shifted(f)[1])
            self._cache[f] = idx
            while len(self._cache) > self._cap:
                self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(f)
        return idx


def stream_report(paths: list[str], top_k: int = 10) -> dict:
    """build_report over trace dumps WITHOUT holding them all in memory:
    one pass, one file resident at a time — a 65,536-vnode swarm's dumps
    don't fit an analyst machine all at once. Per-file events fold into
    bounded state (level-wave timestamp arrays, span count/total/max,
    span-id -> pid for the cross-process hops, a top-k heap of the slowest
    contribution chains); the critical path then walks backwards loading
    only the O(hops) dumps it actually visits (_ExportStream)."""
    files = resolve_trace_files(paths)
    t0 = math.inf
    anchor: dict | None = None
    level_ts: dict[int, array] = {}
    span_agg: dict[str, list[float]] = {}
    send_pid: dict[int, int] = {}
    recv_span_ct: dict[int, int] = {}
    recv_total = 0
    lane_ivs: dict[tuple, list[tuple[float, float]]] = {}
    file_of_pid: dict[int, str] = {}
    offsets: list[float] = []
    heap: list[tuple] = []
    chain_ct, cov_sum, cov_min = 0, 0.0, math.inf
    seq = events_total = 0

    for f in files:
        ex, evs = _load_shifted(f)
        offsets.append(float(ex.get("clockOffset", 0.0) or 0.0))
        for e in evs:
            ph = e.get("ph")
            if ph not in ("X", "i"):
                continue
            events_total += 1
            ts = e["ts"]
            if ts < t0:
                t0 = ts
            pid = e.get("pid", 0)
            if pid not in file_of_pid:
                file_of_pid[pid] = f
            name = e.get("name")
            if ph == "i":
                if name == "level_complete":
                    lvl = int(e.get("args", {}).get("level", -1))
                    level_ts.setdefault(lvl, array("d")).append(ts)
                elif name == "threshold_reached" and (
                    anchor is None or ts < anchor["ts"]
                ):
                    anchor = e
                continue
            dur = e.get("dur", 0.0)
            row = span_agg.get(name)
            if row is None:
                span_agg[name] = [1, dur, dur]
            else:
                row[0] += 1
                row[1] += dur
                if dur > row[2]:
                    row[2] = dur
            a = e.get("args", {})
            if name == "send":
                if a.get("span"):
                    send_pid[a["span"]] = pid
            elif name == "recv":
                if "span" in a:
                    recv_total += 1
                    if a["span"]:
                        recv_span_ct[a["span"]] = (
                            recv_span_ct.get(a["span"], 0) + 1
                        )
            elif name in ("launch_on_device", "launch_on_mesh"):
                lane_ivs.setdefault((pid, e.get("tid", 0)), []).append(
                    (ts, ts + dur)
                )
        # chain spans for one contribution all live on the recipient's
        # recorder, so per-file chain extraction is exact
        for key, c in contribution_chains(evs).items():
            chain_ct += 1
            cov_sum += c["coverage"]
            if c["coverage"] < cov_min:
                cov_min = c["coverage"]
            seq += 1
            item = (c["wall_ms"], seq, key, c)
            if len(heap) < top_k:
                heapq.heappush(heap, item)
            elif item[0] > heap[0][0]:
                heapq.heapreplace(heap, item)
        del ex, evs

    cp = None
    if anchor is not None:
        stream = _ExportStream(file_of_pid)

        def send_of(span: int) -> dict | None:
            spid = send_pid.get(span)
            if spid is None:
                return None
            idx = stream.index_of(spid)
            return idx.sends.get(span) if idx is not None else None

        def device_ivs_of(pid: int):
            idx = stream.index_of(pid)
            return idx.device_ivs.get(pid, ()) if idx is not None else ()

        chain = _walk_chain(anchor, stream.index_of, send_of)
        cp = _chain_to_report(chain, anchor, device_ivs_of)

    wave = {}
    for lvl in sorted(level_ts):
        srt = sorted(level_ts[lvl])
        wave[str(lvl)] = {
            "first": (srt[0] - t0) / 1e6,
            "median": (srt[len(srt) // 2] - t0) / 1e6,
            "last": (srt[-1] - t0) / 1e6,
        }
    linked = sum(
        ct for span, ct in recv_span_ct.items() if span in send_pid
    )
    lanes = {}
    for (pid, tid), ivs in sorted(lane_ivs.items()):
        window = max(hi for _, hi in ivs) - min(lo for lo, _ in ivs)
        lanes[f"{pid}/{tid}"] = (
            _interval_union(ivs) / window if window > 0 else 1.0
        )
    tts = cp["wall_ms"] / 1e3 if cp else 0.0
    return {
        "metric": "trace_time_to_threshold_s",
        "value": tts,
        "backend": "trace",
        "time_to_threshold_s": tts,
        "critical_path_coverage": cp["coverage"] if cp else 0.0,
        "critical_path_len": cp["hops"] if cp else 0,
        "flow_linkage": (linked / recv_total) if recv_total else 0.0,
        "flow_linked": linked,
        "flow_total": recv_total,
        "lane_occupancy": (
            sum(lanes.values()) / len(lanes) if lanes else 0.0
        ),
        "lanes": lanes,
        "critical_path": cp,
        "levels_s": wave,
        "level_wave": wave,
        "span_table": [
            {
                "name": n,
                "count": int(c),
                "total_ms": tot / 1e3,
                "mean_ms": tot / c / 1e3,
                "max_ms": mx / 1e3,
            }
            for n, (c, tot, mx) in sorted(
                span_agg.items(), key=lambda kv: -kv[1][1]
            )
        ],
        "chains": {
            "count": chain_ct,
            "coverage_min": cov_min if chain_ct else 0.0,
            "coverage_mean": cov_sum / chain_ct if chain_ct else 0.0,
            "slowest": [
                {
                    "pid": key[0],
                    "tid": key[1],
                    "origin": key[2],
                    "level": key[3],
                    **c,
                }
                for _, _, key, c in sorted(heap, reverse=True)
            ],
        },
        "clock_offsets_s": offsets,
        "events": events_total,
        "files": len(files),
    }


def build_report(events: list[dict], exports: list[dict] | None = None) -> dict:
    """The machine-readable `trace_report.json`: bench-record shaped
    (metric/value/backend, scripts/bench_check.py extract_metrics) with the
    critical-path breakdown, per-level wave, flow linkage, lane occupancy
    and the per-process clock offsets as payload."""
    cp = critical_path(events)
    linkage, linked, total = flow_linkage(events)
    occ = lane_occupancy(events)
    wave = level_timeline(events)
    offsets = [
        float(ex.get("clockOffset", 0.0) or 0.0) for ex in exports or []
    ]
    tts = cp["wall_ms"] / 1e3 if cp else 0.0
    report = {
        "metric": "trace_time_to_threshold_s",
        "value": tts,
        "backend": "trace",
        "time_to_threshold_s": tts,
        "critical_path_coverage": cp["coverage"] if cp else 0.0,
        "flow_linkage": linkage,
        "flow_linked": linked,
        "flow_total": total,
        "lane_occupancy": occ["mean"],
        "lanes": occ["lanes"],
        "critical_path": cp,
        "levels_s": {
            str(lvl): {"first": f, "median": m, "last": l}
            for lvl, (f, m, l) in wave.items()
        },
        "clock_offsets_s": offsets,
        "events": len(events),
    }
    return report


def print_critical_path(cp: dict | None) -> None:
    if cp is None:
        print("\ncritical path: no threshold_reached instant in trace")
        return
    print(
        f"\ncritical path to threshold: {cp['wall_ms']:.2f} ms over "
        f"{cp['hops']} hop(s), {cp['coverage']:.1%} span-attributed"
    )
    print("  stage breakdown: " + "  ".join(
        f"{k}={v:.2f}ms" for k, v in cp["stages_ms"].items()
    ))
    if cp.get("region_hops"):
        print("  region hops: " + "  ".join(cp["region_hops"]))
    for e in cp["chain"]:
        where = f"pid {e['pid']} tid {e['tid']}"
        tag = (
            f"origin={e['origin']} level={e['level']}"
            if e["origin"] is not None
            else f"level={e['level']}" if e["level"] is not None else ""
        )
        print(
            f"  +{e['t_ms']:9.3f} ms {e['name']:>12} {e['dur_ms']:9.3f} ms"
            f"  [{where}] {tag}"
        )


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m handel_tpu.sim trace",
        description="analyze a traced run's flight-recorder dumps",
    )
    ap.add_argument("paths", nargs="+", help="trace dir(s) or trace_*.json files")
    ap.add_argument("--merged", default="", help="write combined Chrome trace JSON")
    ap.add_argument("--plot", default="", help="write the aggregation-wave PNG")
    ap.add_argument(
        "--top", "--top-k", dest="top", type=int, default=10,
        help="rows kept/shown per table (bounds per-chain output too)",
    )
    ap.add_argument(
        "--critical-path", action="store_true",
        help="walk + print the causal chain to threshold",
    )
    ap.add_argument(
        "--report", default="",
        help="write the machine-readable trace_report.json here",
    )
    args = ap.parse_args(argv)

    # one file resident at a time: a 65k-node swarm's dumps stream through
    report = stream_report(args.paths, top_k=args.top)
    print(
        f"{report['events']} events streamed from {report['files']} file(s)"
    )

    wave = report["levels_s"]
    if wave:
        print("\naggregation wave (level completion, s since first event):")
        print(f"{'level':>6} {'first':>9} {'median':>9} {'last':>9} ")
        for lvl, w in wave.items():
            print(
                f"{int(lvl):>6} {w['first']:>9.4f} {w['median']:>9.4f} "
                f"{w['last']:>9.4f}"
            )

    rows = report["span_table"]
    if rows:
        print("\nslowest-span attribution:")
        print(f"{'span':>14} {'count':>8} {'total ms':>11} {'mean ms':>9} {'max ms':>9}")
        for r in rows[: args.top]:
            print(
                f"{r['name']:>14} {r['count']:>8} {r['total_ms']:>11.2f} "
                f"{r['mean_ms']:>9.3f} {r['max_ms']:>9.3f}"
            )

    ch = report["chains"]
    if ch["count"]:
        print(
            f"\n{ch['count']} contribution chains; span coverage "
            f"min={ch['coverage_min']:.1%} mean={ch['coverage_mean']:.1%}"
        )
        print("slowest contributions (recv -> merge):")
        for c in ch["slowest"]:
            stages = " ".join(
                f"{n}={ms:.2f}ms" for n, ms in c["stages"].items()
            )
            print(
                f"  node {c['tid']} origin={c['origin']} level={c['level']}: "
                f"{c['wall_ms']:.2f} ms ({c['coverage']:.0%} attributed) {stages}"
            )

    if args.critical_path:
        print_critical_path(report["critical_path"])
        print(
            f"\nflow linkage: {report['flow_linked']}/{report['flow_total']} "
            f"recvs resolved to their sender's span "
            f"({report['flow_linkage']:.1%})"
        )
        if report["lanes"]:
            print(
                "lane occupancy: "
                + "  ".join(
                    f"{k}={v:.1%}" for k, v in report["lanes"].items()
                )
                + f"  (mean {report['lane_occupancy']:.1%})"
            )

    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1)
        print(f"\ntrace report -> {args.report}")

    if args.merged:
        # the one path that genuinely needs every event resident
        events = merge_traces(load_exports(args.paths))["traceEvents"]
        with open(args.merged, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        print(f"\nmerged trace -> {args.merged}")
    if args.plot:
        from handel_tpu.sim.plots import plot_trace_timeline

        plot_trace_timeline(
            {
                int(k): (w["first"], w["median"], w["last"])
                for k, w in wave.items()
            },
            args.plot,
        )
        print(f"wave plot -> {args.plot}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))

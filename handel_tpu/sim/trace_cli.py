"""Trace-analysis CLI: reconstruct the aggregation wave from trace dumps.

`python -m handel_tpu.sim trace <run-trace-dir | trace.json ...>` loads the
per-process Chrome `trace_event` dumps a traced run leaves behind
(sim/node.py --trace-dir, or FlightRecorder.dump from an in-process
cluster) and answers the questions the CSV cannot:

- the aggregation wave: per level, when the first / median / last node
  completed it (the paper's completion-time curve, observed per run);
- slowest-span attribution: which pipeline stage (recv, queue, verify,
  merge, dispatch_pack, device_verify, net_transit) the wall time went to;
- per-contribution chains: recv -> queue -> verify -> merge span coverage,
  surfacing where a contribution stalled.

Options: `--merged out.json` writes the combined timeline (open in
chrome://tracing or Perfetto); `--plot out.png` draws the wave via
sim/plots.py; `--top N` bounds the attribution table.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from handel_tpu.core.trace import merge_traces

#: pipeline spans that make up a contribution's recv -> merge chain
CHAIN_SPANS = ("recv", "queue", "verify", "merge")


def load_traces(paths: list[str]) -> list[dict]:
    """Load trace events from files and/or directories of trace_*.json."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "trace_*.json"))))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no trace_*.json under {paths}")
    exports = []
    for f in files:
        with open(f) as fh:
            exports.append(json.load(fh))
    return merge_traces(exports)["traceEvents"]


def _t0(events: list[dict]) -> float:
    tss = [e["ts"] for e in events if e.get("ph") in ("X", "i")]
    return min(tss) if tss else 0.0


def level_timeline(events: list[dict]) -> dict[int, tuple[float, float, float]]:
    """Per protocol level: (first, median, last) completion time in seconds
    relative to the earliest event — the aggregation wave."""
    t0 = _t0(events)
    by_level: dict[int, list[float]] = {}
    for e in events:
        if e.get("ph") == "i" and e.get("name") == "level_complete":
            lvl = int(e.get("args", {}).get("level", -1))
            by_level.setdefault(lvl, []).append((e["ts"] - t0) / 1e6)
    out = {}
    for lvl, tss in sorted(by_level.items()):
        tss.sort()
        out[lvl] = (tss[0], tss[len(tss) // 2], tss[-1])
    return out


def span_table(events: list[dict]) -> list[dict]:
    """Aggregate complete ("X") spans by name: count/total/mean/max (ms),
    sorted by total descending — the slowest-span attribution table."""
    agg: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") == "X":
            agg.setdefault(e["name"], []).append(e.get("dur", 0.0) / 1e3)
    rows = []
    for name, durs in agg.items():
        rows.append(
            {
                "name": name,
                "count": len(durs),
                "total_ms": sum(durs),
                "mean_ms": sum(durs) / len(durs),
                "max_ms": max(durs),
            }
        )
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def contribution_chains(events: list[dict]) -> dict[tuple, dict]:
    """Group pipeline spans into per-contribution chains keyed by
    (pid, tid, origin, level, rts, ind) — `rts` is the arrival stamp that
    separates re-deliveries of the same aggregate, `ind` splits a packet's
    multisig from its piggybacked individual sig (they share one recv).
    Coverage is the UNION of the chain's span intervals over the
    recv-start -> merge-end wall — the fraction of a contribution's life
    the trace can attribute to a pipeline stage."""
    recvs: dict[tuple, dict] = {}
    chains: dict[tuple, list[dict]] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("name") not in CHAIN_SPANS:
            continue
        a = e.get("args", {})
        if "origin" not in a or "level" not in a or "rts" not in a:
            continue
        pkt_key = (e.get("pid", 0), e.get("tid", 0), a["origin"], a["level"],
                   a["rts"])
        if e["name"] == "recv":
            recvs[pkt_key] = e
        else:
            chains.setdefault(pkt_key + (bool(a.get("ind")),), []).append(e)
    out = {}
    for key, evs in chains.items():
        recv = recvs.get(key[:-1])
        if recv is None:
            continue
        evs = evs + [recv]
        names = {e["name"] for e in evs}
        if "merge" not in names:
            continue  # incomplete chain (e.g. never verified)
        start = recv["ts"]
        end = max(e["ts"] + e.get("dur", 0.0) for e in evs if e["name"] == "merge")
        wall = end - start
        ivs = sorted(
            (max(e["ts"], start), min(e["ts"] + e.get("dur", 0.0), end))
            for e in evs
        )
        covered, cur_lo, cur_hi = 0.0, None, None
        for lo, hi in ivs:
            if hi <= lo:
                continue
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    covered += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        if cur_hi is not None:
            covered += cur_hi - cur_lo
        out[key] = {
            "wall_ms": wall / 1e3,
            "coverage": covered / wall if wall > 0 else 1.0,
            "stages": {
                n: sum(e.get("dur", 0.0) for e in evs if e["name"] == n) / 1e3
                for n in sorted(names)
            },
        }
    return out


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m handel_tpu.sim trace",
        description="analyze a traced run's flight-recorder dumps",
    )
    ap.add_argument("paths", nargs="+", help="trace dir(s) or trace_*.json files")
    ap.add_argument("--merged", default="", help="write combined Chrome trace JSON")
    ap.add_argument("--plot", default="", help="write the aggregation-wave PNG")
    ap.add_argument("--top", type=int, default=10, help="attribution rows shown")
    args = ap.parse_args(argv)

    events = load_traces(args.paths)
    print(f"{len(events)} events loaded")

    wave = level_timeline(events)
    if wave:
        print("\naggregation wave (level completion, s since first event):")
        print(f"{'level':>6} {'first':>9} {'median':>9} {'last':>9} ")
        for lvl, (first, med, last) in wave.items():
            print(f"{lvl:>6} {first:>9.4f} {med:>9.4f} {last:>9.4f}")

    rows = span_table(events)
    if rows:
        print("\nslowest-span attribution:")
        print(f"{'span':>14} {'count':>8} {'total ms':>11} {'mean ms':>9} {'max ms':>9}")
        for r in rows[: args.top]:
            print(
                f"{r['name']:>14} {r['count']:>8} {r['total_ms']:>11.2f} "
                f"{r['mean_ms']:>9.3f} {r['max_ms']:>9.3f}"
            )

    chains = contribution_chains(events)
    if chains:
        worst = sorted(chains.items(), key=lambda kv: -kv[1]["wall_ms"])
        cov = [c["coverage"] for c in chains.values()]
        print(
            f"\n{len(chains)} contribution chains; span coverage "
            f"min={min(cov):.1%} median={sorted(cov)[len(cov) // 2]:.1%}"
        )
        print("slowest contributions (recv -> merge):")
        for (pid, tid, origin, level, _rts, _ind), c in worst[: args.top]:
            stages = " ".join(
                f"{n}={ms:.2f}ms" for n, ms in c["stages"].items()
            )
            print(
                f"  node {tid} origin={origin} level={level}: "
                f"{c['wall_ms']:.2f} ms ({c['coverage']:.0%} attributed) {stages}"
            )

    if args.merged:
        with open(args.merged, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        print(f"\nmerged trace -> {args.merged}")
    if args.plot:
        from handel_tpu.sim.plots import plot_trace_timeline

        plot_trace_timeline(wave, args.plot)
        print(f"wave plot -> {args.plot}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))

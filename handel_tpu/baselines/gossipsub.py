"""Gossipsub aggregation baseline — the libp2p comparison protocol.

Reference: simul/p2p/libp2p/node.go:55-434 — each node publishes its
individual signature on its OWN topic and subscribes to every other node's
topic (SubscribeToAll); a setup barrier (special Level=255 packets,
WaitAllSetup) holds publishing until the overlay is known-complete; local
aggregation fires at threshold. The transport there is libp2p's gossipsub
router; this module implements that router's v1.0 semantics directly on the
framework's Packet wire format instead of standing in with plain flooding:

  * per-topic MESH overlays with degree bounds: GRAFT to D when below D_lo,
    PRUNE to D when above D_hi, on a heartbeat (gossipsub §mesh maintenance);
  * eager push: full messages forward once to the topic's mesh members;
  * lazy pull: each heartbeat, IHAVE (seen message ids) goes to D_lazy
    random non-mesh peers, who answer IWANT for what they miss — the repair
    channel that makes the protocol survive UDP loss;
  * SUB announce + setup barrier before the first publish.

Message ids are topic ids (one signature per origin-topic), so IHAVE/IWANT
carry plain topic lists. Control frames ride `Packet.multisig` with a
1-byte type tag under level=254; the data frame carries the marshaled
individual signature. Verification is verify-on-arrival (the reference's
default aggregator mode, simul/p2p/aggregator.go verifyPacket).
"""

from __future__ import annotations

import asyncio
import random
import struct
from typing import Sequence

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import Constructor, MultiSignature
from handel_tpu.core.identity import Identity, Registry
from handel_tpu.core.net import Network, Packet

GOSSIPSUB_LEVEL = 254  # the baseline's private level marker (node.go: 255)

# frame types
_SUB = 0  # subscription announce (setup barrier)
_PUB = 1  # full message: topic's individual signature
_GRAFT = 2
_PRUNE = 3
_IHAVE = 4
_IWANT = 5


def _frame(kind: int, topic: int, payload: bytes = b"") -> bytes:
    return struct.pack(">BI", kind, topic) + payload


def _topics_payload(topics) -> bytes:
    return struct.pack(">H", len(topics)) + b"".join(
        struct.pack(">I", t) for t in topics
    )


def _parse_topics(payload: bytes) -> list[int]:
    (n,) = struct.unpack_from(">H", payload, 0)
    return [struct.unpack_from(">I", payload, 2 + 4 * i)[0] for i in range(n)]


class GossipSubAggregator:
    """One gossipsub node (node.go P2PNode + the gossipsub router itself).

    Same constructor shape as baselines/gossip.py GossipAggregator so the
    sim node binary and the test harness drive either interchangeably.
    """

    def __init__(
        self,
        network: Network,
        registry: Registry,
        identity: Identity,
        constructor: Constructor,
        msg: bytes,
        own_sig,
        threshold: int,
        *,
        heartbeat: float = 0.05,
        degree: int = 6,  # gossipsub D
        degree_lo: int = 4,  # D_lo
        degree_hi: int = 12,  # D_hi
        degree_lazy: int = 6,  # D_lazy (IHAVE fanout)
        rand: random.Random | None = None,
    ):
        self.net = network
        self.reg = registry
        self.id = identity.id
        self.cons = constructor
        self.msg = msg
        self.threshold = threshold
        self.heartbeat = heartbeat
        self.D, self.D_lo, self.D_hi = degree, degree_lo, degree_hi
        self.D_lazy = degree_lazy
        self.rand = rand or random.Random(identity.id)

        # delivered messages: topic (origin id) -> verified signature
        self.sigs: dict[int, object] = {identity.id: own_sig}
        # gossip history window (the spec's mcache): IHAVE advertises only
        # ids learned in the last `history` heartbeats plus our own topic —
        # a full-set advertisement would be O(N) bytes per frame per beat
        # at reference scale (4000 topics = 16 KB fragmenting UDP frames)
        self.history = 6
        self._beat = 0
        self._learned_at: dict[int, int] = {identity.id: 0}
        # per-topic mesh membership (only topics with traffic materialize;
        # the reference's libp2p router does the same lazily per topic)
        self.mesh: dict[int, set[int]] = {}
        # peers whose SUB we've seen — the setup barrier state
        self.subscribed: set[int] = {identity.id}
        self.setup_complete = False

        self.final: asyncio.Future = asyncio.get_event_loop().create_future()
        self._task: asyncio.Task | None = None
        # counters for the monitor plane
        self.sigs_checked = 0
        self.grafts_sent = 0
        self.prunes_sent = 0
        self.ihave_sent = 0
        self.iwant_sent = 0
        network.register_listener(self)

    # -- wire in -------------------------------------------------------------

    def new_packet(self, packet: Packet) -> None:
        if packet.level != GOSSIPSUB_LEVEL or packet.origin == self.id:
            return
        data = packet.multisig
        if len(data) < 5:
            return
        kind, topic = struct.unpack_from(">BI", data, 0)
        payload = data[5:]
        peer = packet.origin
        # ANY valid frame proves the peer is up and subscribed — without
        # this, a peer whose SUB frames were all lost before the sender
        # completed setup would stall forever (the sender stops announcing
        # but keeps heartbeating GRAFT/IHAVE/PUB traffic we can learn from)
        self.subscribed.add(peer)  # _SUB frames carry nothing else
        if kind == _PUB:
            self._deliver(topic, payload, from_peer=peer)
        elif kind == _GRAFT:
            # gossipsub accepts grafts immediately; overshoot beyond D_hi is
            # corrected at the next heartbeat's prune pass
            self.mesh.setdefault(topic, set()).add(peer)
        elif kind == _PRUNE:
            self.mesh.get(topic, set()).discard(peer)
        elif kind in (_IHAVE, _IWANT):
            # a truncated control payload must not raise out of the
            # transport's listener callback — drop the frame, like the
            # unmarshal_signature guard in _deliver
            try:
                topics = _parse_topics(payload)
            except struct.error:
                return
            if kind == _IHAVE:
                missing = [t for t in topics if t not in self.sigs]
                if missing:
                    self.iwant_sent += 1
                    self._send(
                        peer, _frame(_IWANT, 0, _topics_payload(missing))
                    )
            else:
                for t in topics:
                    sig = self.sigs.get(t)
                    if sig is not None:
                        self._send(peer, _frame(_PUB, t, sig.marshal()))

    def _deliver(self, topic: int, sig_bytes: bytes, from_peer: int) -> None:
        if topic in self.sigs or not (0 <= topic < self.reg.size()):
            return
        try:
            sig = self.cons.unmarshal_signature(sig_bytes)
        except Exception:
            return
        pk = self.reg.identity(topic).public_key
        self.sigs_checked += 1
        if not pk.verify(self.msg, sig):
            return
        self.sigs[topic] = sig
        self._learned_at[topic] = self._beat
        # eager push: forward once to the topic's mesh (minus the sender)
        self._publish_to_mesh(topic, sig, exclude=from_peer)
        self._maybe_finish()

    # -- wire out ------------------------------------------------------------

    def _send(self, peer: int, frame: bytes) -> None:
        self.net.send(
            [self.reg.identity(peer)],
            Packet(origin=self.id, level=GOSSIPSUB_LEVEL, multisig=frame),
        )

    def _send_many(self, peers: Sequence[int], frame: bytes) -> None:
        if peers:
            self.net.send(
                [self.reg.identity(p) for p in peers],
                Packet(origin=self.id, level=GOSSIPSUB_LEVEL, multisig=frame),
            )

    def _publish_to_mesh(self, topic: int, sig, exclude: int = -1) -> None:
        members = self._mesh_of(topic)
        self._send_many(
            [p for p in members if p != exclude], _frame(_PUB, topic, sig.marshal())
        )

    def _mesh_of(self, topic: int) -> set[int]:
        """Materialize a topic mesh on first touch: graft D random peers
        (what libp2p does on subscribe/first message)."""
        members = self.mesh.get(topic)
        if members is None:
            members = set(self._sample_peers(self.D, excluding=set()))
            self.mesh[topic] = members
            for p in members:
                self.grafts_sent += 1
                self._send(p, _frame(_GRAFT, topic))
        return members

    def _sample_peers(self, k: int, excluding: set[int]) -> list[int]:
        pool = [
            i
            for i in range(self.reg.size())
            if i != self.id and i not in excluding
        ]
        return self.rand.sample(pool, min(k, len(pool)))

    # -- heartbeat -----------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def _loop(self) -> None:
        sub_frame = _frame(_SUB, self.id)
        while True:
            if not self.setup_complete:
                # setup barrier (node.go WaitAllSetup): announce until the
                # whole registry is known-subscribed, then start publishing
                self._send_many(
                    [i for i in range(self.reg.size()) if i != self.id],
                    sub_frame,
                )
                if len(self.subscribed) == self.reg.size():
                    self.setup_complete = True
                    self._publish_to_mesh(self.id, self.sigs[self.id])
            else:
                self._heartbeat()
            self._maybe_finish()
            await asyncio.sleep(self.heartbeat)

    def _heartbeat(self) -> None:
        self._beat += 1
        # mesh maintenance per active topic (gossipsub §heartbeat)
        for topic, members in self.mesh.items():
            if len(members) < self.D_lo:
                added = self._sample_peers(
                    self.D - len(members), excluding=members
                )
                members.update(added)
                for p in added:
                    self.grafts_sent += 1
                    self._send(p, _frame(_GRAFT, topic))
            elif len(members) > self.D_hi:
                drop = self.rand.sample(sorted(members), len(members) - self.D)
                members.difference_update(drop)
                for p in drop:
                    self.prunes_sent += 1
                    self._send(p, _frame(_PRUNE, topic))
        # lazy gossip: advertise recently learned ids (+ always our own
        # topic, so stragglers can complete from the owner no matter how
        # old the message) to D_lazy random peers outside our own topic's
        # mesh; IWANT answers repair their gaps
        window = sorted(
            t
            for t, b in self._learned_at.items()
            if self._beat - b <= self.history or t == self.id
        )[:8192]
        if window:
            targets = self._sample_peers(
                self.D_lazy, excluding=self.mesh.get(self.id, set())
            )
            self.ihave_sent += len(targets)
            frame = _frame(_IHAVE, 0, _topics_payload(window))
            self._send_many(targets, frame)

    # -- aggregation (aggregator.go at-threshold path) -----------------------

    def _maybe_finish(self) -> None:
        if self.final.done() or len(self.sigs) < self.threshold:
            return
        bs = BitSet(self.reg.size())
        agg = None
        for origin, sig in self.sigs.items():
            bs.set(origin, True)
            agg = sig if agg is None else agg.combine(sig)
        self.final.set_result(MultiSignature(bs, agg))

    def values(self) -> dict[str, float]:
        return {
            "sigsKnown": float(len(self.sigs)),
            "sigCheckedCt": float(self.sigs_checked),
            "graftsSent": float(self.grafts_sent),
            "prunesSent": float(self.prunes_sent),
            "ihaveSent": float(self.ihave_sent),
            "iwantSent": float(self.iwant_sent),
        }


async def run_gossipsub(
    n: int,
    threshold: int | None = None,
    timeout: float = 30.0,
    scheme=None,
    **kwargs,
):
    """n-node gossipsub aggregation over the in-process router."""
    from handel_tpu.baselines.gossip import run_gossip

    return await run_gossip(
        n,
        threshold=threshold,
        timeout=timeout,
        scheme=scheme,
        aggregator_cls=GossipSubAggregator,
        **kwargs,
    )

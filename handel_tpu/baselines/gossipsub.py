"""Mesh-gossip aggregation baseline — the libp2p/gossipsub slot.

Reference: simul/p2p/libp2p/node.go:55-434 — the gossipsub comparison
protocol: every node maintains a bounded mesh of peers (gossipsub's mesh
degree D), floods newly learned individual signatures to its mesh, and
aggregates locally at threshold. The reference's setup barrier (special
Level=255 packets, WaitAllSetup) maps to the sim harness's sync barrier;
topic-per-node subscription maps to origin-tagged packets on the shared
Packet wire format.

Differs from baselines/gossip.py's `random-k` connector (fresh random peers
every round — closer to epidemic gossip): here the mesh is FIXED per node,
built deterministically from the registry, giving gossipsub's stable-overlay
propagation pattern and its characteristic higher latency / lower fanout
redundancy at equal degree.
"""

from __future__ import annotations

import random

from handel_tpu.baselines.gossip import GossipAggregator
from handel_tpu.core.identity import Identity


class MeshGossipAggregator(GossipAggregator):
    """GossipAggregator over a fixed-degree mesh overlay (node.go mesh)."""

    def __init__(self, *args, degree: int = 8, **kwargs):
        kwargs.pop("connector", None)
        super().__init__(*args, connector="mesh", **kwargs)
        n = self.reg.size()
        # deterministic symmetric mesh in O(n) per node: an edge (i, j)
        # exists iff a hash seeded on the unordered pair fires with
        # probability degree/(n-1) — both endpoints compute the same answer
        # without replaying anyone's sampling. Ring neighbors are always
        # linked so the overlay stays connected at any degree.
        p = min(1.0, degree / max(1, n - 1))
        picked = {(self.id - 1) % n, (self.id + 1) % n} - {self.id}
        for j in range(n):
            if j == self.id or j in picked:
                continue
            a, b = min(self.id, j), max(self.id, j)
            if random.Random(0xD15C0 ^ (a * n + b)).random() < p:
                picked.add(j)
        self._mesh = sorted(picked)

    def _peers(self) -> list[Identity]:
        return [self.reg.identity(i) for i in self._mesh]


async def run_mesh_gossip(
    n: int,
    threshold: int | None = None,
    timeout: float = 30.0,
    scheme=None,
    degree: int = 8,
    **kwargs,
):
    """n-node mesh-gossip aggregation over the in-process router
    (run_gossip with the mesh aggregator plugged in)."""
    from handel_tpu.baselines.gossip import run_gossip

    return await run_gossip(
        n,
        threshold=threshold,
        timeout=timeout,
        scheme=scheme,
        aggregator_cls=MeshGossipAggregator,
        degree=degree,
        **kwargs,
    )

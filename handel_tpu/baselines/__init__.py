"""Baseline comparison protocols.

Reference: simul/p2p/ — a gossip aggregator (aggregator.go:17-276) over two
transports: full-mesh "N^2" UDP diffusion (p2p/udp/node.go:17-91) and libp2p
gossipsub (p2p/libp2p/node.go:89-434). These exist only to produce the
comparison curves against Handel (BASELINE.md rows "Baseline N^2 gossip" and
"Baseline libp2p"). Here the gossip aggregator runs over the same Network
interface as the protocol (in-process router or UDP sockets); a gossipsub
mesh would need an external dependency and is represented by the
random-subset connector instead.
"""

from handel_tpu.baselines.gossip import GossipAggregator, run_gossip
from handel_tpu.baselines.gossipsub import MeshGossipAggregator, run_mesh_gossip

__all__ = [
    "GossipAggregator",
    "run_gossip",
    "MeshGossipAggregator",
    "run_mesh_gossip",
]

"""Baseline comparison protocols.

Reference: simul/p2p/ — a gossip aggregator (aggregator.go:17-276) over two
transports: full-mesh "N^2" UDP diffusion (p2p/udp/node.go:17-91) and libp2p
gossipsub (p2p/libp2p/node.go:89-434). These exist only to produce the
comparison curves against Handel (BASELINE.md rows "Baseline N^2 gossip" and
"Baseline libp2p"). Here the gossip aggregator runs over the same Network
interface as the protocol (in-process router or UDP sockets), and the
gossipsub slot implements the router's actual v1.0 semantics (per-topic
meshes, GRAFT/PRUNE, IHAVE/IWANT) on that same interface — no libp2p
dependency needed.
"""

from handel_tpu.baselines.gossip import GossipAggregator, run_gossip
from handel_tpu.baselines.gossipsub import GossipSubAggregator, run_gossipsub

__all__ = [
    "GossipAggregator",
    "run_gossip",
    "GossipSubAggregator",
    "run_gossipsub",
]

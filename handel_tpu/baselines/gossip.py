"""Gossip ("diffuse-everything") aggregation baseline.

Reference: simul/p2p/aggregator.go:17-276 — every node periodically diffuses
individual signatures it knows; aggregation happens locally once `threshold`
distinct signatures are collected. Two verification modes mirror the
reference: `verify_incoming=True` checks every individual signature as it
arrives (aggregator.go verifyPacket); False defers verification to the final
aggregate (aggregate-then-verify, aggregator.go:206 mode). Connectors:
`full` = diffuse to the entire registry ("N^2", p2p/udp/node.go Diffuse) or
`random-k` = k random peers per round (the gossipsub stand-in).

Packet reuse: gossip rides the same `Packet` wire format with level=255 as
the baseline marker (the reference uses a dedicated setup level 255 in
p2p/libp2p/node.go).

Tracing (ISSUE 10 satellite): with a `recorder` attached the baseline emits
the SAME recv/verify/merge pipeline spans, `net_transit`, send-side flow
links and `threshold_reached` instant as Handel (core/handel.py), so
baseline-vs-handel trace comparisons in sim/trace_cli.py are like-for-like.
"""

from __future__ import annotations

import asyncio
import random
from typing import Sequence

from handel_tpu.core.crypto import Constructor, MultiSignature
from handel_tpu.core.bitset import BitSet
from handel_tpu.core.identity import Identity, Registry
from handel_tpu.core.net import Network, Packet
from handel_tpu.core.trace import trace_now

GOSSIP_LEVEL = 255


class GossipAggregator:
    """One gossip node (aggregator.go Aggregator)."""

    def __init__(
        self,
        network: Network,
        registry: Registry,
        identity: Identity,
        constructor: Constructor,
        msg: bytes,
        own_sig,
        threshold: int,
        *,
        period: float = 0.05,
        connector: str = "full",
        fanout: int = 8,
        verify_incoming: bool = True,
        rand: random.Random | None = None,
        recorder=None,
        trace_tid: int | None = None,
    ):
        self.net = network
        self.reg = registry
        self.id = identity.id
        self.cons = constructor
        self.msg = msg
        self.threshold = threshold
        self.period = period
        self.connector = connector
        self.fanout = fanout
        self.verify_incoming = verify_incoming
        self.rand = rand or random.Random(identity.id)
        # flight recorder (core/trace.py), same span names as Handel
        self.rec = recorder
        self._tid = trace_tid if trace_tid is not None else identity.id
        self._span_seq = 0
        if recorder is not None:
            recorder.name_thread(self._tid, f"gossip-{identity.id}")
        # known individual signatures by origin (aggregator.go sigs map)
        self.sigs: dict[int, object] = {identity.id: own_sig}
        self.final: asyncio.Future = asyncio.get_event_loop().create_future()
        self._task: asyncio.Task | None = None
        self.sigs_checked = 0
        # invalid origins evicted by the threshold-time bisection
        # (aggregate-then-verify mode, _maybe_finish)
        self.sigs_evicted = 0
        network.register_listener(self)

    # -- network in ---------------------------------------------------------

    def new_packet(self, packet: Packet) -> None:
        if packet.level != GOSSIP_LEVEL or packet.origin == self.id:
            return
        if packet.origin in self.sigs:
            return
        rec = self.rec
        tracing = rec is not None and rec.enabled
        t0 = trace_now() if tracing else 0.0
        try:
            sig = self.cons.unmarshal_signature(packet.multisig)
        except Exception:
            return
        if tracing:
            if packet.sent_ts and packet.sent_ts <= t0:
                rec.span(
                    "net_transit",
                    packet.sent_ts,
                    t0,
                    tid=self._tid,
                    cat="net",
                    args={
                        "origin": packet.origin,
                        "level": packet.level,
                        "span": packet.span_id,
                    },
                )
            t1 = trace_now()
            rec.span(
                "recv",
                t0,
                t1,
                tid=self._tid,
                cat="pipeline",
                args={
                    "origin": packet.origin,
                    "level": packet.level,
                    "rts": int(t0 * 1e6),
                    "span": packet.span_id,
                },
            )
            if packet.span_id:
                rec.flow("contrib", packet.span_id, "t", t1, tid=self._tid)
        if self.verify_incoming:
            pk = self.reg.identity(packet.origin).public_key
            self.sigs_checked += 1
            tv = trace_now() if tracing else 0.0
            ok = pk.verify(self.msg, sig)
            if tracing:
                rec.span(
                    "verify",
                    tv,
                    trace_now(),
                    tid=self._tid,
                    cat="pipeline",
                    args={
                        "origin": packet.origin,
                        "level": packet.level,
                        "rts": int(t0 * 1e6),
                        "ok": ok,
                        "span": packet.span_id,
                    },
                )
            if not ok:
                return
        if tracing:
            tm = trace_now()
            self.sigs[packet.origin] = sig
            self._maybe_finish()
            tm2 = trace_now()
            rec.span(
                "merge",
                tm,
                tm2,
                tid=self._tid,
                cat="pipeline",
                args={
                    "origin": packet.origin,
                    "level": packet.level,
                    "rts": int(t0 * 1e6),
                    "span": packet.span_id,
                },
            )
            if packet.span_id:
                rec.flow("contrib", packet.span_id, "f", tm2, tid=self._tid)
            return
        self.sigs[packet.origin] = sig
        self._maybe_finish()

    def _maybe_finish(self) -> None:
        if self.final.done() or len(self.sigs) < self.threshold:
            return
        if not self.verify_incoming:
            # aggregate-then-verify mode: one combined check at threshold.
            # On failure, bisect by origin (models/rlc.py bisect_verify —
            # the binary search the reference leaves as a TODO at
            # aggregator.go:206) and EVICT the culprits, so the poisoned
            # subset is never re-verified wholesale on every later packet
            # (the inherited double-count: sigs_checked grew by one full
            # aggregate check per arrival while the set stayed poisoned).
            from handel_tpu.models.rlc import bisect_verify

            keys = [
                self.reg.identity(i).public_key for i in range(self.reg.size())
            ]

            def check(origins) -> bool:
                self.sigs_checked += 1
                b = BitSet(self.reg.size())
                a = None
                for o in origins:
                    b.set(o, True)
                    s = self.sigs[o]
                    a = s if a is None else a.combine(s)
                return bool(
                    self.cons.aggregate_public_keys(keys, b).verify(
                        self.msg, a
                    )
                )

            verdicts = bisect_verify(
                list(self.sigs), check, lambda o: check([o])
            )
            bad = [o for o, ok in verdicts.items() if not ok]
            for o in bad:
                del self.sigs[o]
                self.sigs_evicted += 1
            if bad and len(self.sigs) < self.threshold:
                return  # keep gossiping with the clean partial set
        # every surviving origin passed a combined or per-origin check (or
        # verify_incoming already vetted it at arrival)
        bs = BitSet(self.reg.size())
        agg = None
        for origin, sig in self.sigs.items():
            bs.set(origin, True)
            agg = sig if agg is None else agg.combine(sig)
        ms = MultiSignature(bs, agg)
        if self.rec is not None:
            self.rec.instant(
                "threshold_reached",
                tid=self._tid,
                cat="protocol",
                args={"card": bs.cardinality(), "threshold": self.threshold},
            )
        self.final.set_result(ms)

    # -- gossip loop --------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    def _peers(self) -> Sequence[Identity]:
        ids = [
            self.reg.identity(i)
            for i in range(self.reg.size())
            if i != self.id
        ]
        if self.connector == "full":
            return ids
        return self.rand.sample(ids, min(self.fanout, len(ids)))

    async def _loop(self) -> None:
        # keep diffusing after our own threshold is met — peers on sparse
        # overlays may still need our signatures (the reference's aggregator
        # gossips until the simulation stops it); `stop()` cancels the task
        while True:
            # diffuse every known individual signature (aggregator.go Diffuse)
            rec = self.rec
            tracing = rec is not None and rec.enabled
            for origin, sig in list(self.sigs.items()):
                if tracing:
                    self._span_seq += 1
                    sid = (self.id << 40) | self._span_seq
                    t0 = trace_now()
                else:
                    sid = 0
                peers = self._peers()
                self.net.send(
                    peers,
                    Packet(
                        origin=origin,
                        level=GOSSIP_LEVEL,
                        multisig=sig.marshal(),
                        sent_ts=trace_now(),
                        span_id=sid,
                        # forwarding another node's signature is a hop
                        hop=1 if sid and origin != self.id else 0,
                    ),
                )
                if tracing:
                    rec.span(
                        "send",
                        t0,
                        trace_now(),
                        tid=self._tid,
                        cat="pipeline",
                        args={
                            "level": GOSSIP_LEVEL,
                            "card": 1,
                            "peers": len(peers),
                            "span": sid,
                        },
                    )
                    rec.flow("contrib", sid, "s", t0, tid=self._tid)
            self._maybe_finish()
            await asyncio.sleep(self.period)

    def values(self) -> dict[str, float]:
        return {
            "sigsKnown": float(len(self.sigs)),
            "sigCheckedCt": float(self.sigs_checked),
            "sigEvictedCt": float(self.sigs_evicted),
        }


async def run_gossip(
    n: int,
    threshold: int | None = None,
    timeout: float = 20.0,
    scheme=None,
    aggregator_cls: type | None = None,
    **kwargs,
) -> dict[int, MultiSignature]:
    """Run an n-node gossip aggregation over the in-process router.

    `aggregator_cls` selects the node implementation (default
    GossipAggregator; baselines/gossipsub.py passes its mesh variant)."""
    from handel_tpu.core.test_harness import FakeScheme, InProcessNetwork, InProcessRouter

    cls = aggregator_cls or GossipAggregator
    scheme = scheme or FakeScheme()
    threshold = threshold or (n // 2 + 1)
    router = InProcessRouter()
    idents, secrets = [], []
    for i in range(n):
        sk, pk = scheme.keygen(i)
        idents.append(Identity(i, f"gossip-{i}", pk))
        secrets.append(sk)
    from handel_tpu.core.identity import ArrayRegistry

    registry = ArrayRegistry(idents)
    msg = b"gossip baseline msg"
    nodes = []
    for i in range(n):
        net = InProcessNetwork(router, f"gossip-{i}")
        nodes.append(
            cls(
                net,
                registry,
                idents[i],
                scheme.constructor,
                msg,
                secrets[i].sign(msg),
                threshold,
                **kwargs,
            )
        )
    for node in nodes:
        node.start()
    try:
        finals = await asyncio.wait_for(
            asyncio.gather(*(node.final for node in nodes)), timeout
        )
    finally:
        for node in nodes:
            node.stop()
    return dict(zip(range(n), finals))

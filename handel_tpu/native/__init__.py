"""ctypes bindings for the C++ host arithmetic (bn254.cc).

The shared library is built on first use with g++ (no pybind11 in the image;
plain C ABI + ctypes per the environment constraints) and cached next to the
source. Every entry point has a pure-Python fallback via ops/bn254_ref.py, so
nothing breaks where a compiler is unavailable — the native path is a
host-speed accelerator, not a dependency.

API mirrors the scalar oracle's point representation: affine tuples of ints
(G2 coordinates are (c0, c1) pairs), None = infinity.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "bn254.cc")
_LIB = os.path.join(_HERE, "libbn254.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build(force: bool = False) -> str | None:
    try:
        if (
            not force
            and os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        ):
            return _LIB
        # compile to a per-process temp name and rename into place: many node
        # processes may race to build on a fresh checkout, and rename() is
        # atomic so nobody ever dlopens a half-written .so
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=300,
        )
        os.replace(tmp, _LIB)
        return _LIB
    except Exception:
        return None


def _open(path: str):
    lib = ctypes.CDLL(path)
    lib.bn254_native_version.restype = ctypes.c_int
    if lib.bn254_native_version() != 1:
        raise OSError("native ABI version mismatch")
    return lib


def load():
    """The ctypes library, or None when unavailable. Thread-safe, cached."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        try:
            _lib = _open(path)
        except OSError:
            # a stale/torn artifact (e.g. from a crashed build): force a
            # rebuild once before giving up
            path = _build(force=True)
            if path is not None:
                try:
                    _lib = _open(path)
                except OSError:
                    return None
    return _lib


def available() -> bool:
    return load() is not None


# -- marshalling helpers ----------------------------------------------------


def _i2b(x: int) -> bytes:
    # scalars cross the ABI unreduced (any 256-bit value): [R]P must give
    # infinity for the subgroup check, so reducing mod R here would be wrong
    if not 0 <= x < (1 << 256):
        raise ValueError("scalar out of 256-bit range")
    return int(x).to_bytes(32, "little")


def _b2i(b: bytes) -> int:
    return int.from_bytes(b, "little")


def _g1_buf(p) -> tuple[bytes, int]:
    if p is None:
        return b"\x00" * 64, 1
    return _i2b(p[0]) + _i2b(p[1]), 0


def _g1_out(buf, inf) -> tuple | None:
    if inf.value:
        return None
    raw = bytes(buf)
    return (_b2i(raw[:32]), _b2i(raw[32:64]))


def _g2_buf(p) -> tuple[bytes, int]:
    if p is None:
        return b"\x00" * 128, 1
    (x0, x1), (y0, y1) = p
    return _i2b(x0) + _i2b(x1) + _i2b(y0) + _i2b(y1), 0


def _g2_out(buf, inf) -> tuple | None:
    if inf.value:
        return None
    raw = bytes(buf)
    return (
        (_b2i(raw[:32]), _b2i(raw[32:64])),
        (_b2i(raw[64:96]), _b2i(raw[96:128])),
    )



def _f12_out(raw: bytes):
    """384-byte C layout -> oracle nested Fp12 tuple (6 x 64-byte Fp2)."""
    f2s = [
        (_b2i(raw[64 * i : 64 * i + 32]), _b2i(raw[64 * i + 32 : 64 * i + 64]))
        for i in range(6)
    ]
    return ((f2s[0], f2s[1], f2s[2]), (f2s[3], f2s[4], f2s[5]))


# -- public ops (native if possible, oracle fallback) -----------------------


def g1_add(a, b):
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.g1_add(a, b)
    abuf, ainf = _g1_buf(a)
    bbuf, binf = _g1_buf(b)
    out = ctypes.create_string_buffer(64)
    oinf = ctypes.c_int()
    lib.bn254_g1_add(out, ctypes.byref(oinf), abuf, ainf, bbuf, binf)
    return _g1_out(out, oinf)


def g1_mul(p, k: int):
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.g1_mul(p, k)
    pbuf, pinf = _g1_buf(p)
    out = ctypes.create_string_buffer(64)
    oinf = ctypes.c_int()
    lib.bn254_g1_mul(out, ctypes.byref(oinf), pbuf, pinf, _i2b(k))
    return _g1_out(out, oinf)


def g2_add(a, b):
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.g2_add(a, b)
    abuf, ainf = _g2_buf(a)
    bbuf, binf = _g2_buf(b)
    out = ctypes.create_string_buffer(128)
    oinf = ctypes.c_int()
    lib.bn254_g2_add(out, ctypes.byref(oinf), abuf, ainf, bbuf, binf)
    return _g2_out(out, oinf)


def g2_mul(p, k: int):
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.g2_mul(p, k)
    pbuf, pinf = _g2_buf(p)
    out = ctypes.create_string_buffer(128)
    oinf = ctypes.c_int()
    lib.bn254_g2_mul(out, ctypes.byref(oinf), pbuf, pinf, _i2b(k))
    return _g2_out(out, oinf)


def g1_mul_batch(points, scalars):
    """n independent [k_i]P_i in one native call."""
    lib = load()
    from handel_tpu.ops import bn254_ref as bn

    if lib is None:
        return [bn.g1_mul(p, k) for p, k in zip(points, scalars)]
    n = len(points)
    pts = b"".join(_g1_buf(p)[0] for p in points)
    infs = (ctypes.c_int * n)(*[1 if p is None else 0 for p in points])
    ks = b"".join(_i2b(k) for k in scalars)
    out = ctypes.create_string_buffer(64 * n)
    oinf = (ctypes.c_int * n)()
    lib.bn254_g1_mul_batch(out, oinf, pts, infs, ks, n)
    raw = bytes(out)
    return [
        None
        if oinf[i]
        else (_b2i(raw[64 * i : 64 * i + 32]), _b2i(raw[64 * i + 32 : 64 * i + 64]))
        for i in range(n)
    ]


def g2_mul_batch(points, scalars):
    lib = load()
    from handel_tpu.ops import bn254_ref as bn

    if lib is None:
        return [bn.g2_mul(p, k) for p, k in zip(points, scalars)]
    n = len(points)
    pts = b"".join(_g2_buf(p)[0] for p in points)
    infs = (ctypes.c_int * n)(*[1 if p is None else 0 for p in points])
    ks = b"".join(_i2b(k) for k in scalars)
    out = ctypes.create_string_buffer(128 * n)
    oinf = (ctypes.c_int * n)()
    lib.bn254_g2_mul_batch(out, oinf, pts, infs, ks, n)
    raw = bytes(out)
    res = []
    for i in range(n):
        if oinf[i]:
            res.append(None)
            continue
        o = raw[128 * i : 128 * (i + 1)]
        res.append(
            (
                (_b2i(o[:32]), _b2i(o[32:64])),
                (_b2i(o[64:96]), _b2i(o[96:128])),
            )
        )
    return res


def g1_sum(points):
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        acc = None
        for p in points:
            acc = bn.g1_add(acc, p)
        return acc
    n = len(points)
    pts = b"".join(_g1_buf(p)[0] for p in points)
    infs = (ctypes.c_int * n)(*[1 if p is None else 0 for p in points])
    out = ctypes.create_string_buffer(64)
    oinf = ctypes.c_int()
    lib.bn254_g1_sum(out, ctypes.byref(oinf), pts, infs, n)
    return _g1_out(out, oinf)


def g2_sum(points):
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        acc = None
        for p in points:
            acc = bn.g2_add(acc, p)
        return acc
    n = len(points)
    pts = b"".join(_g2_buf(p)[0] for p in points)
    infs = (ctypes.c_int * n)(*[1 if p is None else 0 for p in points])
    out = ctypes.create_string_buffer(128)
    oinf = ctypes.c_int()
    lib.bn254_g2_sum(out, ctypes.byref(oinf), pts, infs, n)
    return _g2_out(out, oinf)


def pairing_check(pairs) -> bool:
    """prod e(p_i, q_i) == 1 with one shared final exponentiation.

    pairs: sequence of (g1_point, g2_point) in oracle representation.
    Native when available, else the Python oracle (bn254_ref.pairing_check
    — note the oracle takes (p, q) in the same order)."""
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.pairing_check(pairs)
    n = len(pairs)
    g1s = b"".join(_g1_buf(p)[0] for p, _ in pairs)
    g1i = (ctypes.c_int * n)(*[1 if p is None else 0 for p, _ in pairs])
    g2s = b"".join(_g2_buf(q)[0] for _, q in pairs)
    g2i = (ctypes.c_int * n)(*[1 if q is None else 0 for _, q in pairs])
    return bool(lib.bn254_pairing_check(g1s, g1i, g2s, g2i, n))


def pairing(q, p):
    """e(P in G1, Q in G2') -> Fp12 in the oracle's nested-tuple form
    (argument order matches bn254_ref.pairing(q, p)). Infinity inputs give
    the GT identity, matching the oracle."""
    if p is None or q is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.F12_ONE
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.pairing(q, p)
    g1, _ = _g1_buf(p)
    g2, _ = _g2_buf(q)
    out = ctypes.create_string_buffer(384)
    lib.bn254_pairing(out, g1, g2)
    return _f12_out(bytes(out))


def miller(q, p):
    """Miller loop only (no final exponentiation), oracle nested-tuple form
    (argument order matches bn254_ref.miller_loop_projective(q, p))."""
    if p is None or q is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.F12_ONE
    lib = load()
    if lib is None:
        from handel_tpu.ops import bn254_ref as bn

        return bn.miller_loop_projective(q, p)
    g1, _ = _g1_buf(p)
    g2, _ = _g2_buf(q)
    out = ctypes.create_string_buffer(384)
    lib.bn254_miller(out, g1, g2)
    return _f12_out(bytes(out))

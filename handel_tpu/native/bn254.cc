// Host-native BN254 group arithmetic — the framework's C++ fast path.
//
// Role: the reference gets host-speed field arithmetic from the amd64/arm64
// assembly inside its cloudflare/bn256 dependency (SURVEY.md §2.2); this
// library is the equivalent native layer for the host side of the TPU build:
// keygen, signing, point aggregation, and registry construction at
// 4000-node simulation scale, where the pure-Python scalar oracle
// (ops/bn254_ref.py) is orders of magnitude too slow — plus the host-side
// pairing (Fp6/Fp12 tower, Miller loop, final exponentiation) used by
// BN254PublicKey.verify and the gossip baselines. Batched device
// verification stays on the JAX/Pallas path (ops/).
//
// Design: 4x64-bit limb Montgomery arithmetic (CIOS with __uint128_t),
// Jacobian coordinates for G1 (over Fp, y^2 = x^3 + 3) and G2 (over Fp2 on
// the twist, y^2 = x^3 + b'), double-and-add scalar multiplication.
// Exposed as a flat C ABI for ctypes (handel_tpu/native/__init__.py):
// points cross the boundary as 32-byte little-endian affine coordinates
// plus an infinity flag; scalars as 32-byte little-endian.
//
// Correctness oracle: ops/bn254_ref.py (g1_add/g2_add/g1_mul/g2_mul);
// cross-checked in tests/test_native.py.

#include <cstdint>
#include <cstring>

using u64 = uint64_t;
using u128 = __uint128_t;

namespace {

// ---- Fp: 4x64 Montgomery ----------------------------------------------

struct Fp {
  u64 v[4];
};

static const Fp P = {{0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                      0xb85045b68181585dULL, 0x30644e72e131a029ULL}};
static const u64 N0 = 0x87d20782e4866389ULL;  // -p^{-1} mod 2^64
static const Fp R2 = {{0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                       0x47ab1eff0a417ff6ULL, 0x6d89f71cab8351fULL}};
static const Fp ONE_M = {{0xd35d438dc58f0d9dULL, 0xa78eb28f5c70b3dULL,
                          0x666ea36f7879462cULL, 0xe0a77c19a07df2fULL}};

static inline bool ge_p(const Fp &a) {
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] > P.v[i]) return true;
    if (a.v[i] < P.v[i]) return false;
  }
  return true;  // equal
}

static inline void sub_p(Fp &a) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - P.v[i] - borrow;
    a.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

static inline void fp_add(Fp &out, const Fp &a, const Fp &b) {
  u128 carry = 0;
  bool overflow = false;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.v[i] + b.v[i] + carry;
    out.v[i] = (u64)s;
    carry = s >> 64;
  }
  overflow = carry != 0;
  if (overflow || ge_p(out)) sub_p(out);
}

static inline void fp_sub(Fp &out, const Fp &a, const Fp &b) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - b.v[i] - borrow;
    out.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {  // add p back
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
      u128 s = (u128)out.v[i] + P.v[i] + carry;
      out.v[i] = (u64)s;
      carry = s >> 64;
    }
  }
}

static inline void fp_neg(Fp &out, const Fp &a) {
  bool zero = !(a.v[0] | a.v[1] | a.v[2] | a.v[3]);
  if (zero) {
    out = a;
    return;
  }
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)P.v[i] - a.v[i] - borrow;
    out.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

// CIOS Montgomery multiplication: out = a * b * R^{-1} mod p
static inline void fp_mul(Fp &out, const Fp &a, const Fp &b) {
  u64 t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    // t += a[i] * b
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 s = (u128)a.v[i] * b.v[j] + t[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s4 = (u128)t[4] + carry;
    t[4] = (u64)s4;
    t[5] = (u64)(s4 >> 64);
    // reduce: m = t[0] * n0 mod 2^64; t += m * p; t >>= 64
    u64 m = t[0] * N0;
    carry = ((u128)m * P.v[0] + t[0]) >> 64;
    for (int j = 1; j < 4; ++j) {
      u128 s = (u128)m * P.v[j] + t[j] + carry;
      t[j - 1] = (u64)s;
      carry = s >> 64;
    }
    u128 s5 = (u128)t[4] + carry;
    t[3] = (u64)s5;
    t[4] = t[5] + (u64)(s5 >> 64);
    t[5] = 0;
  }
  out.v[0] = t[0];
  out.v[1] = t[1];
  out.v[2] = t[2];
  out.v[3] = t[3];
  if (t[4] || ge_p(out)) sub_p(out);
}

static inline void fp_sqr(Fp &out, const Fp &a) { fp_mul(out, a, a); }

static inline bool fp_is_zero(const Fp &a) {
  return !(a.v[0] | a.v[1] | a.v[2] | a.v[3]);
}

static inline void fp_to_mont(Fp &out, const Fp &a) { fp_mul(out, a, R2); }

static inline void fp_from_mont(Fp &out, const Fp &a) {
  Fp one = {{1, 0, 0, 0}};
  fp_mul(out, a, one);
}

// a^e by square-and-multiply (e not secret here: public curve math)
static void fp_pow(Fp &out, const Fp &a, const Fp &e) {
  Fp acc = ONE_M;
  for (int i = 3; i >= 0; --i) {
    for (int b = 63; b >= 0; --b) {
      fp_sqr(acc, acc);
      if ((e.v[i] >> b) & 1) fp_mul(acc, acc, a);
    }
  }
  out = acc;
}

static void fp_inv(Fp &out, const Fp &a) {
  // Fermat: a^(p-2)
  Fp e = P;
  u128 borrow = 2;
  for (int i = 0; i < 4 && borrow; ++i) {
    u128 d = (u128)e.v[i] - borrow;
    e.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  fp_pow(out, a, e);
}

// ---- Fp2 = Fp[i]/(i^2+1) ----------------------------------------------

struct Fp2 {
  Fp c0, c1;
};

static inline void f2_add(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  fp_add(o.c0, a.c0, b.c0);
  fp_add(o.c1, a.c1, b.c1);
}
static inline void f2_sub(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  fp_sub(o.c0, a.c0, b.c0);
  fp_sub(o.c1, a.c1, b.c1);
}
static inline void f2_neg(Fp2 &o, const Fp2 &a) {
  fp_neg(o.c0, a.c0);
  fp_neg(o.c1, a.c1);
}
static inline void f2_mul(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  Fp t0, t1, t2, t3;
  fp_mul(t0, a.c0, b.c0);
  fp_mul(t1, a.c1, b.c1);
  fp_add(t2, a.c0, a.c1);
  fp_add(t3, b.c0, b.c1);
  fp_mul(t2, t2, t3);  // (a0+a1)(b0+b1)
  Fp r0;
  fp_sub(r0, t0, t1);  // a0b0 - a1b1
  fp_sub(t2, t2, t0);
  fp_sub(t2, t2, t1);  // cross
  o.c0 = r0;
  o.c1 = t2;
}
static inline void f2_sqr(Fp2 &o, const Fp2 &a) { f2_mul(o, a, a); }
static inline bool f2_is_zero(const Fp2 &a) {
  return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static void f2_inv(Fp2 &o, const Fp2 &a) {
  // 1/(c0 + c1 i) = (c0 - c1 i) / (c0^2 + c1^2)
  Fp n, t0, t1;
  fp_sqr(t0, a.c0);
  fp_sqr(t1, a.c1);
  fp_add(n, t0, t1);
  fp_inv(n, n);
  fp_mul(o.c0, a.c0, n);
  Fp neg;
  fp_neg(neg, a.c1);
  fp_mul(o.c1, neg, n);
}

// ---- generic Jacobian curve ops over a field F -------------------------
// (X, Y, Z): x = X/Z^2, y = Y/Z^3; infinity: Z == 0.

template <typename F>
struct CurveOps {
  void (*add)(F &, const F &, const F &);
  void (*sub)(F &, const F &, const F &);
  void (*mul)(F &, const F &, const F &);
  void (*sqr)(F &, const F &);
  void (*neg)(F &, const F &);
  void (*inv)(F &, const F &);
  bool (*is_zero)(const F &);
  F b;  // curve coefficient (Montgomery form)
};

template <typename F>
struct Jac {
  F X, Y, Z;
  bool inf;
};

template <typename F>
static void jac_double(const CurveOps<F> &ops, Jac<F> &o, const Jac<F> &p) {
  if (p.inf || ops.is_zero(p.Y)) {
    o.inf = true;
    return;
  }
  // alias-safe: o may be the same object as p, so everything is computed
  // into locals and assigned at the end
  F A, B, C, D, t0, t1, X3, Y3, Z3;
  ops.sqr(A, p.X);              // X^2
  ops.sqr(B, p.Y);              // Y^2
  ops.sqr(C, B);                // Y^4
  ops.add(t0, p.X, B);
  ops.sqr(t0, t0);
  ops.sub(t0, t0, A);
  ops.sub(t0, t0, C);
  ops.add(D, t0, t0);           // D = 2((X+B)^2 - A - C)
  ops.add(t0, A, A);
  ops.add(t0, t0, A);           // E = 3A
  F E = t0;
  ops.sqr(t1, E);               // E^2
  ops.sub(t1, t1, D);
  ops.sub(X3, t1, D);           // X3 = E^2 - 2D
  ops.sub(t1, D, X3);
  ops.mul(t1, E, t1);
  F c8;
  ops.add(c8, C, C);
  ops.add(c8, c8, c8);
  ops.add(c8, c8, c8);          // 8C
  ops.sub(Y3, t1, c8);
  ops.mul(t1, p.Y, p.Z);
  ops.add(Z3, t1, t1);          // Z3 = 2YZ
  o.X = X3;
  o.Y = Y3;
  o.Z = Z3;
  o.inf = false;
}

template <typename F>
static void jac_add(const CurveOps<F> &ops, Jac<F> &o, const Jac<F> &p,
                    const Jac<F> &q) {
  if (p.inf) {
    o = q;
    return;
  }
  if (q.inf) {
    o = p;
    return;
  }
  F Z1Z1, Z2Z2, U1, U2, S1, S2, t0;
  ops.sqr(Z1Z1, p.Z);
  ops.sqr(Z2Z2, q.Z);
  ops.mul(U1, p.X, Z2Z2);
  ops.mul(U2, q.X, Z1Z1);
  ops.mul(t0, q.Z, Z2Z2);
  ops.mul(S1, p.Y, t0);
  ops.mul(t0, p.Z, Z1Z1);
  ops.mul(S2, q.Y, t0);
  F H, Rr;
  ops.sub(H, U2, U1);
  ops.sub(Rr, S2, S1);
  if (ops.is_zero(H)) {
    if (ops.is_zero(Rr)) {
      jac_double(ops, o, p);
      return;
    }
    o.inf = true;
    return;
  }
  // alias-safe: o may be p or q; compute into locals, assign at the end
  F HH, HHH, V, X3, Y3, Z3;
  ops.sqr(HH, H);
  ops.mul(HHH, H, HH);
  ops.mul(V, U1, HH);
  ops.sqr(X3, Rr);
  ops.sub(X3, X3, HHH);
  ops.sub(X3, X3, V);
  ops.sub(X3, X3, V);
  ops.sub(t0, V, X3);
  ops.mul(t0, Rr, t0);
  F t1;
  ops.mul(t1, S1, HHH);
  ops.sub(Y3, t0, t1);
  ops.mul(t0, p.Z, q.Z);
  ops.mul(Z3, t0, H);
  o.X = X3;
  o.Y = Y3;
  o.Z = Z3;
  o.inf = false;
}

template <typename F>
static void jac_mul(const CurveOps<F> &ops, Jac<F> &o, const Jac<F> &p,
                    const u64 k[4]) {
  Jac<F> acc;
  acc.inf = true;
  bool started = false;
  for (int i = 3; i >= 0; --i) {
    for (int b = 63; b >= 0; --b) {
      if (started) jac_double(ops, acc, acc);
      if ((k[i] >> b) & 1) {
        if (acc.inf)
          acc = p;
        else
          jac_add(ops, acc, acc, p);
        started = true;
      } else if (!started) {
        continue;
      }
    }
  }
  o = acc;
}

template <typename F>
static void jac_to_affine(const CurveOps<F> &ops, F &x, F &y, bool &inf,
                          const Jac<F> &p) {
  if (p.inf || ops.is_zero(p.Z)) {
    inf = true;
    return;
  }
  F zi, zi2, zi3;
  ops.inv(zi, p.Z);
  ops.sqr(zi2, zi);
  ops.mul(zi3, zi2, zi);
  ops.mul(x, p.X, zi2);
  ops.mul(y, p.Y, zi3);
  inf = false;
}

// instantiate for Fp and Fp2
static const CurveOps<Fp> G1OPS = {fp_add, fp_sub, fp_mul, fp_sqr,
                                   fp_neg, fp_inv, fp_is_zero, Fp{}};
static const CurveOps<Fp2> G2OPS = {f2_add, f2_sub, f2_mul, f2_sqr,
                                    f2_neg, f2_inv, f2_is_zero, Fp2{}};

// ---- byte-buffer marshalling -------------------------------------------

static void load_fp(Fp &out, const uint8_t *b) {
  Fp raw;
  std::memcpy(raw.v, b, 32);  // little-endian limbs
  fp_to_mont(out, raw);
}

static void store_fp(uint8_t *b, const Fp &a) {
  Fp raw;
  fp_from_mont(raw, a);
  std::memcpy(b, raw.v, 32);
}

static void load_g1(Jac<Fp> &p, const uint8_t *xy, int inf) {
  p.inf = inf != 0;
  if (p.inf) return;
  load_fp(p.X, xy);
  load_fp(p.Y, xy + 32);
  p.Z = ONE_M;
}

static void store_g1(uint8_t *xy, int *inf, const Jac<Fp> &p) {
  Fp x, y;
  bool isinf;
  jac_to_affine(G1OPS, x, y, isinf, p);
  *inf = isinf ? 1 : 0;
  if (!isinf) {
    store_fp(xy, x);
    store_fp(xy + 32, y);
  } else {
    std::memset(xy, 0, 64);
  }
}

static void load_g2(Jac<Fp2> &p, const uint8_t *xy, int inf) {
  p.inf = inf != 0;
  if (p.inf) return;
  load_fp(p.X.c0, xy);
  load_fp(p.X.c1, xy + 32);
  load_fp(p.Y.c0, xy + 64);
  load_fp(p.Y.c1, xy + 96);
  p.Z.c0 = ONE_M;
  std::memset(p.Z.c1.v, 0, 32);
}

static void store_g2(uint8_t *xy, int *inf, const Jac<Fp2> &p) {
  Fp2 x, y;
  bool isinf;
  jac_to_affine(G2OPS, x, y, isinf, p);
  *inf = isinf ? 1 : 0;
  if (!isinf) {
    store_fp(xy, x.c0);
    store_fp(xy + 32, x.c1);
    store_fp(xy + 64, y.c0);
    store_fp(xy + 96, y.c1);
  } else {
    std::memset(xy, 0, 128);
  }
}

// ---- pairing: Fp6/Fp12 tower, Miller loop, final exponentiation --------
// Mirrors the scalar oracle (ops/bn254_ref.py): Fp6 = Fp2[v]/(v^3 - xi)
// with xi = 9+i, Fp12 = Fp6[w]/(w^2 - v), inversion-free projective Miller
// loop on the twist, easy+hard-part final exponentiation. This is the host
// verify fast path — the role of the assembly-backed cloudflare/bn256 `Pair`
// in the reference (bn256/cf/bn256.go:92-93).

static inline void f2_scalar_small(Fp2 &o, const Fp2 &a, int k) {
  Fp2 acc = a;
  for (int i = 1; i < k; ++i) f2_add(acc, acc, a);
  o = acc;
}

static inline void f2_mul_xi(Fp2 &o, const Fp2 &a) {
  // (9a0 - a1) + (9a1 + a0) i
  Fp2 nine;
  f2_scalar_small(nine, a, 9);
  Fp r0, r1;
  fp_sub(r0, nine.c0, a.c1);
  fp_add(r1, nine.c1, a.c0);
  o.c0 = r0;
  o.c1 = r1;
}

static inline void f2_conj(Fp2 &o, const Fp2 &a) {
  o.c0 = a.c0;
  fp_neg(o.c1, a.c1);
}

struct Fp6 {
  Fp2 c0, c1, c2;
};
struct Fp12 {
  Fp6 c0, c1;
};

static inline void f6_add(Fp6 &o, const Fp6 &a, const Fp6 &b) {
  f2_add(o.c0, a.c0, b.c0);
  f2_add(o.c1, a.c1, b.c1);
  f2_add(o.c2, a.c2, b.c2);
}
static inline void f6_sub(Fp6 &o, const Fp6 &a, const Fp6 &b) {
  f2_sub(o.c0, a.c0, b.c0);
  f2_sub(o.c1, a.c1, b.c1);
  f2_sub(o.c2, a.c2, b.c2);
}
static inline void f6_neg(Fp6 &o, const Fp6 &a) {
  f2_neg(o.c0, a.c0);
  f2_neg(o.c1, a.c1);
  f2_neg(o.c2, a.c2);
}

static void f6_mul(Fp6 &o, const Fp6 &a, const Fp6 &b) {
  // Toom/Karatsuba interpolation (bn254_ref.f6_mul)
  Fp2 t0, t1, t2, s1, s2, u;
  f2_mul(t0, a.c0, b.c0);
  f2_mul(t1, a.c1, b.c1);
  f2_mul(t2, a.c2, b.c2);
  Fp2 r0, r1, r2;
  // c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
  f2_add(s1, a.c1, a.c2);
  f2_add(s2, b.c1, b.c2);
  f2_mul(u, s1, s2);
  f2_sub(u, u, t1);
  f2_sub(u, u, t2);
  f2_mul_xi(u, u);
  f2_add(r0, t0, u);
  // c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
  f2_add(s1, a.c0, a.c1);
  f2_add(s2, b.c0, b.c1);
  f2_mul(u, s1, s2);
  f2_sub(u, u, t0);
  f2_sub(u, u, t1);
  Fp2 xt2;
  f2_mul_xi(xt2, t2);
  f2_add(r1, u, xt2);
  // c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
  f2_add(s1, a.c0, a.c2);
  f2_add(s2, b.c0, b.c2);
  f2_mul(u, s1, s2);
  f2_sub(u, u, t0);
  f2_sub(u, u, t2);
  f2_add(r2, u, t1);
  o.c0 = r0;
  o.c1 = r1;
  o.c2 = r2;
}

static inline void f6_mul_v(Fp6 &o, const Fp6 &a) {
  Fp2 t;
  f2_mul_xi(t, a.c2);
  Fp2 c0 = a.c0, c1 = a.c1;
  o.c0 = t;
  o.c1 = c0;
  o.c2 = c1;
}

static void f6_inv(Fp6 &o, const Fp6 &a) {
  Fp2 t0, t1, t2, u, den, inv;
  // t0 = a0^2 - xi*a1*a2
  f2_sqr(t0, a.c0);
  f2_mul(u, a.c1, a.c2);
  f2_mul_xi(u, u);
  f2_sub(t0, t0, u);
  // t1 = xi*a2^2 - a0*a1
  f2_sqr(t1, a.c2);
  f2_mul_xi(t1, t1);
  f2_mul(u, a.c0, a.c1);
  f2_sub(t1, t1, u);
  // t2 = a1^2 - a0*a2
  f2_sqr(t2, a.c1);
  f2_mul(u, a.c0, a.c2);
  f2_sub(t2, t2, u);
  // den = a0*t0 + xi*(a2*t1 + a1*t2)
  Fp2 d1, d2;
  f2_mul(d1, a.c2, t1);
  f2_mul(d2, a.c1, t2);
  f2_add(u, d1, d2);
  f2_mul_xi(u, u);
  f2_mul(den, a.c0, t0);
  f2_add(den, den, u);
  f2_inv(inv, den);
  f2_mul(o.c0, t0, inv);
  f2_mul(o.c1, t1, inv);
  f2_mul(o.c2, t2, inv);
}

static inline void f12_mul(Fp12 &o, const Fp12 &a, const Fp12 &b) {
  Fp6 t0, t1, s0, s1, u;
  f6_mul(t0, a.c0, b.c0);
  f6_mul(t1, a.c1, b.c1);
  Fp6 r0, r1;
  f6_mul_v(u, t1);
  f6_add(r0, t0, u);
  f6_add(s0, a.c0, a.c1);
  f6_add(s1, b.c0, b.c1);
  f6_mul(u, s0, s1);
  f6_sub(u, u, t0);
  f6_sub(r1, u, t1);
  o.c0 = r0;
  o.c1 = r1;
}

static inline void f12_sqr(Fp12 &o, const Fp12 &a) { f12_mul(o, a, a); }

static inline void f12_conj(Fp12 &o, const Fp12 &a) {
  o.c0 = a.c0;
  f6_neg(o.c1, a.c1);
}

static void f12_inv(Fp12 &o, const Fp12 &a) {
  Fp6 t, u, den;
  Fp6 a0sq, a1sq;
  f6_mul(a0sq, a.c0, a.c0);
  f6_mul(a1sq, a.c1, a.c1);
  f6_mul_v(u, a1sq);
  f6_sub(den, a0sq, u);
  f6_inv(den, den);
  f6_mul(o.c0, a.c0, den);
  f6_mul(t, a.c1, den);
  f6_neg(o.c1, t);
}

// gamma_j = xi^(j*(p-1)/6) (raw, converted to Montgomery at init)
static const Fp2 GAMMA_RAW[5] = {
    {{{0xd60b35dadcc9e470ULL, 0x5c521e08292f2176ULL, 0xe8b99fdd76e68b60ULL,
       0x1284b71c2865a7dfULL}},
     {{0xca5cf05f80f362acULL, 0x747992778eeec7e5ULL, 0xa6327cfe12150b8eULL,
       0x246996f3b4fae7e6ULL}}},
    {{{0x99e39557176f553dULL, 0xb78cc310c2c3330cULL, 0x4c0bec3cf559b143ULL,
       0x2fb347984f7911f7ULL}},
     {{0x1665d51c640fcba2ULL, 0x32ae2a1d0b7c9dceULL, 0x4ba4cc8bd75a0794ULL,
       0x16c9e55061ebae20ULL}}},
    {{{0xdc54014671a0135aULL, 0xdbaae0eda9c95998ULL, 0xdc5ec698b6e2f9b9ULL,
       0x063cf305489af5dcULL}},
     {{0x82d37f632623b0e3ULL, 0x21807dc98fa25bd2ULL, 0x0704b5a7ec796f2bULL,
       0x07c03cbcac41049aULL}}},
    {{{0x848a1f55921ea762ULL, 0xd33365f7be94ec72ULL, 0x80f3c0b75a181e84ULL,
       0x05b54f5e64eea801ULL}},
     {{0xc13b4711cd2b8126ULL, 0x3685d2ea1bdec763ULL, 0x9f3a80b03b0b1c92ULL,
       0x2c145edbe7fd8aeeULL}}},
    {{{0x2ea2c810eab7692fULL, 0x425c459b55aa1bd3ULL, 0xe93a3661a4353ff4ULL,
       0x0183c1e74f798649ULL}},
     {{0x24c6b8ee6e0c2c4bULL, 0xb080cb99678e2ac0ULL, 0xa27fb246c7729f7dULL,
       0x12acf2ca76fd0675ULL}}},
};

static Fp2 GAMMA_M[6];  // 1-indexed Montgomery-form gammas
static bool gamma_ready = false;

static void init_gammas() {
  if (gamma_ready) return;
  for (int j = 1; j <= 5; ++j) {
    fp_to_mont(GAMMA_M[j].c0, GAMMA_RAW[j - 1].c0);
    fp_to_mont(GAMMA_M[j].c1, GAMMA_RAW[j - 1].c1);
  }
  gamma_ready = true;
}

static void f12_frobenius(Fp12 &o, const Fp12 &a) {
  // w-degrees (0, 2, 4) in c0 and (1, 3, 5) in c1 (bn254_ref.f12_frobenius)
  Fp2 t;
  f2_conj(o.c0.c0, a.c0.c0);
  f2_conj(t, a.c0.c1);
  f2_mul(o.c0.c1, t, GAMMA_M[2]);
  f2_conj(t, a.c0.c2);
  f2_mul(o.c0.c2, t, GAMMA_M[4]);
  f2_conj(t, a.c1.c0);
  f2_mul(o.c1.c0, t, GAMMA_M[1]);
  f2_conj(t, a.c1.c1);
  f2_mul(o.c1.c1, t, GAMMA_M[3]);
  f2_conj(t, a.c1.c2);
  f2_mul(o.c1.c2, t, GAMMA_M[5]);
}

static const u64 BN_U = 0x44e992b44a6909f1ULL;

static void f12_pow_u64(Fp12 &o, const Fp12 &a, u64 e) {
  Fp12 result, base = a;
  // result = 1
  std::memset(&result, 0, sizeof(result));
  result.c0.c0.c0 = ONE_M;
  while (e) {
    if (e & 1) f12_mul(result, result, base);
    f12_sqr(base, base);
    e >>= 1;
  }
  o = result;
}

struct TwistPt {  // affine twist point, never infinity on this path
  Fp2 x, y;
};

struct ProjPt {
  Fp2 X, Y, Z;
};

// doubling step + tangent line at T evaluated at P (bn254_ref dbl)
static void miller_dbl(ProjPt &T, Fp12 &line, const Fp &xp, const Fp &yp) {
  Fp2 XX, YY, YZ, n, d, XYY, XYYZ, e, t, t2;
  f2_sqr(XX, T.X);
  f2_sqr(YY, T.Y);
  f2_mul(YZ, T.Y, T.Z);
  f2_scalar_small(n, XX, 3);
  f2_add(d, YZ, YZ);
  f2_mul(XYY, T.X, YY);
  f2_mul(XYYZ, XYY, T.Z);
  f2_sqr(e, n);
  Fp2 x8;
  f2_scalar_small(x8, XYYZ, 8);
  f2_sub(e, e, x8);
  ProjPt T3;
  f2_mul(T3.X, e, d);
  Fp2 x12, nn, yyz2;
  f2_scalar_small(x12, XYYZ, 12);
  f2_sqr(nn, n);
  f2_sub(t, x12, nn);
  f2_mul(t, n, t);
  f2_sqr(t2, YY);
  f2_sqr(yyz2, T.Z);
  f2_mul(t2, t2, yyz2);
  f2_scalar_small(t2, t2, 8);
  f2_sub(T3.Y, t, t2);
  f2_sqr(t, d);
  f2_mul(T3.Z, t, d);
  // line: c0 = 2*Y*Z^2*yp, cw = -(3X^2*Z)*xp, cw3 = 3X^3 - 2Y^2*Z
  // (xp/yp are base-field, so Fp2-by-Fp scaling is two fp_muls)
  Fp2 c0, cw, cw3, nZ;
  f2_mul(t, YZ, T.Z);
  f2_add(t, t, t);
  fp_mul(c0.c0, t.c0, yp);
  fp_mul(c0.c1, t.c1, yp);
  f2_mul(nZ, n, T.Z);
  fp_mul(cw.c0, nZ.c0, xp);
  fp_mul(cw.c1, nZ.c1, xp);
  f2_neg(cw, cw);
  Fp2 nX, yyZ;
  f2_mul(nX, n, T.X);
  f2_mul(yyZ, YY, T.Z);
  f2_add(yyZ, yyZ, yyZ);
  f2_sub(cw3, nX, yyZ);
  std::memset(&line, 0, sizeof(line));
  line.c0.c0 = c0;
  line.c1.c0 = cw;
  line.c1.c1 = cw3;
  T = T3;
}

// mixed addition step T + Q + line through them at P (bn254_ref add)
static void miller_add(ProjPt &T, Fp12 &line, const TwistPt &Q, const Fp &xp,
                       const Fp &yp) {
  Fp2 n, d, dd, x2Z, e, t, u;
  f2_mul(t, Q.y, T.Z);
  f2_sub(n, t, T.Y);
  f2_mul(t, Q.x, T.Z);
  f2_sub(d, t, T.X);
  f2_sqr(dd, d);
  f2_mul(x2Z, Q.x, T.Z);
  f2_sqr(e, n);
  f2_mul(e, e, T.Z);
  f2_add(t, T.X, x2Z);
  f2_mul(t, t, dd);
  f2_sub(e, e, t);
  ProjPt T3;
  f2_mul(T3.X, e, d);
  f2_mul(t, x2Z, dd);
  f2_sub(t, t, e);
  f2_mul(t, n, t);
  Fp2 ddd, y2Z;
  f2_mul(ddd, dd, d);
  f2_mul(y2Z, Q.y, T.Z);
  f2_mul(u, y2Z, ddd);
  f2_sub(T3.Y, t, u);
  f2_mul(T3.Z, T.Z, ddd);
  // line: c0 = d*yp, cw = -n*xp, cw3 = n*x2 - d*y2
  Fp2 c0, cw, cw3;
  fp_mul(c0.c0, d.c0, yp);
  fp_mul(c0.c1, d.c1, yp);
  fp_mul(cw.c0, n.c0, xp);
  fp_mul(cw.c1, n.c1, xp);
  f2_neg(cw, cw);
  Fp2 nx2, dy2;
  f2_mul(nx2, n, Q.x);
  f2_mul(dy2, d, Q.y);
  f2_sub(cw3, nx2, dy2);
  std::memset(&line, 0, sizeof(line));
  line.c0.c0 = c0;
  line.c1.c0 = cw;
  line.c1.c1 = cw3;
  T = T3;
}

// MSB-first bits of 6u+2 with the top bit dropped (64 steps)
static const char ATE_BITS[] =
    "1001110101111001011100000011100110111110011101100011101110101000";

static void miller_loop(Fp12 &f, const TwistPt &Q, const Fp &xp,
                        const Fp &yp) {
  init_gammas();
  ProjPt T;
  T.X = Q.x;
  T.Y = Q.y;
  std::memset(&T.Z, 0, sizeof(T.Z));
  T.Z.c0 = ONE_M;
  std::memset(&f, 0, sizeof(f));
  f.c0.c0.c0 = ONE_M;
  Fp12 line;
  for (const char *b = ATE_BITS; *b; ++b) {
    f12_sqr(f, f);
    miller_dbl(T, line, xp, yp);
    f12_mul(f, f, line);
    if (*b == '1') {
      miller_add(T, line, Q, xp, yp);
      f12_mul(f, f, line);
    }
  }
  // Frobenius corrections: q1 = psi(Q), q2 = -psi^2(Q)
  TwistPt q1, q2;
  Fp2 t;
  f2_conj(t, Q.x);
  f2_mul(q1.x, t, GAMMA_M[2]);
  f2_conj(t, Q.y);
  f2_mul(q1.y, t, GAMMA_M[3]);
  f2_conj(t, q1.x);
  f2_mul(q2.x, t, GAMMA_M[2]);
  f2_conj(t, q1.y);
  f2_mul(q2.y, t, GAMMA_M[3]);
  f2_neg(q2.y, q2.y);
  miller_add(T, line, q1, xp, yp);
  f12_mul(f, f, line);
  miller_add(T, line, q2, xp, yp);
  f12_mul(f, f, line);
}

static void final_exp(Fp12 &o, const Fp12 &f_in) {
  init_gammas();
  Fp12 f, t;
  // easy part: f^(p^6-1) = conj(f)*f^-1, then ^(p^2+1)
  f12_inv(t, f_in);
  f12_conj(f, f_in);
  f12_mul(f, f, t);
  Fp12 fr2;
  f12_frobenius(fr2, f);
  f12_frobenius(fr2, fr2);
  f12_mul(f, fr2, f);

  // hard part (Scott et al. chain; bn254_ref.final_exponentiation)
  Fp12 fu, fu2, fu3, fp1, fp2_, fp3;
  f12_pow_u64(fu, f, BN_U);
  f12_pow_u64(fu2, fu, BN_U);
  f12_pow_u64(fu3, fu2, BN_U);
  f12_frobenius(fp1, f);
  f12_frobenius(fp2_, fp1);
  f12_frobenius(fp3, fp2_);
  Fp12 y0, y1, y2, y3, y4, y5, y6;
  f12_mul(y0, fp1, fp2_);
  f12_mul(y0, y0, fp3);
  f12_conj(y1, f);
  f12_frobenius(y2, fu2);
  f12_frobenius(y2, y2);
  f12_frobenius(y3, fu);
  f12_conj(y3, y3);
  f12_frobenius(y4, fu2);
  f12_mul(y4, fu, y4);
  f12_conj(y4, y4);
  f12_conj(y5, fu2);
  f12_frobenius(y6, fu3);
  f12_mul(y6, fu3, y6);
  f12_conj(y6, y6);

  Fp12 t0, t1;
  f12_sqr(t0, y6);
  f12_mul(t0, t0, y4);
  f12_mul(t0, t0, y5);
  f12_mul(t1, y3, y5);
  f12_mul(t1, t1, t0);
  f12_mul(t0, t0, y2);
  f12_sqr(t1, t1);
  f12_mul(t1, t1, t0);
  f12_sqr(t1, t1);
  f12_mul(t0, t1, y1);
  f12_mul(t1, t1, y0);
  f12_sqr(t0, t0);
  f12_mul(o, t0, t1);
}

static bool f12_is_one(const Fp12 &a) {
  Fp12 one;
  std::memset(&one, 0, sizeof(one));
  one.c0.c0.c0 = ONE_M;
  return std::memcmp(&a, &one, sizeof(Fp12)) == 0;
}

}  // namespace

// ---- C ABI --------------------------------------------------------------

extern "C" {

// G1 points: 64-byte affine (x ‖ y), scalars: 32-byte little-endian.
void bn254_g1_add(uint8_t *out, int *out_inf, const uint8_t *a, int a_inf,
                  const uint8_t *b, int b_inf) {
  Jac<Fp> P1, P2, S;
  load_g1(P1, a, a_inf);
  load_g1(P2, b, b_inf);
  jac_add(G1OPS, S, P1, P2);
  store_g1(out, out_inf, S);
}

void bn254_g1_mul(uint8_t *out, int *out_inf, const uint8_t *a, int a_inf,
                  const uint8_t *scalar) {
  Jac<Fp> P1, S;
  load_g1(P1, a, a_inf);
  u64 k[4];
  std::memcpy(k, scalar, 32);
  jac_mul(G1OPS, S, P1, k);
  store_g1(out, out_inf, S);
}

// G2 points: 128-byte affine (x0 ‖ x1 ‖ y0 ‖ y1).
void bn254_g2_add(uint8_t *out, int *out_inf, const uint8_t *a, int a_inf,
                  const uint8_t *b, int b_inf) {
  Jac<Fp2> P1, P2, S;
  load_g2(P1, a, a_inf);
  load_g2(P2, b, b_inf);
  jac_add(G2OPS, S, P1, P2);
  store_g2(out, out_inf, S);
}

void bn254_g2_mul(uint8_t *out, int *out_inf, const uint8_t *a, int a_inf,
                  const uint8_t *scalar) {
  Jac<Fp2> P1, S;
  load_g2(P1, a, a_inf);
  u64 k[4];
  std::memcpy(k, scalar, 32);
  jac_mul(G2OPS, S, P1, k);
  store_g2(out, out_inf, S);
}

// Batch multi-scalar entry points: n independent muls in one call
// (amortizes the ctypes crossing for registry-scale keygen).
void bn254_g1_mul_batch(uint8_t *out, int *out_inf, const uint8_t *pts,
                        const int *infs, const uint8_t *scalars, int n) {
  for (int i = 0; i < n; ++i)
    bn254_g1_mul(out + 64 * i, out_inf + i, pts + 64 * i, infs[i],
                 scalars + 32 * i);
}

void bn254_g2_mul_batch(uint8_t *out, int *out_inf, const uint8_t *pts,
                        const int *infs, const uint8_t *scalars, int n) {
  for (int i = 0; i < n; ++i)
    bn254_g2_mul(out + 128 * i, out_inf + i, pts + 128 * i, infs[i],
                 scalars + 32 * i);
}

// Sum of n G1 points (the host-side Combine fallback when no device).
void bn254_g1_sum(uint8_t *out, int *out_inf, const uint8_t *pts,
                  const int *infs, int n) {
  Jac<Fp> acc, Q;
  acc.inf = true;
  for (int i = 0; i < n; ++i) {
    load_g1(Q, pts + 64 * i, infs[i]);
    jac_add(G1OPS, acc, acc, Q);
  }
  store_g1(out, out_inf, acc);
}

void bn254_g2_sum(uint8_t *out, int *out_inf, const uint8_t *pts,
                  const int *infs, int n) {
  Jac<Fp2> acc, Q;
  acc.inf = true;
  for (int i = 0; i < n; ++i) {
    load_g2(Q, pts + 128 * i, infs[i]);
    jac_add(G2OPS, acc, acc, Q);
  }
  store_g2(out, out_inf, acc);
}

// Product-of-pairings check: prod e(P_i, Q_i) == 1, one shared final
// exponentiation (the reference's verify at bn256/cf/bn256.go:86-98 as a
// single product; same structure as the device kernel's pairing_check).
// g1 points: 64-byte affine x||y little-endian limbs; g2 points: 128-byte
// affine x0||x1||y0||y1. Infinity pairs contribute 1 and are skipped.
int bn254_pairing_check(const uint8_t *g1s, const int *g1_infs,
                        const uint8_t *g2s, const int *g2_infs, int n) {
  init_gammas();
  Fp12 acc;
  std::memset(&acc, 0, sizeof(acc));
  acc.c0.c0.c0 = ONE_M;
  for (int i = 0; i < n; ++i) {
    if (g1_infs[i] || g2_infs[i]) continue;
    Fp xp, yp;
    load_fp(xp, g1s + 64 * i);
    load_fp(yp, g1s + 64 * i + 32);
    TwistPt Q;
    load_fp(Q.x.c0, g2s + 128 * i);
    load_fp(Q.x.c1, g2s + 128 * i + 32);
    load_fp(Q.y.c0, g2s + 128 * i + 64);
    load_fp(Q.y.c1, g2s + 128 * i + 96);
    Fp12 f;
    miller_loop(f, Q, xp, yp);
    f12_mul(acc, acc, f);
  }
  Fp12 out;
  final_exp(out, acc);
  return f12_is_one(out) ? 1 : 0;
}

// e(P, Q) marshaled out as 12 Fp values (c0.c0.c0.c0, c0.c0.c1, ... raw
// little-endian limb order, 384 bytes) — used by the cross-check tests.
void bn254_pairing(uint8_t *out, const uint8_t *g1, const uint8_t *g2) {
  init_gammas();
  Fp xp, yp;
  load_fp(xp, g1);
  load_fp(yp, g1 + 32);
  TwistPt Q;
  load_fp(Q.x.c0, g2);
  load_fp(Q.x.c1, g2 + 32);
  load_fp(Q.y.c0, g2 + 64);
  load_fp(Q.y.c1, g2 + 96);
  Fp12 f, e;
  miller_loop(f, Q, xp, yp);
  final_exp(e, f);
  const Fp2 *coords[6] = {&e.c0.c0, &e.c0.c1, &e.c0.c2,
                          &e.c1.c0, &e.c1.c1, &e.c1.c2};
  for (int i = 0; i < 6; ++i) {
    store_fp(out + 64 * i, coords[i]->c0);
    store_fp(out + 64 * i + 32, coords[i]->c1);
  }
}

// Miller loop only (no final exp) — oracle cross-check seam.
void bn254_miller(uint8_t *out, const uint8_t *g1, const uint8_t *g2) {
  init_gammas();
  Fp xp, yp;
  load_fp(xp, g1);
  load_fp(yp, g1 + 32);
  TwistPt Q;
  load_fp(Q.x.c0, g2);
  load_fp(Q.x.c1, g2 + 32);
  load_fp(Q.y.c0, g2 + 64);
  load_fp(Q.y.c1, g2 + 96);
  Fp12 f;
  miller_loop(f, Q, xp, yp);
  const Fp2 *coords[6] = {&f.c0.c0, &f.c0.c1, &f.c0.c2,
                          &f.c1.c0, &f.c1.c1, &f.c1.c2};
  for (int i = 0; i < 6; ++i) {
    store_fp(out + 64 * i, coords[i]->c0);
    store_fp(out + 64 * i + 32, coords[i]->c1);
  }
}

int bn254_native_version() { return 1; }

}  // extern "C"

// Host-native BN254 group arithmetic — the framework's C++ fast path.
//
// Role: the reference gets host-speed field arithmetic from the amd64/arm64
// assembly inside its cloudflare/bn256 dependency (SURVEY.md §2.2); this
// library is the equivalent native layer for the host side of the TPU build:
// keygen, signing, point aggregation, and registry construction at
// 4000-node simulation scale, where the pure-Python scalar oracle
// (ops/bn254_ref.py) is orders of magnitude too slow. Device verification
// stays on the JAX/Pallas path (ops/); this code never does pairings.
//
// Design: 4x64-bit limb Montgomery arithmetic (CIOS with __uint128_t),
// Jacobian coordinates for G1 (over Fp, y^2 = x^3 + 3) and G2 (over Fp2 on
// the twist, y^2 = x^3 + b'), double-and-add scalar multiplication.
// Exposed as a flat C ABI for ctypes (handel_tpu/native/__init__.py):
// points cross the boundary as 32-byte little-endian affine coordinates
// plus an infinity flag; scalars as 32-byte little-endian.
//
// Correctness oracle: ops/bn254_ref.py (g1_add/g2_add/g1_mul/g2_mul);
// cross-checked in tests/test_native.py.

#include <cstdint>
#include <cstring>

using u64 = uint64_t;
using u128 = __uint128_t;

namespace {

// ---- Fp: 4x64 Montgomery ----------------------------------------------

struct Fp {
  u64 v[4];
};

static const Fp P = {{0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                      0xb85045b68181585dULL, 0x30644e72e131a029ULL}};
static const u64 N0 = 0x87d20782e4866389ULL;  // -p^{-1} mod 2^64
static const Fp R2 = {{0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                       0x47ab1eff0a417ff6ULL, 0x6d89f71cab8351fULL}};
static const Fp ONE_M = {{0xd35d438dc58f0d9dULL, 0xa78eb28f5c70b3dULL,
                          0x666ea36f7879462cULL, 0xe0a77c19a07df2fULL}};

static inline bool ge_p(const Fp &a) {
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] > P.v[i]) return true;
    if (a.v[i] < P.v[i]) return false;
  }
  return true;  // equal
}

static inline void sub_p(Fp &a) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - P.v[i] - borrow;
    a.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

static inline void fp_add(Fp &out, const Fp &a, const Fp &b) {
  u128 carry = 0;
  bool overflow = false;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.v[i] + b.v[i] + carry;
    out.v[i] = (u64)s;
    carry = s >> 64;
  }
  overflow = carry != 0;
  if (overflow || ge_p(out)) sub_p(out);
}

static inline void fp_sub(Fp &out, const Fp &a, const Fp &b) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - b.v[i] - borrow;
    out.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {  // add p back
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
      u128 s = (u128)out.v[i] + P.v[i] + carry;
      out.v[i] = (u64)s;
      carry = s >> 64;
    }
  }
}

static inline void fp_neg(Fp &out, const Fp &a) {
  bool zero = !(a.v[0] | a.v[1] | a.v[2] | a.v[3]);
  if (zero) {
    out = a;
    return;
  }
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)P.v[i] - a.v[i] - borrow;
    out.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

// CIOS Montgomery multiplication: out = a * b * R^{-1} mod p
static inline void fp_mul(Fp &out, const Fp &a, const Fp &b) {
  u64 t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    // t += a[i] * b
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 s = (u128)a.v[i] * b.v[j] + t[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s4 = (u128)t[4] + carry;
    t[4] = (u64)s4;
    t[5] = (u64)(s4 >> 64);
    // reduce: m = t[0] * n0 mod 2^64; t += m * p; t >>= 64
    u64 m = t[0] * N0;
    carry = ((u128)m * P.v[0] + t[0]) >> 64;
    for (int j = 1; j < 4; ++j) {
      u128 s = (u128)m * P.v[j] + t[j] + carry;
      t[j - 1] = (u64)s;
      carry = s >> 64;
    }
    u128 s5 = (u128)t[4] + carry;
    t[3] = (u64)s5;
    t[4] = t[5] + (u64)(s5 >> 64);
    t[5] = 0;
  }
  out.v[0] = t[0];
  out.v[1] = t[1];
  out.v[2] = t[2];
  out.v[3] = t[3];
  if (t[4] || ge_p(out)) sub_p(out);
}

static inline void fp_sqr(Fp &out, const Fp &a) { fp_mul(out, a, a); }

static inline bool fp_is_zero(const Fp &a) {
  return !(a.v[0] | a.v[1] | a.v[2] | a.v[3]);
}

static inline void fp_to_mont(Fp &out, const Fp &a) { fp_mul(out, a, R2); }

static inline void fp_from_mont(Fp &out, const Fp &a) {
  Fp one = {{1, 0, 0, 0}};
  fp_mul(out, a, one);
}

// a^e by square-and-multiply (e not secret here: public curve math)
static void fp_pow(Fp &out, const Fp &a, const Fp &e) {
  Fp acc = ONE_M;
  for (int i = 3; i >= 0; --i) {
    for (int b = 63; b >= 0; --b) {
      fp_sqr(acc, acc);
      if ((e.v[i] >> b) & 1) fp_mul(acc, acc, a);
    }
  }
  out = acc;
}

static void fp_inv(Fp &out, const Fp &a) {
  // Fermat: a^(p-2)
  Fp e = P;
  u128 borrow = 2;
  for (int i = 0; i < 4 && borrow; ++i) {
    u128 d = (u128)e.v[i] - borrow;
    e.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  fp_pow(out, a, e);
}

// ---- Fp2 = Fp[i]/(i^2+1) ----------------------------------------------

struct Fp2 {
  Fp c0, c1;
};

static inline void f2_add(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  fp_add(o.c0, a.c0, b.c0);
  fp_add(o.c1, a.c1, b.c1);
}
static inline void f2_sub(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  fp_sub(o.c0, a.c0, b.c0);
  fp_sub(o.c1, a.c1, b.c1);
}
static inline void f2_neg(Fp2 &o, const Fp2 &a) {
  fp_neg(o.c0, a.c0);
  fp_neg(o.c1, a.c1);
}
static inline void f2_mul(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  Fp t0, t1, t2, t3;
  fp_mul(t0, a.c0, b.c0);
  fp_mul(t1, a.c1, b.c1);
  fp_add(t2, a.c0, a.c1);
  fp_add(t3, b.c0, b.c1);
  fp_mul(t2, t2, t3);  // (a0+a1)(b0+b1)
  Fp r0;
  fp_sub(r0, t0, t1);  // a0b0 - a1b1
  fp_sub(t2, t2, t0);
  fp_sub(t2, t2, t1);  // cross
  o.c0 = r0;
  o.c1 = t2;
}
static inline void f2_sqr(Fp2 &o, const Fp2 &a) { f2_mul(o, a, a); }
static inline bool f2_is_zero(const Fp2 &a) {
  return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static void f2_inv(Fp2 &o, const Fp2 &a) {
  // 1/(c0 + c1 i) = (c0 - c1 i) / (c0^2 + c1^2)
  Fp n, t0, t1;
  fp_sqr(t0, a.c0);
  fp_sqr(t1, a.c1);
  fp_add(n, t0, t1);
  fp_inv(n, n);
  fp_mul(o.c0, a.c0, n);
  Fp neg;
  fp_neg(neg, a.c1);
  fp_mul(o.c1, neg, n);
}

// ---- generic Jacobian curve ops over a field F -------------------------
// (X, Y, Z): x = X/Z^2, y = Y/Z^3; infinity: Z == 0.

template <typename F>
struct CurveOps {
  void (*add)(F &, const F &, const F &);
  void (*sub)(F &, const F &, const F &);
  void (*mul)(F &, const F &, const F &);
  void (*sqr)(F &, const F &);
  void (*neg)(F &, const F &);
  void (*inv)(F &, const F &);
  bool (*is_zero)(const F &);
  F b;  // curve coefficient (Montgomery form)
};

template <typename F>
struct Jac {
  F X, Y, Z;
  bool inf;
};

template <typename F>
static void jac_double(const CurveOps<F> &ops, Jac<F> &o, const Jac<F> &p) {
  if (p.inf || ops.is_zero(p.Y)) {
    o.inf = true;
    return;
  }
  // alias-safe: o may be the same object as p, so everything is computed
  // into locals and assigned at the end
  F A, B, C, D, t0, t1, X3, Y3, Z3;
  ops.sqr(A, p.X);              // X^2
  ops.sqr(B, p.Y);              // Y^2
  ops.sqr(C, B);                // Y^4
  ops.add(t0, p.X, B);
  ops.sqr(t0, t0);
  ops.sub(t0, t0, A);
  ops.sub(t0, t0, C);
  ops.add(D, t0, t0);           // D = 2((X+B)^2 - A - C)
  ops.add(t0, A, A);
  ops.add(t0, t0, A);           // E = 3A
  F E = t0;
  ops.sqr(t1, E);               // E^2
  ops.sub(t1, t1, D);
  ops.sub(X3, t1, D);           // X3 = E^2 - 2D
  ops.sub(t1, D, X3);
  ops.mul(t1, E, t1);
  F c8;
  ops.add(c8, C, C);
  ops.add(c8, c8, c8);
  ops.add(c8, c8, c8);          // 8C
  ops.sub(Y3, t1, c8);
  ops.mul(t1, p.Y, p.Z);
  ops.add(Z3, t1, t1);          // Z3 = 2YZ
  o.X = X3;
  o.Y = Y3;
  o.Z = Z3;
  o.inf = false;
}

template <typename F>
static void jac_add(const CurveOps<F> &ops, Jac<F> &o, const Jac<F> &p,
                    const Jac<F> &q) {
  if (p.inf) {
    o = q;
    return;
  }
  if (q.inf) {
    o = p;
    return;
  }
  F Z1Z1, Z2Z2, U1, U2, S1, S2, t0;
  ops.sqr(Z1Z1, p.Z);
  ops.sqr(Z2Z2, q.Z);
  ops.mul(U1, p.X, Z2Z2);
  ops.mul(U2, q.X, Z1Z1);
  ops.mul(t0, q.Z, Z2Z2);
  ops.mul(S1, p.Y, t0);
  ops.mul(t0, p.Z, Z1Z1);
  ops.mul(S2, q.Y, t0);
  F H, Rr;
  ops.sub(H, U2, U1);
  ops.sub(Rr, S2, S1);
  if (ops.is_zero(H)) {
    if (ops.is_zero(Rr)) {
      jac_double(ops, o, p);
      return;
    }
    o.inf = true;
    return;
  }
  // alias-safe: o may be p or q; compute into locals, assign at the end
  F HH, HHH, V, X3, Y3, Z3;
  ops.sqr(HH, H);
  ops.mul(HHH, H, HH);
  ops.mul(V, U1, HH);
  ops.sqr(X3, Rr);
  ops.sub(X3, X3, HHH);
  ops.sub(X3, X3, V);
  ops.sub(X3, X3, V);
  ops.sub(t0, V, X3);
  ops.mul(t0, Rr, t0);
  F t1;
  ops.mul(t1, S1, HHH);
  ops.sub(Y3, t0, t1);
  ops.mul(t0, p.Z, q.Z);
  ops.mul(Z3, t0, H);
  o.X = X3;
  o.Y = Y3;
  o.Z = Z3;
  o.inf = false;
}

template <typename F>
static void jac_mul(const CurveOps<F> &ops, Jac<F> &o, const Jac<F> &p,
                    const u64 k[4]) {
  Jac<F> acc;
  acc.inf = true;
  bool started = false;
  for (int i = 3; i >= 0; --i) {
    for (int b = 63; b >= 0; --b) {
      if (started) jac_double(ops, acc, acc);
      if ((k[i] >> b) & 1) {
        if (acc.inf)
          acc = p;
        else
          jac_add(ops, acc, acc, p);
        started = true;
      } else if (!started) {
        continue;
      }
    }
  }
  o = acc;
}

template <typename F>
static void jac_to_affine(const CurveOps<F> &ops, F &x, F &y, bool &inf,
                          const Jac<F> &p) {
  if (p.inf || ops.is_zero(p.Z)) {
    inf = true;
    return;
  }
  F zi, zi2, zi3;
  ops.inv(zi, p.Z);
  ops.sqr(zi2, zi);
  ops.mul(zi3, zi2, zi);
  ops.mul(x, p.X, zi2);
  ops.mul(y, p.Y, zi3);
  inf = false;
}

// instantiate for Fp and Fp2
static const CurveOps<Fp> G1OPS = {fp_add, fp_sub, fp_mul, fp_sqr,
                                   fp_neg, fp_inv, fp_is_zero, Fp{}};
static const CurveOps<Fp2> G2OPS = {f2_add, f2_sub, f2_mul, f2_sqr,
                                    f2_neg, f2_inv, f2_is_zero, Fp2{}};

// ---- byte-buffer marshalling -------------------------------------------

static void load_fp(Fp &out, const uint8_t *b) {
  Fp raw;
  std::memcpy(raw.v, b, 32);  // little-endian limbs
  fp_to_mont(out, raw);
}

static void store_fp(uint8_t *b, const Fp &a) {
  Fp raw;
  fp_from_mont(raw, a);
  std::memcpy(b, raw.v, 32);
}

static void load_g1(Jac<Fp> &p, const uint8_t *xy, int inf) {
  p.inf = inf != 0;
  if (p.inf) return;
  load_fp(p.X, xy);
  load_fp(p.Y, xy + 32);
  p.Z = ONE_M;
}

static void store_g1(uint8_t *xy, int *inf, const Jac<Fp> &p) {
  Fp x, y;
  bool isinf;
  jac_to_affine(G1OPS, x, y, isinf, p);
  *inf = isinf ? 1 : 0;
  if (!isinf) {
    store_fp(xy, x);
    store_fp(xy + 32, y);
  } else {
    std::memset(xy, 0, 64);
  }
}

static void load_g2(Jac<Fp2> &p, const uint8_t *xy, int inf) {
  p.inf = inf != 0;
  if (p.inf) return;
  load_fp(p.X.c0, xy);
  load_fp(p.X.c1, xy + 32);
  load_fp(p.Y.c0, xy + 64);
  load_fp(p.Y.c1, xy + 96);
  p.Z.c0 = ONE_M;
  std::memset(p.Z.c1.v, 0, 32);
}

static void store_g2(uint8_t *xy, int *inf, const Jac<Fp2> &p) {
  Fp2 x, y;
  bool isinf;
  jac_to_affine(G2OPS, x, y, isinf, p);
  *inf = isinf ? 1 : 0;
  if (!isinf) {
    store_fp(xy, x.c0);
    store_fp(xy + 32, x.c1);
    store_fp(xy + 64, y.c0);
    store_fp(xy + 96, y.c1);
  } else {
    std::memset(xy, 0, 128);
  }
}

}  // namespace

// ---- C ABI --------------------------------------------------------------

extern "C" {

// G1 points: 64-byte affine (x ‖ y), scalars: 32-byte little-endian.
void bn254_g1_add(uint8_t *out, int *out_inf, const uint8_t *a, int a_inf,
                  const uint8_t *b, int b_inf) {
  Jac<Fp> P1, P2, S;
  load_g1(P1, a, a_inf);
  load_g1(P2, b, b_inf);
  jac_add(G1OPS, S, P1, P2);
  store_g1(out, out_inf, S);
}

void bn254_g1_mul(uint8_t *out, int *out_inf, const uint8_t *a, int a_inf,
                  const uint8_t *scalar) {
  Jac<Fp> P1, S;
  load_g1(P1, a, a_inf);
  u64 k[4];
  std::memcpy(k, scalar, 32);
  jac_mul(G1OPS, S, P1, k);
  store_g1(out, out_inf, S);
}

// G2 points: 128-byte affine (x0 ‖ x1 ‖ y0 ‖ y1).
void bn254_g2_add(uint8_t *out, int *out_inf, const uint8_t *a, int a_inf,
                  const uint8_t *b, int b_inf) {
  Jac<Fp2> P1, P2, S;
  load_g2(P1, a, a_inf);
  load_g2(P2, b, b_inf);
  jac_add(G2OPS, S, P1, P2);
  store_g2(out, out_inf, S);
}

void bn254_g2_mul(uint8_t *out, int *out_inf, const uint8_t *a, int a_inf,
                  const uint8_t *scalar) {
  Jac<Fp2> P1, S;
  load_g2(P1, a, a_inf);
  u64 k[4];
  std::memcpy(k, scalar, 32);
  jac_mul(G2OPS, S, P1, k);
  store_g2(out, out_inf, S);
}

// Batch multi-scalar entry points: n independent muls in one call
// (amortizes the ctypes crossing for registry-scale keygen).
void bn254_g1_mul_batch(uint8_t *out, int *out_inf, const uint8_t *pts,
                        const int *infs, const uint8_t *scalars, int n) {
  for (int i = 0; i < n; ++i)
    bn254_g1_mul(out + 64 * i, out_inf + i, pts + 64 * i, infs[i],
                 scalars + 32 * i);
}

void bn254_g2_mul_batch(uint8_t *out, int *out_inf, const uint8_t *pts,
                        const int *infs, const uint8_t *scalars, int n) {
  for (int i = 0; i < n; ++i)
    bn254_g2_mul(out + 128 * i, out_inf + i, pts + 128 * i, infs[i],
                 scalars + 32 * i);
}

// Sum of n G1 points (the host-side Combine fallback when no device).
void bn254_g1_sum(uint8_t *out, int *out_inf, const uint8_t *pts,
                  const int *infs, int n) {
  Jac<Fp> acc, Q;
  acc.inf = true;
  for (int i = 0; i < n; ++i) {
    load_g1(Q, pts + 64 * i, infs[i]);
    jac_add(G1OPS, acc, acc, Q);
  }
  store_g1(out, out_inf, acc);
}

void bn254_g2_sum(uint8_t *out, int *out_inf, const uint8_t *pts,
                  const int *infs, int n) {
  Jac<Fp2> acc, Q;
  acc.inf = true;
  for (int i = 0; i < n; ++i) {
    load_g2(Q, pts + 128 * i, infs[i]);
    jac_add(G2OPS, acc, acc, Q);
  }
  store_g2(out, out_inf, acc);
}

int bn254_native_version() { return 1; }

}  // extern "C"

"""Small shared helpers (reference: utils.go:8-38)."""

from handel_tpu.utils.math import log2_ceil, pow2, is_set

__all__ = ["log2_ceil", "pow2", "is_set"]

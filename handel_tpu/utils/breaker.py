"""Circuit breaker for accelerator-health gating.

Lives in utils so both consumers can import it without a cycle: the shared
batch-verifier service (parallel/batch_verifier.py, which imports the
device) and the device constructor itself (models/bn254_jax.py, which the
service imports).
"""

from __future__ import annotations

import time
from typing import Callable


class CircuitBreaker:
    """Device-health gate: closed → (N consecutive failures) → open →
    (cooldown elapses) → half-open probe → closed on success.

    A dead accelerator (device lost, XLA runtime error, tunnel down) would
    otherwise fail EVERY batch after a full dispatch attempt; once the
    breaker opens, batches skip the device entirely and take the host
    fallback until one probe launch after the cooldown proves it back.
    """

    def __init__(
        self,
        threshold: int = 3,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.clock = clock
        self.failures = 0  # consecutive
        self.opened_at: float | None = None
        self.open_count = 0

    def allow(self) -> bool:
        """May the next batch try the device? True while closed, and for
        the half-open probe once the cooldown has elapsed."""
        if self.opened_at is None:
            return True
        return self.clock() - self.opened_at >= self.cooldown_s

    def record_success(self) -> None:
        self.failures = 0
        self.opened_at = None

    def record_failure(self) -> None:
        self.failures += 1
        if self.failures >= self.threshold:
            if self.opened_at is None:
                self.open_count += 1  # closed -> open transition only
            self.opened_at = self.clock()  # (re)start the cooldown

    @property
    def state(self) -> str:
        if self.opened_at is None:
            return "closed"
        return "half-open" if self.allow() else "open"

"""Circuit breaker for accelerator-health gating.

Lives in utils so both consumers can import it without a cycle: the shared
batch-verifier service (parallel/batch_verifier.py, which imports the
device) and the device constructor itself (models/bn254_jax.py, which the
service imports).
"""

from __future__ import annotations

import time
from typing import Callable


class CircuitBreaker:
    """Device-health gate: closed → (N consecutive failures) → open →
    (cooldown elapses) → half-open probe → closed on success.

    A dead accelerator (device lost, XLA runtime error, tunnel down) would
    otherwise fail EVERY batch after a full dispatch attempt; once the
    breaker opens, batches skip the device entirely and take the host
    fallback until one probe launch after the cooldown proves it back.

    State is derived lazily from `opened_at` + cooldown, so transitions
    become visible only when someone looks: every public entry point runs
    `_sync()`, which compares against the last observed state, bumps the
    monotonic `transitions` counter and fires `on_transition(prev, new)`
    — the observability hook incident attribution cites
    (`breakerTransitionsCt`, trace instants in batch_verifier.py). The
    open→half-open edge therefore lands on the first `allow()`/`state`
    probe after the cooldown, which is exactly when it takes effect.
    """

    def __init__(
        self,
        threshold: int = 3,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Callable[[str, str], None] | None = None,
    ):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.clock = clock
        self.on_transition = on_transition
        self.failures = 0  # consecutive
        self.opened_at: float | None = None
        self.open_count = 0
        self.transitions = 0  # every observed state edge, monotonic
        self._last_state = "closed"

    def _raw_state(self) -> str:
        if self.opened_at is None:
            return "closed"
        if self.clock() - self.opened_at >= self.cooldown_s:
            return "half-open"
        return "open"

    def _sync(self) -> str:
        new = self._raw_state()
        prev = self._last_state
        if new != prev:
            self._last_state = new
            self.transitions += 1
            if self.on_transition is not None:
                self.on_transition(prev, new)
        return new

    def allow(self) -> bool:
        """May the next batch try the device? True while closed, and for
        the half-open probe once the cooldown has elapsed."""
        return self._sync() != "open"

    def record_success(self) -> None:
        self.failures = 0
        self.opened_at = None
        self._sync()

    def record_failure(self) -> None:
        self.failures += 1
        if self.failures >= self.threshold:
            if self.opened_at is None:
                self.open_count += 1  # closed -> open transition only
            self.opened_at = self.clock()  # (re)start the cooldown
        self._sync()

    @property
    def state(self) -> str:
        return self._sync()

"""Integer helpers used by the partitioner and level logic.

Reference: utils.go:8-38 (log2 ceil, pow2, isSet).
"""


def log2_ceil(n: int) -> int:
    """Ceiling of log2(n): the number of binomial-tree levels for n nodes.

    log2_ceil(1) == 0, log2_ceil(2) == 1, log2_ceil(5) == 3.
    """
    if n <= 1:
        return 0
    return (n - 1).bit_length()


def pow2(k: int) -> int:
    return 1 << k


def is_set(x: int, bit: int) -> bool:
    return (x >> bit) & 1 == 1

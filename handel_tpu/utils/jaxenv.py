"""Process-level JAX platform selection — the ONE copy of the ritual.

The environment may pre-register an experimental TPU platform plugin at
interpreter startup via a sitecustomize that calls
`jax.config.update("jax_platforms", ...)` — which OVERRIDES the
JAX_PLATFORMS environment variable. Re-overriding through the config API
(which wins over any earlier update) and clearing already-initialized
backends is the only reliable selection; tests/conftest.py and
__graft_entry__.py delegate here.

Knob: HANDEL_TPU_PLATFORM=cpu|tpu|axon (any name jax accepts; this
environment's TPU platform is "axon"). Unset/empty = leave the platform
alone. Calling this imports jax, so sim entry points only call it when the
run's scheme actually needs jax (registry.is_device_scheme) — fake-scheme
protocol runs never touch jax.
"""

from __future__ import annotations

import os

CACHE_DIR = "/tmp/handel_tpu_jax_cache"


def apply_platform_env(
    default: str | None = None, force_host_device_count: int | None = None
) -> None:
    """Force the JAX platform from $HANDEL_TPU_PLATFORM (or `default`).

    force_host_device_count: also expose that many virtual devices on the
    host platform (the 8-device CPU mesh used by tests and dryrun) — must be
    set before jax initializes its backends.
    """
    # the virtual-device flag must be set even when the platform is left
    # alone (e.g. a mesh_devices>1 run with no $HANDEL_TPU_PLATFORM): it
    # only affects the HOST cpu platform, so it is harmless on TPU runs,
    # and it must land before jax initializes its backends
    if force_host_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={force_host_device_count}"
            ).strip()
    plat = os.environ.get("HANDEL_TPU_PLATFORM", default or "")
    if not plat:
        return
    os.environ["JAX_PLATFORMS"] = plat
    import jax

    jax.config.update("jax_platforms", plat)
    # persistent compile cache: pairing-sized graphs take minutes cold
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from jax._src import xla_bridge as xb

    if xb.backends_are_initialized():  # a plugin already built a backend set
        from jax.extend.backend import clear_backends

        clear_backends()

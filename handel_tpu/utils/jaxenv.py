"""Process-level JAX platform selection for CLI entry points.

The environment may pre-register an experimental TPU platform plugin at
interpreter startup via a sitecustomize that calls
`jax.config.update("jax_platforms", ...)` — which OVERRIDES the
JAX_PLATFORMS environment variable (see tests/conftest.py). Simulation node
processes usually want the CPU backend (the TPU is the bench host's, and a
downed TPU tunnel makes jax initialization hang forever), so the sim entry
points call `apply_platform_env()` before anything imports jax-dependent
modules: it re-overrides through the config API, which wins over any
earlier update.

Knob: HANDEL_TPU_PLATFORM=cpu|tpu|axon|"" (empty/unset = leave alone).
"""

from __future__ import annotations

import os


def apply_platform_env(default: str | None = None) -> None:
    """Force the JAX platform from $HANDEL_TPU_PLATFORM (or `default`)."""
    plat = os.environ.get("HANDEL_TPU_PLATFORM", default or "")
    if not plat:
        return
    os.environ["JAX_PLATFORMS"] = plat
    import jax

    jax.config.update("jax_platforms", plat)
    jax.config.update("jax_compilation_cache_dir", "/tmp/handel_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from jax._src import xla_bridge as xb

    if xb.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()

"""Verify-plane elasticity: lanes scale with load, broken lanes get
replaced instead of routed around.

The pre-lifecycle plane (parallel/plane.py) is a fixed K: a breaker-open
lane just stops receiving work, permanently degrading the fleet to K-1.
`LaneAutoscaler` closes that loop each control tick:

- **replace** — every lane whose breaker is open is swapped for a fresh
  engine from `engine_factory`: the replacement attaches FIRST (capacity
  never dips), then the broken lane drains out gracefully. No cooldown —
  a dead chip is urgent.
- **grow** — queue depth at/above `scale_up_depth`, or the recent launch
  fill at/above `high_fill` (launches leaving no slack), adds a lane up
  to `max_lanes`.
- **shrink** — depth at/below `scale_down_depth` AND recent fill at/below
  `low_fill` (lanes mostly empty) drains the newest lane down to
  `min_lanes`.

"Recent fill" is the per-tick delta of the service's dispatch-side fill
accounting, not the lifetime mean — a plane that was busy an hour ago must
not look busy now. Grow/shrink honor `cooldown_s` so one burst cannot
flap the plane.
"""

from __future__ import annotations

import time
from typing import Callable

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger


class LaneAutoscaler:
    """Elastic lane management over one `BatchVerifierService`."""

    def __init__(
        self,
        service,
        engine_factory: Callable[[], object],
        min_lanes: int = 1,
        max_lanes: int = 8,
        scale_up_depth: int = 256,
        scale_down_depth: int = 8,
        high_fill: float = 0.9,
        low_fill: float = 0.25,
        cooldown_s: float = 2.0,
        drain_timeout_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        logger: Logger = DEFAULT_LOGGER,
    ):
        if min_lanes < 1:
            raise ValueError("min_lanes must be >= 1")
        if max_lanes < min_lanes:
            raise ValueError("max_lanes must be >= min_lanes")
        self.service = service
        self.engine_factory = engine_factory
        self.min_lanes = min_lanes
        self.max_lanes = max_lanes
        self.scale_up_depth = scale_up_depth
        self.scale_down_depth = scale_down_depth
        self.high_fill = high_fill
        self.low_fill = low_fill
        self.cooldown_s = cooldown_s
        self.drain_timeout_s = drain_timeout_s
        self.clock = clock
        self.log = logger
        self._last_change = -1e18
        self._fill_mark = (0.0, 0)  # (fill_sum, fill_launches) at last tick
        self.last_fill_signal = 0.0
        self.lanes_grown = 0
        self.lanes_shrunk = 0
        self.lanes_replaced = 0
        self.incident_nudges = 0
        self._repair_first = False

    def notify_incident(self, kind: str = "") -> None:
        """Incident-plane hook (obs/incidents.py listener): a breaker-storm
        incident makes the next tick repair-first — the grow/shrink
        cooldown is waived once so replacement capacity attaches on the
        very next control interval instead of waiting out a cooldown that
        was meant for ordinary load wiggle."""
        self.incident_nudges += 1
        self._repair_first = True

    def _recent_fill(self) -> float:
        """Mean launch fill since the previous tick (windowed, not
        lifetime); carries the last value through ticks with no launches
        so an idle instant doesn't read as an empty plane."""
        svc = self.service
        prev_sum, prev_n = self._fill_mark
        d_sum = svc.fill_sum - prev_sum
        d_n = svc.fill_launches - prev_n
        self._fill_mark = (svc.fill_sum, svc.fill_launches)
        if d_n > 0:
            self.last_fill_signal = d_sum / d_n
        return self.last_fill_signal

    async def tick(self) -> dict:
        """One control interval: replace broken lanes, then grow/shrink on
        the depth + fill signals. Returns what happened (for the
        controller's log/telemetry)."""
        svc = self.service
        actions: list[str] = []

        # 1. replacement — before any scaling math, so capacity decisions
        # see the post-repair plane. Attach first, drain second: the fleet
        # never dips below its pre-failure lane count mid-swap.
        for lane in [
            l for l in list(svc.plane.lanes)
            if l.breaker.state == "open" and not l.draining
        ]:
            replacement = svc.attach_lane(self.engine_factory())
            await svc.drain_lane(lane, timeout_s=self.drain_timeout_s)
            self.lanes_replaced += 1
            actions.append(f"replaced lane {lane.index} -> {replacement.index}")
            self.log.warn(
                "lane_replaced",
                f"breaker-open lane {lane.index} replaced by "
                f"{replacement.index}",
            )

        depth = svc.queue_depth()
        fill = self._recent_fill()
        active = [l for l in svc.plane.lanes if not l.draining]
        now = self.clock()
        if self._repair_first:
            # incident nudge consumed: repairs above already ran, and the
            # scaling pass below sees a waived cooldown this one tick
            self._repair_first = False
            self._last_change = -1e18
        if now - self._last_change >= self.cooldown_s:
            if (
                (depth >= self.scale_up_depth or fill >= self.high_fill)
                and len(active) < self.max_lanes
            ):
                lane = svc.attach_lane(self.engine_factory())
                self.lanes_grown += 1
                self._last_change = now
                actions.append(f"grew lane {lane.index}")
                self.log.info(
                    "lane_grown",
                    f"lane {lane.index} added (depth {depth}, "
                    f"fill {fill:.2f})",
                )
            elif (
                depth <= self.scale_down_depth
                and fill <= self.low_fill
                and len(active) > self.min_lanes
            ):
                lane = active[-1]  # newest first: keep the veterans' stats
                await svc.drain_lane(lane, timeout_s=self.drain_timeout_s)
                self.lanes_shrunk += 1
                self._last_change = now
                actions.append(f"drained lane {lane.index}")
                self.log.info(
                    "lane_drained",
                    f"lane {lane.index} drained (depth {depth}, "
                    f"fill {fill:.2f})",
                )
        return {
            "actions": actions,
            "depth": depth,
            "fill": fill,
            "lanes": len(svc.plane),
        }

    def values(self) -> dict[str, float]:
        return {
            "lanesGrown": float(self.lanes_grown),
            "lanesShrunk": float(self.lanes_shrunk),
            "lanesReplaced": float(self.lanes_replaced),
            "incidentNudgesCt": float(self.incident_nudges),
            "fillSignal": self.last_fill_signal,
        }

    def gauge_keys(self) -> set[str]:
        return {"fillSignal"}

"""Epoch-based validator-set rotation over the shared verify plane.

The committee study behind PAPERS.md (EdDSA-vs-BLS, arxiv 2302.00418)
treats validator rotation as a steady-state event, not a restart; ACE's
continuously-loaded runtime (arxiv 2603.10242) holds sub-second finality
through set churn. This module gives the TPU service the same property:

    stage   `begin_rotation(pubkeys)` builds the NEXT registry bank on
            every lane engine — host pack, `jax.device_put`, prefix-table
            scan — while the ACTIVE bank keeps serving launches
            (models/bn254_jax.py stage_registry; the work runs in executor
            threads, off the event loop and off the launch critical path)
    drain   `commit_rotation()` closes the collector's intake gate and
            waits for every in-flight launch to resolve — old-epoch work
            completes against the old bank, ZERO futures drop
    flip    with the plane idle, `activate_staged()` on every engine is a
            pointer swap; the epoch bumps on the service (new dedup keys),
            the session manager (new sessions version under it) and the
            trace plane, and the gate reopens

The measured gate-closed wall is `epoch_swap_stall_ms` — the soak harness
(sim/soak.py) gates it against the steady-state inter-launch p50 so a
rotation is provably "between launches", not a service pause.
"""

from __future__ import annotations

import asyncio
from functools import partial

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger


class EpochManager:
    """Stages, drains and flips validator-set epochs (module docstring).

    `service` is the shared `BatchVerifierService`; `manager` (optional)
    is the `SessionManager` whose future sessions version under the new
    epoch. Engines without the stage/activate protocol (plain stubs) are
    skipped — the epoch still bumps, which is all the dedup/versioning
    plane needs.
    """

    def __init__(self, service, manager=None, logger: Logger = DEFAULT_LOGGER):
        self.service = service
        self.manager = manager
        self.log = logger
        self.staged = False
        self.rotations = 0
        self.stagings = 0
        self.last_stall_ms = 0.0
        self.stall_ms: list[float] = []

    @property
    def epoch(self) -> int:
        return self.service.epoch

    async def begin_rotation(self, registry_pubkeys) -> int:
        """Stage `registry_pubkeys` as the next bank on every lane engine.
        Expensive by design — and therefore run in executor threads while
        the active bank keeps serving. Returns the number of engines
        staged. Re-staging before a commit replaces the pending set."""
        loop = asyncio.get_running_loop()
        staged = 0
        for lane in list(self.service.plane.lanes):
            eng = lane.engine
            if hasattr(eng, "stage_registry"):
                await loop.run_in_executor(
                    None, partial(eng.stage_registry, registry_pubkeys)
                )
                staged += 1
        self.staged = True
        self.stagings += 1
        self.log.info(
            "epoch_staged",
            f"staged next registry on {staged} engine(s) "
            f"(epoch {self.epoch} -> {self.epoch + 1})",
        )
        return staged

    async def commit_rotation(self) -> float:
        """Drain in-flight work and flip every staged bank live — the
        pointer swap between launches. Returns the stall in seconds (the
        gate-closed wall the swap cost). Queued-but-undispatched work
        verifies against the NEW set; futures are never dropped."""
        if not self.staged:
            raise RuntimeError("no staged rotation: call begin_rotation first")

        def flip() -> None:
            for lane in self.service.plane.lanes:
                eng = lane.engine
                if (
                    hasattr(eng, "activate_staged")
                    and getattr(eng, "_staged", None) is not None
                ):
                    eng.activate_staged()
            self.service.epoch += 1
            if self.manager is not None:
                self.manager.epoch = self.service.epoch

        stall = await self.service.quiesce_and(flip)
        self.staged = False
        self.rotations += 1
        self.last_stall_ms = stall * 1e3
        self.stall_ms.append(self.last_stall_ms)
        self.log.info(
            "epoch_committed",
            f"epoch {self.epoch} live after {self.last_stall_ms:.2f} ms "
            f"stall ({self.rotations} rotation(s))",
        )
        return stall

    async def rotate(self, registry_pubkeys) -> float:
        """stage + drain + flip in one call; returns the flip stall (s)."""
        await self.begin_rotation(registry_pubkeys)
        return await self.commit_rotation()

    def values(self) -> dict[str, float]:
        return {
            "epoch": float(self.epoch),
            "epochRotations": float(self.rotations),
            "epochStagings": float(self.stagings),
            "lastEpochSwapStallMs": self.last_stall_ms,
            "maxEpochSwapStallMs": max(self.stall_ms, default=0.0),
        }

    def gauge_keys(self) -> set[str]:
        return {"epoch", "lastEpochSwapStallMs", "maxEpochSwapStallMs"}

"""The periodic control loop over the lifecycle plane.

`LifecycleController` runs the autoscaler tick and the autotuner
observation every `interval_s` on the service's event loop. Epoch
rotations stay caller-driven (they are triggered by consensus events,
not a timer) — the controller only surfaces the `EpochManager`'s
telemetry alongside its own.

The `report_source` callable decouples the autotuner from where stage
attribution comes from: in the sim it's the in-memory analyzer over the
live recorder; in production it could read the last trace_report.json a
cron-ed `python -m handel_tpu.sim trace` left behind. It may return None
(no report yet) — the autotuner treats that as a no-op.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger


class LifecycleController:
    """Ties autoscaler + autotuner (+ epoch telemetry) into one loop."""

    def __init__(
        self,
        service,
        autoscaler=None,
        autotuner=None,
        epoch_manager=None,
        alert_plane=None,
        host_rollup=None,
        report_source: Callable[[], dict | None] | None = None,
        interval_s: float = 0.25,
        logger: Logger = DEFAULT_LOGGER,
    ):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.service = service
        self.autoscaler = autoscaler
        self.autotuner = autotuner
        self.epoch_manager = epoch_manager
        # detection-and-incident plane (obs/plane.py AlertPlane): ticked
        # on the same cadence as the actuators it feeds, so an incident's
        # autoscaler nudge lands at most one interval after detection
        self.alert_plane = alert_plane
        # hierarchical roll-up (obs/rollup.py HostRollup): its local
        # detectors advance on the control cadence so the digest's top-K
        # carries live z-scores when the emit interval comes around
        self.host_rollup = host_rollup
        self.report_source = report_source
        self.interval_s = interval_s
        self.log = logger
        self._task: asyncio.Task | None = None
        self._lock = asyncio.Lock()  # background loop vs direct tick() calls
        self.ticks = 0

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            await self.tick()

    async def tick(self) -> dict:
        """One control interval, also callable directly from tests/sims
        that want deterministic pacing instead of the background loop (the
        lock serializes direct calls against it)."""
        async with self._lock:
            self.ticks += 1
            out: dict = {}
            if self.alert_plane is not None:
                # evaluate BEFORE the autoscaler: a breaker-storm incident
                # opened this tick nudges the autoscaler pass below
                try:
                    out["alerts"] = self.alert_plane.tick()
                except Exception as exc:
                    self.log.warn(
                        "lifecycle", f"alert plane tick failed: {exc!r}"
                    )
            if self.host_rollup is not None:
                try:
                    self.host_rollup.tick()
                except Exception as exc:
                    self.log.warn(
                        "lifecycle", f"host rollup tick failed: {exc!r}"
                    )
            if self.autoscaler is not None:
                out["autoscaler"] = await self.autoscaler.tick()
            if self.autotuner is not None and self.report_source is not None:
                try:
                    report = self.report_source()
                except Exception as exc:  # a broken report must not kill the loop
                    self.log.warn("lifecycle", f"report_source failed: {exc!r}")
                    report = None
                out["autotune"] = self.autotuner.observe(report)
            return out

    def start(self) -> None:
        if self._task is not None:
            raise RuntimeError("lifecycle controller already started")
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is None:
            return
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        self._task = None

    def values(self) -> dict[str, float]:
        out = {"lifecycleTicks": float(self.ticks)}
        if self.autoscaler is not None:
            out.update(self.autoscaler.values())
        if self.autotuner is not None:
            out.update(self.autotuner.values())
        if self.epoch_manager is not None:
            out.update(self.epoch_manager.values())
        if self.alert_plane is not None:
            out.update(self.alert_plane.values())
        if self.host_rollup is not None:
            out.update(self.host_rollup.values())
        return out

    def gauge_keys(self) -> set[str]:
        keys: set[str] = set()
        for part in (self.autoscaler, self.autotuner, self.epoch_manager,
                     self.alert_plane, self.host_rollup):
            if part is not None:
                keys |= part.gauge_keys()
        return keys

"""Production lifecycle control plane (ROADMAP item 4, ISSUE 12).

The service layers below this package are deliberately static: the registry
is committed once (models/bn254_jax.py), the verify plane is a fixed K
lanes (parallel/plane.py), admission is a flat per-tenant bound
(service/fairness.py). This package closes the loop over all of them so
the service "serves heavy traffic and never restarts":

- `EpochManager` (epoch.py) — double-buffered validator-set rotation:
  stage the next registry bank on every lane engine off the critical path,
  quiesce the plane between launches, pointer-flip, bump the epoch that
  versions sessions, dedup keys and trace spans. Zero dropped futures.
- `LaneAutoscaler` (autoscaler.py) — verify-plane elasticity on
  queue-depth and launch-fill signals, and replacement (not degradation)
  of breaker-open lanes.
- `CriticalPathAutotuner` (autotune.py) — feeds the causal tracer's stage
  attribution (sim/trace_cli.py trace_report.json) back into the
  collector window / in-flight window each control interval.
- `LifecycleController` (controller.py) — the periodic control loop tying
  the three together, with one merged telemetry surface.

Soak-test the whole plane with `python -m handel_tpu.sim soak`
(sim/soak.py; CI gate in scripts/soak_smoke.py).
"""

from handel_tpu.lifecycle.autoscaler import LaneAutoscaler
from handel_tpu.lifecycle.autotune import CriticalPathAutotuner
from handel_tpu.lifecycle.controller import LifecycleController
from handel_tpu.lifecycle.epoch import EpochManager

__all__ = [
    "CriticalPathAutotuner",
    "EpochManager",
    "LaneAutoscaler",
    "LifecycleController",
]

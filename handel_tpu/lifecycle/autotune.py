"""Critical-path autotuning: the causal tracer's stage attribution drives
the collector's knobs.

The end-to-end tracer (sim/trace_cli.py) already decomposes fleet time
into stages — `trace_report.json["stages_ms"]` with keys like `queue`,
`device`, `net`, `verify`, `merge`, `recv` — and its critical-path
analyzer names the dominant one. Until now a human read that report and
edited the config. `CriticalPathAutotuner` closes the loop:

- **queue-dominated** — candidates sit waiting for the collector window
  to close: shrink `max_delay` (smaller batches, sooner launches).
- **device-dominated** — the chip is the wall: grow `max_delay` so each
  launch amortizes more candidates per pairing sweep.
- **net-dominated** — transport dominates compute: raise `max_inflight`
  so more launches overlap the wire (applies to lanes wired after the
  change, i.e. autoscaler-attached ones).

A stage only counts as dominant above `dominance` fraction of the summed
stage time, and only `patience` consecutive intervals of the same verdict
trigger a move — the hysteresis that keeps one noisy report from
thrashing the window. Moves are multiplicative (`step`) and clamped to
[`min_delay_s`, `max_delay_s`] / `max_inflight_cap`.
"""

from __future__ import annotations

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger

# stages the collector window can actually influence; recv/merge live in
# the aggregation tree, not the verify plane, and are left to the topology
ACTIONABLE = ("queue", "device", "net")


class CriticalPathAutotuner:
    """Feeds `stages_ms` attribution back into the verify service."""

    def __init__(
        self,
        service,
        dominance: float = 0.4,
        patience: int = 2,
        step: float = 1.25,
        min_delay_s: float = 0.0005,
        max_delay_s: float = 0.008,
        max_inflight_cap: int = 8,
        logger: Logger = DEFAULT_LOGGER,
    ):
        if not 0.0 < dominance <= 1.0:
            raise ValueError("dominance must be in (0, 1]")
        if step <= 1.0:
            raise ValueError("step must be > 1 (multiplicative)")
        self.service = service
        self.dominance = dominance
        self.patience = max(1, patience)
        self.step = step
        self.min_delay_s = min_delay_s
        self.max_delay_s = max_delay_s
        self.max_inflight_cap = max_inflight_cap
        self.log = logger
        self._streak_stage = ""
        self._streak = 0
        self.adjustments = 0
        self.last_dominant = ""

    def observe(self, report: dict | None) -> str:
        """Consume one stage-attribution report (`trace_report.json` shape
        or anything with a `stages_ms` mapping). Returns a description of
        the adjustment made, or '' if the verdict didn't clear the
        hysteresis. Safe to call with None / empty reports (no-op)."""
        stages = (report or {}).get("stages_ms") or {}
        total = sum(v for v in stages.values() if v > 0)
        if total <= 0:
            return ""
        stage, share = max(
            ((k, stages.get(k, 0.0) / total) for k in ACTIONABLE),
            key=lambda kv: kv[1],
        )
        if share < self.dominance:
            self._streak_stage, self._streak = "", 0
            self.last_dominant = ""
            return ""
        self.last_dominant = stage
        if stage == self._streak_stage:
            self._streak += 1
        else:
            self._streak_stage, self._streak = stage, 1
        if self._streak < self.patience:
            return ""
        self._streak = 0  # reset so the NEXT move needs fresh evidence
        return self._adjust(stage, share)

    def _adjust(self, stage: str, share: float) -> str:
        svc = self.service
        action = ""
        if stage == "queue":
            new = max(self.min_delay_s, svc.max_delay / self.step)
            if new != svc.max_delay:
                action = f"max_delay {svc.max_delay * 1e3:.2f} -> {new * 1e3:.2f} ms"
                svc.max_delay = new
        elif stage == "device":
            new = min(self.max_delay_s, svc.max_delay * self.step)
            if new != svc.max_delay:
                action = f"max_delay {svc.max_delay * 1e3:.2f} -> {new * 1e3:.2f} ms"
                svc.max_delay = new
        elif stage == "net":
            new = min(self.max_inflight_cap, svc.max_inflight + 1)
            if new != svc.max_inflight:
                action = f"max_inflight {svc.max_inflight} -> {new}"
                svc.max_inflight = new
        if action:
            self.adjustments += 1
            self.log.info(
                "autotune",
                f"{stage} dominates ({share:.0%} of stage time): {action}",
            )
        return action

    def values(self) -> dict[str, float]:
        return {
            "autotuneAdjustments": float(self.adjustments),
            "tunedMaxDelayMs": self.service.max_delay * 1e3,
            "tunedMaxInflight": float(self.service.max_inflight),
        }

    def gauge_keys(self) -> set[str]:
        return {"tunedMaxDelayMs", "tunedMaxInflight"}

"""Signature schemes (the framework's "model families").

  fake.py          — boolean fake scheme for fast, deterministic protocol tests
                     (reference: util_test.go:15-99)
  bn254.py         — pure-Python BN254 BLS, the correctness ground truth
                     (reference: bn256/go/bn256.go, bn256/cf/bn256.go)
  bn254_native.py  — C++ host backend via ctypes (native/bn254.cpp)
  bn254_jax.py     — batched JAX/TPU backend (ops/), the flagship compute path
  bls12_381.py     — Eth2 curve behind the same Constructor interface
  registry.py      — string -> constructor dispatch
                     (reference: simul/lib/config.go:211-225)
"""

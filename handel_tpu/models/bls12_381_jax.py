"""BLS-over-BLS12-381 with verification on the JAX/TPU path.

The second device curve behind the Constructor interface — where the
reference offers two interchangeable BN256 backends (bn256/go, bn256/cf)
dispatched by the curve registry (simul/lib/config.go:211-225), this
framework offers two interchangeable PAIRING CURVES on the device path:
`bn254-jax` and `bls12-381-jax`, sharing one launch engine.

All machinery — dense masked-sum kernel, prefix-table O(1) range path,
padded fixed-shape launches, async adapter — is inherited from
models/bn254_jax.py `BN254Device`; this module only binds the BLS12-381
curve family (381-bit field, M-type twist, |z|-bit Miller loop) and the
host wire formats of models/bls12_381.py.
"""

from __future__ import annotations

from handel_tpu.models.bls12_381 import (
    BLS12381Constructor,
    BLS12381Scheme,
    hash_to_g1,
)
from handel_tpu.models.bn254_jax import BN254Device, BN254JaxConstructor
from handel_tpu.ops import bls12_381_ref as bls
from handel_tpu.ops.curve import BLS12Curves
from handel_tpu.ops.pairing import BLS12Pairing


class BLS12381Device(BN254Device):
    """BLS12-381 binding of the device verification engine."""

    ref = bls
    Curves = BLS12Curves
    Pairing = BLS12Pairing
    _hash_to_g1 = staticmethod(hash_to_g1)


class BLS12381JaxConstructor(BLS12381Constructor, BN254JaxConstructor):
    """Constructor whose `batch_verify` runs on the JAX/TPU path; wire
    formats and single-sig verify stay the host BLS12-381 scheme's."""

    Device = BLS12381Device

    def __init__(
        self,
        batch_size: int = 16,
        curves: BLS12Curves | None = None,
        mesh_devices: int = 1,
        warmup: bool = True,
        fp_backend: str | None = None,
        rns_resident: bool | None = None,
        batch_check: str = "per_candidate",
        rlc_rng=None,
    ):
        BN254JaxConstructor.__init__(
            self,
            batch_size=batch_size,
            curves=curves,
            mesh_devices=mesh_devices,
            warmup=warmup,
            fp_backend=fp_backend,
            rns_resident=rns_resident,
            batch_check=batch_check,
            rlc_rng=rlc_rng,
        )


class BLS12381JaxScheme(BLS12381Scheme):
    """Keygen facade for harness/simulation use: the host scheme's keygen and
    wire formats with the device-verification constructor swapped in."""

    def __init__(
        self,
        batch_size: int = 16,
        mesh_devices: int = 1,
        warmup: bool = True,
        fp_backend: str | None = None,
        rns_resident: bool | None = None,
        batch_check: str = "per_candidate",
    ):
        self.constructor = BLS12381JaxConstructor(
            batch_size=batch_size,
            mesh_devices=mesh_devices,
            warmup=warmup,
            fp_backend=fp_backend,
            rns_resident=rns_resident,
            batch_check=batch_check,
        )

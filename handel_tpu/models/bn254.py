"""BLS signatures over BN254, pure-Python backend.

Reference: bn256/go/bn256.go:1-218 and bn256/cf/bn256.go:1-219 — keys are G2
points (X = x*B2), signatures are G1 points (S = x*H(m)), verification checks
e(H(m), X) == e(S, B2), aggregation is plain point addition, and hash-to-G1
derives a scalar from SHA256(msg) and multiplies the G1 base point
(bn256/go/bn256.go:206-218 — the reference's known-scalar construction,
whose exact derivation algorithm is mirrored in `hash_to_g1` below; same
caveat as the reference's issue #122).

Wire formats (64-byte G1 = x||y big-endian, 128-byte G2 with imaginary
coefficient first, zero bytes = point at infinity) mirror cloudflare/bn256's
Marshal layout.

Point arithmetic dispatches to the C++ host library (handel_tpu/native,
the equivalent of the reference's assembly field ops inside cloudflare/bn256)
when it builds, and falls back to the pure-Python oracle (ops/bn254_ref.py)
otherwise; pairings stay on the oracle here. bn254_jax.py (TPU) implements
the same interface with verification on device, validated against this one.
The TPU-relevant structure is already here: `batch_verify` goes through one
product-of-pairings check per candidate, which the device backend turns into a
single vmap'd multi-pairing launch.
"""

from __future__ import annotations

import hashlib
import secrets
import time

from handel_tpu import native as nat
from handel_tpu.core import report
from handel_tpu.core.crypto import Constructor
from handel_tpu.ops import bn254_ref as bn

_G1_SIZE = 64
_G2_SIZE = 128


def _int_to_bytes(x: int) -> bytes:
    return int(x).to_bytes(32, "big")


def _bytes_to_int(b: bytes) -> int:
    x = int.from_bytes(b, "big")
    if x >= bn.P:
        raise ValueError("coordinate >= field modulus")
    return x


def marshal_g1(pt) -> bytes:
    if pt is None:
        return b"\x00" * _G1_SIZE
    return _int_to_bytes(pt[0]) + _int_to_bytes(pt[1])


def unmarshal_g1(data: bytes):
    if len(data) != _G1_SIZE:
        raise ValueError(f"G1 point must be {_G1_SIZE} bytes")
    if data == b"\x00" * _G1_SIZE:
        return None
    pt = (_bytes_to_int(data[:32]), _bytes_to_int(data[32:]))
    if not bn.g1_is_valid(pt):
        raise ValueError("G1 point not on curve")
    return pt


def marshal_g2(pt) -> bytes:
    if pt is None:
        return b"\x00" * _G2_SIZE
    (x0, x1), (y0, y1) = pt
    # imaginary-first coefficient order, as cloudflare/bn256 gfP2 marshals
    return (
        _int_to_bytes(x1) + _int_to_bytes(x0) + _int_to_bytes(y1) + _int_to_bytes(y0)
    )


def unmarshal_g2(data: bytes, check_subgroup: bool = True):
    if len(data) != _G2_SIZE:
        raise ValueError(f"G2 point must be {_G2_SIZE} bytes")
    if data == b"\x00" * _G2_SIZE:
        return None
    x1, x0, y1, y0 = (_bytes_to_int(data[i : i + 32]) for i in range(0, 128, 32))
    pt = ((x0, x1), (y0, y1))
    if not bn.pt_is_on_curve(bn.F2_OPS, pt, bn.TWIST_B):
        raise ValueError("G2 point not on curve")
    # subgroup check [r]P == O on the native path (the Python oracle's
    # g2_is_valid does the same mul ~15x slower — hot in packet unmarshal);
    # counted on the shared plane so large-N runs can attribute host time
    if check_subgroup:
        t0 = time.perf_counter()
        bad = nat.g2_mul(pt, bn.R) is not None
        report.SUBGROUP_CHECKS.add_g2((time.perf_counter() - t0) * 1000.0)
        if bad:
            raise ValueError("G2 point not on curve / wrong subgroup")
    return pt


def hash_to_g1(msg: bytes):
    """H(m) = k*G1, with k derived by the reference's exact algorithm.

    The reference (bn256/go/bn256.go:206-218) feeds SHA256(msg) into a
    bytes.Buffer seeding x/crypto/bn256.RandomG1, i.e. Go crypto/rand.Int
    over the group order: read ceil(BitLen(order)/8) = 32 bytes, mask the
    TOP byte down to BitLen(order) % 8 bits (keep all 8 when that is 0),
    interpret big-endian, and retry on a draw >= order — which on the
    one-shot 32-byte buffer hits EOF, so the reference ERRORS for ~44% of
    possible digests (the known flaw its comment flags as issue #122).

    Mirrored here over OUR order (alt_bn128 r, bit length 254, so the top
    byte keeps 254 % 8 = 6 bits); where the reference would error we
    deterministically re-hash the digest instead, keeping every message
    signable. Note the reference rides golang.org/x/crypto/bn256's 256-bit
    BN curve — a different curve than alt_bn128 — so signatures were never
    byte-cross-verifiable; the mirror is of the scalar derivation, not the
    wire bytes.
    """
    keep = bn.R.bit_length() % 8  # Go rand.Int's top-byte mask width
    mask = (1 << keep) - 1 if keep else 0xFF
    digest = hashlib.sha256(msg).digest()
    while True:
        k = int.from_bytes(bytes([digest[0] & mask]) + digest[1:], "big")
        if 0 < k < bn.R:
            return nat.g1_mul(bn.G1_GEN, k)
        digest = hashlib.sha256(digest).digest()  # reference EOF-errors here


class BN254Signature:
    """A (possibly aggregate) signature: a G1 point (bn256/go/bn256.go SigBLS)."""

    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point

    def marshal(self) -> bytes:
        return marshal_g1(self.point)

    def combine(self, other: "BN254Signature") -> "BN254Signature":
        return BN254Signature(nat.g1_add(self.point, other.point))

    def __eq__(self, other):
        return isinstance(other, BN254Signature) and self.point == other.point


class BN254PublicKey:
    """A (possibly aggregate) public key: a G2 point."""

    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point

    def marshal(self) -> bytes:
        return marshal_g2(self.point)

    def verify(self, msg: bytes, sig: BN254Signature) -> bool:
        """e(H(m), X) == e(S, B2), as one product check
        e(H(m), X) * e(-S, B2) == 1 (bn256/go/bn256.go:82-94); rides the
        C++ Miller loop / final exp when the native library is available."""
        if sig.point is None or self.point is None:
            return False
        hm = hash_to_g1(msg)
        return nat.pairing_check(
            [(hm, self.point), (bn.g1_neg(sig.point), bn.G2_GEN)]
        )

    def combine(self, other: "BN254PublicKey") -> "BN254PublicKey":
        return BN254PublicKey(nat.g2_add(self.point, other.point))

    def __eq__(self, other):
        return isinstance(other, BN254PublicKey) and self.point == other.point


class BN254SecretKey:
    """The secret scalar x; public key X = x*B2, signature S = x*H(m)."""

    __slots__ = ("scalar",)

    def __init__(self, scalar: int):
        self.scalar = scalar % bn.R

    def public_key(self) -> BN254PublicKey:
        return BN254PublicKey(nat.g2_mul(bn.G2_GEN, self.scalar))

    def sign(self, msg: bytes) -> BN254Signature:
        return BN254Signature(nat.g1_mul(hash_to_g1(msg), self.scalar))

    def marshal(self) -> bytes:
        return int(self.scalar).to_bytes(32, "big")

    @classmethod
    def unmarshal(cls, data: bytes) -> "BN254SecretKey":
        return cls(int.from_bytes(data, "big"))


def new_keypair(seed: int | None = None) -> tuple[BN254SecretKey, BN254PublicKey]:
    """Generate a keypair; deterministic when `seed` is given (simulation
    keygen, reference simul/lib/generator.go)."""
    if seed is not None:
        scalar = (
            int.from_bytes(
                hashlib.sha256(b"handel-tpu-key:" + str(seed).encode()).digest(),
                "big",
            )
            % bn.R
        )
    else:
        scalar = secrets.randbelow(bn.R - 1) + 1
    sk = BN254SecretKey(scalar or 1)
    return sk, sk.public_key()


class BN254Constructor(Constructor):
    """Scheme factory (bn256/go/bn256.go Constructor). Pure-Python verify path;
    `batch_verify` is inherited serial aggregation + per-candidate product
    pairing check."""

    def unmarshal_signature(self, data: bytes) -> BN254Signature:
        return BN254Signature(unmarshal_g1(data[:_G1_SIZE]))

    def signature_size(self) -> int:
        return _G1_SIZE


class BN254Scheme:
    """Keygen facade for the test harness / simulation keygen, with the
    marshalable-secret extension of simul/lib/crypto.go:18-169."""

    def __init__(self):
        self.constructor = BN254Constructor()

    def keygen(self, i: int):
        return new_keypair(seed=i)

    def unmarshal_public(self, data: bytes) -> BN254PublicKey:
        return BN254PublicKey(unmarshal_g2(data))

    def unmarshal_secret(self, data: bytes) -> BN254SecretKey:
        return BN254SecretKey.unmarshal(data)

"""BLS-over-BN254 with verification on the JAX/TPU path.

This is the device Constructor the project exists for: it replaces the serial
verify loop of the reference (`verifySignature`, processing.go:342-368 —
aggregate-pubkey loop + `bn256.Pair` at bn256/cf/bn256.go:86-98) with ONE
batched launch per candidate batch:

  1. aggregate public keys = masked G2 tree-sum over the device-resident
     registry array (ops/curve.py `masked_sum`; the reference's per-signature
     Combine loop at processing.go:355-361),
  2. batched product-of-pairings check
     e(H(m), X_j) * e(-S_j, B2) == 1  for every candidate j
     with one shared final exponentiation (ops/pairing.py `pairing_check`;
     the reference's per-signature two-pairing compare, bn256/go/bn256.go:82-94).

Keys/signatures/wire formats are the host objects from models/bn254.py
(cloudflare-compatible marshal); only verification moves on device. Candidate
batches are padded to a fixed `batch_size` so the jit executable is reused
across calls.
"""

from __future__ import annotations

import asyncio
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from handel_tpu.core.bitset import BitSet
from handel_tpu.models.bn254 import (
    BN254Constructor,
    BN254PublicKey,
    BN254Signature,
    hash_to_g1,
    new_keypair,
)
from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.curve import BN254Curves
from handel_tpu.ops.pairing import BN254Pairing


class BN254Device:
    """Device-side verification engine bound to one registry.

    Holds the registry's public keys as dense (nlimbs, N) G2 coordinate
    arrays uploaded once (SURVEY.md §2.1 identity row: "registry pubkeys
    additionally uploaded once to device memory as a dense G2 array").
    """

    def __init__(
        self,
        registry_pubkeys: Sequence[BN254PublicKey],
        batch_size: int = 16,
        curves: BN254Curves | None = None,
    ):
        self.curves = curves or BN254Curves()
        self.pairing = BN254Pairing(self.curves)
        self.batch_size = batch_size
        self.n = len(registry_pubkeys)
        T = self.curves.T
        pts = [pk.point for pk in registry_pubkeys]
        if any(p is None for p in pts):
            raise ValueError("registry public keys must be valid G2 points")
        self._reg_x = T.f2_pack([p[0] for p in pts])  # ((L, N), (L, N))
        self._reg_y = T.f2_pack([p[1] for p in pts])
        self._h_cache: dict[bytes, tuple] = {}
        self._kernel = jax.jit(self._verify_batch)

    # -- the jitted batch kernel -------------------------------------------

    def _verify_batch(self, reg_x, reg_y, mask, sig_x, sig_y, h_x, h_y, valid):
        """One launch: masked G2 segment-sum + batched multi-pairing.

        Shapes: reg_* (L, N) Fp2 pairs; mask (N*C,) bool block-major
        (block i = registry key i across C candidates); sig_*/h_* (L, C);
        valid (C,) bool. Returns (C,) verdicts.
        """
        C = self.batch_size
        g2 = self.curves.g2
        g1c = self.curves.g1
        T = self.curves.T
        F = self.curves.F

        # registry tiled block-major across candidates, masked, tree-summed
        tile = lambda a: jnp.repeat(a, C, axis=1)  # (L, N) -> (L, N*C)
        P2 = g2.from_affine(
            (tile(reg_x[0]), tile(reg_x[1])), (tile(reg_y[0]), tile(reg_y[1]))
        )
        agg = g2.masked_sum(P2, mask, self.n)  # projective, batch C
        agg_inf = g2.is_infinity(agg)
        qx, qy, _ = g2.to_affine(agg)

        # pairs chunk-major: [e(H, X_j)] ++ [e(-S_j, B2)]
        b2 = self.curves.T.f2_pack([bn.G2_GEN[0]] * 1), self.curves.T.f2_pack(
            [bn.G2_GEN[1]] * 1
        )
        bx = (
            jnp.broadcast_to(b2[0][0], qx[0].shape),
            jnp.broadcast_to(b2[0][1], qx[0].shape),
        )
        by = (
            jnp.broadcast_to(b2[1][0], qy[0].shape),
            jnp.broadcast_to(b2[1][1], qy[0].shape),
        )
        neg_sig_y = F.neg(sig_y)
        px = jnp.concatenate([jnp.broadcast_to(h_x, sig_x.shape), sig_x], axis=1)
        py = jnp.concatenate([jnp.broadcast_to(h_y, sig_y.shape), neg_sig_y], axis=1)
        qx2 = (
            jnp.concatenate([qx[0], bx[0]], axis=1),
            jnp.concatenate([qx[1], bx[1]], axis=1),
        )
        qy2 = (
            jnp.concatenate([qy[0], by[0]], axis=1),
            jnp.concatenate([qy[1], by[1]], axis=1),
        )
        ok_lane = valid & ~agg_inf
        lane_mask = jnp.concatenate([ok_lane, ok_lane])
        checks = self.pairing.pairing_check((px, py), (qx2, qy2), lane_mask, C)
        return checks & ok_lane

    # -- host entry points --------------------------------------------------

    def _h_point(self, msg: bytes):
        cached = self._h_cache.get(msg)
        if cached is None:
            h = hash_to_g1(msg)
            cached = (
                self.curves.F.pack([h[0]]),
                self.curves.F.pack([h[1]]),
            )
            self._h_cache[msg] = cached
        return cached

    def batch_verify(
        self,
        msg: bytes,
        requests: Sequence[tuple[BitSet, BN254Signature]],
    ) -> list[bool]:
        """Verify up to batch_size (global bitset, aggregate sig) candidates
        in one device launch; longer request lists run in several launches."""
        out: list[bool] = []
        for i in range(0, len(requests), self.batch_size):
            out.extend(self._one_launch(msg, requests[i : i + self.batch_size]))
        return out

    def _one_launch(self, msg, requests) -> list[bool]:
        C = self.batch_size
        F = self.curves.F
        mask = np.zeros((self.n, C), dtype=bool)
        sig_pts = []
        valid = np.zeros((C,), dtype=bool)
        for j, (bs, sig) in enumerate(requests):
            if len(bs) != self.n:
                raise ValueError("bitset length != registry size")
            idx = list(bs.indices())
            sig_pt = getattr(sig, "point", None)
            if idx and sig_pt is not None:
                mask[idx, j] = True
                valid[j] = True
                sig_pts.append(sig_pt)
            else:
                sig_pts.append(bn.G1_GEN)  # placeholder, lane masked out
        sig_pts += [bn.G1_GEN] * (C - len(sig_pts))  # pad lanes
        sig_x = F.pack([p[0] for p in sig_pts])
        sig_y = F.pack([p[1] for p in sig_pts])
        h_x, h_y = self._h_point(msg)
        verdicts = self._kernel(
            self._reg_x,
            self._reg_y,
            jnp.asarray(mask.reshape(-1)),
            sig_x,
            sig_y,
            h_x,
            h_y,
            jnp.asarray(valid),
        )
        return [bool(v) for v in np.asarray(verdicts)[: len(requests)]]


class BN254JaxConstructor(BN254Constructor):
    """Constructor whose `batch_verify` runs on the JAX/TPU path.

    The device registry is built lazily from the pubkey sequence of the first
    call (Handel passes the same registry list every time) or eagerly via
    `prepare()`. Marshal/unmarshal and single-sig verify stay host-side.
    """

    def __init__(self, batch_size: int = 16, curves: BN254Curves | None = None):
        self.batch_size = batch_size
        self.curves = curves or BN254Curves()
        self._device: BN254Device | None = None
        self._device_for: int | None = None

    def prepare(self, pubkeys: Sequence[BN254PublicKey]) -> BN254Device:
        self._device = BN254Device(
            pubkeys, batch_size=self.batch_size, curves=self.curves
        )
        # hold the list itself: the id() cache key below is only valid while
        # the original object is alive (id reuse after GC would alias a new
        # registry to the cached one)
        self._reg_list = pubkeys
        self._device_for = id(pubkeys)
        self._reg_keys = [pk.point for pk in pubkeys]
        return self._device

    def _device_of(self, pubkeys) -> BN254Device:
        if self._device is None or self._device.n != len(pubkeys):
            self.prepare(pubkeys)
        elif self._device_for != id(pubkeys):
            # same length, different list object: full content check once per
            # new list identity (a same-size registry rebuilt after churn must
            # NOT verify against stale keys), then adopt the id so repeat
            # calls stay O(1)
            if [pk.point for pk in pubkeys] == self._reg_keys:
                self._reg_list = pubkeys
                self._device_for = id(pubkeys)
            else:
                self.prepare(pubkeys)
        return self._device

    def batch_verify(self, msg, pubkeys, requests) -> list[bool]:
        return self._device_of(pubkeys).batch_verify(msg, requests)


class BN254JaxScheme:
    """Keygen facade for harness/simulation use (host keygen, device verify)."""

    def __init__(self, batch_size: int = 16):
        self.constructor = BN254JaxConstructor(batch_size=batch_size)

    def keygen(self, i: int):
        return new_keypair(seed=i)


def make_async_verifier(device: BN254Device):
    """Adapt a BN254Device into the processing pipeline's AsyncVerifier,
    running launches in a worker thread so the event loop stays live."""

    async def verify(msg, pubkeys, requests):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, partial(device.batch_verify, msg, requests)
        )

    return verify

"""BLS-over-BN254 with verification on the JAX/TPU path.

This is the device Constructor the project exists for: it replaces the serial
verify loop of the reference (`verifySignature`, processing.go:342-368 —
aggregate-pubkey loop + `bn256.Pair` at bn256/cf/bn256.go:86-98) with ONE
batched launch per candidate batch:

  1. aggregate public keys = masked G2 tree-sum over the device-resident
     registry array (ops/curve.py `masked_sum`; the reference's per-signature
     Combine loop at processing.go:355-361),
  2. batched product-of-pairings check
     e(H(m), X_j) * e(-S_j, B2) == 1  for every candidate j
     with one shared final exponentiation (ops/pairing.py `pairing_check`;
     the reference's per-signature two-pairing compare, bn256/go/bn256.go:82-94).

Keys/signatures/wire formats are the host objects from models/bn254.py
(cloudflare-compatible marshal); only verification moves on device. Candidate
batches are padded to a fixed `batch_size` so the jit executable is reused
across calls.
"""

from __future__ import annotations

import asyncio
import random
import time
from collections import namedtuple
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.logging import DEFAULT_LOGGER
from handel_tpu.models import rlc
from handel_tpu.models.bn254 import (
    BN254Constructor,
    BN254PublicKey,
    BN254Scheme,
    BN254Signature,
    hash_to_g1,
)
from handel_tpu.utils.breaker import CircuitBreaker
from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.curve import BN254Curves
from handel_tpu.ops.pairing import BN254Pairing

# Device-input arrays for one launch, as the packer hands them to dispatch:
# kind selects the kernel family ("range" = prefix-table path with a miss_k-
# wide hole patch, "dense" = masked registry sum); sig_* are packed limb
# arrays; valid masks the real lanes. Array fields not used by `kind` are
# None. `words` is the (C, W) uint64 bitset-word matrix — for a dense plan
# it IS the device-transfer source (the kernel unpacks the candidate masks
# on device; no host-side (n, C) mask is ever materialized). The loop
# oracle still builds the dense `mask` host-side; vectorized plans leave it
# None. Plans from `_pack_requests` view ROTATED staging buffers (see
# `_StagingSet`): a plan stays valid until the staging rotation wraps back
# onto its set — with the default two sets, the second-next `_pack_requests`
# call invalidates it. `_pack_requests_loop` plans own their arrays.
LaunchPlan = namedtuple(
    "LaunchPlan",
    "kind miss_k lo hi miss_idx miss_ok words mask sig_x sig_y valid",
)


class _StagingSet:
    """One pre-allocated set of host staging buffers for the launch packer.

    The device owns `stage_sets` of these (default two) and rotates per
    `_pack_requests` call — double buffering, so the arrays a still-in-flight
    launch's `jax.device_put` handoff may alias (jax's CPU client zero-copy-
    aliases some dtypes) are never overwritten while that launch can still
    read them. `fence` holds the verdict array of the last launch that used
    this set: before the rotation reuses the set, the packer blocks on it —
    a completed launch has consumed (or device-copied) every input, so the
    wait resolves instantly in steady state and only throttles a pipeline
    that outran `stage_sets` launches of buffering (backpressure, never
    corruption). Single-dispatcher contract: one thread packs/dispatches
    (BatchVerifierService's collector, or a caller's own loop).
    """

    __slots__ = ("words", "valid", "lo", "hi", "miss", "miss_ok",
                 "sig_x", "sig_y", "fence")

    def __init__(self, n: int, C: int, miss_cap: int, nlimbs: int):
        self.words = np.zeros((C, (n + 63) // 64), np.uint64)
        self.valid = np.zeros((C,), bool)
        self.lo = np.zeros((C,), np.int32)
        self.hi = np.zeros((C,), np.int32)
        self.miss = np.zeros((miss_cap, C), np.int64)
        self.miss_ok = np.zeros((miss_cap, C), bool)
        self.sig_x = np.zeros((nlimbs, C), np.uint32)
        self.sig_y = np.zeros((nlimbs, C), np.uint32)
        self.fence = None


class _WarmupSig:
    """Minimal signature stand-in for warmup launches (only `.point` is
    read by the packer); verdicts are discarded, so no real signing."""

    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point


class BN254Device:
    """Device-side verification engine bound to one registry.

    Holds the registry's public keys as dense (nlimbs, N) G2 coordinate
    arrays uploaded once (SURVEY.md §2.1 identity row: "registry pubkeys
    additionally uploaded once to device memory as a dense G2 array").

    Curve-family bindings are class attributes so the BLS12-381 device
    (models/bls12_381_jax.py) reuses the whole launch machinery.
    """

    ref = bn  # scalar-oracle module: generators + placeholder points
    Curves = BN254Curves
    Pairing = BN254Pairing
    _hash_to_g1 = staticmethod(hash_to_g1)

    def __init__(
        self,
        registry_pubkeys: Sequence[BN254PublicKey],
        batch_size: int = 16,
        curves: BN254Curves | None = None,
        mesh_devices: int = 1,
        jax_device=None,
        rns_resident: bool | None = None,
        batch_check: str = "per_candidate",
        rlc_rng: random.Random | None = None,
    ):
        # batch_check selects the launch contract: "per_candidate" = one
        # pairing-check lane pair per candidate (2C Miller loops, C final
        # exps); "rlc" = the random-linear-combination combined check
        # (models/rlc.py — M+1 Miller loops, 1 final exp, two MSMs) with
        # bisection fallback down to the per-candidate oracle on failure
        self.batch_check = rlc.validate_batch_check(batch_check)
        # adversary-facing randomness: SystemRandom unless a test injects
        # a seeded stream for reproducible bisection traces
        self._rlc_rng = rlc_rng or random.SystemRandom()
        self.rlc_stats = rlc.RlcStats()
        self.curves = curves or self.Curves()
        # rns_resident toggles the residue-resident pairing form
        # (ops/pairing.py): None = auto (on exactly for the 'rns' field
        # backend), False forces per-mul CRT, True demands the rns backend
        self.pairing = self.Pairing(self.curves, resident=rns_resident)
        self.batch_size = batch_size
        self.n = len(registry_pubkeys)
        # fleet pinning (parallel/plane.py): when `jax_device` is given,
        # every explicit put — registry commit, staging handoff, cached
        # H(m) — lands COMMITTED to that chip, so jit executes this
        # engine's launches there and K engines fill K chips concurrently.
        # None keeps the historical uncommitted-default placement.
        self.jax_device = jax_device
        self._dput = (
            partial(jax.device_put, device=jax_device)
            if jax_device is not None
            else jax.device_put
        )
        T = self.curves.T
        pts = [pk.point for pk in registry_pubkeys]
        if any(p is None for p in pts):
            raise ValueError("registry public keys must be valid G2 points")
        # the registry is committed to the device ONCE, here, and every
        # launch selects from it with on-device gathers (the prefix table
        # below is derived from these arrays and lives on device too) —
        # steady-state launches perform no implicit host→device transfer
        # of registry/prefix data (pinned by tests/test_device_residency.py
        # under jax.transfer_guard)
        self._reg_x = self._dput(T.f2_pack([p[0] for p in pts]))
        self._reg_y = self._dput(T.f2_pack([p[1] for p in pts]))
        # multi-chip plane (SURVEY.md §5.7): registry shards over the mesh
        # for the masked G2 segment-sum, candidate lanes shard for the
        # pairing check. Same host entry points — `_dispatch_one` routes to
        # a STAGED pipeline of separate executables (sharded sum / range
        # aggregation -> affine epilogue -> sharded pairing check) instead of
        # the single-device monolithic kernels: nesting shard_map regions
        # inside the big jit sends XLA's partitioner over the whole pairing
        # graph, which takes hours on a 1-core host (parallel/sharding.py
        # module docstring has the measurement).
        self.mesh_devices = mesh_devices
        self.mesh = None
        self._sharded_sum = self._sharded_check = None
        self.mesh_launches = 0
        self.mesh_candidates = 0
        if mesh_devices > 1:
            from handel_tpu.parallel.sharding import (
                commit_registry_sharded,
                launch_partition_rules,
                make_mesh,
                make_shard_fns,
                match_partition_rules,
                sharded_masked_sum_g2,
                sharded_pairing_check,
            )

            self.mesh = make_mesh(mesh_devices)
            self._sharded_sum = sharded_masked_sum_g2(
                self.curves, self.mesh, self.n, batch_size
            )
            self._sharded_check = sharded_pairing_check(
                self.pairing, self.mesh, batch_size
            )
            # the mesh counterpart of the single-chip resident registry:
            # pad the coordinate arrays to the device multiple and commit
            # one shard per chip ONCE, here — before this, every dense
            # sharded launch handed the full replicated arrays to
            # `_sharded_sum` and paid a re-shard (all-to-all of the whole
            # registry) per launch
            self._reg_sharded = commit_registry_sharded(
                self.mesh, self._reg_x, self._reg_y, self.n
            )
            # per-launch operand placement by partition rule (the
            # SNIPPETS.md [1][2] rule-matching/shard_fns idiom): the dense
            # candidate mask is pre-padded on the host and device_put in
            # its registry-axis shard_map layout, so `_sharded_sum` sees
            # one shard per chip instead of re-sharding a replicated mask
            # every launch (the same win commit_registry_sharded bought
            # the registry banks)
            self._mesh_pad = (-self.n) % mesh_devices
            self._mesh_put = make_shard_fns(
                self.mesh,
                match_partition_rules(
                    launch_partition_rules(),
                    ["reg_x", "reg_y", "mask", "sig_x", "sig_y", "valid"],
                ),
            )
            self._affine_kernel = jax.jit(self.curves.g2.to_affine)
            self._neg_kernel = jax.jit(self.curves.F.neg)
            self._b2x = T.f2_pack([self.ref.G2_GEN[0]])
            self._b2y = T.f2_pack([self.ref.G2_GEN[1]])
        # staged-kernel cache: used by every mesh launch and by any caller
        # profiling the aggregation stage standalone on one device
        self._range_agg_kernels: dict[int, callable] = {}
        self._h_cache: dict[bytes, tuple] = {}
        # host-side H(m) limb columns + launch counter for the per-lane-h
        # multi-message path (dispatch_multi)
        self._h_np_cache: dict[bytes, tuple] = {}
        self.multi_msg_launches = 0
        # prefix table: slot i = sum of registry keys [0, i) in affine, with
        # an explicit infinity flag (slot 0). Built lazily on the first
        # range-path dispatch (dense-only users never pay the scan); after
        # that every contiguous candidate costs two gathers + one add.
        self._prefix_cache = None
        # buffer donation: per-launch inputs (staging transfers, never the
        # registry/prefix residents or the cached H(m)) are donated so XLA
        # reuses their device buffers in place instead of allocating fresh
        # ones per launch. Gated off the CPU client, where device buffers
        # can ALIAS the host staging arrays — donating an aliased buffer
        # would let XLA scribble over our staging memory.
        donate = jax.default_backend() != "cpu"
        self._kernel = jax.jit(
            self._verify_batch,
            donate_argnums=(2, 3, 4, 7) if donate else (),
        )
        self._donate = donate
        self._range_kernels: dict[int, callable] = {}
        self._combine_kernels: dict[int, callable] = {}
        # RLC launch-class kernels: the MSM/aggregation stage keyed by
        # (kind, miss_k, n_groups) and the G+1-lane pairing tail keyed by
        # n_groups (n_groups quantized to powers of two, same reasoning as
        # the miss_k classes: each tail variant is a pairing-graph compile)
        self._rlc_msm_kernels: dict[tuple, callable] = {}
        self._rlc_check_kernels: dict[int, callable] = {}
        # rotated zero-copy staging (double-buffered by default): bitset
        # uint64 words land directly in these pinned arrays, which are the
        # device-transfer source — ONE explicit jax.device_put per array in
        # `_stage_plan`, no per-launch snapshot copies. See _StagingSet for
        # the rotation/fence contract.
        self.stage_sets = 2
        self._stage = [
            _StagingSet(self.n, batch_size, self.MISS_CAP, self.curves.F.nlimbs)
            for _ in range(self.stage_sets)
        ]
        self._stage_idx = 0
        # host-cost counters (bench.py host_pack_ms/host_dispatch_ms;
        # monitor plane via BatchVerifierService.values): pack = building
        # the launch plan in staging, dispatch = the device handoff + async
        # kernel enqueue that follows it
        self.host_pack_ms = 0.0
        self.host_pack_launches = 0
        self.host_dispatch_ms = 0.0
        self.host_dispatch_launches = 0
        # epoch-based registry rotation (lifecycle/epoch.py): a second
        # device-resident bank is staged via `stage_registry` while this
        # one keeps serving; `activate_staged` is the pointer flip between
        # launches. `epoch` counts flips — 0 is the construction-time set.
        self.epoch = 0
        self._staged: dict | None = None
        self.registry_stagings = 0
        self.registry_staged_ms = 0.0

    @property
    def _prefix(self):
        if self._prefix_cache is None:
            # never build under an active trace — the result would cache
            # tracers (see _range_kernel, which pre-materializes on the
            # host). The guard is defense-in-depth; it degrades to a no-op
            # if a JAX upgrade moves the (private) trace-state probe.
            try:
                from jax._src.core import trace_state_clean
            except ImportError:  # pragma: no cover - jax internals moved
                trace_state_clean = None
            if trace_state_clean is not None and not trace_state_clean():
                raise RuntimeError("prefix table must be built outside jit")
            self._prefix_cache = self._build_prefix()
        return self._prefix_cache

    def _build_prefix(self, reg_x=None, reg_y=None):
        """Prefix table over a registry bank (default: the active one).
        `stage_registry` passes the STAGED bank so the scan runs off the
        launch critical path."""
        g2 = self.curves.g2

        @jax.jit  # one executable for the whole scan + batch affine convert
        def build(reg_x, reg_y):
            P = g2.from_affine(reg_x, reg_y)
            pref = g2.prefix_scan(P)  # inclusive prefix sums, projective
            return g2.to_affine(pref)

        x, y, inf = build(
            self._reg_x if reg_x is None else reg_x,
            self._reg_y if reg_y is None else reg_y,
        )
        pad = lambda a: jnp.pad(a, ((0, 0), (1, 0)))  # exclusive: slot 0 = O
        return (
            (pad(x[0]), pad(x[1])),
            (pad(y[0]), pad(y[1])),
            jnp.pad(inf, (1, 0), constant_values=True),
        )

    # -- epoch-based registry rotation (lifecycle/epoch.py) ----------------

    def stage_registry(
        self, registry_pubkeys: Sequence[BN254PublicKey],
        build_prefix: bool = True,
    ) -> int:
        """Stage the NEXT validator set as a second device-resident bank
        while the active one keeps serving launches. Everything expensive —
        the host f2 pack, the device_put, the prefix-table scan — happens
        here, off the launch critical path; the later `activate_staged` is
        a pointer flip between launches. Re-staging before activation
        replaces the pending bank (last staging wins). Returns the staged
        registry size."""
        t0 = time.perf_counter()
        T = self.curves.T
        pts = [pk.point for pk in registry_pubkeys]
        if any(p is None for p in pts):
            raise ValueError("staged registry keys must be valid G2 points")
        reg_x = self._dput(T.f2_pack([p[0] for p in pts]))
        reg_y = self._dput(T.f2_pack([p[1] for p in pts]))
        prefix = None
        if build_prefix:
            prefix = self._build_prefix(reg_x, reg_y)
            # materialize NOW: the flip must never pay the scan
            jax.block_until_ready(prefix[2])
        else:
            jax.block_until_ready(reg_y)
        self._staged = {
            "reg_x": reg_x, "reg_y": reg_y, "n": len(pts), "prefix": prefix,
        }
        self.registry_stagings += 1
        self.registry_staged_ms += (time.perf_counter() - t0) * 1e3
        return len(pts)

    def activate_staged(self) -> int:
        """Flip the staged bank live — the caller quiesces launches around
        this (lifecycle/epoch.py EpochManager.commit). Cheap by
        construction: pointer swaps, plus a staging-buffer realloc only
        when the registry size changed. Returns the new epoch."""
        st = self._staged
        if st is None:
            raise RuntimeError("no staged registry: call stage_registry first")
        if self.mesh is not None:
            if st["n"] != self.n:
                # the sharded sum/check executables are specialized to the
                # construction-time registry width; resizing would need a
                # rebuild of the whole staged pipeline
                raise RuntimeError(
                    "mesh-sharded registry rotation requires an equal-size "
                    f"validator set (active {self.n}, staged {st['n']})"
                )
            from handel_tpu.parallel.sharding import commit_registry_sharded

            self._reg_sharded = commit_registry_sharded(
                self.mesh, st["reg_x"], st["reg_y"], st["n"]
            )
        self._reg_x, self._reg_y = st["reg_x"], st["reg_y"]
        self._prefix_cache = st["prefix"]
        if st["n"] != self.n:
            self.n = st["n"]
            self._stage = [
                _StagingSet(
                    self.n, self.batch_size, self.MISS_CAP,
                    self.curves.F.nlimbs,
                )
                for _ in range(self.stage_sets)
            ]
            self._stage_idx = 0
        self._staged = None
        self.epoch += 1
        return self.epoch

    # -- the jitted batch kernels ------------------------------------------

    def _pairing_tail(self, agg, sig_x, sig_y, h_x, h_y, valid):
        """Shared epilogue: affine-convert the aggregates and run the batched
        product-of-pairings check  e(H, X_j) * e(-S_j, B2) == 1."""
        C = self.batch_size
        g2 = self.curves.g2
        T = self.curves.T
        F = self.curves.F
        agg_inf = g2.is_infinity(agg)
        qx, qy, _ = g2.to_affine(agg)

        b2 = (
            T.f2_pack([self.ref.G2_GEN[0]] * 1),
            T.f2_pack([self.ref.G2_GEN[1]] * 1),
        )
        bx = (
            jnp.broadcast_to(b2[0][0], qx[0].shape),
            jnp.broadcast_to(b2[0][1], qx[0].shape),
        )
        by = (
            jnp.broadcast_to(b2[1][0], qy[0].shape),
            jnp.broadcast_to(b2[1][1], qy[0].shape),
        )
        neg_sig_y = F.neg(sig_y)
        ok_lane = valid & ~agg_inf
        px = jnp.concatenate([jnp.broadcast_to(h_x, sig_x.shape), sig_x], axis=1)
        py = jnp.concatenate([jnp.broadcast_to(h_y, sig_y.shape), neg_sig_y], axis=1)
        qx2 = (
            jnp.concatenate([qx[0], bx[0]], axis=1),
            jnp.concatenate([qx[1], bx[1]], axis=1),
        )
        qy2 = (
            jnp.concatenate([qy[0], by[0]], axis=1),
            jnp.concatenate([qy[1], by[1]], axis=1),
        )
        lane_mask = jnp.concatenate([ok_lane, ok_lane])
        checks = self.pairing.pairing_check((px, py), (qx2, qy2), lane_mask, C)
        return checks & ok_lane

    def _unpack_words(self, words32, valid):
        """(C, 2W) uint32 bitset words -> (N*C,) block-major candidate mask,
        entirely on device: a gather + shift per registry index replaces the
        host-side (N, C) mask materialization the dense path used to stage
        and transfer (~N*C bytes/launch; the words are N/8 bytes)."""
        idx = jnp.arange(self.n)
        w = words32[:, idx // 32]  # (C, N) on-device gather
        bits = ((w >> (idx % 32).astype(jnp.uint32)) & jnp.uint32(1)) != 0
        bits = bits & valid[:, None]  # invalid lanes contribute nothing
        # block-major flatten: block i = registry key i across C candidates
        return bits.T.reshape(-1)

    def _verify_batch(self, reg_x, reg_y, words32, sig_x, sig_y, h_x, h_y, valid):
        """General launch: masked G2 segment-sum + batched multi-pairing.

        Shapes: reg_* (L, N) Fp2 pairs; words32 (C, 2W) uint32 packed bitset
        words (mask unpacked on device, `_unpack_words`); sig_*/h_* (L, C);
        valid (C,) bool. Returns (C,) verdicts. The fallback for arbitrary
        signer sets — contiguous-range candidates take `_verify_batch_range`.
        """
        C = self.batch_size
        g2 = self.curves.g2
        mask = self._unpack_words(words32, valid)

        # registry tiled block-major across candidates, masked, tree-summed
        tile = lambda a: jnp.repeat(a, C, axis=1)  # (L, N) -> (L, N*C)
        P2 = g2.from_affine(
            (tile(reg_x[0]), tile(reg_x[1])), (tile(reg_y[0]), tile(reg_y[1]))
        )
        agg = g2.masked_sum(P2, mask, self.n)  # projective, batch C
        return self._pairing_tail(agg, sig_x, sig_y, h_x, h_y, valid)

    def _gather_prefix(self, prefix, idx):
        """(C,) int32 -> projective G2 batch from the prefix table."""
        g2 = self.curves.g2
        (x0, x1), (y0, y1), inf = prefix
        take = lambda a: jnp.take(a, idx, axis=1)
        P = g2.from_affine((take(x0), take(x1)), (take(y0), take(y1)))
        return g2.select(jnp.take(inf, idx), g2.infinity(idx.shape[0]), P)

    def _range_aggregate(
        self, lo, hi, miss_idx, miss_ok, prefix, reg_x, reg_y, miss_k
    ):
        """Per-candidate aggregate key (projective) =
        prefix[hi] - prefix[lo] - sum(missing signers in the hull).

        prefix/reg_x/reg_y are jit ARGUMENTS, not closure reads: with the
        bank traced as an input, the compiled executable is shape-keyed
        only, so an epoch flip to an equal-size registry reuses it — no
        retrace, no recompile inside the quiesce window. (Capturing
        `self._reg_x` here would bake the construction-time bank in as a
        compile-time constant and every flip would silently keep verifying
        against the OLD validator set.)"""
        g2 = self.curves.g2
        hull = g2.add(
            self._gather_prefix(prefix, hi),
            g2.neg(self._gather_prefix(prefix, lo)),
        )
        if miss_k:
            take = lambda a: jnp.take(a, miss_idx, axis=1)
            Pm = g2.from_affine(
                (take(reg_x[0]), take(reg_x[1])),
                (take(reg_y[0]), take(reg_y[1])),
            )
            msum = g2.masked_sum(Pm, miss_ok, miss_k)
            hull = g2.add(hull, g2.neg(msum))
        return hull

    def _verify_batch_range(
        self, lo, hi, miss_idx, miss_ok, sig_x, sig_y, h_x, h_y, valid,
        prefix, reg_x, reg_y, miss_k,
    ):
        """Range-candidate launch: per-candidate aggregate key via the prefix
        table — the O(1)-per-candidate path for Handel traffic, where every
        candidate's signer set is an ID range of the binomial partitioner
        (partitioner.go rangeLevel) minus a few offline members. lo/hi: (C,)
        indices into the prefix table; miss_idx/miss_ok: (miss_k*C,)
        block-major registry indices + validity for the subtraction patch.
        prefix/reg_* are the active bank, passed as arguments (see
        _range_aggregate for why).
        """
        hull = self._range_aggregate(
            lo, hi, miss_idx, miss_ok, prefix, reg_x, reg_y, miss_k
        )
        return self._pairing_tail(hull, sig_x, sig_y, h_x, h_y, valid)

    # -- staged sharded pipeline (mesh_devices > 1) -------------------------

    def _range_agg_kernel(self, miss_k: int):
        """Range aggregation alone as its own executable: point adds only,
        no pairing — compiles in seconds and keeps the mesh out of the
        monolithic jit. The returned callable keeps the per-launch
        (lo, hi, miss_idx, miss_ok) signature and injects the CURRENT
        bank's prefix/registry as trailing jit arguments, so an epoch flip
        reaches already-compiled kernels (and an equal-size flip reuses
        the executable outright)."""
        _ = self._prefix
        fn = self._range_agg_kernels.get(miss_k)
        if fn is None:
            jitted = jax.jit(
                partial(self._range_aggregate, miss_k=miss_k),
                # donate only the per-launch staging inputs; the bank args
                # (4, 5, 6) are device residents and must survive launches
                donate_argnums=(0, 1, 2, 3) if self._donate else (),
            )

            def fn(lo, hi, miss_idx, miss_ok, _jitted=jitted):
                return _jitted(
                    lo, hi, miss_idx, miss_ok,
                    self._prefix, self._reg_x, self._reg_y,
                )

            self._range_agg_kernels[miss_k] = fn
        return fn

    def _sharded_tail(self, agg, sig_x, sig_y, h_x, h_y, valid):
        """Affine epilogue + candidate-sharded product-of-pairings, staged
        as separate executables with host glue (the structure the dryrun
        validated; see the __init__ comment for why not one jit)."""
        qx, qy, inf = self._affine_kernel(agg)
        ok = np.asarray(valid) & ~np.asarray(inf)
        hxb = jnp.broadcast_to(h_x, sig_x.shape)
        hyb = jnp.broadcast_to(h_y, sig_y.shape)
        neg_y = self._neg_kernel(sig_y)
        shape = qx[0].shape
        bx = (
            jnp.broadcast_to(self._b2x[0], shape),
            jnp.broadcast_to(self._b2x[1], shape),
        )
        by = (
            jnp.broadcast_to(self._b2y[0], shape),
            jnp.broadcast_to(self._b2y[1], shape),
        )
        checks = self._sharded_check(
            ((hxb, hyb), (sig_x, neg_y)), ((qx, qy), (bx, by)), jnp.asarray(ok)
        )
        return np.asarray(checks) & ok

    def _range_kernel(self, miss_k: int):
        # materialize the prefix table HERE, on the host, before jit runs:
        # if the lazy property first fired inside the trace, the cache would
        # permanently hold tracers from a finished trace and every later
        # launch would die with UnexpectedTracerError
        _ = self._prefix
        fn = self._range_kernels.get(miss_k)
        if fn is None:
            # donate every per-launch staging input; h_x/h_y (args 6, 7) are
            # the cached H(m) and the bank args (9, 10, 11) are the
            # device-resident prefix/registry — all must survive launches
            jitted = jax.jit(
                partial(self._verify_batch_range, miss_k=miss_k),
                donate_argnums=(0, 1, 2, 3, 4, 5, 8) if self._donate else (),
            )

            # same bank-injection wrapper as _range_agg_kernel: callers keep
            # the per-launch signature, epoch flips reach compiled kernels
            def fn(
                lo, hi, miss_idx, miss_ok, sig_x, sig_y, h_x, h_y, valid,
                _jitted=jitted,
            ):
                return _jitted(
                    lo, hi, miss_idx, miss_ok, sig_x, sig_y, h_x, h_y, valid,
                    self._prefix, self._reg_x, self._reg_y,
                )

            self._range_kernels[miss_k] = fn
        return fn

    # -- RLC combined-check launch class (models/rlc.py) --------------------

    # MSM digit width: 64-bit scalars run in 16 windowed steps of 15
    # buckets each (ops/curve.py Curve.msm)
    RLC_WINDOW = 4

    def _rlc_msm_tail(self, agg, sig_x, sig_y, r_bits, group_oh, valid):
        """Shared MSM stage: per-candidate aggregates (projective G2, batch
        C) + signature lanes -> (S, X_g) in affine.

        S = sum_j r_j·sig_j is a G1 MSM over the C signature lanes (C
        blocks of batch 1); X_g = sum_{j in group g} r_j·apk_j tiles each
        candidate across the G group lanes (index j*G + g) with the scalar
        bits gated by the group one-hot, so one G2 MSM computes every
        message group at once. Scalars are masked to the launch hull by
        zeroing invalid lanes' bit columns — those lanes contribute the
        identity. The affine epilogue converts each output batch in one
        stacked-inversion `to_affine` call."""
        C = self.batch_size
        g1, g2 = self.curves.g1, self.curves.g2
        G = group_oh.shape[0]
        rb = r_bits * valid[None, :].astype(r_bits.dtype)
        S = g1.msm(g1.from_affine(sig_x, sig_y), rb, C, window=self.RLC_WINDOW)
        tree = jax.tree_util.tree_map
        tiled = tree(
            lambda a: jnp.broadcast_to(
                a.reshape(a.shape[:-1] + (C, 1)), a.shape[:-1] + (C, G)
            ).reshape(a.shape[:-1] + (C * G,)),
            agg,
        )
        rb2 = (rb[:, :, None] * group_oh.T[None, :, :].astype(rb.dtype)).reshape(
            rb.shape[0], C * G
        )
        X = g2.msm(tiled, rb2, C, window=self.RLC_WINDOW)
        sx, sy, s_inf = g1.to_affine(S)
        xx, xy, x_inf = g2.to_affine(X)
        return sx, sy, s_inf, xx, xy, x_inf

    def _rlc_msm_range(
        self, lo, hi, miss_idx, miss_ok, sig_x, sig_y, r_bits, group_oh,
        valid, prefix, reg_x, reg_y, miss_k,
    ):
        agg = self._range_aggregate(
            lo, hi, miss_idx, miss_ok, prefix, reg_x, reg_y, miss_k
        )
        return self._rlc_msm_tail(agg, sig_x, sig_y, r_bits, group_oh, valid)

    def _rlc_msm_dense(
        self, words32, sig_x, sig_y, r_bits, group_oh, valid, reg_x, reg_y
    ):
        C = self.batch_size
        g2 = self.curves.g2
        mask = self._unpack_words(words32, valid)
        tile = lambda a: jnp.repeat(a, C, axis=1)
        P2 = g2.from_affine(
            (tile(reg_x[0]), tile(reg_x[1])), (tile(reg_y[0]), tile(reg_y[1]))
        )
        agg = g2.masked_sum(P2, mask, self.n)
        return self._rlc_msm_tail(agg, sig_x, sig_y, r_bits, group_oh, valid)

    def _rlc_check(self, sx, sy, s_inf, xx, xy, x_inf, h_gx, h_gy, g_occ):
        """(G+1)-lane product-of-pairings with ONE shared final exponentiation:
        lanes 0..G-1 carry e(H(m_g), X_g), lane G carries e(-S, B2). Masked
        lanes contribute 1 — which IS the factor an infinity operand would
        contribute (e(·, O) = e(O, ·) = 1), so infinity and padding lanes
        mask out without changing the product. Returns the (1,) verdict."""
        T, F = self.curves.T, self.curves.F
        b2x = T.f2_pack([self.ref.G2_GEN[0]])
        b2y = T.f2_pack([self.ref.G2_GEN[1]])
        px = jnp.concatenate([h_gx, sx], axis=1)
        py = jnp.concatenate([h_gy, F.neg(sy)], axis=1)
        qx = (
            jnp.concatenate([xx[0], b2x[0]], axis=1),
            jnp.concatenate([xx[1], b2x[1]], axis=1),
        )
        qy = (
            jnp.concatenate([xy[0], b2y[0]], axis=1),
            jnp.concatenate([xy[1], b2y[1]], axis=1),
        )
        lane_mask = jnp.concatenate([g_occ & ~x_inf, ~s_inf])
        return self.pairing.pairing_check((px, py), (qx, qy), lane_mask, 1)

    def _rlc_msm_kernel(self, kind: str, miss_k: int, G: int):
        """MSM/aggregation stage as its own executable per launch class —
        point adds only, no pairing, so it compiles in seconds and can be
        profiled (or host-checked, scripts/rlc_smoke.py) standalone. Same
        bank-injection wrapper as `_range_agg_kernel`: epoch flips reach
        compiled kernels. G rides in the key for the class bookkeeping;
        the executable itself specializes on the group_oh shape."""
        key = (kind, miss_k, G)
        fn = self._rlc_msm_kernels.get(key)
        if fn is None:
            if kind == "range":
                _ = self._prefix
                jitted = jax.jit(
                    partial(self._rlc_msm_range, miss_k=miss_k),
                    # per-launch staging + scalar operands donate; the bank
                    # args (9, 10, 11) are device residents
                    donate_argnums=tuple(range(9)) if self._donate else (),
                )

                def fn(
                    lo, hi, miss_idx, miss_ok, sig_x, sig_y, r_bits,
                    group_oh, valid, _jitted=jitted,
                ):
                    return _jitted(
                        lo, hi, miss_idx, miss_ok, sig_x, sig_y, r_bits,
                        group_oh, valid,
                        self._prefix, self._reg_x, self._reg_y,
                    )

            else:
                jitted = jax.jit(
                    self._rlc_msm_dense,
                    donate_argnums=tuple(range(6)) if self._donate else (),
                )

                def fn(
                    words32, sig_x, sig_y, r_bits, group_oh, valid,
                    _jitted=jitted,
                ):
                    return _jitted(
                        words32, sig_x, sig_y, r_bits, group_oh, valid,
                        self._reg_x, self._reg_y,
                    )

            self._rlc_msm_kernels[key] = fn
        return fn

    def _rlc_check_kernel(self, G: int):
        fn = self._rlc_check_kernels.get(G)
        if fn is None:
            fn = jax.jit(self._rlc_check)
            self._rlc_check_kernels[G] = fn
        return fn

    def _rlc_combined_launch(self, items, sub):
        """One combined RLC check over candidate indices `sub` of `items`
        ((msg, bitset, sig) triples, pre-screened valid): fresh 64-bit
        scalars, message-grouped G2 MSM (n_groups quantized to the next
        power of two), (G+1)-lane pairing tail. Returns the (1,) device
        verdict — async like every dispatch; staging reuse and fencing
        follow the ordinary launch contract."""
        t0 = time.perf_counter()
        C = self.batch_size
        plan = self._pack_requests([(items[j][1], items[j][2]) for j in sub])
        msgs = [items[j][0] for j in sub]
        uniq: dict[bytes, int] = {}
        gid = [uniq.setdefault(m, len(uniq)) for m in msgs]
        M = len(uniq)
        G = 1
        while G < M:
            G *= 2
        rs = rlc.draw_scalars(len(sub), self._rlc_rng)
        r_bits = np.zeros((rlc.SCALAR_BITS, C), np.uint32)
        r_bits[:, : len(sub)] = np.asarray(self.curves.scalar_bits64(rs))
        group_oh = np.zeros((G, C), bool)
        group_oh[gid, np.arange(len(sub))] = True
        g_occ = np.arange(G) < M
        # per-group H(m) columns; padded groups repeat the last real column
        # (masked out by g_occ, any finite h keeps the math well-defined)
        order = [None] * M
        for m, g in uniq.items():
            order[g] = m
        cols = [self._h_cols(m) for m in order]
        hx = np.concatenate([c[0] for c in cols] + [cols[-1][0]] * (G - M), axis=1)
        hy = np.concatenate([c[1] for c in cols] + [cols[-1][1]] * (G - M), axis=1)
        t1 = time.perf_counter()
        self.host_pack_ms += (t1 - t0) * 1000.0
        self.host_pack_launches += 1
        dp = self._dput
        staged = self._stage_plan(plan)
        if plan.kind == "range":
            lo, hi, mi, mo, sig_x, sig_y, valid = staged
            outs = self._rlc_msm_kernel("range", plan.miss_k, G)(
                lo, hi, mi, mo, sig_x, sig_y,
                dp(r_bits), dp(group_oh), valid,
            )
        else:
            words32, sig_x, sig_y, valid = staged
            outs = self._rlc_msm_kernel("dense", 0, G)(
                words32, sig_x, sig_y, dp(r_bits), dp(group_oh), valid
            )
        verdict = self._rlc_check_kernel(G)(*outs, dp(hx), dp(hy), dp(g_occ))
        self._stage[self._stage_idx].fence = verdict
        self.rlc_stats.miller_lanes += G + 1
        self.rlc_stats.final_exp_lanes += 1
        if M > 1:
            self.multi_msg_launches += 1
        self.host_dispatch_ms += (time.perf_counter() - t1) * 1000.0
        self.host_dispatch_launches += 1
        return verdict

    def _dispatch_rlc(self, items):
        """RLC-mode dispatch: pre-screen validity host-side (the same
        criterion the packer applies), launch the combined check over the
        valid lanes now (async), and defer verdict resolution — including
        any bisection relaunches — to `fetch`."""
        k = len(items)
        valid_j = [
            j
            for j, (_m, bs, sig) in enumerate(items)
            if bs.cardinality() > 0 and getattr(sig, "point", None) is not None
        ]
        vdev = (
            self._rlc_combined_launch(items, valid_j)
            if len(valid_j) > 1
            else None
        )
        return ("rlc", items, valid_j, vdev, k)

    def _fetch_rlc(self, handle):
        """Resolve an RLC handle: a passing combined check accepts every
        valid lane; a failing one bisects with fresh scalars down to the
        per-candidate oracle (`_dispatch_one` on the single candidate), so
        culprits are isolated and attributed exactly as per_candidate mode
        would. Invalid lanes are False without any device work."""
        _, items, valid_j, vdev, k = handle
        verdicts = [False] * k
        top = [vdev]

        def combined(sub):
            v = top[0]
            top[0] = None
            if v is None or len(sub) != len(valid_j):
                v = self._rlc_combined_launch(items, sub)
            return bool(np.asarray(v)[0])

        def oracle(j):
            msg, bs, sig = items[j]
            v = self._dispatch_one(msg, [(bs, sig)])
            return bool(np.asarray(v)[0])

        for j, ok in rlc.bisect_verify(
            valid_j, combined, oracle, self.rlc_stats
        ).items():
            verdicts[j] = ok
        return verdicts

    # -- host entry points --------------------------------------------------

    def _h_point(self, msg: bytes):
        cached = self._h_cache.get(msg)
        if cached is None:
            h = self._hash_to_g1(msg)
            cached = (
                self._dput(self.curves.F.pack([h[0]])),
                self._dput(self.curves.F.pack([h[1]])),
            )
            self._h_cache[msg] = cached
        return cached

    # dispatch-ahead bound for batch_verify: at most this many chunks'
    # device buffers in flight ahead of the fetch cursor (mirrors the
    # service's max_inflight; an unbounded window kept EVERY chunk's
    # uploads resident on device simultaneously — ADVICE r5 #3)
    MAX_DISPATCH_AHEAD = 4

    def batch_verify(
        self,
        msg: bytes,
        requests: Sequence[tuple[BitSet, BN254Signature]],
    ) -> list[bool]:
        """Verify up to batch_size (global bitset, aggregate sig) candidates
        in one device launch; longer request lists run in several launches.

        Launches are PIPELINED: a chunk is dispatched (enqueued on the
        device — jax dispatch is async) before earlier verdict arrays are
        pulled back to the host, so the per-dispatch round trip (~66 ms on
        this environment's tunneled chip, results/verify_profile.json)
        overlaps chip compute of the launches behind it instead of
        serializing with it — but at most MAX_DISPATCH_AHEAD chunks ahead
        of the fetch cursor, bounding device-resident input buffers. The
        reference's loop verifies one signature at a time on the caller's
        goroutine (processing.go:258-287)."""
        out: list[bool] = []
        window: list = []
        for i in range(0, len(requests), self.batch_size):
            if len(window) >= self.MAX_DISPATCH_AHEAD:
                out.extend(self.fetch(window.pop(0)))
            window.append(self.dispatch(msg, requests[i : i + self.batch_size]))
        for h in window:
            out.extend(self.fetch(h))
        return out

    def dispatch(self, msg, requests):
        """Enqueue one launch (≤ batch_size candidates); returns an opaque
        handle for `fetch`. On the single-device path the device work is in
        flight when this returns (jax async dispatch) and `fetch` blocks on
        the verdicts. On the mesh path the staged pipeline's host glue
        (`_sharded_tail`) completes the launch before returning — there
        `fetch` is effectively a no-op and launch wall time lands on the
        dispatch side of the monitor plane. In RLC mode the handle carries
        the in-flight combined check; bisection (if any) runs at fetch."""
        if self.batch_check == "rlc":
            return self._dispatch_rlc([(msg, bs, sig) for bs, sig in requests])
        return (self._dispatch_one(msg, requests), len(requests))

    def fetch(self, handle) -> list[bool]:
        """Block until a dispatched launch's verdicts arrive; host-ordered."""
        if len(handle) == 5 and handle[0] == "rlc":
            return self._fetch_rlc(handle)
        verdicts, k = handle
        return [bool(v) for v in np.asarray(verdicts)[:k]]

    # -- batched aggregate combine (store.py merge path) --------------------

    def _combine_kernel(self, k: int):
        """One masked G1 tree-sum + batch affine convert per group-width
        class (k quantized to powers of two so a handful of executables
        cover every merge shape). Point adds only — compiles in seconds,
        nothing pairing-shaped."""
        fn = self._combine_kernels.get(k)
        if fn is None:
            g1 = self.curves.g1

            def kern(px, py, pz, mask):
                return g1.to_affine(g1.masked_sum((px, py, pz), mask, k))

            fn = jax.jit(kern)
            self._combine_kernels[k] = fn
        return fn

    def combine_batch(self, groups, compiled_only: bool = False):
        """Sum many groups of G1 points — aggregate-signature merges — in
        one vmap'd launch per batch_size chunk.

        `groups` is a sequence of point sequences (affine scalar-oracle
        tuples, None = infinity); returns one combined affine point (or
        None) per group. This is the device replacement for the store's
        per-contribution `Signature.combine` host calls: `SignatureStore`
        merge/patch chains and the partitioner's level combination hand
        their whole point set here via `core/processing.py CombineShim` and
        pay one launch instead of one host pairing-library add per point.

        `compiled_only=True` (the CombineShim path) declines — None result
        entries, caller folds on the host — any chunk whose quantized
        group-width class has no compiled kernel yet, so a protocol round
        can NEVER stall on a mid-run combine compile (warmup covers the
        common classes; see `warmup`). Declines are indistinguishable from
        a legitimate infinity sum, which callers must treat the same way:
        redo on the host.
        """
        out = []
        for i in range(0, len(groups), self.batch_size):
            out.extend(
                self._combine_chunk(groups[i : i + self.batch_size],
                                    compiled_only)
            )
        return out

    def _combine_chunk(self, groups, compiled_only: bool = False):
        C = self.batch_size
        kmax = max((len(g) for g in groups), default=1)
        k = 2
        while k < kmax:
            k *= 2
        if compiled_only and k not in self._combine_kernels:
            return [None] * len(groups)
        # block-major grid: block i = element i of every group's sum
        flat = [None] * (k * C)
        mask = np.zeros((k, C), bool)
        for j, g in enumerate(groups):
            for i, p in enumerate(g):
                flat[i * C + j] = p
                mask[i, j] = p is not None
        P = self.curves.pack_g1(flat)
        x, y, inf = self._combine_kernel(k)(*P, jnp.asarray(mask.reshape(-1)))
        F = self.curves.F
        xs = F.unpack(x)
        ys = F.unpack(y)
        infs = np.asarray(inf)
        return [
            None if infs[j] else (xs[j], ys[j]) for j in range(len(groups))
        ]

    def warmup(self, multi_msg: bool = False) -> int:
        """Compile every kernel a verification round can reach, up front.

        `multi_msg=True` additionally compiles the per-lane-h variant of
        the common range class (the `dispatch_multi` shape a multi-tenant
        service reaches once sessions with distinct messages coalesce into
        one launch) — off by default because single-tenant runs never hit
        it and each variant is a full pairing-graph compile.

        Dispatches one synthetic launch per reachable input class — range
        kernel at miss_k=8, range kernel at miss_k=64, dense fallback — so
        no round ever stalls on a mid-run XLA compile (before this, the
        first candidate in a new hole-count class blocked its whole round).
        Classes a registry of this size cannot produce are skipped: the
        64-hole class needs an 11-wide hull, the dense fallback a
        (MISS_CAP+3)-wide one. Returns the number of launches issued.
        Called at scheme construction (BN254JaxConstructor.prepare).
        """
        shapes: list[list[int]] = [
            # zero holes -> miss_k=8 class (also builds the prefix table)
            list(range(min(self.n, 2)))
        ]
        if self.n >= 11:
            # hull [0, 11) with 9 holes -> miss_k=64 class
            shapes.append([0, 10])
        if self.n >= self.MISS_CAP + 3:
            # MISS_CAP+1 holes -> dense masked-sum fallback
            shapes.append([0, self.MISS_CAP + 2])
        sig = _WarmupSig(self.ref.G1_GEN)
        launches = 0
        for signers in shapes:
            bs = BitSet(self.n)
            for i in signers:
                bs.set(i, True)
            # in RLC mode a single-candidate dispatch resolves through the
            # per-candidate oracle at fetch, so this loop compiles the
            # per-candidate kernel classes (the bisection floor) either way
            self.fetch(self.dispatch(b"bn254-device-warmup", [(bs, sig)]))
            launches += 1
        if self.batch_check == "rlc":
            # compile the RLC combined-check classes (MSM stage + (G+1)-lane
            # pairing tail) with a two-candidate launch per plan class. The
            # warmup sig is not a valid signature, so each combined check
            # FAILS and the bisection path — fresh-scalar singleton oracles
            # — runs too: exactly the kernels a forged batch needs hot.
            for signers in shapes:
                bs = BitSet(self.n)
                for i in signers:
                    bs.set(i, True)
                self.fetch(
                    self.dispatch(b"bn254-device-warmup", [(bs, sig)] * 2)
                )
                launches += 1
        if multi_msg and self.n >= 2:
            bs1, bs2 = BitSet(self.n), BitSet(self.n)
            bs1.set(0, True)
            bs2.set(1, True)
            self.fetch(
                self.dispatch_multi(
                    [
                        (b"bn254-device-warmup-m1", None, bs1, sig),
                        (b"bn254-device-warmup-m2", None, bs2, sig),
                    ]
                )
            )
            launches += 1
        # combine classes k=2/4/8 cover pairwise merges through wide patch
        # chains (point adds only — seconds each, not a pairing graph);
        # the CombineShim path only uses classes compiled HERE
        # (combine_batch(compiled_only=True)), so wider merges host-fold
        # instead of ever compiling mid-round
        for k in (2, 4, 8):
            self.combine_batch([[self.ref.G1_GEN] * k])
            launches += 1
        # warmup launches must not skew the host-cost telemetry
        self.reset_host_counters()
        return launches

    def reset_host_counters(self) -> None:
        """Zero the host pack/dispatch cost counters (warmup and bench
        phase boundaries: accumulation must start at the phase, not at
        construction)."""
        self.host_pack_ms = 0.0
        self.host_pack_launches = 0
        self.host_dispatch_ms = 0.0
        self.host_dispatch_launches = 0
        self.rlc_stats = rlc.RlcStats()

    # missing-signer patch width cap: candidates whose range hull has more
    # holes than this fall back to the dense masked-sum kernel
    MISS_CAP = 64

    @staticmethod
    def _pack_sig_limbs(F, pts, out):
        """Pack G1 coordinate limbs into staging, uniquing by point object
        identity first: Handel traffic re-delivers the same aggregate (one
        signature OBJECT fanned across lanes after dedup coalescing), so the
        bigint limb conversion — the single most expensive per-lane pack op
        — runs once per distinct point, then scatters by fancy index."""
        uniq: dict[int, int] = {}
        inv = np.empty((len(pts),), np.int64)
        upts: list = []
        for j, p in enumerate(pts):
            i = uniq.get(id(p))
            if i is None:
                i = uniq[id(p)] = len(upts)
                upts.append(p)
            inv[j] = i
        ux = F.pack_batch_np([p[0] for p in upts])
        uy = F.pack_batch_np([p[1] for p in upts])
        out.sig_x[:] = ux[:, inv]
        out.sig_y[:] = uy[:, inv]

    # all-ones uint64, for the hull word-mask construction below
    _U64_ONES = np.uint64(0xFFFFFFFFFFFFFFFF)

    @classmethod
    def _ones_below(cls, c):
        """(1 << c) - 1 for per-element widths c in [0, 64] (uint64-safe:
        numpy's shift by 64 is undefined, so full words take a where)."""
        shift = np.minimum(c, np.uint64(63))
        return np.where(
            c >= 64, cls._U64_ONES, (np.uint64(1) << shift) - np.uint64(1)
        )

    def _pack_requests(self, requests) -> "LaunchPlan":
        """Vectorized launch packing: requests -> device-input arrays.

        Bitsets hand over their packed uint64 words (BitSet.words, zero
        copy) straight into the rotated staging set — the same words array
        is later the device-transfer source (zero-copy: no dense bit matrix
        is materialized on the host at all). Cardinalities come from one
        `np.bitwise_count` over the words, range bounds from word-level
        argmax scans plus branch-free bit scans of the two edge words, and
        the missing-signer patch unpacks only the hull-masked COMPLEMENT
        words (skipped entirely for hole-free batches, the common Handel
        case). Staging buffers ROTATE across `stage_sets` sets: a returned
        plan's views stay valid until the rotation wraps back onto its set
        (see _StagingSet for the fence that enforces this against
        still-in-flight launches).

        Bit-identical to `_pack_requests_loop` (property-tested across
        rotation boundaries), which keeps the old per-candidate construction
        as the readable oracle.
        """
        C = self.batch_size
        n = self.n
        k = len(requests)
        self._stage_idx = (self._stage_idx + 1) % len(self._stage)
        st = self._stage[self._stage_idx]
        if st.fence is not None:
            # the last launch that read this set must have consumed its
            # inputs before we overwrite them (no-op once it completed)
            st.fence.block_until_ready()
            st.fence = None
        words = st.words
        words[:] = 0
        valid = st.valid
        valid[:] = False
        sig_pts: list = []
        for j, (bs, sig) in enumerate(requests):
            if len(bs) != n:
                raise ValueError("bitset length != registry size")
            words[j, :] = bs.words()
            sig_pts.append(getattr(sig, "point", None))

        card = np.bitwise_count(words).sum(axis=1, dtype=np.int64)
        if k:
            valid[:k] = (card[:k] > 0) & np.fromiter(
                (p is not None for p in sig_pts), bool, count=k
            )
        words[~valid] = 0  # invalid lanes contribute nothing

        # range bounds without unpacking: first/last nonzero word per lane,
        # then a trailing-zero / leading-bit scan of just those edge words
        wnz = words != 0
        nonempty = wnz.any(axis=1)
        W = words.shape[1]
        rows = np.arange(C)
        fw = wnz.argmax(axis=1)
        lw = (W - 1) - wnz[:, ::-1].argmax(axis=1)
        wf = words[rows, fw]
        tz = np.bitwise_count(  # trailing zeros: popcount((w & -w) - 1)
            (wf & (~wf + np.uint64(1))) - np.uint64(1)
        ).astype(np.int64)
        v = words[rows, lw].copy()  # leading bit: smear right, popcount - 1
        for s in (1, 2, 4, 8, 16, 32):
            v |= v >> np.uint64(s)
        msb = np.bitwise_count(v).astype(np.int64) - 1
        lo, hi = st.lo, st.hi
        lo[:] = np.where(nonempty, fw * 64 + tz, 0)
        hi[:] = np.where(nonempty, lw * 64 + msb + 1, 0)  # one past last bit
        holes = (hi.astype(np.int64) - lo) - np.where(valid, card, 0)
        max_holes = int(holes.max())

        # lanes with a point but an empty bitset stay masked placeholders,
        # like the old loop (valid gating covers both cases)
        pts = [
            pt if valid[j] else self.ref.G1_GEN
            for j, pt in enumerate(sig_pts)
        ]
        pts += [self.ref.G1_GEN] * (C - k)  # pad lanes
        self._pack_sig_limbs(self.curves.F, pts, st)

        if max_holes > self.MISS_CAP:
            # dense fallback: the words themselves are the device input
            # (mask unpacked on device by _unpack_words)
            return LaunchPlan(
                "dense", 0, None, None, None, None, words, None,
                st.sig_x, st.sig_y, valid,
            )

        # quantize the patch width to two classes so at most two range
        # kernels ever compile (each variant jit-compiles the whole
        # pairing graph; a fresh hole-count class mid-run would
        # otherwise stall that verification round on XLA)
        miss_k = 8 if max_holes <= 8 else self.MISS_CAP
        miss_idx = st.miss[:miss_k]
        miss_ok = st.miss_ok[:miss_k]
        miss_idx[:] = 0
        miss_ok[:] = False
        if max_holes > 0:
            # unpack only the hull-masked complement: hole bits = ~words
            # inside each lane's [lo, hi) hull, built as a (C, W) word mask
            base = np.arange(W, dtype=np.int64) * 64
            lo_c = np.clip(lo.astype(np.int64)[:, None] - base, 0, 64)
            hi_c = np.clip(hi.astype(np.int64)[:, None] - base, 0, 64)
            hull = self._ones_below(hi_c.astype(np.uint64)) ^ self._ones_below(
                lo_c.astype(np.uint64)
            )
            missw = hull & ~words
            mbits = np.unpackbits(
                missw.view(np.uint8), axis=1, count=n, bitorder="little"
            ).view(np.bool_)
            rj, cj = np.nonzero(mbits)  # row-major: per-candidate, ascending
            if rj.size:
                counts = mbits.sum(axis=1)
                offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
                pos = np.arange(rj.size) - offs[rj]
                miss_idx[pos, rj] = cj
                miss_ok[pos, rj] = True
        return LaunchPlan(
            "range", miss_k, lo, hi, miss_idx, miss_ok, words, None,
            st.sig_x, st.sig_y, valid,
        )

    def _pack_requests_loop(self, requests) -> "LaunchPlan":
        """The pre-vectorization per-candidate packer, kept as the oracle
        for `_pack_requests` equivalence tests and the bench.py host_pack_ms
        before/after comparison. Allocates fresh arrays (no staging)."""
        C = self.batch_size
        F = self.curves.F
        sig_pts = []
        valid = np.zeros((C,), dtype=bool)
        sets: list[np.ndarray] = []
        for j, (bs, sig) in enumerate(requests):
            if len(bs) != self.n:
                raise ValueError("bitset length != registry size")
            idx = np.fromiter(bs.indices(), dtype=np.int64)
            sig_pt = getattr(sig, "point", None)
            if idx.size and sig_pt is not None:
                valid[j] = True
                sig_pts.append(sig_pt)
            else:
                sig_pts.append(self.ref.G1_GEN)  # placeholder, lane masked out
            sets.append(idx)
        sig_pts += [self.ref.G1_GEN] * (C - len(sig_pts))  # pad lanes
        sig_x = F.pack([p[0] for p in sig_pts])
        sig_y = F.pack([p[1] for p in sig_pts])

        holes = [
            int(idx[-1] - idx[0] + 1 - idx.size) if v and idx.size else 0
            for idx, v in zip(sets, valid)
        ]
        if max(holes, default=0) > self.MISS_CAP:
            mask = np.zeros((self.n, C), dtype=bool)
            for j, idx in enumerate(sets):
                if valid[j] and idx.size:
                    mask[idx, j] = True
            return LaunchPlan(
                "dense", 0, None, None, None, None, None, mask,
                sig_x, sig_y, valid,
            )
        lo = np.zeros((C,), np.int32)
        hi = np.zeros((C,), np.int32)
        miss_k = 8 if max(holes, default=0) <= 8 else self.MISS_CAP
        miss_idx = np.zeros((miss_k, C), np.int64)
        miss_ok = np.zeros((miss_k, C), dtype=bool)
        for j, idx in enumerate(sets):
            if not valid[j] or not idx.size:
                continue
            lo[j] = idx[0]
            hi[j] = idx[-1] + 1
            missing = np.setdiff1d(
                np.arange(idx[0], idx[-1] + 1), idx, assume_unique=True
            )
            miss_idx[: missing.size, j] = missing
            miss_ok[: missing.size, j] = True
        return LaunchPlan(
            "range", miss_k, lo, hi, miss_idx, miss_ok, None, None,
            sig_x, sig_y, valid,
        )

    def _stage_plan(self, plan):
        """Explicit host→device handoff of one plan's staging views.

        One `jax.device_put` per array, no snapshot copies: the rotation +
        fence contract of `_pack_requests` guarantees a still-in-flight
        launch's (possibly aliased, on the CPU client) buffers are never
        overwritten. Explicit puts are the ONLY host→device transfers a
        steady-state launch performs — everything else (registry, prefix
        table, cached H(m)) is device-resident — which is what lets the
        transfer-guard test allowlist staging while banning implicit
        transfers outright. Returns the per-kind device-argument tuple
        (committed to this engine's pinned chip when one was given).
        """
        dp = self._dput
        if plan.kind == "range":
            return (
                dp(plan.lo),
                dp(plan.hi),
                dp(plan.miss_idx.reshape(-1)),
                dp(plan.miss_ok.reshape(-1)),
                dp(plan.sig_x),
                dp(plan.sig_y),
                dp(plan.valid),
            )
        return (
            dp(plan.words.view(np.uint32)),
            dp(plan.sig_x),
            dp(plan.sig_y),
            dp(plan.valid),
        )

    def _run_plan(self, plan, staged, h_x, h_y):
        """Launch one staged plan against the kernels. h_x/h_y may be the
        cached per-message (L, 1) arrays (broadcast across lanes) or the
        multi-message (L, C) per-lane columns — the kernels broadcast to
        the signature shape either way, so both shapes share the math; XLA
        compiles one extra variant per kernel class for the wide shape."""
        # Handel candidates are partitioner ID ranges with few holes: the
        # prefix-table fast path; the dense kernel is the arbitrary-set
        # fallback (plan.kind decides, same classes as always)
        # per-candidate pairing-work accounting (the 2C / C baseline the
        # RLC smoke compares against): every plan run pays a 2C-lane
        # Miller batch and a C-lane final exponentiation
        self.rlc_stats.miller_lanes += 2 * self.batch_size
        self.rlc_stats.final_exp_lanes += self.batch_size
        if self.mesh is not None:
            # whole-mesh (latency-plane) launch accounting: the mesh lane's
            # telemetry row (parallel/telemetry.py) reads these
            self.mesh_launches += 1
            self.mesh_candidates += int(np.count_nonzero(plan.valid))
        if plan.kind == "range":
            lo, hi, miss_idx, miss_ok, sig_x, sig_y, valid = staged
            if self.mesh is not None:
                agg = self._range_agg_kernel(plan.miss_k)(
                    lo, hi, miss_idx, miss_ok
                )
                return self._sharded_tail(agg, sig_x, sig_y, h_x, h_y, valid)
            return self._range_kernel(plan.miss_k)(
                lo, hi, miss_idx, miss_ok, sig_x, sig_y, h_x, h_y, valid
            )
        words32, sig_x, sig_y, valid = staged
        if self.mesh is not None:
            # the staged sharded pipeline still wants the dense (n, C)
            # mask; unpack it host-side here — the mesh path's host glue
            # already materializes per-stage arrays, so this is not the
            # single-chip hot path
            mask = (
                np.unpackbits(
                    plan.words.view(np.uint8),
                    axis=1,
                    count=self.n,
                    bitorder="little",
                )
                .view(np.bool_)
                .T.copy()
            )
            # pre-pad to the device multiple (padded rows False — the rule
            # sharded_masked_sum_g2 applies internally) and place by
            # partition rule, one registry-axis shard per chip, so the
            # shard_map region never re-shards a replicated mask
            if self._mesh_pad:
                mask = np.pad(mask, ((0, self._mesh_pad), (0, 0)))
            mask = self._mesh_put["mask"](mask)
            # registry operands are the PRE-PADDED mesh-resident shards
            # committed at construction (one per chip); only the per-launch
            # mask crosses the host boundary here
            (rx0, rx1), (ry0, ry1) = self._reg_sharded
            agg = self._sharded_sum(rx0, rx1, ry0, ry1, mask)
            return self._sharded_tail(agg, sig_x, sig_y, h_x, h_y, valid)
        return self._kernel(
            self._reg_x,
            self._reg_y,
            words32,
            sig_x,
            sig_y,
            h_x,
            h_y,
            valid,
        )

    def _dispatch_one(self, msg, requests):
        t0 = time.perf_counter()
        plan = self._pack_requests(requests)
        t1 = time.perf_counter()
        self.host_pack_ms += (t1 - t0) * 1000.0
        self.host_pack_launches += 1
        h_x, h_y = self._h_point(msg)
        staged = self._stage_plan(plan)
        verdicts = self._run_plan(plan, staged, h_x, h_y)
        if isinstance(verdicts, jax.Array):
            # fence the staging set this launch reads: _pack_requests blocks
            # on it before the rotation wraps back onto these buffers
            self._stage[self._stage_idx].fence = verdicts
        self.host_dispatch_ms += (time.perf_counter() - t1) * 1000.0
        self.host_dispatch_launches += 1
        return verdicts

    # -- multi-message launches (multi-tenant service coalescing) -----------

    def _h_cols(self, msg: bytes):
        """Host-side (L, 1) limb columns of H(msg) — the np counterpart of
        `_h_point`'s device-resident cache, kept separately so building a
        per-lane h matrix never pulls a device array back to the host."""
        cached = self._h_np_cache.get(msg)
        if cached is None:
            h = self._hash_to_g1(msg)
            F = self.curves.F
            cached = (F.pack_batch_np([h[0]]), F.pack_batch_np([h[1]]))
            self._h_np_cache[msg] = cached
        return cached

    def _h_lanes(self, msgs):
        """(L, C) per-lane H(m) arrays for a mixed-message launch, built by
        scattering the per-distinct-message columns (hash-to-curve runs
        once per distinct message, cached) and explicitly device_put —
        the same staging discipline as `_stage_plan`."""
        C = self.batch_size
        uniq: dict[bytes, int] = {}
        inv = np.empty((len(msgs),), np.int64)
        cols: list[tuple] = []
        for j, m in enumerate(msgs):
            i = uniq.get(m)
            if i is None:
                i = uniq[m] = len(cols)
                cols.append(self._h_cols(m))
            inv[j] = i
        hx = np.concatenate([c[0] for c in cols], axis=1)[:, inv]
        hy = np.concatenate([c[1] for c in cols], axis=1)[:, inv]
        if len(msgs) < C:
            # padded lanes are masked invalid; any finite h keeps the math
            # well-defined, so repeat the last real column
            hx = np.concatenate(
                [hx, np.repeat(hx[:, -1:], C - len(msgs), axis=1)], axis=1
            )
            hy = np.concatenate(
                [hy, np.repeat(hy[:, -1:], C - len(msgs), axis=1)], axis=1
            )
        return self._dput(hx), self._dput(hy)

    def dispatch_multi(self, items):
        """Enqueue one launch whose lanes may carry DIFFERENT messages —
        the multi-tenant service's cross-session coalescing contract
        (parallel/batch_verifier.py): items are (msg, pubkeys, bitset,
        sig); pubkeys are ignored because this device's resident registry
        is the key universe for every lane. A uniform-message batch
        delegates to the ordinary `dispatch` (cached (L, 1) h, no extra
        kernel variant); mixed messages stage per-lane (L, C) h columns
        into the same kernels. Returns a `fetch`-compatible handle.

        In RLC mode mixed messages GROUP rather than widen: the combined
        check groups lanes by message for M+1 Miller loops total, so a
        multi-tenant coalesced launch costs one Miller loop per distinct
        message plus one — not two per candidate."""
        if self.batch_check == "rlc":
            return self._dispatch_rlc([(it[0], it[2], it[3]) for it in items])
        msgs = [it[0] for it in items]
        reqs = [(it[2], it[3]) for it in items]
        if len(set(msgs)) <= 1:
            return self.dispatch(msgs[0] if msgs else b"", reqs)
        t0 = time.perf_counter()
        plan = self._pack_requests(reqs)
        t1 = time.perf_counter()
        self.host_pack_ms += (t1 - t0) * 1000.0
        self.host_pack_launches += 1
        h_x, h_y = self._h_lanes(msgs)
        staged = self._stage_plan(plan)
        verdicts = self._run_plan(plan, staged, h_x, h_y)
        if isinstance(verdicts, jax.Array):
            self._stage[self._stage_idx].fence = verdicts
        self.host_dispatch_ms += (time.perf_counter() - t1) * 1000.0
        self.host_dispatch_launches += 1
        self.multi_msg_launches += 1
        return (verdicts, len(reqs))


class BN254JaxConstructor(BN254Constructor):
    """Constructor whose `batch_verify` runs on the JAX/TPU path.

    The device registry is built lazily from the pubkey sequence of the first
    call (Handel passes the same registry list every time) or eagerly via
    `prepare()`. Marshal/unmarshal and single-sig verify stay host-side.

    Failover (`host_fallback=True`): device/XLA errors — including a compile
    or upload failure inside the lazy prepare — feed a circuit breaker, and
    the batch resolves through the INHERITED host-side serial batch_verify
    (Constructor.batch_verify over the host pubkey objects, i.e. the
    ops/bn254_ref reference math; curve-agnostic, so the BLS12-381 subclass
    inherits the failover too) instead of raising. This covers
    the per-node default-verifier path the same way BatchVerifierService
    covers the shared launch queue (parallel/batch_verifier.py): a dead
    accelerator degrades throughput, it does not stall the node. Request
    errors (ValueError: malformed bitsets) are the caller's bug and
    propagate untouched.
    """

    Device = BN254Device

    def __init__(
        self,
        batch_size: int = 16,
        curves: BN254Curves | None = None,
        mesh_devices: int = 1,
        warmup: bool = True,
        host_fallback: bool = True,
        breaker: CircuitBreaker | None = None,
        fp_backend: str | None = None,
        rns_resident: bool | None = None,
        batch_check: str = "per_candidate",
        rlc_rng: random.Random | None = None,
    ):
        self.batch_size = batch_size
        self.mesh_devices = mesh_devices
        self.fp_backend = fp_backend
        self.rns_resident = rns_resident
        self.batch_check = rlc.validate_batch_check(batch_check)
        self._rlc_rng = rlc_rng
        # fp_backend picks the Field modmul kernel (ops/fp.py backend seam:
        # "cios"/"rns"); an explicit `curves` wins, carrying its own Field
        self.curves = curves or self.Device.Curves(backend=fp_backend)
        self.warmup = warmup
        self.host_fallback = host_fallback
        self.breaker = breaker or CircuitBreaker()
        self.failover_batches = 0
        self.failover_candidates = 0
        self.log = DEFAULT_LOGGER
        self._device: BN254Device | None = None
        self._device_for: int | None = None

    def prepare(self, pubkeys: Sequence[BN254PublicKey]) -> BN254Device:
        self._device = self.Device(
            pubkeys,
            batch_size=self.batch_size,
            curves=self.curves,
            mesh_devices=self.mesh_devices,
            rns_resident=self.rns_resident,
            batch_check=self.batch_check,
            rlc_rng=self._rlc_rng,
        )
        if self.warmup:
            # compile all reachable kernels NOW, at scheme construction, so
            # no verification round stalls on a mid-run XLA compile
            self._device.warmup()
        # hold the list itself: the id() cache key below is only valid while
        # the original object is alive (id reuse after GC would alias a new
        # registry to the cached one)
        self._reg_list = pubkeys
        self._device_for = id(pubkeys)
        self._reg_keys = [pk.point for pk in pubkeys]
        return self._device

    def _device_of(self, pubkeys) -> BN254Device:
        if self._device is None or self._device.n != len(pubkeys):
            self.prepare(pubkeys)
        elif self._device_for != id(pubkeys):
            # same length, different list object: full content check once per
            # new list identity (a same-size registry rebuilt after churn must
            # NOT verify against stale keys), then adopt the id so repeat
            # calls stay O(1)
            if [pk.point for pk in pubkeys] == self._reg_keys:
                self._reg_list = pubkeys
                self._device_for = id(pubkeys)
            else:
                self.prepare(pubkeys)
        return self._device

    def device_combine(self, groups):
        """Batched aggregate combine for `core/processing.py CombineShim`:
        sum each group of G1 signature points in one device launch. Returns
        None (caller falls back to host serial combine) until the device
        exists — the shim must never force an eager registry upload — or
        when the breaker has the device offline."""
        if self._device is None or not self.breaker.allow():
            return None
        try:
            # compiled_only: a merge shape warmup did not cover host-folds
            # (None entry) rather than stalling the round on an XLA compile
            out = self._device.combine_batch(groups, compiled_only=True)
            self.breaker.record_success()
            return out
        except Exception as e:  # device/XLA failure: host fold instead
            self.breaker.record_failure()
            self.log.warn("bn254_device_combine_error", e)
            return None

    def batch_verify(self, msg, pubkeys, requests) -> list[bool]:
        if not self.host_fallback:
            return self._device_of(pubkeys).batch_verify(msg, requests)
        if self.breaker.allow():
            try:
                out = self._device_of(pubkeys).batch_verify(msg, requests)
                self.breaker.record_success()
                return out
            except ValueError:
                raise  # malformed request, not a device failure
            except Exception as e:
                self.breaker.record_failure()
                self.log.warn("bn254_device_error", e)
        self.failover_batches += 1
        self.failover_candidates += len(requests)
        return super().batch_verify(msg, pubkeys, requests)


class BN254JaxScheme(BN254Scheme):
    """Keygen facade for harness/simulation use: the host scheme's keygen and
    wire formats (incl. unmarshal_public/unmarshal_secret for the registry
    CSV) with the device-verification constructor swapped in."""

    def __init__(
        self,
        batch_size: int = 16,
        mesh_devices: int = 1,
        warmup: bool = True,
        fp_backend: str | None = None,
        rns_resident: bool | None = None,
        batch_check: str = "per_candidate",
    ):
        self.constructor = BN254JaxConstructor(
            batch_size=batch_size,
            mesh_devices=mesh_devices,
            warmup=warmup,
            fp_backend=fp_backend,
            rns_resident=rns_resident,
            batch_check=batch_check,
        )


def make_async_verifier(device: BN254Device):
    """Adapt a BN254Device into the processing pipeline's AsyncVerifier,
    running launches in a worker thread so the event loop stays live."""

    async def verify(msg, pubkeys, requests):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, partial(device.batch_verify, msg, requests)
        )

    return verify

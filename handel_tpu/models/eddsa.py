"""Ed25519 (RFC 8032) scheme — the non-aggregating baseline.

BLS's one-pairing-per-verify cost is what the whole device plane exists
to amortize; this scheme is the control group. Ed25519 cannot aggregate:
a "multisignature" here is the literal SET of individual signatures,
each tagged with a key id (kid = first 8 bytes of the signer's encoded
public key), and `combine` is set union. Wire cost therefore grows
linearly with cardinality where BLS stays one G1 point — exactly the
trade the results/README.md comparison row (scripts/eddsa_compare.py)
quantifies. Verification is k independent scalar-mult checks instead of
one pairing product, so it wins at small committees and loses the wire.

Pure-Python big-int field math over 2^255-19, extended homogeneous
coordinates, cofactorless verification (S*B == R + k*A). Deterministic
keygen from a seeded SHA-256, like the other schemes' simulation keygen.

The aggregate wire envelope is fixed-size (Constructor.signature_size
contract: MultiSignature slices a fixed suffix): a uint16 count followed
by MAX_SIGNERS slots of (kid[8] || R[32] || S[32]), zero-padded. Use it
for committees up to MAX_SIGNERS; the registry aliases are "eddsa" and
"ed25519".
"""

from __future__ import annotations

import hashlib
import struct

from handel_tpu.core.crypto import Constructor

# -- curve parameters (RFC 8032 §5.1) ---------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P

_ENTRY = 8 + 64  # kid || R || S
MAX_SIGNERS = 64
_SIG_SIZE = 2 + MAX_SIGNERS * _ENTRY


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


# -- point arithmetic, extended homogeneous (x, y, z, t), t = xy/z ----------


def _pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _pt_mul(s: int, p):
    q = (0, 1, 1, 0)  # identity
    while s > 0:
        if s & 1:
            q = _pt_add(q, p)
        p = _pt_add(p, p)
        s >>= 1
    return q


def _pt_equal(p, q) -> bool:
    # cross-multiply out the projective z
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


_BY = 4 * pow(5, P - 2, P) % P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
B = (_BX, _BY, 1, _BX * _BY % P)

_SQRT_M1 = pow(2, (P - 1) // 4, P)


def _recover_x(y: int, sign: int) -> int | None:
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * _SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


def point_compress(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    x, y = x * zinv % P, y * zinv % P
    return int(y | ((x & 1) << 255)).to_bytes(32, "little")


def point_decompress(data: bytes):
    if len(data) != 32:
        raise ValueError("Ed25519 point must be 32 bytes")
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        raise ValueError("invalid Ed25519 point")
    return (x, y, 1, x * y % P)


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def _kid(enc_pub: bytes) -> bytes:
    return enc_pub[:8]


# -- scheme objects ----------------------------------------------------------


class EdDSASignature:
    """kid -> (R || S) signature set; combine is union (NO aggregation)."""

    __slots__ = ("sigs",)

    def __init__(self, sigs: dict[bytes, bytes]):
        self.sigs = sigs

    def marshal(self) -> bytes:
        if len(self.sigs) > MAX_SIGNERS:
            raise ValueError(
                f"eddsa aggregate holds {len(self.sigs)} > {MAX_SIGNERS} sigs"
            )
        out = [struct.pack(">H", len(self.sigs))]
        for kid in sorted(self.sigs):
            out.append(kid + self.sigs[kid])
        out.append(b"\x00" * ((MAX_SIGNERS - len(self.sigs)) * _ENTRY))
        return b"".join(out)

    def combine(self, other: "EdDSASignature") -> "EdDSASignature":
        merged = dict(self.sigs)
        merged.update(other.sigs)
        return EdDSASignature(merged)

    def wire_cardinality(self) -> int:
        return len(self.sigs)


class EdDSAPublicKey:
    """kid -> curve point set; combine is union, mirroring the signature."""

    __slots__ = ("keys",)

    def __init__(self, keys: dict[bytes, tuple]):
        self.keys = keys

    def marshal(self) -> bytes:
        # single keys round-trip through unmarshal_public; multi-key sets
        # only exist in memory during verification
        return b"".join(point_compress(self.keys[k]) for k in sorted(self.keys))

    def verify(self, msg: bytes, sig: EdDSASignature) -> bool:
        """Every key in this set must have a valid entry in `sig`."""
        if not isinstance(sig, EdDSASignature) or not self.keys:
            return False
        for kid, point in self.keys.items():
            rs = sig.sigs.get(kid)
            if rs is None or not _verify_one(msg, point, rs):
                return False
        return True

    def combine(self, other: "EdDSAPublicKey") -> "EdDSAPublicKey":
        merged = dict(self.keys)
        merged.update(other.keys)
        return EdDSAPublicKey(merged)


def _verify_one(msg: bytes, pub_point, rs: bytes) -> bool:
    try:
        r_pt = point_decompress(rs[:32])
    except ValueError:
        return False
    s = int.from_bytes(rs[32:64], "little")
    if s >= L:
        return False
    enc_a = point_compress(pub_point)
    k = int.from_bytes(_sha512(rs[:32] + enc_a + msg), "little") % L
    return _pt_equal(_pt_mul(s, B), _pt_add(r_pt, _pt_mul(k, pub_point)))


class EdDSASecretKey:
    __slots__ = ("seed", "scalar", "prefix", "enc_pub", "pub_point")

    def __init__(self, seed: bytes):
        self.seed = seed
        h = _sha512(seed)
        self.scalar = _clamp(h[:32])
        self.prefix = h[32:]
        self.pub_point = _pt_mul(self.scalar, B)
        self.enc_pub = point_compress(self.pub_point)

    def public_key(self) -> EdDSAPublicKey:
        return EdDSAPublicKey({_kid(self.enc_pub): self.pub_point})

    def sign(self, msg: bytes) -> EdDSASignature:
        r = int.from_bytes(_sha512(self.prefix + msg), "little") % L
        enc_r = point_compress(_pt_mul(r, B))
        k = int.from_bytes(_sha512(enc_r + self.enc_pub + msg), "little") % L
        s = (r + k * self.scalar) % L
        return EdDSASignature(
            {_kid(self.enc_pub): enc_r + int(s).to_bytes(32, "little")}
        )

    def marshal(self) -> bytes:
        return self.seed


class EdDSAConstructor(Constructor):
    def unmarshal_signature(self, data: bytes) -> EdDSASignature:
        if len(data) < _SIG_SIZE:
            raise ValueError("eddsa signature wire data truncated")
        (count,) = struct.unpack(">H", data[:2])
        if count > MAX_SIGNERS:
            raise ValueError(f"eddsa signature count {count} > {MAX_SIGNERS}")
        sigs: dict[bytes, bytes] = {}
        for i in range(count):
            off = 2 + i * _ENTRY
            entry = data[off : off + _ENTRY]
            sigs[entry[:8]] = entry[8:]
        return EdDSASignature(sigs)

    def signature_size(self) -> int:
        return _SIG_SIZE


def new_keypair(seed: int | None = None) -> tuple[EdDSASecretKey, EdDSAPublicKey]:
    if seed is not None:
        raw = hashlib.sha256(b"handel-tpu-eddsa-key:" + str(seed).encode()).digest()
    else:
        import secrets

        raw = secrets.token_bytes(32)
    sk = EdDSASecretKey(raw)
    return sk, sk.public_key()


class EdDSAScheme:
    """Scheme facade matching fake/bn254/bls12_381 (registry: "eddsa")."""

    def __init__(self):
        self.constructor = EdDSAConstructor()

    def keygen(self, i: int):
        return new_keypair(seed=i)

    def unmarshal_public(self, data: bytes) -> EdDSAPublicKey:
        point = point_decompress(data[:32])
        return EdDSAPublicKey({_kid(data[:32]): point})

    def unmarshal_secret(self, data: bytes) -> EdDSASecretKey:
        return EdDSASecretKey(data[:32])

"""Random-linear-combination (RLC) batch verification: scalars, the
bisection driver, and the host-math combined check.

The small-exponents batch test (Bellare–Garay–Rabin): instead of one
pairing check e(H(m_j), X_j) == e(S_j, B2) per candidate j, draw random
64-bit coefficients r_j (r_0 = 1) and check the single equation

    e(-S, B2) * prod_m e(H(m), X_m) == 1,
    S   = sum_j r_j * S_j           (G1 MSM over the signatures)
    X_m = sum_{j: msg_j = m} r_j * X_j   (G2 MSM per message group)

A forged batch passes only if the forgeries cancel under the random
combination — probability <= 2^-64 per attempt, and the coefficients are
drawn fresh per launch from a CSPRNG so an adversary cannot precompute
them. Honest-case cost per launch drops from 2C Miller loops + C final
exponentiations to M+1 Miller loops + 1 final exponentiation (M = number
of distinct messages) plus the two MSMs, which are plain group ops.

When the combined check fails, `bisect_verify` splits the batch and
rechecks each half with FRESH scalars (reusing scalars would let a
crafted pair of forgeries keep cancelling), recursing down to the
per-candidate oracle for singletons — so forged candidates are isolated
and attributed exactly as in per_candidate mode, at O(f·log C) extra
checks for f forgeries.

Consumers: `service.driver.HostDevice` and the host constructors use
`host_rlc_check` (native/ref group math); `models.bn254_jax.BN254Device`
supplies a device combined check (MSM kernel + fused pairing tail) and
shares `draw_scalars`/`bisect_verify`/`RlcStats`.

`per_candidate` remains required when the caller needs per-candidate
verdicts from a single launch without recheck latency (adversary-heavy
traffic where bisection would dominate), and for schemes that don't
expose the RLC seam (e.g. the test-only FakeScheme).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, NamedTuple, Sequence

BATCH_CHECK_MODES = ("per_candidate", "rlc")
SCALAR_BITS = 64


def validate_batch_check(mode: str) -> str:
    if mode not in BATCH_CHECK_MODES:
        raise ValueError(
            f"batch_check must be one of {list(BATCH_CHECK_MODES)}, got {mode!r}"
        )
    return mode


def draw_scalars(n: int, rng: random.Random | None = None) -> list[int]:
    """n fresh RLC coefficients: r_0 = 1 (free — scaling the whole equation
    by r_0^-1 shows the first candidate needs no blinding), the rest uniform
    nonzero 64-bit. Defaults to `random.SystemRandom` — the scalars are
    adversary-facing and must be unpredictable."""
    rng = rng or random.SystemRandom()
    return [1] + [rng.randrange(1, 1 << SCALAR_BITS) for _ in range(n - 1)]


@dataclass
class RlcStats:
    """Per-device RLC counters, surfaced on the device_verifier_* plane.

    rlc_launches: top-level combined checks issued (one per RLC dispatch
    with >= 2 valid candidates). bisection_ct: follow-up checks after a
    failed combined check — subset rechecks plus per-candidate oracle
    calls. bisection_depth_max: deepest recheck level reached (0 = no
    combined check has ever failed). miller_lanes / final_exp_lanes count
    the pairing work actually issued, so the smoke can assert the M+1 / 1
    contract against the 2C / C per-candidate baseline."""

    rlc_launches: int = 0
    bisection_ct: int = 0
    bisection_depth_max: int = 0
    miller_lanes: int = 0
    final_exp_lanes: int = 0


def bisect_verify(
    idxs: Sequence[int],
    combined: Callable[[list[int]], bool],
    oracle: Callable[[int], bool],
    stats: RlcStats | None = None,
) -> dict[int, bool]:
    """Verdicts for `idxs` via combined-check-then-bisect.

    `combined(subset)` runs one RLC check over the subset, drawing fresh
    scalars internally; `oracle(i)` is the per-candidate check. A passing
    combined check accepts its whole subset; a failing one splits in two
    and rechecks each half, bottoming out at the oracle — so the final
    verdict for any candidate is either "member of a passing combined
    check" (sound to 2^-64) or the oracle's own answer."""
    stats = stats if stats is not None else RlcStats()
    out: dict[int, bool] = {}

    def run(sub: list[int], depth: int) -> None:
        if depth > stats.bisection_depth_max:
            stats.bisection_depth_max = depth
        if len(sub) == 1:
            if depth:
                stats.bisection_ct += 1
            out[sub[0]] = oracle(sub[0])
            return
        if depth:
            stats.bisection_ct += 1
        else:
            stats.rlc_launches += 1
        if combined(sub):
            for i in sub:
                out[i] = True
            return
        mid = (len(sub) + 1) // 2
        run(sub[:mid], depth + 1)
        run(sub[mid:], depth + 1)

    if idxs:
        run(list(idxs), 0)
    return out


class HostRlcOps(NamedTuple):
    """Scalar-oracle group ops one scheme exposes for the host RLC check
    (affine int-tuple points, None = infinity — the native/ref calling
    convention)."""

    g1_mul_batch: Callable
    g1_sum: Callable
    g1_neg: Callable
    g2_mul_batch: Callable
    g2_sum: Callable
    g2_gen: object
    pairing_check: Callable
    hash_to_g1: Callable


def _mul_batch(mul):
    return lambda pts, ks: [mul(p, k) for p, k in zip(pts, ks)]


def _sum_with(add):
    def _sum(pts):
        acc = None
        for p in pts:
            acc = p if acc is None else add(acc, p)
        return acc

    return _sum


def bn254_host_ops() -> HostRlcOps:
    from handel_tpu import native as nat
    from handel_tpu.models.bn254 import hash_to_g1
    from handel_tpu.ops import bn254_ref as bn

    if nat.available():
        return HostRlcOps(
            g1_mul_batch=nat.g1_mul_batch,
            g1_sum=nat.g1_sum,
            g1_neg=bn.g1_neg,
            g2_mul_batch=nat.g2_mul_batch,
            g2_sum=nat.g2_sum,
            g2_gen=bn.G2_GEN,
            pairing_check=nat.pairing_check,
            hash_to_g1=hash_to_g1,
        )
    return HostRlcOps(
        g1_mul_batch=_mul_batch(bn.g1_mul),
        g1_sum=_sum_with(bn.g1_add),
        g1_neg=bn.g1_neg,
        g2_mul_batch=_mul_batch(bn.g2_mul),
        g2_sum=_sum_with(bn.g2_add),
        g2_gen=bn.G2_GEN,
        pairing_check=bn.pairing_check,
        hash_to_g1=hash_to_g1,
    )


def bls12_381_host_ops() -> HostRlcOps:
    from handel_tpu.models.bls12_381 import hash_to_g1
    from handel_tpu.ops import bls12_381_ref as bls

    return HostRlcOps(
        g1_mul_batch=_mul_batch(bls.g1_mul),
        g1_sum=_sum_with(bls.g1_add),
        g1_neg=bls.g1_neg,
        g2_mul_batch=_mul_batch(bls.g2_mul),
        g2_sum=_sum_with(bls.g2_add),
        g2_gen=bls.G2_GEN,
        pairing_check=bls.pairing_check,
        hash_to_g1=hash_to_g1,
    )


def host_ops_for(constructor) -> HostRlcOps | None:
    """The scalar-oracle ops table for a scheme constructor, or None when
    the scheme has no RLC seam (e.g. FakeScheme) — callers fall back to
    per_candidate verification."""
    mod = type(constructor).__module__
    if "bn254" in mod:
        return bn254_host_ops()
    if "bls12_381" in mod:
        return bls12_381_host_ops()
    return None


def host_rlc_check(
    ops: HostRlcOps,
    cands: Sequence[tuple[bytes, object, object]],
    rng: random.Random | None = None,
    stats: RlcStats | None = None,
) -> bool:
    """One combined check over valid candidates (msg, apk_point, sig_point):
    fresh scalars, message-grouped G2 MSM, one product-of-pairings with
    M+1 Miller loops and one shared final exponentiation."""
    rs = draw_scalars(len(cands), rng)
    S = ops.g1_sum(ops.g1_mul_batch([c[2] for c in cands], rs))
    by_msg: dict[bytes, list[int]] = {}
    for j, (msg, _, _) in enumerate(cands):
        by_msg.setdefault(msg, []).append(j)
    pairs = []
    for msg, members in by_msg.items():
        x = ops.g2_sum(
            ops.g2_mul_batch([cands[j][1] for j in members], [rs[j] for j in members])
        )
        pairs.append((ops.hash_to_g1(msg), x))
    pairs.append((ops.g1_neg(S), ops.g2_gen))
    if stats is not None:
        stats.miller_lanes += len(pairs)
        stats.final_exp_lanes += 1
    return bool(ops.pairing_check(pairs))

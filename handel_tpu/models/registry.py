"""String -> signature-scheme dispatch.

Reference: simul/lib/config.go:211-225 (`Config.NewConstructor`: "bn256",
"bn256/cf", "bn256/go"). Here the names select both keygen and the verify
path; "bn254-jax" is the device-verification scheme.
"""

from __future__ import annotations


def new_scheme(name: str, **kwargs):
    name = name.lower()
    if name in ("fake", "empty"):
        from handel_tpu.models.fake import FakeScheme

        return FakeScheme()
    if name in ("bn254", "bn256", "bn254-ref"):
        from handel_tpu.models.bn254 import BN254Scheme

        return BN254Scheme()
    if name in ("bn254-jax", "bn254-tpu", "bn256-tpu"):
        from handel_tpu.models.bn254_jax import BN254JaxScheme

        return BN254JaxScheme(**kwargs)
    if name in ("bls12-381", "bls12381"):
        from handel_tpu.models.bls12_381 import BLS12381Scheme

        return BLS12381Scheme()
    if name in ("bls12-381-jax", "bls12-381-tpu", "bls12381-jax"):
        from handel_tpu.models.bls12_381_jax import BLS12381JaxScheme

        return BLS12381JaxScheme(**kwargs)
    raise ValueError(f"unknown signature scheme: {name!r}")


SCHEMES = ("fake", "bn254", "bn254-jax", "bls12-381", "bls12-381-jax")

_DEVICE_NAMES = frozenset(
    (
        "bn254-jax",
        "bn254-tpu",
        "bn256-tpu",
        "bls12-381-jax",
        "bls12-381-tpu",
        "bls12381-jax",
    )
)


def is_device_scheme(name: str) -> bool:
    """True when `name` selects a device-verification scheme (one whose
    constructor accepts batch_size and exposes a Device class)."""
    return name.lower() in _DEVICE_NAMES

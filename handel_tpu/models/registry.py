"""String -> signature-scheme dispatch.

Reference: simul/lib/config.go:211-225 (`Config.NewConstructor`: "bn256",
"bn256/cf", "bn256/go"). Here the names select both keygen and the verify
path; the "-jax" schemes verify on device.

One table holds every alias: canonical name -> (is_device, factory). Keeping
`is_device_scheme` and `new_scheme` on the same table means a new alias
can't silently miss the batch-size plumbing in sim/node.py.
"""

from __future__ import annotations


def _fake(**kw):
    from handel_tpu.models.fake import FakeScheme

    return FakeScheme()


def _bn254(**kw):
    from handel_tpu.models.bn254 import BN254Scheme

    return BN254Scheme()


def _bn254_jax(**kw):
    from handel_tpu.models.bn254_jax import BN254JaxScheme

    return BN254JaxScheme(**kw)


def _eddsa(**kw):
    from handel_tpu.models.eddsa import EdDSAScheme

    return EdDSAScheme()


def _bls12_381(**kw):
    from handel_tpu.models.bls12_381 import BLS12381Scheme

    return BLS12381Scheme()


def _bls12_381_jax(**kw):
    from handel_tpu.models.bls12_381_jax import BLS12381JaxScheme

    return BLS12381JaxScheme(**kw)


# alias -> (is_device_scheme, factory)
_TABLE = {
    "fake": (False, _fake),
    "empty": (False, _fake),
    "bn254": (False, _bn254),
    "bn256": (False, _bn254),
    "bn254-ref": (False, _bn254),
    "bn254-jax": (True, _bn254_jax),
    "bn254-tpu": (True, _bn254_jax),
    "bn256-tpu": (True, _bn254_jax),
    "eddsa": (False, _eddsa),
    "ed25519": (False, _eddsa),
    "bls12-381": (False, _bls12_381),
    "bls12381": (False, _bls12_381),
    "bls12-381-jax": (True, _bls12_381_jax),
    "bls12-381-tpu": (True, _bls12_381_jax),
    "bls12381-jax": (True, _bls12_381_jax),
}

SCHEMES = ("fake", "bn254", "bn254-jax", "eddsa", "bls12-381", "bls12-381-jax")


def new_scheme(name: str, **kwargs):
    entry = _TABLE.get(name.lower())
    if entry is None:
        raise ValueError(f"unknown signature scheme: {name!r}")
    return entry[1](**kwargs)


def is_device_scheme(name: str) -> bool:
    """True when `name` selects a device-verification scheme (one whose
    constructor accepts batch_size and exposes a Device class)."""
    entry = _TABLE.get(name.lower())
    return bool(entry and entry[0])

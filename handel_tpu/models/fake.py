"""Fake signature scheme for protocol tests.

Reference: util_test.go:15-99 — `fakePublic/fakeSig/fakeSecret/fakeCons`, where
"verification" is a boolean AND. Makes protocol tests fast and deterministic
(SURVEY.md §4 tier 1-2); the scheme carries just enough state to catch
wiring bugs (an invalid sig stays invalid through any combine).
"""

from __future__ import annotations

import struct

from handel_tpu.core.crypto import Constructor
from handel_tpu.core.identity import ArrayRegistry, Identity

_SIG_SIZE = 8


class FakeSignature:
    __slots__ = ("valid",)

    def __init__(self, valid: bool = True):
        self.valid = valid

    def marshal(self) -> bytes:
        return struct.pack(">Q", 1 if self.valid else 0)

    def combine(self, other: "FakeSignature") -> "FakeSignature":
        return FakeSignature(self.valid and other.valid)


class FakePublic:
    __slots__ = ("valid",)

    def __init__(self, valid: bool = True):
        self.valid = valid

    def marshal(self) -> bytes:
        return struct.pack(">Q", 1 if self.valid else 0)

    def verify(self, msg: bytes, sig: FakeSignature) -> bool:
        return self.valid and sig.valid

    def combine(self, other: "FakePublic") -> "FakePublic":
        return FakePublic(self.valid and other.valid)


class FakeSecret:
    __slots__ = ("id",)

    def __init__(self, id: int = 0):
        self.id = id

    def sign(self, msg: bytes) -> FakeSignature:
        return FakeSignature(True)

    def marshal(self) -> bytes:
        return struct.pack(">Q", self.id)


class FakeConstructor(Constructor):
    def unmarshal_signature(self, data: bytes) -> FakeSignature:
        (v,) = struct.unpack(">Q", data[:_SIG_SIZE])
        return FakeSignature(v == 1)

    def signature_size(self) -> int:
        return _SIG_SIZE


def fake_registry(n: int) -> ArrayRegistry:
    """n identities with fake keys, addresses 'fake-<i>' (util_test.go FakeRegistry)."""
    return ArrayRegistry(
        [Identity(i, f"fake-{i}", FakePublic(True)) for i in range(n)]
    )


class FakeScheme:
    """Scheme facade with simulation marshal support (simul/lib/crypto.go's
    empty/fake constructors for network-only tests)."""

    def __init__(self):
        self.constructor = FakeConstructor()

    def keygen(self, i: int):
        return FakeSecret(i), FakePublic(True)

    def unmarshal_public(self, data: bytes) -> FakePublic:
        (v,) = struct.unpack(">Q", data[:8])
        return FakePublic(v == 1)

    def unmarshal_secret(self, data: bytes) -> FakeSecret:
        (i,) = struct.unpack(">Q", data[:8])
        return FakeSecret(i)

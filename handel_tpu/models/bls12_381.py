"""BLS signatures over BLS12-381, pure-Python backend.

The second curve behind the Constructor interface (the slot the reference's
curve registry dispatches on, simul/lib/config.go:211-225). Same key
orientation as models/bn254.py: keys in G2, signatures in G1,
verify e(H(m), X) == e(S, B2) as one product check, hash-to-G1 by the
known-scalar construction (bn256/go/bn256.go:206-218 analogue).

Wire formats: uncompressed big-endian coordinates — G1 = 96 bytes (x||y),
G2 = 192 bytes (x1||x0||y1||y0, imaginary-first like the bn254 scheme),
zero bytes = point at infinity.
"""

from __future__ import annotations

import hashlib
import secrets
import time

from handel_tpu.core import report
from handel_tpu.core.crypto import Constructor
from handel_tpu.ops import bls12_381_ref as bls

_COORD = 48
_G1_SIZE = 2 * _COORD
_G2_SIZE = 4 * _COORD


def _itob(x: int) -> bytes:
    return int(x).to_bytes(_COORD, "big")


def _btoi(b: bytes) -> int:
    x = int.from_bytes(b, "big")
    if x >= bls.P:
        raise ValueError("coordinate >= field modulus")
    return x


def marshal_g1(pt) -> bytes:
    if pt is None:
        return b"\x00" * _G1_SIZE
    return _itob(pt[0]) + _itob(pt[1])


def unmarshal_g1(data: bytes):
    if len(data) != _G1_SIZE:
        raise ValueError(f"G1 point must be {_G1_SIZE} bytes")
    if data == b"\x00" * _G1_SIZE:
        return None
    pt = (_btoi(data[:_COORD]), _btoi(data[_COORD:]))
    if not bls.g1_is_valid(pt):
        raise ValueError("G1 point not on curve / wrong subgroup")
    return pt


def marshal_g2(pt) -> bytes:
    if pt is None:
        return b"\x00" * _G2_SIZE
    (x0, x1), (y0, y1) = pt
    return _itob(x1) + _itob(x0) + _itob(y1) + _itob(y0)


def unmarshal_g2(data: bytes):
    if len(data) != _G2_SIZE:
        raise ValueError(f"G2 point must be {_G2_SIZE} bytes")
    if data == b"\x00" * _G2_SIZE:
        return None
    x1, x0, y1, y0 = (_btoi(data[i : i + _COORD]) for i in range(0, _G2_SIZE, _COORD))
    pt = ((x0, x1), (y0, y1))
    t0 = time.perf_counter()
    ok = bls.g2_is_valid(pt)
    report.SUBGROUP_CHECKS.add_g2((time.perf_counter() - t0) * 1000.0)
    if not ok:
        raise ValueError("G2 point not on curve / wrong subgroup")
    return pt


def hash_to_g1(msg: bytes):
    k = int.from_bytes(hashlib.sha256(b"bls12-381:" + msg).digest(), "big") % bls.R
    return bls.g1_mul(bls.G1_GEN, k or 1)


class BLS12381Signature:
    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point

    def marshal(self) -> bytes:
        return marshal_g1(self.point)

    def combine(self, other: "BLS12381Signature") -> "BLS12381Signature":
        return BLS12381Signature(bls.g1_add(self.point, other.point))

    def __eq__(self, other):
        return isinstance(other, BLS12381Signature) and self.point == other.point


class BLS12381PublicKey:
    __slots__ = ("point",)

    def __init__(self, point):
        self.point = point

    def marshal(self) -> bytes:
        return marshal_g2(self.point)

    def verify(self, msg: bytes, sig: BLS12381Signature) -> bool:
        if sig.point is None or self.point is None:
            return False
        hm = hash_to_g1(msg)
        return bls.pairing_check(
            [(hm, self.point), (bls.g1_neg(sig.point), bls.G2_GEN)]
        )

    def combine(self, other: "BLS12381PublicKey") -> "BLS12381PublicKey":
        return BLS12381PublicKey(bls.g2_add(self.point, other.point))

    def __eq__(self, other):
        return isinstance(other, BLS12381PublicKey) and self.point == other.point


class BLS12381SecretKey:
    __slots__ = ("scalar",)

    def __init__(self, scalar: int):
        self.scalar = scalar % bls.R

    def public_key(self) -> BLS12381PublicKey:
        return BLS12381PublicKey(bls.g2_mul(bls.G2_GEN, self.scalar))

    def sign(self, msg: bytes) -> BLS12381Signature:
        return BLS12381Signature(bls.g1_mul(hash_to_g1(msg), self.scalar))

    def marshal(self) -> bytes:
        return int(self.scalar).to_bytes(32, "big")

    @classmethod
    def unmarshal(cls, data: bytes) -> "BLS12381SecretKey":
        return cls(int.from_bytes(data, "big"))


def new_keypair(seed: int | None = None):
    if seed is not None:
        scalar = (
            int.from_bytes(
                hashlib.sha256(b"handel-tpu-bls-key:" + str(seed).encode()).digest(),
                "big",
            )
            % bls.R
        )
    else:
        scalar = secrets.randbelow(bls.R - 1) + 1
    sk = BLS12381SecretKey(scalar or 1)
    return sk, sk.public_key()


class BLS12381Constructor(Constructor):
    def unmarshal_signature(self, data: bytes) -> BLS12381Signature:
        return BLS12381Signature(unmarshal_g1(data[:_G1_SIZE]))

    def signature_size(self) -> int:
        return _G1_SIZE


class BLS12381Scheme:
    """Keygen facade with simulation marshal support."""

    def __init__(self):
        self.constructor = BLS12381Constructor()

    def keygen(self, i: int):
        return new_keypair(seed=i)

    def unmarshal_public(self, data: bytes) -> BLS12381PublicKey:
        return BLS12381PublicKey(unmarshal_g2(data))

    def unmarshal_secret(self, data: bytes) -> BLS12381SecretKey:
        return BLS12381SecretKey.unmarshal(data)

"""handel-tpu: TPU-native Byzantine multi-signature aggregation framework.

A from-scratch rebuild of the capabilities of the Handel reference implementation
(isabella232/handel, Go): the binomial-tree aggregation protocol, pluggable
BLS signature schemes, pluggable transports, fault injection, and a full
simulation/benchmark harness — with the signature verification hot loop
(BN254/BLS12-381 pairings) implemented as batched JAX kernels for TPU.

Layer map (mirrors reference SURVEY.md §1, redesigned TPU-first):

  L5  sim/        simulation & benchmark harness (platforms, sync, monitor)
  L4  baselines/  gossip comparison protocols
  L3  core/       aggregation runtime (state machine, store, processing)
  L2a models/     signature schemes (bn254 python/c++/jax, bls12-381, fake)
      ops/        JAX field/curve/pairing kernels (the TPU compute path)
      parallel/   device mesh, sharded multi-pairing, batch verifier service
  L2b network/    wire encodings + UDP/TCP/TLS-session transports
      native/     C++ host arithmetic (keygen/sign/aggregate fast path)
  L1  core interfaces (crypto.py, net.py, bitset.py, identity.py)
"""

__version__ = "0.1.0"

from handel_tpu.core.bitset import BitSet
from handel_tpu.core.crypto import (
    Constructor,
    MultiSignature,
    PublicKey,
    SecretKey,
    Signature,
    verify_multisignature,
)
from handel_tpu.core.identity import Identity, Registry, ArrayRegistry
from handel_tpu.core.config import Config, default_config
from handel_tpu.core.handel import Handel

__all__ = [
    "BitSet",
    "Constructor",
    "MultiSignature",
    "PublicKey",
    "SecretKey",
    "Signature",
    "verify_multisignature",
    "Identity",
    "Registry",
    "ArrayRegistry",
    "Config",
    "default_config",
    "Handel",
]

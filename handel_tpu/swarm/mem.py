"""Memory accounting for the swarm runtime.

Two measures, deliberately both reported (ISSUE 11 deliverable is a
bytes-per-identity curve, and either one alone lies):

- `process_rss_bytes()` — the process's resident set from /proc (Linux) or
  the `resource` peak as fallback. Honest about everything (interpreter,
  numpy, allocator slack) but shared across all co-resident vnodes, so
  per-identity RSS *falls* as density rises.
- `deep_size(obj)` — a `sys.getsizeof` walk over one vnode's object graph,
  stopping at objects shared swarm-wide (the registry, identities, pubkeys,
  config singletons) via the caller's `shared` set. This is the marginal
  per-identity footprint the O(active levels) claim is about.
"""

from __future__ import annotations

import sys
from typing import Iterable

import numpy as np


def process_rss_bytes() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    import resource

    # ru_maxrss is KB on Linux (peak, not current — fallback only)
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def deep_size(obj, shared: Iterable[object] = (), max_objects: int = 500_000) -> int:
    """Recursive getsizeof over `obj`'s reachable graph.

    `shared` objects (and everything below them) are excluded — they are
    amortized across the swarm, not part of one vnode's marginal cost.
    Bounded by `max_objects` so a cycle of unexpected shape degrades to an
    undercount, never a hang.
    """
    seen: set[int] = {id(s) for s in shared}
    total = 0
    stack = [obj]
    visited = 0
    while stack and visited < max_objects:
        o = stack.pop()
        oid = id(o)
        if oid in seen:
            continue
        seen.add(oid)
        visited += 1
        try:
            total += sys.getsizeof(o)
        except TypeError:
            continue
        if isinstance(o, np.ndarray):
            total += o.nbytes
            continue
        if isinstance(o, (str, bytes, bytearray, int, float, bool)):
            continue
        if isinstance(o, dict):
            stack.extend(o.keys())
            stack.extend(o.values())
        elif isinstance(o, (list, tuple, set, frozenset)):
            stack.extend(o)
        if hasattr(o, "__dict__"):
            stack.append(o.__dict__)
        if hasattr(o, "__slots__"):
            for s in o.__slots__:
                v = getattr(o, s, None)
                if v is not None:
                    stack.append(v)
    return total

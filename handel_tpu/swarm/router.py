"""In-memory packet router for co-resident virtual nodes, UDP across hosts.

ISSUE 11 tentpole: at swarm scale most traffic is *local* — contiguous ID
blocks live in one process, and Handel's low levels (the bulk of packet
volume: level L has 2^(L-1) candidates, so half of all candidate slots sit
in the two lowest levels) stay entirely inside the block. The router
short-circuits those deliveries: one immutable `Packet` object is handed to
every co-resident recipient via `loop.call_soon` — no encode, no decode, no
socket. Only packets whose recipient lives in another process take the wire,
as one datagram per recipient prefixed with a 4-byte recipient id (every
process's vnodes share ONE socket, so the prefix is the demux key the
per-node UDP transport got from its port).

`Packet` instances are safe to share: `Handel.new_packet` only reads the
fields and unmarshals fresh objects from the payload bytes (core/net.py,
core/handel.py) — nothing mutates a delivered packet.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Sequence

from handel_tpu.core.identity import Identity
from handel_tpu.core.net import Listener, Packet

# cross-process frame: recipient vnode id, then the normal Packet encoding
_FRAME = struct.Struct(">I")


class _SwarmProto(asyncio.DatagramProtocol):
    def __init__(self, router: "SwarmRouter"):
        self.router = router

    def connection_made(self, transport):
        self.router._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        self.router._on_datagram(data)


class SwarmRouter:
    """One per process: local short-circuit + shared-socket UDP fallback.

    `owner_of(node_id)` maps a global id to the process that hosts it —
    contiguous blocks of `block` ids per process index, the same split the
    driver uses to build vnodes — and `ports[pindex]` is that process's
    shared UDP port on localhost (multi-host runs would carry (host, port)
    pairs; the frame format doesn't change).
    """

    def __init__(
        self,
        block: int,
        ports: Sequence[int] | None = None,
        host: str = "127.0.0.1",
    ):
        self.block = max(1, block)
        self.ports = list(ports or [])
        self.host = host
        self.local: dict[int, Listener] = {}
        self._transport = None
        # telemetry plane
        self.local_delivered = 0
        self.udp_sent = 0
        self.udp_rcvd = 0
        self.udp_bytes_sent = 0
        self.udp_rcvd_bad = 0  # truncated/undecodable frames (dropped)
        self.unknown_recipient = 0

    # -- lifecycle ---------------------------------------------------------

    async def open(self, port: int) -> None:
        """Bind the process's shared socket. Single-process swarms (every
        recipient local) can skip this entirely."""
        loop = asyncio.get_running_loop()
        await loop.create_datagram_endpoint(
            lambda: _SwarmProto(self), local_addr=("0.0.0.0", port)
        )

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    # -- registration ------------------------------------------------------

    def register(self, node_id: int, listener: Listener) -> None:
        self.local[int(node_id)] = listener

    def owner_of(self, node_id: int) -> int:
        return node_id // self.block

    # -- delivery ----------------------------------------------------------

    def route(self, identities: Sequence[Identity], packet: Packet) -> None:
        loop = asyncio.get_running_loop()
        wire = None
        for ident in identities:
            nid = ident.id
            lst = self.local.get(nid)
            if lst is not None:
                # shared-object fast path: same Packet for every local
                # recipient, delivered on the next loop turn like a datagram
                self.local_delivered += 1
                loop.call_soon(lst.new_packet, packet)
                continue
            pindex = self.owner_of(nid)
            if pindex >= len(self.ports) or self._transport is None:
                # a recipient nobody hosts (mid-teardown, bad registry) is
                # dropped and counted, never an exception on the send path
                self.unknown_recipient += 1
                continue
            if wire is None:
                wire = packet.encode()  # encode once per route() call
            self.udp_sent += 1
            self.udp_bytes_sent += _FRAME.size + len(wire)
            self._transport.sendto(
                _FRAME.pack(nid) + wire, (self.host, self.ports[pindex])
            )

    def _on_datagram(self, data: bytes) -> None:
        if len(data) <= _FRAME.size:
            self.udp_rcvd_bad += 1
            return
        (nid,) = _FRAME.unpack_from(data)
        lst = self.local.get(nid)
        if lst is None:
            self.unknown_recipient += 1
            return
        try:
            pkt = Packet.decode(data[_FRAME.size:])
        except ValueError:
            self.udp_rcvd_bad += 1
            return
        self.udp_rcvd += 1
        lst.new_packet(pkt)

    # -- reporting ---------------------------------------------------------

    def values(self) -> dict[str, float]:
        return {
            "swarmLocalDelivered": float(self.local_delivered),
            "swarmUdpSent": float(self.udp_sent),
            "swarmUdpRcvd": float(self.udp_rcvd),
            "swarmUdpBytesSent": float(self.udp_bytes_sent),
            "swarmUdpRcvdBad": float(self.udp_rcvd_bad),
            "swarmUnknownRecipient": float(self.unknown_recipient),
        }


class SwarmNetwork:
    """Per-vnode `Network` facade over the shared router (core/net.py
    contract: Handel calls `register_listener(self)` with no id, so the
    facade carries it)."""

    __slots__ = ("router", "node_id")

    def __init__(self, router: SwarmRouter, node_id: int):
        self.router = router
        self.node_id = node_id

    def send(self, identities: Sequence[Identity], packet: Packet) -> None:
        self.router.route(identities, packet)

    def register_listener(self, listener: Listener) -> None:
        self.router.register(self.node_id, listener)

"""Registry paging: device pubkey residency in level-sized chunks.

ISSUE 11 tentpole: a 1M-identity registry is ~64 MB of G2 points (BN254
uncompressed) — too big to re-stage per launch, and wasteful to pin whole
when a verify batch only ever touches the chunks its bitsets cover (one
Handel level is one contiguous ID range, so touched chunks cluster). The
pager wraps a device engine and tracks an LRU set of resident chunks of
2^chunk_bits identities each: before a launch it derives the touched chunk
set from the request bitsets' set *words* (O(set words), not O(bits)),
commits the missing ones, and evicts over budget.

With the host schemes used at swarm scale there is no physical transfer —
`commit` is accounting plus an optional `on_commit(chunk_lo, chunk_hi)`
hook; a device scheme (models/bn254_jax.py BN254Device) plugs its pubkey
staging into exactly that hook, and the hit/commit/evict counters are the
same either way. That keeps the paging POLICY (what is resident when) a
tested, measured artifact now, independent of the staging mechanism.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

_WORD_BITS = 64


class RegistryPager:
    """LRU residency tracker over identity chunks of 2^chunk_bits."""

    def __init__(self, chunk_bits: int = 12, budget_chunks: int = 64,
                 on_commit=None):
        if chunk_bits < 6:
            raise ValueError("chunk_bits must be >= 6 (one bitset word)")
        self.chunk_bits = chunk_bits
        self.budget = max(1, budget_chunks)
        self.on_commit = on_commit
        self._resident: OrderedDict[int, None] = OrderedDict()
        # telemetry plane
        self.pages_committed = 0
        self.page_hits = 0
        self.page_evictions = 0

    def touched_chunks(self, bitset) -> set[int]:
        """Chunk ids covered by a bitset's set bits, via its word array."""
        words = np.flatnonzero(bitset.words())
        shift = self.chunk_bits - 6  # 64 bits per word
        return set((words >> shift).tolist())

    def ensure(self, chunks) -> None:
        for c in sorted(chunks):
            if c in self._resident:
                self.page_hits += 1
                self._resident.move_to_end(c)
                continue
            self.pages_committed += 1
            if self.on_commit is not None:
                lo = c << self.chunk_bits
                self.on_commit(lo, lo + (1 << self.chunk_bits))
            self._resident[c] = None
            while len(self._resident) > self.budget:
                self._resident.popitem(last=False)
                self.page_evictions += 1

    def resident_chunks(self) -> int:
        return len(self._resident)

    def values(self) -> dict[str, float]:
        return {
            "pagesCommitted": float(self.pages_committed),
            "pageHits": float(self.page_hits),
            "pageEvictions": float(self.page_evictions),
            "pagesResident": float(len(self._resident)),
        }

    def gauge_keys(self) -> set[str]:
        return {"pagesResident"}


class PagedDevice:
    """Device-contract wrapper running the pager before every launch.

    Wraps anything with `dispatch_multi`/`fetch`/`batch_size` (HostDevice,
    BN254Device): items are (msg, pubkeys, bitset, sig); the union of the
    batch's touched chunks is ensured resident, then the launch proceeds on
    the wrapped engine unchanged.
    """

    def __init__(self, engine, pager: RegistryPager):
        self.engine = engine
        self.pager = pager
        self.batch_size = engine.batch_size

    def dispatch_multi(self, items):
        touched: set[int] = set()
        for _, _, bs, _ in items:
            touched |= self.pager.touched_chunks(bs)
        self.pager.ensure(touched)
        return self.engine.dispatch_multi(items)

    def fetch(self, handle):
        return self.engine.fetch(handle)

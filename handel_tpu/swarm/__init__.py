"""Virtual-node swarm runtime (ISSUE 11 tentpole).

One process multiplexes thousands of Handel identities: cooperative timers
on a shared wheel (core/timeout.py TimerWheel), in-memory packet delivery
between co-resident vnodes with shared-socket UDP across processes
(swarm/router.py), all verification through ONE BatchVerifierService per
process — one session per committee MEMBER, dedup scoped per committee
(swarm/vnode.py) — windowed signature stores retiring completed levels
(core/store.py), and registry residency paged in level-sized chunks
(swarm/pager.py). Entry points: `sim swarm` (sim/__main__.py) and
`run_swarm` (swarm/driver.py).
"""

from handel_tpu.swarm.driver import SwarmHost, run_swarm
from handel_tpu.swarm.pager import PagedDevice, RegistryPager
from handel_tpu.swarm.router import SwarmNetwork, SwarmRouter
from handel_tpu.swarm.vnode import SWARM_DEDUP_SCOPE, VirtualNode, build_vnode

__all__ = [
    "SWARM_DEDUP_SCOPE",
    "PagedDevice",
    "RegistryPager",
    "SwarmHost",
    "SwarmNetwork",
    "SwarmRouter",
    "VirtualNode",
    "build_vnode",
    "run_swarm",
]

"""Virtual node: one Handel identity on the shared swarm event loop.

ISSUE 11 tentpole: the per-node cost model is inverted versus the service
plane (handel_tpu/service/). A service session is one COMMITTEE sharing the
verify plane; a swarm vnode is one committee MEMBER — the session id is the
member's global id, so fairness/admission isolate members, while the dedup
verdict cache uses one shared `dedup_scope` for the whole committee (every
member sees the same winning aggregates; 65k separate scopes would re-verify
identical bytes 65k times — parallel/batch_verifier.py `verify`).

What a vnode deliberately does NOT own:

- no asyncio timer tasks — level starts ride `WheelTimeout` and the gossip
  round is a `TimerWheel` periodic callback (core/timeout.py), so timer
  state is O(1) per vnode on ONE wheel task;
- no per-node Random — `Config.rand`'s default is a full Mersenne state
  (~2.5 KB); with shuffling disabled it is never drawn from, so every vnode
  shares one;
- no peer scorer — the swarm models honest committees; a scorer dict per
  vnode is per-peer state the memory budget can't carry;
- no candidate-list copies — `disable_shuffling=True` keeps the
  partitioner's O(1) `RegistrySlice` views (core/handel.py create_levels);
- no per-level eager bitsets — `WindowedSignatureStore` retires completed
  levels (core/store.py).
"""

from __future__ import annotations

import time

from handel_tpu.core.config import Config
from handel_tpu.core.handel import Handel
from handel_tpu.core.store import WindowedSignatureStore
from handel_tpu.core.timeout import TimerWheel, WheelTimeout
from handel_tpu.swarm.router import SwarmNetwork, SwarmRouter

#: shared committee-wide dedup scope (one committee per swarm run)
SWARM_DEDUP_SCOPE = "swarm"


class VirtualNode:
    """One Handel instance plus its swarm wiring and completion stamp."""

    __slots__ = ("id", "handel", "started_at", "done_ts", "_gossip")

    def __init__(self, ident, handel: Handel):
        self.id = ident.id
        self.handel = handel
        self.started_at = 0.0
        self.done_ts = 0.0  # monotonic stamp of first observed threshold
        self._gossip = None  # wheel handle for the periodic update

    def start(self, wheel: TimerWheel, phase_s: float) -> None:
        self.started_at = time.monotonic()
        self.handel.start(periodic=False)
        self._gossip = wheel.schedule_periodic(
            self.handel.c.update_period, self.handel.periodic_update,
            phase_s=phase_s,
        )

    def stop(self) -> None:
        if self._gossip is not None:
            self._gossip.cancel()
            self._gossip = None
        self.handel.stop()

    @property
    def reached_threshold(self) -> bool:
        return self.handel.best is not None

    def time_to_threshold(self) -> float:
        """Seconds from start to the driver's first observation of this
        vnode's threshold signature (scan-period granularity; the trace's
        `threshold_reached` instants carry the exact stamps)."""
        if not self.done_ts:
            return 0.0
        return self.done_ts - self.started_at


def build_vnode(
    ident,
    secret,
    registry,
    constructor,
    msg: bytes,
    router: SwarmRouter,
    wheel: TimerWheel,
    verifier_service,
    *,
    threshold: int,
    update_period: float,
    level_timeout: float,
    shared_rand,
    fast_path: int = 3,
    batch_size: int = 64,
    max_pending: int = 256,
    recorder=None,
    logger=None,
) -> VirtualNode:
    """Wire one identity into the swarm runtime (module docstring for why
    each knob is what it is)."""
    cfg = Config(
        contributions=threshold,
        update_period=update_period,
        level_timeout=level_timeout,
        fast_path=fast_path,
        disable_shuffling=True,
        penalize_peers=False,
        rand=shared_rand,
        new_store=WindowedSignatureStore,
        new_timeout=WheelTimeout.factory(wheel, level_timeout),
        session=str(ident.id),
        verifier=verifier_service.session_verifier(
            str(ident.id), dedup_scope=SWARM_DEDUP_SCOPE
        ),
        batch_size=batch_size,
        max_pending=max_pending,
        recorder=recorder,
    )
    if logger is not None:
        cfg.logger = logger
    net = SwarmNetwork(router, ident.id)
    own_sig = secret.sign(msg)
    h = Handel(net, registry, ident, constructor, msg, own_sig, cfg)
    return VirtualNode(ident, h)

"""Swarm driver: thousands of virtual nodes per process, one committee.

ISSUE 11 tentpole, the orchestration layer. `SwarmHost` owns one process's
contiguous ID block: a shared fake registry, ONE `TimerWheel`, ONE
`SwarmRouter`, ONE `BatchVerifierService` over a paged host device, and a
`VirtualNode` per local identity. `run_swarm` is the `sim swarm` entry —
processes = 1 runs the whole committee inline (tests, smoke), otherwise M
worker processes (swarm/worker.py) each run their block behind a UDP sync
barrier and the parent merges their summaries, traces and rollups into
`<workdir>/swarm_summary.json`.

Completion is observed, not awaited: a per-vnode `final_signatures.get()`
would be one more task per vnode, so a single wheel callback scans the
block every `SCAN_PERIOD_S` and stamps first-threshold times at scan
granularity (the trace's `threshold_reached` instants carry exact stamps
for the critical-path report).
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import sys
import time

from handel_tpu.core.config import (
    DEFAULT_CONTRIBUTIONS_PERC,
    percentage_to_contributions,
)
from handel_tpu.core.identity import ArrayRegistry, Identity
from handel_tpu.core.timeout import TimerWheel
from handel_tpu.core.trace import FlightRecorder
from handel_tpu.parallel.batch_verifier import BatchVerifierService
from handel_tpu.service.driver import HostDevice, _split
from handel_tpu.swarm.mem import deep_size, process_rss_bytes
from handel_tpu.swarm.pager import PagedDevice, RegistryPager
from handel_tpu.swarm.router import SwarmRouter
from handel_tpu.swarm.vnode import VirtualNode, build_vnode

SCAN_PERIOD_S = 0.25
MEM_SAMPLE_VNODES = 16


def fake_committee(n: int):
    """One shared registry + per-identity secrets for the whole committee.

    Identity/pubkey objects are built ONCE and shared by every co-resident
    vnode (the registry is most of what `deep_size` excludes as shared)."""
    from handel_tpu.models.fake import FakePublic, FakeSecret

    idents = [Identity(i, f"swarm-{i}", FakePublic(True)) for i in range(n)]
    secrets = [FakeSecret(i) for i in range(n)]
    return ArrayRegistry(idents), secrets


class SwarmHost:
    """One process's share of the committee: vnodes for ids [lo, hi)."""

    def __init__(
        self,
        total: int,
        lo: int,
        hi: int,
        *,
        threshold: int = 0,
        msg: bytes = b"swarm",
        update_period: float = 2.0,
        level_timeout: float = 0.050,
        fast_path: int = 3,
        tick_s: float = 0.010,
        batch_size: int = 64,
        max_pending: int = 256,
        chunk_bits: int = 12,
        page_budget: int = 64,
        block: int = 0,
        ports=None,
        proc_index: int = 0,
        trace: bool = False,
        trace_capacity: int = 1 << 16,
        rollup_top_k: int = 8,
    ):
        self.total = total
        self.lo, self.hi = lo, hi
        self.msg = msg
        self.update_period = update_period
        self.fast_path = fast_path
        self._level_timeout = level_timeout
        self._max_pending = max_pending
        self.proc_index = proc_index
        self.ports = list(ports or [])
        self.threshold = threshold or percentage_to_contributions(
            DEFAULT_CONTRIBUTIONS_PERC, total
        )

        from handel_tpu.models.fake import FakeConstructor

        self.registry, self._secrets = fake_committee(total)
        self.registry.public_keys()  # build the shared cache once, up front
        self.constructor = FakeConstructor()
        self.wheel = TimerWheel(tick_s=tick_s)
        self.router = SwarmRouter(block or total, ports=self.ports)
        self.pager = RegistryPager(
            chunk_bits=chunk_bits, budget_chunks=page_budget
        )
        self.device = PagedDevice(
            HostDevice(self.constructor, batch_size=batch_size), self.pager
        )
        self.recorder = (
            FlightRecorder(capacity=trace_capacity, pid=proc_index)
            if trace
            else None
        )
        self.service = BatchVerifierService(
            self.device, recorder=self.recorder
        )
        # one Mersenne state for the whole block (vnode.py: with shuffling
        # disabled nothing draws from it, and 65k defaults would be ~160 MB)
        self._rand = random.Random(proc_index)
        self.vnodes: list[VirtualNode] = []
        self._all_done = asyncio.Event()
        self._completed = 0
        self._wall_s = 0.0
        self._scan_handle = None
        self.host_rollup = self._build_host_rollup(rollup_top_k)

    def _build_host_rollup(self, top_k: int):
        """O(key-union) digest over this block's O(N) vnode surfaces
        (obs/rollup.py): the master sees one bounded digest per process,
        never a reporter row per identity. The local DetectorBank rides
        the _scan cadence so the digest's top-K carries real z-scores."""
        from handel_tpu.obs.detect import counter_rate
        from handel_tpu.obs.rollup import HostRollup

        hr = HostRollup(f"proc{self.proc_index}", top_k=top_k)

        def vnode_fold():
            gk = (
                frozenset(self.vnodes[0].handel.gauge_keys())
                if self.vnodes else frozenset()
            )
            return ((v.handel.values(), gk) for v in self.vnodes)

        hr.attach_fold("swarm", vnode_fold)
        hr.attach_reporter("router", self.router)
        hr.attach_reporter("wheel", self.wheel)
        hr.attach_reporter("pager", self.pager)
        hr.attach_fold("service", lambda: [({
            "launchesCt": float(self.service.launches),
            "candidatesCt": float(self.service.candidates),
            "dedupHitsCt": float(self.service.cache.hits),
            "completedSize": float(self._completed),
        }, frozenset({"completedSize"}))])
        if self.recorder is not None:
            hr.set_trace(lambda: self.recorder.export()["traceEvents"])
        hr.watch("swarm-completed", lambda: float(self._completed))
        hr.watch("swarm-udp-rate", counter_rate(
            lambda: self.router.values().get("swarmUdpSent")
        ))
        hr.watch("swarm-launch-rate", counter_rate(
            lambda: float(self.service.launches)
        ))
        return hr

    # -- build / lifecycle -------------------------------------------------

    def build(self) -> None:
        """Instantiate the block's vnodes (registers their listeners — call
        before the start barrier so early packets find a recipient)."""
        for nid in range(self.lo, self.hi):
            self.vnodes.append(
                build_vnode(
                    self.registry.identity(nid),
                    self._secrets[nid],
                    self.registry,
                    self.constructor,
                    self.msg,
                    self.router,
                    self.wheel,
                    self.service,
                    threshold=self.threshold,
                    update_period=self.update_period,
                    level_timeout=self._level_timeout,
                    fast_path=self.fast_path,
                    shared_rand=self._rand,
                    batch_size=self.device.batch_size,
                    max_pending=self._max_pending,
                    recorder=self.recorder,
                )
            )

    async def run(self, timeout: float = 120.0, *, teardown: bool = True) -> dict:
        """Start everything, wait until every local vnode holds a threshold
        signature (or the deadline), tear down, and return the summary.

        Workers pass teardown=False: a finished block must keep its router,
        wheel, and vnodes serving until EVERY block is done (the END
        barrier), or other blocks' unfinished vnodes lose their only source
        of this block's contributions mid-wave."""
        t0 = time.perf_counter()
        if len(self.ports) > 1 and self.router._transport is None:
            # the worker binds before the start barrier; this path is for
            # hosts driven directly (tests) that skipped that step
            await self.router.open(self.ports[self.proc_index])
        if not self.vnodes:
            self.build()
        self.wheel.start()
        n = len(self.vnodes)
        stagger = min(self.update_period, 1.0)
        for i, v in enumerate(self.vnodes):
            # phase-stagger the gossip rounds so a block's periodic burst
            # spreads over many wheel ticks — but cap the spread: with the
            # sparse-gossip default period the stagger would otherwise delay
            # the last vnode's START (and the whole wave) by seconds
            v.start(self.wheel, phase_s=(i / n) * stagger)
        self._scan_handle = self.wheel.schedule_periodic(
            SCAN_PERIOD_S, self._scan
        )
        try:
            await asyncio.wait_for(self._all_done.wait(), timeout)
        except asyncio.TimeoutError:
            pass  # partial completion is a reportable outcome, not a crash
        self._wall_s = time.perf_counter() - t0
        self._scan()  # final stamp pass before teardown
        if teardown:
            self.stop()
        return self.summary()

    def _scan(self) -> None:
        done = 0
        now = time.monotonic()
        for v in self.vnodes:
            if v.done_ts:
                done += 1
            elif v.reached_threshold:
                v.done_ts = now
                done += 1
        self._completed = done
        self.host_rollup.tick()
        if done == len(self.vnodes):
            self._all_done.set()

    def stop(self) -> None:
        if self._scan_handle is not None:
            self._scan_handle.cancel()
        for v in self.vnodes:
            v.stop()
        self.wheel.stop()
        self.service.stop()
        self.router.close()

    # -- reporting ---------------------------------------------------------

    def _ttt(self) -> list[float]:
        return sorted(
            v.time_to_threshold() for v in self.vnodes if v.done_ts
        )

    def _mem_sample(self) -> tuple[float, int]:
        """Mean deep-walk bytes over a sample of vnodes, excluding the
        structures shared across the block (mem.py docstring)."""
        if not self.vnodes:
            return 0.0, 0
        shared = [
            self.registry,
            self._secrets,
            self.constructor,
            self.wheel,
            self.router,
            self.service,
            self.device,
            self.msg,
            self._rand,
        ]
        if self.recorder is not None:
            shared.append(self.recorder)
        step = max(1, len(self.vnodes) // MEM_SAMPLE_VNODES)
        sample = self.vnodes[::step][:MEM_SAMPLE_VNODES]
        total = sum(deep_size(v, shared=shared) for v in sample)
        return total / len(sample), len(sample)

    def summary(self) -> dict:
        ttt = self._ttt()

        def q(p: float) -> float:
            return ttt[min(len(ttt) - 1, int(p * len(ttt)))] if ttt else 0.0

        vnode_bytes, sample_n = self._mem_sample()
        stale = sum(
            getattr(v.handel.store, "stale_retired_ct", 0)
            for v in self.vnodes
        )
        retired = sum(
            len(getattr(v.handel.store, "retired", ()))
            for v in self.vnodes
        )
        return {
            "proc_index": self.proc_index,
            "identities": len(self.vnodes),
            "completed": self._completed,
            "threshold": self.threshold,
            "wall_s": round(self._wall_s, 3),
            "ttt_p50_s": round(q(0.50), 4),
            "ttt_p90_s": round(q(0.90), 4),
            "ttt_max_s": round(ttt[-1] if ttt else 0.0, 4),
            "rss_bytes": process_rss_bytes(),
            "vnode_bytes_mean": round(vnode_bytes, 1),
            "vnode_bytes_sample_n": sample_n,
            "stale_retired_ct": stale,
            "retired_level_ct": retired,
            "verifier_launches": self.service.launches,
            "verifier_candidates": self.service.candidates,
            "dedup_hits": self.service.cache.hits,
            **self.router.values(),
            **self.wheel.values(),
            **self.pager.values(),
        }

    def rollup(self, top_k: int = 16) -> dict:
        """Per-process hierarchical rollup of the block's vnode reporters
        (sim/monitor.py Rollup): fleet counters once, not 65k CSV rows."""
        from handel_tpu.sim.monitor import Rollup

        r = Rollup(top_k=top_k)
        gauge_keys = (
            self.vnodes[0].handel.gauge_keys() if self.vnodes else set()
        )
        for v in self.vnodes:
            r.add(
                v.id,
                v.handel.values(),
                gauge_keys=gauge_keys,
                slow_value=v.time_to_threshold(),
            )
        return r.record()


def merge_summaries(parts: list[dict]) -> dict:
    """Fleet record from per-process summaries. The three bench-gated
    metrics (scripts/bench_check.py SIDE_METRICS): `swarm_identities`
    (scale proof, higher is better), `mem_bytes_per_identity` (summed RSS
    over the committee — the extrapolation basis), and
    `swarm_time_to_threshold_s` (wall until the LAST member held a
    threshold signature — the whole-committee completion wave)."""
    identities = sum(p["identities"] for p in parts)
    rss = sum(p["rss_bytes"] for p in parts)
    out = {
        "swarm_identities": identities,
        "processes": len(parts),
        "completed": sum(p["completed"] for p in parts),
        "threshold": parts[0]["threshold"] if parts else 0,
        "wall_s": max((p["wall_s"] for p in parts), default=0.0),
        "swarm_time_to_threshold_s": max(
            (p["ttt_max_s"] for p in parts), default=0.0
        ),
        "ttt_p50_s": max((p["ttt_p50_s"] for p in parts), default=0.0),
        "ttt_p90_s": max((p["ttt_p90_s"] for p in parts), default=0.0),
        "rss_bytes_total": rss,
        "mem_bytes_per_identity": round(rss / identities, 1)
        if identities
        else 0.0,
        "vnode_bytes_mean": max(
            (p["vnode_bytes_mean"] for p in parts), default=0.0
        ),
        "stale_retired_ct": sum(p["stale_retired_ct"] for p in parts),
        "retired_level_ct": sum(p["retired_level_ct"] for p in parts),
        "verifier_launches": sum(p["verifier_launches"] for p in parts),
        "verifier_candidates": sum(p["verifier_candidates"] for p in parts),
        "dedup_hits": sum(p["dedup_hits"] for p in parts),
        "udp_sent": sum(p["swarmUdpSent"] for p in parts),
        "local_delivered": sum(p["swarmLocalDelivered"] for p in parts),
        "pages_committed": sum(p["pagesCommitted"] for p in parts),
        "page_hits": sum(p["pageHits"] for p in parts),
    }
    out["ok"] = out["completed"] == out["swarm_identities"]
    return out


def host_from_params(
    p, lo: int, hi: int, *, block: int, ports, proc_index: int,
    trace: bool, trace_capacity: int, rollup_top_k: int = 8,
) -> SwarmHost:
    """Build one SwarmHost from a SwarmParams section (sim/config.py)."""
    host = SwarmHost(
        p.identities,
        lo,
        hi,
        threshold=p.threshold,
        update_period=p.period_ms / 1000.0,
        level_timeout=p.timeout_ms / 1000.0,
        fast_path=p.fast_path,
        tick_s=p.tick_ms / 1000.0,
        batch_size=p.batch_size,
        max_pending=p.max_pending,
        chunk_bits=p.chunk_bits,
        page_budget=p.page_budget,
        block=block,
        ports=ports,
        proc_index=proc_index,
        trace=trace,
        trace_capacity=trace_capacity,
        rollup_top_k=rollup_top_k,
    )
    return host


def _merge_host_digests(cfg, workdir: str, parts: list[dict]) -> dict:
    """Master-side FleetRollup over the per-process host digests: the
    O(hosts) summary keys plus fleet_rollup.json for `sim watch` / CI.
    Missing digest files degrade to an empty block, never a failure."""
    from handel_tpu.obs.rollup import FleetRollup

    al = getattr(cfg, "alerts", None)
    fleet = FleetRollup(
        top_k=al.rollup_top_k if al is not None else 8,
        stale_after_s=al.rollup_stale_s if al is not None else 5.0,
    )
    hosts = 0
    for i in range(len(parts)):
        path = os.path.join(workdir, f"host_digest_{i}.json")
        if not os.path.exists(path):
            continue
        with open(path) as f:
            fleet.ingest_digest(json.load(f))
        hosts += 1
    if not hosts:
        return {}
    merged = fleet.merged()
    wall = max((float(p.get("wall_s", 0.0)) for p in parts), default=0.0)
    bytes_per_host = sum(
        float(p.get("rollup_bytes", 0.0)) for p in parts
    ) / hosts
    out = {
        "fleet_hosts": hosts,
        "fleet_series_count": merged["series"],
        "rollup_bytes_per_host_s": round(
            bytes_per_host / wall if wall else 0.0, 1
        ),
        "fleet_eval_ms": round(fleet.last_merge_ms, 3),
    }
    with open(os.path.join(workdir, "fleet_rollup.json"), "w") as f:
        json.dump({**out, "fleet": fleet.fleet_payload()}, f, indent=1)
        f.write("\n")
    return out


async def run_swarm(cfg, workdir: str, config_path: str = "") -> dict:
    """The `sim swarm` orchestrator: one committee over M processes."""
    from handel_tpu.sim.config import dump_config

    p = cfg.swarm
    if not p.enabled():
        raise ValueError("no [swarm] section (swarm.identities must be > 0)")
    os.makedirs(workdir, exist_ok=True)
    timeout = p.timeout_s or cfg.max_timeout_s
    procs_n = max(1, p.processes)
    shares = _split(p.identities, procs_n)
    block = shares[0]  # contiguous blocks; the first ones carry the remainder
    bounds = []
    lo = 0
    for share in shares:
        bounds.append((lo, lo + share))
        lo += share

    al = getattr(cfg, "alerts", None)
    rollup_top_k = al.rollup_top_k if al is not None else 8
    trace_paths: list[str] = []
    if procs_n == 1:
        host = host_from_params(
            p, 0, p.identities, block=block, ports=[], proc_index=0,
            trace=cfg.trace, trace_capacity=cfg.trace_capacity,
            rollup_top_k=rollup_top_k,
        )
        part = await host.run(timeout)
        with open(os.path.join(workdir, "swarm_rollup_0.json"), "w") as f:
            json.dump(host.rollup(), f)
        digest = host.host_rollup.digest()
        part["rollup_bytes"] = host.host_rollup.emit()
        with open(os.path.join(workdir, "host_digest_0.json"), "w") as f:
            json.dump(digest, f)
        if host.recorder is not None:
            trace_paths.append(
                host.recorder.dump(
                    os.path.join(workdir, "swarm_trace_0.json")
                )
            )
        parts = [part]
    else:
        if not config_path:
            config_path = os.path.join(workdir, "swarm.toml")
            with open(config_path, "w") as f:
                f.write(dump_config(cfg))
        from handel_tpu.sim.platform import free_ports
        from handel_tpu.sim.sync import STATE_START, SyncMaster

        ports = free_ports(procs_n + 1)
        sync_port, swarm_ports = ports[0], ports[1:]
        with open(os.path.join(workdir, "swarm_ports.json"), "w") as f:
            json.dump({"sync": sync_port, "swarm": swarm_ports}, f)
        master = SyncMaster(sync_port, procs_n)
        await master.start()
        workers = []
        for i in range(procs_n):
            cmd = [
                sys.executable,
                "-m",
                "handel_tpu.swarm.worker",
                "--config",
                config_path,
                "--index",
                str(i),
                "--workdir",
                workdir,
            ]
            workers.append(
                await asyncio.create_subprocess_exec(
                    *cmd,
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.PIPE,
                )
            )
        try:
            # every worker binds + builds before any starts gossiping
            await master.wait_all(STATE_START, timeout=timeout)
            outs = await asyncio.wait_for(
                asyncio.gather(*(w.communicate() for w in workers)),
                # build + run + teardown; generous vs the run deadline
                timeout=timeout * 2 + 120,
            )
        finally:
            master.stop()
            for w in workers:
                if w.returncode is None:
                    w.kill()
        parts = []
        for i, (w, (out, err)) in enumerate(zip(workers, outs)):
            if w.returncode != 0:
                sys.stderr.write(err.decode(errors="replace"))
                raise RuntimeError(f"swarm worker {i} failed (rc={w.returncode})")
            for line in out.decode().splitlines():
                if line.startswith("SWARM_RESULT "):
                    parts.append(json.loads(line[len("SWARM_RESULT "):]))
            tp = os.path.join(workdir, f"swarm_trace_{i}.json")
            if os.path.exists(tp):
                trace_paths.append(tp)
        if len(parts) != procs_n:
            raise RuntimeError(
                f"{len(parts)}/{procs_n} swarm workers reported a summary"
            )

    summary = merge_summaries(parts)
    summary["per_process"] = parts
    summary.update(_merge_host_digests(cfg, workdir, parts))
    if trace_paths:
        # streamed critical-path + level-wave report over the per-process
        # trace files (sim/trace_cli.py; never loads all files at once)
        from handel_tpu.sim.trace_cli import stream_report

        report = stream_report(trace_paths)
        with open(os.path.join(workdir, "swarm_trace_report.json"), "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
        summary["trace_report"] = {
            k: report[k]
            for k in ("time_to_threshold_s", "level_wave", "critical_path_len")
            if k in report
        }
    with open(os.path.join(workdir, "swarm_summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
        f.write("\n")
    return summary

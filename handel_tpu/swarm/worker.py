"""Swarm worker process: one contiguous vnode block of the committee.

Spawned by `run_swarm` (swarm/driver.py) as
`python -m handel_tpu.swarm.worker --config <toml> --index <i> --workdir <d>`.
Reads the `[swarm]` section plus the parent's `swarm_ports.json`, binds its
shared UDP socket, builds its vnodes (registering every listener), joins the
START barrier — no process gossips until every block can receive — runs to
completion, dumps its trace file, and reports its summary as one
`SWARM_RESULT {json}` stdout line (the service/worker.py convention).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys


async def run_worker(args) -> int:
    from handel_tpu.sim.config import load_config
    from handel_tpu.sim.sync import STATE_END, STATE_START, SyncSlave
    from handel_tpu.swarm.driver import _split, host_from_params

    cfg = load_config(args.config)
    p = cfg.swarm
    with open(os.path.join(args.workdir, "swarm_ports.json")) as f:
        ports = json.load(f)
    shares = _split(p.identities, max(1, p.processes))
    lo = sum(shares[: args.index])
    hi = lo + shares[args.index]

    host = host_from_params(
        p,
        lo,
        hi,
        block=shares[0],
        ports=ports["swarm"],
        proc_index=args.index,
        trace=cfg.trace,
        trace_capacity=cfg.trace_capacity,
        rollup_top_k=cfg.alerts.rollup_top_k,
    )
    await host.router.open(ports["swarm"][args.index])
    host.build()

    slave = SyncSlave(f"127.0.0.1:{ports['sync']}", args.index)
    await slave.start()
    timeout = p.timeout_s or cfg.max_timeout_s
    await slave.signal_and_wait(STATE_START, timeout=timeout)
    if host.recorder is not None:
        # barrier handshake clock estimate -> trace alignment at merge
        host.recorder.clock_offset = slave.clock_offset

    summary = await host.run(timeout, teardown=False)
    # END barrier before teardown: our block is done but siblings may still
    # need our contributions — closing the router now would strand them
    try:
        await slave.signal_and_wait(STATE_END, timeout=timeout)
    except asyncio.TimeoutError:
        pass  # a straggling sibling shouldn't wedge our report
    host.stop()
    slave.stop()
    with open(
        os.path.join(args.workdir, f"swarm_rollup_{args.index}.json"), "w"
    ) as f:
        json.dump(host.rollup(), f)
    # hierarchical roll-up: the bounded host digest the master's
    # FleetRollup merges (obs/rollup.py) + the wire bytes a live chunked
    # delta emission would have cost — O(key-union), never O(identities)
    digest = host.host_rollup.digest()
    summary["rollup_bytes"] = host.host_rollup.emit()
    with open(
        os.path.join(args.workdir, f"host_digest_{args.index}.json"), "w"
    ) as f:
        json.dump(digest, f)
    if host.recorder is not None:
        host.recorder.dump(
            os.path.join(args.workdir, f"swarm_trace_{args.index}.json")
        )
    print("SWARM_RESULT " + json.dumps(summary), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True)
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--workdir", required=True)
    return asyncio.run(run_worker(ap.parse_args()))


if __name__ == "__main__":
    sys.exit(main())

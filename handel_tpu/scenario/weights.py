"""Stake-weight profiles: deterministic per-identity weight vectors.

Every profile is a pure function of (profile, n, seed), so all processes
of a run derive the SAME weights independently — the weighted-threshold
analog of the deterministic adversary-role assignment. Non-uniform
profiles normalize to `sum(weights) == n`, keeping weighted thresholds on
the same scale as count thresholds; "count" stays exactly all-1.0 so the
weighted code path is bit-for-bit the count path.
"""

from __future__ import annotations

import random

PROFILES = ("count", "linear", "pareto", "split")


def make_weights(profile: str, n: int, seed: int = 0) -> list[float]:
    if n <= 0:
        return []
    if profile == "count":
        # all-ones, NOT normalized through float math: the strict no-op
        # profile must hand Handel exact 1.0s
        return [1.0] * n
    if profile == "linear":
        # ramp 1..2 by id: mild, deterministic inequality
        w = [1.0 + (i / (n - 1) if n > 1 else 0.0) for i in range(n)]
    elif profile == "split":
        # two castes interleaved by id parity, so stake never correlates
        # with region placement (which is id round-robin too, but over
        # >= 3 regions) or with the high-id adversary seats exclusively
        w = [1.5 if i % 2 == 0 else 0.5 for i in range(n)]
    elif profile == "pareto":
        # heavy-tailed stake: a few whales, a long tail — the realistic
        # shape for proof-of-stake committees. Seeded + capped so one
        # draw cannot dominate the total past any threshold's reach.
        rng = random.Random(f"weights|{seed}")
        w = [min(rng.paretovariate(1.5), 20.0) for _ in range(n)]
    else:
        raise ValueError(
            f"unknown weight profile {profile!r} (known: {', '.join(PROFILES)})"
        )
    total = sum(w)
    return [v * n / total for v in w]

"""Deterministic membership schedules: who leaves/joins, and when.

A schedule is a pure function of its constructor arguments, so every
process — and every re-run with the same TOML — derives the identical
timeline. Leaves seat on the churner adversary ids (sim/adversary.py
`adversary_roles`: highest non-offline ids), each with a seeded stagger
around the configured departure time so a 10%-churn run doesn't drop all
its churners on one tick. Joins are new identities ABOVE the current
registry (ids n, n+1, ...), admitted through the epoch path
(lifecycle/epoch.py stage_registry -> activate_staged): a join lands in
the NEXT epoch's committee, it does not retro-enter a running round.
"""

from __future__ import annotations

import random
from dataclasses import dataclass


@dataclass(frozen=True)
class MembershipEvent:
    at_s: float  # seconds after run start
    kind: str  # "leave" | "join"
    node_id: int


class MembershipSchedule:
    """The run's membership timeline over an n-node starting committee."""

    def __init__(
        self,
        nodes: int,
        churner_ids: tuple[int, ...] | list[int] = (),
        churn_after_s: float = 0.5,
        joins: int = 0,
        join_at_s: float = 1.0,
        seed: int = 0,
    ):
        self.nodes = nodes
        rng = random.Random(f"membership|{seed}")
        events: list[MembershipEvent] = []
        for nid in sorted(churner_ids):
            # stagger each departure within ±25% of the nominal time
            at = churn_after_s * (0.75 + 0.5 * rng.random())
            events.append(MembershipEvent(at, "leave", nid))
        for k in range(joins):
            events.append(MembershipEvent(join_at_s, "join", nodes + k))
        self.events = sorted(events, key=lambda e: (e.at_s, e.node_id))

    def leaves(self) -> list[MembershipEvent]:
        return [e for e in self.events if e.kind == "leave"]

    def joins(self) -> list[MembershipEvent]:
        return [e for e in self.events if e.kind == "join"]

    def leave_time_of(self, node_id: int) -> float | None:
        for e in self.events:
            if e.kind == "leave" and e.node_id == node_id:
                return e.at_s
        return None

    def final_size(self) -> int:
        return self.nodes - len(self.leaves()) + len(self.joins())

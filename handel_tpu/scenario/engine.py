"""The WAN scenario engine: compose geo latency, churn, weights, joins.

`run_scenario(cfg, workdir)` takes one parsed sim TOML (sim/config.py;
`[scenario]` + the usual `[[runs]]` shape) and drives a single in-process
aggregation round with every configured axis active at once:

  geo       every node's transport is a GeoNetwork (network/geo.py) over
            the planet's region RTT matrix; each node's Config.region tag
            rides its trace spans so the critical path attributes WAN
            hops by region pair (sim/trace_cli.py region_hops);
  weights   a deterministic stake profile (scenario/weights.py) feeds the
            weighted threshold plane (core/handel.py): the round completes
            when the aggregate's WEIGHT clears the stake threshold;
  churn     `[runs.adversaries] churner = K` nodes participate honestly
            then depart on the MembershipSchedule's staggered timeline,
            broadcasting Handel.mark_departed so survivors re-level and
            re-evaluate reachability;
  joins     `joins = J` new identities are admitted through the epoch
            path — an enlarged registry staged on every verify lane, then
            quiesce + flip (lifecycle/epoch.py). A join lands in the next
            epoch's committee; the running round is unaffected by design.

The result is a bench-record-shaped report (scripts/bench_check.py,
headline `geo_weighted_ttt_s`) plus the trace dump + trace report in
`workdir`, making every scenario a captured, regression-gated artifact.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from handel_tpu.core.logging import DEFAULT_LOGGER
from handel_tpu.core.test_harness import LocalCluster
from handel_tpu.core.trace import FlightRecorder
from handel_tpu.scenario.membership import MembershipSchedule
from handel_tpu.sim.adversary import (
    ROLE_CHURNER,
    adversary_roles,
    check_threshold_reachable,
)


async def _admit_joins(scen, nodes: int, scheme, logger) -> tuple[int, float]:
    """Join-side membership: stage an ENLARGED registry (the original n
    identities plus `joins` new keys) on a live verify plane and flip the
    epoch — PR 12's stage -> quiesce -> activate choreography, here driven
    by membership change instead of key rotation. Returns (epochs advanced,
    swap stall seconds)."""
    from handel_tpu.lifecycle.epoch import EpochManager
    from handel_tpu.service.driver import MultiSessionCluster

    cluster = MultiSessionCluster(
        sessions=0, nodes=nodes, scheme=scheme, batch_size=32,
        max_sessions=1,
    )
    cluster.service.start()
    try:
        epochs = EpochManager(cluster.service, cluster.manager, logger=logger)
        pubkeys = [
            scheme.keygen(i)[1] for i in range(nodes + scen.joins)
        ]
        await epochs.begin_rotation(pubkeys)
        stall_s = await epochs.commit_rotation()
        return epochs.rotations, stall_s
    finally:
        cluster.service.stop()


async def run_scenario(cfg, workdir: str, logger=DEFAULT_LOGGER) -> dict:
    """Run the scenario described by `cfg` (a SimConfig with `[scenario]`),
    writing scenario_trace.json + scenario_report.json into `workdir`."""
    scen = cfg.scenario
    run = cfg.runs[0]
    n = run.nodes
    threshold = run.resolved_threshold()

    geo = scen.geo_config() if scen.geo_enabled() else None
    weights = scen.make_weights(n) if scen.weights_enabled() else None
    weight_threshold = (
        scen.weight_threshold(threshold, n, weights)
        if weights is not None
        else 0.0
    )

    roles = (
        adversary_roles(run.adversaries.counts(), n)
        if run.adversaries.total()
        else {}
    )
    check_threshold_reachable(
        threshold,
        n,
        run.failing,
        roles,
        weights=weights,
        weight_threshold=weight_threshold,
    )

    churn_after_s = run.adversaries.churn_after_ms / 1000.0
    schedule = MembershipSchedule(
        nodes=n,
        churner_ids=[i for i, r in roles.items() if r == ROLE_CHURNER],
        churn_after_s=churn_after_s,
        joins=scen.joins,
        join_at_s=scen.join_at_frac * max(1.0, 2.0 * churn_after_s),
        seed=scen.geo_seed,
    )

    recorder = FlightRecorder(capacity=cfg.trace_capacity)

    def config_factory(i: int):
        c = run.handel.to_config(threshold, seed=i)
        if weights is not None:
            c.weights = weights
            c.weight_threshold = weight_threshold
        return c

    cluster = LocalCluster(
        n,
        threshold=threshold,
        offline=[],
        config_factory=config_factory,
        adversaries=roles,
        recorder=recorder,
        geo=geo,
        chaos=cfg.chaos if cfg.chaos.any() else None,
        churn_after_s=churn_after_s,
    )
    # per-churner staggered departure times from the deterministic schedule
    for nid, a in cluster.adversaries.items():
        if getattr(a, "role", None) == ROLE_CHURNER:
            at = schedule.leave_time_of(nid)
            if at is not None:
                a.leave_after_s = at

    epochs_advanced, swap_stall_s = 0, 0.0
    join_task = None
    t0 = time.monotonic()
    cluster.start()
    try:
        if scen.joins > 0:
            join_at = schedule.joins()[0].at_s

            async def _join_later():
                await asyncio.sleep(join_at)
                return await _admit_joins(scen, n, cluster.scheme, logger)

            join_task = asyncio.ensure_future(_join_later())
        finals = await cluster.wait_complete_success(
            timeout=cfg.max_timeout_s
        )
        ttt = time.monotonic() - t0
        if join_task is not None:
            epochs_advanced, swap_stall_s = await asyncio.wait_for(
                join_task, timeout=cfg.max_timeout_s
            )
            join_task = None
    finally:
        if join_task is not None:
            join_task.cancel()
        cluster.stop()

    # -- verdicts over the converged state ---------------------------------
    final = next(iter(finals.values()))
    card = final.bitset.cardinality()
    achieved_weight = (
        final.bitset.weight_sum(weights) if weights is not None else float(card)
    )
    reached = (
        achieved_weight >= weight_threshold - 1e-9
        if weights is not None
        else card >= threshold
    )
    churner_ids = [i for i, r in roles.items() if r == ROLE_CHURNER]
    departed_everywhere = all(
        set(churner_ids) <= h.departed for h in cluster.handels.values()
    )

    trace_path = os.path.join(workdir, "scenario_trace.json")
    recorder.dump(trace_path)
    from handel_tpu.sim.trace_cli import build_report

    trace_report = build_report(recorder.export()["traceEvents"])
    cp = trace_report.get("critical_path") or {}
    region_hops = cp.get("region_hops", [])

    checks = {
        "threshold_reached": bool(reached),
        "departures_marked": departed_everywhere,
        "epoch_advanced": scen.joins == 0 or epochs_advanced >= 1,
        "region_attributed": geo is None or len(region_hops) >= 1,
    }
    report = {
        # bench-record shape (scripts/bench_check.py SIDE_METRICS)
        "metric": "geo_weighted_ttt_s",
        "value": round(ttt, 6),
        "backend": "scenario",
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "ok": all(checks.values()),
        "checks": checks,
        "geo_weighted_ttt_s": round(ttt, 6),
        "scenario": {
            "name": scen.name or "unnamed",
            "planet": scen.planet,
            "regions": geo.regions if geo is not None else [],
            "nodes": n,
            "threshold": threshold,
            "failing": run.failing,
            "churners": len(churner_ids),
            "departed_ids": sorted(churner_ids),
            "joins": scen.joins,
            "epochs_advanced": epochs_advanced,
            "epoch_swap_stall_ms": round(swap_stall_s * 1e3, 3),
            "weight_profile": scen.weight_profile,
            "weight_threshold": round(weight_threshold, 6),
            "achieved_weight": round(achieved_weight, 6),
            "achieved_cardinality": card,
            "region_hops": region_hops,
            "critical_path_ms": cp.get("wall_ms", 0.0),
            "stages_ms": cp.get("stages_ms", {}),
            "sent_packets": cluster.router.sent_packets,
        },
    }
    with open(os.path.join(workdir, "scenario_report.json"), "w") as f:
        json.dump(report, f, indent=1)
    return report


def run_scenario_sync(cfg, workdir: str, logger=DEFAULT_LOGGER) -> dict:
    return asyncio.run(run_scenario(cfg, workdir, logger=logger))

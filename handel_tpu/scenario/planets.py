"""Named planet presets: region lists + symmetric RTT matrices (ms).

The numbers are representative public-cloud inter-region RTTs, rounded —
the point is the SHAPE (one close pair, one far pair, a mid band), not
basis-point accuracy. Scenarios reference a preset by name in the
`[scenario]` TOML section (`planet = "planet-5region"`) or supply an
inline `regions` + `rtt_ms` matrix instead.
"""

from __future__ import annotations

PLANETS: dict[str, tuple[list[str], list[list[float]]]] = {
    # one continent-local pair, one transpacific pair
    "planet-3region": (
        ["eu-west", "us-east", "ap-east"],
        [
            [4.0, 80.0, 220.0],
            [80.0, 4.0, 170.0],
            [220.0, 170.0, 4.0],
        ],
    ),
    # the 5-region capture shape: two US coasts, Europe, Asia, South America
    "planet-5region": (
        ["eu-west", "us-east", "us-west", "ap-east", "sa-south"],
        [
            [4.0, 80.0, 140.0, 220.0, 190.0],
            [80.0, 4.0, 65.0, 170.0, 115.0],
            [140.0, 65.0, 4.0, 110.0, 175.0],
            [220.0, 170.0, 110.0, 4.0, 300.0],
            [190.0, 115.0, 175.0, 300.0, 4.0],
        ],
    ),
    # a deliberately tiny planet for fast CI smokes: same structure, RTTs
    # an order of magnitude down so a 32-node run converges in seconds
    "planet-3region-fast": (
        ["eu-west", "us-east", "ap-east"],
        [
            [0.5, 8.0, 22.0],
            [8.0, 0.5, 17.0],
            [22.0, 17.0, 0.5],
        ],
    ),
}


def planet_names() -> list[str]:
    return sorted(PLANETS)


def planet_preset(name: str) -> tuple[list[str], list[list[float]]]:
    """(regions, rtt_ms) for a named preset; copies, safe to mutate."""
    try:
        regions, rtt = PLANETS[name]
    except KeyError:
        raise ValueError(
            f"unknown planet {name!r} (known: {', '.join(planet_names())})"
        ) from None
    return list(regions), [list(row) for row in rtt]

"""WAN scenario engine: geo-latency planets, churn, stake weights.

`python -m handel_tpu.sim scenario --config <toml>` runs one; `confgen
--scenario geo|churn|weighted` emits ready-to-run TOMLs (sim/confgen.py).
"""

from handel_tpu.scenario.engine import run_scenario, run_scenario_sync
from handel_tpu.scenario.membership import MembershipEvent, MembershipSchedule
from handel_tpu.scenario.planets import PLANETS, planet_names, planet_preset
from handel_tpu.scenario.weights import PROFILES, make_weights

__all__ = [
    "run_scenario",
    "run_scenario_sync",
    "MembershipEvent",
    "MembershipSchedule",
    "PLANETS",
    "planet_names",
    "planet_preset",
    "PROFILES",
    "make_weights",
]

"""Real transports for the protocol plane.

Reference: network/ — the `Encoding` wire abstraction
(network/wireencoding.go:10-13), the byte-counting decorator
(network/counter_encoding.go:13-63), and the UDP (network/udp/net.go:19-226)
and TCP (network/tcp/net.go:16-127) transports.

The in-process transport for pod-local simulation lives in
core/test_harness.py; these sockets carry protocol traffic between hosts
(DCN). Signature batches ride the separate host<->device plane
(parallel/batch_verifier.py), never these sockets.
"""

from handel_tpu.network.chaos import ChaosConfig, ChaosNetwork
from handel_tpu.network.encoding import (
    BinaryEncoding,
    CounterEncoding,
    Encoding,
)
from handel_tpu.network.udp import UDPNetwork
from handel_tpu.network.tcp import TCPNetwork
from handel_tpu.network.quic import QUICNetwork

__all__ = [
    "Encoding",
    "BinaryEncoding",
    "CounterEncoding",
    "ChaosConfig",
    "ChaosNetwork",
    "UDPNetwork",
    "TCPNetwork",
    "QUICNetwork",
]

"""Shared machinery for stream transports (TCP and the TLS/QUIC slot).

One place for the length-prefixed frame protocol (uint32 header + encoded
packet — TCP gives no message boundaries), the inbound read loop, and the
background-send task registry (asyncio keeps only weak refs to tasks, so a
fire-and-forget `create_task` can be garbage-collected mid-await; senders
must hold strong refs until completion).
"""

from __future__ import annotations

import asyncio
import struct

_LEN = struct.Struct(">I")
IDLE_TIMEOUT = 60.0  # reference's 1-minute conn deadline (tcp/net.go:100)


def frame(wire: bytes) -> bytes:
    return _LEN.pack(len(wire)) + wire


async def read_frames(reader, enc, listeners, log, tag: str, on_packet=None):
    """Length-prefixed read loop shared by every stream transport: decode
    each frame and fan out to listeners until EOF/idle-timeout/error."""
    try:
        while True:
            hdr = await asyncio.wait_for(
                reader.readexactly(_LEN.size), IDLE_TIMEOUT
            )
            (size,) = _LEN.unpack(hdr)
            data = await reader.readexactly(size)
            try:
                packet = enc.decode(data)
            except Exception as e:
                log.warn(f"{tag}_decode", e)
                continue
            if on_packet is not None:
                on_packet()
            for lst in listeners:
                lst.new_packet(packet)
    except (asyncio.IncompleteReadError, asyncio.TimeoutError, OSError):
        pass


class TaskSet:
    """Strong-reference holder for fire-and-forget send tasks."""

    def __init__(self):
        self._tasks: set[asyncio.Task] = set()

    def spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    def cancel_all(self) -> None:
        for t in list(self._tasks):
            t.cancel()


class Session:
    """One live outbound session (a stream to a peer)."""

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer

    def alive(self) -> bool:
        return not self.writer.is_closing()

    def close(self) -> None:
        self.writer.close()


class SessionManager:
    """Per-peer session cache that dedups concurrent dials
    (quic/sessionmanager.go:11-93 `simpleSesssionManager`): while a dial to a
    peer is in flight, other senders await the same future instead of opening
    a second connection. Transport-agnostic via the dialer seam
    (quic/dialer.go) — TCP and TLS transports pass their own dialer."""

    def __init__(self, dialer):
        self._dialer = dialer  # async addr -> Session
        self._sessions: dict[str, Session] = {}
        self._waiting: dict[str, asyncio.Future] = {}  # isWaiting set

    async def session(self, addr: str) -> Session:
        ses = self._sessions.get(addr)
        if ses is not None and ses.alive():
            return ses
        fut = self._waiting.get(addr)
        if fut is not None:  # a dial is already in flight: piggyback
            return await asyncio.shield(fut)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._waiting[addr] = fut
        try:
            ses = await self._dialer(addr)
        except BaseException as e:
            fut.set_exception(e)
            # consume the exception if nobody else awaited the future
            fut.exception()
            raise
        finally:
            self._waiting.pop(addr, None)
        if not fut.done():
            fut.set_result(ses)
        self._sessions[addr] = ses
        return ses

    def drop(self, addr: str) -> None:
        ses = self._sessions.pop(addr, None)
        if ses is not None:
            ses.close()

    def close_all(self) -> None:
        for addr in list(self._sessions):
            self.drop(addr)

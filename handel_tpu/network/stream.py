"""Shared machinery for stream transports (TCP and the TLS/QUIC slot).

One place for the length-prefixed frame protocol (uint32 header + encoded
packet — TCP gives no message boundaries), the inbound read loop, and the
background-send task registry (asyncio keeps only weak refs to tasks, so a
fire-and-forget `create_task` can be garbage-collected mid-await; senders
must hold strong refs until completion).
"""

from __future__ import annotations

import asyncio
import struct

_LEN = struct.Struct(">I")
IDLE_TIMEOUT = 60.0  # reference's 1-minute conn deadline (tcp/net.go:100)


def frame(wire: bytes) -> bytes:
    return _LEN.pack(len(wire)) + wire


async def read_frames(reader, enc, listeners, log, tag: str, on_packet=None):
    """Length-prefixed read loop shared by every stream transport: decode
    each frame and fan out to listeners until EOF/idle-timeout/error."""
    try:
        while True:
            hdr = await asyncio.wait_for(
                reader.readexactly(_LEN.size), IDLE_TIMEOUT
            )
            (size,) = _LEN.unpack(hdr)
            data = await reader.readexactly(size)
            try:
                packet = enc.decode(data)
            except Exception as e:
                log.warn(f"{tag}_decode", e)
                continue
            if on_packet is not None:
                on_packet()
            for lst in listeners:
                lst.new_packet(packet)
    except (asyncio.IncompleteReadError, asyncio.TimeoutError, OSError):
        pass


class TaskSet:
    """Strong-reference holder for fire-and-forget send tasks."""

    def __init__(self):
        self._tasks: set[asyncio.Task] = set()

    def spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    def cancel_all(self) -> None:
        for t in list(self._tasks):
            t.cancel()

"""UDP transport — the default protocol-plane network.

Reference: network/udp/net.go:19-226 — bind on 0.0.0.0:port, fire-and-forget
send to each peer, an inbound pipeline that decouples the socket from packet
handling (20000-slot queue + pending list + dispatch loop, :148-209), and
sent/rcvd packet counters for the monitor (:212-226).

asyncio redesign: one DatagramProtocol endpoint per node; the kernel socket
feeds a bounded asyncio.Queue (drop-on-overflow, like the reference's select
with a full newPacket channel) drained by a dispatch task that decodes and
fans out to listeners. Everything runs on the node's event loop — no locks.

Identity addresses are "host:port" strings (simul/lib CSV registry format).
"""

from __future__ import annotations

import asyncio
from typing import Sequence

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.net import Listener, Packet
from handel_tpu.core.report import WarnOnce
from handel_tpu.network.encoding import Encoding, BinaryEncoding

QUEUE_SIZE = 20_000  # inbound buffer slots (udp/net.go:33)


def split_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class _Proto(asyncio.DatagramProtocol):
    def __init__(self, net: "UDPNetwork"):
        self.net = net

    def datagram_received(self, data: bytes, addr) -> None:
        self.net._enqueue(data)

    def error_received(self, exc) -> None:
        # ICMP errors (port unreachable etc.) stay fire-and-forget for the
        # protocol, but silently discarding them hid dead peers from every
        # stall diagnosis — count them on the monitor plane and warn once
        self.net._icmp_error(exc)


class UDPNetwork:
    """Datagram Network bound to a local port (udp/net.go:19-226)."""

    def __init__(
        self,
        listen_addr: str,
        encoding: Encoding | None = None,
        logger: Logger = DEFAULT_LOGGER,
    ):
        self.listen_addr = listen_addr
        self.enc = encoding or BinaryEncoding()
        self.log = logger
        self.listeners: list[Listener] = []
        self._queue: asyncio.Queue[bytes] = asyncio.Queue(QUEUE_SIZE)
        self._transport: asyncio.DatagramTransport | None = None
        self._dispatch_task: asyncio.Task | None = None
        self.sent = 0  # packets out (udp/net.go:212-226)
        self.rcvd = 0  # packets dispatched to listeners
        self.dropped = 0  # queue-full drops
        self.icmp_errors = 0  # error_received callbacks (ICMP unreachable)
        self.decode_errors = 0  # malformed datagrams rejected by the codec
        # warn-once per reason + the logWarnCt counter (core/report.py): a
        # dead peer or flooder fires thousands of identical warnings
        self._warn = WarnOnce(self.log)

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        host, port = split_addr(self.listen_addr)
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _Proto(self), local_addr=("0.0.0.0", port)
        )
        self._dispatch_task = loop.create_task(self._dispatch_loop())

    def stop(self) -> None:
        if self._dispatch_task:
            self._dispatch_task.cancel()
        if self._transport:
            self._transport.close()

    # -- outbound -----------------------------------------------------------

    def send(self, identities: Sequence["Identity"], packet: Packet) -> None:  # noqa: F821
        if self._transport is None:
            raise RuntimeError("UDPNetwork not started")
        wire = self.enc.encode(packet)
        for ident in identities:
            try:
                self._transport.sendto(wire, split_addr(ident.address))
                self.sent += 1
            except OSError as e:  # unreachable peer: datagrams just vanish
                self._warn.warn("udp_send", f"{ident.address}: {e}")

    # -- inbound pipeline ---------------------------------------------------

    def _icmp_error(self, exc) -> None:
        self.icmp_errors += 1
        self._warn.warn("udp_icmp", f"{self.listen_addr}: {exc}")

    def _enqueue(self, data: bytes) -> None:
        try:
            self._queue.put_nowait(data)
        except asyncio.QueueFull:  # drop, like the reference's full channel
            self.dropped += 1
            self._warn.warn(
                "udp_queue_full",
                f"{self.listen_addr}: dropping inbound datagrams",
            )

    async def _dispatch_loop(self) -> None:
        while True:
            data = await self._queue.get()
            try:
                packet = self.enc.decode(data)
            except Exception as e:  # malformed datagram: count and move on
                self.decode_errors += 1
                self._warn.warn("udp_decode", e)
                continue
            self.rcvd += 1
            for lst in self.listeners:
                lst.new_packet(packet)

    def register_listener(self, listener: Listener) -> None:
        self.listeners.append(listener)

    # -- reporter (udp/net.go:212-226) --------------------------------------

    def values(self) -> dict[str, float]:
        out = {
            "sentPackets": float(self.sent),
            "rcvdPackets": float(self.rcvd),
            "droppedPackets": float(self.dropped),
            "icmpErrors": float(self.icmp_errors),
            "decodeErrors": float(self.decode_errors),
            **self._warn.values(),
        }
        if hasattr(self.enc, "values"):
            out.update(self.enc.values())
        return out

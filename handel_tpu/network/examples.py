"""Minimal multi-peer transport demo.

Reference: network/examples/start.go:35-85 + its README — three peers load a
CSV registry, bind their transport, and exchange a hello packet with every
other peer. Here each peer is an asyncio task in one process binding a real
socket, so the demo doubles as a live check of the transport stack:

    python -m handel_tpu.network.examples [n_peers] [udp|tcp]
"""

from __future__ import annotations

import asyncio
import os
import sys
import tempfile

from handel_tpu.core.net import Packet
from handel_tpu.sim.keys import (
    generate_nodes,
    read_registry_csv,
    registry_from_records,
    write_registry_csv,
)
from handel_tpu.sim.platform import free_ports


def _make_network(kind: str, addr: str):
    if kind == "udp":
        from handel_tpu.network.udp import UDPNetwork

        return UDPNetwork(addr)
    if kind == "tcp":
        from handel_tpu.network.tcp import TCPNetwork

        return TCPNetwork(addr)
    raise ValueError(f"unknown transport {kind!r}")


class _Collector:
    """Listener counting hello packets from distinct origins."""

    def __init__(self, expect: int):
        self.origins: set[int] = set()
        self.done = asyncio.Event()
        self.expect = expect

    def new_packet(self, packet: Packet) -> None:
        self.origins.add(packet.origin)
        if len(self.origins) >= self.expect:
            self.done.set()


async def run_demo(n_peers: int = 3, kind: str = "udp", timeout: float = 10.0):
    """Returns {peer_id: set of origins heard from}. Raises on timeout."""
    from handel_tpu.models.registry import new_scheme

    ports = free_ports(n_peers)
    addresses = [f"127.0.0.1:{p}" for p in ports]
    # round-trip the registry through CSV like the reference demo does
    scheme = new_scheme("fake")
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "registry.csv")
        write_registry_csv(path, generate_nodes(scheme, addresses))
        registry = registry_from_records(read_registry_csv(path), scheme)

    nets, collectors = [], []
    peers = [registry.identity(i) for i in range(n_peers)]
    try:
        for i in range(n_peers):
            net = _make_network(kind, addresses[i])
            col = _Collector(expect=n_peers - 1)
            net.register_listener(col)
            await net.start()
            nets.append(net)
            collectors.append(col)
        # datagrams can race the receiving endpoints; resend until heard
        # (wait_for, not asyncio.timeout: the latter is 3.11-only and this
        # module is the last thing keeping the package off 3.10)
        async def resend_until_heard():
            while not all(c.done.is_set() for c in collectors):
                for i, (net, col) in enumerate(zip(nets, collectors)):
                    if not col.done.is_set():
                        others = [p for j, p in enumerate(peers) if j != i]
                        net.send(others, Packet(origin=i, level=1, multisig=b"hello"))
                await asyncio.sleep(0.05)

        await asyncio.wait_for(resend_until_heard(), timeout)
    finally:
        for net in nets:
            net.stop()
        await asyncio.sleep(0)
    return {i: col.origins for i, col in enumerate(collectors)}


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    kind = sys.argv[2] if len(sys.argv) > 2 else "udp"
    heard = asyncio.run(run_demo(n, kind))
    for i, origins in heard.items():
        print(f"peer {i}: heard from {sorted(origins)}")


if __name__ == "__main__":
    main()

"""Deterministic fault-injection transport wrapper.

Handel's whole claim is byzantine tolerance, but a transport that only ever
delivers perfectly cannot exercise it. `ChaosNetwork` wraps ANY `Network`
implementation (UDP/TCP/QUIC sockets or the in-process router,
core/test_harness.py) and injects seeded per-link faults on the outbound
path:

  drop       the datagram vanishes (loss)
  corrupt    1-3 bytes of the payload are flipped — the receiver sees
             either an unparseable packet or a parseable-but-invalid
             signature, exercising both rejection paths
  duplicate  the datagram is delivered twice (dedup-cache fodder)
  delay      delivery is deferred by delay_ms ± jitter
  reorder    the datagram is held back and released after the NEXT send to
             the same link (with a flush timer so a quiet link cannot
             strand it)

Determinism: each (seed, destination address) link gets its own
`random.Random`, so fault placement depends only on the configured seed and
each link's own traffic order — never on cross-link interleaving or wall
time. The same seed reproduces the same fault pattern run over run, which
is what lets the chaos integration tests assert convergence instead of
flakiness (tests/test_chaos.py).

Counters ride the monitor plane through `values()`, merged over the inner
transport's own counters.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, replace
from typing import Sequence

from handel_tpu.core.identity import Identity
from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.net import Listener, Packet
from handel_tpu.core.trace import LogHistogram

# how long a reordered (held-back) packet may wait for the next send to its
# link before a timer flushes it anyway
REORDER_FLUSH_S = 0.05


@dataclass
class ChaosConfig:
    """Per-link fault rates (each in [0, 1]) + the determinism seed."""

    drop_rate: float = 0.0
    corrupt_rate: float = 0.0
    duplicate_rate: float = 0.0
    reorder_rate: float = 0.0
    delay_rate: float = 0.0
    delay_ms: float = 0.0
    delay_jitter_ms: float = 0.0
    seed: int = 0

    def any(self) -> bool:
        return any(
            r > 0.0
            for r in (
                self.drop_rate,
                self.corrupt_rate,
                self.duplicate_rate,
                self.reorder_rate,
                self.delay_rate,
            )
        )

    def validate(self) -> "ChaosConfig":
        for name in (
            "drop_rate",
            "corrupt_rate",
            "duplicate_rate",
            "reorder_rate",
            "delay_rate",
        ):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"chaos {name} must be in [0, 1], got {v}")
        return self

    def for_node(self, node_id: int) -> "ChaosConfig":
        """Derive a node-local config: same rates, node-unique seed — so
        every node's links fault independently but deterministically."""
        return replace(self, seed=self.seed * 1_000_003 + node_id)


class ChaosNetwork:
    """`Network` implementing seeded fault injection over an inner transport."""

    def __init__(
        self,
        inner,
        config: ChaosConfig,
        logger: Logger = DEFAULT_LOGGER,
    ):
        self.inner = inner
        self.cfg = config.validate()
        self.log = logger
        self._rngs: dict[str, random.Random] = {}
        self._held: dict[str, tuple[Identity, Packet]] = {}  # reorder slots
        # fault counters (monitor plane)
        self.dropped = 0
        self.corrupted = 0
        self.duplicated = 0
        self.delayed = 0
        self.reordered = 0
        # sampled-delay distribution (ms): delays were the one effect class
        # with no counter beyond a count — the histogram puts the injected
        # latency on the monitor plane (`net_delayMs_p50/_p90/_p99` CSV
        # columns, `sim watch`, trace reports). GeoNetwork records its
        # per-link WAN delays into the same histogram.
        self.hist_delay = LogHistogram()

    # -- lifecycle / listener passthrough -----------------------------------

    async def start(self) -> None:
        start = getattr(self.inner, "start", None)
        if start is not None:
            await start()

    def stop(self) -> None:
        # flush anything still held back so no packet is silently eaten by
        # teardown (the counters already recorded the reorder)
        for addr in list(self._held):
            self._flush_held(addr)
        stop = getattr(self.inner, "stop", None)
        if stop is not None:
            stop()

    def register_listener(self, listener: Listener) -> None:
        self.inner.register_listener(listener)

    # -- outbound fault pipeline ---------------------------------------------

    def send(self, identities: Sequence[Identity], packet: Packet) -> None:
        for ident in identities:
            self._send_one(ident, packet)

    def _rng(self, addr: str) -> random.Random:
        rng = self._rngs.get(addr)
        if rng is None:
            # string seeds hash through SHA-512 inside random.Random — stable
            # across processes and PYTHONHASHSEED values
            rng = random.Random(f"{self.cfg.seed}|{addr}")
            self._rngs[addr] = rng
        return rng

    def _send_one(self, ident: Identity, packet: Packet) -> None:
        cfg = self.cfg
        rng = self._rng(ident.address)

        if cfg.drop_rate and rng.random() < cfg.drop_rate:
            self.dropped += 1
            return
        if cfg.corrupt_rate and rng.random() < cfg.corrupt_rate:
            packet = self._corrupt(packet, rng)
            self.corrupted += 1
        copies = 1
        if cfg.duplicate_rate and rng.random() < cfg.duplicate_rate:
            copies = 2
            self.duplicated += 1

        for _ in range(copies):
            if cfg.reorder_rate and rng.random() < cfg.reorder_rate:
                self._hold(ident, packet)
                continue
            if cfg.delay_rate and rng.random() < cfg.delay_rate:
                delay_ms = cfg.delay_ms
                if cfg.delay_jitter_ms:
                    delay_ms += rng.uniform(
                        -cfg.delay_jitter_ms, cfg.delay_jitter_ms
                    )
                self.delayed += 1
                delay_ms = max(0.0, delay_ms)
                self.hist_delay.add(delay_ms)
                self._later(delay_ms / 1000.0, ident, packet)
                continue
            self._deliver(ident, packet)
            # a prior held-back packet is released AFTER this newer one:
            # that is the reorder
            self._flush_held(ident.address)

    def _deliver(self, ident: Identity, packet: Packet) -> None:
        self.inner.send([ident], packet)

    def _later(self, delay_s: float, ident: Identity, packet: Packet) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:  # no loop (sync test caller): deliver now
            self._deliver(ident, packet)
            return
        loop.call_later(delay_s, self._deliver, ident, packet)

    def _hold(self, ident: Identity, packet: Packet) -> None:
        self._flush_held(ident.address)  # at most one held packet per link
        self._held[ident.address] = (ident, packet)
        self.reordered += 1
        self._later_flush(ident.address)

    def _later_flush(self, addr: str) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._flush_held(addr)
            return
        loop.call_later(REORDER_FLUSH_S, self._flush_held, addr)

    def _flush_held(self, addr: str) -> None:
        held = self._held.pop(addr, None)
        if held is not None:
            self._deliver(*held)

    def _corrupt(self, packet: Packet, rng: random.Random) -> Packet:
        """Flip 1-3 bytes across the payload fields of a COPY — the original
        may be aliased by other destinations' deliveries."""
        ms = bytearray(packet.multisig)
        ind = bytearray(packet.individual_sig or b"")
        total = len(ms) + len(ind)
        if total == 0:
            return packet
        for _ in range(rng.randint(1, 3)):
            pos = rng.randrange(total)
            if pos < len(ms):
                ms[pos] ^= 1 << rng.randrange(8)
            else:
                ind[pos - len(ms)] ^= 1 << rng.randrange(8)
        return Packet(
            origin=packet.origin,
            level=packet.level,
            multisig=bytes(ms),
            individual_sig=bytes(ind) if ind else packet.individual_sig,
        )

    # -- reporter -------------------------------------------------------------

    def values(self) -> dict[str, float]:
        out = {
            "chaosDropped": float(self.dropped),
            "chaosCorrupted": float(self.corrupted),
            "chaosDuplicated": float(self.duplicated),
            "chaosDelayed": float(self.delayed),
            "chaosReordered": float(self.reordered),
        }
        if hasattr(self.inner, "values"):
            out.update(self.inner.values())
        return out

    def histograms(self) -> dict[str, LogHistogram]:
        """Distribution measures for the monitor's histogram plane
        (sim/monitor.py HistogramIO under the `net` plane name)."""
        out = {"delayMs": self.hist_delay}
        if hasattr(self.inner, "histograms"):
            out.update(self.inner.histograms())
        return out

"""Secure session transport — the QUIC slot of the transport matrix.

Reference: network/quic/net.go:22-139 (session-per-peer transport with a TLS
config), sessionmanager.go:11-93 (session cache + dedup of concurrent dials
to the same peer via an isWaiting set), dialer.go (pluggable dialer), and
config.go:14-71 (`NewInsecureTestConfig` — self-signed cert, verification
skipped).

No QUIC stack is available in this environment, so the same component is
built on TLS-over-TCP: what the reference gets from QUIC (an authenticated,
encrypted, session-oriented channel with cheap per-peer session reuse) maps
to cached TLS streams; the session manager, dialer seam, and insecure test
config are ported semantically. Packets are length-prefixed on the stream
like network/tcp.py.
"""

from __future__ import annotations

import asyncio
import os
import ssl
import tempfile
from typing import Callable, Sequence

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.net import Listener, Packet
from handel_tpu.network.encoding import BinaryEncoding, Encoding
from handel_tpu.network.stream import (
    Session,
    SessionManager,
    TaskSet,
    frame,
    read_frames,
)
from handel_tpu.network.udp import split_addr

# back-compat aliases: the session machinery moved to network/stream.py so
# the TCP transport can share it
_Session = Session


def new_insecure_test_config() -> tuple[ssl.SSLContext, ssl.SSLContext]:
    """(server_ctx, client_ctx) with a fresh self-signed certificate and
    client verification disabled (quic/config.go:14-71
    `NewInsecureTestConfig`). Test/simulation use only."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    import datetime

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "handel-tpu")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .sign(key, hashes.SHA256())
    )
    with tempfile.NamedTemporaryFile(suffix=".pem", delete=False) as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
        path = f.name
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    try:
        server_ctx.load_cert_chain(path)
    finally:
        os.unlink(path)  # key material must not linger on disk
    client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client_ctx.check_hostname = False
    client_ctx.verify_mode = ssl.CERT_NONE
    return server_ctx, client_ctx


class QUICNetwork:
    """Session-oriented secure Network (network/quic/net.go:22-139).

    `server_ctx`/`client_ctx` default to the insecure test config; pass real
    SSL contexts for deployment."""

    def __init__(
        self,
        listen_addr: str,
        encoding: Encoding | None = None,
        logger: Logger = DEFAULT_LOGGER,
        server_ctx: ssl.SSLContext | None = None,
        client_ctx: ssl.SSLContext | None = None,
    ):
        self.listen_addr = listen_addr
        self.enc = encoding or BinaryEncoding()
        self.log = logger
        self.listeners: list[Listener] = []
        if server_ctx is None or client_ctx is None:
            server_ctx, client_ctx = new_insecure_test_config()
        self._server_ctx = server_ctx
        self._client_ctx = client_ctx
        self._server: asyncio.Server | None = None
        self.sessions = SessionManager(self._dial)
        self._tasks = TaskSet()
        self.sent = 0
        self.rcvd = 0

    async def start(self) -> None:
        host, port = split_addr(self.listen_addr)
        self._server = await asyncio.start_server(
            self._handle_conn, "0.0.0.0", port, ssl=self._server_ctx
        )

    def stop(self) -> None:
        if self._server:
            self._server.close()
        self._tasks.cancel_all()
        self.sessions.close_all()

    # -- dialer seam (quic/dialer.go) ---------------------------------------

    async def _dial(self, addr: str) -> _Session:
        host, port = split_addr(addr)
        _, writer = await asyncio.open_connection(
            host, port, ssl=self._client_ctx
        )
        return Session(writer)

    # -- inbound ------------------------------------------------------------

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        def count():
            self.rcvd += 1

        try:
            await read_frames(
                reader, self.enc, self.listeners, self.log, "quic", count
            )
        finally:
            writer.close()

    # -- outbound -----------------------------------------------------------

    def send(self, identities: Sequence["Identity"], packet: Packet) -> None:  # noqa: F821
        framed = frame(self.enc.encode(packet))
        for ident in identities:
            self._tasks.spawn(self._send_to(ident.address, framed))

    async def _send_to(self, addr: str, framed: bytes) -> None:
        try:
            ses = await self.sessions.session(addr)
            ses.writer.write(framed)
            await ses.writer.drain()
            self.sent += 1
        except (OSError, ssl.SSLError) as e:
            self.log.warn("quic_send", f"{addr}: {e}")
            self.sessions.drop(addr)

    def register_listener(self, listener: Listener) -> None:
        self.listeners.append(listener)

    def values(self) -> dict[str, float]:
        out = {"sentPackets": float(self.sent), "rcvdPackets": float(self.rcvd)}
        if hasattr(self.enc, "values"):
            out.update(self.enc.values())
        return out

"""Pluggable wire codecs + the byte-counting decorator.

Reference: network/wireencoding.go:10-13 (`Encoding` interface), the gob codec
(network/gobEncoding.go:10-32) — replaced by the fixed binary layout from
core/net.py (language-neutral, constant-time parse) — and the monitor-facing
byte counter (network/counter_encoding.go:13-63).
"""

from __future__ import annotations

from typing import Protocol

from handel_tpu.core.net import Packet


class Encoding(Protocol):
    """Packet <-> bytes codec (wireencoding.go:10-13)."""

    def encode(self, packet: Packet) -> bytes: ...

    def decode(self, data: bytes) -> Packet: ...


class BinaryEncoding:
    """The default fixed-layout codec (core/net.py Packet.encode/decode)."""

    def encode(self, packet: Packet) -> bytes:
        return packet.encode()

    def decode(self, data: bytes) -> Packet:
        return Packet.decode(data)


class CounterEncoding:
    """Decorator counting encoded/decoded bytes for the monitor plane
    (counter_encoding.go:13-63). Exposes `values()` in the Reporter shape."""

    def __init__(self, inner: Encoding | None = None):
        self.inner = inner or BinaryEncoding()
        self.sent_bytes = 0
        self.rcvd_bytes = 0

    def encode(self, packet: Packet) -> bytes:
        data = self.inner.encode(packet)
        self.sent_bytes += len(data)
        return data

    def decode(self, data: bytes) -> Packet:
        packet = self.inner.decode(data)
        self.rcvd_bytes += len(data)
        return packet

    def values(self) -> dict[str, float]:
        return {
            "sentBytes": float(self.sent_bytes),
            "rcvdBytes": float(self.rcvd_bytes),
        }

"""TCP transport with connection caching.

Reference: network/tcp/net.go:16-127 — a listener accepting length-delimited
packet streams, lazy dial-on-send with a per-peer connection cache, and a
1-minute idle deadline.

asyncio redesign: an asyncio.Server per node; outbound writers are cached per
peer address and dropped on error (next send re-dials). Packets on the stream
are length-prefixed (uint32) since TCP has no message boundaries.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Sequence

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.net import Listener, Packet
from handel_tpu.network.encoding import Encoding, BinaryEncoding
from handel_tpu.network.udp import split_addr

_LEN = struct.Struct(">I")
IDLE_TIMEOUT = 60.0  # reference's 1-minute conn deadline (tcp/net.go:100)


class TCPNetwork:
    """Stream-based Network with cached outbound connections."""

    def __init__(
        self,
        listen_addr: str,
        encoding: Encoding | None = None,
        logger: Logger = DEFAULT_LOGGER,
    ):
        self.listen_addr = listen_addr
        self.enc = encoding or BinaryEncoding()
        self.log = logger
        self.listeners: list[Listener] = []
        self._server: asyncio.Server | None = None
        self._writers: dict[str, asyncio.StreamWriter] = {}
        self.sent = 0
        self.rcvd = 0

    async def start(self) -> None:
        host, port = split_addr(self.listen_addr)
        self._server = await asyncio.start_server(
            self._handle_conn, "0.0.0.0", port
        )

    def stop(self) -> None:
        if self._server:
            self._server.close()
        for w in self._writers.values():
            w.close()
        self._writers.clear()

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                hdr = await asyncio.wait_for(
                    reader.readexactly(_LEN.size), IDLE_TIMEOUT
                )
                (size,) = _LEN.unpack(hdr)
                data = await reader.readexactly(size)
                try:
                    packet = self.enc.decode(data)
                except Exception as e:
                    self.log.warn("tcp_decode", e)
                    continue
                self.rcvd += 1
                for lst in self.listeners:
                    lst.new_packet(packet)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError, OSError):
            pass
        finally:
            writer.close()

    def send(self, identities: Sequence["Identity"], packet: Packet) -> None:  # noqa: F821
        wire = self.enc.encode(packet)
        framed = _LEN.pack(len(wire)) + wire
        for ident in identities:
            asyncio.get_running_loop().create_task(
                self._send_to(ident.address, framed)
            )

    async def _send_to(self, addr: str, framed: bytes) -> None:
        writer = self._writers.get(addr)
        if writer is None or writer.is_closing():
            host, port = split_addr(addr)
            try:
                _, writer = await asyncio.open_connection(host, port)
            except OSError as e:
                self.log.warn("tcp_dial", f"{addr}: {e}")
                return
            self._writers[addr] = writer
        try:
            writer.write(framed)
            await writer.drain()
            self.sent += 1
        except OSError as e:
            self.log.warn("tcp_send", f"{addr}: {e}")
            self._writers.pop(addr, None)

    def register_listener(self, listener: Listener) -> None:
        self.listeners.append(listener)

    def values(self) -> dict[str, float]:
        out = {"sentPackets": float(self.sent), "rcvdPackets": float(self.rcvd)}
        if hasattr(self.enc, "values"):
            out.update(self.enc.values())
        return out

"""TCP transport with connection caching.

Reference: network/tcp/net.go:16-127 — a listener accepting length-delimited
packet streams, lazy dial-on-send with a per-peer connection cache, and a
1-minute idle deadline.

asyncio redesign: an asyncio.Server per node; outbound writers are cached per
peer address and dropped on error (next send re-dials). Concurrent sends to a
not-yet-connected peer share one in-flight dial (the same dedup the reference
gives QUIC a session manager for). Framing/read-loop/task bookkeeping live in
network/stream.py, shared with the TLS transport.
"""

from __future__ import annotations

import asyncio
from typing import Sequence

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.net import Listener, Packet
from handel_tpu.network.encoding import Encoding, BinaryEncoding
from handel_tpu.network.stream import TaskSet, frame, read_frames
from handel_tpu.network.udp import split_addr


class TCPNetwork:
    """Stream-based Network with cached outbound connections."""

    def __init__(
        self,
        listen_addr: str,
        encoding: Encoding | None = None,
        logger: Logger = DEFAULT_LOGGER,
    ):
        self.listen_addr = listen_addr
        self.enc = encoding or BinaryEncoding()
        self.log = logger
        self.listeners: list[Listener] = []
        self._server: asyncio.Server | None = None
        self._writers: dict[str, asyncio.StreamWriter] = {}
        self._dialing: dict[str, asyncio.Future] = {}  # dedup in-flight dials
        self._tasks = TaskSet()
        self.sent = 0
        self.rcvd = 0

    async def start(self) -> None:
        host, port = split_addr(self.listen_addr)
        self._server = await asyncio.start_server(
            self._handle_conn, "0.0.0.0", port
        )

    def stop(self) -> None:
        if self._server:
            self._server.close()
        self._tasks.cancel_all()
        for w in self._writers.values():
            w.close()
        self._writers.clear()

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        def count():
            self.rcvd += 1

        try:
            await read_frames(
                reader, self.enc, self.listeners, self.log, "tcp", count
            )
        finally:
            writer.close()

    def send(self, identities: Sequence["Identity"], packet: Packet) -> None:  # noqa: F821
        framed = frame(self.enc.encode(packet))
        for ident in identities:
            self._tasks.spawn(self._send_to(ident.address, framed))

    async def _writer_for(self, addr: str) -> asyncio.StreamWriter | None:
        writer = self._writers.get(addr)
        if writer is not None and not writer.is_closing():
            return writer
        fut = self._dialing.get(addr)
        if fut is not None:  # piggyback on the in-flight dial
            return await asyncio.shield(fut)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._dialing[addr] = fut
        try:
            host, port = split_addr(addr)
            _, writer = await asyncio.open_connection(host, port)
        except OSError as e:
            self.log.warn("tcp_dial", f"{addr}: {e}")
            if not fut.done():
                fut.set_result(None)
            return None
        finally:
            self._dialing.pop(addr, None)
        self._writers[addr] = writer
        if not fut.done():
            fut.set_result(writer)
        return writer

    async def _send_to(self, addr: str, framed: bytes) -> None:
        writer = await self._writer_for(addr)
        if writer is None:
            return
        try:
            writer.write(framed)
            await writer.drain()
            self.sent += 1
        except OSError as e:
            self.log.warn("tcp_send", f"{addr}: {e}")
            self._writers.pop(addr, None)

    def register_listener(self, listener: Listener) -> None:
        self.listeners.append(listener)

    def values(self) -> dict[str, float]:
        out = {"sentPackets": float(self.sent), "rcvdPackets": float(self.rcvd)}
        if hasattr(self.enc, "values"):
            out.update(self.enc.values())
        return out

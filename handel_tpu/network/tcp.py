"""TCP transport with connection caching.

Reference: network/tcp/net.go:16-127 — a listener accepting length-delimited
packet streams, lazy dial-on-send with a per-peer connection cache, and a
1-minute idle deadline.

asyncio redesign: an asyncio.Server per node; outbound connections are cached
per peer as Sessions behind the shared SessionManager (network/stream.py),
which also dedups concurrent dials to a not-yet-connected peer — the same
machinery the TLS transport uses, with a plain-TCP dialer plugged into the
dialer seam. Framing/read-loop/task bookkeeping also live in stream.py.
"""

from __future__ import annotations

import asyncio
from typing import Sequence

from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.net import Listener, Packet
from handel_tpu.network.encoding import Encoding, BinaryEncoding
from handel_tpu.network.stream import (
    Session,
    SessionManager,
    TaskSet,
    frame,
    read_frames,
)
from handel_tpu.network.udp import split_addr


class TCPNetwork:
    """Stream-based Network with cached outbound connections."""

    def __init__(
        self,
        listen_addr: str,
        encoding: Encoding | None = None,
        logger: Logger = DEFAULT_LOGGER,
    ):
        self.listen_addr = listen_addr
        self.enc = encoding or BinaryEncoding()
        self.log = logger
        self.listeners: list[Listener] = []
        self._server: asyncio.Server | None = None
        self.sessions = SessionManager(self._dial)
        self._tasks = TaskSet()
        self.sent = 0
        self.rcvd = 0

    async def start(self) -> None:
        host, port = split_addr(self.listen_addr)
        self._server = await asyncio.start_server(
            self._handle_conn, "0.0.0.0", port
        )

    def stop(self) -> None:
        if self._server:
            self._server.close()
        self._tasks.cancel_all()
        self.sessions.close_all()

    async def _dial(self, addr: str) -> Session:
        host, port = split_addr(addr)
        _, writer = await asyncio.open_connection(host, port)
        return Session(writer)

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        def count():
            self.rcvd += 1

        try:
            await read_frames(
                reader, self.enc, self.listeners, self.log, "tcp", count
            )
        finally:
            writer.close()

    def send(self, identities: Sequence["Identity"], packet: Packet) -> None:  # noqa: F821
        framed = frame(self.enc.encode(packet))
        for ident in identities:
            self._tasks.spawn(self._send_to(ident.address, framed))

    async def _send_to(self, addr: str, framed: bytes) -> None:
        try:
            ses = await self.sessions.session(addr)
            ses.writer.write(framed)
            await ses.writer.drain()
            self.sent += 1
        except OSError as e:
            self.log.warn("tcp_send", f"{addr}: {e}")
            self.sessions.drop(addr)

    def register_listener(self, listener: Listener) -> None:
        self.listeners.append(listener)

    def values(self) -> dict[str, float]:
        out = {"sentPackets": float(self.sent), "rcvdPackets": float(self.rcvd)}
        if hasattr(self.enc, "values"):
            out.update(self.enc.values())
        return out

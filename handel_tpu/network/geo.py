"""Geo-latency planet model: region-aware WAN delay over any transport.

`ChaosNetwork` injects *faults* — uniform, link-agnostic drop/corrupt/delay
rates. A planet is not uniform: Frankfurt<->Zurich is 8 ms while
Sydney<->Sao Paulo is 320 ms, and Handel's level schedule interacts with
that asymmetry (close peers complete low levels long before far peers can
contribute). `GeoNetwork` generalizes the chaos wrapper with a
region-to-region RTT matrix:

  - every node is assigned a region (round-robin by id unless the scenario
    pins an explicit assignment),
  - every outbound packet samples a one-way delay from the (src region,
    dst region) entry — RTT/2 plus Gaussian jitter — on the same
    per-(seed, destination) `random.Random` discipline ChaosNetwork uses,
    so a seed reproduces the same planet run over run,
  - chaos faults COMPOSE on top: GeoNetwork subclasses ChaosNetwork and
    adds its WAN delay at the `_deliver` stage, after the fault pipeline
    (a chaos-delayed packet pays chaos delay, then WAN delay).

Sampled delays ride the shared `net_delayMs` histogram plus a `geoDelayed`
counter, so `sim watch` and trace reports see the injected WAN latency.
The node's own region is tagged onto every trace span via Config.region
(core/handel.py), which is what lets the critical-path analyzer attribute
hops to region pairs (sim/trace_cli.py).

Presets ("planet-3region", "planet-5region") live in
handel_tpu/scenario/planets.py; this module is pure mechanism.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

from handel_tpu.core.identity import Identity
from handel_tpu.core.logging import DEFAULT_LOGGER, Logger
from handel_tpu.core.net import Packet
from handel_tpu.network.chaos import ChaosConfig, ChaosNetwork


@dataclass
class GeoConfig:
    """Planet model: named regions, a symmetric RTT matrix between them,
    and this node's own placement."""

    # region names, indexing rtt_ms rows/cols
    regions: Sequence[str] = ()
    # rtt_ms[i][j] = round-trip ms between regions i and j (0 on diagonal)
    rtt_ms: Sequence[Sequence[float]] = ()
    # Gaussian jitter (std dev, ms) added to each sampled one-way delay
    jitter_ms: float = 0.0
    seed: int = 0
    # this node's id — picks its region
    node_id: int = 0
    # explicit node-id -> region-index pinning; ids not present fall back
    # to round-robin (id % len(regions))
    assignment: dict[int, int] = field(default_factory=dict)

    def region_index(self, node_id: int) -> int:
        idx = self.assignment.get(node_id)
        if idx is None:
            idx = node_id % len(self.regions)
        return idx

    def region_of(self, node_id: int) -> str:
        return self.regions[self.region_index(node_id)]

    def _resolve_region(self, r: "str | int") -> int:
        if isinstance(r, str):
            try:
                return list(self.regions).index(r)
            except ValueError:
                raise ValueError(
                    f"unknown region {r!r} "
                    f"(known: {', '.join(self.regions)})"
                ) from None
        if not 0 <= r < len(self.regions):
            raise ValueError(
                f"region index {r} out of range "
                f"(0..{len(self.regions) - 1})"
            )
        return int(r)

    def rtt(self, a: "str | int", b: "str | int") -> float:
        """Region-to-region round-trip ms, by name or index — the public
        lookup the front door (service/federation.py) routes by, so
        callers never index the matrix representation directly."""
        return float(self.rtt_ms[self._resolve_region(a)][self._resolve_region(b)])

    def validate(self) -> "GeoConfig":
        n = len(self.regions)
        if n == 0:
            raise ValueError("geo config needs at least one region")
        if len(self.rtt_ms) != n or any(len(row) != n for row in self.rtt_ms):
            raise ValueError(
                f"geo rtt_ms must be a {n}x{n} matrix matching regions"
            )
        for i, row in enumerate(self.rtt_ms):
            for j, v in enumerate(row):
                if v < 0:
                    raise ValueError(f"geo rtt_ms[{i}][{j}] negative: {v}")
        if self.jitter_ms < 0:
            raise ValueError("geo jitter_ms must be >= 0")
        for nid, idx in self.assignment.items():
            if not 0 <= idx < n:
                raise ValueError(
                    f"geo assignment pins node {nid} to region {idx}, "
                    f"but only {n} regions exist"
                )
        return self

    def for_node(self, node_id: int) -> "GeoConfig":
        """Node-local view: same planet, this node's placement, and a
        node-unique seed (same derivation as ChaosConfig.for_node)."""
        return replace(
            self, node_id=node_id, seed=self.seed * 1_000_003 + node_id
        )


class GeoNetwork(ChaosNetwork):
    """ChaosNetwork + region-pair WAN delay on every delivery."""

    def __init__(
        self,
        inner,
        geo: GeoConfig,
        chaos: Optional[ChaosConfig] = None,
        logger: Logger = DEFAULT_LOGGER,
    ):
        super().__init__(inner, chaos or ChaosConfig(), logger=logger)
        self.geo = geo.validate()
        self._src_region = geo.region_index(geo.node_id)
        # geo draws get their own rng streams so enabling the planet model
        # never perturbs the chaos fault placement for a given seed
        self._geo_rngs: dict[str, random.Random] = {}
        self.geo_delayed = 0

    @property
    def region(self) -> str:
        return self.geo.regions[self._src_region]

    # -- delay model ---------------------------------------------------------

    def _geo_rng(self, addr: str) -> random.Random:
        rng = self._geo_rngs.get(addr)
        if rng is None:
            rng = random.Random(f"geo|{self.geo.seed}|{addr}")
            self._geo_rngs[addr] = rng
        return rng

    def sample_delay_ms(self, ident: Identity) -> float:
        dst = self.geo.region_index(ident.id)
        one_way = self.geo.rtt_ms[self._src_region][dst] / 2.0
        if self.geo.jitter_ms:
            one_way += self._geo_rng(ident.address).gauss(
                0.0, self.geo.jitter_ms
            )
        return max(0.0, one_way)

    # -- delivery override ---------------------------------------------------

    def _deliver(self, ident: Identity, packet: Packet) -> None:
        """Every delivery — direct, chaos-delayed, or reorder-flushed —
        funnels through here, so WAN delay composes after any fault."""
        delay_ms = self.sample_delay_ms(ident)
        if delay_ms <= 0.0:
            self.inner.send([ident], packet)
            return
        self.geo_delayed += 1
        self.hist_delay.add(delay_ms)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:  # no loop (sync test caller): deliver now
            self.inner.send([ident], packet)
            return
        # schedule inner.send directly — NOT self._later, which would
        # re-enter this override and sample the delay twice
        loop.call_later(delay_ms / 1000.0, self.inner.send, [ident], packet)

    # -- reporter -------------------------------------------------------------

    def values(self) -> dict[str, float]:
        out = {"geoDelayed": float(self.geo_delayed)}
        out.update(super().values())
        return out

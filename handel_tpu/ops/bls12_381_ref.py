"""Pure-Python BLS12-381: fields, curves, optimal ate pairing (scalar oracle).

Second curve behind the same Constructor interface — the BLS12-381 slot the
reference's curve registry leaves open (simul/lib/config.go:211-225 dispatches
curve names; BASELINE.json lists bls12-381 configs). Same shape as
ops/bn254_ref.py: plain ints, clarity over speed, used as the ground truth
for device kernels and as a host scheme.

Curve family differences vs BN254 that this module encodes:
  * p, r from the BLS12 parameterization z = -0xd201000000010000:
      p = (z-1)^2 (z^4 - z^2 + 1)/3 + z,  r = z^4 - z^2 + 1
  * Fp2 = Fp[i]/(i^2+1); Fp6 = Fp2[v]/(v^3 - xi), xi = 1 + i;
    Fp12 = Fp6[w]/(w^2 - v)
  * E:  y^2 = x^3 + 4;   E'(Fp2): y^2 = x^3 + 4(1+i)  (M-type twist)
  * ate loop count = |z| (no correction lines — plain Miller over z bits),
    with a final conjugation because z < 0.

Keys in G2 (96-byte pubkeys), signatures in G1 (48-byte) — the same
minimal-signature orientation as the BN254 scheme here.
"""

from __future__ import annotations

# -- parameters -------------------------------------------------------------

Z = -0xD201000000010000  # BLS parameter (negative)
P = (
    0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

assert P == (Z - 1) ** 2 * (Z**4 - Z**2 + 1) // 3 + Z
assert R == Z**4 - Z**2 + 1
assert P % 4 == 3

B = 4  # E: y^2 = x^3 + 4

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)


# -- Fp2 = Fp[i]/(i^2+1) ----------------------------------------------------

F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (1, 1)  # Fp6 non-residue


def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def f2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    return ((a0 * b0 - a1 * b1) % P, (a0 * b1 + a1 * b0) % P)


def f2_sqr(a):
    a0, a1 = a
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def f2_scalar(a, k):
    return (a[0] * k % P, a[1] * k % P)


def f2_conj(a):
    return (a[0], (-a[1]) % P)


def f2_inv(a):
    a0, a1 = a
    den = pow(a0 * a0 + a1 * a1, -1, P)
    return (a0 * den % P, (-a1) * den % P)


def f2_mul_xi(a):
    """(1+i)(a0 + a1 i) = (a0 - a1) + (a0 + a1) i."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


# -- Fp6 / Fp12 (same tower construction as bn254_ref, xi differs) ----------


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_neg(a):
    return tuple(f2_neg(x) for x in a)


def f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0, t1, t2 = f2_mul(a0, b0), f2_mul(a1, b1), f2_mul(a2, b2)
    c0 = f2_add(
        t0,
        f2_mul_xi(
            f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2))
        ),
    )
    c1 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)),
        f2_mul_xi(t2),
    )
    c2 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)), t1
    )
    return (c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_mul_v(a):
    return (f2_mul_xi(a[2]), a[0], a[1])


def f6_inv(a):
    a0, a1, a2 = a
    t0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    t1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    t2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    den = f2_add(
        f2_mul(a0, t0), f2_mul_xi(f2_add(f2_mul(a2, t1), f2_mul(a1, t2)))
    )
    inv = f2_inv(den)
    return (f2_mul(t0, inv), f2_mul(t1, inv), f2_mul(t2, inv))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_v(t1))
    c1 = f6_sub(f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), t0), t1)
    return (c0, c1)


def f12_sqr(a):
    return f12_mul(a, a)


def f12_conj(a):
    return (a[0], f6_neg(a[1]))


def f12_inv(a):
    a0, a1 = a
    den = f6_inv(f6_sub(f6_sqr(a0), f6_mul_v(f6_sqr(a1))))
    return (f6_mul(a0, den), f6_neg(f6_mul(a1, den)))


def f12_pow(a, e):
    result = F12_ONE
    base = a
    while e:
        if e & 1:
            result = f12_mul(result, base)
        base = f12_sqr(base)
        e >>= 1
    return result


F12_ZERO = (F6_ZERO, F6_ZERO)
F12_ONE = (F6_ONE, F6_ZERO)


def _f2_pow(a, e):
    result = F2_ONE
    base = a
    while e:
        if e & 1:
            result = f2_mul(result, base)
        base = f2_sqr(base)
        e >>= 1
    return result


_GAMMA = [None] + [_f2_pow(XI, j * (P - 1) // 6) for j in range(1, 6)]


def f12_frobenius(a):
    (c00, c01, c02), (c10, c11, c12) = a
    return (
        (
            f2_conj(c00),
            f2_mul(f2_conj(c01), _GAMMA[2]),
            f2_mul(f2_conj(c02), _GAMMA[4]),
        ),
        (
            f2_mul(f2_conj(c10), _GAMMA[1]),
            f2_mul(f2_conj(c11), _GAMMA[3]),
            f2_mul(f2_conj(c12), _GAMMA[5]),
        ),
    )


def f12_frobenius2(a):
    return f12_frobenius(f12_frobenius(a))


# -- curves (generic affine ops shared with bn254_ref) ----------------------

from handel_tpu.ops.bn254_ref import _FieldOps, pt_add, pt_is_on_curve, pt_mul, pt_neg

FP_OPS = _FieldOps(
    lambda a, b: (a + b) % P,
    lambda a, b: (a - b) % P,
    lambda a, b: a * b % P,
    lambda a: a * a % P,
    lambda a: pow(a, -1, P),
    lambda a: (-a) % P,
    lambda a, k: a * k % P,
    0,
    1,
)
F2_OPS = _FieldOps(
    f2_add, f2_sub, f2_mul, f2_sqr, f2_inv, f2_neg, f2_scalar, F2_ZERO, F2_ONE
)

TWIST_B = f2_scalar(XI, B)  # E' coefficient: 4(1+i), M-type twist


def g1_add(p1, p2):
    return pt_add(FP_OPS, p1, p2)


def g1_mul(pt, k):
    return pt_mul(FP_OPS, pt, k)


def g1_neg(pt):
    return pt_neg(FP_OPS, pt)


def g1_is_valid(pt):
    """On curve and in the order-r subgroup (G1 cofactor is ~2^125)."""
    return pt_is_on_curve(FP_OPS, pt, B) and (
        pt is None or pt_mul(FP_OPS, pt, R) is None
    )


def g2_add(p1, p2):
    return pt_add(F2_OPS, p1, p2)


def g2_mul(pt, k):
    return pt_mul(F2_OPS, pt, k)


def g2_neg(pt):
    return pt_neg(F2_OPS, pt)


def g2_is_valid(pt):
    return pt_is_on_curve(F2_OPS, pt, TWIST_B) and (
        pt is None or g2_mul(pt, R) is None
    )


# -- pairing ----------------------------------------------------------------
#
# M-type twist: the untwist is psi(x', y') = (x' w^-2, y' w^-3). Scaling each
# line by d'·w^3·Z^3 (the w^3 dies in the final exponentiation because
# (w^3)^((p^6-1)(p^2+1)) = (-1)^(p^2+1) = 1; the Fp2 factors die because
# Frobenius^2 fixes Fp2) puts the line coefficients at w-degrees (0, 2, 3):
#
#   doubling at T=(X,Y,Z):   (3X^3 - 2Y^2 Z)  -  3X^2 Z·xp w^2  +  2YZ^2·yp w^3
#   mixed add  T + (x2,y2):  (n x2 - d y2)    -  n·xp w^2       +  d·yp w^3
#
# with n, d the scaled slope numerator/denominator. The point update formulas
# are the generic b-independent projective ones (same as bn254_ref's).


def miller_loop(q, p):
    """f_{|z|,Q}(P) with projective doubling/addition; conjugated at the end
    because z < 0. q on E'(Fp2) affine, p on E(Fp) affine."""
    if q is None or p is None:
        return F12_ONE
    xp, yp = p

    def sparse_line(c0, cw2, cw3):
        # w-degree slots 0 (=1), 2 (=v), 3 (=v*w)
        return ((c0, cw2, F2_ZERO), (F2_ZERO, cw3, F2_ZERO))

    def dbl(T):
        X, Y, Zc = T
        XX = f2_sqr(X)
        YY = f2_sqr(Y)
        YZ = f2_mul(Y, Zc)
        n = f2_scalar(XX, 3)
        d = f2_scalar(YZ, 2)
        XYYZ = f2_mul(f2_mul(X, YY), Zc)
        e = f2_sub(f2_sqr(n), f2_scalar(XYYZ, 8))
        X3 = f2_mul(e, d)
        Y3 = f2_sub(
            f2_mul(n, f2_sub(f2_scalar(XYYZ, 12), f2_sqr(n))),
            f2_scalar(f2_sqr(f2_mul(YY, Zc)), 8),
        )
        Z3 = f2_mul(f2_sqr(d), d)
        cw3 = f2_scalar(f2_mul(f2_mul(YZ, Zc), (yp, 0)), 2)
        cw2 = f2_neg(f2_mul(f2_mul(n, Zc), (xp, 0)))
        c0 = f2_sub(f2_mul(n, X), f2_scalar(f2_mul(YY, Zc), 2))
        return (X3, Y3, Z3), sparse_line(c0, cw2, cw3)

    def add(T, Q2):
        X, Y, Zc = T
        x2, y2 = Q2
        n = f2_sub(f2_mul(y2, Zc), Y)
        d = f2_sub(f2_mul(x2, Zc), X)
        dd = f2_sqr(d)
        x2Z = f2_mul(x2, Zc)
        e = f2_sub(f2_mul(f2_sqr(n), Zc), f2_mul(f2_add(X, x2Z), dd))
        X3 = f2_mul(e, d)
        Y3 = f2_sub(
            f2_mul(n, f2_sub(f2_mul(x2Z, dd), e)),
            f2_mul(f2_mul(y2, Zc), f2_mul(dd, d)),
        )
        Z3 = f2_mul(Zc, f2_mul(dd, d))
        cw3 = f2_mul(d, (yp, 0))
        cw2 = f2_neg(f2_mul(n, (xp, 0)))
        c0 = f2_sub(f2_mul(n, x2), f2_mul(d, y2))
        return (X3, Y3, Z3), sparse_line(c0, cw2, cw3)

    T = (q[0], q[1], F2_ONE)
    f = F12_ONE
    for bit in bin(-Z)[3:]:
        T, line = dbl(T)
        f = f12_mul(f12_sqr(f), line)
        if bit == "1":
            T, line = add(T, q)
            f = f12_mul(f, line)
    # z < 0: f_{z} = 1 / f_{|z|} up to final exp -> conjugate
    return f12_conj(f)


def final_exponentiation_naive(f):
    return f12_pow(f, (P**12 - 1) // R)


def final_exponentiation(f):
    """Easy part + BLS12 hard part via the integer identity

        3·(p^4 - p^2 + 1)/r = (z-1)^2 (z+p) (z^2+p^2-1) + 3

    (verified exactly in tests), i.e. this computes the CUBED ate pairing —
    itself a bilinear non-degenerate pairing since gcd(3, r) = 1, and the
    standard trick for BLS12 final exponentiation. `pairing_check`
    equivalence is unaffected: f^(3·hard) = 1  <=>  f^hard = 1."""
    f = f12_mul(f12_conj(f), f12_inv(f))  # f^(p^6 - 1)
    f = f12_mul(f12_frobenius2(f), f)  # ^(p^2 + 1)

    def pow_z(x):
        # z < 0: x^z = conj(x^|z|) in the cyclotomic subgroup
        return f12_conj(f12_pow(x, -Z))

    t0 = f12_mul(pow_z(f), f12_conj(f))  # f^(z-1)
    t1 = f12_mul(pow_z(t0), f12_conj(t0))  # f^((z-1)^2) = f^A
    g = f12_mul(pow_z(t1), f12_frobenius(t1))  # f^(A(z+p))
    gz2 = pow_z(pow_z(g))  # f^(A(z+p)z^2)
    h = f12_mul(
        f12_mul(gz2, f12_frobenius2(g)), f12_conj(g)
    )  # f^(A(z+p)(z^2+p^2-1))
    return f12_mul(h, f12_mul(f12_sqr(f), f))  # * f^3


def pairing(q, p, fast: bool = True):
    f = miller_loop(q, p)
    return final_exponentiation(f) if fast else final_exponentiation_naive(f)


def pairing_check(pairs) -> bool:
    f = F12_ONE
    for p, q in pairs:
        f = f12_mul(f, miller_loop(q, p))
    return final_exponentiation(f) == F12_ONE

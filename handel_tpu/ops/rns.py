"""Residue-number-system Montgomery multiplication — the MXU-shaped modmul.

The production CIOS kernel (ops/fp.py) is a VPU workload: a 254-bit limb
product is an outer product (contraction depth 1), so the 128x128 systolic
array contributes nothing and the measured 16.7 T int8-ops/s MXU ceiling
(results/fp_microbench.json "mxu_lab") sits idle through every pairing.
RNS restructures the same arithmetic so the heavy steps ARE deep matmul
contractions against constant matrices — the shape the AI-ASIC ZKP
literature targets (PAPERS.md, arxiv 2604.17808; ROADMAP item 1):

  * **Residue mapping.** An operand's 8-bit limb vector (2n, B) maps to
    residues mod k small coprime primes via one constant-matrix contraction
    ``W @ limbs`` with ``W[i, j] = 2^(8j) mod m_i`` — contraction depth 2n
    (32 for BN254), batch B in the other MXU dimension.
  * **Residue-wise product.** Elementwise (k, B) int32 lane multiplies —
    k ≈ 42 small products replace the CIOS kernel's n^2 = 256 limb products
    plus its interleaved reduction columns.
  * **Montgomery step in RNS** (Bajard/Kawamura shape). With base A
    (product M, the RNS Montgomery constant) and base B (product MB):
    q = T·(-p^{-1}) mod M is elementwise in base A; extending q's residues
    to base B is another constant-matrix contraction ``E @ xi`` with
    ``E[j, i] = (M/m_i) mod m_j``; then r = (T + q·p)/M is elementwise in
    base B. The extension is offset-tolerant (q may come out as q + c·M,
    c < k_A): it only shifts r by c·p, absorbed by the final reduction.
  * **Exact CRT reconstruction** (Shenoy–Kumaresan). A redundant channel
    m_r rides the whole pipeline, so the CRT offset alpha in
    r = sum(xi'_j · MB/m_j) - alpha·MB is recovered EXACTLY (alpha < k_B
    <= m_r) — no floating-point base-extension approximation anywhere in
    the value path. Positional limbs come back via a third constant
    contraction against the 8-bit limb decomposition of the MB/m_j.

`RnsField` keeps the public Field contract intact: canonical (< p)
(nlimbs, B) uint32 limbs at every op boundary, so `ops/tower.py`'s
batch-stacking entry points, the curve adapters, and `BN254Device`
dispatch route through unchanged — CRT reconstruction is paid inside
`mul`, i.e. at every call boundary. add/sub/neg/inv/pow/select/eq are
inherited verbatim.

**Resident value form.** That per-mul CRT round trip is the standing
ceiling for the pairing (ROADMAP item 2): the Miller loop never needs
positional limbs between line evaluations, so `mul` repacking at every
tower multiplication is pure overhead. The resident form keeps a value as
its JOINT residue vector — a plain (k_all, B) int32 array, base A rows ++
base B rows ++ the m_r channel — and closes multiplication inside that
representation:

  * `mul_resident` runs the Montgomery steps on the joint residues and
    base-extends the result B -> A (a second Shenoy-exact extension with
    constants `E2[i, j] = (MB/m_j) mod m_i`), so the output is again a
    full joint-residue vector. No positional limbs anywhere.
  * Chained products stay exact because base A is built with
    M >= 2^RES_MUL_LOG2 * p: any product of operands bounded by
    2^la * p and 2^lb * p with la + lb <= RES_MUL_LOG2 keeps T < M*p, so
    r = (T + q_hat*p)/M < (kA+1)*p <= 2^6*p — the loop-invariant output
    bound. `ops/tower.py` threads static per-site bound literals (`blog`)
    through its subtraction sites; HACKING.md "Residue-resident pairing"
    carries the full bound walk.
  * `add_resident`/`sub_resident` are residue-wise; subtraction adds the
    precomputed residues of (p << blog) so the represented value stays
    nonnegative (blog >= the subtrahend's static bound).
  * `to_resident`/`from_resident` convert at genuine boundaries only;
    `from_resident` first refreshes (one `mul_resident` by the Montgomery
    one, resetting any bound <= RES_MUL_LOG2 to < (kA+1)p < MB) and then
    runs the same exact CRT + conditional-subtract ladder as `mul`, so
    canonical boundary limbs remain bit-identical to the CIOS backend.
  * `ResidentRns` (via `RnsField.resident()`) wraps all of this in the
    Field method surface so `Tower.as_resident()` reuses every tower
    formula unchanged; `eq`/`is_zero` raise — comparisons are positional
    boundaries by definition.

The residue<->positional conversion counters (`conversion_counts`)
increment at TRACE time — one count per traced call site, so a
`lax.scan` body counts once however many steps it runs. That is exactly
the right unit for the claim they substantiate (bench.py
`rns_conversions_per_pairing`): per-mul before, per-line-boundary after.

**Montgomery convention.** The backend's Montgomery constant is M (the
base-A product), not the CIOS kernel's R = 2^(16n): division by M is what
the RNS reduction gets for free. `mont_r`/`mont_r2` are overridden
accordingly, so pack/unpack/to_mont/from_mont stay self-consistent and
every *non-Montgomery* boundary value (unpacked results, verify verdicts,
affine coordinates) is bit-identical to the CIOS backend — that is the
bit-exactness contract tests/test_fp_jax.py and scripts/rns_smoke.py pin.

**Exactness.** Every modular reduction is the float-assisted
`v - floor(v/m)·m` with integer correction (`_mod_rows`): the float
estimate may be off by ±1, the integer fix-up makes the result exact, so
the whole pipeline is integer-exact end to end. All intermediate
magnitudes are proven < 2^30 (comments at each site), inside int32.

On CPU the contractions run as single int32 `dot_general`s (exact, XLA);
`int8_dots=True` (default on accelerators) splits each constant matrix
and operand into <=7-bit planes so every contraction is an int8 x int8 ->
int32 MXU matmul — bit-identical output, property-tested against the
int32 lowering.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from handel_tpu.ops.fp import (
    LIMB_BITS,
    LIMB_MASK,
    Field,
    _has_pallas_tpu,
    _int_to_limbs,
)

_PRIME_BOUND = 1 << 13  # residue moduli < 2^13: products and dot terms fit int32


def _small_primes_desc(bound: int) -> list[int]:
    sieve = np.ones(bound, dtype=bool)
    sieve[:2] = False
    for i in range(2, int(bound**0.5) + 1):
        if sieve[i]:
            sieve[i * i :: i] = False
    return [int(x) for x in np.nonzero(sieve)[0][::-1]]


def _limbs8(x: int, n: int) -> list[int]:
    return [(x >> (8 * t)) & 0xFF for t in range(n)]


class RnsField(Field):
    """Field with `mul` replaced by the RNS Montgomery pipeline.

    Representation-compatible with the CIOS backend (canonical positional
    limbs at boundaries); Montgomery constant is M = prod(base A) instead
    of 2^(16n). Works for any odd prime p with enough sub-2^13 primes —
    BN254 (k_A=20, k_B=21) and BLS12-381 (k_A=30, k_B=31) both fit.
    """

    backend = "rns"

    # Closure exponent for resident chaining: base A is grown until
    # M >= 2^RES_MUL_LOG2 * p, so mul_resident stays exact for any operand
    # pair whose static bound exponents sum to <= RES_MUL_LOG2. The tower's
    # deepest product (conj(f) * f^-1 in the final-exp easy part) multiplies
    # bounds 2^24*p x 2^16*p inside f6_mul pre-sums — max exponent sum 54;
    # 56 leaves margin without growing base A by another prime.
    RES_MUL_LOG2 = 56
    # sub_resident offset table covers blog in [0, RES_MAX_BLOG]
    RES_MAX_BLOG = 32

    def __init__(self, p: int, use_pallas: bool | None = None,
                 backend: str | None = None):
        # the CIOS Pallas kernel computes a*b*R^-1 — wrong constant for this
        # backend; mul() below never consults use_pallas
        super().__init__(p, use_pallas=False)
        if backend not in (None, "rns"):
            raise ValueError(
                f"RnsField is the 'rns' backend, got {backend!r}: construct "
                f"Field(p, backend='cios') for the CIOS kernel or "
                f"Field(p, backend='rns') for this one"
            )
        self._build_bases(p)
        # Montgomery constant: M, not R (see module docstring)
        self.mont_r = self.M % p
        self.mont_r2 = self.mont_r * self.mont_r % p
        # int8-plane lowering maps the contractions onto the MXU; the int32
        # single-dot lowering is bit-identical and cheaper to compile on CPU
        self.int8_dots = _has_pallas_tpu()
        # Pallas-fused resident kernel (elementwise Montgomery steps + both
        # base extensions in one VMEM-resident body) where available; the
        # XLA lowering is the same `_mul_resident_core` body, bit-identical
        self.fused_resident = _has_pallas_tpu()
        self._fused_fns: dict = {}
        # residue<->positional conversion counters (trace-time semantics —
        # module docstring): per traced call site, the provable-win metric
        self._n_to_resident = 0
        self._n_from_resident = 0
        self._adapter = None

    # -- construction -------------------------------------------------------

    def _build_bases(self, p: int) -> None:
        primes = iter(_small_primes_desc(_PRIME_BOUND))
        mA: list[int] = []
        M = 1
        # M >= 2^RES_MUL_LOG2 * p closes RESIDENT chaining: for operands
        # bounded by 2^la*p, 2^lb*p with la+lb <= RES_MUL_LOG2 the product
        # T < M*p, so r = (T + q_hat*p)/M < p + kA*p = (kA+1)*p — the same
        # output bound the canonical path (T < p^2 << M*p) always had. The
        # per-mul path's old M >= 4p condition is strictly implied.
        while M < (p << self.RES_MUL_LOG2):
            mA.append(next(primes))
            M *= mA[-1]
        kA = len(mA)
        # mul_resident's advertised output bound is 2^6 * p (HACKING.md
        # bound walk); (kA+1) <= 64 makes (kA+1)p <= 2^6*p. Holds with huge
        # margin for 13-bit moduli (kA ~ 24 for BN254, ~34 for BLS12-381).
        assert kA + 1 <= 64, "resident output bound 2^6*p needs kA+1 <= 64"
        mB: list[int] = []
        MB = 1
        while MB <= 2 * (kA + 1) * p:  # r < (k_A+1)p must be < MB (CRT range)
            mB.append(next(primes))
            MB *= mB[-1]
        kB = len(mB)
        mr = next(primes)
        assert mr > kB + 1, "redundant modulus must bound the CRT offset"
        self.mA, self.mB, self.mr = mA, mB, mr
        self.M, self.MB = M, MB
        self.kA, self.kB = kA, kB
        self.k_all = kA + kB + 1  # joint base: A ++ B ++ [m_r]

        n8 = 2 * self.nlimbs  # 8-bit limb count of the positional form
        m_all = mA + mB + [mr]
        # positional->residue conversion: W[i, j] = 2^(8j) mod m_i
        W = np.array(
            [[pow(2, 8 * j, m) for j in range(n8)] for m in m_all], np.int32
        )
        # folded q/xi constant: xi_i = T_i * (-p^{-1} * (M/m_i)^{-1}) mod m_i
        c1 = np.array(
            [(-pow(p, -1, m) * pow(M // m, -1, m)) % m for m in mA], np.int32
        )
        # base extension A -> B ++ [m_r]: E[j, i] = (M/m_i) mod m_j
        mB_r = mB + [mr]
        E = np.array([[(M // mi) % mj for mi in mA] for mj in mB_r], np.int32)
        p_modB = np.array([p % m for m in mB_r], np.int32)
        MinvB = np.array([pow(M % m, -1, m) for m in mB_r], np.int32)
        # exact CRT over base B: xi'_j = r_j * (MB/m_j)^{-1} mod m_j, then
        # r = sum(xi'_j * MB/m_j) - alpha*MB with alpha recovered through m_r
        c2 = np.array([pow(MB // m, -1, m) % m for m in mB], np.int32)
        L_mr = np.array([(MB // m) % mr for m in mB], np.int32)
        self._MBinv_r = int(pow(MB % mr, -1, mr))
        n8out = (MB.bit_length() + 7) // 8
        n8out += n8out % 2  # even, so 8->16 repack is a clean reshape
        L8 = np.array(
            [_limbs8(MB // m, n8out) for m in mB], np.int32
        ).T  # (n8out, kB)
        MB8 = np.array(_limbs8(MB, n8out), np.int32)
        self.n8out = n8out
        self.n16out = n8out // 2
        # binary canonicalization ladder: r < (kA+1)p <= 2^smax * p
        smax = (kA + 1 - 1).bit_length()
        self._sub_consts = [
            np.array(
                [((p << s) >> (16 * t)) & 0xFFFF for t in range(self.n16out)],
                np.int32,
            )
            for s in range(smax - 1, -1, -1)
        ]
        self._W, self._E, self._L8 = W, E, L8
        self._c1, self._c2 = c1, c2
        self._p_modB, self._MinvB = p_modB, MinvB
        self._L_mr, self._MB8 = L_mr, MB8
        self._m_all = np.array(m_all, np.int32)
        self._minv_all = (1.0 / self._m_all.astype(np.float64)).astype(
            np.float32
        )
        # -- resident-form constants ---------------------------------------
        # exact base extension B -> A (mul_resident's closing step): the
        # same Shenoy digits xi'_j = r_j * (MB/m_j)^{-1} the CRT uses, but
        # recombined mod base A instead of positionally
        self._E2 = np.array(
            [[(MB // mj) % mi for mj in mB] for mi in mA], np.int32
        )  # (kA, kB)
        self._MB_modA = np.array([MB % mi for mi in mA], np.int32)
        # Montgomery one (M mod p) as joint residues: the refresh multiplier
        # (x * one_hat * M^{-1} = x mod p with the bound reset to < (kA+1)p)
        self._one_res = np.array([(M % p) % m for m in m_all], np.int32)
        # sub_resident offsets: residues of (p << s) — adding the offset
        # keeps the represented difference nonnegative for any subtrahend
        # bounded by 2^s * p
        self._off_res = np.array(
            [[((p << s) % m) for m in m_all]
             for s in range(self.RES_MAX_BLOG + 1)],
            np.int32,
        )

    # -- exact modular primitives ------------------------------------------

    @staticmethod
    def _mod_rows(v, m, minv):
        """v mod m, exact, for int32 v in [0, 2^30) and m in (2, 2^13).

        Float estimate first: q = floor(f32(v)/m) is within ±1 of the true
        quotient (relative error <= ~3*2^-24 on a ratio < 2^19, absolute
        error < 0.1), then integer correction makes the residue exact —
        q*m <= v + m stays inside int32.
        """
        import jax.numpy as jnp

        q = jnp.floor(v.astype(jnp.float32) * minv).astype(jnp.int32)
        r = v - q * m
        r = jnp.where(r < 0, r + m, r)
        r = jnp.where(r >= m, r - m, r)
        return r

    def _dot(self, Wnp: np.ndarray, x, exact: bool = False, mvec=None,
             minvvec=None):
        """Constant-matrix contraction ``W @ x`` for int32 x (d, B).

        int32 mode: W split into <=7-bit planes so every partial dot stays
        < 2^26 (depth <= 48, terms < 2^7 * 2^13); the high plane is reduced
        mod m before the <<7 recombination unless `exact` (W < 2^8 there,
        so the raw recombination already fits).
        int8 mode (`self.int8_dots`): x additionally splits at bit 7 and
        all four partial contractions run as int8 x int8 -> int32
        `dot_general` — the MXU-native form; bit-identical results.
        """
        import jax
        import jax.numpy as jnp

        d = Wnp.shape[1]
        Wlo = jnp.asarray(Wnp & 0x7F)
        Whi = jnp.asarray(Wnp >> 7)  # < 2^6 (W < 2^13) or <= 1 (exact, W < 2^8)
        dn = (((1,), (0,)), ((), ()))

        def dot(a, b):
            return jax.lax.dot_general(a, b, dn,
                                       preferred_element_type=jnp.int32)

        if not self.int8_dots:
            # terms < 2^7 * 2^13 = 2^20, depth <= 48 -> partials < 2^26
            lo = dot(Wlo, x)
            hi = dot(Whi, x)
        else:
            xl = (x & 0x7F).astype(jnp.int8)
            xh = (x >> 7).astype(jnp.int8)  # < 2^6: residues and limbs < 2^13
            i8 = lambda w: w.astype(jnp.int8)
            # every partial: terms <= 127*127, depth <= 48 -> < 2^20.9
            lo = dot(i8(Wlo), xl) + (dot(i8(Wlo), xh) << 7)
            hi = dot(i8(Whi), xl) + (dot(i8(Whi), xh) << 7)
        if exact:
            # W < 2^8: hi <= depth * xmax -> lo + (hi << 7) < 2^27, exact
            return lo + (hi << 7)
        # congruence-preserving recombination: reduce hi first so the shift
        # cannot overflow (hi < 2^26 -> mod -> < 2^13 -> <<7 -> < 2^20)
        hi = self._mod_rows(hi, mvec, minvvec)
        return self._mod_rows(lo + (hi << 7), mvec, minvvec)

    # -- residue conversion -------------------------------------------------

    def _split8(self, a):
        """(nlimbs, B) uint32 16-bit limbs -> (2*nlimbs, B) int32 8-bit."""
        import jax.numpy as jnp

        a = a.astype(jnp.int32)
        return jnp.stack([a & 0xFF, a >> 8], axis=1).reshape(
            2 * self.nlimbs, a.shape[1]
        )

    def to_rns(self, a):
        """Positional (nlimbs, B) uint32 -> joint-base residues (k_all, B)
        int32 (base A rows, then base B rows, then the m_r channel)."""
        import jax.numpy as jnp

        m = jnp.asarray(self._m_all)[:, None]
        minv = jnp.asarray(self._minv_all)[:, None]
        return self._dot(self._W, self._split8(a), mvec=m, minvvec=minv)

    def from_rns_base_b(self, rB, rr):
        """Exact CRT: base-B residues (kB, B) + m_r channel (B,) of a value
        v < MB -> canonical positional 16-bit limbs (n16out, B) int32.

        Shenoy–Kumaresan: alpha = (sum(xi'_j * (MB/m_j)) - v) / MB is
        recovered exactly through the redundant channel (alpha < kB < m_r),
        then v = L8 @ xi' - alpha*MB8 in 8-bit columns, carry-propagated.
        """
        import jax.numpy as jnp

        mB = jnp.asarray(np.array(self.mB, np.int32))[:, None]
        mBinv = jnp.asarray(self._minv_all[self.kA : self.kA + self.kB])[:, None]
        mr = jnp.int32(self.mr)
        mrinv = jnp.float32(1.0 / self.mr)
        xi = self._mod_rows(rB * jnp.asarray(self._c2)[:, None], mB, mBinv)
        # alpha channel: per-term mod keeps the sum < kB * 2^13 < 2^19
        terms = self._mod_rows(xi * jnp.asarray(self._L_mr)[:, None], mr, mrinv)
        s = self._mod_rows(jnp.sum(terms, axis=0), mr, mrinv)
        # (s - v) * MB^{-1} mod m_r; + m_r keeps the difference nonnegative
        alpha = self._mod_rows(
            (s - rr + mr) * jnp.int32(self._MBinv_r), mr, mrinv
        )  # < kB exactly — the true CRT offset
        # positional columns: exact int32 (terms < 2^21, depth kB -> < 2^26)
        cols = self._dot(self._L8, xi, exact=True)
        cols = cols - alpha[None, :] * jnp.asarray(self._MB8)[:, None]
        # signed sequential carry: v - (v & 0xFF) is a multiple of 256, so
        # the arithmetic shift is exact floor division for negatives too
        carry = jnp.zeros_like(cols[0])
        out8 = []
        for t in range(self.n8out):
            v = cols[t] + carry
            low = v & 0xFF
            out8.append(low)
            carry = (v - low) >> 8
        # top carry is 0: the reconstructed integer is < MB by CRT range
        o8 = jnp.stack(out8)
        return o8[0::2] + (o8[1::2] << 8)  # (n16out, B) 16-bit rows

    def _cond_sub_const(self, v, cnp: np.ndarray):
        """v - C if v >= C else v, over (n16out, B) int32 16-bit rows."""
        import jax.numpy as jnp

        borrow = jnp.zeros_like(v[0])
        diff = []
        for i in range(self.n16out):
            d = v[i] - jnp.int32(int(cnp[i])) - borrow
            borrow = (d < 0).astype(jnp.int32)
            diff.append(d + (borrow << 16))
        keep = borrow > 0  # borrowed past the top -> v < C
        return jnp.stack(
            [jnp.where(keep, v[i], diff[i]) for i in range(self.n16out)]
        )

    # -- the kernel ---------------------------------------------------------

    def _mont_reduce(self, d):
        """Montgomery reduction steps 3-5 on a joint-residue product
        d = (ra*rb mod m) of shape (k_all, B): folded quotient digits in
        base A, offset-tolerant extension A -> B ++ [m_r], then
        r = (T + q_hat*p)/M elementwise. Returns (kB+1, B) residues of r in
        base B ++ [m_r]; r < (kA+1)p whenever T < M*p (always true for
        canonical operands, and guaranteed for resident chains by the
        RES_MUL_LOG2 basis condition). Shared by `mul` and `mul_resident`.
        """
        import jax.numpy as jnp

        kA = self.kA
        m_all = jnp.asarray(self._m_all)[:, None]
        minv_all = jnp.asarray(self._minv_all)[:, None]
        mB_r = m_all[kA:]
        mBinv_r = minv_all[kA:]
        # folded Montgomery quotient digits in base A (products < 2^26)
        xi = self._mod_rows(d[:kA] * jnp.asarray(self._c1)[:, None],
                            m_all[:kA], minv_all[:kA])
        # base extension A -> B ++ [m_r]: q_hat = q + c*M, c < kA — the
        # offset only shifts r by c*p, absorbed downstream (ladder or the
        # resident bound budget)
        Q = self._dot(self._E, xi, mvec=mB_r, minvvec=mBinv_r)
        # r = (T + q_hat*p)/M elementwise in B ++ [m_r]:
        # (d + Q*p) < 2^14 after reduction; * Minv < 2^27
        u = self._mod_rows(Q * jnp.asarray(self._p_modB)[:, None], mB_r,
                           mBinv_r)
        return self._mod_rows(
            (d[kA:] + u) * jnp.asarray(self._MinvB)[:, None], mB_r, mBinv_r
        )

    def _extend_b_to_a(self, r):
        """Exact base extension B ++ [m_r] -> A for a value v < MB given as
        (kB+1, B) residues: the Shenoy digits xi'_j plus the redundant
        channel recover the CRT offset alpha EXACTLY (alpha < kB < m_r), so
        v mod mA_i = (sum_j xi'_j * E2[i, j] - alpha * MB) mod mA_i with no
        approximation. Returns (kA, B) base-A residues."""
        import jax.numpy as jnp

        kA, kB = self.kA, self.kB
        mA = jnp.asarray(self._m_all[:kA])[:, None]
        mAinv = jnp.asarray(self._minv_all[:kA])[:, None]
        mB = jnp.asarray(self._m_all[kA : kA + kB])[:, None]
        mBinv = jnp.asarray(self._minv_all[kA : kA + kB])[:, None]
        mr = jnp.int32(self.mr)
        mrinv = jnp.float32(1.0 / self.mr)
        xi = self._mod_rows(r[:kB] * jnp.asarray(self._c2)[:, None], mB, mBinv)
        # alpha through the redundant channel (same algebra as
        # from_rns_base_b; per-term mod keeps the sum < kB * 2^13 < 2^19)
        terms = self._mod_rows(xi * jnp.asarray(self._L_mr)[:, None], mr, mrinv)
        s = self._mod_rows(jnp.sum(terms, axis=0), mr, mrinv)
        alpha = self._mod_rows(
            (s - r[kB] + mr) * jnp.int32(self._MBinv_r), mr, mrinv
        )
        rA = self._dot(self._E2, xi, mvec=mA, minvvec=mAinv)
        corr = self._mod_rows(
            alpha[None, :] * jnp.asarray(self._MB_modA)[:, None], mA, mAinv
        )
        # rA < mA, corr < mA: + mA keeps the difference nonnegative (< 2^14)
        return self._mod_rows(rA + mA - corr, mA, mAinv)

    def mul(self, a, b):
        """RNS Montgomery product: canonical a, b (< p, positional Montgomery
        form with constant M) -> canonical a*b*M^{-1} mod p. See module
        docstring for the step-by-step bound/exactness argument. Pays one
        residue conversion in and one CRT reconstruction out — the per-mul
        cost the resident form (`mul_resident`) eliminates."""
        import jax.numpy as jnp

        bsz = a.shape[1]
        if bsz == 0:  # empty slices appear inside library combinators
            return jnp.zeros_like(a)
        self._n_to_resident += 1
        self._n_from_resident += 1
        kB = self.kB
        m_all = jnp.asarray(self._m_all)[:, None]
        minv_all = jnp.asarray(self._minv_all)[:, None]

        # 1) residues of both operands in one contraction (batch-stacked)
        res = self._dot(
            self._W,
            jnp.concatenate([self._split8(a), self._split8(b)], axis=1),
            mvec=m_all,
            minvvec=minv_all,
        )
        ra, rb = res[:, :bsz], res[:, bsz:]
        # 2) residue-wise product T mod m_i (products < 2^26)
        d = self._mod_rows(ra * rb, m_all, minv_all)
        # 3-5) Montgomery reduction into base B ++ [m_r]
        r = self._mont_reduce(d)
        # 6) exact CRT back to positional form; r < (kA+1)p < MB
        v16 = self.from_rns_base_b(r[:kB], r[kB])
        # 7) canonicalize r < 2^smax * p down to < p (binary ladder)
        for cnp in self._sub_consts:
            v16 = self._cond_sub_const(v16, cnp)
        # value < p fits the field's limb count; higher rows are zero
        return v16[: self.nlimbs].astype(jnp.uint32)

    # -- resident form ------------------------------------------------------
    #
    # A resident value is a plain (k_all, B) int32 array of joint-base
    # residues (base A rows ++ base B rows ++ the m_r channel) representing
    # some integer v < 2^lb * p, where the bound exponent lb is a STATIC
    # property tracked by construction (ops/tower.py's per-site `blog`
    # literals), never materialized in arrays — so `jnp.concatenate`,
    # `lax.scan` carries, and `tree_map` stacking all work unchanged.

    def _mul_resident_core(self, ra, rb):
        """mul_resident body (shared verbatim by the XLA and Pallas-fused
        lowerings): joint residues x joint residues -> joint residues of
        ra*rb*M^{-1}, bound < (kA+1)p <= 2^6*p. Exact whenever the operand
        bound exponents sum to <= RES_MUL_LOG2 (T < M*p)."""
        import jax.numpy as jnp

        m_all = jnp.asarray(self._m_all)[:, None]
        minv_all = jnp.asarray(self._minv_all)[:, None]
        d = self._mod_rows(ra * rb, m_all, minv_all)
        r = self._mont_reduce(d)  # base B ++ [m_r] residues, r < (kA+1)p
        rA = self._extend_b_to_a(r)  # exact: (kA+1)p < MB
        return jnp.concatenate([rA, r], axis=0)

    def mul_resident(self, ra, rb):
        """Resident Montgomery product — no positional limbs anywhere.
        Inputs/outputs are (k_all, B) joint residues; the caller owns the
        static bound bookkeeping (sum of operand bound exponents must be
        <= RES_MUL_LOG2; output bound 2^6 * p)."""
        import jax.numpy as jnp

        ra = ra.astype(jnp.int32)
        rb = rb.astype(jnp.int32)
        if ra.shape[1] == 0:
            return jnp.zeros_like(ra)
        if self.fused_resident:
            return self._mul_resident_pallas(ra, rb)
        return self._mul_resident_core(ra, rb)

    def _mul_resident_pallas(self, ra, rb):
        """Pallas-fused lowering of `_mul_resident_core`: one kernel holds
        the residue product, both base extensions, and every float-assisted
        reduction in VMEM, so XLA cannot split the elementwise chain between
        the `dot_general`s into separate HBM round trips (it measurably
        won't fuse across the int8-plane contractions). Bit-identical by
        construction — the body IS `_mul_resident_core`."""
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        import jax.numpy as jnp

        k = self.k_all
        bsz = ra.shape[1]
        tile = min(512, bsz)
        while bsz % tile != 0:
            tile //= 2
        key = (bsz, tile)
        fn = self._fused_fns.get(key)
        if fn is None:

            def kernel(a_ref, b_ref, o_ref):
                o_ref[:] = self._mul_resident_core(a_ref[:], b_ref[:])

            fn = pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((k, bsz), jnp.int32),
                grid=(bsz // tile,),
                in_specs=[
                    pl.BlockSpec((k, tile), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((k, tile), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((k, tile), lambda i: (0, i),
                                       memory_space=pltpu.VMEM),
            )
            self._fused_fns[key] = fn
        return fn(ra, rb)

    def add_resident(self, ra, rb):
        """Residue-wise modular add; represented-value bound grows to
        max(la, lb) + 1 (caller-tracked)."""
        import jax.numpy as jnp

        m = jnp.asarray(self._m_all)[:, None]
        minv = jnp.asarray(self._minv_all)[:, None]
        return self._mod_rows(
            ra.astype(jnp.int32) + rb.astype(jnp.int32), m, minv
        )

    def sub_resident(self, ra, rb, blog: int):
        """Residue-wise subtract with a nonnegativity offset: computes
        ra + (p << blog) - rb in the residue domain, which represents
        a - b + 2^blog * p — congruent to a - b mod p and nonnegative
        whenever the subtrahend's static bound exponent is <= blog. Output
        bound max(la, blog) + 1 (caller-tracked)."""
        import jax.numpy as jnp

        if blog is None:
            raise ValueError(
                "resident subtraction needs a static `blog` bound literal "
                "for the subtrahend (see HACKING.md 'Residue-resident "
                "pairing'); positional backends ignore it"
            )
        if not 0 <= blog <= self.RES_MAX_BLOG:
            raise ValueError(
                f"blog={blog} outside the offset table [0, "
                f"{self.RES_MAX_BLOG}] — the tower bound walk never "
                f"exceeds 24; widen RES_MAX_BLOG if a new site does"
            )
        m = jnp.asarray(self._m_all)[:, None]
        minv = jnp.asarray(self._minv_all)[:, None]
        off = jnp.asarray(self._off_res[blog])[:, None]
        # ra + off + m - rb in [1, 3*2^13): inside _mod_rows' exact domain
        return self._mod_rows(
            ra.astype(jnp.int32) + off + m - rb.astype(jnp.int32), m, minv
        )

    def to_resident(self, a):
        """Canonical positional limbs -> resident joint residues (bound
        exponent 0). Counts one residue conversion (trace-time)."""
        self._n_to_resident += 1
        return self.to_rns(a)

    def refresh_resident(self, r):
        """Bound reset without leaving the residue domain: multiply by the
        Montgomery one (M mod p), so the value is unchanged mod p (and stays
        in Montgomery form) while the bound drops to < (kA+1)p <= 2^6*p.
        Valid for any input bound <= RES_MUL_LOG2."""
        import jax.numpy as jnp

        one = jnp.broadcast_to(
            jnp.asarray(self._one_res, jnp.int32)[:, None],
            (self.k_all, r.shape[1]),
        )
        return self.mul_resident(r, one)

    def from_resident(self, r):
        """Resident joint residues (any bound <= RES_MUL_LOG2) -> canonical
        positional limbs, bit-identical to the CIOS backend's boundary
        values. Refreshes first so the CRT range condition (value < MB)
        holds, then runs the same exact CRT + conditional-subtract ladder
        as `mul`. Counts one CRT reconstruction (trace-time)."""
        import jax.numpy as jnp

        self._n_from_resident += 1
        rr = self.refresh_resident(r)  # value < (kA+1)p < MB
        v16 = self.from_rns_base_b(
            rr[self.kA : self.kA + self.kB], rr[self.kA + self.kB]
        )
        for cnp in self._sub_consts:
            v16 = self._cond_sub_const(v16, cnp)
        return v16[: self.nlimbs].astype(jnp.uint32)

    # -- conversion accounting (trace-time; module docstring) ---------------

    def conversion_counts(self) -> dict:
        return {
            "to_resident": self._n_to_resident,
            "from_resident": self._n_from_resident,
            "total": self._n_to_resident + self._n_from_resident,
        }

    def reset_conversion_counts(self) -> None:
        self._n_to_resident = 0
        self._n_from_resident = 0

    def resident(self) -> "ResidentRns":
        """The Field-shaped adapter over resident values (cached)."""
        if self._adapter is None:
            self._adapter = ResidentRns(self)
        return self._adapter


class ResidentRns:
    """Field-shaped adapter over the resident value form.

    Duck-types the `Field` surface `ops/tower.py` consumes, with values as
    (k_all, B) int32 joint-residue arrays instead of (nlimbs, B) uint32
    positional limbs — so `Tower.as_resident()` reuses every tower formula
    (Karatsuba stacking, cyclotomic squaring, windowed pow) unchanged while
    no op pays a CRT round trip. The represented-value bound discipline is
    STATIC: `sub`/`neg` demand the per-site `blog` literal (subtrahend bound
    exponent, see HACKING.md "Residue-resident pairing"); the positional
    backends accept and ignore the same literal, keeping tower code
    backend-agnostic.

    `eq`/`is_zero` raise: two residue vectors of non-canonical values are
    not comparable without reconstruction — comparisons are positional
    boundaries by definition (`from_resident` first).
    """

    backend = "rns"
    is_resident = True
    limb_dtype = jnp.int32

    def __init__(self, F: RnsField):
        self.base = F
        self.p = F.p
        self.mont_r = F.mont_r
        self.mont_r2 = F.mont_r2
        # one batch row per joint residue channel: concatenation-stacking
        # and `_split` in ops/tower.py only need a consistent row count
        self.nlimbs = F.k_all

    # -- host-side conversions ---------------------------------------------

    def pack(self, xs, mont: bool = True):
        return self.base.to_resident(self.base.pack(xs, mont=mont))

    def unpack(self, limbs, mont: bool = True) -> list[int]:
        return self.base.unpack(self.base.from_resident(limbs), mont=mont)

    def constant(self, x: int, batch: int):
        """Montgomery-form constant broadcast to (k_all, batch) residues —
        computed directly on the host (bound exponent 0, no conversion
        counted: nothing crosses the residue/positional seam at runtime)."""
        v = x % self.p * self.mont_r % self.p
        res = np.array([v % int(m) for m in self.base._m_all], np.int32)
        return jnp.broadcast_to(res[:, None], (self.base.k_all, batch))

    # -- arithmetic ---------------------------------------------------------

    def add(self, a, b):
        return self.base.add_resident(a, b)

    def sub(self, a, b, blog: int | None = None):
        return self.base.sub_resident(a, b, blog)

    def neg(self, a, blog: int | None = None):
        return self.base.sub_resident(jnp.zeros_like(a), a, blog)

    def mul(self, a, b):
        return self.base.mul_resident(a, b)

    def sqr(self, a):
        return self.base.mul_resident(a, a)

    def refresh(self, a):
        return self.base.refresh_resident(a)

    def pow_const(self, a, e: int, window: int | None = None):
        """Windowed square-and-multiply on resident values. Bound-safe for
        inputs <= 2^28 * p: every internal product multiplies two values
        bounded by max(input, 2^6*p), well under the RES_MUL_LOG2 budget."""
        from handel_tpu.ops.fp import default_pow_window, windowed_pow

        return windowed_pow(
            a,
            e,
            default_pow_window() if window is None else window,
            mul=self.mul,
            sqr=lambda x: self.mul(x, x),
            stack=lambda t: jnp.stack(t),
            take=lambda s, i: s[i],
            select=lambda c, x, y: jnp.where(c, x, y),
        )

    def inv(self, a):
        """Fermat inverse a^(p-2); zero maps to zero. Output bound 2^6*p."""
        return self.pow_const(a, self.p - 2)

    def select(self, mask, a, b):
        return jnp.where(
            mask[None, :], a.astype(jnp.int32), b.astype(jnp.int32)
        )

    # -- positional-boundary ops: not available in residence ---------------

    def eq(self, a, b):
        raise RuntimeError(
            "ResidentRns.eq: residue vectors of non-canonical values are "
            "not directly comparable — reconstruct with from_resident() "
            "first (comparisons are positional boundaries)"
        )

    def is_zero(self, a):
        raise RuntimeError(
            "ResidentRns.is_zero: reconstruct with from_resident() first "
            "(comparisons are positional boundaries)"
        )

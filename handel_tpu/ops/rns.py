"""Residue-number-system Montgomery multiplication — the MXU-shaped modmul.

The production CIOS kernel (ops/fp.py) is a VPU workload: a 254-bit limb
product is an outer product (contraction depth 1), so the 128x128 systolic
array contributes nothing and the measured 16.7 T int8-ops/s MXU ceiling
(results/fp_microbench.json "mxu_lab") sits idle through every pairing.
RNS restructures the same arithmetic so the heavy steps ARE deep matmul
contractions against constant matrices — the shape the AI-ASIC ZKP
literature targets (PAPERS.md, arxiv 2604.17808; ROADMAP item 1):

  * **Residue mapping.** An operand's 8-bit limb vector (2n, B) maps to
    residues mod k small coprime primes via one constant-matrix contraction
    ``W @ limbs`` with ``W[i, j] = 2^(8j) mod m_i`` — contraction depth 2n
    (32 for BN254), batch B in the other MXU dimension.
  * **Residue-wise product.** Elementwise (k, B) int32 lane multiplies —
    k ≈ 42 small products replace the CIOS kernel's n^2 = 256 limb products
    plus its interleaved reduction columns.
  * **Montgomery step in RNS** (Bajard/Kawamura shape). With base A
    (product M, the RNS Montgomery constant) and base B (product MB):
    q = T·(-p^{-1}) mod M is elementwise in base A; extending q's residues
    to base B is another constant-matrix contraction ``E @ xi`` with
    ``E[j, i] = (M/m_i) mod m_j``; then r = (T + q·p)/M is elementwise in
    base B. The extension is offset-tolerant (q may come out as q + c·M,
    c < k_A): it only shifts r by c·p, absorbed by the final reduction.
  * **Exact CRT reconstruction** (Shenoy–Kumaresan). A redundant channel
    m_r rides the whole pipeline, so the CRT offset alpha in
    r = sum(xi'_j · MB/m_j) - alpha·MB is recovered EXACTLY (alpha < k_B
    <= m_r) — no floating-point base-extension approximation anywhere in
    the value path. Positional limbs come back via a third constant
    contraction against the 8-bit limb decomposition of the MB/m_j.

`RnsField` keeps the public Field contract intact: canonical (< p)
(nlimbs, B) uint32 limbs at every op boundary, so `ops/tower.py`'s
batch-stacking entry points, the curve adapters, and `BN254Device`
dispatch route through unchanged — CRT reconstruction is paid inside
`mul`, i.e. exactly at the boundaries where tower/pairing consume
positional form (line evaluations, Frobenius twists, final-exponentiation
exits all call back into add/sub/eq which need positional limbs).
add/sub/neg/inv/pow/select/eq are inherited verbatim.

**Montgomery convention.** The backend's Montgomery constant is M (the
base-A product), not the CIOS kernel's R = 2^(16n): division by M is what
the RNS reduction gets for free. `mont_r`/`mont_r2` are overridden
accordingly, so pack/unpack/to_mont/from_mont stay self-consistent and
every *non-Montgomery* boundary value (unpacked results, verify verdicts,
affine coordinates) is bit-identical to the CIOS backend — that is the
bit-exactness contract tests/test_fp_jax.py and scripts/rns_smoke.py pin.

**Exactness.** Every modular reduction is the float-assisted
`v - floor(v/m)·m` with integer correction (`_mod_rows`): the float
estimate may be off by ±1, the integer fix-up makes the result exact, so
the whole pipeline is integer-exact end to end. All intermediate
magnitudes are proven < 2^30 (comments at each site), inside int32.

On CPU the contractions run as single int32 `dot_general`s (exact, XLA);
`int8_dots=True` (default on accelerators) splits each constant matrix
and operand into <=7-bit planes so every contraction is an int8 x int8 ->
int32 MXU matmul — bit-identical output, property-tested against the
int32 lowering.
"""

from __future__ import annotations

import numpy as np

from handel_tpu.ops.fp import (
    LIMB_BITS,
    LIMB_MASK,
    Field,
    _has_pallas_tpu,
    _int_to_limbs,
)

_PRIME_BOUND = 1 << 13  # residue moduli < 2^13: products and dot terms fit int32


def _small_primes_desc(bound: int) -> list[int]:
    sieve = np.ones(bound, dtype=bool)
    sieve[:2] = False
    for i in range(2, int(bound**0.5) + 1):
        if sieve[i]:
            sieve[i * i :: i] = False
    return [int(x) for x in np.nonzero(sieve)[0][::-1]]


def _limbs8(x: int, n: int) -> list[int]:
    return [(x >> (8 * t)) & 0xFF for t in range(n)]


class RnsField(Field):
    """Field with `mul` replaced by the RNS Montgomery pipeline.

    Representation-compatible with the CIOS backend (canonical positional
    limbs at boundaries); Montgomery constant is M = prod(base A) instead
    of 2^(16n). Works for any odd prime p with enough sub-2^13 primes —
    BN254 (k_A=20, k_B=21) and BLS12-381 (k_A=30, k_B=31) both fit.
    """

    backend = "rns"

    def __init__(self, p: int, use_pallas: bool | None = None,
                 backend: str | None = None):
        # the CIOS Pallas kernel computes a*b*R^-1 — wrong constant for this
        # backend; mul() below never consults use_pallas
        super().__init__(p, use_pallas=False)
        if backend not in (None, "rns"):
            raise ValueError(f"RnsField is the 'rns' backend, got {backend!r}")
        self._build_bases(p)
        # Montgomery constant: M, not R (see module docstring)
        self.mont_r = self.M % p
        self.mont_r2 = self.mont_r * self.mont_r % p
        # int8-plane lowering maps the contractions onto the MXU; the int32
        # single-dot lowering is bit-identical and cheaper to compile on CPU
        self.int8_dots = _has_pallas_tpu()

    # -- construction -------------------------------------------------------

    def _build_bases(self, p: int) -> None:
        primes = iter(_small_primes_desc(_PRIME_BOUND))
        mA: list[int] = []
        M = 1
        while M < 4 * p:  # M >= 4p => r = T/M + c*p < (k_A + 1)*p for T < p^2
            mA.append(next(primes))
            M *= mA[-1]
        kA = len(mA)
        mB: list[int] = []
        MB = 1
        while MB <= 2 * (kA + 1) * p:  # r < (k_A+1)p must be < MB (CRT range)
            mB.append(next(primes))
            MB *= mB[-1]
        kB = len(mB)
        mr = next(primes)
        assert mr > kB + 1, "redundant modulus must bound the CRT offset"
        self.mA, self.mB, self.mr = mA, mB, mr
        self.M, self.MB = M, MB
        self.kA, self.kB = kA, kB
        self.k_all = kA + kB + 1  # joint base: A ++ B ++ [m_r]

        n8 = 2 * self.nlimbs  # 8-bit limb count of the positional form
        m_all = mA + mB + [mr]
        # positional->residue conversion: W[i, j] = 2^(8j) mod m_i
        W = np.array(
            [[pow(2, 8 * j, m) for j in range(n8)] for m in m_all], np.int32
        )
        # folded q/xi constant: xi_i = T_i * (-p^{-1} * (M/m_i)^{-1}) mod m_i
        c1 = np.array(
            [(-pow(p, -1, m) * pow(M // m, -1, m)) % m for m in mA], np.int32
        )
        # base extension A -> B ++ [m_r]: E[j, i] = (M/m_i) mod m_j
        mB_r = mB + [mr]
        E = np.array([[(M // mi) % mj for mi in mA] for mj in mB_r], np.int32)
        p_modB = np.array([p % m for m in mB_r], np.int32)
        MinvB = np.array([pow(M % m, -1, m) for m in mB_r], np.int32)
        # exact CRT over base B: xi'_j = r_j * (MB/m_j)^{-1} mod m_j, then
        # r = sum(xi'_j * MB/m_j) - alpha*MB with alpha recovered through m_r
        c2 = np.array([pow(MB // m, -1, m) % m for m in mB], np.int32)
        L_mr = np.array([(MB // m) % mr for m in mB], np.int32)
        self._MBinv_r = int(pow(MB % mr, -1, mr))
        n8out = (MB.bit_length() + 7) // 8
        n8out += n8out % 2  # even, so 8->16 repack is a clean reshape
        L8 = np.array(
            [_limbs8(MB // m, n8out) for m in mB], np.int32
        ).T  # (n8out, kB)
        MB8 = np.array(_limbs8(MB, n8out), np.int32)
        self.n8out = n8out
        self.n16out = n8out // 2
        # binary canonicalization ladder: r < (kA+1)p <= 2^smax * p
        smax = (kA + 1 - 1).bit_length()
        self._sub_consts = [
            np.array(
                [((p << s) >> (16 * t)) & 0xFFFF for t in range(self.n16out)],
                np.int32,
            )
            for s in range(smax - 1, -1, -1)
        ]
        self._W, self._E, self._L8 = W, E, L8
        self._c1, self._c2 = c1, c2
        self._p_modB, self._MinvB = p_modB, MinvB
        self._L_mr, self._MB8 = L_mr, MB8
        self._m_all = np.array(m_all, np.int32)
        self._minv_all = (1.0 / self._m_all.astype(np.float64)).astype(
            np.float32
        )

    # -- exact modular primitives ------------------------------------------

    @staticmethod
    def _mod_rows(v, m, minv):
        """v mod m, exact, for int32 v in [0, 2^30) and m in (2, 2^13).

        Float estimate first: q = floor(f32(v)/m) is within ±1 of the true
        quotient (relative error <= ~3*2^-24 on a ratio < 2^19, absolute
        error < 0.1), then integer correction makes the residue exact —
        q*m <= v + m stays inside int32.
        """
        import jax.numpy as jnp

        q = jnp.floor(v.astype(jnp.float32) * minv).astype(jnp.int32)
        r = v - q * m
        r = jnp.where(r < 0, r + m, r)
        r = jnp.where(r >= m, r - m, r)
        return r

    def _dot(self, Wnp: np.ndarray, x, exact: bool = False, mvec=None,
             minvvec=None):
        """Constant-matrix contraction ``W @ x`` for int32 x (d, B).

        int32 mode: W split into <=7-bit planes so every partial dot stays
        < 2^26 (depth <= 48, terms < 2^7 * 2^13); the high plane is reduced
        mod m before the <<7 recombination unless `exact` (W < 2^8 there,
        so the raw recombination already fits).
        int8 mode (`self.int8_dots`): x additionally splits at bit 7 and
        all four partial contractions run as int8 x int8 -> int32
        `dot_general` — the MXU-native form; bit-identical results.
        """
        import jax
        import jax.numpy as jnp

        d = Wnp.shape[1]
        Wlo = jnp.asarray(Wnp & 0x7F)
        Whi = jnp.asarray(Wnp >> 7)  # < 2^6 (W < 2^13) or <= 1 (exact, W < 2^8)
        dn = (((1,), (0,)), ((), ()))

        def dot(a, b):
            return jax.lax.dot_general(a, b, dn,
                                       preferred_element_type=jnp.int32)

        if not self.int8_dots:
            # terms < 2^7 * 2^13 = 2^20, depth <= 48 -> partials < 2^26
            lo = dot(Wlo, x)
            hi = dot(Whi, x)
        else:
            xl = (x & 0x7F).astype(jnp.int8)
            xh = (x >> 7).astype(jnp.int8)  # < 2^6: residues and limbs < 2^13
            i8 = lambda w: w.astype(jnp.int8)
            # every partial: terms <= 127*127, depth <= 48 -> < 2^20.9
            lo = dot(i8(Wlo), xl) + (dot(i8(Wlo), xh) << 7)
            hi = dot(i8(Whi), xl) + (dot(i8(Whi), xh) << 7)
        if exact:
            # W < 2^8: hi <= depth * xmax -> lo + (hi << 7) < 2^27, exact
            return lo + (hi << 7)
        # congruence-preserving recombination: reduce hi first so the shift
        # cannot overflow (hi < 2^26 -> mod -> < 2^13 -> <<7 -> < 2^20)
        hi = self._mod_rows(hi, mvec, minvvec)
        return self._mod_rows(lo + (hi << 7), mvec, minvvec)

    # -- residue conversion -------------------------------------------------

    def _split8(self, a):
        """(nlimbs, B) uint32 16-bit limbs -> (2*nlimbs, B) int32 8-bit."""
        import jax.numpy as jnp

        a = a.astype(jnp.int32)
        return jnp.stack([a & 0xFF, a >> 8], axis=1).reshape(
            2 * self.nlimbs, a.shape[1]
        )

    def to_rns(self, a):
        """Positional (nlimbs, B) uint32 -> joint-base residues (k_all, B)
        int32 (base A rows, then base B rows, then the m_r channel)."""
        import jax.numpy as jnp

        m = jnp.asarray(self._m_all)[:, None]
        minv = jnp.asarray(self._minv_all)[:, None]
        return self._dot(self._W, self._split8(a), mvec=m, minvvec=minv)

    def from_rns_base_b(self, rB, rr):
        """Exact CRT: base-B residues (kB, B) + m_r channel (B,) of a value
        v < MB -> canonical positional 16-bit limbs (n16out, B) int32.

        Shenoy–Kumaresan: alpha = (sum(xi'_j * (MB/m_j)) - v) / MB is
        recovered exactly through the redundant channel (alpha < kB < m_r),
        then v = L8 @ xi' - alpha*MB8 in 8-bit columns, carry-propagated.
        """
        import jax.numpy as jnp

        mB = jnp.asarray(np.array(self.mB, np.int32))[:, None]
        mBinv = jnp.asarray(self._minv_all[self.kA : self.kA + self.kB])[:, None]
        mr = jnp.int32(self.mr)
        mrinv = jnp.float32(1.0 / self.mr)
        xi = self._mod_rows(rB * jnp.asarray(self._c2)[:, None], mB, mBinv)
        # alpha channel: per-term mod keeps the sum < kB * 2^13 < 2^19
        terms = self._mod_rows(xi * jnp.asarray(self._L_mr)[:, None], mr, mrinv)
        s = self._mod_rows(jnp.sum(terms, axis=0), mr, mrinv)
        # (s - v) * MB^{-1} mod m_r; + m_r keeps the difference nonnegative
        alpha = self._mod_rows(
            (s - rr + mr) * jnp.int32(self._MBinv_r), mr, mrinv
        )  # < kB exactly — the true CRT offset
        # positional columns: exact int32 (terms < 2^21, depth kB -> < 2^26)
        cols = self._dot(self._L8, xi, exact=True)
        cols = cols - alpha[None, :] * jnp.asarray(self._MB8)[:, None]
        # signed sequential carry: v - (v & 0xFF) is a multiple of 256, so
        # the arithmetic shift is exact floor division for negatives too
        carry = jnp.zeros_like(cols[0])
        out8 = []
        for t in range(self.n8out):
            v = cols[t] + carry
            low = v & 0xFF
            out8.append(low)
            carry = (v - low) >> 8
        # top carry is 0: the reconstructed integer is < MB by CRT range
        o8 = jnp.stack(out8)
        return o8[0::2] + (o8[1::2] << 8)  # (n16out, B) 16-bit rows

    def _cond_sub_const(self, v, cnp: np.ndarray):
        """v - C if v >= C else v, over (n16out, B) int32 16-bit rows."""
        import jax.numpy as jnp

        borrow = jnp.zeros_like(v[0])
        diff = []
        for i in range(self.n16out):
            d = v[i] - jnp.int32(int(cnp[i])) - borrow
            borrow = (d < 0).astype(jnp.int32)
            diff.append(d + (borrow << 16))
        keep = borrow > 0  # borrowed past the top -> v < C
        return jnp.stack(
            [jnp.where(keep, v[i], diff[i]) for i in range(self.n16out)]
        )

    # -- the kernel ---------------------------------------------------------

    def mul(self, a, b):
        """RNS Montgomery product: canonical a, b (< p, positional Montgomery
        form with constant M) -> canonical a*b*M^{-1} mod p. See module
        docstring for the step-by-step bound/exactness argument."""
        import jax.numpy as jnp

        bsz = a.shape[1]
        if bsz == 0:  # empty slices appear inside library combinators
            return jnp.zeros_like(a)
        kA, kB = self.kA, self.kB
        m_all = jnp.asarray(self._m_all)[:, None]
        minv_all = jnp.asarray(self._minv_all)[:, None]
        mB_r = m_all[kA:]
        mBinv_r = minv_all[kA:]

        # 1) residues of both operands in one contraction (batch-stacked)
        res = self._dot(
            self._W,
            jnp.concatenate([self._split8(a), self._split8(b)], axis=1),
            mvec=m_all,
            minvvec=minv_all,
        )
        ra, rb = res[:, :bsz], res[:, bsz:]
        # 2) residue-wise product T mod m_i (products < 2^26)
        d = self._mod_rows(ra * rb, m_all, minv_all)
        # 3) folded Montgomery quotient digits in base A (< 2^26)
        mA = m_all[:kA]
        xi = self._mod_rows(d[:kA] * jnp.asarray(self._c1)[:, None], mA,
                            minv_all[:kA])
        # 4) base extension A -> B ++ [m_r]: q_hat = q + c*M, c < kA — the
        #    offset only shifts r by c*p, absorbed by canonicalization
        Q = self._dot(self._E, xi, mvec=mB_r, minvvec=mBinv_r)
        # 5) r = (T + q_hat*p)/M elementwise in B ++ [m_r]:
        #    (d + Q*p) < 2^14 after reduction; * Minv < 2^27
        u = self._mod_rows(Q * jnp.asarray(self._p_modB)[:, None], mB_r,
                           mBinv_r)
        r = self._mod_rows(
            (d[kA:] + u) * jnp.asarray(self._MinvB)[:, None], mB_r, mBinv_r
        )
        # 6) exact CRT back to positional form; r < (kA+1)p < MB
        v16 = self.from_rns_base_b(r[:kB], r[kB])
        # 7) canonicalize r < 2^smax * p down to < p (binary ladder)
        for cnp in self._sub_consts:
            v16 = self._cond_sub_const(v16, cnp)
        # value < p fits the field's limb count; higher rows are zero
        return v16[: self.nlimbs].astype(jnp.uint32)

"""JAX prime-field arithmetic on limb vectors — the TPU's bignum engine.

This layer replaces the reference's native field arithmetic (the amd64/arm64
assembly inside its cloudflare/bn256 dependency, SURVEY.md §2.2) with
TPU-friendly kernels. It is the risk item called out in SURVEY.md §7 hard part
(a); the design below is what measured fastest on a real v5e chip.

Design:

  * **Limbs-major layout.** An Fp element batch is a uint32 array of shape
    (NLIMBS, B): limb index in the sublane dimension, batch in the lane
    dimension. Every limb operation is then a full-width (B,) vector op on the
    VPU — with batch-last, a 16-limb element would occupy 16/128 lanes.
  * **16-bit limbs in uint32 lanes.** Limb products fit uint32 exactly (no
    mul-high needed) and anti-diagonal column sums of split lo/hi halves stay
    < 2^23, so carries are propagated lazily once per multiplication.
  * **Montgomery multiplication** (radix 2^16, CIOS-style column interleave)
    as one fused Pallas kernel: inputs stream HBM->VMEM in (NLIMBS, TILE_B)
    blocks, all ~n^2 limb products and column sums happen in VMEM/registers.
    Measured 357.0M 254-bit mults/s MARGINAL at B=262144 on the one
    available chip (TPU v5 lite0, results/fp_microbench.json; run-to-run
    ~250-436M with tunnel weather) vs ~1M/s for the naive XLA graph that
    materializes (B,16,16) intermediates through HBM.
    Marginal means chained-muls-in-one-dispatch slope: this environment's
    tunneled chip pays a ~57-68 ms host<->device round trip per dispatch that
    dwarfs the kernel (a naive time-one-call loop reads 15.5M/s and is
    measuring the tunnel, not the VPU — see `_throughput_bench`). The
    figure is batch-sensitive: the artifact's `mxu_lab` control reads 13.1M
    at B=32768 on a capture-contended host — 1/8 the production batch fills
    a fraction of the lanes/VMEM tiles, and contention inflates the slope;
    the artifact's `note` walks all four figures (15.5M / 13.1M / 357M /
    250-436M) back to one story. The dispatch floor, not mul throughput,
    dominates the ~104 ms 128-lane verify p50 (results/verify_profile.json
    breaks the launch down).
  * **Batch stacking beats vmap.** Callers (ops/tower.py) flatten independent
    field muls into the batch dimension (one Fp12 mul = ONE mont_mul call at
    54x batch), keeping lanes full even for small pairing batches.
  * A pure-XLA fallback with identical semantics runs where Pallas TPU kernels
    aren't available (CPU tests); both paths are cross-validated.

All values are kept canonical (< p) at op boundaries. Elements are in
Montgomery form except where a method says otherwise; the Montgomery
constant is backend-specific (R = 2^(16*NLIMBS) for CIOS, the base-A
product M for RNS) but canonical non-Montgomery boundary values are
bit-identical across backends.

**Backend seam.** `Field(p, backend=...)` selects the modmul kernel:

  * ``backend="cios"`` (default) — this module's CIOS kernel above.
  * ``backend="rns"`` — `ops/rns.py`'s residue-number-system Montgomery
    pipeline, which restructures the multiply into constant-matrix
    `dot_general` contractions so the MXU (idle under CIOS — contraction
    depth 1, ~47x headroom vs the measured 16.7 T int8-ops/s ceiling,
    scripts/mxu_limb_lab.py) carries the bulk work. `Field.__new__`
    redirects construction to `RnsField`, a subclass overriding only
    `mul`; everything else here (add/sub/inv/pow/pack/unpack, the
    carry-lookahead machinery) is inherited, and `ops/tower.py`'s
    batch-stacking entry points route through whichever kernel the
    constructed Field carries — `BN254Device` dispatch, the fleet plane,
    and the lifecycle/epoch paths inherit the backend transparently.
    Config plumbing: `fp_backend` in the TOML -> SimConfig ->
    models/bn254_jax.py -> ops/curve.py -> here. The CIOS kernel stays
    the bit-exact oracle (tests/test_fp_jax.py, scripts/rns_smoke.py).

    On top of the per-mul pipeline the RNS backend exposes a **resident
    value form** (`RnsField.to_resident`/`mul_resident`/`from_resident`
    plus the `ResidentRns` Field-shaped adapter): values stay as joint
    residue planes across whole tower formulas, and the CRT
    reconstruction that `RnsField.mul` pays at every call is deferred to
    genuine pairing boundaries (point coordinates entering the Miller
    loop, the final GT verdict). `ops/pairing.py` threads that form
    through the Miller loop and the final-exponentiation tower when the
    backend is RNS (opt out via `rns_resident`); subtraction sites carry
    static per-site bound literals (`blog`, accepted-and-ignored by the
    CIOS `sub`/`neg` above) — HACKING.md "Residue-resident pairing" has
    the bound algebra.

Figure walk-through (results/fp_microbench.json): the artifact's `note`
reconciles the four CIOS figures (15.5M naive-timing error / 13.1M
small-batch mxu_lab control / 357M production marginal / 250-436M tunnel
weather band); per-backend `mont_muls_per_s` records measured under the
SAME chained-dispatch methodology (`chained_marginal`, shared by
`_throughput_bench`, scripts/fp_kernel_lab.py, and scripts/mxu_limb_lab.py)
sit beside it and are gated like-for-like by scripts/bench_check.py —
a CIOS row never judges an RNS row.

Correctness oracle: ops/bn254_ref.py; property tests in tests/test_fp_jax.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1

# lane-dimension granularity: uint32 tiles are (8, 128); tile batches to 128
_LANE = 128
_MAX_TILE_B = 2048


def _int_to_limbs(x: int, nlimbs: int) -> np.ndarray:
    out = np.zeros(nlimbs, dtype=np.uint32)
    for i in range(nlimbs):
        out[i] = (x >> (LIMB_BITS * i)) & LIMB_MASK
    assert x >> (LIMB_BITS * nlimbs) == 0, "value too large for limb count"
    return out


def _limbs_to_int(limbs) -> int:
    limbs = np.asarray(limbs)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(limbs))


def windowed_pow_digits(e: int, window: int) -> list[int] | None:
    """MSB-first w-bit digit decomposition of a public exponent, or None when
    the exponent is small enough that a direct chain beats the table. Shared
    by Field.pow_const and Tower.f12_pow_const (one copy of the digit
    arithmetic: a window change must not be able to diverge between them)."""
    bits = bin(e)[2:]
    if len(bits) <= window:
        return None
    pad = (-len(bits)) % window
    padded = "0" * pad + bits
    return [int(padded[i : i + window], 2) for i in range(0, len(padded), window)]


def default_pow_window() -> int:
    """Backend-aware pow strategy: 4-bit windows on accelerators (~3x fewer
    executed muls per chain), plain bit scan on XLA:CPU. The windowed form
    builds a 15-entry table plus a gather-inside-scan at EVERY pow site, and
    the CPU backend — where only compile time matters (virtual-mesh dryruns,
    CI) — pays for that in compile seconds multiplied across the staged
    sharded executables (the r04 multichip-dryrun timeout). The bit scan
    compiles to the smallest graph; the executed-mul count it wastes is
    irrelevant off-chip."""
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    return 1 if backend == "cpu" else 4


def windowed_pow(a, e: int, window: int, mul, sqr, stack, take, select):
    """Left-to-right windowed square-and-multiply, representation-agnostic.

    The plain bit scan executes a multiply EVERY step (compute-and-select —
    data-independent control flow); a w-bit window keeps the squaring count
    but replaces w bit-steps with one digit-step (w sqrs + 1 table mul + 1
    select), cutting executed muls from bits-1 to 2^w-2 + bits/w while the
    traced graph stays scan-sized (the digit-loop body is traced once).

    window<=1 selects the plain bit scan (scan over bits, square + selected
    multiply per step, no table/gather) — the compile-cheapest lowering,
    the right choice where compile time dominates (see default_pow_window).

    Primitives: mul(a,b), sqr(a); stack(list_of_elems) -> stacked repr;
    take(stacked, traced_idx) -> elem; select(traced_bool, if_true, if_false).
    """
    import jax

    if window <= 1:
        bits = bin(e)[2:]
        if len(bits) <= 8:  # tiny exponent: direct chain
            acc = a
            for c in bits[1:]:
                acc = sqr(acc)
                if c == "1":
                    acc = mul(acc, a)
            return acc

        def bit_step(acc, bit):
            acc = sqr(acc)
            return select(bit == 1, mul(acc, a), acc), None

        acc, _ = jax.lax.scan(
            bit_step, a, jnp.asarray([int(c) for c in bits[1:]], jnp.uint32)
        )
        return acc

    digits = windowed_pow_digits(e, window)
    if digits is None:  # tiny exponent: direct chain
        acc = a
        for c in bin(e)[3:]:
            acc = sqr(acc)
            if c == "1":
                acc = mul(acc, a)
        return acc
    # table[k] = a^(k+1), k = 0..2^w-2 (digit 0 lanes select "no mul")
    table = [a]
    for _ in range(2**window - 2):
        table.append(mul(table[-1], a))
    stacked = stack(table)
    acc = table[digits[0] - 1]  # MSB digit is nonzero by construction

    def step(acc, digit):
        for _ in range(window):
            acc = sqr(acc)
        m = take(stacked, jnp.maximum(digit, 1) - 1)
        return select(digit != 0, mul(acc, m), acc), None

    acc, _ = jax.lax.scan(step, acc, jnp.asarray(digits[1:], jnp.uint32))
    return acc


def _has_pallas_tpu() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


class Field:
    """Modular arithmetic over a fixed prime on uint32 limb vectors.

    All jax methods take/return uint32 arrays of shape (nlimbs, B) in
    Montgomery form (except where noted) and are jit/shard-safe. B must be a
    multiple of 128 for the Pallas path; `pad_batch` helps callers comply.

    `backend` selects the modmul kernel: "cios" (this class) or "rns"
    (ops/rns.py — `__new__` redirects construction there). Canonical
    non-Montgomery boundary values are bit-identical across backends.
    """

    backend = "cios"
    # dtype of one batch row; ops/tower.py consults this so its zero/one
    # constructors match the value representation (uint32 positional limbs
    # here, int32 residue rows for ops/rns.py's ResidentRns adapter)
    limb_dtype = jnp.uint32

    def __new__(cls, p: int = 0, use_pallas: bool | None = None,
                backend: str | None = None):
        if cls is Field and backend == "rns":
            from handel_tpu.ops.rns import RnsField  # lazy: avoid cycle

            return super().__new__(RnsField)
        return super().__new__(cls)

    def __init__(self, p: int, use_pallas: bool | None = None,
                 backend: str | None = None):
        if backend not in (None, "cios", "rns"):
            raise ValueError(
                f"unknown Field backend {backend!r}: valid choices are "
                f"'cios' (VPU CIOS kernel, the bit-exact oracle) and 'rns' "
                f"(MXU residue pipeline, ops/rns.py; its residue-resident "
                f"pairing form is toggled by the `rns_resident` knob)"
            )
        self.p = p
        self.nlimbs = (p.bit_length() + LIMB_BITS - 1) // LIMB_BITS
        n = self.nlimbs
        self.mont_r = (1 << (LIMB_BITS * n)) % p
        self.mont_r2 = self.mont_r * self.mont_r % p
        # -p^{-1} mod 2^16: the Montgomery reduction multiplier
        self.n0 = int((-pow(p, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS))
        self.p_limbs_np = _int_to_limbs(p, n)
        self.p_limbs = jnp.asarray(self.p_limbs_np)
        self.use_pallas = _has_pallas_tpu() if use_pallas is None else use_pallas
        self._pallas_fns: dict = {}

    # -- host-side conversions (not jittable) ------------------------------

    def pack(self, xs, mont: bool = True) -> jnp.ndarray:
        """List of ints -> (nlimbs, len(xs)) limb array (Montgomery by default)."""
        mult = self.mont_r if mont else 1
        arr = np.stack(
            [_int_to_limbs(x % self.p * mult % self.p, self.nlimbs) for x in xs],
            axis=1,
        )
        return jnp.asarray(arr, jnp.uint32)

    def pack_batch_np(self, xs, mont: bool = True, out=None) -> np.ndarray:
        """`pack_batch` stopping at the host: the (nlimbs, len(xs)) uint32
        limb array as numpy (optionally written into a caller-owned `out`
        buffer). The zero-copy launch packer builds signature limbs with
        this and scatters them into its staging buffers, which reach the
        device via ONE explicit `jax.device_put` instead of an implicit
        per-array transfer (models/bn254_jax.py `_pack_sig_limbs`)."""
        mult = self.mont_r if mont else 1
        p = self.p
        lbytes = LIMB_BITS // 8  # LIMB_BITS is byte-aligned by construction
        buf = b"".join(
            (x % p * mult % p).to_bytes(self.nlimbs * lbytes, "little")
            for x in xs
        )
        arr = np.frombuffer(buf, dtype=np.dtype(f"<u{lbytes}")).reshape(
            len(xs), self.nlimbs
        )
        if out is not None:
            out[:, : len(xs)] = arr.T
            return out
        return arr.T.astype(np.uint32)

    def pack_batch(self, xs, mont: bool = True) -> jnp.ndarray:
        """`pack`, array-at-once: one bigint mulmod + `to_bytes` per element
        and a single vectorized byte→limb reinterpretation for the whole
        batch, instead of `_int_to_limbs`'s nlimbs shift/mask Python ops per
        element. Bit-identical output to `pack` (property-tested); this is
        the launch-packing hot path (models/bn254_jax.py `_pack_requests`),
        where per-launch host cost at batch 256 is what it saves."""
        return jnp.asarray(self.pack_batch_np(xs, mont=mont))

    def unpack(self, limbs, mont: bool = True) -> list[int]:
        """(nlimbs, B) limb array -> list of ints (from Montgomery by default)."""
        arr = np.asarray(limbs)
        mult = pow(self.mont_r, -1, self.p) if mont else 1
        return [
            _limbs_to_int(arr[:, k]) * mult % self.p for k in range(arr.shape[1])
        ]

    @staticmethod
    def pad_batch(b: int) -> int:
        """Smallest Pallas-friendly batch >= b."""
        return max(_LANE, (b + _LANE - 1) // _LANE * _LANE)

    def constant(self, x: int, batch: int) -> jnp.ndarray:
        """Montgomery-form constant broadcast to (nlimbs, batch)."""
        limbs = _int_to_limbs(x % self.p * self.mont_r % self.p, self.nlimbs)
        return jnp.broadcast_to(
            jnp.asarray(limbs, jnp.uint32)[:, None], (self.nlimbs, batch)
        )

    # -- shared limb algebra (used by both XLA and Pallas paths) -----------

    def _mul_cols(self, a, b):
        """Full schoolbook product + interleaved Montgomery reduction on
        limbs-major operands; returns canonical (nlimbs, B) limbs.

        Column magnitudes stay < 2^23 (<= 2n 16-bit terms per column plus
        reduction contributions), so a single lazy carry pass at the end
        suffices. Statically unrolled: no data-dependent control flow.
        """
        n = self.nlimbs
        zero = jnp.zeros_like(a[0])
        cols = [zero] * (2 * n + 1)
        for i in range(n):
            prod = a[i][None, :] * b  # (n, B), exact 32-bit products
            lo = prod & LIMB_MASK
            hi = prod >> LIMB_BITS
            for j in range(n):
                cols[i + j] = cols[i + j] + lo[j]
                cols[i + j + 1] = cols[i + j + 1] + hi[j]
        n0 = jnp.uint32(self.n0)
        carry = zero
        for i in range(n):
            t = cols[i] + carry
            m = (t * n0) & LIMB_MASK
            for j in range(n):
                mp = m * jnp.uint32(int(self.p_limbs_np[j]))
                mlo = mp & LIMB_MASK
                mhi = mp >> LIMB_BITS
                if j == 0:
                    carry = (t + mlo) >> LIMB_BITS
                else:
                    cols[i + j] = cols[i + j] + mlo
                cols[i + j + 1] = cols[i + j + 1] + mhi
        cols[n] = cols[n] + carry
        out = []
        carry = zero
        for k in range(n, 2 * n):
            t = cols[k] + carry
            out.append(t & LIMB_MASK)
            carry = t >> LIMB_BITS
        # CIOS bound: result < 2p < 2^(16n), so no carry out of the top limb
        return self._cond_sub_p_rows(out)

    def _cond_sub_p_rows(self, rows):
        """Conditionally subtract p from a list of n canonical 16-bit rows."""
        n = self.nlimbs
        borrow = jnp.zeros_like(rows[0], dtype=jnp.int32)
        diff = []
        for i in range(n):
            d = (
                rows[i].astype(jnp.int32)
                - jnp.int32(int(self.p_limbs_np[i]))
                - borrow
            )
            borrow = (d < 0).astype(jnp.int32)
            diff.append((d + (borrow << LIMB_BITS)).astype(jnp.uint32))
        keep = borrow > 0  # borrowed past the top -> value < p -> keep as-is
        out = [jnp.where(keep, rows[i], diff[i]) for i in range(n)]
        return jnp.stack(out)

    def _add_rows(self, a, b):
        n = self.nlimbs
        carry = jnp.zeros_like(a[0])
        out = []
        for i in range(n):
            t = a[i] + b[i] + carry
            out.append(t & LIMB_MASK)
            carry = t >> LIMB_BITS
        return self._cond_sub_p_rows(out)

    def _sub_rows(self, a, b):
        n = self.nlimbs
        borrow = jnp.zeros_like(a[0], dtype=jnp.int32)
        raw = []
        for i in range(n):
            d = a[i].astype(jnp.int32) - b[i].astype(jnp.int32) - borrow
            borrow = (d < 0).astype(jnp.int32)
            raw.append(d + (borrow << LIMB_BITS))
        # if we borrowed past the top, add p back
        need_p = borrow > 0
        carry = jnp.zeros_like(a[0], dtype=jnp.int32)
        out = []
        for i in range(n):
            t = raw[i] + jnp.where(need_p, jnp.int32(int(self.p_limbs_np[i])), 0) + carry
            out.append((t & LIMB_MASK).astype(jnp.uint32))
            carry = t >> LIMB_BITS
        return jnp.stack(out)

    # -- carry-lookahead machinery (bit-packed, fully fusable) --------------
    #
    # The per-limb Python loops above (_add_rows/_cond_sub_p_rows) trace to
    # ~150 primitive ops per field add; a pairing contains tens of thousands
    # of adds, which made XLA tracing/compilation minutes-slow. Shift-based
    # Kogge-Stone was no better: every `pad` becomes its own unfused LLVM
    # kernel on the XLA CPU backend. Instead, per-limb generate/propagate
    # bits are PACKED into one uint32 word per lane and the carry closure is
    # computed with the classic adder identity
    #
    #     carries(A + B) = A ^ B ^ (A + B)   (carry INTO bit i)
    #
    # applied to A = g, B = g|p: maj(g, g|p, c) = g | (p & c), exactly the
    # carry recurrence. ~10 elementwise/reduction ops per add, no data
    # movement, fuses into one kernel on every backend. Requires nlimbs < 32.
    # The unrolled per-limb forms are kept for the Pallas kernel body, where
    # Mosaic wants straight-line register code.

    @property
    def _bit_weights(self):
        # plain numpy so it embeds as a fresh constant in every trace
        w = getattr(self, "_bw", None)
        if w is None:
            w = (np.uint64(1) << np.arange(self.nlimbs, dtype=np.uint64)).astype(
                np.uint32
            )[:, None]
            self._bw = w
        return w

    def _carry_word(self, g, p):
        """Closed carry word from per-limb generate/propagate (0/1 uint32
        rows): bit i of the result = carry INTO position i."""
        gb = jnp.sum(g * self._bit_weights, axis=0, dtype=jnp.uint32)
        pb = jnp.sum(p * self._bit_weights, axis=0, dtype=jnp.uint32)
        b = gb | pb
        return (gb + b) ^ gb ^ b

    def _ks_carry(self, s):
        """Normalize (nlimbs, B) limbs with values < 2^17 to canonical 16-bit
        limbs via bit-packed carry-lookahead. Returns (limbs, carry_out)."""
        r = s & LIMB_MASK
        g = s >> LIMB_BITS  # 0/1
        p = (r == LIMB_MASK).astype(jnp.uint32)
        c = self._carry_word(g, p)
        cin = (c[None, :] >> jnp.arange(self.nlimbs, dtype=jnp.uint32)[:, None]) & 1
        out = (r + cin) & LIMB_MASK
        return out, ((c >> self.nlimbs) & 1).astype(bool)

    def _borrow_chain(self, t):
        """Closed borrow bits for int32 limb differences t (t<0 generates a
        borrow, t==0 propagates one). Returns (borrow_in, borrowed_past_top)."""
        g = (t < 0).astype(jnp.uint32)
        p = (t == 0).astype(jnp.uint32)
        c = self._carry_word(g, p)
        bin_ = (c[None, :] >> jnp.arange(self.nlimbs, dtype=jnp.uint32)[:, None]) & 1
        return bin_.astype(jnp.int32), ((c >> self.nlimbs) & 1).astype(bool)

    def _cond_sub_p(self, r):
        """Canonicalize r (< 2p, canonical limbs) to r mod p."""
        t = r.astype(jnp.int32) - jnp.asarray(self.p_limbs_np, jnp.int32)[:, None]
        b, borrowed = self._borrow_chain(t)
        out = ((t - b) & LIMB_MASK).astype(jnp.uint32)
        return jnp.where(borrowed, r, out)  # borrowed past top -> r < p

    # -- public ring ops ----------------------------------------------------

    def add(self, a, b):
        r, _ = self._ks_carry(a + b)  # a, b < p so a+b < 2p < 2^256
        return self._cond_sub_p(r)

    def sub(self, a, b, blog: int | None = None):
        """a - b mod p. `blog` is the resident-form subtrahend bound knob
        (ops/rns.py `ResidentRns.sub`): canonical positional limbs are always
        < p, so the CIOS kernel ignores it — accepting the parameter keeps
        ops/tower.py's per-site bound literals backend-agnostic."""
        t = a.astype(jnp.int32) - b.astype(jnp.int32)
        bor, borrowed = self._borrow_chain(t)
        raw = ((t - bor) & LIMB_MASK).astype(jnp.uint32)  # a-b mod 2^256
        # if a < b, add p back
        padd = jnp.where(
            borrowed, jnp.asarray(self.p_limbs_np, jnp.uint32)[:, None], 0
        )
        r, _ = self._ks_carry(raw + padd)
        return r

    def neg(self, a, blog: int | None = None):
        return self.sub(jnp.zeros_like(a), a, blog)

    def mul(self, a, b):
        """Montgomery product. Pallas kernel on TPU, pure XLA elsewhere."""
        if self.use_pallas:
            return self._mul_pallas(a, b)
        return self._mul_cols_vec(a, b)

    def _mul_cols_vec(self, a, b):
        """Same CIOS Montgomery product as `_mul_cols`, but expressed with
        (n, n, B) tensor ops and slice-updates instead of fully unrolled
        per-limb scalar graphs.

        Rationale: `_mul_cols` unrolls to ~n^2*6 primitive ops, which is what
        the Pallas kernel wants (Mosaic compiles it to tight VPU code) but
        makes plain-XLA compilation of pairing-sized graphs minutes-slow on
        CPU. This form is ~6x fewer HLO ops with identical semantics; both
        paths are cross-validated in tests/test_fp_jax.py.
        """
        n = self.nlimbs
        bsz = a.shape[1]
        t = a[:, None, :] * b[None, :, :]  # (n, n, B); 16x16-bit products, exact
        lo = t & LIMB_MASK
        hi = t >> LIMB_BITS
        cols = jnp.zeros((2 * n + 1, bsz), jnp.uint32)
        for i in range(n):
            cols = cols.at[i : i + n].add(lo[i])
            cols = cols.at[i + 1 : i + n + 1].add(hi[i])
        # interleaved Montgomery reduction (identical column algebra to
        # _mul_cols: per-column magnitudes stay < 2^23, one lazy carry pass)
        n0 = jnp.uint32(self.n0)
        p_col = jnp.asarray(self.p_limbs_np, jnp.uint32)[:, None]  # (n, 1)
        carry = jnp.zeros((bsz,), jnp.uint32)
        for i in range(n):
            t0 = cols[i] + carry
            m = (t0 * n0) & LIMB_MASK
            mp = m[None, :] * p_col  # (n, B)
            mlo = mp & LIMB_MASK
            mhi = mp >> LIMB_BITS
            carry = (t0 + mlo[0]) >> LIMB_BITS
            cols = cols.at[i + 1 : i + n].add(mlo[1:])
            cols = cols.at[i + 1 : i + n + 1].add(mhi)
        cols = cols.at[n].add(carry)
        hi = cols[n : 2 * n]  # column values < 2^23 (CIOS bound)
        spill = jnp.pad(hi >> LIMB_BITS, ((1, 0), (0, 0)))[:n]  # multi-bit carries
        r, _ = self._ks_carry((hi & LIMB_MASK) + spill)
        return self._cond_sub_p(r)

    def sqr(self, a):
        return self.mul(a, a)

    def _mul_pallas(self, a, b):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        n = self.nlimbs
        bsz = a.shape[1]
        if bsz == 0:  # empty slices show up inside associative_scan
            return jnp.zeros_like(a)
        if bsz % _LANE != 0:
            # odd widths appear inside library combinators (e.g. the interior
            # slices of associative_scan): zero-pad to the lane granularity
            # and slice back — Montgomery 0*0 = 0 stays canonical
            padded = self.pad_batch(bsz)
            pad = lambda x: jnp.pad(x, ((0, 0), (0, padded - bsz)))
            return self._mul_pallas(pad(a), pad(b))[:, :bsz]
        tile = min(_MAX_TILE_B, bsz)
        while bsz % tile != 0:
            tile //= 2
        key = (bsz, tile)
        fn = self._pallas_fns.get(key)
        if fn is None:

            def kernel(a_ref, b_ref, o_ref):
                o_ref[:] = self._mul_cols(a_ref[:], b_ref[:])

            fn = pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((n, bsz), jnp.uint32),
                grid=(bsz // tile,),
                in_specs=[
                    pl.BlockSpec((n, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
                    pl.BlockSpec((n, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(
                    (n, tile), lambda i: (0, i), memory_space=pltpu.VMEM
                ),
            )
            self._pallas_fns[key] = fn
        return fn(a, b)

    # -- derived ops --------------------------------------------------------

    def pow_const(self, a, e: int, window: int | None = None):
        """a^e for a fixed public exponent: windowed square-and-multiply
        (`windowed_pow`) — for the 254-bit Fermat inversion, 77 executed
        muls instead of the bit-scan's 253 on accelerators; plain bit scan
        on CPU where compile time dominates (default_pow_window)."""
        return windowed_pow(
            a,
            e,
            default_pow_window() if window is None else window,
            mul=self.mul,
            sqr=lambda x: self.mul(x, x),
            stack=lambda t: jnp.stack(t),
            take=lambda s, i: s[i],
            select=lambda c, x, y: jnp.where(c, x, y),
        )

    def inv(self, a):
        """Field inverse by Fermat: a^(p-2). Zero maps to zero."""
        return self.pow_const(a, self.p - 2)

    def select(self, mask, a, b):
        """Per-element select: mask (B,) bool -> limbs from a else b."""
        return jnp.where(mask[None, :], a, b)

    def is_zero(self, a):
        return jnp.all(a == 0, axis=0)

    def eq(self, a, b):
        return jnp.all(a == b, axis=0)

    # -- Montgomery domain conversions (jittable) ---------------------------

    def to_mont(self, a):
        r2 = jnp.broadcast_to(
            jnp.asarray(_int_to_limbs(self.mont_r2, self.nlimbs), jnp.uint32)[
                :, None
            ],
            a.shape,
        )
        return self.mul(a, r2)

    def from_mont(self, a):
        one = jnp.zeros_like(a).at[0].set(1)
        return self.mul(a, one)


def chained_marginal(fn, a, b, k1: int = 8, k2: int = 72, trials: int = 4):
    """Marginal throughput of a binary op under chained dispatch — THE
    methodology every throughput figure in results/fp_microbench.json uses
    (shared by `_throughput_bench`, scripts/fp_kernel_lab.py, and
    scripts/mxu_limb_lab.py so the candidates stay comparable).

    On this environment's tunneled TPU a single dispatch pays a ~30-90 ms
    host<->device round trip that dwarfs the kernel, so a naive
    time-one-call loop measures the tunnel, not the chip (that error
    produced the 15.5M/s figure first captured in fp_microbench.json).
    Instead: time k1- and k2-deep chains of dependent `fn(out, b)` calls
    inside ONE jitted executable each (best of `trials`, completion forced
    by a one-column device_get), and report the slope
    (k2-k1)*batch/(t2-t1) — dispatch/fetch overhead cancels in the
    difference. Returns (rate_ops_per_s, dispatch_floor_s); rate is None
    when the slope is non-positive after one retry (timing noise at tiny
    batches): a non-measurement, never an absurd figure.
    """
    import time

    import jax

    def chain(k):
        def f(x, y):
            out = x
            for _ in range(k):
                out = fn(out, y)
            return out

        return jax.jit(f)

    def best_of(cf):
        jax.device_get(cf(a, b)[:, :1])  # compile + warm
        best = float("inf")
        for _ in range(trials):
            t0 = time.perf_counter()
            jax.device_get(cf(a, b)[:, :1])
            best = min(best, time.perf_counter() - t0)
        return best

    c1, c2 = chain(k1), chain(k2)
    t1, t2 = best_of(c1), best_of(c2)
    if t2 <= t1:  # timing noise (tiny batches / tunnel hiccup): one retry
        t1, t2 = best_of(c1), best_of(c2)
    if t2 <= t1:
        return None, t1
    batch = a.shape[-1]
    rate = (k2 - k1) * batch / (t2 - t1)
    floor = max(t1 - k1 * batch / rate, 0.0)
    return rate, floor


def _throughput_bench(
    batch: int = 1 << 18, trials: int = 4, backend: str = "cios"
):
    """Substantiates the module docstring's mult/s figure; run with
    `python -m handel_tpu.ops.fp [batch] [backend]` on the target chip.
    Chained-dispatch marginal methodology — see `chained_marginal`.
    Returns (marginal_rate, dispatch_floor_s); rate 0.0 when the slope is
    not measurable."""
    import jax

    from handel_tpu.ops import bn254_ref as bn

    F = Field(bn.P, backend=backend)
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.integers(0, 1 << LIMB_BITS, (F.nlimbs, batch), np.uint32))
    b = jnp.asarray(rng.integers(0, 1 << LIMB_BITS, (F.nlimbs, batch), np.uint32))
    k1, k2 = 8, 72
    rate, floor = chained_marginal(F.mul, a, b, k1=k1, k2=k2, trials=trials)
    if rate is None:
        print(
            f"{jax.default_backend()}: marginal slope not measurable "
            f"(floor ~{floor*1e3:.2f} ms at batch {batch}) — "
            f"increase batch or chain depth",
        )
        return 0.0, floor
    print(
        f"{jax.default_backend()}: {rate/1e6:.1f}M {bn.P.bit_length()}-bit "
        f"mont-muls/s marginal [{backend}] (batch {batch}, chain {k1}->{k2}, "
        f"dispatch floor ~{floor*1e3:.1f} ms)"
    )
    return rate, floor


if __name__ == "__main__":
    import sys

    _throughput_bench(
        int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20,
        backend=sys.argv[2] if len(sys.argv) > 2 else "cios",
    )

"""Batched JAX elliptic-curve group ops for BN254 G1 (Fp) and G2 (Fp2').

Replaces the reference's point arithmetic (`Combine`'s G1/G2 adds at
bn256/cf/bn256.go:107,199 and scalar mults at :134,153) with TPU-shaped
kernels. Design choices, TPU-first:

  * **Complete projective formulas** (Renes–Costello–Batina 2015, Alg. 7 for
    a = 0): ONE branch-free formula covers generic add, doubling, the
    identity, and inverse points. No data-dependent control flow inside jit —
    the whole point-add graph is straight-line VPU code, so it vmaps/scans/
    reduces freely. (The scalar oracle bn254_ref.pt_add branches four ways;
    that shape would force `lax.cond` everywhere on device.)
  * Points are (X, Y, Z) homogeneous projective; infinity = (0, 1, 0).
  * **Mul stacking**: the 14 field multiplications of one complete add are
    grouped into 3 stacked `Field.mul` calls (widths 3, 4, 6 and one b3 mul),
    keeping the Pallas mont-mul lanes full even at small point batches
    (ops/fp.py "batch stacking beats vmap").
  * **Tree reduction** for aggregate keys/sigs: `sum_points` folds an
    n-block batch in ceil(log2 n) complete-add stages — the device-side
    replacement for the reference's sequential pubkey-aggregation loop
    (processing.go:355-361).

Correctness oracle: ops/bn254_ref.py (g1_add/g2_add/pt_mul); tests in
tests/test_curve_jax.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from handel_tpu.ops import bls12_381_ref as _bls
from handel_tpu.ops import bn254_ref as bn
from handel_tpu.ops.fp import Field
from handel_tpu.ops.tower import Tower


class _FpAdapter:
    """Base-field element algebra for G1: elements are (nlimbs, B) arrays.

    b3 = 3b for the curve constant (y^2 = x^3 + b): 9 for BN254's b = 3,
    12 for BLS12-381's b = 4 — both realized as add chains."""

    def __init__(self, F: Field, b3: int = 9):
        self.F = F
        self.b3 = b3
        if b3 not in (9, 12):
            raise ValueError(f"unsupported curve constant b3={b3}")

    def add(self, a, b):
        return self.F.add(a, b)

    def sub(self, a, b):
        return self.F.sub(a, b)

    def neg(self, a):
        return self.F.neg(a)

    def select(self, mask, a, b):
        return self.F.select(mask, a, b)

    def zero(self, batch):
        return jnp.zeros((self.F.nlimbs, batch), jnp.uint32)

    def one(self, batch):
        return self.F.constant(1, batch)

    def eq(self, a, b):
        return self.F.eq(a, b)

    def is_zero(self, a):
        return self.F.is_zero(a)

    def batch(self, a):
        return a.shape[1]

    def mul_many(self, lhs, rhs):
        """Stacked multiplication: one mont_mul call for k independent muls."""
        k = len(lhs)
        prod = self.F.mul(jnp.concatenate(lhs, axis=1), jnp.concatenate(rhs, axis=1))
        b = prod.shape[1] // k
        return [prod[:, i * b : (i + 1) * b] for i in range(k)]

    def mul_b3(self, a):
        """x * b3 by add chain, no mul (x9 for BN254, x12 for BLS12-381)."""
        a2 = self.F.add(a, a)
        a4 = self.F.add(a2, a2)
        a8 = self.F.add(a4, a4)
        return self.F.add(a8, a if self.b3 == 9 else a4)

    def inv(self, a):
        return self.F.inv(a)

    def mul(self, a, b):
        return self.F.mul(a, b)

    def concat(self, elems):
        return jnp.concatenate(elems, axis=1)

    def split(self, e, k):
        b = e.shape[1] // k
        return [e[:, i * b : (i + 1) * b] for i in range(k)]


class _Fp2Adapter:
    """Quadratic-extension algebra for G2': elements are Fp2 pairs."""

    def __init__(self, T: Tower, params=bn):
        self.T = T
        # E' twist coefficient b' (3/xi for BN254's D-twist, 4*xi for
        # BLS12-381's M-twist); b3 = 3*b' as a host constant
        self._b3 = params.f2_scalar(params.TWIST_B, 3)
        self._b3_packed = None

    def add(self, a, b):
        return self.T.f2_add(a, b)

    def sub(self, a, b):
        return self.T.f2_sub(a, b)

    def neg(self, a):
        return self.T.f2_neg(a)

    def select(self, mask, a, b):
        return self.T.f2_select(mask, a, b)

    def zero(self, batch):
        return self.T.f2_zero(batch)

    def one(self, batch):
        return self.T.f2_one(batch)

    def eq(self, a, b):
        return self.T.f2_eq(a, b)

    def is_zero(self, a):
        return self.T.f2_is_zero(a)

    def batch(self, a):
        return a[0].shape[1]

    def mul_many(self, lhs, rhs):
        out = self.T.f2_mul(self.T._f2_stack(lhs), self.T._f2_stack(rhs))
        return self.T._f2_unstack(out, len(lhs))

    def mul_b3(self, a):
        b3 = self.T.f2_constant(self._b3, a[0].shape[1])
        return self.T.f2_mul(a, b3)

    def inv(self, a):
        return self.T.f2_inv(a)

    def mul(self, a, b):
        return self.T.f2_mul(a, b)

    def concat(self, elems):
        return self.T._f2_stack(elems)

    def split(self, e, k):
        return self.T._f2_unstack(e, k)


class Curve:
    """Batched short-Weierstrass group (y^2 = x^3 + b, a = 0) over an element
    algebra. Points are (X, Y, Z) pytrees; identity is (0, 1, 0)."""

    def __init__(self, ops):
        self.ops = ops

    # -- constructors -------------------------------------------------------

    def infinity(self, batch: int):
        o = self.ops
        return (o.zero(batch), o.one(batch), o.zero(batch))

    def from_affine(self, x, y):
        o = self.ops
        return (x, y, o.one(o.batch(x)))

    # -- predicates ---------------------------------------------------------

    def is_infinity(self, P):
        return self.ops.is_zero(P[2])

    def eq(self, P, Q):
        """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1, with both-
        infinite handled by the cross products all being zero."""
        o = self.ops
        a, b, c, d = o.mul_many([P[0], Q[0], P[1], Q[1]], [Q[2], P[2], Q[2], P[2]])
        both_inf = self.is_infinity(P) & self.is_infinity(Q)
        one_inf = self.is_infinity(P) ^ self.is_infinity(Q)
        return (o.eq(a, b) & o.eq(c, d) & ~one_inf) | both_inf

    # -- group law ----------------------------------------------------------

    def add(self, P, Q):
        """Complete projective addition (RCB15 Alg. 7, a = 0): 12 muls +
        2 b3-muls, stacked into 3 wide Field.mul calls. Handles P == Q,
        P == -Q, and either operand at infinity with the same code path."""
        o = self.ops
        X1, Y1, Z1 = P
        X2, Y2, Z2 = Q
        a, b, c = o.mul_many([X1, Y1, Z1], [X2, Y2, Z2])
        d, e, f = o.mul_many(
            [o.add(X1, Y1), o.add(Y1, Z1), o.add(X1, Z1)],
            [o.add(X2, Y2), o.add(Y2, Z2), o.add(X2, Z2)],
        )
        d = o.sub(d, o.add(a, b))  # X1Y2 + X2Y1
        e = o.sub(e, o.add(b, c))  # Y1Z2 + Y2Z1
        f = o.sub(f, o.add(a, c))  # X1Z2 + X2Z1
        g = o.add(o.add(a, a), a)  # 3 X1X2
        h = o.mul_b3(c)
        i = o.add(b, h)
        j = o.sub(b, h)
        k = o.mul_b3(f)
        m0, m1, m2, m3, m4, m5 = o.mul_many([d, e, j, k, g, i], [j, k, i, g, d, e])
        X3 = o.sub(m0, m1)  # d*j - e*k
        Y3 = o.add(m2, m3)  # j*i + k*g
        Z3 = o.add(m5, m4)  # i*e + g*d
        return (X3, Y3, Z3)

    def double(self, P):
        return self.add(P, P)

    def neg(self, P):
        return (P[0], self.ops.neg(P[1]), P[2])

    def select(self, mask, P, Q):
        o = self.ops
        return tuple(o.select(mask, p, q) for p, q in zip(P, Q))

    # -- scalar multiplication ----------------------------------------------

    def scalar_mul(self, P, bits):
        """[k]P with per-lane scalars. bits: (nbits, B) uint32 array, MSB
        first. Double-and-add under lax.scan with a per-lane select — fixed
        trip count, no data-dependent control flow."""

        def step(acc, bit):
            acc = self.double(acc)
            added = self.add(acc, P)
            acc = self.select(bit == 1, added, acc)
            return acc, None

        acc, _ = jax.lax.scan(step, self.infinity(self.ops.batch(P[0])), bits)
        return acc

    # -- reductions ----------------------------------------------------------

    def sum_points(self, P, n: int):
        """Tree-sum n point blocks laid out block-major along the batch axis:
        each coordinate has shape (..., n*b); returns points of batch b.

        This is the aggregation kernel: ceil(log2 n) complete-add stages,
        each a single stacked launch at half the remaining width — vs the
        reference's n sequential `Combine` calls (processing.go:355-361)."""
        o = self.ops
        b = o.batch(P[0]) // n
        while n > 1:
            if n % 2:  # pad with one infinity block
                inf = self.infinity(b)
                P = tuple(
                    o.concat([coord, icoord]) for coord, icoord in zip(P, inf)
                )
                n += 1
            half = n // 2 * b
            lo = tuple(o.split(coord, 2)[0] for coord in P)
            hi = tuple(o.split(coord, 2)[1] for coord in P)
            P = self.add(lo, hi)
            n //= 2
        return P

    def masked_sum(self, P, mask, n: int):
        """Sum of the blocks whose mask bit is set. mask: (n*b,) bool over the
        block-major batch axis. Unset blocks are replaced by infinity first,
        then tree-summed — the device form of bitset-selected aggregation."""
        P = self.select(mask, P, self.infinity(self.ops.batch(P[0])))
        return self.sum_points(P, n)

    def msm(self, P, bits, n: int, window: int = 4):
        """Batched multi-scalar multiplication: out lane j = sum_i k[i,j]·P[i,j]
        over n point blocks laid out block-major along the batch axis (block i
        lane j = batch index i*b + j, like `sum_points`). bits: (nbits, n*b)
        uint32 MSB-first per-lane scalar bits (`BN254Curves.scalar_bits` /
        `scalar_bits64` shape). Lanes whose scalar is 0 contribute the
        identity, so masking to the launch hull is just zeroing those
        columns before the call.

        Windowed/bucketed accumulation shaped for the existing reduction
        kernels rather than a per-point double-and-add: the scalar stream is
        cut into w-bit digits; each window step sorts blocks into the
        V = 2^w - 1 nonzero buckets with ONE `masked_sum` over a (n, V, b)
        tiling (the bucket histogram is a select mask, not a gather), turns
        bucket sums into Σ v·B_v with a Hillis-Steele *suffix* scan over the
        bucket axis (Σ_v v·B_v = Σ_v Σ_{u≥v} B_u — log2 V adds, no scalar
        mul), and Horner-folds windows under `lax.scan` (w doublings + one
        add per step), so compile cost is independent of nbits. window=1
        degenerates to a shared double-and-add over `masked_sum`.

        Cost per window step: w doubles + [log2 n + 2·log2 V + 1] complete
        adds, all stacked full-width — 64-bit scalars at w=4 are 16 steps."""
        o = self.ops
        tree = jax.tree_util.tree_map
        nb = o.batch(P[0])
        b = nb // n
        V = (1 << window) - 1
        nbits = bits.shape[0]
        pad = (-nbits) % window
        if pad:
            bits = jnp.concatenate([jnp.zeros((pad, nb), bits.dtype), bits])
        nwin = (nbits + pad) // window
        weights = (1 << jnp.arange(window - 1, -1, -1, dtype=jnp.uint32))
        digits = (bits.reshape(nwin, window, nb) * weights[None, :, None]).sum(
            axis=1, dtype=jnp.int32
        )  # (nwin, n*b), each in [0, 2^w)

        # Tile each block across the V buckets: (..., n*b) -> (..., n*V*b),
        # tiled index i*V*b + v*b + j <- block i lane j. Loop-invariant.
        tiled = tree(
            lambda a: jnp.broadcast_to(
                a.reshape(a.shape[:-1] + (n, 1, b)), a.shape[:-1] + (n, V, b)
            ).reshape(a.shape[:-1] + (n * V * b,)),
            P,
        )
        bucket_of = jnp.arange(V * b) // b  # suffix-scan block ids

        def step(acc, d):
            for _ in range(window):
                acc = self.double(acc)
            # bucket membership: tiled lane (i, v, j) set iff digit == v+1
            hit = d[None, :] == jnp.arange(1, V + 1, dtype=jnp.int32)[:, None]
            mask = hit.reshape(V, n, b).transpose(1, 0, 2).reshape(n * V * b)
            buckets = self.masked_sum(tiled, mask, n)  # (V, b) bucket-major
            d2 = 1
            while d2 < V:  # suffix sums R_v = sum_{u >= v} B_u
                keep = bucket_of + d2 < V
                shifted = self.select(
                    keep,
                    tree(lambda a: jnp.roll(a, -d2 * b, axis=-1), buckets),
                    self.infinity(V * b),
                )
                buckets = self.add(buckets, shifted)
                d2 *= 2
            return self.add(acc, self.sum_points(buckets, V)), None

        acc, _ = jax.lax.scan(step, self.infinity(b), digits)
        return acc

    def prefix_scan(self, P):
        """Inclusive prefix sums along the batch axis: out lane i = sum of
        lanes 0..i. Hillis-Steele doubling scan over the complete add: every
        stage is one full-width add + shift/select, so all ceil(log2 n)
        stages share a single op shape (Pallas-friendly, one executable)
        — unlike `associative_scan`, whose interior odd-width slices each
        compile separately.

        One-time registry precompute for O(1) range aggregation: a Handel
        candidate's signer set is an ID range of the binomial partitioner
        (partitioner.go rangeLevel), so its aggregate key is
        prefix[hi] - prefix[lo] — two gathers and one add instead of a
        masked tree-sum over the whole registry."""
        o = self.ops
        n = o.batch(P[0])
        tree = jax.tree_util.tree_map
        d = 1
        while d < n:
            keep = jnp.arange(n) >= d  # lanes with a neighbor d to the left
            shifted = tree(lambda a: jnp.roll(a, d, axis=-1), P)
            inf = self.infinity(n)
            shifted = self.select(keep, shifted, inf)
            P = self.add(P, shifted)
            d *= 2
        return P

    # -- affine conversion (host boundary) -----------------------------------

    def to_affine(self, P):
        """(x, y, inf_mask): one field inversion per lane. Infinity lanes
        return (0, 0) with the mask set."""
        o = self.ops
        inf = self.is_infinity(P)
        z = o.select(inf, o.one(o.batch(P[2])), P[2])
        zinv = o.inv(z)
        x, y = o.mul_many([P[0], P[1]], [zinv, zinv])
        zero = o.zero(o.batch(x))
        return (
            o.select(inf, zero, x),
            o.select(inf, zero, y),
            inf,
        )

    def on_curve(self, P):
        """Projective curve membership: Y^2 Z == X^3 + b Z^3 (b3/3 = b).
        Infinity (0,1,0) satisfies it."""
        o = self.ops
        yy, xx, zz = o.mul_many([P[1], P[0], P[2]], [P[1], P[0], P[2]])
        lhs, x3, z3 = o.mul_many([yy, xx, zz], [P[2], P[0], P[2]])
        # b*Z^3 = b3*Z^3 / 3: cheaper to compute b3*z3 then... 3 is not
        # invertible by shifts; instead compute b*Z^3 via b3 chain on a third.
        # Use: rhs = X^3 + b*Z^3 where b*Z^3 = mul_b3(z3) "minus" 2/3 — avoid
        # division: compare 3*Y^2 Z == 3*X^3 + b3*Z^3.
        three = lambda t: o.add(o.add(t, t), t)
        return o.eq(three(lhs), o.add(three(x3), o.mul_b3(z3)))


class BN254Curves:
    """The two pairing groups sharing one Field/Tower, plus host conversions.

    Parameterized by the scalar-oracle module (`params`): BN254 by default;
    `BLS12Curves` below binds the same machinery to BLS12-381 (b = 4,
    M-type twist, 381-bit field)."""

    params = bn
    g1_b3 = 9  # 3*b for E: y^2 = x^3 + 3

    def __init__(
        self,
        field: Field | None = None,
        tower: Tower | None = None,
        backend: str | None = None,
    ):
        # `backend` picks the Field modmul kernel ("cios"/"rns", ops/fp.py
        # seam); everything above the Field — tower, curve adapters, pairing
        # — routes through whichever kernel the constructed Field carries.
        self.F = field or Field(self.params.P, backend=backend)
        self.T = tower or Tower(self.F, params=self.params)
        self.g1 = Curve(_FpAdapter(self.F, b3=self.g1_b3))
        self.g2 = Curve(_Fp2Adapter(self.T, params=self.params))

    # -- host packing: scalar oracle points <-> device batches ---------------

    def pack_g1(self, pts):
        """List of scalar-oracle affine G1 points (or None) -> projective batch."""
        xs = [0 if p is None else p[0] for p in pts]
        ys = [1 if p is None else p[1] for p in pts]
        zs = [0 if p is None else 1 for p in pts]
        return (self.F.pack(xs), self.F.pack(ys), self.F.pack(zs))

    def unpack_g1(self, P):
        x, y, inf = self.g1.to_affine(P)
        xs = self.F.unpack(x)
        ys = self.F.unpack(y)
        import numpy as np

        infs = np.asarray(inf)
        return [None if infs[i] else (xs[i], ys[i]) for i in range(len(xs))]

    def pack_g2(self, pts):
        f20, f21 = (0, 0), (1, 0)
        xs = [f20 if p is None else p[0] for p in pts]
        ys = [f21 if p is None else p[1] for p in pts]
        zs = [f20 if p is None else f21 for p in pts]
        return (self.T.f2_pack(xs), self.T.f2_pack(ys), self.T.f2_pack(zs))

    def unpack_g2(self, P):
        x, y, inf = self.g2.to_affine(P)
        xs = self.T.f2_unpack(x)
        ys = self.T.f2_unpack(y)
        import numpy as np

        infs = np.asarray(inf)
        return [None if infs[i] else (xs[i], ys[i]) for i in range(len(xs))]

    @staticmethod
    def scalar_bits(ks, nbits: int = 256):
        """Host: list of ints -> (nbits, len(ks)) uint32 MSB-first bit array.
        Vectorized over 32-bit words so packing C scalars per launch is numpy
        work, not a python bit loop."""
        import numpy as np

        nwords = (nbits + 31) // 32
        words = np.empty((nwords, len(ks)), np.uint32)
        for w in range(nwords):
            words[w] = [(k >> (32 * w)) & 0xFFFFFFFF for k in ks]
        shifts = np.arange(31, -1, -1, dtype=np.uint32)
        bits = (words[:, None, :] >> shifts[None, :, None]) & np.uint32(1)
        # word w covers bit rows [nbits-32(w+1), nbits-32w): stack words
        # high-to-low (bit order within each word is already MSB-first),
        # then trim any rows above nbits
        bits = bits[::-1].reshape(nwords * 32, len(ks))
        bits = bits[nwords * 32 - nbits :]
        return jnp.asarray(np.ascontiguousarray(bits))

    @staticmethod
    def scalar_bits64(ks):
        """Host: 64-bit scalars -> (64, len(ks)) uint32 MSB-first — the RLC
        launch's per-candidate random-coefficient operand."""
        import numpy as np

        a = np.asarray(ks, dtype=np.uint64)
        shifts = np.arange(63, -1, -1, dtype=np.uint64)
        return jnp.asarray(((a[None, :] >> shifts[:, None]) & np.uint64(1)).astype(np.uint32))


class BLS12Curves(BN254Curves):
    """BLS12-381 binding: E: y^2 = x^3 + 4 (b3 = 12) over the 381-bit field,
    E'(Fp2) with the M-type twist coefficient 4(1+i)
    (ops/bls12_381_ref.py TWIST_B)."""

    params = _bls
    g1_b3 = 12

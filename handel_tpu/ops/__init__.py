"""Field/curve/pairing math: the TPU compute path and its scalar ground truth.

  bn254_ref.py — pure-Python (bigint) BN254: tower fields, curve groups,
                 optimal ate pairing. Correctness oracle for every kernel.
  fp.py        — JAX limb-vector Fp arithmetic (Montgomery form)
  tower.py     — JAX Fp2/Fp6/Fp12
  curve.py     — JAX G1/G2 Jacobian ops, masked segment sums
  pairing.py   — JAX Miller loop + final exponentiation, batched verify
"""
